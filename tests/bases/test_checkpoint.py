"""Checkpoint/resume of metric state through orbax (SURVEY §5 checkpoint/resume).

The reference persists metric state via the nn.Module state-dict protocol;
here metric state is a pytree, so orbax handles it natively — these tests pin
the full save → restore → identical-compute contract, including list-kind
("cat") states and collections.
"""
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp
import pytest

import metrics_tpu as mt


def _ckpt(tmp_path):
    return ocp.PyTreeCheckpointer(), tmp_path / "ckpt"


class TestOrbaxRoundTrip:
    def test_tensor_state_metric(self, tmp_path):
        m = mt.Accuracy(num_classes=3)
        m.update(jnp.asarray([[0.8, 0.1, 0.1], [0.2, 0.7, 0.1]]), jnp.asarray([0, 2]))
        expected = float(m.compute())

        ckptr, path = _ckpt(tmp_path)
        ckptr.save(path, m.metric_state)

        fresh = mt.Accuracy(num_classes=3)
        restored = ckptr.restore(path)
        fresh._restore_state({k: jnp.asarray(v) for k, v in restored.items()})
        fresh._update_count = 1
        assert float(fresh.compute()) == expected

    def test_list_state_metric(self, tmp_path):
        m = mt.SpearmanCorrCoef()
        rng = np.random.RandomState(0)
        for _ in range(3):
            p = rng.randn(16).astype(np.float32)
            m.update(jnp.asarray(p), jnp.asarray(p + 0.1 * rng.randn(16).astype(np.float32)))
        expected = float(m.compute())

        ckptr, path = _ckpt(tmp_path)
        # list states are pytrees of arrays — saved as-is
        ckptr.save(path, m.metric_state)
        restored = ckptr.restore(path)

        fresh = mt.SpearmanCorrCoef()
        fresh._restore_state(
            {k: [jnp.asarray(x) for x in v] if isinstance(v, list) else jnp.asarray(v) for k, v in restored.items()}
        )
        fresh._update_count = 3
        np.testing.assert_allclose(float(fresh.compute()), expected, rtol=1e-6)

    def test_collection_state_dict_roundtrip(self, tmp_path):
        suite = mt.MetricCollection(
            {"acc": mt.Accuracy(num_classes=3), "mean": mt.MeanMetric()}
        )
        suite.persistent(True)  # states opt into state_dict (reference default is off)
        suite["acc"].update(jnp.asarray([[0.8, 0.1, 0.1]]), jnp.asarray([0]))
        suite["mean"].update(jnp.asarray([2.0, 4.0]))
        sd = {k: jnp.asarray(v) for k, v in suite.state_dict().items()}

        ckptr, path = _ckpt(tmp_path)
        ckptr.save(path, sd)
        restored = ckptr.restore(path)

        fresh = mt.MetricCollection({"acc": mt.Accuracy(num_classes=3), "mean": mt.MeanMetric()})
        fresh.persistent(True)
        fresh.load_state_dict({k: jnp.asarray(v) for k, v in restored.items()})
        for sub in fresh.values():
            sub._update_count = 1
        out = fresh.compute()
        assert float(out["acc"]) == 1.0
        assert float(out["mean"]) == 3.0

    def test_persistent_flag_controls_state_dict(self):
        class P(mt.Metric):
            full_state_update = False

            def __init__(self):
                super().__init__()
                self.add_state("kept", jnp.asarray(0.0), dist_reduce_fx="sum", persistent=True)
                self.add_state("dropped", jnp.asarray(0.0), dist_reduce_fx="sum", persistent=False)

            def update(self, x):
                self.kept = self.kept + x
                self.dropped = self.dropped + x

            def compute(self):
                return self.kept

        m = P()
        m.update(jnp.asarray(5.0))
        sd = m.state_dict()
        assert "kept" in sd and "dropped" not in sd


def test_wrapper_persistent_recurses_divergence_pinned():
    """Documented divergence (README ledger): `persistent()` recurses into
    child metrics for EVERY wrapper here, so a wrapper's checkpoint carries
    its children's states. The reference forwards the flag only from
    CompositionalMetric (`src/torchmetrics/metric.py:893-897`) — there,
    BootStrapper.persistent(True) would leave the bootstrap copies out of
    state_dict."""
    # multinomial: every clone draws exactly n samples, so no clone can get
    # an empty draw (poisson's unseeded empty draws made clone means NaN
    # depending on suite ordering)
    boot = mt.BootStrapper(mt.MeanMetric(), num_bootstraps=3, sampling_strategy="multinomial")
    boot.update(jnp.asarray([1.0, 2.0]))
    boot.persistent(True)
    sd = boot.state_dict()
    # children's states present under dotted prefixes — the divergent behaviour
    assert {f"metrics.{i}.{s}" for i in range(3) for s in ("value", "weight")} == set(sd), sorted(sd)
    restored = mt.BootStrapper(mt.MeanMetric(), num_bootstraps=3)
    restored.persistent(True)
    restored.load_state_dict(sd)
    restored._update_count = 1
    for child in restored.metrics:
        child._update_count = 1
    out = restored.compute()
    assert jnp.isfinite(out["mean"])
