"""EVERY exported module metric honors the lifecycle invariants.

The reference's ``_class_test`` pushes each metric through pickle round-trip
(`tests/unittests/helpers/testers.py:174-176`), reset semantics, and
state_dict checks; here the same registry SPEC as the distributed/precision
contracts drives four invariants per metric:

1. mid-stream pickle round-trip: the clone finishes the stream and computes
   the same value as the original;
2. reset(): a reset metric re-fed the stream equals a fresh instance;
3. clone(): updating the clone leaves the original's value unchanged;
4. state_dict()/load_state_dict(): persisted states restore to an instance
   that computes identically.
"""
from __future__ import annotations

import pickle

import numpy as np
import pytest

from tests.bases.test_registry_distributed import SPEC
from tests.bases.test_registry_precision import _split
from tests.helpers import assert_tree_close

# value-bearing compute needs at least one update; SPEC batches guarantee it


def _feed(metric, batches):
    for batch in batches:
        args, kwargs = _split(batch)
        metric.update(*args, **kwargs)
    return metric


@pytest.mark.parametrize("name", sorted(SPEC))
def test_pickle_midstream(name):
    factory, batches, atol = SPEC[name]
    half = max(1, len(batches) // 2)
    metric = _feed(factory(), batches[:half])
    clone = pickle.loads(pickle.dumps(metric))
    _feed(metric, batches[half:])
    _feed(clone, batches[half:])
    assert_tree_close(clone.compute(), metric.compute(), atol=atol, rtol=1e-5)


@pytest.mark.parametrize("name", sorted(SPEC))
def test_reset_equals_fresh(name):
    factory, batches, atol = SPEC[name]
    metric = _feed(factory(), batches)
    _ = metric.compute()
    metric.reset()
    _feed(metric, batches)
    fresh = _feed(factory(), batches)
    assert_tree_close(metric.compute(), fresh.compute(), atol=atol, rtol=1e-5)


@pytest.mark.parametrize("name", sorted(SPEC))
def test_clone_independence(name):
    """Updating a clone must not disturb the original's state — detects a
    shallow clone sharing mutable list states (appends would contaminate)."""
    factory, batches, atol = SPEC[name]
    metric = _feed(factory(), batches[:1])
    before = metric.compute()
    clone = metric.clone()
    _feed(clone, batches[1:])
    metric._computed = None  # recompute from the ORIGINAL's (untouched) state
    assert_tree_close(metric.compute(), before, atol=atol, rtol=1e-5)


@pytest.mark.parametrize("name", sorted(SPEC))
def test_state_dict_roundtrip(name):
    factory, batches, atol = SPEC[name]
    metric = _feed(factory(), batches)
    # persist everything for the round-trip regardless of per-state defaults
    metric.persistent(True)
    state = metric.state_dict()
    restored = factory()
    restored.persistent(True)
    restored.load_state_dict(state)
    # _update_count does not travel with the state dict (matching the
    # reference); mark the restored metric as updated so compute() does not
    # warn — the contract under test is value equality
    def _mark_updated(m):
        m._update_count = max(m._update_count, 1)
        for _, child in m._named_child_metrics():
            _mark_updated(child)

    _mark_updated(restored)
    assert_tree_close(restored.compute(), metric.compute(), atol=atol, rtol=1e-5)
