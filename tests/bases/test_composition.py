"""CompositionalMetric operator tests (analogue of reference tests/unittests/bases/test_composition.py)."""
import jax.numpy as jnp
import pytest

from metrics_tpu import CompositionalMetric
from tests.helpers.testers import DummyMetric


def _pair(a=2.0, b=3.0):
    m1, m2 = DummyMetric(), DummyMetric()
    m1.update(a)
    m2.update(b)
    return m1, m2


@pytest.mark.parametrize(
    "op, expected",
    [
        (lambda a, b: a + b, 5.0),
        (lambda a, b: a - b, -1.0),
        (lambda a, b: a * b, 6.0),
        (lambda a, b: a / b, 2.0 / 3.0),
        (lambda a, b: a % b, 2.0),
        (lambda a, b: a**b, 8.0),
        (lambda a, b: a // b, 0.0),
    ],
)
def test_binary_metric_metric(op, expected):
    m1, m2 = _pair()
    comp = op(m1, m2)
    assert isinstance(comp, CompositionalMetric)
    assert float(comp.compute()) == pytest.approx(expected)


@pytest.mark.parametrize(
    "op, expected",
    [
        (lambda a: a + 10, 12.0),
        (lambda a: 10 + a, 12.0),
        (lambda a: a * 4, 8.0),
        (lambda a: 10 - a, 8.0),
        (lambda a: a / 2, 1.0),
        (lambda a: 8 / a, 4.0),
    ],
)
def test_binary_metric_scalar(op, expected):
    m1, _ = _pair()
    comp = op(m1)
    assert float(comp.compute()) == pytest.approx(expected)


@pytest.mark.parametrize(
    "op, expected",
    [
        (lambda a, b: a == b, False),
        (lambda a, b: a != b, True),
        (lambda a, b: a < b, True),
        (lambda a, b: a <= b, True),
        (lambda a, b: a > b, False),
        (lambda a, b: a >= b, False),
    ],
)
def test_comparison_ops(op, expected):
    m1, m2 = _pair()
    assert bool(op(m1, m2).compute()) is expected


def test_unary_ops():
    m = DummyMetric()
    m.update(-4.0)
    assert float(abs(m).compute()) == 4.0
    assert float((+m).compute()) == 4.0  # __pos__ is abs, like the reference
    assert float((-m).compute()) == -4.0  # __neg__ is -abs


def test_getitem():
    m = DummyMetric()
    m.update(jnp.asarray([1.0, 2.0, 3.0]))
    comp = m[1]
    assert float(comp.compute()) == 2.0


def test_composition_update_fans_out():
    m1, m2 = DummyMetric(), DummyMetric()
    comp = m1 + m2
    comp.update(1.0)
    assert float(m1.x) == 1.0
    assert float(m2.x) == 1.0
    assert float(comp.compute()) == 2.0


def test_composition_forward():
    m1, m2 = DummyMetric(), DummyMetric()
    comp = m1 + m2
    out = comp(2.0)
    assert float(out) == 4.0


def test_composition_reset():
    m1, m2 = _pair()
    comp = m1 + m2
    comp.reset()
    assert float(m1.x) == 0.0
    assert float(m2.x) == 0.0


def test_nested_composition():
    m1, m2 = _pair()
    comp = (m1 + m2) * 2
    assert float(comp.compute()) == 10.0


def test_bitwise_ops():
    m1, m2 = DummyMetric(), DummyMetric()
    m1.update(jnp.asarray(3))
    m2.update(jnp.asarray(5))

    class IntMetric(DummyMetric):
        def update(self, x):
            self.x = jnp.asarray(x, dtype=jnp.int32)

        def compute(self):
            return self.x

    a, b = IntMetric(), IntMetric()
    a.update(3)
    b.update(5)
    assert int((a & b).compute()) == 1
    assert int((a | b).compute()) == 7
    assert int((a ^ b).compute()) == 6


def test_composition_as_functions_refuses():
    """The composition has no states of its own — a silent empty-state export
    would compute on reset components (review regression)."""
    import metrics_tpu as mt

    comp = mt.MeanMetric() + mt.MeanMetric()
    with pytest.raises(NotImplementedError, match="component"):
        comp.as_functions()
