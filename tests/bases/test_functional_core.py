"""Functional pytree core contracts (ISSUE-16 tentpole).

Contracts (`metrics_tpu/functional_core.py`):

- **One code path** — ``init()/apply_update()/apply_compute()`` are built
  from the same ``_inner_update``/``_inner_compute`` bodies the module API
  dispatches, so the two surfaces are bit-exact on identical data
  (Accuracy, MeanMetric, AUROC, CatMetric, a compute-group collection).
- **Epoch rides the state tree** — ``FuncState`` carries the world epoch as
  STATIC pytree aux data: a membership transition changes the treedef (jit
  retraces), and a stale-stamped tree classifies as ``EpochFault`` at the
  ``host_handoff`` seam with the shell state intact.
- **Donation-safe** — ``init()`` returns fresh buffers, so
  ``jax.jit(..., donate_argnums=0)`` steps never alias a live module's
  state or the cached template defaults.
- **In-graph merge == host sync** — under an 8-device ``shard_map`` world,
  ``apply_compute(axis_name=...)`` matches the host-path ``_FakeGather``
  sync oracle bit-for-bit, with ZERO host sync collectives issued.
- **No double merge at the seam** — ``host_handoff`` lands merged state
  pre-synced: a following ``sync_context``/``compute()`` serves it without
  re-entering the sync protocol; ``unsync()`` is an idempotent restore.
- **Hot-path caching pins** — one export build per config fingerprint
  (``funcore_exports``), one backend walk per process
  (``sync_dist_resolutions``), memoized window values and decay layouts
  (``window_value_cache_hits`` / ``window_decay_layout_reuses``).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import metrics_tpu as mt
from metrics_tpu import streaming
from metrics_tpu.functional_core import FuncState, funcore_stats
from metrics_tpu.ops import engine
from metrics_tpu.parallel import sync as psync
from metrics_tpu.parallel.sharding import infer_state_pspecs
from metrics_tpu.utils.exceptions import EpochFault
from tests.helpers.testers import _FakeGather

DIST_ON = lambda: True  # noqa: E731
N_DEV = 8


def shard_map(f, **kw):
    kw.setdefault("check_vma", False)
    return jax.shard_map(f, **kw)


@pytest.fixture(autouse=True)
def _clean_world():
    psync.reset_membership()
    engine.reset_stats()
    yield
    psync.reset_membership()
    engine.reset_stats()


def _cls_data(n=64, c=8, seed=0):
    rng = np.random.RandomState(seed)
    logits = rng.rand(n, c).astype(np.float32)
    preds = logits / logits.sum(axis=1, keepdims=True)
    target = rng.randint(0, c, size=n)
    return jnp.asarray(preds), jnp.asarray(target)


def _bin_data(n=64, seed=0):
    rng = np.random.RandomState(seed)
    return (
        jnp.asarray(rng.rand(n).astype(np.float32)),
        jnp.asarray(rng.randint(0, 2, size=n)),
    )


# ------------------------------------------------------------------- parity
class TestModuleParity:
    """apply_update/apply_compute bit-exact vs the stateful module API."""

    @pytest.mark.parametrize(
        "build, batches",
        [
            pytest.param(
                lambda: mt.Accuracy(num_classes=8),
                [_cls_data(seed=s) for s in range(3)],
                id="accuracy",
            ),
            pytest.param(
                lambda: mt.MeanMetric(),
                [(jnp.asarray([float(s), float(s) + 2.0]),) for s in range(3)],
                id="mean",
            ),
            pytest.param(
                lambda: mt.AUROC(pos_label=1),
                [_bin_data(seed=s) for s in range(3)],
                id="auroc-cat-lists",
            ),
            pytest.param(
                lambda: mt.CatMetric(),
                [(jnp.arange(4.0) + s,) for s in range(3)],
                id="cat",
            ),
        ],
    )
    def test_bit_exact(self, build, batches):
        m = build()
        state = m.init()
        assert isinstance(state, FuncState)
        for batch in batches:
            state = m.apply_update(state, *batch)
        value = m.apply_compute(state)

        oracle = build()
        for batch in batches:
            oracle.update(*batch)
        np.testing.assert_array_equal(np.asarray(value), np.asarray(oracle.compute()))

    def test_jitted_update_parity(self):
        m = mt.Accuracy(num_classes=8)
        step = jax.jit(lambda st, p, t: m.apply_update(st, p, t))
        state = m.init()
        for seed in range(3):
            state = step(state, *_cls_data(seed=seed))
        oracle = mt.Accuracy(num_classes=8)
        for seed in range(3):
            oracle.update(*_cls_data(seed=seed))
        np.testing.assert_array_equal(
            np.asarray(m.apply_compute(state)), np.asarray(oracle.compute())
        )

    def test_compute_group_collection_parity(self):
        suite = mt.MetricCollection(
            {"acc": mt.Accuracy(num_classes=8), "prec": mt.Precision(num_classes=8, average="macro")},
            compute_groups=True,
        )
        state = suite.init()
        for seed in range(3):
            state = suite.apply_update(state, *_cls_data(seed=seed))
        values = suite.apply_compute(state)

        oracle = mt.MetricCollection(
            {"acc": mt.Accuracy(num_classes=8), "prec": mt.Precision(num_classes=8, average="macro")},
            compute_groups=True,
        )
        for seed in range(3):
            oracle.update(*_cls_data(seed=seed))
        expected = oracle.compute()
        assert set(values) == set(expected) == {"acc", "prec"}
        for key in expected:
            np.testing.assert_array_equal(np.asarray(values[key]), np.asarray(expected[key]))


# --------------------------------------------------------------- epoch fence
class TestEpochInState:
    def test_init_stamps_live_epoch(self):
        state = mt.MeanMetric().init()
        assert state.epoch == psync.world_epoch()

    def test_epoch_is_static_treedef_metadata(self):
        """A restamped tree has a DIFFERENT treedef — jit retraces, the
        in-graph analogue of the host plane's epoch fence."""
        state = mt.SumMetric().init()
        traces = []

        @jax.jit
        def f(st):
            traces.append(1)
            return jax.tree_util.tree_map(lambda x: x + 1, st)

        f(state)
        f(state)
        assert len(traces) == 1  # same epoch: cache hit
        bumped = f(state.with_epoch(state.epoch + 1))
        assert len(traces) == 2  # new epoch: new treedef, retrace
        assert isinstance(bumped, FuncState) and bumped.epoch == state.epoch + 1

    def test_stale_handoff_classifies_epoch_fault(self):
        m = mt.SumMetric()
        state = m.apply_update(m.init(), jnp.asarray([3.0]))
        trips = psync.collective_stats()["sync_epoch_fence_trips"]
        psync.bump_epoch("simulated membership transition")
        with pytest.raises(EpochFault):
            m.host_handoff(state)
        assert psync.collective_stats()["sync_epoch_fence_trips"] == trips + 1
        # shell state intact: nothing landed
        assert float(m.compute()) == 0.0
        # explicit restamp lands the same tree
        m.host_handoff(state.with_epoch(psync.world_epoch()))
        assert float(m.compute()) == 3.0


# ----------------------------------------------------------------- donation
class TestDonationSafety:
    def test_donated_step_never_aliases_template(self):
        m = mt.SumMetric()
        step = jax.jit(lambda st, x: m.apply_update(st, x), donate_argnums=0)
        state = m.init()
        state = step(state, jnp.asarray([2.0]))
        state = step(state, jnp.asarray([4.0]))
        assert float(m.apply_compute(state)) == 6.0
        # the donated buffers were fresh copies: the cached template's
        # defaults are untouched and a new tree starts at zero
        fresh = m.init()
        assert float(m.apply_compute(fresh)) == 0.0
        # and the live module shell never shared those buffers either
        assert float(m.compute()) == 0.0

    def test_funcstate_is_donatable(self):
        state = mt.SumMetric().init()
        leaves, treedef = jax.tree_util.tree_flatten(state)
        assert len(leaves) == 1
        assert engine.state_donatable(state)


# ---------------------------------------------------------- shard_map world
class TestInGraphMerge:
    """The zero-host-round-trip claim on an 8-device shard_map world."""

    C = 8

    def test_matches_host_sync_oracle_zero_host_collectives(self):
        mesh = Mesh(np.array(jax.devices()[:N_DEV]), ("dp",))
        m = mt.Accuracy(num_classes=self.C)
        preds, target = _cls_data(n=N_DEV * 16, c=self.C, seed=11)

        def f(p, t):
            st = m.apply_update(m.init(), p, t)
            return m.apply_compute(st, axis_name="dp")

        before = psync.collective_stats()["sync_collectives_issued"]
        value = jax.jit(
            shard_map(f, mesh=mesh, in_specs=(P("dp", None), P("dp")), out_specs=P())
        )(preds, target)
        assert psync.collective_stats()["sync_collectives_issued"] == before, (
            "the in-graph merge must issue ZERO host sync collectives"
        )

        # host-sync oracle: one module instance per rank fed that rank's
        # shard, merged through the host gather path
        ranks = [mt.Accuracy(num_classes=self.C) for _ in range(N_DEV)]
        for i, rank in enumerate(ranks):
            rank.update(
                preds[i * 16 : (i + 1) * 16], target[i * 16 : (i + 1) * 16]
            )
        gather = _FakeGather(ranks)
        with ranks[0].sync_context(dist_sync_fn=gather, distributed_available=DIST_ON):
            host_value = ranks[0].compute()
        np.testing.assert_array_equal(np.asarray(value), np.asarray(host_value))

    def test_collection_suite_in_one_step(self):
        mesh = Mesh(np.array(jax.devices()[:N_DEV]), ("dp",))
        suite = mt.MetricCollection(
            {"acc": mt.Accuracy(num_classes=self.C), "prec": mt.Precision(num_classes=self.C, average="macro")}
        )
        preds, target = _cls_data(n=N_DEV * 16, c=self.C, seed=5)

        def f(p, t):
            st = suite.apply_update(suite.init(), p, t)
            return suite.apply_compute(st, axis_name="dp")

        values = jax.jit(
            shard_map(f, mesh=mesh, in_specs=(P("dp", None), P("dp")), out_specs=P())
        )(preds, target)

        oracle = mt.MetricCollection(
            {"acc": mt.Accuracy(num_classes=self.C), "prec": mt.Precision(num_classes=self.C, average="macro")}
        )
        oracle.update(preds, target)
        expected = oracle.compute()
        assert set(values) == set(expected)
        for key in expected:
            np.testing.assert_array_equal(np.asarray(values[key]), np.asarray(expected[key]))

    def test_pspec_inference(self):
        mesh = Mesh(np.array(jax.devices()[:N_DEV]), ("dp",))
        states = {
            "tp": jnp.zeros((64,)),          # sum-reduced: replicate
            "preds": jnp.zeros((16, 4)),     # cat-kind: shard the sample axis
            "rows": [jnp.zeros((3,))],       # list state: host-owned, no placement
        }
        specs = {"tp": "sum", "preds": "cat", "rows": "cat"}
        pspecs = infer_state_pspecs(states, mesh, specs)
        assert pspecs["tp"] == P()
        assert pspecs["preds"] == P("dp")
        assert pspecs["rows"] is None


# ------------------------------------------------------------- handoff seam
class TestHostHandoff:
    def test_merged_handoff_serves_without_resync(self):
        m = mt.SumMetric()
        state = m.apply_update(m.init(), jnp.asarray([5.0]))
        out = m.host_handoff(state)
        assert out is m and m._is_synced
        # a sync context that WOULD merge again enters pre-synced: the
        # landed value is served as-is, no gather, no double merge
        peer = mt.SumMetric()
        peer.update(jnp.asarray([5.0]))
        with m.sync_context(dist_sync_fn=_FakeGather([m, peer]), distributed_available=DIST_ON):
            assert float(m.compute()) == 5.0
        # explicit unsync is an idempotent restore of the same tree
        m.unsync()
        assert not m._is_synced and float(m.compute()) == 5.0

    def test_unmerged_handoff_leaves_sync_armed(self):
        m = mt.SumMetric()
        state = m.apply_update(m.init(), jnp.asarray([2.0]))
        m.host_handoff(state, merged=False)
        assert not m._is_synced and m._cache is None
        peer = mt.SumMetric()
        peer.update(jnp.asarray([3.0]))
        with m.sync_context(dist_sync_fn=_FakeGather([m, peer]), distributed_available=DIST_ON):
            assert float(m.compute()) == 5.0  # per-rank partial: host sync merges
        # local state restored after the context (the compute cache keeps the
        # merged value until the next update, as on the host path)
        m.update(jnp.asarray([0.0]))
        assert float(m.compute()) == 2.0

    def test_collection_handoff(self):
        suite = mt.MetricCollection({"mean": mt.MeanMetric(), "total": mt.SumMetric()})
        state = suite.apply_update(suite.init(), jnp.asarray([2.0, 4.0]))
        before = funcore_stats()
        suite.host_handoff(state)
        after = funcore_stats()
        assert after["funcore_handoffs"] - before["funcore_handoffs"] == 1
        assert after["funcore_handoff_nodes"] - before["funcore_handoff_nodes"] == 2
        values = suite.compute()
        assert float(values["mean"]) == 3.0 and float(values["total"]) == 6.0


# ------------------------------------------------------------- caching pins
class TestCachingPins:
    def test_export_built_inside_trace_stays_concrete(self):
        # The first export build may happen INSIDE a jit/shard_map trace (a
        # user's first call is their training step). The cached template's
        # reset state must still be concrete — a build that binds to the
        # ambient trace caches leaked tracers and every later host-side
        # init() dies with UnexpectedTracerError.
        suite = mt.MetricCollection(
            {
                "acc": mt.Accuracy(num_classes=4, average="macro"),
                "prec": mt.Precision(num_classes=4, average="macro"),
            }
        )
        preds, target = _cls_data(n=N_DEV * 8, c=4, seed=3)

        def step(p, t):
            st = suite.apply_update(suite.init(), p, t)
            return suite.apply_compute(st, axis_name="dp")

        mesh = jax.sharding.Mesh(np.array(jax.devices()), ("dp",))
        jax.jit(
            shard_map(step, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P())
        )(preds, target)
        # host-side init/update/compute on the SAME cached export must work
        state = suite.init()
        for leaf in jax.tree_util.tree_leaves(state):
            assert isinstance(leaf, jax.Array) and not isinstance(
                leaf, jax.core.Tracer
            )
        state = suite.apply_update(state, preds, target)
        vals = suite.apply_compute(state)
        assert all(np.isfinite(float(v)) for v in vals.values())

    def test_one_export_build_per_config(self):
        m = mt.Accuracy(num_classes=8)
        before = funcore_stats()
        state = m.init()
        for seed in range(5):
            state = m.apply_update(state, *_cls_data(seed=seed))
        m.apply_compute(state)
        after = funcore_stats()
        assert after["funcore_exports"] - before["funcore_exports"] == 1, (
            "a hot loop must build the export template ONCE per config"
        )
        assert after["funcore_export_hits"] - before["funcore_export_hits"] == 6
        # a config change invalidates the fingerprint key: fresh build
        m.persistent(True)  # persistence is not fingerprinted — still cached
        m.init()
        assert funcore_stats()["funcore_exports"] - before["funcore_exports"] == 1

    def test_export_cache_dropped_on_clone(self):
        import copy

        m = mt.MeanMetric()
        m.init()
        assert "_funcore_export" in m.__dict__
        clone = copy.deepcopy(m)
        assert "_funcore_export" not in clone.__dict__

    def test_distributed_available_single_resolution(self):
        psync.invalidate_distributed_cache()
        before = psync.collective_stats()["sync_dist_resolutions"]
        for _ in range(5):
            psync.distributed_available()
        assert psync.collective_stats()["sync_dist_resolutions"] == before + 1, (
            "the backend walk must be memoized after the first resolution"
        )
        psync.invalidate_distributed_cache()
        psync.distributed_available()
        assert psync.collective_stats()["sync_dist_resolutions"] == before + 2

    def test_window_value_memoized_between_closes(self):
        win = streaming.Windowed(mt.SumMetric(), window=2, stride=2, name="memo")
        for i in range(2):
            win.update(jnp.asarray([float(i)]))
        first = win.value()
        before = streaming.streaming_stats()["window_value_cache_hits"]
        assert np.array_equal(np.asarray(win.value()), np.asarray(first))
        assert np.array_equal(np.asarray(win.value()), np.asarray(first))
        assert streaming.streaming_stats()["window_value_cache_hits"] == before + 2
        # the next close invalidates the memo
        for i in range(2):
            win.update(jnp.asarray([10.0 + i]))
        assert float(win.value()) == 21.0

    def test_decay_layout_memoized_across_ticks(self):
        ema = streaming.Decayed(mt.SumMetric(), halflife=2.0, name="memo-ema")
        before = streaming.streaming_stats()["window_decay_layout_reuses"]
        for x in (1.0, 2.0, 4.0, 8.0):
            ema.update(jnp.asarray([x]))
        reuses = streaming.streaming_stats()["window_decay_layout_reuses"] - before
        assert reuses >= 2, "decay ticks after the first must reuse the dtype layout"
