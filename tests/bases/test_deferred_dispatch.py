"""Deferred micro-batched dispatch contract (ISSUE 3 tentpole).

Eligible eager ``update``/``forward`` calls enqueue into a pending queue and
flush as stacked donated-state ``lax.scan`` programs at the size/age
threshold or at the next state observation. Pins:

- queue-flushed results are BIT-EXACT against the unbatched eager oracle
  (``np.testing.assert_array_equal`` — no tolerance widening), including
  mid-queue observations (compute/reset/clone/pickle/state access/sync
  surfaces), order-sensitive states (MinMax extrema, max/min reductions),
  RNG-consuming wrappers (BootStrapper seeded replay), and compute-group
  collections;
- ``forward`` returns a lazy handle that forces the flush only when read;
- flush dispatch count amortizes (one stacked program per bucket, not one
  per call), observable via ``engine.engine_stats()``;
- ``METRICS_TPU_DEFER=0`` / ``set_deferred_dispatch(False)`` restores the
  PR-1 per-call fused dispatch exactly (single-step program builds again).
"""
from __future__ import annotations

import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu.ops import engine
from metrics_tpu.utils import checks

RNG = np.random.RandomState(11)
P = jnp.asarray(RNG.rand(64).astype(np.float32))
T = jnp.asarray(RNG.randint(0, 2, 64))
A = jnp.asarray(RNG.rand(48).astype(np.float32))
B = jnp.asarray(RNG.rand(48).astype(np.float32))


@pytest.fixture(autouse=True)
def _first_mode_deferred():
    checks.set_validation_mode("first")
    engine.set_deferred_dispatch(True)
    yield
    engine.set_deferred_dispatch(True)
    checks.set_validation_mode("first")


def _with_deferral(enabled, fn):
    engine.set_deferred_dispatch(enabled)
    try:
        return fn()
    finally:
        engine.set_deferred_dispatch(True)


def _assert_tree_equal(a, b):
    la, ta = jax.tree.flatten(a)
    lb, tb = jax.tree.flatten(b)
    assert ta == tb
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestUpdateQueue:
    def test_updates_enqueue_and_flush_amortized(self):
        m = mt.Accuracy()
        m.update(P, T)  # first call per signature: eager, validated
        s0 = engine.engine_stats()
        for _ in range(16):
            m.update(P, T)
        assert m._defer_pending is not None
        assert len(m._defer_pending.entries) == 16
        assert engine.engine_stats()["deferred_steps"] - s0["deferred_steps"] == 16
        value = m.compute()  # observation: one stacked flush
        assert m._defer_pending is None
        s1 = engine.engine_stats()
        assert s1["deferred_flushes"] - s0["deferred_flushes"] == 1

        def oracle():
            e = mt.Accuracy()
            for _ in range(17):
                e.update(P, T)
            return e.compute()

        np.testing.assert_array_equal(np.asarray(value), np.asarray(_with_deferral(False, oracle)))

    def test_direct_state_access_is_an_observation(self):
        m = mt.MeanSquaredError()
        m.update(A, B)
        for _ in range(5):
            m.update(A, B)
        assert "sum_squared_error" not in m.__dict__  # popped while pending
        total = m.sum_squared_error  # __getattr__ barrier flushes

        def oracle():
            e = mt.MeanSquaredError()
            for _ in range(6):
                e.update(A, B)
            return e.sum_squared_error

        np.testing.assert_array_equal(np.asarray(total), np.asarray(_with_deferral(False, oracle)))

    def test_size_threshold_triggers_flush(self):
        engine.set_deferred_dispatch(True, max_pending=4)
        try:
            m = mt.MeanMetric()
            x = jnp.asarray(RNG.rand(8).astype(np.float32))
            m.update(x)
            for _ in range(4):
                m.update(x)
            # the 4th enqueue hit the threshold and flushed
            assert m._defer_pending is None
        finally:
            engine.set_deferred_dispatch(True, max_pending=128)

    def test_signature_change_flushes_in_enqueue_order(self):
        short = jnp.asarray(RNG.rand(16).astype(np.float32))

        def run():
            m = mt.MeanMetric()
            for _ in range(2):  # license both signatures
                m.update(A)
                m.update(short)
            for _ in range(3):
                m.update(A)
                m.update(short)  # each switch flushes the previous queue
            return m.compute()

        deferred = run()
        eager = _with_deferral(False, run)
        np.testing.assert_array_equal(np.asarray(deferred), np.asarray(eager))


class TestLazyForward:
    def test_forward_returns_lazy_handle_bitexact(self):
        m = mt.Accuracy()
        first = m(P, T)
        assert isinstance(first, jax.Array)  # first per signature: eager
        handles = [m(P, T) for _ in range(5)]
        assert all(isinstance(h, engine.LazyValue) for h in handles)
        assert m._defer_pending is not None  # unread handles: no flush yet
        vals = [float(h) for h in handles]

        def oracle():
            e = mt.Accuracy()
            return [float(e(P, T)) for _ in range(6)]

        assert [float(first)] + vals == _with_deferral(False, oracle)

    def test_lazy_handle_interfaces(self):
        m = mt.Accuracy()
        m(P, T)
        h = m(P, T)
        assert isinstance(h, engine.LazyValue)
        as_np = np.asarray(h)
        as_jnp = jnp.asarray(h)
        np.testing.assert_array_equal(as_np, np.asarray(as_jnp))
        assert float(h + 1.0) == float(as_np) + 1.0
        assert h.shape == as_jnp.shape
        assert bool(h <= 1.0)
        assert f"{float(h):.3f}" == f"{float(as_np):.3f}"

    def test_unread_handles_resolve_at_state_observation(self):
        m = mt.Accuracy()
        m(P, T)
        handles = [m(P, T) for _ in range(3)]
        _ = m.compute()  # observation flushes the queue
        assert all(h._ready for h in handles)


MIX_CASES = [
    ("Accuracy", lambda: mt.Accuracy(), (P, T)),
    ("MSE", lambda: mt.MeanSquaredError(), (A, B)),
    ("MeanMetric", lambda: mt.MeanMetric(), (A,)),
    ("MaxMetric", lambda: mt.MaxMetric(), (A,)),  # order-sensitive reduction spec
    ("MinMetric", lambda: mt.MinMetric(), (A,)),
]


class TestMidQueueObservationOrdering:
    """Interleave update/compute/reset/clone/pickle/sync with a NON-EMPTY
    queue and pin bit-exact equality with the unbatched eager oracle."""

    @pytest.mark.parametrize("name,factory,batch", MIX_CASES, ids=[c[0] for c in MIX_CASES])
    def test_interleaved_script_bitexact(self, name, factory, batch):
        def script(m):
            out = []
            m.update(*batch)
            m.update(*batch)
            out.append(m.compute())          # mid-queue compute
            m.update(*batch)
            out.append(m(*batch))            # forward mixed into update stream
            m.update(*batch)
            c = m.clone()                    # mid-queue clone (deepcopy)
            out.append(c.compute())
            m.update(*batch)
            m2 = pickle.loads(pickle.dumps(m))  # mid-queue pickle
            out.append(m2.compute())
            m.sync(should_sync=False)        # explicit sync surface (no-op dist)
            out.append(m.metric_state)       # state observation
            m.reset()                        # mid-script reset
            m.update(*batch)
            out.append(m.compute())
            return out

        deferred = script(factory())
        eager = _with_deferral(False, lambda: script(factory()))
        for d, e in zip(deferred, eager):
            _assert_tree_equal(
                jax.tree.map(lambda v: np.asarray(v), d if not isinstance(d, engine.LazyValue) else d._force()),
                jax.tree.map(lambda v: np.asarray(v), e),
            )

    def test_minmax_wrapper_interleaved(self):
        p2 = jnp.asarray(RNG.rand(64).astype(np.float32))

        def script(m):
            out = []
            out.append(m(P, T))
            out.append(m(p2, T))
            out.append(m.compute())
            out.append(m(P, T))
            out.append(m.compute())
            return jax.tree.map(lambda v: np.asarray(v), out)

        deferred = script(mt.MinMaxMetric(mt.Accuracy()))
        eager = _with_deferral(False, lambda: script(mt.MinMaxMetric(mt.Accuracy())))
        _assert_tree_equal(deferred, eager)

    def test_bootstrapper_rng_replay(self):
        def script(seed):
            b = mt.BootStrapper(mt.MeanSquaredError(), num_bootstraps=4)
            b._rng = np.random.RandomState(seed)
            out = []
            b.update(A, B)
            b.update(A, B)
            out.append(b.compute())          # mid-stream observation
            b.update(A, B)
            b.update(A, B)
            out.append(b.compute())
            out.append([m.metric_state for m in b.metrics])
            return jax.tree.map(lambda v: np.asarray(v), out)

        deferred = script(3)
        eager = _with_deferral(False, lambda: script(3))
        _assert_tree_equal(deferred, eager)


class TestCollections:
    C = 4

    def _data(self):
        rng = np.random.RandomState(5)
        probs = rng.rand(32, self.C).astype(np.float32)
        probs /= probs.sum(1, keepdims=True)
        return jnp.asarray(probs), jnp.asarray(rng.randint(0, self.C, 32))

    def _suite(self):
        # Precision/Recall share identical stat states → one compute group
        return mt.MetricCollection(
            {
                "prec": mt.Precision(num_classes=self.C, average="macro"),
                "rec": mt.Recall(num_classes=self.C, average="macro"),
                "acc": mt.Accuracy(num_classes=self.C, average="macro"),
            }
        )

    def test_update_uses_one_suite_queue(self):
        p, t = self._data()
        col = self._suite()
        col.update(p, t)  # first call: member-wise, groups derived
        assert col._groups_checked
        for _ in range(6):
            col.update(p, t)
        q = col._defer_pending
        assert q is not None and q.kind == "collection-update"
        assert len(q.entries) == 6

        def oracle():
            c = self._suite()
            for _ in range(7):
                c.update(p, t)
            return c.compute()

        res = col.compute()
        eager = _with_deferral(False, oracle)
        assert set(res) == set(eager)
        for k in res:
            np.testing.assert_array_equal(np.asarray(res[k]), np.asarray(eager[k]))

    def test_forward_interleaved_with_compute(self):
        p, t = self._data()

        def script(c):
            out = []
            out.append(c(p, t))
            out.append(c(p, t))
            out.append(c.compute())   # mid-queue observation
            out.append(c(p, t))
            c.reset()
            out.append(c(p, t))
            out.append(c.compute())
            return out

        deferred = script(self._suite())
        eager = _with_deferral(False, lambda: script(self._suite()))
        for d, e in zip(deferred, eager):
            assert set(d) == set(e)
            for k in e:
                dv = d[k]._force() if isinstance(d[k], engine.LazyValue) else d[k]
                np.testing.assert_array_equal(np.asarray(dv), np.asarray(e[k]))

    def test_new_kwarg_mid_queue_is_not_dropped(self):
        """A kwarg appearing after the suite queue opened (e.g. a weight a
        member optionally consumes) must leave the fast path — not be
        silently filtered to the queue-opening call's kwarg set."""
        x = jnp.asarray(RNG.rand(16).astype(np.float32))
        w = jnp.asarray((RNG.rand(16) * 2).astype(np.float32))

        def script(c):
            c.update(x)
            c.update(x, weight=w)  # license both signatures
            for _ in range(3):
                c.update(x)        # opens the no-kwarg queue
            c.update(x, weight=w)  # NEW kwarg: must flush + take its own path
            c.update(x, weight=w)
            return c.compute()

        make = lambda: mt.MetricCollection({"mean": mt.MeanMetric()})
        deferred = script(make())
        eager = _with_deferral(False, lambda: script(make()))
        for k in eager:
            np.testing.assert_array_equal(np.asarray(deferred[k]), np.asarray(eager[k]))

    def test_mode_switch_mid_queue_regains_full_validation(self):
        """Switching to validation mode 'full' while a suite queue is open
        must stop enqueueing immediately (per-call checks resume)."""
        p, t = self._data()
        col = self._suite()
        col.update(p, t)
        for _ in range(3):
            col.update(p, t)
        assert col._defer_pending is not None
        checks.set_validation_mode("full")
        try:
            col.update(p, t)  # flushes the queue, runs fully validated
            assert col._defer_pending is None
        finally:
            checks.set_validation_mode("first")

    def test_member_state_access_flushes_suite_queue(self):
        p, t = self._data()
        col = self._suite()
        col.update(p, t)
        for _ in range(3):
            col.update(p, t)
        assert col._defer_pending is not None
        # direct member state access is an observation of the WHOLE suite
        _ = col["acc"].compute()
        assert col._defer_pending is None


class TestEscapeHatch:
    def test_defer_off_restores_per_call_fused_dispatch(self):
        engine.set_deferred_dispatch(False)
        try:
            m = mt.Accuracy()
            for _ in range(4):
                m.update(P, T)
            # the PR-1 contract: single-step fused program built and no queue
            assert m._fused_update_program is not None
            assert m._defer_pending is None
        finally:
            engine.set_deferred_dispatch(True)

    def test_env_var_controls_default(self, monkeypatch):
        import metrics_tpu.ops.engine as eng

        monkeypatch.setattr(eng, "_defer_enabled", None)
        monkeypatch.setenv("METRICS_TPU_DEFER", "0")
        assert not eng.defer_enabled()
        monkeypatch.setattr(eng, "_defer_enabled", None)
        monkeypatch.delenv("METRICS_TPU_DEFER", raising=False)
        assert eng.defer_enabled()
        monkeypatch.setattr(eng, "_defer_enabled", None)

    def test_full_validation_mode_disables_deferral(self):
        checks.set_validation_mode("full")
        m = mt.Accuracy()
        for _ in range(3):
            m.update(P, T)
        assert m._defer_pending is None

    def test_flush_failure_replays_eagerly_and_disables(self, monkeypatch):
        m = mt.MeanMetric()
        m.update(A)
        for _ in range(3):
            m.update(A)
        assert m._defer_pending is not None
        # force the stacked flush to die: the queue must replay eagerly,
        # keep the values exact, and disable deferral for the instance
        monkeypatch.setattr(
            type(m), "_build_deferred_update", lambda self, *a: (_ for _ in ()).throw(RuntimeError("boom"))
        )
        with pytest.warns(UserWarning, match="Replaying the queue eagerly"):
            value = m.compute()
        assert not m._defer_ok

        def oracle():
            e = mt.MeanMetric()
            for _ in range(4):
                e.update(A)
            return e.compute()

        np.testing.assert_array_equal(np.asarray(value), np.asarray(_with_deferral(False, oracle)))
        # later updates keep working on the per-call path
        m.update(A)
        assert m._defer_pending is None


class TestLazyHandleCopy:
    def test_copy_and_pickle_of_unresolved_handle_resolve(self):
        """Copying/pickling a LazyValue is an observation: the copy is a
        detached RESOLVED handle, never a deep-copied queue binding (whose
        id-keyed backing lookup would raise an opaque KeyError on read)."""
        import copy as _copy

        m = mt.Accuracy()
        m(P, T)
        h = m(P, T)
        hc = _copy.deepcopy(h)  # forces the flush
        np.testing.assert_array_equal(np.asarray(hc), np.asarray(h))
        h2 = m(P, T)
        hp = pickle.loads(pickle.dumps(h2))
        np.testing.assert_array_equal(np.asarray(hp), np.asarray(h2))
        # the copies are detached: further reads cost no queue machinery
        assert hc._queue is None and hp._queue is None


class TestProgramSharing:
    def test_flush_shares_forward_many_scan_program(self):
        """The deferred flush acquires through the same engine key as
        forward_many — one compiled scan program serves both."""
        engine.reset_engine()
        m = mt.Accuracy()
        m(P, T)
        for _ in range(4):
            m(P, T)
        _ = m.compute()  # flush: builds the "many" program for this layout
        builds_after_flush = engine.engine_stats()["builds"]
        m2 = mt.Accuracy()
        stacked_p = jnp.stack([P] * 4)
        stacked_t = jnp.stack([T] * 4)
        m2.forward_many(stacked_p, stacked_t)  # first chunk: eager replay
        m2.forward_many(stacked_p, stacked_t)  # scan path: cache hit expected
        assert engine.engine_stats()["builds"] == builds_after_flush
