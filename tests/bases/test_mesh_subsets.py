"""Sync scoping over mesh-axis subsets — the `process_group` analogue.

Parity target: the reference restricts sync scope with a `process_group`
object (`src/torchmetrics/metric.py:105,368`); here scope is the mesh axis
name handed to the collective. These tests pin the scoping semantics on a 2D
``(host, dp)`` mesh: reducing over ``"dp"`` combines within each host row
only, ``("host", "dp")`` combines globally, and cat-states gather exactly the
rows of the chosen axis.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import metrics_tpu as mt
from metrics_tpu.parallel.collectives import sync_pytree


def shard_map(f, **kw):
    kw.setdefault("check_vma", False)
    return jax.shard_map(f, **kw)


def _mesh2d():
    return Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("host", "dp"))


def test_sum_scoped_to_subaxis():
    """psum over "dp" reduces within each host row independently."""
    mesh = _mesh2d()

    def f(x):
        return sync_pytree({"s": x}, {"s": "sum"}, "dp")["s"]

    x = jnp.arange(8.0).reshape(2, 4)  # host row 0: 0..3, row 1: 4..7
    out = jax.jit(
        shard_map(f, mesh=mesh, in_specs=P("host", "dp"), out_specs=P("host", None))
    )(x)
    # row sums replicated along dp, distinct per host row
    np.testing.assert_allclose(np.asarray(out).ravel(), [6.0, 22.0])


def test_sum_scoped_globally():
    """psum over both axes reduces across the whole mesh."""
    mesh = _mesh2d()

    def f(x):
        return sync_pytree({"s": x}, {"s": "sum"}, ("host", "dp"))["s"]

    x = jnp.arange(8.0).reshape(2, 4)
    out = jax.jit(
        shard_map(f, mesh=mesh, in_specs=P("host", "dp"), out_specs=P(None, None))
    )(x)
    assert float(np.asarray(out).ravel()[0]) == 28.0


def test_cat_scoped_to_subaxis():
    """all_gather over "dp" concatenates the 4 row-local shards only."""
    mesh = _mesh2d()

    def f(x):
        # x block: (1, 1) → row-local gather along dp gives (4,)
        return sync_pytree({"c": x[0]}, {"c": "cat"}, "dp")["c"][None]

    x = jnp.arange(8.0).reshape(2, 4)
    out = jax.jit(
        shard_map(f, mesh=mesh, in_specs=P("host", "dp"), out_specs=P("host", None))
    )(x)
    # each host row gathered its own four values
    np.testing.assert_allclose(np.asarray(out)[0], [0, 1, 2, 3])
    np.testing.assert_allclose(np.asarray(out)[1], [4, 5, 6, 7])


def test_custom_callable_reduction_spmd():
    """A custom dist_reduce_fx callable runs on the stacked per-device states."""
    mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))

    def geometric_mean(stacked):
        return jnp.exp(jnp.mean(jnp.log(stacked), axis=0))

    def f(x):
        return sync_pytree({"g": x}, {"g": geometric_mean}, "dp")["g"]

    x = jnp.asarray([1.0, 2.0, 4.0, 8.0])
    out = jax.jit(shard_map(f, mesh=mesh, in_specs=P("dp"), out_specs=P()))(x)
    np.testing.assert_allclose(float(np.asarray(out).ravel()[0]), (1 * 2 * 4 * 8) ** 0.25, rtol=1e-6)


def test_metric_compute_on_subaxis():
    """A real metric's as_functions compute scoped to a sub-axis: each host row
    computes accuracy over its own row's data only."""
    mesh = _mesh2d()
    init, upd, cmp = mt.Accuracy(num_classes=3).as_functions()

    rng = np.random.RandomState(0)
    preds = rng.rand(8, 16, 3).astype(np.float32)  # (devices, per-device batch, C)
    target_row0 = preds[:4].argmax(-1)  # host row 0: all correct
    target_row1 = (preds[4:].argmax(-1) + 1) % 3  # host row 1: all wrong
    target = np.concatenate([target_row0, target_row1]).astype(np.int32)
    preds = preds.reshape(2, 4, 16, 3)
    target = target.reshape(2, 4, 16)

    def f(p, t):
        st = upd(init(), p[0, 0], t[0, 0])
        return cmp(st, axis_name="dp")[None, None]

    out = jax.jit(
        shard_map(
            f,
            mesh=mesh,
            in_specs=(P("host", "dp"), P("host", "dp")),
            out_specs=P("host", None),
        )
    )(jnp.asarray(preds), jnp.asarray(target))
    vals = np.asarray(out).ravel()
    assert vals[0] == pytest.approx(1.0)
    assert vals[1] == pytest.approx(0.0)


def test_cat_state_metric_spmd_end_to_end():
    """A cat-state metric (CosineSimilarity: raw rows kept per device) under
    shard_map equals the single-device result — per-device shards stay in HBM
    until the gather inside compute (SURVEY §5 long-sequence analogue)."""
    mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
    metric = mt.CosineSimilarity(reduction="mean")
    init, upd, cmp = metric.as_functions()

    rng = np.random.RandomState(1)
    preds = rng.randn(64, 8).astype(np.float32)
    target = rng.randn(64, 8).astype(np.float32)

    def f(p, t):
        st = upd(init(), p, t)
        return cmp(st, axis_name="dp")

    spmd = jax.jit(shard_map(f, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P()))(
        jnp.asarray(preds), jnp.asarray(target)
    )

    oracle = mt.CosineSimilarity(reduction="mean")
    oracle.update(preds, target)
    np.testing.assert_allclose(float(spmd), float(oracle.compute()), atol=1e-6)


def test_inferred_hyperparams_flow_to_compute_fn():
    """Metrics that infer num_classes/pos_label from the first batch must
    carry the inference into the pure-function export's compute (regression
    test for the as_functions template-propagation fix)."""
    init, upd, cmp = mt.AveragePrecision().as_functions()
    rng = np.random.RandomState(1)
    preds = rng.rand(64).astype(np.float32)
    target = (rng.rand(64) > 0.5).astype(np.int32)
    st = upd(init(), preds, target)  # eager: exact curves are host-side
    oracle = mt.AveragePrecision()
    oracle.update(preds, target)
    np.testing.assert_allclose(float(cmp(st)), float(oracle.compute()), atol=1e-6)


@pytest.mark.parametrize("case", ["binary", "multiclass"])
@pytest.mark.parametrize("metric", ["AveragePrecision", "PrecisionRecallCurve", "ROC", "AUROC"])
def test_restored_state_computes_in_fresh_export(metric, case):
    """Checkpoint-restore across processes: a state produced by one export
    must compute correctly through a brand-new export whose update never ran
    (the curve family re-derives shape-inferred hyperparams from its stored
    data at compute time)."""
    rng = np.random.RandomState(3)
    if case == "binary":
        preds = rng.rand(64).astype(np.float32)
        target = (rng.rand(64) > 0.5).astype(np.int32)
        kwargs = {}
    else:
        # multiclass requires explicit num_classes (reference parity) — but
        # AUROC's data `mode` is still update-inferred and must be re-derived
        p = rng.rand(64, 4).astype(np.float32)
        preds = p / p.sum(-1, keepdims=True)
        target = rng.randint(0, 4, 64)
        kwargs = {"num_classes": 4}

    klass = getattr(mt, metric)
    _, upd, _ = klass(**kwargs).as_functions()
    st = upd(klass(**kwargs).as_functions()[0](), preds, target)

    # "fresh process": a new export that never saw an update
    _, _, cmp_fresh = klass(**kwargs).as_functions()
    restored = cmp_fresh(st)

    oracle = klass(**kwargs)
    oracle.update(preds, target)
    expected = oracle.compute()
    got = restored if isinstance(restored, (tuple, list)) else [restored]
    want = expected if isinstance(expected, (tuple, list)) else [expected]
    for g, w in zip(got, want):
        for gi, wi in zip(g if isinstance(g, list) else [g], w if isinstance(w, list) else [w]):
            np.testing.assert_allclose(np.asarray(gi), np.asarray(wi), atol=1e-6)


def test_mean_metric_weighted_on_subaxis():
    """MeanMetric's weighted mean syncs correctly when scoped to a sub-axis."""
    mesh = _mesh2d()
    init, upd, cmp = mt.MeanMetric().as_functions()

    def f(v, w):
        st = upd(init(), v[0], w[0])
        return cmp(st, axis_name="dp")[None, None]

    vals = jnp.arange(8.0).reshape(2, 4, 1)
    wts = jnp.asarray([1.0, 2.0, 3.0, 4.0] * 2).reshape(2, 4, 1)
    out = jax.jit(
        shard_map(
            f, mesh=mesh, in_specs=(P("host", "dp"), P("host", "dp")), out_specs=P("host", None)
        )
    )(vals, wts)
    flat = np.asarray(out).ravel()
    assert flat[0] == pytest.approx((0 * 1 + 1 * 2 + 2 * 3 + 3 * 4) / 10)
    assert flat[1] == pytest.approx((4 * 1 + 5 * 2 + 6 * 3 + 7 * 4) / 10)
