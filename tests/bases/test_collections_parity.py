"""MetricCollection differential tests vs the mounted reference.

The composition layer's observable contract on identical data: output dict
keys under prefix/postfix/nesting, compute-group results matching ungrouped
results, kwarg filtering across heterogeneous update signatures, and clone
independence — each cell runs both stacks side by side.
"""
from __future__ import annotations

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from tests.helpers.reference_oracle import get_reference
from tests.helpers.testers import assert_dict_outputs_equal

_ref = get_reference()
pytestmark = pytest.mark.skipif(_ref is None, reason="reference mount unavailable")

import metrics_tpu as mt  # noqa: E402

RNG = np.random.RandomState(37)
PREDS = RNG.rand(64, 5).astype(np.float32)
PREDS /= PREDS.sum(1, keepdims=True)
TARGET = RNG.randint(0, 5, 64)


def _suites(**kwargs):
    ours = mt.MetricCollection(
        {
            "acc": mt.Accuracy(num_classes=5, average="macro"),
            "prec": mt.Precision(num_classes=5, average="macro"),
            "rec": mt.Recall(num_classes=5, average="macro"),
        },
        **kwargs,
    )
    ref = _ref.MetricCollection(
        {
            "acc": _ref.Accuracy(num_classes=5, average="macro"),
            "prec": _ref.Precision(num_classes=5, average="macro"),
            "rec": _ref.Recall(num_classes=5, average="macro"),
        },
        **kwargs,
    )
    return ours, ref


def _assert_same_outputs(ours_out, ref_out):
    assert_dict_outputs_equal(ours_out, {k: v.numpy() for k, v in ref_out.items()})


@pytest.mark.parametrize("kwargs", [{}, {"prefix": "train_"}, {"postfix": "_val"}, {"prefix": "a/", "postfix": "/b"}])
def test_naming_parity(kwargs):
    ours, ref = _suites(**kwargs)
    ours.update(jnp.asarray(PREDS), jnp.asarray(TARGET))
    ref.update(torch.tensor(PREDS), torch.tensor(TARGET))
    _assert_same_outputs(ours.compute(), ref.compute())


@pytest.mark.parametrize("compute_groups", [True, False])
def test_compute_groups_value_equivalence(compute_groups):
    """Grouped (state-shared) and ungrouped collections must agree with the
    reference bit-for-bit over multiple updates."""
    ours, ref = _suites(compute_groups=compute_groups)
    for start in (0, 32):
        ours.update(jnp.asarray(PREDS[start : start + 32]), jnp.asarray(TARGET[start : start + 32]))
        ref.update(torch.tensor(PREDS[start : start + 32]), torch.tensor(TARGET[start : start + 32]))
    _assert_same_outputs(ours.compute(), ref.compute())


def test_nested_collection_key_parity():
    """Constructor-list nesting flattens, keeping the inner prefix; the keys
    and values must match the reference. add_metrics(collection) is rejected
    by BOTH stacks (only the constructor flattens)."""
    ours = mt.MetricCollection(
        [mt.MetricCollection({"mse": mt.MeanSquaredError()}, prefix="reg_"), mt.MeanAbsoluteError()]
    )
    ref = _ref.MetricCollection(
        [_ref.MetricCollection({"mse": _ref.MeanSquaredError()}, prefix="reg_"), _ref.MeanAbsoluteError()]
    )
    p = RNG.randn(16).astype(np.float32)
    t = RNG.randn(16).astype(np.float32)
    ours.update(jnp.asarray(p), jnp.asarray(t))
    ref.update(torch.tensor(p), torch.tensor(t))
    _assert_same_outputs(ours.compute(), ref.compute())

    with pytest.raises(ValueError, match="Unknown input"):
        mt.MetricCollection({"mae": mt.MeanAbsoluteError()}).add_metrics(
            mt.MetricCollection({"mse": mt.MeanSquaredError()})
        )
    with pytest.raises(ValueError, match="Unknown input"):
        _ref.MetricCollection({"mae": _ref.MeanAbsoluteError()}).add_metrics(
            _ref.MetricCollection({"mse": _ref.MeanSquaredError()})
        )


def test_kwarg_filtering_across_signatures():
    """A collection mixing metrics whose updates take different kwargs must
    route each metric only the kwargs its signature accepts."""
    # MSE takes only (preds, target): the collection must DROP `indexes`
    # for it while the retrieval members receive it
    ours = mt.MetricCollection({"map": mt.RetrievalMAP(), "mrr": mt.RetrievalMRR(), "mse": mt.MeanSquaredError()})
    ref = _ref.MetricCollection({"map": _ref.RetrievalMAP(), "mrr": _ref.RetrievalMRR(), "mse": _ref.MeanSquaredError()})
    idx = np.asarray([0, 0, 1, 1], dtype=np.int64)
    preds = RNG.rand(4).astype(np.float32)
    target = np.asarray([1, 0, 0, 1], dtype=np.int64)
    ours.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(idx))
    ref.update(torch.tensor(preds), torch.tensor(target), indexes=torch.tensor(idx))
    _assert_same_outputs(ours.compute(), ref.compute())


def test_clone_is_independent_in_both():
    ours, ref = _suites()
    ours.update(jnp.asarray(PREDS), jnp.asarray(TARGET))
    ref.update(torch.tensor(PREDS), torch.tensor(TARGET))
    ours_clone = ours.clone(prefix="c_")
    ref_clone = ref.clone(prefix="c_")
    ours_clone.reset()
    ref_clone.reset()
    # resetting the clone must not touch the original
    _assert_same_outputs(ours.compute(), ref.compute())
    assert set(ours_clone.keys()) == set(ref_clone.keys())


def test_missing_kwarg_raises_in_both():
    ours = mt.MetricCollection({"map": mt.RetrievalMAP()})
    ref = _ref.MetricCollection({"map": _ref.RetrievalMAP()})
    ours_exc = ref_exc = None
    try:
        ours.update(jnp.asarray([0.5, 0.2]), jnp.asarray([1, 0]))
    except (ValueError, TypeError) as err:
        ours_exc = type(err)
    try:
        ref.update(torch.tensor([0.5, 0.2]), torch.tensor([1, 0]))
    except (ValueError, TypeError) as err:
        ref_exc = type(err)
    assert ours_exc is not None and ref_exc is not None
    assert ours_exc is ref_exc  # exception-type parity for migrating catch blocks
