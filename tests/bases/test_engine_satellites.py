"""Round-5 advisor findings fixed alongside the dispatch engine, pinned.

- BootStrapper prefetch: a ``sampling_strategy`` flip mid-stream must drop
  the lookahead draw and rewind the RNG (a prefetched poisson COUNT matrix
  must never be consumed as multinomial INDEX draws).
- ``weighted_state_apply``: integer/count sum-states contract exactly in
  their own dtype (the float32 path truncated past 2^24).
- Per-owner eviction diagnostics: the "first"-mode cache-churn warning
  names the churning instance and fires once per owner.
- Host fast lane semantics: a new signature falls off the lane and gets the
  full validated path; "full" mode disables lanes.
- SQuAD host accumulation: pending totals fold into device states at every
  observation surface (compute, state_dict, snapshot, forward).
"""
from __future__ import annotations

import warnings

import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu.utils import checks
from metrics_tpu.wrappers._fanout import weighted_state_apply


@pytest.fixture(autouse=True)
def _first_mode():
    checks.set_validation_mode("first")
    yield
    checks.set_validation_mode("first")


RNG = np.random.RandomState(11)


class TestPrefetchStrategyFlip:
    P = jnp.asarray(np.random.RandomState(21).rand(64).astype(np.float32))
    T = jnp.asarray(np.random.RandomState(22).rand(64).astype(np.float32))

    def _run(self, flip_after: int, fused: bool) -> list:
        b = mt.BootStrapper(mt.MeanSquaredError(), num_bootstraps=4)
        b._rng = np.random.RandomState(1234)
        if not fused:
            object.__setattr__(b, "_boot_ok", False)  # never prefetches
        p, t = self.P, self.T
        for i in range(flip_after):
            b.update(p, t)
        b.sampling_strategy = "multinomial"
        for _ in range(2):
            b.update(p, t)
        return [np.asarray(m.metric_state["total"]) for m in b.metrics] + [
            np.asarray(m.metric_state["sum_squared_error"]) for m in b.metrics
        ]

    def test_strategy_flip_drops_prefetch_and_rewinds_rng(self):
        # enough poisson steps that the fused path ran and stored a lookahead
        fused_states = self._run(flip_after=4, fused=True)
        eager_states = self._run(flip_after=4, fused=False)
        for f, e in zip(fused_states, eager_states):
            np.testing.assert_allclose(f, e, rtol=1e-4, atol=1e-5)

    def test_prefetch_tuple_carries_strategy(self):
        b = mt.BootStrapper(mt.MeanSquaredError(), num_bootstraps=2)
        p = jnp.asarray(RNG.rand(32).astype(np.float32))
        t = jnp.asarray(RNG.rand(32).astype(np.float32))
        for _ in range(4):
            b.update(p, t)
        pf = b._boot_prefetch
        assert pf is not None and pf[1] == "poisson"
        b.sampling_strategy = "multinomial"
        assert b._take_prefetch(32) is None  # strategy mismatch → dropped


class TestWeightedIntegerExactness:
    def test_count_state_exact_past_2_24(self):
        big = 2**24 + 3  # not representable in float32
        stacked = {"total": jnp.asarray([big], jnp.int32)}
        deltas = {"total": jnp.asarray([1, 1], jnp.int32)}
        weights = jnp.ones((1, 2), jnp.int32)
        out = weighted_state_apply(stacked, deltas, weights)
        assert int(out["total"][0]) == big + 2  # float32 would land on an even neighbor

    def test_float_weights_round_into_integer_state(self):
        big = 2**24 + 1
        stacked = {"n": jnp.asarray([big], jnp.int32)}
        deltas = {"n": jnp.asarray([1, 1, 1], jnp.int32)}
        weights = jnp.asarray([[1.0, 0.0, 1.0]], jnp.float32)  # NaN-mask style
        out = weighted_state_apply(stacked, deltas, weights)
        assert int(out["n"][0]) == big + 2

    def test_float_states_unchanged_semantics(self):
        stacked = {"s": jnp.asarray([1.5], jnp.float32)}
        deltas = {"s": jnp.asarray([0.25, 0.25], jnp.float32)}
        weights = jnp.asarray([[2, 2]], jnp.int32)
        out = weighted_state_apply(stacked, deltas, weights)
        np.testing.assert_allclose(float(out["s"][0]), 2.5, rtol=1e-6)


class TestPerOwnerEvictionDiagnostics:
    def test_two_churning_instances_get_two_attributed_warnings(self, monkeypatch):
        monkeypatch.setattr(checks, "_SEEN_KEYS_CAP", 4)
        checks.set_validation_mode("first")
        m1, m2 = mt.Accuracy(), mt.Accuracy()
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            for size in range(8, 28):  # 20 distinct signatures per instance
                p = jnp.asarray(RNG.rand(size).astype(np.float32))
                t = jnp.asarray(RNG.randint(0, 2, size))
                m1.update(p, t)
                m2.update(p, t)
        texts = [str(w.message) for w in caught if "evicted more than" in str(w.message)]
        assert len(texts) == 2, texts
        assert all("`Accuracy`" in t for t in texts)
        assert f"0x{id(m1):x}" in "".join(texts) and f"0x{id(m2):x}" in "".join(texts)
        assert texts[0] != texts[1]  # distinct owners, distinct attributions

    def test_quiet_instance_never_warns(self, monkeypatch):
        monkeypatch.setattr(checks, "_SEEN_KEYS_CAP", 4)
        checks.set_validation_mode("first")
        churner, quiet = mt.Accuracy(), mt.Accuracy()
        pq = jnp.asarray(RNG.rand(16).astype(np.float32))
        tq = jnp.asarray(RNG.randint(0, 2, 16))
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            for size in range(30, 50):
                p = jnp.asarray(RNG.rand(size).astype(np.float32))
                t = jnp.asarray(RNG.randint(0, 2, size))
                churner.update(p, t)
                quiet.update(pq, tq)
        texts = [str(w.message) for w in caught if "evicted more than" in str(w.message)]
        assert len(texts) == 1
        assert f"0x{id(churner):x}" in texts[0]


class TestHostLaneSemantics:
    def test_new_signature_falls_off_lane_and_validates(self):
        cm = mt.CatMetric(nan_strategy="error")
        x = jnp.asarray(RNG.rand(8).astype(np.float32))
        cm.update(x)
        cm.update(x)
        assert cm._update_lane is not None
        bad = jnp.asarray(np.asarray([1.0, np.nan, 3.0], np.float32))
        with pytest.raises(RuntimeError, match="nan"):
            cm.update(bad)  # new signature → full path → "first"-mode check fires

    def test_full_mode_disables_lane(self):
        cm = mt.CatMetric()
        x = jnp.asarray(RNG.rand(8).astype(np.float32))
        cm.update(x)
        cm.update(x)
        assert cm._update_lane is not None
        checks.set_validation_mode("full")
        cm.update(x)  # generation bump kills the lane
        assert cm._update_lane is None

    def test_lane_values_match_full_path(self):
        lane_m = mt.CatMetric()
        x1 = jnp.asarray(RNG.rand(8).astype(np.float32))
        x2 = jnp.asarray(RNG.rand(8).astype(np.float32))
        for x in (x1, x2, x1, x2):
            lane_m.update(x)
        checks.set_validation_mode("full")
        full_m = mt.CatMetric()
        for x in (x1, x2, x1, x2):
            full_m.update(x)
        assert full_m._update_lane is None
        np.testing.assert_array_equal(
            np.asarray(lane_m.compute()), np.asarray(full_m.compute())
        )

    def test_retrieval_lane_matches_full_path(self):
        p = jnp.asarray(RNG.rand(32).astype(np.float32))
        t = jnp.asarray((RNG.rand(32) > 0.6).astype(np.int32))
        i = jnp.asarray(np.repeat(np.arange(8), 4).astype(np.int64))
        lane_m = mt.RetrievalMRR()
        for _ in range(4):
            lane_m.update(p, t, i)
        checks.set_validation_mode("full")
        full_m = mt.RetrievalMRR()
        for _ in range(4):
            full_m.update(p, t, i)
        assert full_m._update_lane is None
        np.testing.assert_allclose(float(lane_m.compute()), float(full_m.compute()), rtol=1e-6)

    def test_hyperparameter_change_kills_lane(self):
        cm = mt.CatMetric()
        x = jnp.asarray(RNG.rand(8).astype(np.float32))
        cm.update(x)
        cm.update(x)
        assert cm._update_lane is not None
        cm.nan_strategy = "ignore"
        assert cm._update_lane is None  # closure baked the old gate

    def test_compute_on_cpu_bypasses_lane(self):
        """Toggling compute_on_cpu after a lane installed must keep the
        per-update host offload running (review finding: the lane skipped
        _move_list_states_to_host)."""
        cm = mt.CatMetric()
        x = jnp.asarray(RNG.rand(8).astype(np.float32))
        cm.update(x)
        cm.update(x)
        assert cm._update_lane is not None
        cm.compute_on_cpu = True
        cm.update(x)
        assert all(isinstance(v, np.ndarray) for v in cm.value)


class TestSquadHostAccumulation:
    PREDS = [{"prediction_text": "london", "id": "q0"}]
    TARGET = [{"answers": {"answer_start": [0], "text": ["london"]}, "id": "q0"}]

    def test_states_fold_at_observation(self):
        sq = mt.SQuAD()
        for _ in range(3):
            sq.update(self.PREDS, self.TARGET)
        assert sq._pending is not None  # still buffered on host
        out = {k: float(v) for k, v in sq.compute().items()}
        assert out == {"exact_match": 100.0, "f1": 100.0}
        assert sq._pending is None
        assert int(sq.total) == 3

    def test_state_dict_sees_pending(self):
        sq = mt.SQuAD()
        sq.persistent(True)
        sq.update(self.PREDS, self.TARGET)
        sd = sq.state_dict()
        assert int(sd["total"]) == 1

    def test_forward_matches_reference_contract(self):
        sq = mt.SQuAD()
        batch_val = sq(self.PREDS, self.TARGET)
        assert round(float(batch_val["f1"]), 1) == 100.0
        sq.update(self.PREDS, self.TARGET)
        assert int(sq.compute()["exact_match"]) == 100
        assert sq._update_count == 2

    def test_reset_clears_pending(self):
        sq = mt.SQuAD()
        sq.update(self.PREDS, self.TARGET)
        sq.reset()
        assert sq._pending is None
        sq.update(self.PREDS, self.TARGET)
        assert int(sq.metric_state["total"]) == 1
