"""The fused (single-dispatch) forward path: semantics pinned vs eager.

After the first always-eager call, fusable metrics run forward as ONE jitted
program (batch update + batch compute + state merge). These tests require
bit-level agreement with the eager path across every reduction spec, the
documented fallbacks (list states, validation mode "full"), inferred-attr
propagation, and pickling after fused use.
"""
from __future__ import annotations

import pickle

import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu.utils import checks

RNG = np.random.RandomState(2)
BATCHES = [
    (jnp.asarray(RNG.rand(64).astype(np.float32)), jnp.asarray(RNG.randint(0, 2, 64)))
    for _ in range(5)
]


@pytest.fixture(autouse=True)
def _first_mode():
    checks.set_validation_mode("first")
    yield
    checks.set_validation_mode("full")


@pytest.mark.parametrize(
    "factory",
    [
        lambda: mt.Accuracy(),                      # sum states
        lambda: mt.MeanMetric(),                    # mean state
        lambda: mt.MaxMetric(),                     # max state
        lambda: mt.MinMetric(),                     # min state
        lambda: mt.MeanSquaredError(),              # sum + count
        lambda: mt.F1Score(num_classes=1, average="macro"),
    ],
    ids=["Accuracy", "MeanMetric", "MaxMetric", "MinMetric", "MSE", "F1"],
)
def test_fused_equals_eager(factory):
    fused = factory()
    eager = factory()
    eager._fused_forward_ok = False  # force the reference eager path

    single_input = factory().update.__wrapped__.__code__.co_argcount == 2

    for p, t in BATCHES:
        args = (p,) if single_input else (p, t)
        np.testing.assert_allclose(
            np.asarray(fused(*args)), np.asarray(eager(*args)), atol=1e-6
        )
    np.testing.assert_allclose(np.asarray(fused.compute()), np.asarray(eager.compute()), atol=1e-6)
    # the fused path really engaged (first call is eager by design)
    assert fused._fused_forward is not None


def test_list_state_metric_falls_back():
    metric = mt.CatMetric()
    for p, _ in BATCHES:
        metric(p)
    # list states short-circuit to eager with zero signature bookkeeping
    assert metric._fused_forward is None
    assert metric._fused_seen_signatures is None
    assert np.asarray(metric.compute()).shape == (len(BATCHES) * 64,)


def test_full_validation_mode_keeps_eager_checks():
    checks.set_validation_mode("full")
    metric = mt.Accuracy()
    p, t = BATCHES[0]
    metric(p, t)
    metric(p, t)
    assert metric._fused_forward is None  # never fused in full mode
    with pytest.raises(ValueError, match="non-negative"):
        metric(p, jnp.asarray([-1] * 64))


def test_inferred_attrs_propagate_through_fused_forward():
    """Accuracy infers its input mode from the first batch; forward-only usage
    followed by compute() must still see it after fused calls."""
    rng = np.random.RandomState(0)
    probs = rng.rand(4, 32, 5).astype(np.float32)
    probs /= probs.sum(-1, keepdims=True)
    targets = rng.randint(0, 5, (4, 32))
    metric = mt.Accuracy(num_classes=5, average="macro")
    for i in range(4):
        metric(jnp.asarray(probs[i]), jnp.asarray(targets[i]))
    assert metric._fused_forward is not None
    want = mt.Accuracy(num_classes=5, average="macro")
    for i in range(4):
        want.update(jnp.asarray(probs[i]), jnp.asarray(targets[i]))
    np.testing.assert_allclose(float(metric.compute()), float(want.compute()), atol=1e-6)


def test_pickle_and_clone_after_fused_use():
    metric = mt.Accuracy()
    for p, t in BATCHES:
        metric(p, t)
    assert metric._fused_forward is not None
    clone = pickle.loads(pickle.dumps(metric))
    assert clone._fused_forward is None  # machinery dropped, rebuilt lazily
    p, t = BATCHES[0]
    clone(p, t)
    clone(p, t)
    assert clone._fused_forward is not None  # rebuilt
    deep = metric.clone()
    deep(p, t)


def test_bad_input_error_still_surfaces_and_does_not_disable_fusion():
    metric = mt.Accuracy()
    p, t = BATCHES[0]
    metric(p, t)
    metric(p, t)  # fused engaged
    assert metric._fused_forward is not None
    with pytest.raises(ValueError):
        metric(jnp.zeros((3,)), jnp.zeros((4,), jnp.int32))  # shape mismatch
    assert metric._fused_forward_ok is True  # input error, not a fusion defect
    metric(p, t)  # keeps working fused


def test_hyperparameter_mutation_invalidates_fused_program():
    """Mutating a public hyperparameter after fusion engaged must take effect
    (the old trace baked in the previous value) and must not be reverted by
    the template write-back (review regression)."""
    p, t = BATCHES[0]
    metric = mt.Accuracy()
    metric(p, t)
    metric(p, t)
    assert metric._fused_forward is not None
    metric.threshold = 0.9
    assert metric._fused_forward is None  # program invalidated
    got = float(metric(p, t))
    assert metric.threshold == 0.9  # not reverted
    eager = mt.Accuracy(threshold=0.9)
    eager._fused_forward_ok = False
    want = float(eager(p, t))
    assert got == pytest.approx(want, abs=1e-6)
    # and fusion re-engages with the new value baked in
    metric(p, t)
    assert metric._fused_forward is not None
    assert float(metric(p, t)) == pytest.approx(want, abs=1e-6)


def test_new_signature_gets_eager_validation():
    """'first' mode validates the FIRST update of each input signature; a new
    batch shape arriving after fusion engaged must still be value-checked
    (review regression: the fused program can't check values)."""
    metric = mt.Accuracy()
    p, t = BATCHES[0]
    metric(p, t)
    metric(p, t)  # fused for the (64,) signature
    assert metric._fused_forward is not None
    bad = jnp.asarray([-1] * 128)
    with pytest.raises(ValueError, match="non-negative"):
        metric(jnp.asarray(np.random.rand(128).astype(np.float32)), bad)
    # and a GOOD new signature works eagerly once, then fuses
    p2 = jnp.asarray(np.random.rand(128).astype(np.float32))
    t2 = jnp.asarray(np.random.randint(0, 2, 128))
    metric(p2, t2)
    metric(p2, t2)


def test_bad_batch_preserves_accumulated_state():
    """A malformed batch must not wipe history (review regression: the eager
    forward resets before updating; the snapshot must come back on error)."""
    metric = mt.Accuracy()
    eager = mt.Accuracy()
    eager._fused_forward_ok = False
    for p, t in BATCHES:
        metric(p, t)
        eager(p, t)
    for m in (metric, eager):
        with pytest.raises(ValueError):
            m(jnp.zeros((3,)), jnp.zeros((4,), jnp.int32))
    np.testing.assert_allclose(float(metric.compute()), float(eager.compute()), atol=1e-6)
    want = mt.Accuracy()
    for p, t in BATCHES:
        want.update(p, t)
    np.testing.assert_allclose(float(metric.compute()), float(want.compute()), atol=1e-6)


class TestCollectionFusedForward:
    """The whole-suite fused forward: one program per step across members."""

    @staticmethod
    def _suite():
        return mt.MetricCollection(
            {
                "acc": mt.Accuracy(num_classes=1, average="macro"),
                "f1": mt.F1Score(num_classes=1, average="macro"),
                "mean": mt.MeanMetric(),
            }
        )

    def test_fused_equals_eager(self):
        fused = self._suite()
        eager = self._suite()
        eager._fused_disabled = True
        for p, t in BATCHES:
            fused_out = fused(p, t)
            eager_out = eager(p, t)
            assert set(fused_out) == set(eager_out)
            for key in eager_out:
                np.testing.assert_allclose(
                    np.asarray(fused_out[key]), np.asarray(eager_out[key]), atol=1e-6, err_msg=key
                )
        for key, value in eager.compute().items():
            np.testing.assert_allclose(np.asarray(fused.compute()[key]), np.asarray(value), atol=1e-6)
        assert fused._fused_program is not None  # the suite really fused

    def test_member_mutation_invalidates_suite_program(self):
        suite = self._suite()
        p, t = BATCHES[0]
        suite(p, t)
        suite(p, t)
        assert suite._fused_program is not None
        suite["acc"].threshold = 0.9
        out = suite(p, t)  # must not use the stale program
        want = mt.Accuracy(num_classes=1, average="macro", threshold=0.9)
        want._fused_forward_ok = False
        np.testing.assert_allclose(np.asarray(out["acc"]), np.asarray(want(p, t)), atol=1e-6)
        assert suite["acc"].threshold == 0.9

    def test_unfusable_member_keeps_member_wise_path(self):
        suite = mt.MetricCollection({"mean": mt.MeanMetric(), "cat": mt.CatMetric()})
        for p, _ in BATCHES:
            suite(p)
        assert suite._fused_program is None  # CatMetric blocks suite fusion
        assert np.asarray(suite.compute()["cat"]).shape == (len(BATCHES) * 64,)

    def test_pickle_and_clone_after_fused_use(self):
        suite = self._suite()
        for p, t in BATCHES:
            suite(p, t)
        assert suite._fused_program is not None
        clone = pickle.loads(pickle.dumps(suite))
        assert clone._fused_program is None
        p, t = BATCHES[0]
        clone(p, t)
        deep = suite.clone(prefix="x_")
        deep(p, t)

    def test_prefix_naming_preserved(self):
        suite = mt.MetricCollection({"mean": mt.MeanMetric()}, prefix="tr_")
        p, _ = BATCHES[0]
        suite(p)
        out = suite(p)
        assert set(out) == {"tr_mean"}


def test_collection_fusion_survives_ignored_varying_kwarg():
    """A kwarg no member consumes (e.g. a step counter) must neither defeat
    suite fusion nor leak into the jitted program (review regression)."""
    suite = mt.MetricCollection({"mean": mt.MeanMetric()})
    p, _ = BATCHES[0]
    for step in range(4):
        suite(p, step_label=f"step{step}")
    assert suite._fused_program is not None


def test_collection_program_survives_new_signature_eager_pass():
    """A partial final batch (new shape -> eager member-wise pass) must not
    invalidate the suite program for the shapes already compiled (review
    regression: the eager path's compute_on_cpu toggle bumped versions)."""
    suite = mt.MetricCollection({"mean": mt.MeanMetric(), "mx": mt.MaxMetric()})
    p, _ = BATCHES[0]
    suite(p)
    suite(p)
    program = suite._fused_program
    assert program is not None
    suite(jnp.asarray(np.random.rand(17).astype(np.float32)))  # new shape: eager
    suite(p)  # the original shape keeps its compiled program
    assert suite._fused_program is program


def test_collection_seen_signatures_bounded():
    suite = mt.MetricCollection({"mean": mt.MeanMetric()})
    cap, mt.Metric._FUSED_SIG_CAP = mt.Metric._FUSED_SIG_CAP, 8
    try:
        for n in range(1, 20):
            suite(jnp.asarray(np.random.rand(n).astype(np.float32)))
        assert len(suite._fused_seen) <= 8
    finally:
        mt.Metric._FUSED_SIG_CAP = cap


def test_aliased_member_instance_stays_member_wise():
    """The same Metric instance under two keys must accumulate the batch once
    PER KEY (the member-wise contract); suite fusion would merge it once, so
    it must not engage (review regression)."""
    shared = mt.MeanMetric()
    suite = mt.MetricCollection({"a": shared, "b": shared})
    p, _ = BATCHES[0]
    for _ in range(3):
        suite(p)
    assert suite._fused_program is None
    want = mt.MeanMetric()
    want._fused_forward_ok = False
    for _ in range(3):
        want(p)
        want(p)  # twice per step, like the shared instance
    np.testing.assert_allclose(float(shared.compute()), float(want.compute()), atol=1e-6)


def test_signature_eviction_is_fifo():
    """Recurring (hot) signatures must survive eviction when distinct
    signatures exceed the cap (review regression: set.pop is arbitrary)."""
    metric = mt.MeanMetric()
    cap, mt.Metric._FUSED_SIG_CAP = mt.Metric._FUSED_SIG_CAP, 4
    try:
        hot = BATCHES[0][0]
        metric(hot)
        metric(hot)  # hot signature fused
        for n in range(70, 73):  # a few cold signatures, below cap pressure
            metric(jnp.asarray(np.random.rand(n).astype(np.float32)))
        # hot signature was inserted FIRST; after 3 cold inserts the cache is
        # full (4) — one more cold insert evicts the OLDEST (hot)
        metric(jnp.asarray(np.random.rand(99).astype(np.float32)))
        assert len(metric._fused_seen_signatures) <= 4
        # FIFO evicted `hot`: its next call re-validates eagerly, then re-fuses
        metric(hot)
        metric(hot)
        assert metric._fused_forward is not None
    finally:
        mt.Metric._FUSED_SIG_CAP = cap


def test_same_value_reassignment_keeps_fused_program():
    """Re-assigning an unchanged public attribute (a metric that re-derives an
    inferred hyperparameter inside update) must NOT invalidate the fused
    program (advisor regression: every write bumped _fused_version)."""
    metric = mt.MeanMetric()
    p, _ = BATCHES[0]
    metric(p)
    metric(p)
    assert metric._fused_forward is not None
    version = metric._fused_version
    metric.sync_on_compute = metric.sync_on_compute  # same value
    assert metric._fused_version == version
    assert metric._fused_forward is not None
    metric.sync_on_compute = not metric.sync_on_compute  # genuine change
    assert metric._fused_version == version + 1


def test_fused_disable_emits_warning():
    """Permanently disabling a fused path must warn (advisor: silent
    performance degradation is undiagnosable)."""

    class _Flaky(mt.MeanMetric):
        boom = False

    metric = _Flaky()
    p, _ = BATCHES[0]
    metric(p)
    # sabotage the built program so the NEXT fused call raises
    metric._fused_forward = lambda *a, **k: (_ for _ in ()).throw(RuntimeError("synthetic"))
    with pytest.warns(UserWarning, match="Falling back to the eager"):
        metric(p)
    assert metric._fused_forward_ok is False


def test_unset_full_state_update_warns_once_per_class():
    """Reference parity (`metric.py:139-151`): leaving full_state_update=None
    silently picks the slow two-update forward — warn once, with the remedy."""
    import warnings

    class _Unset(mt.Metric):
        def __init__(self):
            super().__init__()
            self.add_state("total", 0.0, "sum")

        def update(self, v):
            self.total = self.total + jnp.sum(v)

        def compute(self):
            return self.total

    # the dedup set is process-global; drop this class's key so the test is
    # independent of prior constructions (e.g. under pytest-repeat)
    mt.Metric._full_state_warned.discard(f"{_Unset.__module__}.{_Unset.__qualname__}")
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        _Unset()
        _Unset()
    hits = [w for w in caught if "full_state_update" in str(w.message)]
    assert len(hits) == 1
