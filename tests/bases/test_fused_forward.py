"""The fused (single-dispatch) forward path: semantics pinned vs eager.

After the first always-eager call, fusable metrics run forward as ONE jitted
program (batch update + batch compute + state merge). These tests require
bit-level agreement with the eager path across every reduction spec, the
documented fallbacks (list states, validation mode "full"), inferred-attr
propagation, and pickling after fused use.
"""
from __future__ import annotations

import pickle

import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu.ops import engine
from metrics_tpu.utils import checks

RNG = np.random.RandomState(2)
BATCHES = [
    (jnp.asarray(RNG.rand(64).astype(np.float32)), jnp.asarray(RNG.randint(0, 2, 64)))
    for _ in range(5)
]


@pytest.fixture(autouse=True)
def _first_mode():
    # this file pins the PER-CALL fused dispatch contract — exactly the
    # behavior METRICS_TPU_DEFER=0 preserves; the deferred-queue analogues
    # live in tests/bases/test_deferred_dispatch.py
    checks.set_validation_mode("first")
    engine.set_deferred_dispatch(False)
    yield
    engine.set_deferred_dispatch(True)
    checks.set_validation_mode("first")


@pytest.mark.parametrize(
    "factory",
    [
        lambda: mt.Accuracy(),                      # sum states
        lambda: mt.MeanMetric(),                    # mean state
        lambda: mt.MaxMetric(),                     # max state
        lambda: mt.MinMetric(),                     # min state
        lambda: mt.MeanSquaredError(),              # sum + count
        lambda: mt.F1Score(num_classes=1, average="macro"),
    ],
    ids=["Accuracy", "MeanMetric", "MaxMetric", "MinMetric", "MSE", "F1"],
)
def test_fused_equals_eager(factory):
    fused = factory()
    eager = factory()
    eager._fused_forward_ok = False  # force the reference eager path

    single_input = factory().update.__wrapped__.__code__.co_argcount == 2

    for p, t in BATCHES:
        args = (p,) if single_input else (p, t)
        np.testing.assert_allclose(
            np.asarray(fused(*args)), np.asarray(eager(*args)), atol=1e-6
        )
    np.testing.assert_allclose(np.asarray(fused.compute()), np.asarray(eager.compute()), atol=1e-6)
    # the fused path really engaged (first call is eager by design)
    assert fused._fused_forward is not None


def test_list_state_metric_falls_back():
    metric = mt.CatMetric()
    for p, _ in BATCHES:
        metric(p)
    # list states short-circuit to eager with zero signature bookkeeping
    assert metric._fused_forward is None
    assert metric._fused_seen_signatures is None
    assert np.asarray(metric.compute()).shape == (len(BATCHES) * 64,)


def test_full_validation_mode_keeps_eager_checks():
    checks.set_validation_mode("full")  # strict reference-parity mode
    metric = mt.Accuracy()
    p, t = BATCHES[0]
    metric(p, t)
    metric(p, t)
    assert metric._fused_forward is None  # never fused in full mode
    with pytest.raises(ValueError, match="non-negative"):
        metric(p, jnp.asarray([-1] * 64))


def test_inferred_attrs_propagate_through_fused_forward():
    """Accuracy infers its input mode from the first batch; forward-only usage
    followed by compute() must still see it after fused calls."""
    rng = np.random.RandomState(0)
    probs = rng.rand(4, 32, 5).astype(np.float32)
    probs /= probs.sum(-1, keepdims=True)
    targets = rng.randint(0, 5, (4, 32))
    metric = mt.Accuracy(num_classes=5, average="macro")
    for i in range(4):
        metric(jnp.asarray(probs[i]), jnp.asarray(targets[i]))
    assert metric._fused_forward is not None
    want = mt.Accuracy(num_classes=5, average="macro")
    for i in range(4):
        want.update(jnp.asarray(probs[i]), jnp.asarray(targets[i]))
    np.testing.assert_allclose(float(metric.compute()), float(want.compute()), atol=1e-6)


def test_pickle_and_clone_after_fused_use():
    metric = mt.Accuracy()
    for p, t in BATCHES:
        metric(p, t)
    assert metric._fused_forward is not None
    clone = pickle.loads(pickle.dumps(metric))
    assert clone._fused_forward is None  # machinery dropped, rebuilt lazily
    p, t = BATCHES[0]
    clone(p, t)
    clone(p, t)
    assert clone._fused_forward is not None  # rebuilt
    deep = metric.clone()
    deep(p, t)


def test_bad_input_error_still_surfaces_and_does_not_disable_fusion():
    metric = mt.Accuracy()
    p, t = BATCHES[0]
    metric(p, t)
    metric(p, t)  # fused engaged
    assert metric._fused_forward is not None
    with pytest.raises(ValueError):
        metric(jnp.zeros((3,)), jnp.zeros((4,), jnp.int32))  # shape mismatch
    assert metric._fused_forward_ok is True  # input error, not a fusion defect
    metric(p, t)  # keeps working fused


def test_hyperparameter_mutation_invalidates_fused_program():
    """Mutating a public hyperparameter after fusion engaged must take effect
    (the old trace baked in the previous value) and must not be reverted by
    the template write-back (review regression)."""
    p, t = BATCHES[0]
    metric = mt.Accuracy()
    metric(p, t)
    metric(p, t)
    assert metric._fused_forward is not None
    metric.threshold = 0.9
    assert metric._fused_forward is None  # program invalidated
    got = float(metric(p, t))
    assert metric.threshold == 0.9  # not reverted
    eager = mt.Accuracy(threshold=0.9)
    eager._fused_forward_ok = False
    want = float(eager(p, t))
    assert got == pytest.approx(want, abs=1e-6)
    # and fusion re-engages with the new value baked in
    metric(p, t)
    assert metric._fused_forward is not None
    assert float(metric(p, t)) == pytest.approx(want, abs=1e-6)


def test_new_signature_gets_eager_validation():
    """'first' mode validates the FIRST update of each input signature; a new
    batch shape arriving after fusion engaged must still be value-checked
    (review regression: the fused program can't check values)."""
    metric = mt.Accuracy()
    p, t = BATCHES[0]
    metric(p, t)
    metric(p, t)  # fused for the (64,) signature
    assert metric._fused_forward is not None
    bad = jnp.asarray([-1] * 128)
    with pytest.raises(ValueError, match="non-negative"):
        metric(jnp.asarray(np.random.rand(128).astype(np.float32)), bad)
    # and a GOOD new signature works eagerly once, then fuses
    p2 = jnp.asarray(np.random.rand(128).astype(np.float32))
    t2 = jnp.asarray(np.random.randint(0, 2, 128))
    metric(p2, t2)
    metric(p2, t2)


def test_bad_batch_preserves_accumulated_state():
    """A malformed batch must not wipe history (review regression: the eager
    forward resets before updating; the snapshot must come back on error)."""
    metric = mt.Accuracy()
    eager = mt.Accuracy()
    eager._fused_forward_ok = False
    for p, t in BATCHES:
        metric(p, t)
        eager(p, t)
    for m in (metric, eager):
        with pytest.raises(ValueError):
            m(jnp.zeros((3,)), jnp.zeros((4,), jnp.int32))
    np.testing.assert_allclose(float(metric.compute()), float(eager.compute()), atol=1e-6)
    want = mt.Accuracy()
    for p, t in BATCHES:
        want.update(p, t)
    np.testing.assert_allclose(float(metric.compute()), float(want.compute()), atol=1e-6)


class TestCollectionFusedForward:
    """The whole-suite fused forward: one program per step across members."""

    @staticmethod
    def _suite():
        return mt.MetricCollection(
            {
                "acc": mt.Accuracy(num_classes=1, average="macro"),
                "f1": mt.F1Score(num_classes=1, average="macro"),
                "mean": mt.MeanMetric(),
            }
        )

    def test_fused_equals_eager(self):
        fused = self._suite()
        eager = self._suite()
        eager._fused_disabled = True
        for p, t in BATCHES:
            fused_out = fused(p, t)
            eager_out = eager(p, t)
            assert set(fused_out) == set(eager_out)
            for key in eager_out:
                np.testing.assert_allclose(
                    np.asarray(fused_out[key]), np.asarray(eager_out[key]), atol=1e-6, err_msg=key
                )
        for key, value in eager.compute().items():
            np.testing.assert_allclose(np.asarray(fused.compute()[key]), np.asarray(value), atol=1e-6)
        assert fused._fused_program is not None  # the suite really fused

    def test_member_mutation_invalidates_suite_program(self):
        suite = self._suite()
        p, t = BATCHES[0]
        suite(p, t)
        suite(p, t)
        assert suite._fused_program is not None
        suite["acc"].threshold = 0.9
        out = suite(p, t)  # must not use the stale program
        want = mt.Accuracy(num_classes=1, average="macro", threshold=0.9)
        want._fused_forward_ok = False
        np.testing.assert_allclose(np.asarray(out["acc"]), np.asarray(want(p, t)), atol=1e-6)
        assert suite["acc"].threshold == 0.9

    def test_unfusable_member_keeps_member_wise_path(self):
        suite = mt.MetricCollection({"mean": mt.MeanMetric(), "cat": mt.CatMetric()})
        for p, _ in BATCHES:
            suite(p)
        assert suite._fused_program is None  # CatMetric blocks suite fusion
        assert np.asarray(suite.compute()["cat"]).shape == (len(BATCHES) * 64,)

    def test_pickle_and_clone_after_fused_use(self):
        suite = self._suite()
        for p, t in BATCHES:
            suite(p, t)
        assert suite._fused_program is not None
        clone = pickle.loads(pickle.dumps(suite))
        assert clone._fused_program is None
        p, t = BATCHES[0]
        clone(p, t)
        deep = suite.clone(prefix="x_")
        deep(p, t)

    def test_prefix_naming_preserved(self):
        suite = mt.MetricCollection({"mean": mt.MeanMetric()}, prefix="tr_")
        p, _ = BATCHES[0]
        suite(p)
        out = suite(p)
        assert set(out) == {"tr_mean"}


def test_collection_fusion_survives_ignored_varying_kwarg():
    """A kwarg no member consumes (e.g. a step counter) must neither defeat
    suite fusion nor leak into the jitted program (review regression)."""
    suite = mt.MetricCollection({"mean": mt.MeanMetric()})
    p, _ = BATCHES[0]
    for step in range(4):
        suite(p, step_label=f"step{step}")
    assert suite._fused_program is not None


def test_collection_program_survives_new_signature_eager_pass():
    """A partial final batch (new shape -> eager member-wise pass) must not
    invalidate the suite program for the shapes already compiled (review
    regression: the eager path's compute_on_cpu toggle bumped versions)."""
    suite = mt.MetricCollection({"mean": mt.MeanMetric(), "mx": mt.MaxMetric()})
    p, _ = BATCHES[0]
    suite(p)
    suite(p)
    program = suite._fused_program
    assert program is not None
    suite(jnp.asarray(np.random.rand(17).astype(np.float32)))  # new shape: eager
    suite(p)  # the original shape keeps its compiled program
    assert suite._fused_program is program


def test_collection_seen_signatures_bounded():
    suite = mt.MetricCollection({"mean": mt.MeanMetric()})
    cap, mt.Metric._FUSED_SIG_CAP = mt.Metric._FUSED_SIG_CAP, 8
    try:
        for n in range(1, 20):
            suite(jnp.asarray(np.random.rand(n).astype(np.float32)))
        assert len(suite._fused_seen) <= 8
    finally:
        mt.Metric._FUSED_SIG_CAP = cap


def test_aliased_member_instance_stays_member_wise():
    """The same Metric instance under two keys must accumulate the batch once
    PER KEY (the member-wise contract); suite fusion would merge it once, so
    it must not engage (review regression)."""
    shared = mt.MeanMetric()
    suite = mt.MetricCollection({"a": shared, "b": shared})
    p, _ = BATCHES[0]
    for _ in range(3):
        suite(p)
    assert suite._fused_program is None
    want = mt.MeanMetric()
    want._fused_forward_ok = False
    for _ in range(3):
        want(p)
        want(p)  # twice per step, like the shared instance
    np.testing.assert_allclose(float(shared.compute()), float(want.compute()), atol=1e-6)


def test_signature_eviction_is_fifo():
    """Recurring (hot) signatures must survive eviction when distinct
    signatures exceed the cap (review regression: set.pop is arbitrary)."""
    metric = mt.MeanMetric()
    cap, mt.Metric._FUSED_SIG_CAP = mt.Metric._FUSED_SIG_CAP, 4
    try:
        hot = BATCHES[0][0]
        metric(hot)
        metric(hot)  # hot signature fused
        for n in range(70, 73):  # a few cold signatures, below cap pressure
            metric(jnp.asarray(np.random.rand(n).astype(np.float32)))
        # hot signature was inserted FIRST; after 3 cold inserts the cache is
        # full (4) — one more cold insert evicts the OLDEST (hot)
        metric(jnp.asarray(np.random.rand(99).astype(np.float32)))
        assert len(metric._fused_seen_signatures) <= 4
        # FIFO evicted `hot`: its next call re-validates eagerly, then re-fuses
        metric(hot)
        metric(hot)
        assert metric._fused_forward is not None
    finally:
        mt.Metric._FUSED_SIG_CAP = cap


def test_same_value_reassignment_keeps_fused_program():
    """Re-assigning an unchanged public attribute (a metric that re-derives an
    inferred hyperparameter inside update) must NOT invalidate the fused
    program (advisor regression: every write bumped _fused_version)."""
    metric = mt.MeanMetric()
    p, _ = BATCHES[0]
    metric(p)
    metric(p)
    assert metric._fused_forward is not None
    version = metric._fused_version
    metric.sync_on_compute = metric.sync_on_compute  # same value
    assert metric._fused_version == version
    assert metric._fused_forward is not None
    metric.sync_on_compute = not metric.sync_on_compute  # genuine change
    assert metric._fused_version == version + 1


def test_fused_disable_emits_warning():
    """Permanently disabling a fused path must warn (advisor: silent
    performance degradation is undiagnosable)."""

    class _Flaky(mt.MeanMetric):
        boom = False

    metric = _Flaky()
    p, _ = BATCHES[0]
    metric(p)
    # sabotage the built program so the NEXT fused call raises
    metric._fused_forward = lambda *a, **k: (_ for _ in ()).throw(RuntimeError("synthetic"))
    with pytest.warns(UserWarning, match="Falling back to the eager"):
        metric(p)
    assert metric._fused_forward_ok is False


def test_unset_full_state_update_warns_once_per_class():
    """Reference parity (`metric.py:139-151`): leaving full_state_update=None
    silently picks the slow two-update forward — warn once, with the remedy."""
    import warnings

    class _Unset(mt.Metric):
        def __init__(self):
            super().__init__()
            self.add_state("total", 0.0, "sum")

        def update(self, v):
            self.total = self.total + jnp.sum(v)

        def compute(self):
            return self.total

    # the dedup set is process-global; drop this class's key so the test is
    # independent of prior constructions (e.g. under pytest-repeat)
    mt.Metric._full_state_warned.discard(f"{_Unset.__module__}.{_Unset.__qualname__}")
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        _Unset()
        _Unset()
    hits = [w for w in caught if "full_state_update" in str(w.message)]
    assert len(hits) == 1


class TestBatchedStepAPI:
    """`update_many`/`forward_many`: N steps in one `lax.scan` dispatch must
    agree bit-for-bit in semantics with N sequential `forward` calls."""

    def _chunk(self, n=6, batch=32):
        rng = np.random.RandomState(7)
        return (
            jnp.asarray(rng.rand(n, batch).astype(np.float32)),
            jnp.asarray(rng.randint(0, 2, (n, batch))),
        )

    @pytest.mark.parametrize(
        "factory",
        [
            lambda: mt.Accuracy(),
            lambda: mt.MeanMetric(),
            lambda: mt.MaxMetric(),
            lambda: mt.MeanSquaredError(),
        ],
        ids=["Accuracy", "MeanMetric", "MaxMetric", "MSE"],
    )
    def test_matches_sequential_forward(self, factory):
        p, t = self._chunk()
        single_input = factory().update.__wrapped__.__code__.co_argcount == 2

        many = factory()
        seq = factory()
        seq._fused_forward_ok = False  # reference eager path
        args_many = (p,) if single_input else (p, t)
        vals_first = many.forward_many(*args_many)   # first call: eager-validated
        vals_fused = factory()
        vals2 = vals_fused.forward_many(*args_many)  # fresh instance, same shapes
        vals3 = vals_fused.forward_many(*args_many)  # second call: scan program
        assert vals_fused._many_program_vals is not None

        seq_vals = []
        for i in range(p.shape[0]):
            a = (p[i],) if single_input else (p[i], t[i])
            seq_vals.append(seq(*a))
            seq_vals.append(seq(*a))  # vals_fused saw each chunk twice
        want = np.asarray(seq_vals[::2])[: p.shape[0]]
        np.testing.assert_allclose(np.asarray(vals_first), want, atol=1e-6)
        np.testing.assert_allclose(np.asarray(vals2), np.asarray(vals_first), atol=1e-6)
        # state after two chunks == 2x sequential updates
        np.testing.assert_allclose(
            np.asarray(vals_fused.compute()), np.asarray(seq.compute()), atol=1e-6
        )
        assert vals_fused._update_count == 2 * p.shape[0]

    def test_update_many_returns_none_and_accumulates(self):
        p, t = self._chunk()
        m = mt.Accuracy()
        assert m.update_many(p, t) is None
        m.update_many(p, t)  # second call takes the scan program
        assert m._many_program_novals is not None
        ref = mt.Accuracy()
        for i in range(p.shape[0]):
            ref.update(p[i], t[i])
            ref.update(p[i], t[i])
        np.testing.assert_allclose(float(m.compute()), float(ref.compute()), atol=1e-6)

    def test_list_state_metric_uses_eager_loop(self):
        p, _ = self._chunk()
        m = mt.CatMetric()
        vals = m.forward_many(p)
        assert m._many_program_vals is None
        assert np.asarray(vals).shape[0] == p.shape[0]
        assert np.asarray(m.compute()).shape == (p.shape[0] * p.shape[1],)

    def test_hyperparameter_mutation_invalidates_many_program(self):
        p, t = self._chunk()
        m = mt.Accuracy()
        m.update_many(p, t)
        m.update_many(p, t)
        assert m._many_program_novals is not None
        m.threshold = 0.7
        assert m._many_program_novals is None

    def test_pickle_after_many_use(self):
        p, t = self._chunk()
        m = mt.Accuracy()
        m.forward_many(p, t)
        m.forward_many(p, t)
        m2 = pickle.loads(pickle.dumps(m))
        np.testing.assert_allclose(float(m2.compute()), float(m.compute()), atol=1e-6)
        m2.forward_many(p, t)  # program rebuilds lazily


def test_forward_override_keeps_eager_many_path():
    """A subclass with a custom forward() must not have forward_many swap in
    scan semantics that bypass the override (review regression)."""

    class _Halving(mt.MeanMetric):
        def forward(self, v):
            return super().forward(v * 0.5)

    rng = np.random.RandomState(3)
    chunk = jnp.asarray(rng.rand(4, 16).astype(np.float32))
    m = _Halving()
    m.forward_many(chunk)
    vals = m.forward_many(chunk)
    assert m._many_program_vals is None  # never fused
    want = _Halving()
    for i in range(4):
        want.forward(chunk[i])
        want.forward(chunk[i])
    np.testing.assert_allclose(float(m.compute()), float(want.compute()), atol=1e-6)
    np.testing.assert_allclose(float(np.asarray(vals)[-1]), float(want._forward_cache), atol=1e-6)


def test_forward_cache_tracks_last_step_through_fused_many():
    rng = np.random.RandomState(4)
    chunk = jnp.asarray(rng.rand(4, 16).astype(np.float32))
    m = mt.MeanMetric()
    m.forward_many(chunk)
    vals = m.forward_many(chunk)  # scan program
    assert m._many_program_vals is not None
    np.testing.assert_allclose(
        float(m._forward_cache), float(np.asarray(vals)[-1]), atol=1e-6
    )


def test_first_many_chunk_does_not_compile_single_step_program():
    """The eager first chunk must not register per-step signatures (the
    single-step fused program would compile and never be used)."""
    rng = np.random.RandomState(5)
    chunk = jnp.asarray(rng.rand(4, 16).astype(np.float32))
    m = mt.MeanMetric()
    m.forward_many(chunk)
    assert m._fused_forward is None
    per_step = [s for s in (m._fused_seen_signatures or {}) if not (isinstance(s, tuple) and s and s[0] == "__many__")]
    assert per_step == []


def test_many_signature_not_registered_on_failed_chunk():
    """A chunk that fails validation must not register the chunk signature —
    a same-shaped retry stays on the eager path (matching the single-step
    contract, which registers only after the eager call succeeds); the scan
    program may only build after a chunk that completed (review regression)."""
    m = mt.Accuracy()
    p = jnp.asarray(np.random.RandomState(8).rand(3, 16).astype(np.float32))
    bad = jnp.asarray([[-1] * 16] * 3)
    with pytest.raises(ValueError):
        m.forward_many(p, bad)
    assert not (m._fused_seen_signatures or {})  # failed chunk left no license
    good = jnp.asarray((np.random.RandomState(8).rand(3, 16) > 0.5).astype(np.int64))
    m.forward_many(p, good)  # first SUCCESSFUL chunk: eager, registers
    assert m._many_program_vals is None
    m.forward_many(p, good)  # now the scan program builds
    assert m._many_program_vals is not None


def test_scalar_kwarg_rides_fused_many_path():
    """Python-scalar and 0-d-array kwargs are per-chunk constants; they must
    not defeat fusion (review regression: silent permanent eager loop)."""
    rng = np.random.RandomState(9)
    chunk = jnp.asarray(rng.rand(4, 16).astype(np.float32))
    m = mt.MeanMetric()
    m.forward_many(chunk, weight=0.5)
    m.forward_many(chunk, weight=0.5)
    assert m._many_program_vals is not None  # fused despite the scalar kwarg
    want = mt.MeanMetric()
    for i in range(4):
        want(chunk[i], weight=0.5)
        want(chunk[i], weight=0.5)
    np.testing.assert_allclose(float(m.compute()), float(want.compute()), atol=1e-6)
    # changed python constant: new signature -> eager validation -> rebuilt
    # program with the NEW value baked (not the stale 0.5 trace)
    m2 = mt.MeanMetric()
    m2.forward_many(chunk, weight=0.5)
    m2.forward_many(chunk, weight=0.5)
    m2.forward_many(chunk, weight=2.0)
    m2.forward_many(chunk, weight=2.0)
    want2 = mt.MeanMetric()
    for w in (0.5, 0.5, 2.0, 2.0):
        for i in range(4):
            want2(chunk[i], weight=w)
    np.testing.assert_allclose(float(m2.compute()), float(want2.compute()), atol=1e-6)


def test_0d_array_kwarg_rides_fused_many_path():
    rng = np.random.RandomState(10)
    chunk = jnp.asarray(rng.rand(4, 16).astype(np.float32))
    m = mt.MeanMetric()
    w = jnp.asarray(0.25)
    m.forward_many(chunk, weight=w)
    m.forward_many(chunk, weight=w)
    assert m._many_program_vals is not None
    want = mt.MeanMetric()
    for i in range(4):
        want(chunk[i], weight=w)
        want(chunk[i], weight=w)
    np.testing.assert_allclose(float(m.compute()), float(want.compute()), atol=1e-6)


def test_many_on_synced_metric_raises():
    from metrics_tpu.utils.exceptions import MetricsUserError

    m = mt.MeanMetric()
    p = jnp.asarray(np.random.RandomState(11).rand(2, 8).astype(np.float32))
    m.forward_many(p)
    m.sync(dist_sync_fn=lambda x, group=None: [x], distributed_available=lambda: True)
    with pytest.raises(MetricsUserError, match="synced"):
        m.forward_many(p)
    m.unsync()
    m.forward_many(p)


def test_separate_templates_for_vals_and_novals_programs():
    """update_many and forward_many trace separately; attr propagation must
    use the matching template (review regression: shared slot)."""
    rng = np.random.RandomState(12)
    p5 = rng.rand(3, 32, 5).astype(np.float32)
    p5 /= p5.sum(-1, keepdims=True)
    t5 = rng.randint(0, 5, (3, 32))
    m = mt.Accuracy(num_classes=5, average="macro")
    m.update_many(jnp.asarray(p5), jnp.asarray(t5))
    m.update_many(jnp.asarray(p5), jnp.asarray(t5))
    m.forward_many(jnp.asarray(p5), jnp.asarray(t5))
    m.forward_many(jnp.asarray(p5), jnp.asarray(t5))
    assert m._many_template_vals is not m._many_template_novals
    want = mt.Accuracy(num_classes=5, average="macro")
    for _ in range(4):
        for i in range(3):
            want.update(jnp.asarray(p5[i]), jnp.asarray(t5[i]))
    np.testing.assert_allclose(float(m.compute()), float(want.compute()), atol=1e-6)


def test_mismatched_chunk_lengths_raise():
    """Silent index clamping on a leading-axis mismatch would corrupt state;
    both the eager and scan paths must reject it (review regression)."""
    m = mt.Accuracy()
    p = jnp.asarray(np.random.RandomState(13).rand(4, 16).astype(np.float32))
    t = jnp.asarray((np.random.RandomState(13).rand(3, 16) > 0.5).astype(np.int64))
    with pytest.raises(ValueError, match="same leading steps-axis"):
        m.forward_many(p, t)


def test_batched_fallback_does_not_disable_single_step_fusion():
    """One bad chunk may disable only the batched path; plain forward() keeps
    its fused program (review regression: shared flag)."""
    rng = np.random.RandomState(14)
    chunk = jnp.asarray(rng.rand(4, 16).astype(np.float32))
    m = mt.MeanMetric()
    m.forward_many(chunk)
    m.forward_many(chunk)  # scan program built, layout recorded
    assert m._many_program_vals is not None
    # sabotage the built program so the next chunk raises inside it
    m._many_program_vals = lambda *a, **k: (_ for _ in ()).throw(RuntimeError("synthetic"))
    with pytest.warns(UserWarning, match="batched API"):
        m.forward_many(chunk)
    assert m._many_ok is False
    assert m._fused_forward_ok is True
    p = chunk[0]
    m(p)
    m(p)
    assert m._fused_forward is not None  # single-step fusion unaffected


class TestCollectionBatchedStepAPI:
    """Suite-level `update_many`/`forward_many`: the whole collection's chunk
    runs as ONE scan program; semantics equal member-wise sequential forward."""

    @staticmethod
    def _suite():
        return mt.MetricCollection(
            {
                "acc": mt.Accuracy(num_classes=1, average="macro"),
                "f1": mt.F1Score(num_classes=1, average="macro"),
                "mean": mt.MeanMetric(),
            }
        )

    def _chunk(self, n=5, batch=24):
        rng = np.random.RandomState(21)
        return (
            jnp.asarray(rng.rand(n, batch).astype(np.float32)),
            jnp.asarray(rng.randint(0, 2, (n, batch))),
        )

    def test_matches_sequential_forward(self):
        p, t = self._chunk()
        suite = self._suite()
        v1 = suite.forward_many(p, t)
        v2 = suite.forward_many(p, t)  # scan program
        assert suite._many_programs and True in suite._many_programs
        want = self._suite()
        want._fused_disabled = True
        seq_last = None
        for _ in range(2):
            for i in range(p.shape[0]):
                seq_last = want(p[i], t[i])
        got = suite.compute()
        expect = want.compute()
        for k in expect:
            np.testing.assert_allclose(float(got[k]), float(expect[k]), atol=1e-6)
            np.testing.assert_allclose(
                float(np.asarray(v2[k])[-1]), float(seq_last[k]), atol=1e-6
            )
            assert np.asarray(v1[k]).shape[0] == p.shape[0]

    def test_update_many_accumulates(self):
        p, t = self._chunk()
        suite = self._suite()
        assert suite.update_many(p, t) is None
        suite.update_many(p, t)
        assert suite._many_programs and False in suite._many_programs
        want = self._suite()
        for _ in range(2):
            for i in range(p.shape[0]):
                want.update(p[i], t[i])
        got, expect = suite.compute(), want.compute()
        for k in expect:
            np.testing.assert_allclose(float(got[k]), float(expect[k]), atol=1e-6)

    def test_member_mutation_rebuilds_suite_program(self):
        p, t = self._chunk()
        suite = self._suite()
        suite.forward_many(p, t)
        suite.forward_many(p, t)
        assert suite._many_programs and True in suite._many_programs
        suite["acc"].threshold = 0.8
        suite.forward_many(p, t)  # must run with the NEW threshold baked in
        want = mt.Accuracy(num_classes=1, average="macro")
        want._fused_forward_ok = False
        for i in range(p.shape[0]):  # chunks 1-2 at the default threshold
            want(p[i], t[i])
            want(p[i], t[i])
        want.threshold = 0.8
        for i in range(p.shape[0]):  # chunk 3 at the mutated threshold
            want(p[i], t[i])
        np.testing.assert_allclose(
            float(suite.compute()["acc"]), float(want.compute()), atol=1e-6
        )

    def test_unfusable_member_uses_eager_loop(self):
        p, _ = self._chunk()
        suite = mt.MetricCollection({"cat": mt.CatMetric(), "mean": mt.MeanMetric()})
        vals = suite.forward_many(p)
        assert not suite._many_programs
        assert np.asarray(vals["mean"]).shape[0] == p.shape[0]

    def test_prefix_naming_preserved(self):
        p, t = self._chunk()
        suite = mt.MetricCollection({"acc": mt.Accuracy()}, prefix="val_")
        suite.forward_many(p, t)
        out = suite.forward_many(p, t)
        assert set(out) == {"val_acc"}


def test_empty_chunk_raises_clearly():
    m = mt.MeanMetric()
    with pytest.raises(ValueError, match="zero-length"):
        m.forward_many(jnp.zeros((0, 8)))
    suite = mt.MetricCollection({"mean": mt.MeanMetric()})
    with pytest.raises(ValueError, match="zero-length"):
        suite.forward_many(jnp.zeros((0, 8)))


def test_collection_batched_fallback_keeps_single_step_fusion():
    """A failed scan program disables only the collection's batched API; the
    per-step whole-suite fused forward keeps working (review regression)."""
    rng = np.random.RandomState(31)
    chunk = jnp.asarray(rng.rand(3, 16).astype(np.float32))
    suite = mt.MetricCollection({"mean": mt.MeanMetric()})
    suite.forward_many(chunk)
    suite.forward_many(chunk)
    assert suite._many_programs
    suite._many_programs[True] = lambda *a, **k: (_ for _ in ()).throw(RuntimeError("synthetic"))
    with pytest.warns(UserWarning, match="batched API"):
        suite.forward_many(chunk)
    assert suite._many_ok is False
    assert suite._fused_disabled is False
    p = chunk[0]
    suite(p)
    suite(p)
    assert suite._fused_program is not None  # single-step suite fusion unaffected


def test_collection_first_chunk_skips_single_step_compile():
    rng = np.random.RandomState(32)
    chunk = jnp.asarray(rng.rand(3, 16).astype(np.float32))
    suite = mt.MetricCollection({"mean": mt.MeanMetric()})
    suite.forward_many(chunk)
    assert suite._fused_program is None
    per_step = [
        s for s in (suite._fused_seen or {}) if not (isinstance(s, tuple) and s and s[0] == "__many__")
    ]
    assert per_step == []
    for _, m in suite.items(keep_base=True, copy_state=False):
        assert m._fused_forward is None


def test_collection_ignored_varying_kwarg_does_not_defeat_chunk():
    rng = np.random.RandomState(33)
    chunk = jnp.asarray(rng.rand(4, 16).astype(np.float32))
    suite = mt.MetricCollection({"mean": mt.MeanMetric()})
    # `aux` is consumed by no member and has a DIFFERENT leading length
    suite.forward_many(chunk, aux=jnp.zeros(3))
    out = suite.forward_many(chunk, aux=jnp.zeros(3))
    assert suite._many_programs and True in suite._many_programs
    assert np.asarray(out["mean"]).shape[0] == 4


def test_collection_alternating_many_flavors_keep_both_programs():
    rng = np.random.RandomState(34)
    chunk = jnp.asarray(rng.rand(3, 16).astype(np.float32))
    suite = mt.MetricCollection({"mean": mt.MeanMetric()})
    suite.forward_many(chunk)
    suite.update_many(chunk)
    suite.forward_many(chunk)
    suite.update_many(chunk)
    assert set(suite._many_programs) == {True, False}
    want = mt.MeanMetric()
    for _ in range(4):
        for i in range(3):
            want.update(chunk[i])
    np.testing.assert_allclose(float(suite.compute()["mean"]), float(want.compute()), atol=1e-6)
