"""Per-metric distributed sync contract across ALL state kinds (VERDICT #2).

The reference runs every metric through ``_class_test`` with ``ddp=True``
(`tests/unittests/helpers/testers.py:398-476`). Round 1 covered the
classification/regression/image/audio domains; this module extends the same
two sync paths to the remaining state shapes:

- text: scalar/vector ``sum`` states (BLEU/WER/CHRF/SQuAD) and per-sentence
  ``cat`` list states (ROUGE);
- retrieval: ``dist_reduce_fx=None`` (indexes, preds, target) triples whose
  per-element gather must preserve query grouping;
- detection: ``MeanAveragePrecision``'s five variable-shape list states;
- wrappers: BootStrapper (cloned children), MinMaxMetric (min/max +
  wrapped), MetricTracker (history of clones).

Contract asserted: N emulated ranks striping the data, synced through the
REAL host sync path (``Metric.sync`` with an injected gather), must produce
exactly the single-instance value over all data — and rank-local state must
survive unsync. For numeric-state metrics the same merge is additionally
run through the SPMD path (``as_functions`` compute with fused collectives
under ``shard_map``).
"""
from __future__ import annotations

from typing import Any, Callable, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import metrics_tpu as mt
from tests.helpers.testers import _FakeGather, shard_map

NUM_RANKS = 2


def _values_close(a: Any, b: Any, atol: float = 1e-6) -> None:
    if isinstance(a, dict):
        assert set(a) == set(b)
        for k in a:
            _values_close(a[k], b[k], atol)
    elif isinstance(a, (tuple, list)):
        assert len(a) == len(b)
        for x, y in zip(a, b):
            _values_close(x, y, atol)
    else:
        np.testing.assert_allclose(np.asarray(a, np.float64), np.asarray(b, np.float64), atol=atol, rtol=1e-5)


def run_emulated_ddp(
    metric_factory: Callable[[], "mt.Metric"],
    rank_updates: Sequence[Sequence[tuple]],
    atol: float = 1e-6,
) -> None:
    """Stripe ``rank_updates[rank] = [(args, kwargs), ...]`` over emulated
    ranks, sync through the host gather path, and require the single-instance
    all-data value. Every rank must see the identical merged result."""
    single = metric_factory()
    for updates in rank_updates:
        for args, kwargs in updates:
            single.update(*args, **kwargs)
    want = single.compute()

    rank_metrics = [metric_factory() for _ in range(len(rank_updates))]
    for metric, updates in zip(rank_metrics, rank_updates):
        for args, kwargs in updates:
            metric.update(*args, **kwargs)

    for metric in rank_metrics:
        gather = _FakeGather(rank_metrics)
        with metric.sync_context(dist_sync_fn=gather, distributed_available=lambda: True):
            synced = metric._inner_compute()
        _values_close(synced, want, atol)
        assert metric._is_synced is False  # local state restored


def run_spmd_state_merge(
    metric_factory: Callable[[], "mt.Metric"],
    rank_updates: Sequence[Sequence[tuple]],
    atol: float = 1e-6,
) -> None:
    """Host-side updates (text kernels tokenize on host), then the per-rank
    state pytrees are stacked onto a 2-device mesh and merged by the SPMD
    compute path's fused collectives."""
    single = metric_factory()
    for updates in rank_updates:
        for args, kwargs in updates:
            single.update(*args, **kwargs)
    want = single.compute()

    init, update_fn, compute_fn = metric_factory().as_functions()
    rank_states = []
    for updates in rank_updates:
        state = init()
        for args, kwargs in updates:
            state = update_fn(state, *args, **kwargs)
        rank_states.append(state)

    stacked = jax.tree.map(lambda *leaves: jnp.stack([jnp.asarray(l) for l in leaves]), *rank_states)
    mesh = Mesh(np.array(jax.devices()[: len(rank_states)]), ("dp",))
    merged = jax.jit(
        shard_map(
            lambda s: compute_fn(jax.tree.map(lambda x: x[0], s), axis_name="dp"),
            mesh=mesh,
            in_specs=P("dp"),
            out_specs=P(),
        )
    )(stacked)
    _values_close(merged, want, atol)


# ---------------------------------------------------------------------- text

PREDS_TEXT = [
    ["the cat is on the mat", "a quick brown fox"],
    ["there is a big tree", "the sun is bright today"],
    ["dogs run fast", "it rains a lot here"],
    ["the house is red", "birds sing in the morning"],
]
TARGET_TEXT = [
    [["a cat is on the mat"], ["the quick brown fox jumps"]],
    [["there is a large tree"], ["the sun shines bright"]],
    [["dogs run very fast"], ["it rains often here"]],
    [["the house is painted red"], ["birds sing at dawn"]],
]


def _stripe(items: list, rank: int) -> list:
    return items[rank::NUM_RANKS]


class TestTextSync:
    def test_bleu_ddp(self):
        run_emulated_ddp(
            lambda: mt.BLEUScore(n_gram=2),
            [[((p, t), {}) for p, t in zip(_stripe(PREDS_TEXT, r), _stripe(TARGET_TEXT, r))] for r in range(NUM_RANKS)],
        )

    def test_bleu_spmd(self):
        run_spmd_state_merge(
            lambda: mt.BLEUScore(n_gram=2),
            [[((p, t), {}) for p, t in zip(_stripe(PREDS_TEXT, r), _stripe(TARGET_TEXT, r))] for r in range(NUM_RANKS)],
        )

    def test_sacre_bleu_ddp(self):
        run_emulated_ddp(
            lambda: mt.SacreBLEUScore(n_gram=2, tokenize="13a"),
            [[((p, t), {}) for p, t in zip(_stripe(PREDS_TEXT, r), _stripe(TARGET_TEXT, r))] for r in range(NUM_RANKS)],
        )

    def test_wer_ddp(self):
        flat_t = [t[0][0] for t in TARGET_TEXT]
        run_emulated_ddp(
            lambda: mt.WordErrorRate(),
            [[((p, t), {}) for p, t in zip(_stripe([x[0] for x in PREDS_TEXT], r), _stripe(flat_t, r))] for r in range(NUM_RANKS)],
        )

    def test_wer_spmd(self):
        flat_t = [t[0][0] for t in TARGET_TEXT]
        run_spmd_state_merge(
            lambda: mt.WordErrorRate(),
            [[((p, t), {}) for p, t in zip(_stripe([x[0] for x in PREDS_TEXT], r), _stripe(flat_t, r))] for r in range(NUM_RANKS)],
        )

    def test_chrf_ddp(self):
        run_emulated_ddp(
            lambda: mt.CHRFScore(n_char_order=3, n_word_order=1),
            [[((p, t), {}) for p, t in zip(_stripe(PREDS_TEXT, r), _stripe(TARGET_TEXT, r))] for r in range(NUM_RANKS)],
        )

    def test_rouge_ddp(self):
        """ROUGE keeps per-sentence score lists (cat states)."""
        flat_t = [t[0][0] for t in TARGET_TEXT]
        run_emulated_ddp(
            lambda: mt.ROUGEScore(rouge_keys=("rouge1", "rougeL")),
            [[((p, t), {}) for p, t in zip(_stripe([x[0] for x in PREDS_TEXT], r), _stripe(flat_t, r))] for r in range(NUM_RANKS)],
            atol=1e-5,
        )

    def test_squad_ddp(self):
        preds = [{"prediction_text": "paris", "id": "q1"}, {"prediction_text": "blue whale", "id": "q2"},
                 {"prediction_text": "7", "id": "q3"}, {"prediction_text": "einstein", "id": "q4"}]
        targets = [
            {"answers": {"answer_start": [0], "text": ["paris"]}, "id": "q1"},
            {"answers": {"answer_start": [0], "text": ["the blue whale"]}, "id": "q2"},
            {"answers": {"answer_start": [0], "text": ["seven"]}, "id": "q3"},
            {"answers": {"answer_start": [0], "text": ["albert einstein"]}, "id": "q4"},
        ]
        run_emulated_ddp(
            lambda: mt.SQuAD(),
            [[(([p], [t]), {}) for p, t in zip(_stripe(preds, r), _stripe(targets, r))] for r in range(NUM_RANKS)],
        )


# ----------------------------------------------------------------- retrieval

RET_RNG = np.random.RandomState(13)
RET_BATCHES = []
for b in range(4):
    n = 16
    RET_BATCHES.append(
        (
            jnp.asarray(RET_RNG.randint(0, 4, n) + 4 * b),  # distinct queries per batch
            jnp.asarray(RET_RNG.rand(n).astype(np.float32)),
            jnp.asarray(RET_RNG.randint(0, 2, n)),
        )
    )


class TestRetrievalSync:
    """`dist_reduce_fx=None` triples: the per-element gather must preserve
    (index, pred, target) row alignment so query grouping survives the merge."""

    @pytest.mark.parametrize(
        "factory",
        [
            lambda: mt.RetrievalMAP(),
            lambda: mt.RetrievalNormalizedDCG(),
            lambda: mt.RetrievalMRR(),
        ],
        ids=["RetrievalMAP", "RetrievalNormalizedDCG", "RetrievalMRR"],
    )
    def test_ddp(self, factory):
        run_emulated_ddp(
            factory,
            [
                [((p, t), {"indexes": idx}) for idx, p, t in _stripe(RET_BATCHES, r)]
                for r in range(NUM_RANKS)
            ],
            atol=1e-5,
        )


# ----------------------------------------------------------------- detection

def _det_batch(seed: int):
    rng = np.random.RandomState(seed)
    n_pred, n_gt = rng.randint(2, 5), rng.randint(1, 4)
    xy = rng.rand(n_pred, 2) * 50
    boxes = np.concatenate([xy, xy + 10 + rng.rand(n_pred, 2) * 30], axis=1).astype(np.float32)
    gxy = rng.rand(n_gt, 2) * 50
    gboxes = np.concatenate([gxy, gxy + 10 + rng.rand(n_gt, 2) * 30], axis=1).astype(np.float32)
    preds = [dict(boxes=jnp.asarray(boxes), scores=jnp.asarray(rng.rand(n_pred).astype(np.float32)),
                  labels=jnp.asarray(rng.randint(0, 2, n_pred)))]
    target = [dict(boxes=jnp.asarray(gboxes), labels=jnp.asarray(rng.randint(0, 2, n_gt)))]
    return preds, target


class TestDetectionSync:
    def test_mean_ap_ddp(self):
        """Five variable-shape list states ride the per-element gather; the
        merged mAP must equal the single-instance value over all images."""
        batches = [_det_batch(s) for s in range(4)]
        run_emulated_ddp(
            lambda: mt.MeanAveragePrecision(iou_thresholds=[0.5, 0.75]),
            [[((p, t), {}) for p, t in _stripe(batches, r)] for r in range(NUM_RANKS)],
            atol=1e-5,
        )


# ------------------------------------------------------------------ wrappers

WRAP_RNG = np.random.RandomState(5)
WRAP_BATCHES = [
    (jnp.asarray(WRAP_RNG.rand(16).astype(np.float32)), jnp.asarray(WRAP_RNG.rand(16).astype(np.float32)))
    for _ in range(4)
]


class TestWrapperSync:
    """Wrapper metrics delegate sync to their child metrics (reference
    semantics: each clone/child is a full Metric with its own states). The
    distributed contract is therefore that every child's states merge like a
    standalone metric's — pinned here through the real sync path."""

    def test_bootstrapper_clone_sync(self):
        """For every bootstrap clone index, the per-rank clone states must
        merge to exactly (Σ sse) / (Σ n) across ranks."""
        rank_bs = [
            mt.BootStrapper(mt.MeanSquaredError(), num_bootstraps=4, sampling_strategy="multinomial")
            for _ in range(NUM_RANKS)
        ]
        for r, bs in enumerate(rank_bs):
            bs._rng = np.random.RandomState(100 + r)
            for p, t in _stripe(WRAP_BATCHES, r):
                bs.update(p, t)

        for i in range(4):
            clones = [bs.metrics[i] for bs in rank_bs]
            sse = sum(float(c.sum_squared_error) for c in clones)
            n = sum(int(c.total) for c in clones)
            gather = _FakeGather(clones)
            with clones[0].sync_context(dist_sync_fn=gather, distributed_available=lambda: True):
                synced = clones[0]._inner_compute()
            _values_close(synced, sse / n, atol=1e-5)
            assert clones[0]._is_synced is False

    def test_minmax_base_sync(self):
        """MinMaxMetric delegates accumulation to the wrapped metric; its
        distributed value is the wrapped metric's merged value."""
        single = mt.MeanSquaredError()
        for p, t in WRAP_BATCHES:
            single.update(p, t)
        want = single.compute()

        rank_wrappers = [mt.MinMaxMetric(mt.MeanSquaredError()) for _ in range(NUM_RANKS)]
        for r, wrapper in enumerate(rank_wrappers):
            for p, t in _stripe(WRAP_BATCHES, r):
                wrapper.update(p, t)

        bases = [w._base_metric for w in rank_wrappers]
        for base in bases:
            gather = _FakeGather(bases)
            with base.sync_context(dist_sync_fn=gather, distributed_available=lambda: True):
                synced = base._inner_compute()
            _values_close(synced, want, atol=1e-5)
            assert base._is_synced is False

    def test_tracker_sync(self):
        """MetricTracker: the CURRENT step's metric syncs across ranks."""
        single = mt.MetricTracker(mt.MeanSquaredError())
        single.increment()
        for p, t in WRAP_BATCHES:
            single.update(p, t)
        want = single.compute()

        rank_trackers = [mt.MetricTracker(mt.MeanSquaredError()) for _ in range(NUM_RANKS)]
        for r, tracker in enumerate(rank_trackers):
            tracker.increment()
            for p, t in _stripe(WRAP_BATCHES, r):
                tracker.update(p, t)

        current = [t._history[-1] for t in rank_trackers]
        for metric in current:
            gather = _FakeGather(current)
            with metric.sync_context(dist_sync_fn=gather, distributed_available=lambda: True):
                synced = metric._inner_compute()
            _values_close(synced, want, atol=1e-5)
            assert metric._is_synced is False
