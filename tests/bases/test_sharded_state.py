"""Metric STATE sharded over the mesh — the TPU-native scale axis.

The reference can only replicate state per process and gather
(`src/torchmetrics/metric.py:356-382`). Here the accumulators themselves are
partitioned (class axis) with `parallel.shard_states`, and three invariants
hold on the 8-device mesh:

1. values equal the replicated (single-placement) oracle bit-for-bit paths;
2. the state STAYS sharded through jitted updates (XLA propagation — no
   silent gather-to-one-device on the accumulation hot path);
3. each device holds only its ``1/n_shards`` slice (the HBM-scaling claim).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import metrics_tpu as mt
from metrics_tpu.parallel import shard_states, state_shardings

N_DEV = 8


@pytest.fixture
def mesh():
    return Mesh(np.array(jax.devices()[:N_DEV]), ("c",))


def _data(n=256, c=64, seed=0):
    rng = np.random.RandomState(seed)
    logits = rng.rand(n, c).astype(np.float32)
    preds = logits / logits.sum(axis=1, keepdims=True)
    target = rng.randint(0, c, size=n)
    return jnp.asarray(preds), jnp.asarray(target)


class TestBinnedCurveClassSharded:
    C, T = 64, 100

    def test_values_and_placement(self, mesh):
        metric = mt.BinnedPrecisionRecallCurve(num_classes=self.C, thresholds=self.T)
        init, update, compute = metric.as_functions()
        specs = {name: P("c", None) for name in ("TPs", "FPs", "FNs")}
        states = shard_states(init(), mesh, specs)
        for name in specs:
            assert states[name].sharding.is_equivalent_to(NamedSharding(mesh, specs[name]), ndim=2)

        jit_update = jax.jit(update, donate_argnums=0)
        for seed in range(3):
            preds, target = _data(c=self.C, seed=seed)
            states = jit_update(states, preds, target)
        # (2) still class-sharded after jitted accumulation
        for name in specs:
            assert states[name].sharding.is_equivalent_to(NamedSharding(mesh, specs[name]), ndim=2), (
                f"state {name} lost its sharding through the jitted update"
            )
            # (3) each device holds a (C/N_DEV, T) slice only
            shard_shapes = {s.data.shape for s in states[name].addressable_shards}
            assert shard_shapes == {(self.C // N_DEV, self.T)}

        # (1) equals the replicated oracle on identical data
        oracle = mt.BinnedPrecisionRecallCurve(num_classes=self.C, thresholds=self.T)
        for seed in range(3):
            oracle.update(*_data(c=self.C, seed=seed))
        o_prec, o_rec, _ = oracle.compute()
        precisions, recalls, _ = compute(states)
        np.testing.assert_allclose(np.asarray(precisions), np.asarray(o_prec), atol=1e-6)
        np.testing.assert_allclose(np.asarray(recalls), np.asarray(o_rec), atol=1e-6)

    def test_binned_ap_value(self, mesh):
        metric = mt.BinnedAveragePrecision(num_classes=self.C, thresholds=self.T)
        init, update, compute = metric.as_functions()
        states = shard_states(init(), mesh, {n: P("c", None) for n in ("TPs", "FPs", "FNs")})
        preds, target = _data(c=self.C, seed=7)
        states = jax.jit(update, donate_argnums=0)(states, preds, target)
        oracle = mt.BinnedAveragePrecision(num_classes=self.C, thresholds=self.T)
        oracle.update(preds, target)
        np.testing.assert_allclose(
            np.asarray(compute(states)), np.asarray(oracle.compute()), atol=1e-6
        )


class TestStatScoresClassSharded:
    C = 64

    def test_macro_family(self, mesh):
        """(C,)-vector tp/fp/tn/fn states sharded over the class axis."""
        metric = mt.F1Score(num_classes=self.C, average="macro")
        init, update, compute = metric.as_functions()
        specs = {name: P("c") for name in ("tp", "fp", "tn", "fn")}
        states = shard_states(init(), mesh, specs)
        jit_update = jax.jit(update, donate_argnums=0)
        for seed in range(2):
            states = jit_update(states, *_data(c=self.C, seed=seed))
        for name in specs:
            assert states[name].sharding.is_equivalent_to(NamedSharding(mesh, specs[name]), ndim=1)
        oracle = mt.F1Score(num_classes=self.C, average="macro")
        for seed in range(2):
            oracle.update(*_data(c=self.C, seed=seed))
        np.testing.assert_allclose(np.asarray(compute(states)), np.asarray(oracle.compute()), atol=1e-6)


class TestDataAndStateAxesCompose:
    """Batch sharded over dp x state sharded over c in ONE jitted program.

    XLA turns the (N,C)x(N,T) count contraction into a distributed matmul:
    partial counts per dp shard, psum over dp, result sharded over c — all
    inferred from input shardings, no shard_map needed.
    """

    C, T = 64, 50

    def test_dp_times_c(self):
        mesh = Mesh(np.array(jax.devices()[:N_DEV]).reshape(4, 2), ("dp", "c"))
        metric = mt.BinnedPrecisionRecallCurve(num_classes=self.C, thresholds=self.T)
        init, update, compute = metric.as_functions()
        specs = {n: P("c", None) for n in ("TPs", "FPs", "FNs")}
        states = shard_states(init(), mesh, specs)
        preds, target = _data(n=512, c=self.C, seed=3)
        preds = jax.device_put(preds, NamedSharding(mesh, P("dp", None)))
        target = jax.device_put(target, NamedSharding(mesh, P("dp")))
        states = jax.jit(update, donate_argnums=0)(states, preds, target)
        for name in specs:
            assert states[name].sharding.is_equivalent_to(NamedSharding(mesh, specs[name]), ndim=2)
        oracle = mt.BinnedPrecisionRecallCurve(num_classes=self.C, thresholds=self.T)
        oracle.update(*_data(n=512, c=self.C, seed=3))
        o_prec, _, _ = oracle.compute()
        precisions, _, _ = compute(states)
        np.testing.assert_allclose(np.asarray(precisions), np.asarray(o_prec), atol=1e-6)


class TestHelperContract:
    def test_list_state_rejected(self, mesh):
        metric = mt.AUROC()  # cat states: preds/target lists
        init, *_ = metric.as_functions()
        with pytest.raises(ValueError, match="cat"):
            state_shardings(init(), mesh, {"preds": P("c")})

    def test_unnamed_states_replicated(self, mesh):
        metric = mt.BinnedPrecisionRecallCurve(num_classes=8, thresholds=5)
        init, _, _ = metric.as_functions()
        sh = state_shardings(init(), mesh, {"TPs": P("c", None)})
        assert sh["TPs"].spec == P("c", None)
        assert sh["FPs"].spec == P()

    def test_unknown_spec_key_rejected(self, mesh):
        metric = mt.BinnedPrecisionRecallCurve(num_classes=8, thresholds=5)
        init, _, _ = metric.as_functions()
        with pytest.raises(ValueError, match="tps"):
            state_shardings(init(), mesh, {"tps": P("c", None)})
