"""EVERY exported module metric crosses the real sync path as itself.

The reference pushes every metric through ``_class_test`` with
``ddp=[False, True]`` (`tests/unittests/helpers/testers.py:398-476`). The
hand-written contract suites (test_ddp.py, test_distributed_contract.py)
cover every state KIND; this module closes the remaining gap by AUTO-
ENUMERATING the registry: each exported :class:`~metrics_tpu.Metric`
subclass gets canned hyperparameters + canned per-domain inputs, two
emulated ranks stripe the batches, sync runs through the REAL host gather
path, and the merged value must equal a single instance over all data.
Metrics whose states are all fixed-shape arrays additionally cross the SPMD
merge (``as_functions`` compute with fused collectives under ``shard_map``).

A completeness guard asserts the spec table plus the skip list covers the
registry EXACTLY, so a newly exported metric fails CI until it declares its
distributed contract here.
"""
from __future__ import annotations

import inspect

import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from tests.bases.test_distributed_contract import run_emulated_ddp, run_spmd_state_merge

RNG = np.random.RandomState(77)
N, C = 24, 4  # per-batch rows, classes
NUM_BATCHES = 4  # striped 2/2 over the emulated ranks


def _batches(maker):
    return [maker(i) for i in range(NUM_BATCHES)]


def _probs(i):
    p = RNG.rand(N, C).astype(np.float32)
    return jnp.asarray(p / p.sum(1, keepdims=True))


def _labels(i):
    return jnp.asarray(RNG.randint(0, C, N))


def _binary_scores(i):
    return jnp.asarray(RNG.rand(N).astype(np.float32))


def _binary_labels(i):
    return jnp.asarray(RNG.randint(0, 2, N))


def _reg(i):
    return jnp.asarray(RNG.randn(N).astype(np.float32))


def _reg_pos(i):
    return jnp.asarray((np.abs(RNG.randn(N)) + 0.1).astype(np.float32))


def _mlabel_ind(i):
    return jnp.asarray(RNG.randint(0, 2, (N, C)))


def _img(i):
    return jnp.asarray(RNG.rand(2, 3, 32, 32).astype(np.float32))


def _img_big(i):
    return jnp.asarray(RNG.rand(1, 1, 192, 192).astype(np.float32))


def _audio(i):
    return jnp.asarray(RNG.randn(2, 2000).astype(np.float32))


def _audio_multisrc(i):
    return jnp.asarray(RNG.randn(2, 2, 1500).astype(np.float32))


CLS2 = [(_probs(i), _labels(i)) for i in range(NUM_BATCHES)]
BIN2 = [(_binary_scores(i), _binary_labels(i)) for i in range(NUM_BATCHES)]
REG2 = [(_reg(i), _reg(i) + 0.1) for i in range(NUM_BATCHES)]
POS2 = [(_reg_pos(i), _reg_pos(i)) for i in range(NUM_BATCHES)]
ML2 = [(_probs(i), _mlabel_ind(i)) for i in range(NUM_BATCHES)]
IMG2 = [(_img(i), _img(i) * 0.9 + 0.05) for i in range(NUM_BATCHES)]
IMGB2 = [(_img_big(i), _img_big(i) * 0.9 + 0.05) for i in range(NUM_BATCHES)]
AUD2 = [(_audio(i), _audio(i) * 0.8) for i in range(NUM_BATCHES)]
AUDM2 = [(_audio_multisrc(i), _audio_multisrc(i) * 0.8) for i in range(NUM_BATCHES)]
AGG1 = [(_reg(i),) for i in range(NUM_BATCHES)]
REG2D = [
    (jnp.asarray(RNG.randn(N, 6).astype(np.float32)), jnp.asarray(RNG.randn(N, 6).astype(np.float32)))
    for _ in range(NUM_BATCHES)
]
MOUT2 = [
    (jnp.asarray(RNG.randn(N, 2).astype(np.float32)), jnp.asarray(RNG.randn(N, 2).astype(np.float32)))
    for _ in range(NUM_BATCHES)
]
PERP2 = [
    (jnp.asarray(RNG.randn(2, 6, 8).astype(np.float32)), jnp.asarray(RNG.randint(0, 8, (2, 6))))
    for _ in range(NUM_BATCHES)
]
RET2 = [
    (
        jnp.asarray(RNG.rand(N).astype(np.float32)),
        jnp.asarray(RNG.randint(0, 2, N)),
        {"indexes": jnp.asarray(RNG.randint(0, 3, N) + 3 * i)},
    )
    for i in range(NUM_BATCHES)
]

TEXT_P = ["the cat is on the mat", "a quick brown fox", "there is a big tree", "the sun is bright"]
TEXT_T = [
    ["a cat sat on the mat"],
    ["the quick brown fox jumps"],
    ["there is a large tree"],
    ["the sun shines bright"],
]
TXT2 = [([p], [t]) for p, t in zip(TEXT_P, TEXT_T)]
TXTFLAT2 = [([p], [t[0]]) for p, t in zip(TEXT_P, TEXT_T)]

SQUAD2 = [
    (
        [{"prediction_text": p, "id": f"q{i}"}],
        [{"answers": {"answer_start": [0], "text": [t[0]]}, "id": f"q{i}"}],
    )
    for i, (p, t) in enumerate(zip(TEXT_P, TEXT_T))
]


def _det_batch(seed):
    rng = np.random.RandomState(seed)
    n_pred, n_gt = rng.randint(2, 5), rng.randint(1, 4)
    xy = rng.rand(n_pred, 2) * 50
    boxes = np.concatenate([xy, xy + 10 + rng.rand(n_pred, 2) * 30], 1).astype(np.float32)
    gxy = rng.rand(n_gt, 2) * 50
    gboxes = np.concatenate([gxy, gxy + 10 + rng.rand(n_gt, 2) * 30], 1).astype(np.float32)
    return (
        [dict(boxes=jnp.asarray(boxes), scores=jnp.asarray(rng.rand(n_pred).astype(np.float32)),
              labels=jnp.asarray(rng.randint(0, 2, n_pred)))],
        [dict(boxes=jnp.asarray(gboxes), labels=jnp.asarray(rng.randint(0, 2, n_gt)))],
    )


DET2 = [_det_batch(s) for s in range(NUM_BATCHES)]

# name -> (factory, batches, atol). Batches: list of (args...) tuples or
# (args..., kwargs_dict) when the trailing element is a dict.
SPEC = {
    "AUC": (lambda: mt.AUC(reorder=True), [(jnp.sort(_reg(i)), _reg(i)) for i in range(NUM_BATCHES)], 1e-5),
    "AUROC": (lambda: mt.AUROC(), BIN2, 1e-5),
    "Accuracy": (lambda: mt.Accuracy(num_classes=C, average="macro"), CLS2, 1e-6),
    "AveragePrecision": (lambda: mt.AveragePrecision(), BIN2, 1e-5),
    "BLEUScore": (lambda: mt.BLEUScore(n_gram=2), TXT2, 1e-6),
    "BinnedAveragePrecision": (lambda: mt.BinnedAveragePrecision(num_classes=1, thresholds=20), BIN2, 1e-5),
    "BinnedPrecisionRecallCurve": (lambda: mt.BinnedPrecisionRecallCurve(num_classes=1, thresholds=20), BIN2, 1e-5),
    "BinnedRecallAtFixedPrecision": (
        lambda: mt.BinnedRecallAtFixedPrecision(num_classes=1, min_precision=0.3, thresholds=20), BIN2, 1e-5,
    ),
    # BootStrapper crosses sync as itself in test_bootstrapper_wrapper_sync
    # below: bootstrap resampling is rank-local randomness, so the merged
    # value cannot equal a single instance's (different resamples) — the
    # contract is per-clone state merging through the wrapper's own sync.
    "CHRFScore": (lambda: mt.CHRFScore(n_char_order=3, n_word_order=1), TXT2, 1e-5),
    "CalibrationError": (lambda: mt.CalibrationError(), BIN2, 1e-6),
    "CatMetric": (lambda: mt.CatMetric(), AGG1, 1e-6),
    "CharErrorRate": (lambda: mt.CharErrorRate(), TXTFLAT2, 1e-6),
    "ClasswiseWrapper": (
        lambda: mt.ClasswiseWrapper(mt.Accuracy(num_classes=C, average="none")), CLS2, 1e-6,
    ),
    "CohenKappa": (lambda: mt.CohenKappa(num_classes=C), CLS2, 1e-6),
    "CompositionalMetric": (
        lambda: mt.Accuracy(num_classes=C, average="macro") + mt.Accuracy(num_classes=C, average="micro"),
        CLS2, 1e-6,
    ),
    "ConfusionMatrix": (lambda: mt.ConfusionMatrix(num_classes=C), CLS2, 1e-6),
    "CosineSimilarity": (lambda: mt.CosineSimilarity(), REG2D, 1e-5),
    "CoverageError": (lambda: mt.CoverageError(), ML2, 1e-6),
    "Dice": (lambda: mt.Dice(num_classes=C), CLS2, 1e-6),
    "ErrorRelativeGlobalDimensionlessSynthesis": (
        lambda: mt.ErrorRelativeGlobalDimensionlessSynthesis(), IMG2, 1e-3,
    ),
    "ExplainedVariance": (lambda: mt.ExplainedVariance(), REG2, 1e-5),
    "ExtendedEditDistance": (lambda: mt.ExtendedEditDistance(), TXTFLAT2, 1e-5),
    "F1Score": (lambda: mt.F1Score(num_classes=C, average="macro"), CLS2, 1e-6),
    "FBetaScore": (lambda: mt.FBetaScore(num_classes=C, beta=0.5), CLS2, 1e-6),
    "HammingDistance": (lambda: mt.HammingDistance(), ML2, 1e-6),
    "HingeLoss": (lambda: mt.HingeLoss(), BIN2, 1e-5),
    "JaccardIndex": (lambda: mt.JaccardIndex(num_classes=C), CLS2, 1e-6),
    "KLDivergence": (lambda: mt.KLDivergence(), [(_probs(i), _probs(i)) for i in range(NUM_BATCHES)], 1e-5),
    "LabelRankingAveragePrecision": (lambda: mt.LabelRankingAveragePrecision(), ML2, 1e-5),
    "LabelRankingLoss": (lambda: mt.LabelRankingLoss(), ML2, 1e-5),
    "MatchErrorRate": (lambda: mt.MatchErrorRate(), TXTFLAT2, 1e-6),
    "MatthewsCorrCoef": (lambda: mt.MatthewsCorrCoef(num_classes=C), CLS2, 1e-5),
    "MaxMetric": (lambda: mt.MaxMetric(), AGG1, 1e-6),
    "MeanAbsoluteError": (lambda: mt.MeanAbsoluteError(), REG2, 1e-5),
    "MeanAbsolutePercentageError": (lambda: mt.MeanAbsolutePercentageError(), POS2, 1e-5),
    "MeanAveragePrecision": (lambda: mt.MeanAveragePrecision(iou_thresholds=[0.5]), DET2, 1e-5),
    "MeanMetric": (lambda: mt.MeanMetric(), AGG1, 1e-5),
    "MeanSquaredError": (lambda: mt.MeanSquaredError(), REG2, 1e-5),
    "MeanSquaredLogError": (lambda: mt.MeanSquaredLogError(), POS2, 1e-5),
    "MinMaxMetric": (lambda: mt.MinMaxMetric(mt.MeanSquaredError()), REG2, 1e-5),
    "MinMetric": (lambda: mt.MinMetric(), AGG1, 1e-6),
    "MultiScaleStructuralSimilarityIndexMeasure": (
        lambda: mt.MultiScaleStructuralSimilarityIndexMeasure(data_range=1.0), IMGB2, 1e-4,
    ),
    "MultioutputWrapper": (
        lambda: mt.MultioutputWrapper(mt.MeanSquaredError(), num_outputs=2), MOUT2, 1e-5,
    ),
    "PeakSignalNoiseRatio": (lambda: mt.PeakSignalNoiseRatio(data_range=1.0), IMG2, 1e-4),
    "PearsonCorrCoef": (lambda: mt.PearsonCorrCoef(), REG2, 1e-4),
    "PermutationInvariantTraining": (
        lambda: mt.PermutationInvariantTraining(
            mt.functional.scale_invariant_signal_noise_ratio, eval_func="max"
        ),
        AUDM2, 1e-4,
    ),
    "Perplexity": (lambda: mt.Perplexity(), PERP2, 1e-4),
    "Precision": (lambda: mt.Precision(num_classes=C, average="macro"), CLS2, 1e-6),
    "PrecisionRecallCurve": (lambda: mt.PrecisionRecallCurve(), BIN2, 1e-5),
    "R2Score": (lambda: mt.R2Score(), REG2, 1e-5),
    "ROC": (lambda: mt.ROC(), BIN2, 1e-5),
    "ROUGEScore": (lambda: mt.ROUGEScore(rouge_keys=("rouge1", "rougeL")), TXTFLAT2, 1e-5),
    "Recall": (lambda: mt.Recall(num_classes=C, average="macro"), CLS2, 1e-6),
    "RetrievalFallOut": (lambda: mt.RetrievalFallOut(), RET2, 1e-5),
    "RetrievalHitRate": (lambda: mt.RetrievalHitRate(), RET2, 1e-5),
    "RetrievalMAP": (lambda: mt.RetrievalMAP(), RET2, 1e-5),
    "RetrievalMRR": (lambda: mt.RetrievalMRR(), RET2, 1e-5),
    "RetrievalNormalizedDCG": (lambda: mt.RetrievalNormalizedDCG(), RET2, 1e-5),
    "RetrievalPrecision": (lambda: mt.RetrievalPrecision(), RET2, 1e-5),
    "RetrievalPrecisionRecallCurve": (lambda: mt.RetrievalPrecisionRecallCurve(max_k=4), RET2, 1e-5),
    "RetrievalRPrecision": (lambda: mt.RetrievalRPrecision(), RET2, 1e-5),
    "RetrievalRecall": (lambda: mt.RetrievalRecall(), RET2, 1e-5),
    "RetrievalRecallAtFixedPrecision": (
        lambda: mt.RetrievalRecallAtFixedPrecision(min_precision=0.3, max_k=4), RET2, 1e-5,
    ),
    "SQuAD": (lambda: mt.SQuAD(), SQUAD2, 1e-6),
    "SacreBLEUScore": (lambda: mt.SacreBLEUScore(n_gram=2, tokenize="13a"), TXT2, 1e-6),
    "ScaleInvariantSignalDistortionRatio": (lambda: mt.ScaleInvariantSignalDistortionRatio(), AUD2, 1e-4),
    "ScaleInvariantSignalNoiseRatio": (lambda: mt.ScaleInvariantSignalNoiseRatio(), AUD2, 1e-4),
    "SignalDistortionRatio": (lambda: mt.SignalDistortionRatio(), AUD2, 1e-3),
    "ShortTimeObjectiveIntelligibility": (
        lambda: mt.ShortTimeObjectiveIntelligibility(10000),
        [
            (
                jnp.asarray((np.sin(2 * np.pi * 500 * np.arange(6000) / 10000) * (1 + 0.4 * np.sin(2 * np.pi * 3 * np.arange(6000) / 10000)) + 0.3 * RNG.randn(6000)).astype(np.float32)),
                jnp.asarray((np.sin(2 * np.pi * 500 * np.arange(6000) / 10000) * (1 + 0.4 * np.sin(2 * np.pi * 3 * np.arange(6000) / 10000)) + 0.02 * RNG.randn(6000)).astype(np.float32)),
            )
            for _ in range(NUM_BATCHES)
        ],
        1e-5,
    ),
    "SignalNoiseRatio": (lambda: mt.SignalNoiseRatio(), AUD2, 1e-4),
    "SpearmanCorrCoef": (lambda: mt.SpearmanCorrCoef(), REG2, 1e-5),
    "Specificity": (lambda: mt.Specificity(num_classes=C), CLS2, 1e-6),
    "SpectralAngleMapper": (lambda: mt.SpectralAngleMapper(), IMG2, 1e-4),
    "SpectralDistortionIndex": (lambda: mt.SpectralDistortionIndex(), IMG2, 1e-4),
    "StatScores": (lambda: mt.StatScores(num_classes=C, reduce="macro"), CLS2, 1e-6),
    "StructuralSimilarityIndexMeasure": (lambda: mt.StructuralSimilarityIndexMeasure(), IMG2, 1e-4),
    "SumMetric": (lambda: mt.SumMetric(), AGG1, 1e-5),
    "SymmetricMeanAbsolutePercentageError": (lambda: mt.SymmetricMeanAbsolutePercentageError(), POS2, 1e-5),
    "TranslationEditRate": (lambda: mt.TranslationEditRate(), TXT2, 1e-5),
    "TweedieDevianceScore": (lambda: mt.TweedieDevianceScore(power=1.5), POS2, 1e-5),
    "UniversalImageQualityIndex": (lambda: mt.UniversalImageQualityIndex(), IMG2, 1e-4),
    "WeightedMeanAbsolutePercentageError": (lambda: mt.WeightedMeanAbsolutePercentageError(), POS2, 1e-5),
    "WordErrorRate": (lambda: mt.WordErrorRate(), TXTFLAT2, 1e-6),
    "WordInfoLost": (lambda: mt.WordInfoLost(), TXTFLAT2, 1e-6),
    "WordInfoPreserved": (lambda: mt.WordInfoPreserved(), TXTFLAT2, 1e-6),
}

# model-backed metrics need pretrained weights / external DSP backends; their
# sync machinery is the plain state registry, covered by the state-kind
# contract suites
SKIP = {
    "BERTScore": "model-backed (transformer weights)",
    "InfoLM": "model-backed (transformer weights)",
    "FrechetInceptionDistance": "model-backed (InceptionV3 weights)",
    "InceptionScore": "model-backed (InceptionV3 weights)",
    "KernelInceptionDistance": "model-backed (InceptionV3 weights)",
    "LearnedPerceptualImagePatchSimilarity": "model-backed (LPIPS nets)",
    "PerceptualEvaluationSpeechQuality": "gated external backend (pesq)",
}


def _registry():
    names = []
    for name in sorted(dir(mt)):
        obj = getattr(mt, name)
        if (
            inspect.isclass(obj)
            and issubclass(obj, mt.Metric)
            and obj is not mt.Metric
            and not inspect.isabstract(obj)
        ):
            names.append(name)
    return names


CUSTOM = {"BootStrapper": "rank-local resampling; custom contract test below"}


def test_spec_covers_entire_registry():
    registry = set(_registry())
    covered = set(SPEC) | set(SKIP) | set(CUSTOM)
    assert registry - covered == set(), f"metrics missing a distributed contract: {sorted(registry - covered)}"
    assert covered - registry == set(), f"stale spec entries: {sorted(covered - registry)}"
    assert set(SPEC) & set(SKIP) == set()


def test_bootstrapper_wrapper_sync():
    """BootStrapper syncs AS ITSELF (wrapper sync recurses into clones): for
    every clone index the synced wrapper's value reflects the cross-rank
    merged clone states — (sum sse)/(sum n) per clone."""
    from tests.helpers.testers import _FakeGather

    rank_bs = [
        mt.BootStrapper(mt.MeanSquaredError(), num_bootstraps=3, sampling_strategy="multinomial")
        for _ in range(2)
    ]
    for r, bs in enumerate(rank_bs):
        bs._rng = np.random.RandomState(100 + r)
        for p, t in [b for b in REG2[r::2]]:
            bs.update(p, t)

    want_per_clone = []
    for i in range(3):
        sse = sum(float(bs.metrics[i].sum_squared_error) for bs in rank_bs)
        n = sum(int(bs.metrics[i].total) for bs in rank_bs)
        want_per_clone.append(sse / n)

    bs0 = rank_bs[0]
    gather = _FakeGather(rank_bs)
    with bs0.sync_context(dist_sync_fn=gather, distributed_available=lambda: True):
        synced = bs0._inner_compute()
    np.testing.assert_allclose(float(synced["mean"]), np.mean(want_per_clone), atol=1e-5)
    assert bs0._is_synced is False
    for clone in bs0.metrics:
        assert clone._is_synced is False  # children restored by wrapper unsync


def _rank_updates(batches):
    def norm(b):
        if b and isinstance(b[-1], dict):
            return (tuple(b[:-1]), b[-1])
        return (tuple(b), {})

    return [[norm(b) for b in batches[r::2]] for r in range(2)]


@pytest.mark.parametrize("name", sorted(SPEC))
def test_registry_ddp(name):
    factory, batches, atol = SPEC[name]
    run_emulated_ddp(factory, _rank_updates(batches), atol=atol)


# SPMD merge: metrics whose init() state is entirely fixed-shape arrays AND
# whose compute traces. Exact-curve/host-grouping metrics are excluded with
# the reason (the module API covers their sync above).
SPMD_EXCLUDE = {
    "AUC": "cat states (x/y pairs)",
    "AUROC": "cat states (exact scores)",
    "AveragePrecision": "cat states",
    "CatMetric": "cat states",
    "ClasswiseWrapper": "child-metric states (wrapper)",
    "CompositionalMetric": "component states",
    "CHRFScore": "per-sentence cat lists (sentence-level score option)",
    "CosineSimilarity": "cat states",
    "ExtendedEditDistance": "per-sentence cat lists",
    "CoverageError": "sum states but host ranking update",
    "ErrorRelativeGlobalDimensionlessSynthesis": "cat states",
    "MeanAveragePrecision": "variable-shape list states",
    "MinMaxMetric": "child-metric states (wrapper)",
    "MultiScaleStructuralSimilarityIndexMeasure": "cat states",
    "MultioutputWrapper": "child-metric states (wrapper)",
    "PearsonCorrCoef": "stacked-stat merge covered in dryrun/mesh tests",
    "PrecisionRecallCurve": "cat states + untraceable exact curve",
    "ROC": "cat states + untraceable exact curve",
    "ROUGEScore": "per-sentence cat lists",
    "SQuAD": "host string matching",
    "SpearmanCorrCoef": "cat states",
    "SpectralAngleMapper": "cat states",
    "SpectralDistortionIndex": "cat states",
    "StructuralSimilarityIndexMeasure": "cat states",
    "UniversalImageQualityIndex": "cat states",
    "RetrievalFallOut": "per-query grouping (None-spec states)",
    "RetrievalHitRate": "per-query grouping",
    "RetrievalMAP": "per-query grouping",
    "RetrievalMRR": "per-query grouping",
    "RetrievalNormalizedDCG": "per-query grouping",
    "RetrievalPrecision": "per-query grouping",
    "RetrievalPrecisionRecallCurve": "per-query grouping",
    "RetrievalRPrecision": "per-query grouping",
    "RetrievalRecall": "per-query grouping",
    "RetrievalRecallAtFixedPrecision": "per-query grouping",
    "PermutationInvariantTraining": "metric_func closure (callable hyperparam)",
}


@pytest.mark.parametrize("name", sorted(set(SPEC) - set(SPMD_EXCLUDE)))
def test_registry_spmd_merge(name):
    factory, batches, atol = SPEC[name]
    probe = factory()
    state = probe.as_functions()[0]()
    assert not any(isinstance(v, list) for v in state.values()), (
        f"{name} grew a list state; move it to SPMD_EXCLUDE with the reason"
    )
    run_spmd_state_merge(factory, _rank_updates(batches), atol=atol)


# ------------------------------------------------- batched-step (chunk) API

# entries whose update arguments are not stackable arrays (host-side strings,
# per-image dict lists, ragged shapes) have no chunked contract — their hot
# path is the host loop
CHUNK_SKIP = {
    "BLEUScore": "string inputs",
    "CHRFScore": "string inputs",
    "CharErrorRate": "string inputs",
    "ExtendedEditDistance": "string inputs",
    "MatchErrorRate": "string inputs",
    "MeanAveragePrecision": "per-image dict lists",
    "ROUGEScore": "string inputs",
    "SQuAD": "dict inputs",
    "SacreBLEUScore": "string inputs",
    "TranslationEditRate": "string inputs",
    "WordErrorRate": "string inputs",
    "WordInfoLost": "string inputs",
    "WordInfoPreserved": "string inputs",
}


def _stackable(batches):
    import jax

    norm = _rank_updates(batches)  # reuse arg/kwargs normalization
    flat_batches = [b for rank in norm for b in rank]
    structure0 = jax.tree.structure((flat_batches[0][0], flat_batches[0][1]))
    leaves0 = jax.tree.leaves((flat_batches[0][0], flat_batches[0][1]))
    if not all(hasattr(x, "shape") for x in leaves0):
        return None
    shapes0 = [x.shape for x in leaves0]
    for args, kwargs in flat_batches[1:]:
        if jax.tree.structure((args, kwargs)) != structure0:
            return None
        leaves = jax.tree.leaves((args, kwargs))
        if any(not hasattr(x, "shape") or x.shape != s for x, s in zip(leaves, shapes0)):
            return None
    return flat_batches


def test_chunk_skip_is_consistent():
    assert set(CHUNK_SKIP) <= set(SPEC), sorted(set(CHUNK_SKIP) - set(SPEC))


@pytest.mark.parametrize("name", sorted(set(SPEC) - set(CHUNK_SKIP)))
def test_registry_update_many_matches_sequential(name):
    """`update_many` over the stacked chunk must equal sequential `update`
    calls for every exported metric with stackable inputs — and the SECOND
    identical chunk must cross the compiled scan path (the first chunk per
    signature is eager-validated by design), so a scan-program bug in any
    registry metric fails here."""
    import jax

    from metrics_tpu.utils import checks

    factory, batches, atol = SPEC[name]
    flat = _stackable(batches)
    assert flat is not None, (
        f"{name}: inputs not stackable — declare it in CHUNK_SKIP with the reason"
    )

    chunk_args, chunk_kwargs = jax.tree.map(lambda *xs: jnp.stack(xs), *[(a, k) for a, k in flat])

    # validation mode "first" lets the scan path engage on the second chunk —
    # the default "full" mode keeps every chunk on the eager loop by design
    checks.set_validation_mode("first")
    try:
        chunked = factory()
        chunked.update_many(*chunk_args, **chunk_kwargs)  # eager-validated first chunk
        chunked.update_many(*chunk_args, **chunk_kwargs)  # scan path (when fusable)
        sequential = factory()
        for _ in range(2):
            for args, kwargs in flat:
                sequential.update(*args, **kwargs)
    finally:
        checks.set_validation_mode("first")

    from tests.bases.test_distributed_contract import _values_close

    _values_close(chunked.compute(), sequential.compute(), atol)
    assert chunked._update_count == 2 * len(flat)
