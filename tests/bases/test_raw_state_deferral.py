"""Deferred canonicalization contract for cat-state metrics.

Cat-state ("list") metrics buffer RAW input rows at ``update`` time — zero
device dispatches on the steady-state hot path — and canonicalize at
observation time: per-row via ``Metric._canonicalize_list_states`` before
sync/state_dict/pickle, post-concat inside ``compute``. These tests pin:

1. raw appends — the buffered row IS the input object (no copy, no cast);
2. fail-fast parity — invalid inputs still raise at ``update``;
3. observation canonicalizes — state_dict/pickle rows are 1-D/formatted and
   idempotent under repeated canonicalization;
4. commutation — multi-batch compute equals single-shot compute on the
   concatenated data, including the heterogeneous-trailing-shape fallback;
5. emulated multi-rank sync still reduces correctly over raw rows.

Reference behavior being preserved: per-update canonicalization in
`retrieval/base.py:122-131`, `classification/precision_recall_curve.py`,
`image/uqi.py`, `aggregation.py:268-313` of the reference tree.
"""
import pickle

import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from tests.helpers.testers import _FakeGather


def test_retrieval_update_appends_raw_rows():
    m = mt.RetrievalMRR()
    p = jnp.asarray([[0.3, 0.7], [0.4, 0.1]])
    t = jnp.asarray([[0, 1], [1, 0]])
    idx = jnp.asarray([[0, 0], [1, 1]])
    m.update(p, t, idx)
    assert m.preds[0] is p and m.target[0] is t and m.indexes[0] is idx


def test_curve_update_appends_raw_rows():
    m = mt.PrecisionRecallCurve(num_classes=3)
    p = jnp.asarray(np.random.RandomState(0).rand(6, 3).astype(np.float32))
    t = jnp.asarray([0, 1, 2, 0, 1, 2])
    m.update(p, t)
    assert m.preds[0] is p and m.target[0] is t


def test_cat_metric_gated_update_appends_raw(monkeypatch):
    from metrics_tpu.utils import checks

    monkeypatch.setattr(checks, "_validation_mode", "off")
    m = mt.CatMetric()
    v = jnp.asarray([1.0, 2.0])
    m.update(v)
    assert m.value[0] is v
    np.testing.assert_allclose(np.asarray(m.compute()), [1.0, 2.0])


def test_update_still_fails_fast():
    m = mt.RetrievalMRR()
    with pytest.raises(ValueError, match="same shape"):
        m.update(jnp.asarray([[0.5]]), jnp.asarray([1]), jnp.asarray([0]))
    with pytest.raises(ValueError, match="long integers"):
        m.update(jnp.asarray([0.5]), jnp.asarray([1]), jnp.asarray([0.5]))
    with pytest.raises(ValueError, match="binary values"):
        m.update(jnp.asarray([0.5]), jnp.asarray([7]), jnp.asarray([0]))

    c = mt.PrecisionRecallCurve(num_classes=2)
    with pytest.raises(ValueError, match="number of classes"):
        c.update(jnp.asarray(np.random.rand(4, 3)), jnp.asarray([0, 1, 2, 1]))

    s = mt.SpearmanCorrCoef()
    with pytest.raises(ValueError, match="1 dimensional"):
        s.update(jnp.asarray(np.random.rand(4, 3)), jnp.asarray(np.random.rand(4, 3)))

    img = mt.UniversalImageQualityIndex()
    with pytest.raises(ValueError, match="BxCxHxW"):
        img.update(jnp.zeros((3, 4, 4)), jnp.zeros((3, 4, 4)))


def test_state_dict_rows_are_canonical_and_idempotent():
    m = mt.RetrievalNormalizedDCG(ignore_index=-1)
    rng = np.random.RandomState(0)
    for _ in range(2):
        t = rng.randint(0, 2, (4, 8))
        t[0, 0] = -1
        m.update(rng.rand(4, 8).astype(np.float32), t, np.repeat(np.arange(4), 8).reshape(4, 8))
    before = float(m.compute())
    m.persistent(True)
    sd = m.state_dict()
    # flattened, filtered, canonically typed
    assert all(v.ndim == 1 and v.shape[0] == 31 for v in sd["preds"])
    assert sd["target"][0].dtype == np.int32
    assert sd["preds"][0].dtype == np.float32
    # idempotent: canonicalizing again changes nothing
    m._canonicalize_list_states()
    assert float(m.compute()) == before
    # host rows stayed host arrays (compute_on_cpu compatibility)
    assert isinstance(m.preds[0], np.ndarray)

    m2 = pickle.loads(pickle.dumps(m))
    assert float(m2.compute()) == before


@pytest.mark.parametrize("cls", [mt.PrecisionRecallCurve, mt.ROC])
def test_curve_multibatch_commutation_multidim(cls):
    """Varying extra-dim batches hit the per-row canonicalization fallback."""
    rng = np.random.RandomState(1)
    batches = []
    for x in (3, 5):  # heterogeneous trailing shape across batches
        p = rng.rand(4, 5, x).astype(np.float32)
        p /= p.sum(1, keepdims=True)
        t = rng.randint(0, 5, (4, x))
        batches.append((p, t))

    m = cls(num_classes=5)
    for p, t in batches:
        m.update(jnp.asarray(p), jnp.asarray(t))
    streamed = m.compute()

    # single-shot on the flattened equivalent (canonical formatting applied
    # per batch, concatenated): the reference's per-update storage layout
    from metrics_tpu.functional.classification.precision_recall_curve import (
        _precision_recall_curve_update,
    )

    fp, ft = [], []
    for p, t in batches:
        a, b, _, _ = _precision_recall_curve_update(jnp.asarray(p), jnp.asarray(t), 5, None)
        fp.append(a)
        ft.append(b)
    m2 = cls(num_classes=5)
    m2.update(jnp.concatenate(fp), jnp.concatenate(ft))
    oneshot = m2.compute()

    for a, b in zip(streamed, oneshot):
        if isinstance(a, (list, tuple)):
            for ai, bi in zip(a, b):
                np.testing.assert_allclose(np.asarray(ai), np.asarray(bi), atol=1e-6)
        else:
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_auroc_multibatch_matches_oneshot():
    rng = np.random.RandomState(2)
    p1, p2 = rng.rand(16).astype(np.float32), rng.rand(24).astype(np.float32)
    t1, t2 = rng.randint(0, 2, 16), rng.randint(0, 2, 24)
    m = mt.AUROC(pos_label=1)
    m.update(jnp.asarray(p1), jnp.asarray(t1))
    m.update(jnp.asarray(p2), jnp.asarray(t2))
    one = mt.AUROC(pos_label=1)
    one.update(jnp.asarray(np.concatenate([p1, p2])), jnp.asarray(np.concatenate([t1, t2])))
    np.testing.assert_allclose(np.asarray(m.compute()), np.asarray(one.compute()), atol=1e-6)


def test_fake_gather_sync_over_raw_rows():
    """Emulated 2-rank sync: raw rows on the non-syncing rank canonicalize."""
    ranks = [mt.RetrievalMRR() for _ in range(2)]
    rng = np.random.RandomState(3)
    for r, rank in enumerate(ranks):
        # different RAW shapes per rank: (2, 4) vs (8,) — rank-1 would break
        # the pad-to-max gather without symmetric canonicalization
        if r == 0:
            rank.update(rng.rand(2, 4).astype(np.float32), rng.randint(0, 2, (2, 4)), np.zeros((2, 4), np.int64))
        else:
            rank.update(rng.rand(8).astype(np.float32), rng.randint(0, 2, 8), np.ones(8, np.int64))
    expected = mt.RetrievalMRR()
    for rank_src in [0, 1]:
        expected.update(
            ranks[rank_src].preds[0].reshape(-1),
            ranks[rank_src].target[0].reshape(-1),
            ranks[rank_src].indexes[0].reshape(-1),
        )
    gather = _FakeGather(ranks)
    m = ranks[0]
    m.sync(dist_sync_fn=gather, distributed_available=lambda: True)
    synced = float(m.compute())
    m._computed = None
    np.testing.assert_allclose(synced, float(expected.compute()), atol=1e-6)
    m.unsync()


def test_post_sync_state_dict_and_compute_on_reduced_cat_state():
    """After sync reduces a "cat" list state to one bare array, the
    canonicalization hooks must no-op (state_dict/pickle inside the sync
    context used to item-assign into the immutable array) and compute must
    not iterate the array row-by-row."""
    ranks = [mt.PrecisionRecallCurve(pos_label=1) for _ in range(2)]
    rng = np.random.RandomState(7)
    for rank in ranks:
        rank.update(jnp.asarray(rng.rand(16).astype(np.float32)), jnp.asarray(rng.randint(0, 2, 16)))
        rank.persistent(True)
    gather = _FakeGather(ranks)
    m = ranks[0]
    with m.sync_context(dist_sync_fn=gather, distributed_available=lambda: True):
        assert not isinstance(m.preds, list)  # reduced to one bare array
        sd = m.state_dict()  # must not raise on the immutable array
        assert sd["preds"].shape == (32,)
        pickle.dumps(m)
        p, r, t = m.compute()  # bare-array fast path in _cat_raw
        assert p.shape[0] == r.shape[0]
    assert isinstance(m.preds, list)  # local state restored


def test_compute_on_cpu_with_raw_curve_rows():
    """Host-offloaded raw rows must stay numpy through canonicalization and
    still compute correctly (multidim multiclass exercises the full layout
    transform on host arrays)."""
    rng = np.random.RandomState(9)
    m = mt.PrecisionRecallCurve(num_classes=3, compute_on_cpu=True)
    ref = mt.PrecisionRecallCurve(num_classes=3)
    for _ in range(2):
        p = rng.rand(4, 3, 5).astype(np.float32)
        p /= p.sum(1, keepdims=True)
        t = rng.randint(0, 3, (4, 5))
        m.update(jnp.asarray(p), jnp.asarray(t))
        ref.update(jnp.asarray(p), jnp.asarray(t))
    assert all(isinstance(r, np.ndarray) for r in m.preds)  # offloaded raw rows
    m._canonicalize_list_states()
    assert all(isinstance(r, np.ndarray) for r in m.preds)  # still host-side
    for a, b in zip(m.compute(), ref.compute()):
        for x, y in zip(a if isinstance(a, list) else [a], b if isinstance(b, list) else [b]):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=1e-6)


def test_cosine_similarity_defers_cast():
    m = mt.CosineSimilarity(reduction="mean")
    p = jnp.asarray([[2.0, 0.0], [1.0, 1.0]])
    t = jnp.asarray([[1.0, 0.0], [1.0, 0.0]])
    m.update(p, t)
    assert m.preds[0] is p
    assert round(float(m.compute()), 4) == 0.8536


def test_spearman_raw_rows_and_squeeze_semantics():
    m = mt.SpearmanCorrCoef()
    p = jnp.asarray(np.random.RandomState(4).rand(8, 1).astype(np.float32))
    t = jnp.asarray(np.random.RandomState(5).rand(8, 1).astype(np.float32))
    m.update(p, t)  # (N, 1) squeezes to (N,) — allowed
    assert m.preds[0] is p
    ref = mt.SpearmanCorrCoef()
    ref.update(p.reshape(-1), t.reshape(-1))
    np.testing.assert_allclose(np.asarray(m.compute()), np.asarray(ref.compute()), atol=1e-6)
