"""Failure-domain engine contract (ISSUE 4 tentpole).

Under deterministic fault injection at every named site (``probe``,
``compile``, ``flush-chunk-<k>``, ``donation``, ``host-offload``; the
``sync-gather`` site is pinned in ``tests/parallel/test_sync_faults.py``),
every degradation-ladder transition preserves state BIT-EXACTLY against the
step-by-step eager oracle (``np.testing.assert_array_equal`` — no tolerance
widening), and the recovery edge is pinned: a transiently-failed owner
returns to the fused path within N clean steps with ``engine_stats`` showing
the demotion AND the re-promotion. Trace-domain declines stay silent and
permanent (the round-5 silent-decline contract).
"""
from __future__ import annotations

import warnings

import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu.ops import engine, faults
from metrics_tpu.utils import checks
from metrics_tpu.utils.exceptions import (
    CompileFault,
    DonationFault,
    RuntimeFault,
    SyncFault,
    TraceFault,
)

RNG = np.random.RandomState(7)
P = jnp.asarray(RNG.rand(32).astype(np.float32))
T = jnp.asarray(RNG.randint(0, 2, 32))
A = jnp.asarray(RNG.rand(24).astype(np.float32))
B = jnp.asarray(RNG.rand(24).astype(np.float32))


@pytest.fixture(autouse=True)
def _fault_mode():
    """Validation "first" (fused paths engage), short recovery threshold so
    the recovery edge is testable in a handful of steps, clean counters."""
    checks.set_validation_mode("first")
    engine.set_deferred_dispatch(True)
    faults.set_recovery_policy(steps=3, max_exponent=6)
    yield
    engine.set_deferred_dispatch(True)
    faults.set_recovery_policy(steps=8, max_exponent=6)
    checks.set_validation_mode("first")


def _mean_oracle(n_updates, x=A):
    """Step-by-step eager oracle: deferral off, fresh instance."""
    engine.set_deferred_dispatch(False)
    try:
        e = mt.MeanMetric()
        for _ in range(n_updates):
            e.update(x)
        return np.asarray(e.compute())
    finally:
        engine.set_deferred_dispatch(True)


def _acc_forward_oracle(n_steps):
    engine.set_deferred_dispatch(False)
    try:
        e = mt.Accuracy()
        vals = [np.asarray(e(P, T)) for _ in range(n_steps)]
        return vals, np.asarray(e.compute())
    finally:
        engine.set_deferred_dispatch(True)


# --------------------------------------------------------------- the machine
class TestLadderStateMachine:
    def test_tiers_and_transitions(self):
        lad = faults.Ladder("update")
        assert lad.tier == "fused" and not lad.demoted
        lad.demote("runtime")
        assert lad.demoted and lad.domain == "runtime" and lad.recoverable
        assert lad.threshold == 3  # fixture policy
        assert not lad.note_clean()  # 1 < 3
        assert not lad.note_clean()
        assert lad.note_clean()  # threshold reached: recovery edge fires
        lad.promote()
        assert not lad.demoted and lad.clean == 0
        # exponential backoff: second failure doubles the threshold
        lad.demote("runtime")
        assert lad.threshold == 6
        assert "promote" in lad.history and lad.history.count("demote:runtime:eager") == 2

    def test_trace_domain_never_recovers(self):
        lad = faults.Ladder("update")
        lad.demote("trace")
        assert lad.demoted and not lad.recoverable
        for _ in range(100):
            assert not lad.note_clean()

    def test_recovery_steps_zero_disables_recovery(self):
        faults.set_recovery_policy(steps=0)
        lad = faults.Ladder("update")
        lad.demote("runtime")
        assert not lad.recoverable
        assert not lad.note_clean()

    def test_classify(self):
        assert faults.classify(RuntimeFault("x")) == "runtime"
        assert faults.classify(TraceFault("x")) == "trace"
        assert faults.classify(DonationFault("x")) == "donation"
        assert faults.classify(SyncFault("x")) == "sync"
        assert faults.classify(ValueError("boom"), default="runtime") == "runtime"
        assert faults.classify(RuntimeError("XLA compilation failure")) == "compile"
        assert faults.classify(RuntimeError("RESOURCE_EXHAUSTED: out of memory")) == "compile"
        assert faults.classify(RuntimeError("buffer has been deleted or donated")) == "donation"
        import jax

        try:
            jax.jit(lambda x: bool(x > 0))(jnp.asarray(1.0))
        except Exception as exc:
            assert faults.classify(exc) == "trace"

    def test_env_hook_parses_plans(self, monkeypatch):
        monkeypatch.setenv("METRICS_TPU_FAULTS", "probe:2,sync-gather:1:sync, ,bad")
        before = {site: list(stack) for site, stack in faults._plans.items()}
        try:
            faults._env_plans()
            assert faults.armed
            assert any(p.remaining == 2 for p in faults._plans["probe"])
            assert any(p.exc_type is SyncFault for p in faults._plans["sync-gather"])
        finally:
            faults._plans.clear()
            faults._plans.update(before)
            faults._rearm()


# ------------------------------------------------------------------- probe site
class TestProbeSite:
    def test_probe_fault_declines_silently_bit_exact(self):
        engine.set_deferred_dispatch(False)
        m = mt.MeanMetric()
        m.update(A)  # first signature: eager, validated
        s0 = engine.engine_stats()
        with warnings.catch_warnings():
            warnings.simplefilter("error")  # any warning fails the test
            with faults.inject_faults("probe") as plan:
                m.update(A)  # probe declines -> silent eager
        assert plan.fired == 1
        assert m._fused_update_ok is False  # trace decline: permanent
        m.update(A)
        # trace declines never re-promote, however many clean steps pass
        for _ in range(10):
            m.update(A)
        assert m._fused_update_ok is False
        np.testing.assert_array_equal(np.asarray(m.compute()), _mean_oracle(13))
        s1 = engine.engine_stats()
        assert s1["fault_trace"] > s0["fault_trace"]


# ----------------------------------------------------------------- compile site
class TestCompileSite:
    def test_compile_fault_demotes_then_recovers(self):
        engine.set_deferred_dispatch(False)
        engine.reset_engine()  # cache miss => the compile site fires
        m = mt.MeanMetric()
        m.update(A)
        with pytest.warns(UserWarning, match="Building the fused update program"):
            with faults.inject_faults("compile") as plan:
                m.update(A)
        assert plan.fired == 1
        assert m._fused_update_ok is False
        # recovery edge: 3 clean eager steps re-arm the fused path
        m.update(A)
        m.update(A)
        m.update(A)
        assert m._fused_update_ok is True
        m.update(A)  # re-probes and runs fused again
        assert isinstance(m._fused_update_program, engine.Executable)
        np.testing.assert_array_equal(np.asarray(m.compute()), _mean_oracle(6))
        stats = engine.engine_stats()
        assert stats["fault_compile"] >= 1
        assert stats["fault_demotions"] >= 1
        assert stats["fault_promotions"] >= 1
        lad = faults.ladder(m, "update")
        assert lad.history[-2:] == ["demote:compile:eager", "promote"]

    def test_forward_compile_fault_bit_exact(self):
        engine.set_deferred_dispatch(False)
        engine.reset_engine()
        m = mt.Accuracy()
        m(P, T)
        with pytest.warns(UserWarning, match="Building the fused forward program"):
            with faults.inject_faults("compile"):
                v1 = m(P, T)
        vals, final = _acc_forward_oracle(4)
        np.testing.assert_array_equal(np.asarray(v1), vals[1])
        m(P, T)
        m(P, T)
        np.testing.assert_array_equal(np.asarray(m.compute()), final)


# ------------------------------------------------------------- flush-chunk site
class TestFlushChunkSite:
    @pytest.mark.parametrize("chunk_index", [0, 1])
    def test_failure_between_applied_chunks_bit_exact(self, chunk_index):
        """A failure while PREPARING chunk k must replay ONLY entries from
        chunk k on (the applied-chunks counter from PR 2, now pinned under
        real injection): 7 queued entries flush as [4, 2, 1] chunks."""
        m = mt.MeanMetric()
        m.update(A)  # eager-validated
        for _ in range(7):
            m.update(A)
        assert m._defer_pending is not None and len(m._defer_pending.entries) == 7
        with pytest.warns(UserWarning, match="Replaying the queue eagerly"):
            with faults.inject_faults(f"flush-chunk-{chunk_index}") as plan:
                value = np.asarray(m.compute())
        assert plan.fired == 1
        assert m._defer_ok is False
        np.testing.assert_array_equal(value, _mean_oracle(8))
        assert m._update_count == 8

    def test_defer_lane_recovers_after_clean_steps(self):
        m = mt.MeanMetric()
        m.update(A)
        for _ in range(3):
            m.update(A)
        with pytest.warns(UserWarning, match="Replaying the queue eagerly"):
            with faults.inject_faults("flush-chunk"):
                _ = m.metric_state
        assert m._defer_ok is False
        m.update(A)
        m.update(A)
        m.update(A)  # three clean per-call steps: recovery edge fires
        assert m._defer_ok is True
        m.update(A)
        m.update(A)
        assert m._defer_pending is not None  # deferral re-engaged
        np.testing.assert_array_equal(np.asarray(m.compute()), _mean_oracle(9))
        stats = engine.engine_stats()
        assert stats["fault_demotions"] >= 1 and stats["fault_promotions"] >= 1

    def test_forward_flush_chunk_fault_resolves_handles(self):
        """Lazy forward handles issued before a failed flush must still
        resolve to the exact eager per-step values."""
        m = mt.Accuracy()
        m(P, T)
        handles = [m(P, T) for _ in range(5)]
        with pytest.warns(UserWarning, match="Replaying the queue eagerly"):
            with faults.inject_faults("flush-chunk-1"):
                got = [np.asarray(h) for h in handles]
        vals, final = _acc_forward_oracle(6)
        for g, v in zip(got, vals[1:]):
            np.testing.assert_array_equal(g, v)
        np.testing.assert_array_equal(np.asarray(m.compute()), final)

    def test_suite_flush_chunk_fault_bit_exact(self):
        """MetricCollection's suite queue: an injected chunk failure replays
        member-wise with every member ending bit-exact vs its oracle."""
        col = mt.MetricCollection([mt.SumMetric(), mt.MeanMetric()])
        col.update(A)
        for _ in range(4):
            col.update(A)
        with pytest.warns(UserWarning, match="Replaying the queue eagerly"):
            with faults.inject_faults("flush-chunk"):
                res = col.compute()
        engine.set_deferred_dispatch(False)
        try:
            oracle = mt.MetricCollection([mt.SumMetric(), mt.MeanMetric()])
            for _ in range(5):
                oracle.update(A)
            expected = oracle.compute()
        finally:
            engine.set_deferred_dispatch(True)
        assert res.keys() == expected.keys()
        for key in res:
            np.testing.assert_array_equal(np.asarray(res[key]), np.asarray(expected[key]))


# ---------------------------------------------------------------- donation site
class TestDonationSite:
    def test_donation_fault_demotes_then_recovers(self):
        engine.set_deferred_dispatch(False)
        m = mt.Accuracy()
        m(P, T)
        m(P, T)  # licensed + fused (program built)
        with pytest.warns(UserWarning, match="Fused forward for `Accuracy`"):
            with faults.inject_faults("donation") as plan:
                v = m(P, T)
        assert plan.fired == 1
        assert m._fused_forward_ok is False
        vals, _ = _acc_forward_oracle(3)
        np.testing.assert_array_equal(np.asarray(v), vals[2])
        # clean eager steps -> recovery edge -> fused path again
        m(P, T)
        m(P, T)
        m(P, T)
        assert m._fused_forward_ok is True
        m(P, T)
        vals7, final7 = _acc_forward_oracle(7)
        np.testing.assert_array_equal(np.asarray(m.compute()), final7)
        stats = engine.engine_stats()
        assert stats["fault_donation"] >= 1
        lad = faults.ladder(m, "forward")
        assert lad.history[-2:] == ["demote:donation:eager", "promote"]

    def test_donation_fault_order_sensitive_state(self):
        """MinMax extrema are order-sensitive: the eager fallback must apply
        the failing step exactly once, in order."""
        engine.set_deferred_dispatch(False)
        xs = [jnp.asarray(RNG.rand(8).astype(np.float32)) for _ in range(6)]
        m = mt.MinMetric()
        m.update(xs[0])
        m.update(xs[1])
        with pytest.warns(UserWarning, match="Fused update for `MinMetric`"):
            with faults.inject_faults("donation"):
                m.update(xs[2])
        for x in xs[3:]:
            m.update(x)
        e = mt.MinMetric()
        for x in xs:
            e.update(x)
        np.testing.assert_array_equal(np.asarray(m.compute()), np.asarray(e.compute()))


# ------------------------------------------------------------ host-offload site
class TestHostOffloadSite:
    def test_offload_fault_keeps_rows_on_device_then_recovers(self):
        rows = jnp.asarray([1.5, 2.5])
        c = mt.CatMetric(compute_on_cpu=True)
        c.update(rows)
        assert isinstance(c.value[0], np.ndarray)  # offloaded to host
        with pytest.warns(UserWarning, match="Host offload .* for `CatMetric`"):
            with faults.inject_faults("host-offload") as plan:
                c.update(rows)
        assert plan.fired == 1
        assert c._host_offload_ok is False
        c.update(rows)  # degraded tier: rows stay on device, update succeeds
        assert not isinstance(c.value[-1], np.ndarray)
        c.update(rows)
        c.update(rows)  # third CLEAN step (the failing call does not count)
        assert c._host_offload_ok is True
        c.update(rows)
        assert isinstance(c.value[-1], np.ndarray)  # offload resumed
        e = mt.CatMetric()
        for _ in range(6):
            e.update(rows)
        np.testing.assert_array_equal(np.asarray(c.compute()), np.asarray(e.compute()))
        assert engine.engine_stats()["fault_host"] >= 1


# ----------------------------------------------------- suite-flush atomicity
class TestSuiteFlushAtomicity:
    def test_failure_mid_suite_replay_never_splits_members(self):
        """Satellite regression: a failure mid-suite-flush must never leave
        one member flushed and another pending — the replay snapshots every
        leader per entry and restores all of them on a member failure."""
        col = mt.MetricCollection([mt.SumMetric(), mt.MeanMetric()])
        col.update(A)  # member-wise eager: validates + derives groups
        col.update(A)  # enqueues into the suite queue
        col.update(A)
        q = col._defer_pending
        assert q is not None and len(q.entries) == 2
        mean = col._modules["MeanMetric"]
        sum_m = col._modules["SumMetric"]
        # read the pre-flush state out of the queue backing: a plain
        # `sum_m.value` read IS an observation and would flush the queue here
        value_before = np.asarray(q.backing[id(sum_m)]["value"])

        calls = {"n": 0}
        orig_update = mean.update

        def poisoned(*a, **k):
            calls["n"] += 1
            raise RuntimeError("poison mid-suite replay")

        # object.__setattr__: a plain setattr would hit the observation
        # barrier and flush the queue before the poison is installed
        object.__setattr__(mean, "update", poisoned)
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                with faults.inject_faults("flush-chunk"):
                    with pytest.raises(RuntimeError, match="poison mid-suite replay"):
                        sum_m.compute()  # observation -> flush -> eager replay
        finally:
            object.__setattr__(mean, "update", orig_update)
        assert calls["n"] == 1
        # BOTH members rolled back to the pre-entry point: neither half-flushed
        assert sum_m._update_count == mean._update_count == 1
        np.testing.assert_array_equal(np.asarray(sum_m.value), value_before)
        np.testing.assert_array_equal(np.asarray(mean.compute()), _mean_oracle(1))
        np.testing.assert_array_equal(np.asarray(sum_m.compute()), value_before)

    def test_forward_replay_failure_never_splits_members(self):
        col = mt.MetricCollection([mt.SumMetric(), mt.MeanMetric()])
        col(A)
        col(A)  # enqueued suite forward
        assert col._defer_pending is not None
        mean = col._modules["MeanMetric"]
        sum_m = col._modules["SumMetric"]

        def poisoned(*a, **k):
            raise RuntimeError("poison forward replay")

        object.__setattr__(mean, "_forward_reduce_state_update_eager", poisoned)
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                with faults.inject_faults("flush-chunk"):
                    with pytest.raises(RuntimeError, match="poison forward replay"):
                        sum_m.compute()
        finally:
            del mean.__dict__["_forward_reduce_state_update_eager"]
        assert sum_m._update_count == mean._update_count == 1
        np.testing.assert_array_equal(np.asarray(sum_m.compute()), np.asarray(A.sum()))


# ----------------------------------------------- telemetry / engine_stats shape
class TestTelemetry:
    def test_engine_stats_exposes_fault_surface(self):
        stats = engine.engine_stats()
        for domain in ("trace", "compile", "runtime", "donation", "host", "sync"):
            assert isinstance(stats[f"fault_{domain}"], int)
        assert isinstance(stats["fault_demotions"], int)
        assert isinstance(stats["fault_promotions"], int)
        assert isinstance(stats["failure_log"], list)

    def test_failure_log_is_bounded(self):
        engine.reset_engine()
        for i in range(200):
            faults.note_fault("runtime", site=f"s{i}")
        log = engine.engine_stats()["failure_log"]
        assert len(log) == 64
        assert log[-1]["site"] == "s199"  # newest last, oldest evicted

    def test_injected_exception_carries_site_and_domain(self):
        with faults.inject_faults("flush-chunk-2") as plan:
            with pytest.raises(RuntimeFault) as ei:
                faults.maybe_fail("flush-chunk", index=2)
        assert plan.fired == 1
        assert ei.value.site == "flush-chunk-2"
        assert ei.value.domain == "runtime"
        # index mismatch does not fire
        with faults.inject_faults("flush-chunk-3"):
            faults.maybe_fail("flush-chunk", index=1)

    def test_exhausted_plan_stops_firing(self):
        with faults.inject_faults("probe", count=1) as plan:
            with pytest.raises(TraceFault):
                faults.maybe_fail("probe")
            faults.maybe_fail("probe")  # budget spent: no-op
        assert plan.fired == 1
        assert not faults.armed
