"""Sync-engine tests: emulated multi-rank host path + SPMD shard_map path.

Analogue of reference tests/unittests/bases/test_ddp.py (drives `_sync_dist`
with injected gathers `:31-48`, uneven shapes `:63-81`, state_dict sync).
"""
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from metrics_tpu.parallel import gather_all_tensors, sync_pytree
from tests.helpers.testers import DummyListMetric, DummyMetric, _FakeGather

def shard_map(f, **kw):
    kw.setdefault('check_vma', False)
    return jax.shard_map(f, **kw)


def test_gather_single_process_identity():
    x = jnp.arange(4.0)
    out = gather_all_tensors(x)
    assert len(out) == 1
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(x))


def test_gather_group_contract():
    """Host-path process groups: iterable of valid process indices; every
    process participates, members' entries are returned (VERDICT #8 pin)."""
    from metrics_tpu.parallel.sync import _resolve_group

    # single-process: the only valid subset is [0], behaving like None
    out = gather_all_tensors(jnp.arange(3.0), group=[0])
    assert len(out) == 1
    np.testing.assert_array_equal(np.asarray(out[0]), np.arange(3.0))

    with pytest.raises(ValueError, match="out of range"):
        gather_all_tensors(jnp.zeros(2), group=[1])
    with pytest.raises(ValueError, match="iterable of process indices"):
        gather_all_tensors(jnp.zeros(2), group=123)
    with pytest.raises(ValueError, match="at least one"):
        gather_all_tensors(jnp.zeros(2), group=[])
    with pytest.raises(ValueError, match="duplicate"):
        _resolve_group([0, 0], 2)
    # members come back sorted ascending regardless of input order
    assert _resolve_group([2, 0], 4) == [0, 2]
    assert _resolve_group(None, 4) is None


def test_metric_accepts_process_group_single_process():
    """A Metric constructed with a host-path process_group syncs fine in
    single-process mode (the kwarg no longer errors at sync time)."""
    import metrics_tpu as mt

    m = mt.SumMetric(process_group=[0])
    m.update(jnp.asarray([1.0, 2.0]))
    assert float(m.compute()) == 3.0
    # one-shot iterables are materialized at construction, not consumed
    gen = mt.SumMetric(process_group=iter([0]))
    assert gen.process_group == [0]
    gen.update(jnp.asarray([1.0]))
    assert float(gen.compute()) == 1.0
    # structural misuse fails fast at construction...
    with pytest.raises(ValueError, match="duplicate"):
        mt.SumMetric(process_group=[0, 0])
    with pytest.raises(ValueError, match="at least one"):
        mt.SumMetric(process_group=[])
    with pytest.raises(ValueError, match="non-negative"):
        mt.SumMetric(process_group=[-1])
    # ...but the range check defers to sync: metrics may be constructed
    # before jax.distributed initializes (reference permits the same)
    mt.SumMetric(process_group=[3])
    # SPMD mesh-axis names pass through untouched
    assert mt.SumMetric(process_group="dp").process_group == "dp"
    mt.SumMetric(process_group=("dp", "tp"))
    # a mesh-axis name reaching the host gather gets the routing error
    with pytest.raises(ValueError, match="mesh-axis name"):
        gather_all_tensors(jnp.zeros(2), group="dp")


def test_injected_sync_sum():
    """Two emulated ranks; sum state reduces across both through Metric.sync."""
    ranks = [DummyMetric() for _ in range(2)]
    ranks[0].update(1.0)
    ranks[1].update(5.0)
    gather = _FakeGather(ranks)
    m = ranks[0]
    m.sync(dist_sync_fn=gather, distributed_available=lambda: True)
    assert float(m.x) == 6.0
    m.unsync()
    assert float(m.x) == 1.0  # local state restored


def test_injected_sync_cat_uneven():
    """Cat states with different lengths per rank concatenate correctly."""
    ranks = [DummyListMetric() for _ in range(2)]
    ranks[0].update(jnp.asarray([1.0, 2.0]))
    ranks[1].update(jnp.asarray([3.0]))
    ranks[1].update(jnp.asarray([4.0, 5.0, 6.0]))
    gather = _FakeGather(ranks)
    m = ranks[0]
    m.sync(dist_sync_fn=gather, distributed_available=lambda: True)
    np.testing.assert_allclose(np.asarray(jnp.concatenate(m.x if isinstance(m.x, list) else [m.x])).ravel(),
                               [1, 2, 3, 4, 5, 6])
    m.unsync()
    assert len(m.x) == 1  # pre-concatenated local state


def test_state_dict_is_synced():
    """state_dict taken inside sync context contains the reduced value."""
    ranks = [DummyMetric() for _ in range(2)]
    ranks[0].persistent(True)
    ranks[1].persistent(True)
    ranks[0].update(2.0)
    ranks[1].update(3.0)
    gather = _FakeGather(ranks)
    m = ranks[0]
    with m.sync_context(dist_sync_fn=gather, distributed_available=lambda: True):
        sd = m.state_dict()
    assert float(np.asarray(sd["x"])) == 5.0
    assert float(m.x) == 2.0  # restored after context


def test_sync_pytree_specs():
    """All reduction specs lower to correct collectives under shard_map."""
    mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
    specs = {"s": "sum", "m": "mean", "mx": "max", "mn": "min", "c": "cat", "n": None}

    def f(x):
        state = {"s": x, "m": x, "mx": x, "mn": x, "c": jnp.atleast_1d(x), "n": jnp.atleast_1d(x)}
        return sync_pytree(state, specs, "dp")

    x = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    out = jax.jit(
        shard_map(f, mesh=mesh, in_specs=P("dp"), out_specs=P())
    )(x)
    assert float(out["s"][0]) == 10.0
    assert float(out["m"][0]) == 2.5
    assert float(out["mx"][0]) == 4.0
    assert float(out["mn"][0]) == 1.0
    np.testing.assert_allclose(np.asarray(out["c"]).ravel(), [1, 2, 3, 4])
    assert out["n"].shape[-2] == 4  # stacked


def test_spmd_metric_as_functions():
    """Full metric lifecycle under shard_map over 8 devices."""
    m = DummyMetric()
    init, upd, cmp = m.as_functions()
    mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))

    def f(x):
        st = init()
        st = upd(st, x[0])
        return cmp(st, axis_name="dp")

    x = jnp.arange(8.0).reshape(8, 1)
    out = jax.jit(shard_map(f, mesh=mesh, in_specs=P("dp"), out_specs=P()))(x)
    assert float(out) == float(x.sum())


def test_compute_sync_on_compute_toggle():
    """sync_on_compute=False must skip sync even when 'distributed'."""
    m = DummyMetric(sync_on_compute=False)
    m.update(1.0)
    # _to_sync is False; compute returns the local value even with a gather that would double it
    assert float(m.compute()) == 1.0


def test_sync_empty_list_state():
    """Regression: syncing a never-updated cat state must not crash (review finding)."""
    ranks = [DummyListMetric() for _ in range(2)]
    gather = _FakeGather(ranks)
    m = ranks[0]
    m.sync(dist_sync_fn=gather, distributed_available=lambda: True)
    assert m.x == []
    m.unsync()
    assert m.x == []
