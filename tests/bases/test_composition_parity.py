"""CompositionalMetric operators vs the mounted reference on identical data."""
from __future__ import annotations

import operator

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
pytestmark = pytest.mark.skipif(_ref is None, reason="reference mount unavailable")

import metrics_tpu as mt  # noqa: E402

RNG = np.random.RandomState(41)
PREDS = RNG.rand(32).astype(np.float32)
TARGET = RNG.rand(32).astype(np.float32)


def _pair():
    ours_a, ours_b = mt.MeanSquaredError(), mt.MeanAbsoluteError()
    ref_a, ref_b = _ref.MeanSquaredError(), _ref.MeanAbsoluteError()
    return (ours_a, ours_b), (ref_a, ref_b)


def _drive(composed_ours, composed_ref, metrics_ours, metrics_ref):
    for m in metrics_ours:
        m.update(jnp.asarray(PREDS), jnp.asarray(TARGET))
    for m in metrics_ref:
        m.update(torch.tensor(PREDS), torch.tensor(TARGET))
    np.testing.assert_allclose(
        float(composed_ours.compute()), float(composed_ref.compute()), atol=1e-5, rtol=1e-5
    )


@pytest.mark.parametrize(
    "op", [operator.add, operator.sub, operator.mul, operator.truediv], ids=["add", "sub", "mul", "div"]
)
def test_metric_op_metric(op):
    (oa, ob), (ra, rb) = _pair()
    _drive(op(oa, ob), op(ra, rb), (oa, ob), (ra, rb))


@pytest.mark.parametrize("scalar", [2.0, -0.5])
@pytest.mark.parametrize("op", [operator.add, operator.mul, operator.pow], ids=["add", "mul", "pow"])
def test_metric_op_scalar(op, scalar):
    if op is operator.pow and scalar < 0:
        pytest.skip("fractional root of positive value only")
    (oa, _), (ra, _) = _pair()
    _drive(op(oa, scalar), op(ra, scalar), (oa,), (ra,))


@pytest.mark.parametrize("op", [abs, operator.neg], ids=["abs", "neg"])
def test_unary(op):
    (oa, _), (ra, _) = _pair()
    _drive(op(oa), op(ra), (oa,), (ra,))


def test_nested_expression():
    (oa, ob), (ra, rb) = _pair()
    ours = abs(oa - ob) * 2.0
    ref = abs(ra - rb) * 2.0
    _drive(ours, ref, (oa, ob), (ra, rb))


def test_comparison_ops():
    (oa, ob), (ra, rb) = _pair()
    ours = oa > ob
    ref = ra > rb
    for m in (oa, ob):
        m.update(jnp.asarray(PREDS), jnp.asarray(TARGET))
    for m in (ra, rb):
        m.update(torch.tensor(PREDS), torch.tensor(TARGET))
    assert bool(np.asarray(ours.compute())) == bool(ref.compute())


def test_forward_through_composition():
    (oa, ob), (ra, rb) = _pair()
    ours = oa + ob
    ref = ra + rb
    ours_val = ours(jnp.asarray(PREDS), jnp.asarray(TARGET))
    ref_val = ref(torch.tensor(PREDS), torch.tensor(TARGET))
    np.testing.assert_allclose(float(ours_val), float(ref_val), atol=1e-5)
    np.testing.assert_allclose(float(ours.compute()), float(ref.compute()), atol=1e-5)


def test_reset_propagates():
    (oa, ob), (ra, rb) = _pair()
    ours = oa + ob
    ref = ra + rb
    for m in (oa, ob):
        m.update(jnp.asarray(PREDS), jnp.asarray(TARGET))
    for m in (ra, rb):
        m.update(torch.tensor(PREDS), torch.tensor(TARGET))
    ours.reset()
    ref.reset()
    oa.update(jnp.asarray(PREDS), jnp.asarray(TARGET))
    ob.update(jnp.asarray(PREDS), jnp.asarray(TARGET))
    ra.update(torch.tensor(PREDS), torch.tensor(TARGET))
    rb.update(torch.tensor(PREDS), torch.tensor(TARGET))
    np.testing.assert_allclose(float(ours.compute()), float(ref.compute()), atol=1e-5)
