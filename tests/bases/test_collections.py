"""MetricCollection tests incl. compute groups (analogue of reference tests/unittests/bases/test_collections.py)."""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import Accuracy, F1Score, MaxMetric, MeanMetric, MetricCollection, MinMetric, SumMetric
from tests.helpers.testers import DummyMetric


class DummyA(DummyMetric):
    pass


class DummyB(DummyMetric):
    def compute(self):
        return self.x * 2


def test_list_input_keys_by_class_name():
    col = MetricCollection([DummyA(), DummyB()])
    assert set(col.keys()) == {"DummyA", "DummyB"}


def test_duplicate_class_names_raise():
    with pytest.raises(ValueError, match="two metrics both named"):
        MetricCollection([DummyA(), DummyA()])


def test_dict_input_sorted():
    col = MetricCollection({"b": DummyA(), "a": DummyB()})
    assert list(col.keys(keep_base=True)) == ["a", "b"]


def test_invalid_input_raises():
    with pytest.raises(ValueError):
        MetricCollection([DummyA(), "not a metric"])
    with pytest.raises(ValueError):
        MetricCollection({"a": "not a metric"})


def test_prefix_postfix():
    col = MetricCollection([DummyA()], prefix="pre_", postfix="_post")
    col.update(1.0)
    out = col.compute()
    assert set(out) == {"pre_DummyA_post"}
    with pytest.raises(ValueError, match="Expected input `prefix`"):
        MetricCollection([DummyA()], prefix=5)


def test_clone_with_new_prefix():
    col = MetricCollection([DummyA()], prefix="a_")
    c2 = col.clone(prefix="b_")
    col.update(1.0)
    c2.update(2.0)
    assert set(col.compute()) == {"a_DummyA"}
    assert set(c2.compute()) == {"b_DummyA"}
    assert float(list(c2.compute().values())[0]) == 2.0


def test_update_and_compute_fan_out():
    col = MetricCollection([DummyA(), DummyB()])
    col.update(2.0)
    out = col.compute()
    assert float(out["DummyA"]) == 2.0
    assert float(out["DummyB"]) == 4.0


def test_forward_returns_dict():
    col = MetricCollection([DummyA(), DummyB()])
    out = col(3.0)
    assert float(out["DummyA"]) == 3.0
    assert float(out["DummyB"]) == 6.0


def test_compute_groups_merge_identical_states():
    """DummyA and DummyB share identical state -> one compute group after first update."""
    col = MetricCollection([DummyA(), DummyB()])
    col.update(1.0)
    assert len(col.compute_groups) == 1
    # second update only touches the leader but results stay correct
    col.update(2.0)
    out = col.compute()
    assert float(out["DummyA"]) == 3.0
    assert float(out["DummyB"]) == 6.0


def test_compute_groups_distinct_states_stay_separate():
    col = MetricCollection([SumMetric(), MaxMetric()])
    col.update(jnp.asarray([1.0, 4.0]))
    assert len(col.compute_groups) == 2
    col.update(jnp.asarray([2.0]))
    out = col.compute()
    assert float(out["SumMetric"]) == 7.0
    assert float(out["MaxMetric"]) == 4.0


def test_compute_groups_disabled():
    col = MetricCollection([DummyA(), DummyB()], compute_groups=False)
    col.update(1.0)
    assert col.compute_groups == {}
    col.update(2.0)
    out = col.compute()
    assert float(out["DummyA"]) == 3.0


def test_compute_groups_user_specified():
    col = MetricCollection([DummyA(), DummyB()], compute_groups=[["DummyA", "DummyB"]])
    col.update(1.0)
    col.update(1.0)
    out = col.compute()
    assert float(out["DummyA"]) == 2.0
    assert float(out["DummyB"]) == 4.0
    with pytest.raises(ValueError, match="does not match a metric"):
        MetricCollection([DummyA()], compute_groups=[["Nope"]])


def test_reset_restores_group_refs():
    col = MetricCollection([DummyA(), DummyB()])
    col.update(1.0)
    col.reset()
    col.update(5.0)
    out = col.compute()
    assert float(out["DummyA"]) == 5.0
    assert float(out["DummyB"]) == 10.0


def test_getitem_gives_safe_copy_state():
    col = MetricCollection([DummyA(), DummyB()])
    col.update(1.0)
    a = col["DummyA"]
    assert float(a.compute()) == 1.0


def test_nested_collection_flattens():
    inner = MetricCollection([DummyA()], prefix="in_")
    col = MetricCollection({"outer": inner})
    col.update(1.0)
    out = col.compute()
    assert set(out) == {"outer_in_DummyA"}


def test_add_metrics_after_init():
    col = MetricCollection([DummyA()])
    col.add_metrics(DummyB())
    col.update(1.0)
    assert set(col.compute()) == {"DummyA", "DummyB"}


def test_len_iter_contains():
    col = MetricCollection([DummyA(), DummyB()])
    assert len(col) == 2
    assert "DummyA" in col
    assert set(iter(col)) == {"DummyA", "DummyB"}


def test_collection_state_dict_roundtrip():
    col = MetricCollection([SumMetric(), MeanMetric()])
    col.persistent(True)
    col.update(jnp.asarray([1.0, 2.0]))
    sd = col.state_dict()
    col2 = MetricCollection([SumMetric(), MeanMetric()])
    col2.persistent(True)
    col2.load_state_dict(sd)
    for m in col2.values(copy_state=False):
        m._update_count = 1  # state came from the checkpoint, not update()
    out = col2.compute()
    assert float(out["SumMetric"]) == 3.0
    assert float(out["MeanMetric"]) == 1.5


def test_compute_group_member_cache_invalidated():
    """Regression: member's _computed cache must clear when only leader updates."""
    col = MetricCollection([DummyA(), DummyB()])
    col.update(1.0)
    out1 = col.compute()
    assert float(out1["DummyA"]) == 1.0 and float(out1["DummyB"]) == 2.0
    col.update(2.0)  # only leader updates now
    out2 = col.compute()
    assert float(out2["DummyA"]) == 3.0
    assert float(out2["DummyB"]) == 6.0  # was returning stale 2.0 before fix


class TestCollectionAsFunctions:
    def test_fused_update_matches_stateful(self):
        import jax

        coll = MetricCollection(
            {"acc": Accuracy(num_classes=3), "f1": F1Score(num_classes=3, average="macro")}
        )
        init, update, compute = coll.as_functions()
        rng = np.random.RandomState(0)
        states = init()
        fused = jax.jit(update)
        for _ in range(3):
            p = jnp.asarray(rng.rand(16, 3).astype(np.float32))
            t = jnp.asarray(rng.randint(0, 3, 16))
            states = fused(states, p, t)
            coll.update(p, t)
        out_fn = compute(states)
        out_st = coll.compute()
        assert set(out_fn) == set(out_st)
        for k in out_fn:
            np.testing.assert_allclose(np.asarray(out_fn[k]), np.asarray(out_st[k]), atol=1e-6)

    def test_spmd_collection_compute(self):
        import jax
        from jax.sharding import Mesh, PartitionSpec as P

        coll = MetricCollection({"acc": Accuracy(num_classes=3), "mean": MeanMetric()})
        init, update, compute = coll.as_functions()
        mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))
        rng = np.random.RandomState(1)
        p = jnp.asarray(rng.rand(32, 3).astype(np.float32))
        t = jnp.asarray(rng.randint(0, 3, 32))

        def shard_fn(pb, tb):
            # kwargs route per update signature (positional args would go to all)
            states = update(init(), preds=pb, target=tb, value=pb.mean())
            return compute(states, axis_name="dp")

        out = jax.jit(
            jax.shard_map(shard_fn, mesh=mesh, in_specs=(P("dp", None), P("dp")), out_specs=P(), check_vma=False)
        )(p, t)
        # whole-data truth
        coll2 = MetricCollection({"acc": Accuracy(num_classes=3), "mean": MeanMetric()})
        coll2["acc"].update(p, t)
        coll2["mean"].update(jnp.stack([p[:16].mean(), p[16:].mean()]))
        np.testing.assert_allclose(float(out["acc"]), float(coll2.compute()["acc"]), atol=1e-6)
        np.testing.assert_allclose(float(out["mean"]), float(coll2.compute()["mean"]), atol=1e-6)
