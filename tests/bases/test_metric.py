"""Base-class lifecycle tests (analogue of reference tests/unittests/bases/test_metric.py)."""
import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import Metric
from metrics_tpu.utils.exceptions import MetricsUserError
from tests.helpers.testers import DummyListMetric, DummyMetric


def test_inherit_instantiation_error():
    class Incomplete(Metric):
        pass

    with pytest.raises(TypeError):
        Incomplete()


def test_add_state_kinds():
    m = DummyMetric()
    m.add_state("a", jnp.asarray(0.0), "sum")
    m.add_state("b", [], "cat")
    with pytest.raises(ValueError):
        m.add_state("c", [jnp.asarray(1.0)], "cat")  # non-empty list default
    with pytest.raises(ValueError):
        m.add_state("d", jnp.asarray(0.0), "invalid")
    with pytest.raises(ValueError):
        m.add_state("not an identifier!", jnp.asarray(0.0), "sum")
    # callables are allowed
    m.add_state("e", jnp.asarray(0.0), lambda x: jnp.sum(x, axis=0))


def test_update_and_reset():
    m = DummyMetric()
    assert not m.update_called
    m.update(1.0)
    assert m.update_called
    assert m._update_count == 1
    assert float(m.x) == 1.0
    m.update(2.0)
    assert float(m.x) == 3.0
    m.reset()
    assert not m.update_called
    assert float(m.x) == 0.0


def test_reset_list_state():
    m = DummyListMetric()
    m.update(1.0)
    assert len(m.x) == 1
    m.reset()
    assert m.x == []
    # reset must not alias the default list
    m.update(2.0)
    m2 = DummyListMetric()
    assert m2.x == []


def test_compute_caching():
    m = DummyMetric()
    m.update(1.0)
    v1 = m.compute()
    assert m._computed is not None
    m.update(1.0)
    assert m._computed is None  # update invalidates cache
    assert float(m.compute()) == 2.0
    assert float(v1) == 1.0


def test_compute_before_update_warns():
    m = DummyMetric()
    with pytest.warns(UserWarning, match="called before"):
        m.compute()


def test_forward_returns_batch_value():
    m = DummyMetric()
    out = m(2.0)
    assert float(out) == 2.0
    out = m(3.0)
    assert float(out) == 3.0  # batch-local, not accumulated
    assert float(m.compute()) == 5.0  # accumulated


def test_forward_full_vs_reduce_state_paths():
    class FullState(DummyMetric):
        full_state_update = True

    class ReduceState(DummyMetric):
        full_state_update = False

    for cls in (FullState, ReduceState):
        m = cls()
        assert float(m(1.0)) == 1.0
        assert float(m(2.0)) == 2.0
        assert float(m.compute()) == 3.0


def test_forward_mean_merge():
    """The 'mean' reduce spec merges via running average weighted by update count."""

    class MeanState(Metric):
        full_state_update = False

        def __init__(self):
            super().__init__()
            self.add_state("m", jnp.asarray(0.0), "mean")

        def update(self, x):
            self.m = jnp.asarray(x, dtype=jnp.float32)

        def compute(self):
            return self.m

    m = MeanState()
    m(1.0)
    m(3.0)
    assert float(m.compute()) == pytest.approx(2.0)


def test_const_attributes_frozen():
    m = DummyMetric()
    with pytest.raises(RuntimeError, match="Can't change const"):
        m.is_differentiable = True
    with pytest.raises(RuntimeError, match="Can't change const"):
        m.higher_is_better = False
    with pytest.raises(RuntimeError, match="Can't change const"):
        m.full_state_update = True


def test_hash_and_pickle():
    m = DummyMetric()
    m.update(5.0)
    assert isinstance(hash(m), int)
    m2 = pickle.loads(pickle.dumps(m))
    assert float(m2.x) == 5.0
    m2.update(1.0)
    assert float(m2.x) == 6.0
    assert float(m.x) == 5.0  # original untouched


def test_clone_independent():
    m = DummyMetric()
    m.update(1.0)
    c = m.clone()
    c.update(10.0)
    assert float(m.x) == 1.0
    assert float(c.x) == 11.0


def test_state_dict_persistent_flag():
    m = DummyMetric()
    assert m.state_dict() == {}
    m.persistent(True)
    m.update(4.0)
    sd = m.state_dict()
    assert set(sd) == {"x"}
    assert np.asarray(sd["x"]) == pytest.approx(4.0)

    m2 = DummyMetric()
    m2.persistent(True)
    m2.load_state_dict(sd)
    assert float(m2.x) == 4.0

    m3 = DummyMetric()
    m3.persistent(True)
    with pytest.raises(KeyError):
        m3.load_state_dict({}, strict=True)


def test_metric_state_property():
    m = DummyMetric()
    m.update(2.0)
    assert set(m.metric_state) == {"x"}
    assert float(m.metric_state["x"]) == 2.0


def test_double_sync_raises():
    m = DummyMetric()
    m.update(1.0)
    m.sync(dist_sync_fn=lambda x, group=None: [x], distributed_available=lambda: True)
    with pytest.raises(MetricsUserError, match="already been synced"):
        m.sync(dist_sync_fn=lambda x, group=None: [x], distributed_available=lambda: True)
    m.unsync()
    with pytest.raises(MetricsUserError, match="un-synced"):
        m.unsync()


def test_forward_while_synced_raises():
    m = DummyMetric()
    m.update(1.0)
    m.sync(dist_sync_fn=lambda x, group=None: [x], distributed_available=lambda: True)
    with pytest.raises(MetricsUserError, match="shouldn't be synced"):
        m(1.0)


def test_filter_kwargs():
    class TwoArg(Metric):
        full_state_update = False

        def __init__(self):
            super().__init__()
            self.add_state("x", jnp.asarray(0.0), "sum")

        def update(self, preds, target):
            self.x = self.x + jnp.sum(preds) + jnp.sum(target)

        def compute(self):
            return self.x

    m = TwoArg()
    filtered = m._filter_kwargs(preds=1, target=2, extra=3)
    assert set(filtered) == {"preds", "target"}


def test_astype_casts_float_states_only():
    class Mixed(Metric):
        full_state_update = False

        def __init__(self):
            super().__init__()
            self.add_state("f", jnp.asarray(0.0), "sum")
            self.add_state("i", jnp.asarray(0, dtype=jnp.int32), "sum")

        def update(self, x):
            pass

        def compute(self):
            return self.f

    m = Mixed().astype(jnp.bfloat16)
    assert m.f.dtype == jnp.bfloat16
    assert m.i.dtype == jnp.int32


def test_functional_export_jit_scan():
    """as_functions kernels work under jit and lax.scan (trace-safety)."""
    m = DummyMetric()
    init, upd, cmp = m.as_functions()
    state = init()

    def body(st, x):
        return upd(st, x), None

    final, _ = jax.lax.scan(body, state, jnp.arange(5.0))
    assert float(cmp(final)) == pytest.approx(10.0)


def test_unexpected_kwargs_raise():
    with pytest.raises(ValueError, match="Unexpected keyword"):
        DummyMetric(not_a_real_kwarg=True)


def test_is_overridden():
    from metrics_tpu.metric import Metric
    from metrics_tpu.utils.checks import is_overridden

    class Sub(Metric):
        full_state_update = False

        def update(self):
            pass

        def compute(self):
            return 0

    assert is_overridden("update", Sub(), Metric)
    assert not is_overridden("reset", Sub(), Metric)
    assert not is_overridden("missing_method", Sub(), Metric)


def test_compare_version():
    import operator

    from metrics_tpu.utils.imports import compare_version

    assert compare_version("numpy", operator.ge, "1.0")
    assert not compare_version("numpy", operator.lt, "1.0")
    assert not compare_version("definitely_not_a_package", operator.ge, "1.0")


def test_validation_modes():
    import jax.numpy as jnp
    import pytest

    import metrics_tpu as mt
    from metrics_tpu.utils.checks import set_validation_mode

    bad_preds, bad_target = jnp.asarray([-1, 0, 1]), jnp.asarray([-1, 0, 1])
    good_p, good_t = jnp.asarray([0.2, 0.8, 0.5]), jnp.asarray([0, 1, 1])
    try:
        set_validation_mode("first")
        # first update with a signature: misuse raises
        m = mt.Accuracy(num_classes=3)
        with pytest.raises(ValueError, match="non-negative"):
            m.update(bad_preds, bad_target)
        # SAME INSTANCE, same signature again: value checks skipped (no raise)
        m.update(bad_preds, bad_target)
        # a FRESH instance re-validates — signature memory is per metric, so a
        # new metric always gets reference-grade first-update protection
        with pytest.raises(ValueError, match="non-negative"):
            mt.Accuracy(num_classes=3).update(bad_preds, bad_target)
        # shape checks still always run
        with pytest.raises(ValueError):
            mt.Accuracy(num_classes=3).update(jnp.zeros((2, 3)), jnp.zeros((5,), jnp.int32))

        set_validation_mode("off")
        mt.Accuracy(num_classes=3).update(bad_preds, bad_target)  # no raise

        set_validation_mode("first")
        with pytest.raises(ValueError, match="non-negative"):
            mt.Accuracy(num_classes=3).update(bad_preds, bad_target)
        acc = mt.Accuracy()
        acc.update(good_p, good_t)  # normal path still works
        assert float(acc.compute()) >= 0
        with pytest.raises(ValueError):
            set_validation_mode("bogus")
    finally:
        set_validation_mode("first")


def test_validation_first_mode_key_includes_config():
    """A permissive config (ignore_index) must not mark the signature safe for
    a strict config (review regression)."""
    import jax.numpy as jnp
    import pytest

    import metrics_tpu as mt
    from metrics_tpu.utils.checks import set_validation_mode

    try:
        set_validation_mode("first")
        neg = jnp.asarray([-1, 0, 1])
        m_ok = mt.Accuracy(num_classes=2, ignore_index=-1, multiclass=True)
        m_ok.update(jnp.asarray([0, 0, 1]), neg)  # legitimately passes
        with pytest.raises(ValueError, match="non-negative"):
            mt.Accuracy(num_classes=2, multiclass=True).update(jnp.asarray([0, 0, 1]), neg)
    finally:
        set_validation_mode("first")


def test_validation_first_mode_traced_does_not_consume_signature():
    """A jitted update never value-checks; the NEXT eager update with the same
    shapes must still be validated (review regression)."""
    import jax
    import jax.numpy as jnp
    import pytest

    import metrics_tpu as mt
    from metrics_tpu.utils.checks import set_validation_mode

    try:
        set_validation_mode("first")
        init, upd, _ = mt.Accuracy(num_classes=3).as_functions()
        good = jnp.asarray([1, 0, 2])
        jax.jit(upd)(init(), good, good)  # traced: no value checks run
        bad = jnp.asarray([-1, 0, 1])
        with pytest.raises(ValueError, match="non-negative"):
            mt.Accuracy(num_classes=3).update(jnp.asarray([1, 0, 2]), bad)
    finally:
        set_validation_mode("first")


def test_compute_on_cpu_offloads_list_states():
    """compute_on_cpu moves cat-state chunks to host numpy after each update
    (HBM relief for feature banks) without changing any computed value."""
    import metrics_tpu as mt

    rng = np.random.RandomState(0)
    preds = rng.rand(64).astype(np.float32)
    target = (rng.rand(64) > 0.5).astype(np.int32)

    offloaded = mt.AveragePrecision(compute_on_cpu=True)
    regular = mt.AveragePrecision()
    for sl in (slice(0, 32), slice(32, 64)):
        offloaded.update(jnp.asarray(preds[sl]), jnp.asarray(target[sl]))
        regular.update(jnp.asarray(preds[sl]), jnp.asarray(target[sl]))

    assert all(isinstance(v, np.ndarray) for v in offloaded.preds)  # host-resident
    assert all(isinstance(v, jax.Array) for v in regular.preds)  # device-resident
    np.testing.assert_allclose(float(offloaded.compute()), float(regular.compute()), atol=1e-6)

    offloaded.reset()
    assert offloaded.preds == []


def test_validation_first_mode_signature_memory_is_bounded():
    """'first' mode under perpetual shape churn must not grow its signature
    memory without bound (advisor regression): the FIFO cap evicts old
    signatures, which then simply get value-checked again."""
    import jax.numpy as jnp

    from metrics_tpu.utils import checks
    from metrics_tpu.utils.checks import set_validation_mode

    try:
        set_validation_mode("first")
        for n in range(1, 40):
            checks._should_value_check(jnp.zeros((n,)), jnp.zeros((n,), jnp.int32))
        assert len(checks._seen_check_keys) <= checks._SEEN_KEYS_CAP
        cap, checks._SEEN_KEYS_CAP = checks._SEEN_KEYS_CAP, 16
        try:
            import warnings

            with warnings.catch_warnings():
                # churn past the lowered cap fires the one-shot eviction
                # warning (pinned in test_validation_gating.py)
                warnings.simplefilter("ignore", UserWarning)
                for n in range(40, 80):
                    checks._should_value_check(jnp.zeros((n,)), jnp.zeros((n,), jnp.int32))
            assert len(checks._seen_check_keys) <= 16
            # evicted signature checks again instead of being silently skipped
            assert checks._should_value_check(jnp.zeros((1,)), jnp.zeros((1,), jnp.int32))
        finally:
            checks._SEEN_KEYS_CAP = cap
    finally:
        set_validation_mode("first")


def test_value_stats_mixed_traced_concrete():
    """Concrete target + traced preds must not crash the fused stats fetch
    (advisor regression): each concrete side is read on its own."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu.utils.checks import _ValueStats

    target = jnp.asarray([0, 1, 2], jnp.int32)

    seen = {}

    def traced_preds_fn(preds):
        stats = _ValueStats(preds, target, force=True)
        seen["tmin"] = stats.target_min
        seen["tmax"] = stats.target_max
        return preds.sum()

    jax.jit(traced_preds_fn)(jnp.asarray([0.1, 0.5, 0.9]))
    assert seen["tmin"] == 0.0 and seen["tmax"] == 2.0
