"""Aggregation metric tests (analogue of reference tests/unittests/bases/test_aggregation.py)."""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import CatMetric, MaxMetric, MeanMetric, MinMetric, SumMetric


@pytest.mark.parametrize(
    "metric_cls, values, expected",
    [
        (SumMetric, [[1.0, 2.0], [3.0]], 6.0),
        (MaxMetric, [[1.0, 5.0], [3.0]], 5.0),
        (MinMetric, [[2.0, 5.0], [3.0]], 2.0),
        (MeanMetric, [[1.0, 2.0], [3.0, 6.0]], 3.0),
    ],
)
def test_aggregators(metric_cls, values, expected):
    m = metric_cls()
    for v in values:
        m.update(jnp.asarray(v))
    assert float(m.compute()) == pytest.approx(expected)


def test_cat_metric():
    m = CatMetric()
    m.update(jnp.asarray([1.0, 2.0]))
    m.update(3.0)
    np.testing.assert_allclose(np.asarray(m.compute()), [1.0, 2.0, 3.0])


def test_mean_metric_weighted():
    m = MeanMetric()
    m.update(jnp.asarray([1.0, 3.0]), weight=jnp.asarray([1.0, 3.0]))
    assert float(m.compute()) == pytest.approx((1 * 1 + 3 * 3) / 4)


def test_nan_strategy_error():
    m = SumMetric(nan_strategy="error")
    with pytest.raises(RuntimeError, match="nan"):
        m.update(jnp.asarray([1.0, float("nan")]))


def test_nan_strategy_warn():
    m = SumMetric(nan_strategy="warn")
    with pytest.warns(UserWarning):
        m.update(jnp.asarray([1.0, float("nan"), 2.0]))
    assert float(m.compute()) == pytest.approx(3.0)


def test_nan_strategy_ignore():
    m = SumMetric(nan_strategy="ignore")
    m.update(jnp.asarray([1.0, float("nan"), 2.0]))
    assert float(m.compute()) == pytest.approx(3.0)


def test_nan_strategy_impute():
    m = MeanMetric(nan_strategy=0.0)
    m.update(jnp.asarray([2.0, float("nan"), 4.0]))
    assert float(m.compute()) == pytest.approx(2.0)


def test_invalid_nan_strategy():
    with pytest.raises(ValueError, match="nan_strategy"):
        SumMetric(nan_strategy="bogus")


def test_aggregator_forward():
    m = MeanMetric()
    batch_val = m(jnp.asarray([2.0, 4.0]))
    assert float(batch_val) == pytest.approx(3.0)
    m(jnp.asarray([6.0]))
    assert float(m.compute()) == pytest.approx(4.0)


def test_nan_ignore_does_not_corrupt_max_min():
    """Regression: 'ignore' must drop NaNs, not zero-substitute (review finding)."""
    m = MaxMetric(nan_strategy="ignore")
    m.update(jnp.asarray([-5.0, float("nan")]))
    assert float(m.compute()) == -5.0
    m2 = MinMetric(nan_strategy="ignore")
    m2.update(jnp.asarray([5.0, float("nan")]))
    assert float(m2.compute()) == 5.0
    m3 = CatMetric(nan_strategy="ignore")
    m3.update(jnp.asarray([1.0, float("nan"), 2.0]))
    np.testing.assert_allclose(np.asarray(m3.compute()), [1.0, 2.0])


def test_nan_weight_checked():
    """Regression: NaN in weight must trigger the strategy too (review finding)."""
    m = MeanMetric(nan_strategy="error")
    with pytest.raises(RuntimeError, match="nan"):
        m.update(jnp.asarray([1.0]), weight=jnp.asarray([float("nan")]))
    m2 = MeanMetric(nan_strategy="ignore")
    m2.update(jnp.asarray([1.0, 3.0]), weight=jnp.asarray([1.0, float("nan")]))
    assert float(m2.compute()) == pytest.approx(1.0)
