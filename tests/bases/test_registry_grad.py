"""EVERY is_differentiable metric yields finite gradients under jax.grad.

The reference runs ``torch.autograd.gradcheck`` per metric
(`tests/unittests/helpers/testers.py:536-570`); the JAX analogue
differentiates the pure ``as_functions`` chain — grad of
``compute(update(init(), preds, target))`` with respect to ``preds`` — over
every exported metric that declares ``is_differentiable=True``, on the same
registry SPEC inputs as the other contracts. Also pins the flag itself: a
metric NOT in SPEC or without float preds is listed explicitly so a newly
exported differentiable metric fails CI until it declares coverage.
"""
from __future__ import annotations

import inspect

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from tests.bases.test_registry_distributed import SPEC
from tests.bases.test_registry_precision import _is_float_array, _split

# differentiable exports with no SPEC float-preds path, each with the reason;
# must be DISJOINT from SPEC (asserted below) so stale entries can't mask a
# lost SPEC row
EXEMPT = {
    "LearnedPerceptualImagePatchSimilarity": "model-backed: needs real weights (golden-tested in tests/models)",
}


def _differentiable_names():
    names = []
    for name in mt.__all__:
        obj = getattr(mt, name, None)
        if inspect.isclass(obj) and getattr(obj, "is_differentiable", None) is True:
            names.append(name)
    return names


def _scalarize(value):
    leaves = [v for v in jax.tree_util.tree_leaves(value) if hasattr(v, "dtype")]
    return sum(jnp.sum(leaf) for leaf in leaves if jnp.issubdtype(leaf.dtype, jnp.floating))


@pytest.mark.parametrize("name", sorted(set(_differentiable_names()) & set(SPEC)))
def test_grad_finite(name):
    factory, batches, _ = SPEC[name]
    args, kwargs = _split(batches[0])
    assert _is_float_array(args[0]), (
        f"{name} is is_differentiable=True but its SPEC preds are not a float "
        "array — give it a float-preds SPEC row or an EXEMPT entry with a reason"
    )
    metric = factory()
    init, update, compute = metric.as_functions()
    rest = args[1:]

    def loss(preds):
        return _scalarize(compute(update(init(), preds, *rest, **kwargs)))

    grad = jax.grad(loss)(args[0])
    assert grad.shape == args[0].shape
    assert bool(jnp.all(jnp.isfinite(grad))), f"non-finite gradient for {name}"


def test_flag_coverage_is_exhaustive():
    """Every is_differentiable export is either grad-tested here or exempted
    with a reason — new differentiable exports must declare themselves."""
    assert not (set(EXEMPT) & set(SPEC)), "EXEMPT entries must not shadow live SPEC rows"
    uncovered = set(_differentiable_names()) - set(SPEC) - set(EXEMPT)
    assert not uncovered, f"differentiable exports with no grad contract: {sorted(uncovered)}"


def test_grad_through_jit():
    """Differentiation composes with jit: value-and-grad of a jitted fused
    update+compute chain (the training-loop shape for a differentiable
    metric regularizer)."""
    metric = mt.MeanSquaredError()
    init, update, compute = metric.as_functions()
    preds = jnp.asarray(np.random.RandomState(0).randn(32).astype(np.float32))
    target = jnp.zeros(32)

    @jax.jit
    def loss(p):
        return compute(update(init(), p, target))

    val, grad = jax.value_and_grad(loss)(preds)
    np.testing.assert_allclose(np.asarray(grad), np.asarray(2 * preds / 32), atol=1e-6)
