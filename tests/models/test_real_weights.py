"""Real-pretrained-weight numeric parity (gated: ``METRICS_TPU_REAL_WEIGHTS``).

The offline CI tier pins converter LAYOUTS against vendored manifests
(`test_checkpoint_layouts.py`) and weight-sharing NUMERICS against torch
mirrors on synthetic weights (`test_inception_parity.py`,
`test_lpips_parity.py`). What it cannot do without egress is run a REAL
published checkpoint end to end. This module closes that gap the moment one
exists: point ``METRICS_TPU_REAL_WEIGHTS`` at a directory holding any of

    inception.npz / *inception*.pth   (torch-fidelity FID weights,
                                       reference `image/fid.py:41-58`)
    lpips_<net>.npz / lpips_<net>.pth (``lpips.LPIPS(net=...)`` state dict,
                                       reference `image/lpip.py:24-77`)
    bert/ (an HF model dir)           (reference `text/bert.py:171-205`)

(``make convert-weights WEIGHTS=<dir>`` performs the .pth -> .npz step) and
each present artifact is loaded through the production converters, run on
fixed synthetic inputs, and asserted against the reference computation path
executing THE SAME real weights (torch mirror for vision; the mounted
reference package for BERTScore). If the directory carries an
``expected.json`` (written by a previous run with
``METRICS_TPU_REAL_WEIGHTS_RECORD=1``), values are additionally pinned
against those recorded outputs, catching cross-machine drift.

Without the env var every test here SKIPS — cleanly, by design: this
environment has no egress to fetch the artifacts.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

_DIR = os.environ.get("METRICS_TPU_REAL_WEIGHTS")
pytestmark = [
    pytest.mark.skipif(not _DIR, reason="METRICS_TPU_REAL_WEIGHTS not set (no real checkpoints offline)"),
    pytest.mark.slow,
]

_REPO = Path(__file__).resolve().parents[2]


def _ensure_converted() -> Path:
    root = Path(_DIR)
    subprocess.run(
        [sys.executable, str(_REPO / "tools" / "convert_real_weights.py"), str(root)],
        check=True,
    )
    return root


def _maybe_check_recorded(key: str, value) -> None:
    root = Path(_DIR)
    expected_path = root / "expected.json"
    expected = json.loads(expected_path.read_text()) if expected_path.exists() else {}
    if os.environ.get("METRICS_TPU_REAL_WEIGHTS_RECORD") == "1":
        expected[key] = value
        expected_path.write_text(json.dumps(expected, indent=2, sort_keys=True))
    elif key in expected:
        np.testing.assert_allclose(
            np.asarray(value, np.float64), np.asarray(expected[key], np.float64), rtol=1e-4,
            err_msg=f"{key} drifted from the recorded real-weights output",
        )


def _images(n=8, size=299, seed=3):
    return np.random.RandomState(seed).randint(0, 256, size=(n, 3, size, size), dtype=np.uint8)


def test_fid_real_inception_matches_torch_path():
    torch = pytest.importorskip("torch")
    root = _ensure_converted()
    npz = root / "inception.npz"
    if not npz.exists():
        pytest.skip("no inception checkpoint in METRICS_TPU_REAL_WEIGHTS")
    pth = next(iter(sorted(root.glob("*inception*.pth"))), None)
    if pth is None:
        pytest.skip("need the source .pth too (torch-side oracle loads it)")

    import jax.numpy as jnp

    import metrics_tpu as mt
    from tests.helpers.torch_mirrors import TorchInceptionMirror

    real, fake = _images(seed=3), _images(seed=4)
    ours = mt.image.FrechetInceptionDistance(feature=2048, npz_path=str(npz))
    ours.update(jnp.asarray(real), real=True)
    ours.update(jnp.asarray(fake), real=False)
    our_fid = float(ours.compute())

    # the torch mirror IS the published architecture: the real state dict
    # must load strict, and its features drive the reference FID formula
    mirror = TorchInceptionMirror()
    mirror.load_state_dict(torch.load(pth, map_location="cpu"), strict=True)
    mirror.eval()

    def feats(imgs):
        x = torch.from_numpy(imgs).float() / 255.0 * 2.0 - 1.0
        with torch.no_grad():
            return mirror(x)["2048"].numpy().astype(np.float64)

    from tests.helpers.reference_oracle import get_reference

    ref = get_reference()
    if ref is not None:
        import torch.nn as nn

        class _Feat(nn.Module):
            def forward(self, x):
                x = x.float() / 255.0 * 2.0 - 1.0
                return mirror(x)["2048"]

        rfid = ref.image.fid.FrechetInceptionDistance(feature=_Feat())
        rfid.update(torch.from_numpy(real), real=True)
        rfid.update(torch.from_numpy(fake), real=False)
        torch_fid = float(rfid.compute())
    else:  # reference mount unavailable: use the closed-form FID on features
        from scipy import linalg

        f1, f2 = feats(real), feats(fake)
        mu1, mu2 = f1.mean(0), f2.mean(0)
        c1, c2 = np.cov(f1, rowvar=False), np.cov(f2, rowvar=False)
        covmean = linalg.sqrtm(c1 @ c2).real
        torch_fid = float(((mu1 - mu2) ** 2).sum() + np.trace(c1 + c2 - 2 * covmean))

    np.testing.assert_allclose(our_fid, torch_fid, rtol=1e-3, atol=1e-2)
    _maybe_check_recorded("fid_2048_seed3v4_8img", our_fid)


@pytest.mark.parametrize("net", ["alex", "vgg", "squeeze"])
def test_lpips_real_weights_match_torch_mirror(net):
    torch = pytest.importorskip("torch")
    root = _ensure_converted()
    npz = root / f"lpips_{net}.npz"
    pth = next(iter(sorted(root.glob(f"lpips_{net}*.pth"))), None)
    if not npz.exists() or pth is None:
        pytest.skip(f"no lpips_{net} checkpoint in METRICS_TPU_REAL_WEIGHTS")

    import jax.numpy as jnp

    import metrics_tpu as mt
    from metrics_tpu.models.inception import params_from_npz

    rng = np.random.RandomState(5)
    a = rng.rand(4, 3, 64, 64).astype(np.float32) * 2 - 1
    b = np.clip(a + 0.1 * rng.randn(*a.shape).astype(np.float32), -1, 1)

    ours = mt.image.LearnedPerceptualImagePatchSimilarity(
        net_type=net, params=params_from_npz(str(npz))
    )
    our_val = float(ours(jnp.asarray(a), jnp.asarray(b)))
    assert np.isfinite(our_val)

    if net == "alex":
        # the alex mirror follows the ``lpips`` package key layout exactly, so
        # the real state dict loads into it directly — a live torch oracle
        from tests.helpers.torch_mirrors import TorchAlexLPIPSMirror

        mirror = TorchAlexLPIPSMirror()
        mirror.load_state_dict(torch.load(pth, map_location="cpu"), strict=False)
        mirror.eval()
        with torch.no_grad():
            torch_val = float(mirror(torch.from_numpy(a), torch.from_numpy(b)).mean())
        np.testing.assert_allclose(our_val, torch_val, rtol=1e-3, atol=1e-4)
    _maybe_check_recorded(f"lpips_{net}_seed5_4img", our_val)


def test_bert_score_real_model_matches_reference():
    pytest.importorskip("torch")
    root = Path(_DIR)
    bert_dir = root / "bert"
    if not (bert_dir / "config.json").exists():
        pytest.skip("no HF model dir `bert/` in METRICS_TPU_REAL_WEIGHTS")

    from tests.helpers.reference_oracle import get_reference

    ref = get_reference()
    if ref is None:
        pytest.skip("reference mount unavailable")

    import metrics_tpu as mt

    preds = ["the cat sat on the mat", "a quick brown fox"]
    target = ["a cat sat on a mat", "the quick brown fox jumps"]
    ours = mt.BERTScore(model_name_or_path=str(bert_dir), num_layers=4)
    our_out = {k: [float(x) for x in v] for k, v in ours(preds, target).items()}
    rscore = ref.BERTScore(model_name_or_path=str(bert_dir), num_layers=4)
    ref_out = {k: [float(x) for x in v] for k, v in rscore(preds, target).items()}
    for key in ("precision", "recall", "f1"):
        np.testing.assert_allclose(our_out[key], ref_out[key], rtol=1e-3, atol=1e-3)
    _maybe_check_recorded("bert_f1_fixed2", our_out["f1"])
