"""Golden InfoLM parity vs the mounted reference with SHARED weights.

A tiny BERT masked-LM is initialized in torch, saved locally, and loaded by
BOTH stacks by path (the reference's only injection surface): ours through
`metrics_tpu.functional.text.infolm` (FlaxAutoModelForMaskedLM), the oracle
through the reference's torch `infolm`
(`/root/reference/src/torchmetrics/functional/text/infolm.py`). Every
information measure, the idf toggle, and sentence-level output are compared
on identical sentences — the model-backed-metric analogue of the BERTScore
golden suite.
"""
from __future__ import annotations

import numpy as np
import pytest

transformers = pytest.importorskip("transformers")

from tests.helpers.reference_oracle import get_reference  # noqa: E402

pytestmark = pytest.mark.slow  # deep-coverage tier (see docs/testing.md)

_WORDS = ["the", "cat", "sat", "on", "mat", "a", "dog", "ran", "fast", "slow"]

PREDS = ["the cat sat on mat", "a dog ran fast", "the mat sat"]
TARGET = ["a cat sat on the mat", "a dog ran slow", "the cat sat"]


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    reference = get_reference()
    if reference is None:
        pytest.skip("mounted reference unavailable")
    import torch
    from transformers import BertConfig, BertForMaskedLM, BertTokenizerFast

    root = tmp_path_factory.mktemp("infolm_parity")
    (root / "vocab.txt").write_text("\n".join(["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + _WORDS))
    tokenizer = BertTokenizerFast(vocab_file=str(root / "vocab.txt"), do_lower_case=True)
    cfg = BertConfig(
        vocab_size=len(tokenizer),
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=2,
        intermediate_size=64,
        max_position_embeddings=32,
    )
    torch.manual_seed(11)
    model = BertForMaskedLM(cfg)
    model.eval()
    model_path = root / "model"
    model.save_pretrained(str(model_path))
    tokenizer.save_pretrained(str(model_path))
    return str(model_path)


def _ours(model_dir, **kwargs):
    from transformers import AutoTokenizer, FlaxAutoModelForMaskedLM

    from metrics_tpu.functional.text.infolm import infolm

    model = FlaxAutoModelForMaskedLM.from_pretrained(model_dir, from_pt=True)
    tokenizer = AutoTokenizer.from_pretrained(model_dir)
    return infolm(PREDS, TARGET, model=model, user_tokenizer=tokenizer, max_length=16, **kwargs)


def _theirs(model_dir, **kwargs):
    import importlib

    ref_mod = importlib.import_module("torchmetrics.functional.text.infolm")

    # py3.12 compat shim for the ORACLE only: the reference dispatches with
    # f"_calculate_{self.information_measure}", relying on the old enum
    # str() format; modern python renders "_IMEnum.KL_DIVERGENCE" and the
    # lookup fails. Route through .value without changing any math.
    if not getattr(ref_mod._InformationMeasure, "_py312_shimmed", False):
        def _call(self, preds_distribution, target_distribution):
            name = getattr(self.information_measure, "value", self.information_measure)
            return getattr(self, f"_calculate_{name}")(preds_distribution, target_distribution)

        ref_mod._InformationMeasure.__call__ = _call
        ref_mod._InformationMeasure._py312_shimmed = True

    return ref_mod.infolm(PREDS, TARGET, model_name_or_path=model_dir, max_length=16, verbose=False, **kwargs)


# measures whose formulas agree verbatim between the two stacks
EXACT_MEASURES = [
    ("ab_divergence", {"alpha": 0.6, "beta": 0.3}),
    ("renyi_divergence", {"alpha": 0.8}),
    ("l1_distance", {}),
    ("l2_distance", {}),
    ("l_infinity_distance", {}),
    ("fisher_rao_distance", {}),
]


@pytest.mark.parametrize("measure,kwargs", EXACT_MEASURES, ids=[m for m, _ in EXACT_MEASURES])
def test_exact_measures_match_reference(model_dir, measure, kwargs):
    """Same sentences, same weights, same pipeline: ab/renyi/distances must
    agree to float tolerance end to end (masking, temperature, aggregation)."""
    ours = _ours(model_dir, information_measure=measure, idf=False, **kwargs)
    theirs = _theirs(model_dir, information_measure=measure, idf=False, **kwargs)
    np.testing.assert_allclose(float(np.asarray(ours)), float(theirs), atol=2e-4, rtol=1e-4)


def test_kl_documented_divergence(model_dir):
    """Documented divergence: the reference's "kl_divergence" computes
    sum(T * log(P/T)) — the NEGATIVE of KL(T‖P), so it can be negative where
    a true KL cannot. Ours returns the paper's KL(P‖T) >= 0. The exact
    relationship ref(P, T) == -ours(T, P) pins that both pipelines otherwise
    agree (same distributions, masking, aggregation)."""
    theirs = float(_theirs(model_dir, information_measure="kl_divergence", idf=False))
    ours_swapped = _ours_swapped(model_dir, information_measure="kl_divergence", idf=False)
    np.testing.assert_allclose(-float(np.asarray(ours_swapped)), theirs, atol=2e-4, rtol=1e-4)
    ours = float(np.asarray(_ours(model_dir, information_measure="kl_divergence", idf=False)))
    assert ours >= 0.0  # a true KL


def test_alpha_documented_divergence(model_dir):
    """Documented divergence: the reference's alpha divergence is the negative
    of Amari's (non-negative) alpha divergence for alpha in (0, 1); ours
    returns the paper's sign. Exact relationship: ref == -ours."""
    kwargs = dict(information_measure="alpha_divergence", alpha=0.5, idf=False)
    theirs = float(_theirs(model_dir, **kwargs))
    ours = float(np.asarray(_ours(model_dir, **kwargs)))
    np.testing.assert_allclose(-ours, theirs, atol=2e-4, rtol=1e-4)
    assert ours >= 0.0


def test_beta_documented_divergence(model_dir):
    """Documented divergence: the reference's beta_divergence reuses its
    log-form AB divergence with alpha silently overwritten to 1.0 (a stateful
    mutation); ours implements the paper's log-free beta divergence. Exact
    relationship: ref beta(beta=b) == our ab_divergence(alpha=1, beta=b)."""
    theirs = float(_theirs(model_dir, information_measure="beta_divergence", beta=0.7, idf=False))
    ours_ab = float(
        np.asarray(_ours(model_dir, information_measure="ab_divergence", alpha=1.0, beta=0.7, idf=False))
    )
    np.testing.assert_allclose(ours_ab, theirs, atol=2e-4, rtol=1e-4)


def _ours_swapped(model_dir, **kwargs):
    from transformers import AutoTokenizer, FlaxAutoModelForMaskedLM

    from metrics_tpu.functional.text.infolm import infolm

    model = FlaxAutoModelForMaskedLM.from_pretrained(model_dir, from_pt=True)
    tokenizer = AutoTokenizer.from_pretrained(model_dir)
    return infolm(TARGET, PREDS, model=model, user_tokenizer=tokenizer, max_length=16, **kwargs)


def test_idf_matches_reference(model_dir):
    ours = _ours(model_dir, information_measure="l1_distance", idf=True)
    theirs = _theirs(model_dir, information_measure="l1_distance", idf=True)
    np.testing.assert_allclose(float(np.asarray(ours)), float(theirs), atol=2e-4, rtol=1e-4)


def test_sentence_level_scores_match_reference(model_dir):
    ours = _ours(model_dir, information_measure="l2_distance", idf=False, return_sentence_level_score=True)
    theirs = _theirs(model_dir, information_measure="l2_distance", idf=False, return_sentence_level_score=True)
    np.testing.assert_allclose(float(np.asarray(ours[0])), float(theirs[0]), atol=2e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(ours[1]), np.asarray(theirs[1]), atol=2e-4, rtol=1e-4)


def test_temperature_sweep_matches_reference(model_dir):
    for temperature in (0.25, 1.0, 3.0):
        kwargs = dict(information_measure="fisher_rao_distance", idf=False, temperature=temperature)
        ours = _ours(model_dir, **kwargs)
        theirs = _theirs(model_dir, **kwargs)
        np.testing.assert_allclose(float(np.asarray(ours)), float(theirs), atol=2e-4, rtol=1e-4)
