"""Golden weight-sharing parity: Flax LPIPS vs an independent torch mirror.

Same strategy as test_inception_parity.py, for the reference's
``NoTrainLpips`` (`/root/reference/src/torchmetrics/image/lpip.py:24-40`):
the torch mirror carries ``lpips``-package state-dict naming, the production
converter (`tools/convert_lpips_weights.py`) maps those weights into the
Flax ``LPIPSNet``, and per-pair scores must agree end to end.
"""
import os
import sys
import tempfile

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax.numpy as jnp  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "tools"))
from convert_lpips_weights import BACKBONE_INDEX_MAPS, convert_state_dict  # noqa: E402

from tests.helpers.torch_mirrors import TorchAlexLPIPSMirror, randomize_lpips_  # noqa: E402


@pytest.fixture(scope="module")
def shared():
    from metrics_tpu.models.inception import params_from_npz

    mirror = TorchAlexLPIPSMirror()
    randomize_lpips_(mirror, seed=5)
    state = {k: v.numpy() for k, v in mirror.state_dict().items()}
    converted = convert_state_dict("alex", state)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "w.npz")
        np.savez(path, **converted)
        variables = params_from_npz(path)
    rng = np.random.RandomState(9)
    img1 = (rng.rand(4, 3, 64, 64) * 2 - 1).astype(np.float32)
    img2 = (rng.rand(4, 3, 64, 64) * 2 - 1).astype(np.float32)
    return mirror, variables, img1, img2


def test_scores_match(shared):
    from metrics_tpu.models.lpips import LPIPSExtractor

    mirror, variables, img1, img2 = shared
    ours = np.asarray(LPIPSExtractor(net_type="alex", params=variables)(img1, img2))
    with torch.no_grad():
        want = mirror(torch.from_numpy(img1), torch.from_numpy(img2)).numpy()
    np.testing.assert_allclose(ours, want, rtol=1e-4, atol=1e-5)


def test_identical_pair_is_zero(shared):
    from metrics_tpu.models.lpips import LPIPSExtractor

    _, variables, img1, _ = shared
    ours = np.asarray(LPIPSExtractor(net_type="alex", params=variables)(img1, img1))
    np.testing.assert_allclose(ours, np.zeros(img1.shape[0]), atol=1e-6)


def test_metric_end_to_end(shared):
    from metrics_tpu.image.generative import LearnedPerceptualImagePatchSimilarity

    mirror, variables, img1, img2 = shared
    metric = LearnedPerceptualImagePatchSimilarity(net_type="alex", params=variables)
    metric.update(jnp.asarray(img1), jnp.asarray(img2))
    with torch.no_grad():
        want = float(mirror(torch.from_numpy(img1), torch.from_numpy(img2)).mean())
    assert float(metric.compute()) == pytest.approx(want, rel=1e-4)


def test_converter_rejects_untapped_index():
    with pytest.raises(ValueError, match="not a tapped conv"):
        convert_state_dict("alex", {"features.2.weight": np.zeros((1, 1, 1, 1), np.float32)})


def test_converter_drops_duplicate_modulelist_heads():
    """lpips.LPIPS registers heads twice (lin{k} attrs + self.lins ModuleList);
    state_dict() duplicates them under lins.{k}.* — those must be dropped."""
    out = convert_state_dict(
        "alex",
        {
            "lin0.model.1.weight": np.ones((1, 64, 1, 1), np.float32),
            "lins.0.model.1.weight": np.zeros((1, 64, 1, 1), np.float32),
        },
    )
    assert list(out) == ["params/lin0/kernel"]
    assert out["params/lin0/kernel"].sum() == 64  # the lin{k} copy won


def test_converter_covers_every_flax_leaf():
    """Every parameter the Flax AlexNet LPIPS owns has exactly one torch key."""
    import jax

    from metrics_tpu.models.lpips import LPIPSNet

    model = LPIPSNet(net_type="alex")
    dummy = jnp.zeros((1, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), dummy, dummy)
    flat = {"/".join(str(p.key) for p in path): v for path, v in jax.tree_util.tree_flatten_with_path(variables)[0]}

    mirror = TorchAlexLPIPSMirror()
    converted = convert_state_dict("alex", {k: v.numpy() for k, v in mirror.state_dict().items()})
    assert set(converted) == set(flat)
    for key, value in converted.items():
        assert value.shape == flat[key].shape, key


@pytest.mark.parametrize("net_type", ["vgg", "squeeze"])
def test_converter_covers_other_backbones(net_type):
    """The vgg/squeeze index maps line up with the Flax trunk's parameters
    (heads checked for alex above; backbones differ only in the trunk)."""
    import jax

    from metrics_tpu.models.lpips import LPIPSNet

    model = LPIPSNet(net_type=net_type)
    dummy = jnp.zeros((1, 64, 64, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), dummy, dummy)
    trunk = variables["params"]["net"]

    # synthesize a torchvision-style backbone state dict from the flax shapes
    synthetic = {}
    for idx, name in BACKBONE_INDEX_MAPS[net_type].items():
        node = trunk[name]
        if "kernel" in node:  # plain conv
            h, w, i, o = node["kernel"].shape
            synthetic[f"features.{idx}.weight"] = np.zeros((o, i, h, w), np.float32)
            synthetic[f"features.{idx}.bias"] = np.zeros((o,), np.float32)
        else:  # Fire module
            for sub, subnode in node.items():
                h, w, i, o = subnode["kernel"].shape
                synthetic[f"features.{idx}.{sub}.weight"] = np.zeros((o, i, h, w), np.float32)
                synthetic[f"features.{idx}.{sub}.bias"] = np.zeros((o,), np.float32)
    converted = convert_state_dict(net_type, synthetic)

    flat = {
        "params/" + "/".join(str(p.key) for p in path): v
        for path, v in jax.tree_util.tree_flatten_with_path({"net": trunk})[0]
    }
    assert set(converted) == set(flat)
    for key, value in converted.items():
        assert value.shape == flat[key].shape, key
