"""InfoLM with a REAL Flax masked-LM forward (offline-constructed).

Exercises the full pipeline — per-position masking, MLM forward, temperature-
scaled distribution aggregation, information measures — with a tiny randomly
initialized `FlaxBertForMaskedLM` plus a genuine WordPiece tokenizer, since
hub checkpoints are unreachable here (reference counterpart:
`tests/unittests/text/test_infolm.py`).
"""
from __future__ import annotations

import numpy as np
import pytest

transformers = pytest.importorskip("transformers")

from transformers import BertConfig, BertTokenizerFast, FlaxBertForMaskedLM  # noqa: E402

from metrics_tpu import InfoLM  # noqa: E402
from metrics_tpu.functional.text.infolm import infolm  # noqa: E402

_WORDS = ["the", "cat", "sat", "on", "mat", "a", "dog", "ran", "fast", "slow"]


@pytest.fixture(scope="module")
def tiny_mlm(tmp_path_factory):
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + _WORDS
    vocab_file = tmp_path_factory.mktemp("mlm") / "vocab.txt"
    vocab_file.write_text("\n".join(vocab))
    tokenizer = BertTokenizerFast(vocab_file=str(vocab_file), do_lower_case=True)
    cfg = BertConfig(
        vocab_size=len(vocab),
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=2,
        intermediate_size=64,
        max_position_embeddings=32,
    )
    return FlaxBertForMaskedLM(cfg, seed=0), tokenizer


@pytest.mark.slow
def test_identical_sentences_zero_divergence(tiny_mlm):
    model, tokenizer = tiny_mlm
    sents = ["the cat sat on mat", "a dog ran fast"]
    score = infolm(sents, sents, model=model, user_tokenizer=tokenizer, max_length=16, idf=False)
    assert float(score) == pytest.approx(0.0, abs=1e-5)  # KL(p‖p) = 0


@pytest.mark.parametrize(
    "measure,kwargs",
    [
        ("kl_divergence", {}),
        ("l2_distance", {}),
        ("fisher_rao_distance", {}),
        ("alpha_divergence", {"alpha": 0.5}),
        ("beta_divergence", {"beta": 0.7}),
    ],
)
def test_measures_nonnegative_and_finite(tiny_mlm, measure, kwargs):
    model, tokenizer = tiny_mlm
    preds = ["the cat sat on mat", "a dog ran fast"]
    target = ["a dog ran slow", "the mat sat"]
    score = infolm(
        preds, target, model=model, user_tokenizer=tokenizer, max_length=16, idf=False,
        information_measure=measure, **kwargs,
    )
    val = float(score)
    assert np.isfinite(val)
    assert val >= -1e-6


def test_module_metric_accumulates(tiny_mlm):
    model, tokenizer = tiny_mlm
    m = InfoLM(model=model, user_tokenizer=tokenizer, max_length=16, idf=False,
               return_sentence_level_score=True)
    m.update(["the cat sat"], ["the cat sat"])
    m.update(["a dog ran"], ["a dog ran slow"])
    mean_score, per_sentence = m.compute()
    assert np.asarray(per_sentence).shape == (2,)
    assert float(per_sentence[0]) == pytest.approx(0.0, abs=1e-5)
    assert float(per_sentence[1]) > 0.0


def test_injection_requires_pair(tiny_mlm):
    model, _ = tiny_mlm
    with pytest.raises(ValueError, match="together"):
        infolm(["a"], ["a"], model=model)


def test_empty_sentence_stays_finite_with_idf(tiny_mlm):
    """Empty hypotheses must not NaN the corpus score even under idf, where
    the attention-mask fallback alone would still zero out ([CLS]/[SEP]
    appear in every document so their idf weight is 0) — review regression."""
    model, tokenizer = tiny_mlm
    for idf in (False, True):
        score = infolm(
            ["", "a dog ran"], ["the cat sat", "a dog ran fast"],
            model=model, user_tokenizer=tokenizer, max_length=16, idf=idf,
        )
        assert np.isfinite(float(np.asarray(score))), f"idf={idf}"
