"""Real-checkpoint layout fidelity for the weight converters.

The reference loads torch-fidelity's ``FeatureExtractorInceptionV3``
(`/root/reference/src/torchmetrics/image/fid.py:41-58`), the ``lpips``
package nets (`image/lpip.py:24-77`), and HF checkpoints for BERTScore
(`functional/text/bert.py:45-123`). This repo's converters were previously
validated only against in-repo torch mirrors — a key-layout drift between
mirror and upstream would have passed every test and still broken the first
real user.

These tests anchor everything to the VENDORED manifests in
``tests/fixtures/manifests/`` — the exact upstream state-dict key names,
shapes, and dtypes, transcribed from the published module definitions by
``tools/gen_checkpoint_manifests.py`` (independent of this repo's Flax models
and torch mirrors). A failure here means a converter key-mapping (or a
mirror) drifted from the real checkpoint layout.
"""
from __future__ import annotations

import json
import os
import sys

import numpy as np
import pytest

_MANIFEST_DIR = os.path.join(os.path.dirname(__file__), "..", "fixtures", "manifests")
_TOOLS_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "tools")
if _TOOLS_DIR not in sys.path:
    sys.path.insert(0, _TOOLS_DIR)


def _manifest(name: str) -> dict:
    with open(os.path.join(_MANIFEST_DIR, name)) as handle:
        return json.load(handle)


def _synthetic_numpy_state(manifest: dict, seed: int = 0, include_optional: bool = True) -> dict:
    """A synthetic checkpoint with EXACTLY the upstream layout."""
    rng = np.random.RandomState(seed)
    state = {}
    for key, spec in manifest.items():
        if spec.get("optional") and not include_optional:
            continue
        shape = spec["shape"]
        if spec["dtype"] == "int64":
            state[key] = np.asarray(rng.randint(0, 100), dtype=np.int64).reshape(shape)
        elif key.endswith("running_var") or key.endswith("bn.weight") or key.endswith(".scale"):
            state[key] = (rng.rand(*shape).astype(np.float32) * 0.5 + 0.75)
        elif len(shape) >= 2:
            # fan-in-scaled weights keep activations (and hence feature
            # covariances) non-degenerate through the deep nets
            fan_in = int(np.prod(shape[1:]))
            state[key] = rng.randn(*shape).astype(np.float32) * (2.0 / max(fan_in, 1)) ** 0.5
        else:
            state[key] = rng.randn(*shape).astype(np.float32) * 0.1
    return state


# ----------------------------------------------------------------- Inception


class TestInceptionLayout:
    def test_manifest_is_the_published_layout(self):
        """Structural invariants of the pt_inception-2015-12-05 artifact:
        94 conv+bn modules, 1008-way fc, 2048-d final features."""
        man = _manifest("torch_fidelity_inception_v3.json")
        conv_keys = [k for k in man if k.endswith(".conv.weight")]
        assert len(conv_keys) == 94
        assert man["fc.weight"]["shape"] == [1008, 2048]
        assert man["Mixed_7c.branch_pool.conv.weight"]["shape"][1] == 2048
        # every conv has its full BN quartet + the optional tracked counter
        for key in conv_keys:
            stem = key[: -len(".conv.weight")]
            for suffix in ("weight", "bias", "running_mean", "running_var"):
                assert f"{stem}.bn.{suffix}" in man, f"{stem} missing bn.{suffix}"
            assert man[f"{stem}.bn.num_batches_tracked"]["optional"] is True

    def test_torch_mirror_matches_upstream_layout(self):
        """The in-repo torch mirror must carry the REAL checkpoint's key set
        and shapes — this is the test that breaks if mirror and upstream
        drift apart."""
        torch = pytest.importorskip("torch")
        from tests.helpers.torch_mirrors import TorchInceptionMirror

        man = _manifest("torch_fidelity_inception_v3.json")
        mirror_state = TorchInceptionMirror().state_dict()
        assert set(mirror_state) == set(man)
        for key, value in mirror_state.items():
            assert list(value.shape) == man[key]["shape"], key

    def test_converter_accepts_real_layout(self):
        """convert_state_dict over a synthetic REAL-layout checkpoint must
        produce exactly the Flax model's parameter manifest."""
        jnp = pytest.importorskip("jax.numpy")
        from convert_inception_weights import convert_state_dict

        from metrics_tpu.models.inception import InceptionV3Extractor
        from metrics_tpu.models.manifest import _flatten_with_paths, expected_manifest

        man = _manifest("torch_fidelity_inception_v3.json")
        converted = convert_state_dict(_synthetic_numpy_state(man))

        tree: dict = {}
        for key, value in converted.items():
            node = tree
            parts = key.split("/")
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = value

        extractor = InceptionV3Extractor(feature="2048", seed=0)
        dummy = jnp.zeros((1, 299, 299, 3), jnp.float32)
        want = expected_manifest(extractor.model, dummy)
        got = _flatten_with_paths(tree)
        assert want == got

    def test_converter_accepts_pre_tracked_bn_checkpoints(self):
        """The 2015 artifact predates BN's num_batches_tracked buffer — the
        converter must accept a checkpoint without those keys too."""
        from convert_inception_weights import convert_state_dict

        man = _manifest("torch_fidelity_inception_v3.json")
        with_opt = convert_state_dict(_synthetic_numpy_state(man, include_optional=True))
        without_opt = convert_state_dict(_synthetic_numpy_state(man, include_optional=False))
        assert set(with_opt) == set(without_opt)

    def test_converter_rejects_foreign_keys(self):
        from convert_inception_weights import convert_state_dict

        with pytest.raises(ValueError, match="Unrecognized torch key"):
            convert_state_dict({"some.unknown.module.weight": np.zeros((1,), np.float32)})

    @pytest.mark.slow
    def test_fid_end_to_end_from_real_layout_checkpoint(self, tmp_path):
        """Full user path: real-layout .pth-equivalent -> converter -> .npz ->
        FrechetInceptionDistance -> finite score. Fails if any converter key
        mapping drifts from the upstream layout."""
        jnp = pytest.importorskip("jax.numpy")
        from convert_inception_weights import convert_state_dict

        import metrics_tpu as mt

        man = _manifest("torch_fidelity_inception_v3.json")
        converted = convert_state_dict(_synthetic_numpy_state(man))
        npz_path = tmp_path / "inception.npz"
        np.savez(npz_path, **converted)

        fid = mt.FrechetInceptionDistance(feature=2048, npz_path=str(npz_path))
        rng = np.random.RandomState(0)
        real = jnp.asarray(rng.randint(0, 256, (2, 3, 299, 299), dtype=np.uint8))
        fake = jnp.asarray(rng.randint(0, 256, (2, 3, 299, 299), dtype=np.uint8))
        fid.update(real, real=True)
        fid.update(fake, real=False)
        assert np.isfinite(float(fid.compute()))


# --------------------------------------------------------------------- LPIPS


class TestLPIPSLayout:
    @pytest.mark.parametrize("net_type", ["alex", "vgg", "squeeze"])
    def test_manifest_head_and_backbone_invariants(self, net_type):
        man = _manifest(f"lpips_{net_type}.json")
        # scaling buffers + the double-registered heads are part of the layout
        assert man["scaling_layer.shift"]["shape"] == [1, 3, 1, 1]
        lin_keys = sorted(k for k in man if k.startswith("lin") and not k.startswith("lins."))
        dup_keys = sorted(k for k in man if k.startswith("lins."))
        assert len(lin_keys) == len(dup_keys) == {"alex": 5, "vgg": 5, "squeeze": 7}[net_type]
        for k, dup in zip(lin_keys, dup_keys):
            assert man[k]["shape"] == man[dup]["shape"]
        # heads are 1x1 single-output convs over the tap channels
        for k in lin_keys:
            shape = man[k]["shape"]
            assert shape[0] == 1 and shape[2:] == [1, 1]

    def test_alex_mirror_backbone_matches_upstream_layout(self):
        """The alex mirror's backbone/head keys must be a subset of the real
        lpips.LPIPS(net='alex') state dict with identical shapes (the mirror
        omits the constant scaling buffers and the ModuleList duplicates)."""
        torch = pytest.importorskip("torch")
        from tests.helpers.torch_mirrors import TorchAlexLPIPSMirror

        man = _manifest("lpips_alex.json")
        mirror_state = TorchAlexLPIPSMirror().state_dict()
        assert set(mirror_state) <= set(man)
        for key, value in mirror_state.items():
            assert list(value.shape) == man[key]["shape"], key
        # everything the mirror omits is either constant or a duplicate
        omitted = set(man) - set(mirror_state)
        assert all(k.startswith(("scaling_layer.", "lins.")) for k in omitted)

    @pytest.mark.slow  # ~17s/net: builds a full synthetic checkpoint + eval_shape validation
    @pytest.mark.parametrize("net_type", ["alex", "vgg", "squeeze"])
    def test_converter_accepts_real_layout(self, net_type):
        """convert_state_dict over the full real-layout LPIPS state dict must
        produce exactly the Flax LPIPSNet parameter manifest."""
        jnp = pytest.importorskip("jax.numpy")
        from convert_lpips_weights import convert_state_dict

        from metrics_tpu.models.lpips import LPIPSExtractor
        from metrics_tpu.models.manifest import _flatten_with_paths, expected_manifest

        man = _manifest(f"lpips_{net_type}.json")
        converted = convert_state_dict(net_type, _synthetic_numpy_state(man))

        tree: dict = {}
        for key, value in converted.items():
            node = tree
            parts = key.split("/")
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = value

        extractor = LPIPSExtractor(net_type=net_type, seed=0)
        dummy = jnp.zeros((1, 64, 64, 3), jnp.float32)  # model is NHWC inside
        want = expected_manifest(extractor.model, dummy, dummy)
        got = _flatten_with_paths(tree)
        assert want == got

    def test_converter_rejects_untapped_backbone_index(self):
        from convert_lpips_weights import convert_state_dict

        with pytest.raises(ValueError, match="not a tapped conv"):
            convert_state_dict("alex", {"net.slice1.1.weight": np.zeros((1,), np.float32)})

    @pytest.mark.slow
    def test_lpips_end_to_end_from_real_layout_checkpoint(self, tmp_path):
        jnp = pytest.importorskip("jax.numpy")
        from convert_lpips_weights import convert_state_dict

        import metrics_tpu as mt

        man = _manifest("lpips_alex.json")
        converted = convert_state_dict("alex", _synthetic_numpy_state(man))
        npz_path = tmp_path / "lpips_alex.npz"
        np.savez(npz_path, **converted)

        metric = mt.LearnedPerceptualImagePatchSimilarity(net_type="alex", npz_path=str(npz_path))
        rng = np.random.RandomState(0)
        a = jnp.asarray(rng.rand(2, 3, 64, 64).astype(np.float32) * 2 - 1)
        b = jnp.asarray(rng.rand(2, 3, 64, 64).astype(np.float32) * 2 - 1)
        metric.update(a, b)
        assert np.isfinite(float(metric.compute()))


# ---------------------------------------------------------------------- BERT


class TestBERTLayout:
    def test_vendored_manifest_matches_installed_bert_definition(self):
        """The vendored bert-base-uncased manifest must equal the installed
        transformers BertModel definition (meta-device instantiation — the
        published module definition itself)."""
        pytest.importorskip("transformers")
        from gen_checkpoint_manifests import bert_manifest

        assert bert_manifest() == _manifest("hf_bert_base_uncased.json")

    def test_manifest_invariants(self):
        man = _manifest("hf_bert_base_uncased.json")
        assert man["embeddings.word_embeddings.weight"]["shape"] == [30522, 768]
        assert man["pooler.dense.weight"]["shape"] == [768, 768]
        # 12 encoder layers, each with the full attention + FFN parameter set
        for layer in range(12):
            prefix = f"encoder.layer.{layer}."
            assert f"{prefix}attention.self.query.weight" in man
            assert man[f"{prefix}intermediate.dense.weight"]["shape"] == [3072, 768]

    @pytest.mark.slow
    def test_bert_score_from_local_torch_checkpoint(self, tmp_path):
        """Full user path: a local HF directory holding only TORCH weights
        (the layout `save_pretrained` and hub snapshots produce) must load
        through the flax path and produce a finite BERTScore."""
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")
        from transformers import BertConfig, BertModel, BertTokenizer

        import metrics_tpu as mt

        ckpt_dir = tmp_path / "tiny-bert"
        ckpt_dir.mkdir()
        cfg = BertConfig(
            vocab_size=64, hidden_size=32, num_hidden_layers=2, num_attention_heads=2,
            intermediate_size=64, max_position_embeddings=64,
        )
        torch.manual_seed(0)
        model = BertModel(cfg)
        # per-layer key pattern must match the vendored real manifest
        man_keys = set(_manifest("hf_bert_base_uncased.json"))
        tiny_keys = {
            k.replace("layer.0.", "layer.N.").replace("layer.1.", "layer.N.")
            for k in model.state_dict()
        }
        real_keys = {
            k.replace("layer.0.", "layer.N.") if ".layer.0." in k else k
            for k in man_keys
            if ".layer." not in k or ".layer.0." in k
        }
        assert tiny_keys == real_keys
        model.save_pretrained(ckpt_dir, safe_serialization=False)  # pytorch_model.bin

        vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]", "hello", "world", "the", "cat", "sat"]
        vocab += [f"tok{i}" for i in range(64 - len(vocab))]
        (ckpt_dir / "vocab.txt").write_text("\n".join(vocab))
        BertTokenizer(str(ckpt_dir / "vocab.txt"), model_max_length=64).save_pretrained(ckpt_dir)

        res = mt.functional.bert_score(
            ["hello world"], ["hello the cat"], model_name_or_path=str(ckpt_dir), num_layers=2,
        )
        assert np.isfinite(float(np.asarray(res["f1"]).mean()))
