"""Golden weight-sharing parity: Flax InceptionV3 vs an independent torch mirror.

The reference's FID/KID/IS numbers come from torch-fidelity's InceptionV3
(`/root/reference/src/torchmetrics/image/fid.py:27-58`). No egress means the
real checkpoint can't be fetched, so parity is pinned the strongest way
available: a torch-side mirror of the same published architecture
(tests/helpers/torch_mirrors.py) is given random-but-well-conditioned
weights, those exact weights are pushed through the production converter
(`tools/convert_inception_weights.py`) into the Flax model, and every
feature tap plus the end-to-end FID/KID/IS numbers must agree. Any drift in
tap ordering, pooling mode, padding, BN epsilon, or converter layout fails
these tests — which is precisely the class of bug that would silently
corrupt published-number parity once real weights are loaded.
"""
import os
import sys
import tempfile

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "tools"))
from convert_inception_weights import convert_state_dict  # noqa: E402

from tests.helpers.torch_mirrors import TorchInceptionMirror, randomize_inception_  # noqa: E402

pytestmark = pytest.mark.slow  # deep-coverage tier (see docs/testing.md)

TAPS = ("64", "192", "768", "2048", "logits_unbiased", "logits")


@pytest.fixture(scope="module")
def shared():
    """(torch mirror, flax variables, uint8 test images) with identical weights."""
    from metrics_tpu.models.inception import params_from_npz

    mirror = TorchInceptionMirror()
    randomize_inception_(mirror, seed=7)
    state = {k: v.numpy() for k, v in mirror.state_dict().items()}
    converted = convert_state_dict(state)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "w.npz")
        np.savez(path, **converted)
        variables = params_from_npz(path)
    imgs = np.random.RandomState(11).randint(0, 256, size=(2, 3, 299, 299), dtype=np.uint8)
    return mirror, variables, imgs


def _torch_taps(mirror, imgs_uint8):
    x = torch.from_numpy(imgs_uint8).float() / 255.0 * 2.0 - 1.0
    with torch.no_grad():
        return {k: v.numpy() for k, v in mirror(x).items()}


def _flax_taps(variables, imgs_uint8):
    from metrics_tpu.models.inception import InceptionV3

    x = jnp.asarray(imgs_uint8).astype(jnp.float32) / 255.0 * 2.0 - 1.0
    x = jnp.transpose(x, (0, 2, 3, 1))
    out = InceptionV3().apply(variables, x)
    return {k: np.asarray(v) for k, v in out.items()}


def test_all_taps_match(shared):
    """Feature-tap equality at 64/192/768/2048/logits — the VERDICT #1 gate."""
    mirror, variables, imgs = shared
    got = _flax_taps(variables, imgs)
    want = _torch_taps(mirror, imgs)
    assert set(got) == set(want)
    for name in TAPS:
        scale = np.abs(want[name]).mean() + 1e-6
        err = np.abs(got[name] - want[name]).max()
        assert err / scale < 5e-3, f"tap {name}: max abs err {err} vs mean scale {scale}"


def test_extractor_end_to_end_matches(shared):
    """The user-facing extractor path (uint8 NCHW -> resize -> normalize) agrees."""
    from metrics_tpu.models.inception import InceptionV3Extractor

    mirror, variables, imgs = shared
    feats = np.asarray(InceptionV3Extractor(feature="2048", params=variables)(jnp.asarray(imgs)))
    want = _torch_taps(mirror, imgs)["2048"]
    scale = np.abs(want).mean() + 1e-6
    assert np.abs(feats - want).max() / scale < 5e-3


@pytest.fixture(scope="module")
def mirror_features(shared):
    """Larger image batches featurized by BOTH stacks (feature=64 keeps the
    covariance small and the oracle numerically honest with 24 samples)."""
    mirror, variables, _ = shared
    rng = np.random.RandomState(3)
    real = rng.randint(0, 256, size=(24, 3, 299, 299), dtype=np.uint8)
    fake = np.clip(real.astype(np.int16) + rng.randint(-40, 40, size=real.shape), 0, 255).astype(np.uint8)
    with torch.no_grad():
        t_real = _torch_taps(mirror, real)
        t_fake = _torch_taps(mirror, fake)
    return real, fake, t_real, t_fake


def test_fid_matches_scipy_oracle(shared, mirror_features):
    """End-to-end FID: our metric (Flax features + eigh sqrtm) vs torch-mirror
    features + scipy.linalg.sqrtm — the reference's exact host formula
    (`image/fid.py:61-126`)."""
    import scipy.linalg

    from metrics_tpu.image.generative import FrechetInceptionDistance

    _, variables, _ = shared
    real, fake, t_real, t_fake = mirror_features

    fid = FrechetInceptionDistance(feature=64, params=variables)
    fid.update(jnp.asarray(real), real=True)
    fid.update(jnp.asarray(fake), real=False)
    ours = float(fid.compute())

    r, f = t_real["64"].astype(np.float64), t_fake["64"].astype(np.float64)
    mu1, mu2 = r.mean(0), f.mean(0)
    cov1, cov2 = np.cov(r, rowvar=False), np.cov(f, rowvar=False)
    covmean = scipy.linalg.sqrtm(cov1 @ cov2)
    if np.iscomplexobj(covmean):
        covmean = covmean.real
    want = float((mu1 - mu2) @ (mu1 - mu2) + np.trace(cov1) + np.trace(cov2) - 2 * np.trace(covmean))

    assert ours == pytest.approx(want, rel=2e-2, abs=1e-3)


def test_kid_matches_numpy_oracle(shared, mirror_features):
    """End-to-end KID vs a numpy polynomial-MMD oracle on torch-mirror features."""
    from metrics_tpu.image.generative import KernelInceptionDistance

    _, variables, _ = shared
    real, fake, t_real, t_fake = mirror_features

    kid = KernelInceptionDistance(feature=64, params=variables, subsets=1, subset_size=24, seed=0)
    kid.update(jnp.asarray(real), real=True)
    kid.update(jnp.asarray(fake), real=False)
    ours = float(kid.compute()[0])

    r, f = t_real["64"].astype(np.float64), t_fake["64"].astype(np.float64)
    gamma = 1.0 / r.shape[1]
    k_xx = (r @ r.T * gamma + 1.0) ** 3
    k_yy = (f @ f.T * gamma + 1.0) ** 3
    k_xy = (r @ f.T * gamma + 1.0) ** 3
    m = r.shape[0]
    want = float(
        ((k_xx.sum() - np.trace(k_xx)) + (k_yy.sum() - np.trace(k_yy))) / (m * (m - 1))
        - 2 * k_xy.sum() / m**2
    )
    assert ours == pytest.approx(want, rel=2e-2, abs=1e-4)


def test_inception_score_matches_numpy_oracle(shared, mirror_features):
    """End-to-end IS on logits_unbiased vs a numpy KL oracle."""
    from metrics_tpu.image.generative import InceptionScore

    _, variables, _ = shared
    real, _, t_real, _ = mirror_features

    iscore = InceptionScore(feature="logits_unbiased", params=variables, splits=2, seed=0)
    iscore.update(jnp.asarray(real))
    ours_mean, ours_std = (float(v) for v in iscore.compute())

    logits = t_real["logits_unbiased"].astype(np.float64)
    logits = logits[np.random.RandomState(0).permutation(logits.shape[0])]
    z = logits - logits.max(axis=1, keepdims=True)
    prob = np.exp(z) / np.exp(z).sum(axis=1, keepdims=True)
    log_prob = z - np.log(np.exp(z).sum(axis=1, keepdims=True))
    scores = []
    for chunk_p, chunk_lp in zip(np.array_split(prob, 2), np.array_split(log_prob, 2)):
        mean_p = chunk_p.mean(axis=0, keepdims=True)
        kl = (chunk_p * (chunk_lp - np.log(mean_p))).sum(axis=1).mean()
        scores.append(np.exp(kl))
    want_mean, want_std = float(np.mean(scores)), float(np.std(scores, ddof=1))
    assert ours_mean == pytest.approx(want_mean, rel=1e-2)
    assert ours_std == pytest.approx(want_std, rel=0.2, abs=1e-3)


def test_trace_sqrtm_identity_vs_scipy():
    """The device-path identity trace sqrtm(AB) = sum sqrt(eig(sqrt(A) B sqrt(A)))
    against scipy.linalg.sqrtm on random (incl. rank-deficient) PSD pairs."""
    import scipy.linalg

    from metrics_tpu.image.generative import _trace_sqrtm_product

    rng = np.random.RandomState(0)
    for n, rank in ((16, 16), (32, 10), (8, 3)):
        a = rng.randn(n, rank)
        b = rng.randn(n, max(rank - 1, 1))
        cov1 = (a @ a.T) / n
        cov2 = (b @ b.T) / n
        want = np.trace(scipy.linalg.sqrtm(cov1 @ cov2).real)
        with jax.enable_x64(True):  # production FID compute runs under x64
            got = float(_trace_sqrtm_product(jnp.asarray(cov1, jnp.float64), jnp.asarray(cov2, jnp.float64)))
        assert got == pytest.approx(float(want), rel=1e-6, abs=1e-9)
