"""Structural validation of the torch->flax Inception weight converter.

Real torch-fidelity weights are not downloadable here (zero egress), so the
mapping is validated by round-trip: flatten our Flax model's own parameter
tree to npz keys, invert each to its torch name/layout via npz_key_to_torch,
convert back with the production converter, and require bit-identical trees —
proving every parameter in the model has exactly one torch counterpart with
consistent transposition.
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "tools"))
from convert_inception_weights import convert_state_dict, npz_key_to_torch  # noqa: E402


def _flatten(tree, prefix=""):
    flat = {}
    for k, v in tree.items():
        key = f"{prefix}/{k}" if prefix else k
        if isinstance(v, dict):
            flat.update(_flatten(v, key))
        else:
            flat[key] = np.asarray(v)
    return flat


@pytest.fixture(scope="module")
def flax_flat():
    try:
        import jax
        import jax.numpy as jnp

        from metrics_tpu.models.inception import InceptionV3
    except ModuleNotFoundError:
        pytest.skip("flax unavailable")
    model = InceptionV3()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 299, 299, 3)))
    return _flatten(variables)


def test_round_trip_is_identity(flax_flat):
    synthetic_torch = dict(npz_key_to_torch(k, v) for k, v in flax_flat.items())
    # plus the inference-irrelevant key torch checkpoints carry
    synthetic_torch["Conv2d_1a_3x3.bn.num_batches_tracked"] = np.asarray(0)
    back = convert_state_dict(synthetic_torch)
    assert set(back) == set(flax_flat), (
        set(back) ^ set(flax_flat)
    )
    for k in flax_flat:
        np.testing.assert_array_equal(back[k], flax_flat[k], err_msg=k)


def test_converted_params_drive_the_model(flax_flat):
    import jax.numpy as jnp

    from metrics_tpu.models.inception import InceptionV3, params_from_npz
    import tempfile, os

    synthetic_torch = dict(npz_key_to_torch(k, v) for k, v in flax_flat.items())
    converted = convert_state_dict(synthetic_torch)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "w.npz")
        np.savez(path, **converted)
        params = params_from_npz(path)
    out = InceptionV3().apply(params, jnp.zeros((1, 299, 299, 3)))
    assert out["2048"].shape == (1, 2048)
    assert out["logits"].shape == (1, 1008)


def test_conv_kernel_layout():
    # OIHW -> HWIO for convs; (O,I) -> (I,O) for the fc
    w = np.arange(2 * 3 * 5 * 7).reshape(2, 3, 5, 7).astype(np.float32)
    out = convert_state_dict({"Mixed_5b.branch1x1.conv.weight": w})
    assert out["params/Mixed_5b/branch1x1/conv/kernel"].shape == (5, 7, 3, 2)
    fc = np.arange(6).reshape(2, 3).astype(np.float32)
    assert convert_state_dict({"fc.weight": fc})["params/fc/kernel"].shape == (3, 2)
