"""BERTScore with a REAL Flax transformer forward (offline-constructed).

The default BERTScore path embeds sentences with `FlaxAutoModel`
(`metrics_tpu/functional/text/bert.py`); hub downloads are unavailable here,
so these tests construct a tiny randomly-initialized `FlaxBertModel` plus a
genuine WordPiece tokenizer from a locally written vocab — exercising the
identical tokenize → Flax forward → cosine-match pipeline the pretrained path
uses (reference counterpart: `tests/unittests/text/test_bertscore.py`).
"""
from __future__ import annotations

import numpy as np
import pytest

transformers = pytest.importorskip("transformers")

from transformers import BertConfig, BertTokenizerFast, FlaxBertModel  # noqa: E402

from metrics_tpu.functional.text.bert import bert_score  # noqa: E402

pytestmark = pytest.mark.slow  # deep-coverage tier (see docs/testing.md)

_WORDS = ["the", "cat", "sat", "on", "mat", "a", "dog", "ran", "fast", "slow"]


@pytest.fixture(scope="module")
def tiny_bert(tmp_path_factory):
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + _WORDS
    vocab_file = tmp_path_factory.mktemp("bert") / "vocab.txt"
    vocab_file.write_text("\n".join(vocab))
    tokenizer = BertTokenizerFast(vocab_file=str(vocab_file), do_lower_case=True)
    cfg = BertConfig(
        vocab_size=len(vocab),
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=2,
        intermediate_size=64,
        max_position_embeddings=64,
    )
    model = FlaxBertModel(cfg, seed=0)
    return model, tokenizer


def test_identical_sentences_score_one(tiny_bert):
    model, tokenizer = tiny_bert
    sents = ["the cat sat on mat", "a dog ran fast"]
    out = bert_score(sents, sents, model=model, user_tokenizer=tokenizer, max_length=16)
    assert set(out) == {"precision", "recall", "f1"}
    np.testing.assert_allclose(np.asarray(out["f1"]), 1.0, atol=1e-4)
    np.testing.assert_allclose(np.asarray(out["precision"]), 1.0, atol=1e-4)


def test_different_sentences_score_below_one(tiny_bert):
    model, tokenizer = tiny_bert
    preds = ["the cat sat on mat", "a dog ran fast"]
    target = ["a dog ran slow", "the mat sat"]
    out = bert_score(preds, target, model=model, user_tokenizer=tokenizer, max_length=16)
    f1 = np.asarray(out["f1"])
    assert f1.shape == (2,)
    assert np.all(f1 < 1.0) and np.all(f1 > -1.0)


def test_idf_weighting_changes_score(tiny_bert):
    model, tokenizer = tiny_bert
    preds = ["the cat sat on mat", "the dog ran fast", "the cat ran"]
    target = ["the cat sat on the mat", "a dog ran slow", "a cat ran fast"]
    plain = bert_score(preds, target, model=model, user_tokenizer=tokenizer, max_length=16)
    idf = bert_score(preds, target, model=model, user_tokenizer=tokenizer, max_length=16, idf=True)
    assert not np.allclose(np.asarray(plain["f1"]), np.asarray(idf["f1"]))


def test_module_metric_with_real_model(tiny_bert):
    model, tokenizer = tiny_bert
    from metrics_tpu import BERTScore

    # the module API accepts a custom forward built on the real Flax model
    def forward(sentences):
        enc = tokenizer(sentences, padding="max_length", max_length=16, truncation=True, return_tensors="np")
        out = model(enc["input_ids"], enc["attention_mask"]).last_hidden_state
        return np.asarray(out), np.asarray(enc["attention_mask"])

    m = BERTScore(user_forward_fn=forward)
    m.update(["the cat sat"], ["the cat sat"])
    m.update(["a dog ran"], ["a dog ran fast"])
    out = m.compute()
    f1 = np.asarray(out["f1"])
    assert f1.shape == (2,)
    assert f1[0] == pytest.approx(1.0, abs=1e-4)
