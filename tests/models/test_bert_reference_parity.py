"""Golden BERTScore parity vs the mounted reference with SHARED weights.

A tiny BERT is initialized once in Flax, converted to a torch `BertModel`
with identical parameters, and both stacks score the same sentence pairs:
ours through `metrics_tpu.functional.text.bert.bert_score` (Flax forward),
the oracle through the reference's torch `bert_score`
(`/root/reference/src/torchmetrics/functional/text/bert.py`). Covers the
default path, `idf`, `num_layers`, `all_layers`, baseline rescaling, hash,
and the empty-input contract — the VERDICT #5 gate.
"""
from __future__ import annotations

import numpy as np
import pytest

transformers = pytest.importorskip("transformers")

from transformers import BertConfig, BertTokenizerFast, FlaxBertModel  # noqa: E402

from metrics_tpu.functional.text.bert import bert_score  # noqa: E402
from tests.helpers.reference_oracle import get_reference  # noqa: E402

_WORDS = ["the", "cat", "sat", "on", "mat", "a", "dog", "ran", "fast", "slow"]

# NOTE: token lengths ascend in lock-step (2 < 4 < 6 on both sides). The
# reference's functional path sorts preds and target EACH by their own length
# and never restores input order (`helper_embedding_metric.py:76-81,126-133`),
# which scrambles the pred↔target pairing when the two length orders differ —
# its module path opts out via sort_according_length=False (`text/bert.py:189`).
# We return scores in input order (see test_input_order_is_preserved), so the
# oracle comparison uses inputs where the reference's sort is the identity.
PREDS = ["the cat", "a dog ran fast", "the cat sat on mat slow"]
TARGET = ["the mat", "a dog ran slow", "a cat sat on the mat"]


@pytest.fixture(scope="module")
def stacks(tmp_path_factory):
    """(flax model, torch model with identical weights, tokenizer)."""
    reference = get_reference()
    if reference is None:
        pytest.skip("mounted reference unavailable")
    import torch
    from transformers import BertModel

    root = tmp_path_factory.mktemp("bert_parity")
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + _WORDS
    (root / "vocab.txt").write_text("\n".join(vocab))
    tokenizer = BertTokenizerFast(vocab_file=str(root / "vocab.txt"), do_lower_case=True)
    cfg = BertConfig(
        vocab_size=len(vocab),
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=2,
        intermediate_size=64,
        max_position_embeddings=64,
    )
    torch.manual_seed(3)
    torch_model = BertModel(cfg)
    torch_model.eval()
    torch_model.save_pretrained(str(root / "model"))
    flax_model = FlaxBertModel.from_pretrained(str(root / "model"), from_pt=True)
    return flax_model, torch_model, tokenizer


def _ours(stacks, **kwargs):
    flax_model, _, tokenizer = stacks
    return bert_score(PREDS, TARGET, model=flax_model, user_tokenizer=tokenizer, max_length=16, **kwargs)


def _theirs(stacks, **kwargs):
    _, torch_model, tokenizer = stacks
    from torchmetrics.functional.text.bert import bert_score as ref_bert_score

    return ref_bert_score(
        PREDS, TARGET, model=torch_model, user_tokenizer=tokenizer, max_length=16, num_threads=0, **kwargs
    )


def _assert_close(ours, theirs, atol=2e-4):
    assert set(ours) >= {"precision", "recall", "f1"}
    for key in ("precision", "recall", "f1"):
        np.testing.assert_allclose(np.asarray(ours[key]), np.asarray(theirs[key]), atol=atol, err_msg=key)


@pytest.mark.parametrize("idf", [False, True])
def test_default_layer_matches_reference(stacks, idf):
    _assert_close(_ours(stacks, idf=idf), _theirs(stacks, idf=idf))


def test_num_layers_matches_reference(stacks):
    _assert_close(_ours(stacks, num_layers=1), _theirs(stacks, num_layers=1))


@pytest.mark.parametrize("idf", [False, True])
def test_all_layers_matches_reference(stacks, idf):
    ours = _ours(stacks, all_layers=True, idf=idf)
    theirs = _theirs(stacks, all_layers=True, idf=idf)
    assert np.asarray(ours["f1"]).shape == (3, len(PREDS))  # embeddings + 2 layers
    _assert_close(ours, theirs)


@pytest.mark.parametrize("all_layers", [False, True])
def test_baseline_rescale_matches_reference(stacks, all_layers, tmp_path):
    baseline = tmp_path / "baseline.csv"
    rows = ["layer,P,R,F"] + [f"{i},{0.1 + 0.05 * i},{0.2 + 0.02 * i},{0.15 + 0.04 * i}" for i in range(3)]
    baseline.write_text("\n".join(rows))
    kwargs = dict(rescale_with_baseline=True, baseline_path=str(baseline), all_layers=all_layers)
    _assert_close(_ours(stacks, **kwargs), _theirs(stacks, **kwargs))


def test_return_hash_matches_reference(stacks):
    ours = _ours(stacks, return_hash=True)
    theirs = _theirs(stacks, return_hash=True)
    assert ours["hash"] == theirs["hash"]


def test_input_order_is_preserved(stacks):
    """Documented divergence: our scores come back in INPUT order even when
    sentence lengths are unsorted (the reference functional path returns them
    length-sorted, mis-pairing preds/targets whose length orders differ)."""
    flax_model, _, tokenizer = stacks
    preds = ["the cat sat on mat slow", "a dog ran fast", "the cat"]
    target = ["a cat sat on the mat", "a dog ran slow", "the mat"]
    out = bert_score(preds, target, model=flax_model, user_tokenizer=tokenizer, max_length=16)
    rev = bert_score(preds[::-1], target[::-1], model=flax_model, user_tokenizer=tokenizer, max_length=16)
    np.testing.assert_allclose(np.asarray(out["f1"]), np.asarray(rev["f1"])[::-1], atol=1e-6)


def test_empty_input_contract(stacks):
    flax_model, _, tokenizer = stacks
    out = bert_score([], [], model=flax_model, user_tokenizer=tokenizer)
    assert out == {"precision": [0.0], "recall": [0.0], "f1": [0.0]}


def test_num_layers_out_of_range_raises(stacks):
    flax_model, _, tokenizer = stacks
    with pytest.raises(ValueError, match="num_layers=7 is forbidden"):
        bert_score(PREDS, TARGET, model=flax_model, user_tokenizer=tokenizer, num_layers=7)


def test_baseline_layer_out_of_range_raises(stacks, tmp_path):
    baseline = tmp_path / "baseline.csv"
    baseline.write_text("layer,P,R,F\n0,0.1,0.1,0.1\n1,0.1,0.1,0.1")
    with pytest.raises(ValueError, match="out of range for the baseline"):
        _ours(stacks, rescale_with_baseline=True, baseline_path=str(baseline), num_layers=2)


def test_matcher_batching_is_invariant(stacks):
    """Pair-batched matching (HBM guard) must not change any score."""
    small = _ours(stacks, batch_size=1)
    big = _ours(stacks, batch_size=64)
    for key in ("precision", "recall", "f1"):
        np.testing.assert_allclose(np.asarray(small[key]), np.asarray(big[key]), atol=1e-6)
