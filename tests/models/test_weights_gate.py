"""The real-weights load gate: manifest validation + allow_random_weights.

VERDICT r2 #6: default random-init on the model-backed metrics must RAISE
(a warning is too quiet for metrics whose numbers are meaningless without
real weights), and any user-supplied parameter set must be validated against
the model's manifest with actionable errors.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu.models.inception import InceptionV3Extractor
from metrics_tpu.models.lpips import LPIPSExtractor
from metrics_tpu.models.manifest import expected_manifest, validate_params


@pytest.mark.parametrize(
    "ctor",
    [
        lambda **kw: mt.image.FrechetInceptionDistance(feature=64, **kw),
        lambda **kw: mt.image.KernelInceptionDistance(feature=64, subsets=2, subset_size=4, **kw),
        lambda **kw: mt.image.InceptionScore(feature=64, **kw),
        lambda **kw: mt.image.LearnedPerceptualImagePatchSimilarity(net_type="squeeze", **kw),
    ],
    ids=["FID", "KID", "IS", "LPIPS"],
)
def test_default_construction_raises_without_weights(ctor):
    with pytest.raises(RuntimeError, match="allow_random_weights"):
        ctor()
    with pytest.warns(UserWarning, match="NOT comparable"):
        ctor(allow_random_weights=True)


def test_callable_feature_needs_no_waiver():
    """A user-supplied extractor callable carries its own weights story."""
    fid = mt.image.FrechetInceptionDistance(feature=lambda x: jnp.asarray(x).reshape(x.shape[0], -1)[:, :4])
    assert fid is not None


class TestManifest:
    def test_correct_params_pass(self):
        model = LPIPSExtractor(net_type="squeeze").model
        dummy = jnp.zeros((1, 64, 64, 3), jnp.float32)
        params = model.init(jax.random.PRNGKey(0), dummy, dummy)
        validate_params(params, model, (dummy, dummy), "converter")  # no raise

    def test_missing_key_reported(self):
        ex = LPIPSExtractor(net_type="squeeze")
        params = jax.tree.map(lambda x: x, ex.params)
        removed = next(iter(params["params"]))
        del params["params"][removed]
        dummy = jnp.zeros((1, 64, 64, 3), jnp.float32)
        with pytest.raises(ValueError, match="missing"):
            validate_params(params, ex.model, (dummy, dummy), "converter")

    def test_shape_mismatch_reported_with_both_shapes(self):
        ex = LPIPSExtractor(net_type="squeeze")
        bad = jax.tree.map(lambda x: jnp.zeros(tuple(s + 1 for s in x.shape), x.dtype), ex.params)
        dummy = jnp.zeros((1, 64, 64, 3), jnp.float32)
        with pytest.raises(ValueError, match="shape mismatch"):
            validate_params(bad, ex.model, (dummy, dummy), "converter")

    def test_extra_key_reported(self):
        ex = LPIPSExtractor(net_type="squeeze")
        params = jax.tree.map(lambda x: x, ex.params)
        params["params"]["not_a_real_layer"] = {"kernel": jnp.zeros((1,))}
        dummy = jnp.zeros((1, 64, 64, 3), jnp.float32)
        with pytest.raises(ValueError, match="unexpected"):
            validate_params(params, ex.model, (dummy, dummy), "converter")

    def test_error_names_converter_command(self):
        ex = LPIPSExtractor(net_type="squeeze")
        dummy = jnp.zeros((1, 64, 64, 3), jnp.float32)
        with pytest.raises(ValueError, match="convert_it_cmd"):
            validate_params({"params": {}}, ex.model, (dummy, dummy), "convert_it_cmd")

    def test_extractor_validates_supplied_params(self):
        """A wrong pytree passed straight to the extractor is rejected at
        construction, before any image is scored."""
        with pytest.raises(ValueError, match="manifest"):
            LPIPSExtractor(net_type="squeeze", params={"params": {"junk": jnp.zeros((3,))}})

    def test_npz_roundtrip_passes_manifest(self, tmp_path):
        """Saving a valid param tree to flat npz and reloading it must pass
        the gate (the converter's output format)."""
        from metrics_tpu.models.inception import params_from_npz

        ex = LPIPSExtractor(net_type="squeeze")
        flat = {}

        def walk(node, prefix=""):
            for k, v in node.items():
                key = f"{prefix}/{k}" if prefix else str(k)
                if isinstance(v, dict):
                    walk(v, key)
                else:
                    flat[key] = np.asarray(v)

        walk(ex.params)
        path = tmp_path / "weights.npz"
        np.savez(path, **flat)
        reloaded = LPIPSExtractor(net_type="squeeze", npz_path=str(path))
        np.testing.assert_allclose(
            np.asarray(jax.tree.leaves(reloaded.params)[0]),
            np.asarray(jax.tree.leaves(ex.params)[0]),
        )

    def test_inception_manifest_nonempty(self):
        ex = InceptionV3Extractor(feature="64")
        man = expected_manifest(ex.model, jnp.zeros((1, 299, 299, 3), jnp.float32))
        assert len(man) > 100  # the full InceptionV3 tree
        assert any("conv" in k for k in man)


def test_invalid_net_type_beats_weights_gate():
    """An invalid backbone must get the ValueError naming valid choices, not
    a converter hint embedding the bogus name (review regression)."""
    with pytest.raises(ValueError, match="net_type"):
        mt.image.LearnedPerceptualImagePatchSimilarity(net_type="resnet")


def test_params_and_npz_path_conflict_raises(tmp_path):
    path = tmp_path / "w.npz"
    np.savez(path, **{"params/x": np.zeros(1)})
    with pytest.raises(ValueError, match="not both"):
        LPIPSExtractor(net_type="squeeze", params={"params": {}}, npz_path=str(path))
    with pytest.raises(ValueError, match="not both"):
        InceptionV3Extractor(feature="64", params={"params": {}}, npz_path=str(path))


class TestExtractorPickle:
    """Model-backed metrics checkpoint via pickle like any other metric —
    the jitted-apply partial is dropped and rebuilt across the round trip,
    and a lazy (not-yet-initialized) random-weights extractor stays lazy."""

    def test_fid_pickles_while_lazy(self):
        import pickle

        with pytest.warns(UserWarning, match="NOT comparable"):
            fid = mt.FrechetInceptionDistance(feature=64, allow_random_weights=True)
        clone = pickle.loads(pickle.dumps(fid))  # params still lazy: tiny payload
        assert clone.inception._params is None
        assert callable(clone.inception._forward)  # rebuilt on load

    @pytest.mark.slow  # materializes the full InceptionV3 random init (~40s on one core)
    def test_fid_pickles_after_first_use(self):
        import pickle

        with pytest.warns(UserWarning, match="NOT comparable"):
            fid = mt.FrechetInceptionDistance(feature=64, allow_random_weights=True)
        imgs = np.random.RandomState(0).randint(0, 255, (2, 3, 32, 32), dtype=np.uint8)
        fid.update(imgs, real=True)
        fid.update(imgs, real=False)
        again = pickle.loads(pickle.dumps(fid))  # now with materialized params
        assert again.inception._params is not None
        assert float(again.compute()) == pytest.approx(0.0, abs=1e-3)

    def test_lpips_extractor_pickle_round_trip(self):
        import pickle

        ex = LPIPSExtractor(net_type="alex")
        clone = pickle.loads(pickle.dumps(ex))
        a = jnp.asarray(np.random.RandomState(1).rand(1, 3, 64, 64).astype(np.float32) * 2 - 1)
        np.testing.assert_allclose(np.asarray(clone(a, a)), np.asarray(ex(a, a)), atol=1e-6)
