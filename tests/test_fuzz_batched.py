"""Fuzz-parity for the batched-step API (`update_many` / `forward_many`).

Randomized chunk lengths, batch shapes, dtypes, kwarg styles (python scalar
vs 0-d array), interleavings of single-step `forward` with chunks, and
mid-stream hyperparameter mutation — every draw must agree exactly with the
sequential eager oracle on the identical data. The structured contract
tests pin the designed cases; this bank hunts the unplanned interactions.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu.utils import checks

pytestmark = pytest.mark.slow  # deep-coverage tier (see docs/testing.md)

N_DRAWS = 12


@pytest.fixture(autouse=True)
def _first_mode():
    checks.set_validation_mode("first")
    yield
    checks.set_validation_mode("first")


FACTORIES = [
    ("Accuracy", lambda: mt.Accuracy(), 2),
    ("MeanMetric", lambda: mt.MeanMetric(), 1),
    ("MSE", lambda: mt.MeanSquaredError(), 2),
    ("MaxMetric", lambda: mt.MaxMetric(), 1),
    ("F1", lambda: mt.F1Score(num_classes=1, average="macro"), 2),
    ("CalibrationError", lambda: mt.CalibrationError(), 2),
    ("CatMetric(eager)", lambda: mt.CatMetric(), 1),
]


def _args_for(rng, n_args, n_steps, batch):
    p = jnp.asarray(rng.rand(n_steps, batch).astype(np.float32))
    if n_args == 1:
        return (p,)
    return (p, jnp.asarray(rng.randint(0, 2, (n_steps, batch))))


@pytest.mark.parametrize("draw", range(N_DRAWS))
@pytest.mark.parametrize("name,factory,n_args", FACTORIES, ids=[f[0] for f in FACTORIES])
def test_random_chunk_schedule_matches_sequential(name, factory, n_args, draw):
    """A random schedule of forward_many / update_many / single forward calls
    with varying chunk lengths equals the flattened sequential run."""
    rng = np.random.RandomState(1000 + draw)
    batch = int(rng.randint(8, 48))
    chunked, sequential = factory(), factory()
    sequential._fused_forward_ok = False

    for _ in range(int(rng.randint(2, 5))):
        kind = rng.choice(["forward_many", "update_many", "forward"])
        if kind == "forward":
            args = _args_for(rng, n_args, 1, batch)
            single = tuple(a[0] for a in args)
            chunked(*single)
            sequential(*single)
        else:
            n_steps = int(rng.randint(1, 6))
            args = _args_for(rng, n_args, n_steps, batch)
            if kind == "forward_many":
                vals = chunked.forward_many(*args)
                seq_vals = [sequential(*tuple(a[i] for a in args)) for i in range(n_steps)]
                np.testing.assert_allclose(
                    np.asarray(vals), np.asarray(seq_vals), atol=1e-6, rtol=1e-5
                )
            else:
                chunked.update_many(*args)
                for i in range(n_steps):
                    sequential.update(*tuple(a[i] for a in args))
    np.testing.assert_allclose(
        np.asarray(chunked.compute()), np.asarray(sequential.compute()), atol=1e-6, rtol=1e-5
    )
    assert chunked._update_count == sequential._update_count


@pytest.mark.parametrize("draw", range(N_DRAWS))
def test_random_weighted_mean_chunks(draw):
    """MeanMetric with a weight argument drawn as: stacked array, 0-d array,
    or python scalar — all three must ride the chunk correctly."""
    rng = np.random.RandomState(2000 + draw)
    batch = int(rng.randint(4, 32))
    chunked, sequential = mt.MeanMetric(), mt.MeanMetric()
    sequential._fused_forward_ok = False
    for _ in range(int(rng.randint(2, 4))):
        n_steps = int(rng.randint(1, 5))
        v = jnp.asarray(rng.rand(n_steps, batch).astype(np.float32))
        style = rng.choice(["stacked", "zero_d", "scalar", "none"])
        if style == "stacked":
            w = jnp.asarray(rng.rand(n_steps, batch).astype(np.float32) + 0.1)
            chunked.forward_many(v, weight=w)
            for i in range(n_steps):
                sequential(v[i], weight=w[i])
        elif style == "zero_d":
            w0 = jnp.asarray(float(rng.rand() + 0.1))
            chunked.forward_many(v, weight=w0)
            for i in range(n_steps):
                sequential(v[i], weight=w0)
        elif style == "scalar":
            ws = float(rng.rand() + 0.1)
            chunked.forward_many(v, weight=ws)
            for i in range(n_steps):
                sequential(v[i], weight=ws)
        else:
            chunked.forward_many(v)
            for i in range(n_steps):
                sequential(v[i])
    np.testing.assert_allclose(
        float(chunked.compute()), float(sequential.compute()), atol=1e-5, rtol=1e-5
    )


@pytest.mark.parametrize("draw", range(6))
def test_random_suite_chunks(draw):
    """MetricCollection chunks with random member sets and chunk lengths."""
    rng = np.random.RandomState(3000 + draw)
    batch = int(rng.randint(8, 32))
    pool = {
        "acc": lambda: mt.Accuracy(num_classes=1, average="macro"),
        "f1": lambda: mt.F1Score(num_classes=1, average="macro"),
        "mean": lambda: mt.MeanMetric(),
        "mse": lambda: mt.MeanSquaredError(),
    }
    names = sorted(rng.choice(sorted(pool), size=int(rng.randint(2, 4)), replace=False))
    chunked = mt.MetricCollection({n: pool[n]() for n in names})
    sequential = mt.MetricCollection({n: pool[n]() for n in names})
    sequential._fused_disabled = True
    for _, m in sequential.items(keep_base=True, copy_state=False):
        m._fused_forward_ok = False  # the oracle must be the EAGER path
    for _ in range(int(rng.randint(2, 4))):
        n_steps = int(rng.randint(1, 5))
        p = jnp.asarray(rng.rand(n_steps, batch).astype(np.float32))
        t = jnp.asarray(rng.randint(0, 2, (n_steps, batch)))
        vals = chunked.forward_many(p, t)
        for i in range(n_steps):
            seq_vals = sequential(p[i], t[i])
        for k in seq_vals:
            np.testing.assert_allclose(
                float(np.asarray(vals[k])[-1]), float(seq_vals[k]), atol=1e-6, rtol=1e-5
            )
    got, want = chunked.compute(), sequential.compute()
    for k in want:
        np.testing.assert_allclose(float(got[k]), float(want[k]), atol=1e-6, rtol=1e-5)


def test_mutation_mid_schedule_takes_effect():
    """Hyperparameter mutation between chunks must apply to later chunks and
    never be reverted by template write-back."""
    rng = np.random.RandomState(7)
    p = jnp.asarray(rng.rand(4, 16).astype(np.float32))
    t = jnp.asarray(rng.randint(0, 2, (4, 16)))
    chunked, sequential = mt.Accuracy(), mt.Accuracy()
    sequential._fused_forward_ok = False
    chunked.update_many(p, t)
    chunked.update_many(p, t)
    for _ in range(2):
        for i in range(4):
            sequential.update(p[i], t[i])
    chunked.threshold = 0.9
    sequential.threshold = 0.9
    chunked.update_many(p, t)
    for i in range(4):
        sequential.update(p[i], t[i])
    assert chunked.threshold == 0.9
    np.testing.assert_allclose(
        float(chunked.compute()), float(sequential.compute()), atol=1e-6
    )
