"""Fuzz-parity wave 3: binned curve variants, calibration norms, text scores.

Covers the families waves 1-2 skipped: the O(1)-state binned curve metrics
(the blessed jit path), every CalibrationError norm, and the remaining text
metrics (SQuAD, Perplexity, SacreBLEU tokenizer draws, ROUGE variants).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
pytestmark = [pytest.mark.skipif(_ref is None, reason="reference mount unavailable"),
              pytest.mark.slow]  # deep-coverage tier (see docs/testing.md)

import metrics_tpu as mt  # noqa: E402

N_VARIATIONS = 3


def _close(a, b, atol=1e-5):
    flat_a = a if isinstance(a, (list, tuple)) else [a]
    flat_b = b if isinstance(b, (list, tuple)) else [b]
    assert len(flat_a) == len(flat_b)
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=atol, rtol=1e-4)


@pytest.mark.parametrize("seed", range(N_VARIATIONS))
@pytest.mark.parametrize("num_classes", [1, 4])
@pytest.mark.parametrize(
    "name,extra",
    [
        ("BinnedAveragePrecision", {}),
        ("BinnedPrecisionRecallCurve", {}),
        ("BinnedRecallAtFixedPrecision", {"min_precision": 0.4}),
    ],
)
def test_binned_curves_fuzz(name, extra, num_classes, seed):
    rng = np.random.RandomState(seed)
    thresholds = int(rng.choice([25, 50, 101]))
    n = int(rng.choice([64, 129]))
    if num_classes == 1:
        preds = rng.rand(n).astype(np.float32)
        target = (rng.rand(n) > 0.4).astype(np.int64)
    else:
        p = rng.rand(n, num_classes).astype(np.float32)
        preds = p / p.sum(1, keepdims=True)
        target = np.eye(num_classes, dtype=np.int64)[rng.randint(0, num_classes, n)]
    ours = getattr(mt, name)(num_classes=num_classes, thresholds=thresholds, **extra)
    ref = getattr(_ref, name)(num_classes=num_classes, thresholds=thresholds, **extra)
    for chunk in range(2):
        sl = slice(chunk * n // 2, (chunk + 1) * n // 2)
        ours.update(jnp.asarray(preds[sl]), jnp.asarray(target[sl]))
        ref.update(torch.tensor(preds[sl]), torch.tensor(target[sl]))
    a, b = ours.compute(), ref.compute()
    if name == "BinnedPrecisionRecallCurve":
        for x, y in zip(a, b):
            if isinstance(x, list):
                for xi, yi in zip(x, y):
                    _close(xi, yi.numpy())
            else:
                _close(x, y.numpy())
    else:
        _close(a, [t.numpy() for t in b] if isinstance(b, (list, tuple)) else b.numpy())


@pytest.mark.parametrize("seed", range(N_VARIATIONS))
@pytest.mark.parametrize("norm", ["l1", "l2", "max"])
def test_calibration_norms_fuzz(norm, seed):
    rng = np.random.RandomState(10 + seed)
    n_bins = int(rng.choice([10, 15, 20]))
    preds = rng.rand(128).astype(np.float32)
    target = (rng.rand(128) > 0.5).astype(np.int64)
    ours = mt.CalibrationError(n_bins=n_bins, norm=norm)
    ref = _ref.CalibrationError(n_bins=n_bins, norm=norm)
    ours.update(jnp.asarray(preds), jnp.asarray(target))
    ref.update(torch.tensor(preds), torch.tensor(target))
    _close(ours.compute(), ref.compute().numpy())


@pytest.mark.parametrize("seed", range(N_VARIATIONS))
def test_squad_fuzz(seed):
    rng = np.random.RandomState(20 + seed)
    answers = ["the cat", "a dog ran", "on the mat", "hello world"]
    preds, targets = [], []
    for i in range(int(rng.randint(2, 5))):
        ans = answers[rng.randint(0, len(answers))]
        guess = ans if rng.rand() > 0.5 else answers[rng.randint(0, len(answers))]
        preds.append({"prediction_text": guess, "id": str(i)})
        targets.append({"answers": {"answer_start": [0], "text": [ans]}, "id": str(i)})
    ours, ref = mt.SQuAD(), _ref.SQuAD()
    ours.update(preds, targets)
    ref.update(preds, targets)
    a, b = ours.compute(), ref.compute()
    for k in ("exact_match", "f1"):
        np.testing.assert_allclose(float(a[k]), float(b[k]), atol=1e-5)


@pytest.mark.parametrize("seed", range(N_VARIATIONS))
def test_perplexity_fuzz(seed):
    rng = np.random.RandomState(30 + seed)
    b, s, v = 2, int(rng.choice([8, 17])), int(rng.choice([5, 11]))
    logits = rng.randn(b, s, v).astype(np.float32)
    target = rng.randint(0, v, (b, s))
    ignore = None if rng.rand() > 0.5 else 0
    ours = mt.Perplexity(ignore_index=ignore)
    ref = _ref.Perplexity(ignore_index=ignore)
    ours.update(jnp.asarray(logits), jnp.asarray(target))
    ref.update(torch.tensor(logits), torch.tensor(target))
    np.testing.assert_allclose(float(ours.compute()), float(ref.compute()), rtol=1e-4)


@pytest.mark.parametrize("tokenize", ["13a", "char", "none"])
def test_sacrebleu_tokenizers(tokenize):
    preds = ["the cat sat on the mat", "hello world this is a test"]
    targets = [["the cat sat on a mat"], ["hello world this was a test sentence"]]
    ours = mt.SacreBLEUScore(tokenize=tokenize)
    ref = _ref.SacreBLEUScore(tokenize=tokenize)
    ours.update(preds, targets)
    ref.update(preds, targets)
    np.testing.assert_allclose(float(ours.compute()), float(ref.compute()), atol=1e-5)


@pytest.mark.parametrize("use_stemmer", [False, True])
def test_rouge_variants(use_stemmer):
    from torchmetrics.text.rouge import ROUGEScore as RefROUGE

    from metrics_tpu.utils.imports import _NLTK_AVAILABLE

    if use_stemmer and not _NLTK_AVAILABLE:
        pytest.skip("nltk unavailable")
    preds = ["the cat sat on the mat", "dogs running fast"]
    targets = ["a cat sat on the mat", "the dog ran faster"]
    ours = mt.ROUGEScore(use_stemmer=use_stemmer)
    try:
        ref = RefROUGE(use_stemmer=use_stemmer)
        ref.update(preds, targets)
    except LookupError:
        pytest.skip("reference ROUGE needs nltk data unavailable offline")
    ours.update(preds, targets)
    a, b = ours.compute(), ref.compute()
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_allclose(float(a[k]), float(b[k]), atol=1e-5)
