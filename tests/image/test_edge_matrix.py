"""Constructed image corner cases vs the mounted reference.

Degenerate pictures built on purpose: identical pairs (perfect scores),
constant images (zero variance), inverted contrast, tiny spatial dims at
the SSIM kernel-size floor, kernel/sigma/data_range sweeps, and the
uniform-kernel variant — identical data through both stacks.
"""
from __future__ import annotations

import warnings

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
pytestmark = pytest.mark.skipif(_ref is None, reason="reference mount unavailable")

import metrics_tpu.functional as F  # noqa: E402

RNG = np.random.RandomState(29)
IMG = RNG.rand(2, 3, 32, 32).astype(np.float32)
NOISY = np.clip(IMG + 0.05 * RNG.randn(*IMG.shape), 0, 1).astype(np.float32)


def _close(ours, theirs, atol=1e-4):
    np.testing.assert_allclose(
        np.asarray(ours, np.float64), np.asarray(theirs.numpy(), np.float64), atol=atol, rtol=1e-4, equal_nan=True
    )


class TestPerfectAndDegenerate:
    def test_identical_images_ssim_is_one(self):
        ours = F.structural_similarity_index_measure(jnp.asarray(IMG), jnp.asarray(IMG), data_range=1.0)
        theirs = _ref.functional.structural_similarity_index_measure(
            torch.tensor(IMG), torch.tensor(IMG), data_range=1.0
        )
        _close(ours, theirs)
        assert float(np.asarray(ours)) == pytest.approx(1.0, abs=1e-5)

    def test_identical_images_psnr_is_inf(self):
        ours = F.peak_signal_noise_ratio(jnp.asarray(IMG), jnp.asarray(IMG), data_range=1.0)
        theirs = _ref.functional.peak_signal_noise_ratio(torch.tensor(IMG), torch.tensor(IMG), data_range=1.0)
        assert np.isinf(float(np.asarray(ours))) and np.isinf(float(theirs))

    def test_constant_images_ssim(self):
        """Zero variance on both sides: stabilizer constants decide the value."""
        a = np.full((1, 3, 16, 16), 0.5, dtype=np.float32)
        b = np.full((1, 3, 16, 16), 0.7, dtype=np.float32)
        ours = F.structural_similarity_index_measure(jnp.asarray(a), jnp.asarray(b), data_range=1.0)
        theirs = _ref.functional.structural_similarity_index_measure(
            torch.tensor(a), torch.tensor(b), data_range=1.0
        )
        # padded border windows accumulate in different orders; in this
        # stabilizer-dominated regime that skews the value by ~5e-4
        _close(ours, theirs, atol=1e-3)

    def test_inverted_contrast_uqi(self):
        inverted = (1.0 - IMG).astype(np.float32)
        ours = F.universal_image_quality_index(jnp.asarray(IMG), jnp.asarray(inverted))
        theirs = _ref.functional.universal_image_quality_index(torch.tensor(IMG), torch.tensor(inverted))
        _close(ours, theirs)

    def test_identical_images_sam_is_zero(self):
        ours = F.spectral_angle_mapper(jnp.asarray(IMG), jnp.asarray(IMG))
        theirs = _ref.functional.spectral_angle_mapper(torch.tensor(IMG), torch.tensor(IMG))
        _close(ours, theirs, atol=1e-3)


class TestSsimParamSweeps:
    @pytest.mark.parametrize("kernel_size", [3, 7, 11])
    def test_kernel_size(self, kernel_size):
        ours = F.structural_similarity_index_measure(
            jnp.asarray(IMG), jnp.asarray(NOISY), data_range=1.0, kernel_size=kernel_size
        )
        theirs = _ref.functional.structural_similarity_index_measure(
            torch.tensor(IMG), torch.tensor(NOISY), data_range=1.0, kernel_size=kernel_size
        )
        _close(ours, theirs)

    @pytest.mark.parametrize("sigma", [0.5, 1.5, 2.5])
    def test_sigma(self, sigma):
        ours = F.structural_similarity_index_measure(
            jnp.asarray(IMG), jnp.asarray(NOISY), data_range=1.0, sigma=sigma
        )
        theirs = _ref.functional.structural_similarity_index_measure(
            torch.tensor(IMG), torch.tensor(NOISY), data_range=1.0, sigma=sigma
        )
        _close(ours, theirs)

    def test_uniform_kernel(self):
        ours = F.structural_similarity_index_measure(
            jnp.asarray(IMG), jnp.asarray(NOISY), data_range=1.0, gaussian_kernel=False
        )
        theirs = _ref.functional.structural_similarity_index_measure(
            torch.tensor(IMG), torch.tensor(NOISY), data_range=1.0, gaussian_kernel=False
        )
        _close(ours, theirs)

    def test_minimal_spatial_dims(self):
        """Images exactly at the kernel footprint."""
        small = RNG.rand(1, 1, 11, 11).astype(np.float32)
        noisy = np.clip(small + 0.1 * RNG.randn(*small.shape), 0, 1).astype(np.float32)
        ours = F.structural_similarity_index_measure(jnp.asarray(small), jnp.asarray(noisy), data_range=1.0)
        theirs = _ref.functional.structural_similarity_index_measure(
            torch.tensor(small), torch.tensor(noisy), data_range=1.0
        )
        _close(ours, theirs)

    def test_return_full_image(self):
        ours = F.structural_similarity_index_measure(
            jnp.asarray(IMG), jnp.asarray(NOISY), data_range=1.0, return_full_image=True
        )
        theirs = _ref.functional.structural_similarity_index_measure(
            torch.tensor(IMG), torch.tensor(NOISY), data_range=1.0, return_full_image=True
        )
        _close(ours[0], theirs[0])
        np.testing.assert_allclose(
            np.asarray(ours[1], np.float64), theirs[1].numpy().astype(np.float64), atol=1e-4, rtol=1e-4
        )


class TestPsnrEdges:
    def test_data_range_inferred_from_data(self):
        scaled = (IMG * 37.0).astype(np.float32)
        noisy = (NOISY * 37.0).astype(np.float32)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            ours = F.peak_signal_noise_ratio(jnp.asarray(scaled), jnp.asarray(noisy), data_range=None)
            theirs = _ref.functional.peak_signal_noise_ratio(
                torch.tensor(scaled), torch.tensor(noisy), data_range=None
            )
        _close(ours, theirs)

    @pytest.mark.parametrize("reduction", ["elementwise_mean", "sum", "none"])
    def test_reduction_with_dim(self, reduction):
        ours = F.peak_signal_noise_ratio(
            jnp.asarray(IMG), jnp.asarray(NOISY), data_range=1.0, reduction=reduction, dim=(1, 2, 3)
        )
        theirs = _ref.functional.peak_signal_noise_ratio(
            torch.tensor(IMG), torch.tensor(NOISY), data_range=1.0, reduction=reduction, dim=(1, 2, 3)
        )
        _close(ours, theirs)

    def test_base_parametrization(self):
        ours = F.peak_signal_noise_ratio(jnp.asarray(IMG), jnp.asarray(NOISY), data_range=1.0, base=2.0)
        theirs = _ref.functional.peak_signal_noise_ratio(
            torch.tensor(IMG), torch.tensor(NOISY), data_range=1.0, base=2.0
        )
        _close(ours, theirs)


class TestSpectralEdges:
    @pytest.mark.parametrize("ratio", [2, 4])
    def test_ergas_ratio(self, ratio):
        ours = F.error_relative_global_dimensionless_synthesis(
            jnp.asarray(IMG), jnp.asarray(NOISY), ratio=ratio
        )
        theirs = _ref.functional.error_relative_global_dimensionless_synthesis(
            torch.tensor(IMG), torch.tensor(NOISY), ratio=ratio
        )
        _close(ours, theirs, atol=1e-3)

    @pytest.mark.parametrize("reduction", ["elementwise_mean", "sum", "none"])
    def test_sam_reductions(self, reduction):
        ours = F.spectral_angle_mapper(jnp.asarray(IMG), jnp.asarray(NOISY), reduction=reduction)
        theirs = _ref.functional.spectral_angle_mapper(
            torch.tensor(IMG), torch.tensor(NOISY), reduction=reduction
        )
        _close(ours, theirs, atol=1e-3)
