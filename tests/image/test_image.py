"""Image metrics — differential tests against the mounted reference implementation."""
from functools import partial

import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import (
    ErrorRelativeGlobalDimensionlessSynthesis,
    MultiScaleStructuralSimilarityIndexMeasure,
    PeakSignalNoiseRatio,
    SpectralAngleMapper,
    SpectralDistortionIndex,
    StructuralSimilarityIndexMeasure,
    UniversalImageQualityIndex,
)
from metrics_tpu.functional import (
    error_relative_global_dimensionless_synthesis,
    image_gradients,
    multiscale_structural_similarity_index_measure,
    peak_signal_noise_ratio,
    spectral_angle_mapper,
    spectral_distortion_index,
    structural_similarity_index_measure,
    universal_image_quality_index,
)
from tests.helpers.reference_oracle import get_reference
from tests.helpers.testers import NUM_BATCHES, MetricTester

_ref = get_reference()
needs_ref = pytest.mark.skipif(_ref is None, reason="reference implementation not importable")

_rng = np.random.RandomState(7)
# positive-valued images so ERGAS/MSLE-style ratios are well-behaved
_preds = jnp.asarray(_rng.rand(NUM_BATCHES, 4, 3, 32, 32).astype(np.float32)) * 0.8 + 0.1
_target = jnp.asarray(_rng.rand(NUM_BATCHES, 4, 3, 32, 32).astype(np.float32)) * 0.8 + 0.1
# MS-SSIM with kernel 11 and 5 betas needs height/width > 160
_preds_big = jnp.asarray(_rng.rand(NUM_BATCHES, 2, 1, 192, 192).astype(np.float32))
_target_big = jnp.asarray(_rng.rand(NUM_BATCHES, 2, 1, 192, 192).astype(np.float32))


def _torch(fn, **fixed):
    import torch

    def wrapped(preds, target):
        return fn(torch.from_numpy(np.asarray(preds)), torch.from_numpy(np.asarray(target)), **fixed).numpy()

    return wrapped


@needs_ref
class TestPSNR(MetricTester):
    atol = 1e-4

    def test_functional(self):
        self.run_functional_metric_test(
            _preds,
            _target,
            peak_signal_noise_ratio,
            _torch(_ref.functional.peak_signal_noise_ratio, data_range=1.0),
            metric_args={"data_range": 1.0},
        )

    def test_functional_data_range_from_data(self):
        self.run_functional_metric_test(
            _preds, _target, peak_signal_noise_ratio, _torch(_ref.functional.peak_signal_noise_ratio)
        )

    def test_functional_dim(self):
        self.run_functional_metric_test(
            _preds,
            _target,
            peak_signal_noise_ratio,
            _torch(_ref.functional.peak_signal_noise_ratio, data_range=1.0, dim=(1, 2, 3)),
            metric_args={"data_range": 1.0, "dim": (1, 2, 3)},
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_class(self, ddp):
        self.run_class_metric_test(
            _preds,
            _target,
            PeakSignalNoiseRatio,
            _torch(_ref.functional.peak_signal_noise_ratio, data_range=1.0),
            metric_args={"data_range": 1.0},
            ddp=ddp,
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_class_tracked_range(self, ddp):
        # data_range inferred from observed target min/max (incl. the 0.0 init quirk)
        def ref(preds, target):
            import torch

            p, t = torch.from_numpy(preds), torch.from_numpy(target)
            data_range = max(float(t.max()), 0.0) - min(float(t.min()), 0.0)
            return _ref.functional.peak_signal_noise_ratio(p, t, data_range=data_range).numpy()

        self.run_class_metric_test(
            _preds, _target, PeakSignalNoiseRatio, ref, ddp=ddp, check_batch=False, atol=1e-4
        )

    def test_spmd(self):
        self.run_spmd_test(
            _preds,
            _target,
            PeakSignalNoiseRatio,
            _torch(_ref.functional.peak_signal_noise_ratio, data_range=1.0),
            metric_args={"data_range": 1.0},
        )


@needs_ref
class TestSSIM(MetricTester):
    atol = 1e-4

    @pytest.mark.parametrize("gaussian_kernel", [True, False])
    def test_functional(self, gaussian_kernel):
        self.run_functional_metric_test(
            _preds,
            _target,
            structural_similarity_index_measure,
            _torch(
                _ref.functional.structural_similarity_index_measure,
                data_range=1.0,
                gaussian_kernel=gaussian_kernel,
            ),
            metric_args={"data_range": 1.0, "gaussian_kernel": gaussian_kernel},
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_class(self, ddp):
        self.run_class_metric_test(
            _preds,
            _target,
            StructuralSimilarityIndexMeasure,
            _torch(_ref.functional.structural_similarity_index_measure, data_range=1.0),
            metric_args={"data_range": 1.0},
            ddp=ddp,
        )

    def test_3d_volumes(self):
        preds = jnp.asarray(_rng.rand(2, 1, 8, 8, 8).astype(np.float32))
        target = jnp.asarray(_rng.rand(2, 1, 8, 8, 8).astype(np.float32))
        import torch

        ref = _ref.functional.structural_similarity_index_measure(
            torch.from_numpy(np.asarray(preds)), torch.from_numpy(np.asarray(target)), data_range=1.0
        ).numpy()
        got = structural_similarity_index_measure(preds, target, data_range=1.0)
        np.testing.assert_allclose(np.asarray(got), ref, atol=1e-4)


@needs_ref
class TestMSSSIM(MetricTester):
    atol = 1e-4

    @pytest.mark.slow
    def test_functional(self):
        self.run_functional_metric_test(
            _preds_big,
            _target_big,
            multiscale_structural_similarity_index_measure,
            _torch(_ref.functional.multiscale_structural_similarity_index_measure, data_range=1.0),
            metric_args={"data_range": 1.0},
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_class(self, ddp):
        self.run_class_metric_test(
            _preds_big,
            _target_big,
            MultiScaleStructuralSimilarityIndexMeasure,
            _torch(_ref.functional.multiscale_structural_similarity_index_measure, data_range=1.0),
            metric_args={"data_range": 1.0},
            ddp=ddp,
        )


@needs_ref
class TestUQI(MetricTester):
    atol = 1e-4

    def test_functional(self):
        self.run_functional_metric_test(
            _preds, _target, universal_image_quality_index, _torch(_ref.functional.universal_image_quality_index)
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_class(self, ddp):
        self.run_class_metric_test(
            _preds,
            _target,
            UniversalImageQualityIndex,
            _torch(_ref.functional.universal_image_quality_index),
            ddp=ddp,
        )


@needs_ref
class TestERGAS(MetricTester):
    atol = 1e-2  # relative magnitudes ~100; fp32 accumulation differences

    def test_functional(self):
        self.run_functional_metric_test(
            _preds,
            _target,
            error_relative_global_dimensionless_synthesis,
            _torch(_ref.functional.error_relative_global_dimensionless_synthesis),
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_class(self, ddp):
        self.run_class_metric_test(
            _preds,
            _target,
            ErrorRelativeGlobalDimensionlessSynthesis,
            _torch(_ref.functional.error_relative_global_dimensionless_synthesis),
            ddp=ddp,
        )


@needs_ref
class TestSAM(MetricTester):
    atol = 1e-4

    def test_functional(self):
        self.run_functional_metric_test(
            _preds, _target, spectral_angle_mapper, _torch(_ref.functional.spectral_angle_mapper)
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_class(self, ddp):
        self.run_class_metric_test(
            _preds, _target, SpectralAngleMapper, _torch(_ref.functional.spectral_angle_mapper), ddp=ddp
        )


@needs_ref
class TestDLambda(MetricTester):
    atol = 1e-4

    def test_functional(self):
        self.run_functional_metric_test(
            _preds, _target, spectral_distortion_index, _torch(_ref.functional.spectral_distortion_index)
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_class(self, ddp):
        self.run_class_metric_test(
            _preds, _target, SpectralDistortionIndex, _torch(_ref.functional.spectral_distortion_index), ddp=ddp
        )


@needs_ref
def test_image_gradients():
    import torch

    img = _rng.rand(2, 3, 16, 16).astype(np.float32)
    ref_dy, ref_dx = _ref.functional.image_gradients(torch.from_numpy(img))
    dy, dx = image_gradients(jnp.asarray(img))
    np.testing.assert_allclose(np.asarray(dy), ref_dy.numpy(), atol=1e-6)
    np.testing.assert_allclose(np.asarray(dx), ref_dx.numpy(), atol=1e-6)


def test_psnr_dim_requires_data_range():
    with pytest.raises(ValueError, match="data_range"):
        PeakSignalNoiseRatio(dim=1)


def test_ssim_invalid_ndim():
    with pytest.raises(ValueError, match="BxCxHxW"):
        structural_similarity_index_measure(jnp.zeros((4, 4)), jnp.zeros((4, 4)))
