"""FID / KID / InceptionScore / LPIPS — differential tests.

The reference classes accept a custom ``nn.Module`` feature extractor, which
sidesteps their torch-fidelity dependency: both sides see byte-identical
features, so the metric math (covariance + sqrtm, poly-MMD, KL splits) is
compared directly — ours on device vs the reference's scipy/torch path.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import (
    FrechetInceptionDistance,
    InceptionScore,
    KernelInceptionDistance,
    LearnedPerceptualImagePatchSimilarity,
)
from tests.helpers.reference_oracle import get_reference

_ref = get_reference()


def _import_ref_module(name):
    import importlib

    # the reference's sqrtm autograd Function uses np.float_ (removed in numpy 2.0)
    if not hasattr(np, "float_"):
        np.float_ = np.float64
    return importlib.import_module(f"torchmetrics.image.{name}")

needs_ref = pytest.mark.skipif(_ref is None, reason="reference implementation not importable")

_D = 16  # feature dim: keeps float32-vs-float64 sqrtm differences tiny


def _jax_flat_features(x):
    return jnp.asarray(x).reshape(x.shape[0], -1)[:, :_D]


def _torch_flat_module():
    import torch

    class Flat(torch.nn.Module):
        def forward(self, x):
            return x.reshape(x.shape[0], -1)[:, :_D]

    return Flat()


_rng = np.random.RandomState(42)
_real = _rng.rand(64, 1, 4, 4).astype(np.float32)
_fake = (_rng.rand(64, 1, 4, 4) * 0.8 + 0.2).astype(np.float32)


@needs_ref
class TestFID:
    def test_vs_reference(self):
        import torch

        fid = FrechetInceptionDistance(feature=_jax_flat_features)
        fid.update(jnp.asarray(_real), real=True)
        fid.update(jnp.asarray(_fake), real=False)
        got = float(fid.compute())

        ref_fid = _import_ref_module('fid').FrechetInceptionDistance(feature=_torch_flat_module())
        ref_fid.update(torch.from_numpy(_real), real=True)
        ref_fid.update(torch.from_numpy(_fake), real=False)
        expected = float(ref_fid.compute())
        assert got == pytest.approx(expected, rel=1e-3, abs=1e-4)

    def test_identical_distributions_near_zero(self):
        fid = FrechetInceptionDistance(feature=_jax_flat_features)
        fid.update(jnp.asarray(_real), real=True)
        fid.update(jnp.asarray(_real), real=False)
        assert float(fid.compute()) == pytest.approx(0.0, abs=1e-3)

    def test_reset_real_features(self):
        fid = FrechetInceptionDistance(feature=_jax_flat_features, reset_real_features=False)
        fid.update(jnp.asarray(_real), real=True)
        fid.update(jnp.asarray(_fake), real=False)
        v1 = float(fid.compute())
        fid.reset()
        assert len(fid.real_features) == 1 and len(fid.fake_features) == 0
        fid.update(jnp.asarray(_fake), real=False)
        assert float(fid.compute()) == pytest.approx(v1, rel=1e-5)

        fid2 = FrechetInceptionDistance(feature=_jax_flat_features, reset_real_features=True)
        fid2.update(jnp.asarray(_real), real=True)
        fid2.reset()
        assert len(fid2.real_features) == 0

    def test_invalid_feature(self):
        with pytest.raises(ValueError, match="feature"):
            FrechetInceptionDistance(feature=13)


@needs_ref
class TestKID:
    def test_vs_reference_full_subset(self):
        import torch

        # subset_size == n_samples makes the permutation irrelevant → exact parity
        kid = KernelInceptionDistance(feature=_jax_flat_features, subsets=3, subset_size=64)
        kid.update(jnp.asarray(_real), real=True)
        kid.update(jnp.asarray(_fake), real=False)
        got_mean, got_std = kid.compute()

        ref_kid = _import_ref_module('kid').KernelInceptionDistance(
            feature=_torch_flat_module(), subsets=3, subset_size=64
        )
        ref_kid.update(torch.from_numpy(_real), real=True)
        ref_kid.update(torch.from_numpy(_fake), real=False)
        ref_mean, ref_std = ref_kid.compute()
        assert float(got_mean) == pytest.approx(float(ref_mean), rel=1e-4, abs=1e-6)
        assert float(got_std) == pytest.approx(0.0, abs=1e-7)

    def test_subset_size_guard(self):
        kid = KernelInceptionDistance(feature=_jax_flat_features, subset_size=1000)
        kid.update(jnp.asarray(_real), real=True)
        kid.update(jnp.asarray(_fake), real=False)
        with pytest.raises(ValueError, match="subset_size"):
            kid.compute()

    def test_arg_validation(self):
        with pytest.raises(ValueError, match="subsets"):
            KernelInceptionDistance(feature=_jax_flat_features, subsets=0)
        with pytest.raises(ValueError, match="degree"):
            KernelInceptionDistance(feature=_jax_flat_features, degree=-1)


@needs_ref
class TestInceptionScore:
    def test_vs_reference_single_split(self):
        import torch

        iscore = InceptionScore(feature=_jax_flat_features, splits=1)
        iscore.update(jnp.asarray(_real))
        got_mean, _ = iscore.compute()

        ref_is = _import_ref_module('inception').InceptionScore(feature=_torch_flat_module(), splits=1)
        ref_is.update(torch.from_numpy(_real))
        ref_mean, _ = ref_is.compute()
        assert float(got_mean) == pytest.approx(float(ref_mean), rel=1e-4)

    def test_uniform_logits_give_score_one(self):
        iscore = InceptionScore(feature=lambda x: jnp.zeros((x.shape[0], 10)), splits=2)
        iscore.update(jnp.asarray(_real))
        mean, std = iscore.compute()
        assert float(mean) == pytest.approx(1.0, abs=1e-6)
        assert float(std) == pytest.approx(0.0, abs=1e-6)


@pytest.mark.slow
class TestLPIPS:
    def test_zero_for_identical(self):
        lpips = LearnedPerceptualImagePatchSimilarity(net_type="alex", allow_random_weights=True)
        img = jax.random.uniform(jax.random.PRNGKey(0), (2, 3, 64, 64))
        lpips.update(img, img)
        assert float(lpips.compute()) == pytest.approx(0.0, abs=1e-6)

    @pytest.mark.parametrize("net_type", ["alex", "vgg", "squeeze"])
    def test_backbones_run(self, net_type):
        lpips = LearnedPerceptualImagePatchSimilarity(net_type=net_type, allow_random_weights=True)
        img1 = jax.random.uniform(jax.random.PRNGKey(0), (2, 3, 64, 64))
        img2 = jax.random.uniform(jax.random.PRNGKey(1), (2, 3, 64, 64))
        val = lpips(img1, img2)
        assert float(val) >= 0

    def test_symmetry(self):
        lpips = LearnedPerceptualImagePatchSimilarity(net_type="alex", allow_random_weights=True)
        img1 = jax.random.uniform(jax.random.PRNGKey(0), (2, 3, 64, 64))
        img2 = jax.random.uniform(jax.random.PRNGKey(1), (2, 3, 64, 64))
        a = float(lpips(img1, img2))
        lpips.reset()
        b = float(lpips(img2, img1))
        assert a == pytest.approx(b, rel=1e-5)

    def test_sum_reduction_and_accumulation(self):
        lpips = LearnedPerceptualImagePatchSimilarity(net_type="alex", reduction="sum", allow_random_weights=True)
        img1 = jax.random.uniform(jax.random.PRNGKey(0), (2, 3, 64, 64))
        img2 = jax.random.uniform(jax.random.PRNGKey(1), (2, 3, 64, 64))
        lpips.update(img1, img2)
        lpips.update(img1, img2)
        total = float(lpips.compute())
        lpips2 = LearnedPerceptualImagePatchSimilarity(net_type="alex", allow_random_weights=True)
        lpips2.update(img1, img2)
        lpips2.update(img1, img2)
        assert total == pytest.approx(float(lpips2.compute()) * 4, rel=1e-5)

    def test_invalid_inputs(self):
        lpips = LearnedPerceptualImagePatchSimilarity(net_type="alex", allow_random_weights=True)
        with pytest.raises(ValueError, match="normalized"):
            lpips.update(jnp.ones((2, 3, 32, 32)) * 2.0, jnp.ones((2, 3, 32, 32)))
        with pytest.raises(ValueError, match="net_type"):
            LearnedPerceptualImagePatchSimilarity(net_type="resnet", allow_random_weights=True)
        with pytest.raises(ValueError, match="reduction"):
            LearnedPerceptualImagePatchSimilarity(reduction="max", allow_random_weights=True)


class TestInceptionV3Model:
    @pytest.mark.slow
    def test_feature_taps_and_dtypes(self):
        from metrics_tpu.models.inception import InceptionV3Extractor

        ex = InceptionV3Extractor(feature="64")
        imgs_u8 = np.random.RandomState(0).randint(0, 255, (2, 3, 32, 32), dtype=np.uint8)
        out = ex(jnp.asarray(imgs_u8))
        assert out.shape == (2, 64)
        out_f = ex(jnp.asarray(imgs_u8.astype(np.float32)))
        np.testing.assert_allclose(np.asarray(out), np.asarray(out_f), atol=1e-5)

    def test_invalid_feature(self):
        from metrics_tpu.models.inception import InceptionV3Extractor

        with pytest.raises(ValueError, match="feature"):
            InceptionV3Extractor(feature="1234")

    def test_logits_bias_relation(self):
        from metrics_tpu.models.inception import InceptionV3, InceptionV3Extractor

        ex = InceptionV3Extractor(feature="logits")
        imgs = jnp.asarray(np.random.RandomState(0).rand(1, 3, 32, 32).astype(np.float32))
        logits = ex(imgs)
        ex_unb = InceptionV3Extractor(feature="logits_unbiased")
        unb = ex_unb(imgs)
        bias = ex.params["params"]["fc_bias"]
        np.testing.assert_allclose(np.asarray(logits), np.asarray(unb + bias), atol=1e-5)


def test_fid_host_path_matches_device_path(monkeypatch):
    """The TPU host-LAPACK FID route must agree with the on-device f64 route
    (the backend picks between them; the value must not depend on it)."""
    import numpy as np

    import metrics_tpu.image.generative as G

    rng = np.random.RandomState(7)
    feat = lambda x: jnp.asarray(x).reshape(x.shape[0], -1)[:, :16]  # noqa: E731

    def build():
        fid = G.FrechetInceptionDistance(feature=feat)
        fid.update(jnp.asarray(rng.rand(32, 3, 4, 4).astype(np.float32)), real=True)
        fid.update(jnp.asarray(rng.rand(32, 3, 4, 4).astype(np.float32) + 0.3), real=False)
        return fid

    rng = np.random.RandomState(7)
    monkeypatch.setattr(G, "_native_f64_backend", lambda: True)
    device_val = float(build().compute())
    rng = np.random.RandomState(7)
    monkeypatch.setattr(G, "_native_f64_backend", lambda: False)
    host_val = float(build().compute())
    assert host_val == pytest.approx(device_val, rel=1e-5)
