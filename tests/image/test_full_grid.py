"""The full image option grid vs the mounted reference.

Enumerates the pure-statistics image metrics over their constructor spaces
(reference `tests/unittests/image/`, ~1.7k LoC: PSNR data_range x base x
dim/reduction, SSIM kernel x sigma x k1/k2 x gaussian/uniform, MS-SSIM betas
x normalize, UQI kernels, ERGAS ratios, SAM/D-lambda reductions) on seeded
streamed batches, every cell differentially checked against the reference on
identical data. Model-backed metrics (FID/KID/IS/LPIPS) have their own
weight-sharing golden tests under tests/models/.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tests.helpers import cell_seed as _cell_seed
from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
pytestmark = [pytest.mark.skipif(_ref is None, reason="reference mount unavailable"),
              pytest.mark.slow]  # deep-coverage tier (see docs/testing.md)

import metrics_tpu as mt  # noqa: E402

N_BATCHES = 2


def _make_batches(seed: int, shape=(2, 3, 24, 24), scale=1.0):
    rng = np.random.RandomState(seed)
    return [
        (rng.rand(*shape).astype(np.float32) * scale, rng.rand(*shape).astype(np.float32) * scale)
        for _ in range(N_BATCHES)
    ]


def _run_cell(name, kwargs, seed, shape=(2, 3, 24, 24), scale=1.0, atol=1e-4, ref_name=None):
    ours = getattr(mt, name)(**kwargs)
    ref = getattr(_ref, ref_name or name)(**kwargs)
    for preds, target in _make_batches(seed, shape, scale):
        ours.update(jnp.asarray(preds), jnp.asarray(target))
        ref.update(torch.tensor(preds), torch.tensor(target))
    np.testing.assert_allclose(np.asarray(ours.compute()), np.asarray(ref.compute()), atol=atol, rtol=1e-4)


class TestPsnrGrid:
    @pytest.mark.parametrize("data_range", (None, 1.0, 255.0))
    @pytest.mark.parametrize("base", (10.0, 2.0))
    def test_range_base(self, data_range, base):
        _run_cell(
            "PeakSignalNoiseRatio",
            {"data_range": data_range, "base": base},
            _cell_seed("psnr", data_range, base),
        )

    @pytest.mark.parametrize("reduction", ("elementwise_mean", "sum", "none"))
    def test_dim_reduction(self, reduction):
        _run_cell(
            "PeakSignalNoiseRatio",
            {"data_range": 1.0, "dim": (1, 2, 3), "reduction": reduction},
            _cell_seed("psnr-dim", reduction),
        )


class TestSsimGrid:
    @pytest.mark.parametrize("gaussian_kernel", (True, False))
    @pytest.mark.parametrize("kernel_size", (11, 7))
    @pytest.mark.parametrize("sigma", (1.5, 0.8))
    def test_kernels(self, gaussian_kernel, kernel_size, sigma):
        _run_cell(
            "StructuralSimilarityIndexMeasure",
            {"gaussian_kernel": gaussian_kernel, "kernel_size": kernel_size, "sigma": sigma, "data_range": 1.0},
            _cell_seed("ssim", gaussian_kernel, kernel_size, sigma),
        )

    @pytest.mark.parametrize("k1,k2", [(0.01, 0.03), (0.05, 0.1)])
    def test_stability_constants(self, k1, k2):
        _run_cell(
            "StructuralSimilarityIndexMeasure",
            {"k1": k1, "k2": k2, "data_range": 1.0},
            _cell_seed("ssim-k", k1, k2),
        )


class TestMsSsimGrid:
    SHAPE = (2, 3, 180, 180)  # >= (kernel-1)*2**4 per side for 5 scales

    @pytest.mark.parametrize("normalize", (None, "relu", "simple"))
    def test_normalize(self, normalize):
        _run_cell(
            "MultiScaleStructuralSimilarityIndexMeasure",
            {"normalize": normalize, "data_range": 1.0},
            _cell_seed("msssim", normalize),
            shape=self.SHAPE,
        )

    def test_custom_betas(self):
        _run_cell(
            "MultiScaleStructuralSimilarityIndexMeasure",
            {"betas": (0.3, 0.4, 0.3), "data_range": 1.0},
            _cell_seed("msssim-betas"),
            shape=(2, 3, 48, 48),
        )


class TestSpectralGrid:
    @pytest.mark.parametrize("kernel_size", ((11, 11), (5, 5)))
    def test_uqi(self, kernel_size):
        _run_cell(
            "UniversalImageQualityIndex", {"kernel_size": kernel_size}, _cell_seed("uqi", kernel_size)
        )

    @pytest.mark.parametrize("ratio", (2, 4))
    @pytest.mark.parametrize("reduction", ("elementwise_mean", "sum", "none"))
    def test_ergas(self, ratio, reduction):
        _run_cell(
            "ErrorRelativeGlobalDimensionlessSynthesis",
            {"ratio": ratio, "reduction": reduction},
            _cell_seed("ergas", ratio, reduction),
            scale=255.0,
            atol=1e-2,
        )

    @pytest.mark.parametrize("reduction", ("elementwise_mean", "sum", "none"))
    def test_sam(self, reduction):
        _run_cell(
            "SpectralAngleMapper", {"reduction": reduction}, _cell_seed("sam", reduction), atol=1e-5
        )

    @pytest.mark.parametrize("p", (1, 3))
    def test_d_lambda(self, p):
        _run_cell("SpectralDistortionIndex", {"p": p}, _cell_seed("dlambda", p), shape=(2, 3, 16, 16))
