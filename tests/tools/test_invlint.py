"""Invariant-linter contract tests.

Fixture pairs under ``tests/fixtures/invlint/`` carry ``# expect: RULE``
markers: every bad fixture must fire exactly the marked (line, rule) set,
every good fixture must be clean. On top of that: pragma and baseline
round-trips, the registry extraction vs the imported package, a seeded
violation against the REAL sync protocol (the acceptance criterion), and
the whole-tree run that ``make lint`` gates CI with.
"""
from __future__ import annotations

import json
import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.invlint import DEFAULT_BASELINE, DEFAULT_PATHS, RULES, registry  # noqa: E402
from tools.invlint.core import (  # noqa: E402
    BaselineError,
    load_baseline,
    run_paths,
    write_baseline,
)

FIXTURES = os.path.join(REPO, "tests", "fixtures", "invlint")
_EXPECT = re.compile(r"#\s*expect:\s*(INV\d{3}(?:\s*,\s*INV\d{3})*)")

BAD_FIXTURES = sorted(f for f in os.listdir(FIXTURES) if f.endswith("_bad.py"))
GOOD_FIXTURES = sorted(f for f in os.listdir(FIXTURES) if f.endswith("_good.py"))


def _expected(path):
    out = set()
    with open(path, encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, start=1):
            m = _EXPECT.search(line)
            if m:
                for rule in m.group(1).split(","):
                    out.add((lineno, rule.strip()))
    return out


def _findings(path, **kw):
    report = run_paths([path], **kw)
    assert not report["errors"], report["errors"]
    return report


class TestFixturePairs:
    def test_fixture_inventory(self):
        # one known-bad + one known-good file per pass
        assert BAD_FIXTURES == [
            "collective_bad.py",
            "funcore_bad.py",
            "hist_bad.py",
            "perfkeys_bad.py",
            "retry_bad.py",
            "taxonomy_bad.py",
            "telemetry_bad.py",
            "warn_bad.py",
        ]
        assert [f.replace("_good", "_bad") for f in GOOD_FIXTURES] == BAD_FIXTURES

    @pytest.mark.parametrize("name", BAD_FIXTURES)
    def test_bad_fixture_fires_at_expected_lines(self, name):
        path = os.path.join(FIXTURES, name)
        expected = _expected(path)
        assert expected, f"{name} carries no # expect markers"
        got = {(f.line, f.rule) for f in _findings(path)["findings"]}
        assert got == expected

    @pytest.mark.parametrize("name", GOOD_FIXTURES)
    def test_good_fixture_is_clean(self, name):
        report = _findings(os.path.join(FIXTURES, name))
        assert report["findings"] == []


class TestSuppression:
    def test_pragma_suppresses_and_requires_reason(self, tmp_path):
        src = tmp_path / "swallow.py"
        src.write_text(
            "def f(fn):\n"
            "    try:\n"
            "        return fn()\n"
            "    except Exception:  # invlint: allow(INV201) — probe: failure is the signal\n"
            "        return None\n"
        )
        report = _findings(str(src))
        assert report["findings"] == []
        assert report["pragma_suppressed"] == 1

        # a reasonless pragma does NOT suppress and is itself flagged
        src.write_text(
            "def f(fn):\n"
            "    try:\n"
            "        return fn()\n"
            "    except Exception:  # invlint: allow(INV201)\n"
            "        return None\n"
        )
        rules = sorted(f.rule for f in _findings(str(src))["findings"])
        assert rules == ["INV000", "INV201"]

    def test_pragma_on_preceding_line_suppresses(self, tmp_path):
        src = tmp_path / "warned.py"
        src.write_text(
            "import warnings\n"
            "def f(msg):\n"
            "    # invlint: allow(INV401) — deliberate direct warning in a fixture\n"
            "    warnings.warn(msg)\n"
        )
        assert _findings(str(src))["findings"] == []

    def test_prose_mentioning_pragma_syntax_is_ignored(self, tmp_path):
        src = tmp_path / "prose.py"
        src.write_text('MSG = "use `# invlint: allow(RULE) — <reason>` to suppress"\n')
        assert _findings(str(src))["findings"] == []


class TestBaseline:
    def test_roundtrip(self, tmp_path):
        bad = os.path.join(FIXTURES, "taxonomy_bad.py")
        first = _findings(bad)["findings"]
        assert first
        baseline_path = tmp_path / "baseline.json"
        write_baseline(str(baseline_path), first, reason="accepted for the round-trip test")
        entries = load_baseline(str(baseline_path))
        assert len(entries) == len(first)
        report = _findings(bad, baseline=entries)
        assert report["findings"] == []
        assert len(report["baselined"]) == len(first)
        assert report["stale_baseline"] == []

    def test_reason_is_required(self, tmp_path):
        baseline_path = tmp_path / "baseline.json"
        baseline_path.write_text(
            json.dumps(
                {"findings": [{"file": "x.py", "line": 1, "rule": "INV201", "reason": "  "}]}
            )
        )
        with pytest.raises(BaselineError, match="reason"):
            load_baseline(str(baseline_path))

    def test_unknown_rule_rejected(self, tmp_path):
        baseline_path = tmp_path / "baseline.json"
        baseline_path.write_text(
            json.dumps(
                {"findings": [{"file": "x.py", "line": 1, "rule": "INV999", "reason": "r"}]}
            )
        )
        with pytest.raises(BaselineError, match="unknown rule"):
            load_baseline(str(baseline_path))

    def test_shipped_baseline_loads_and_has_reasons(self):
        entries = load_baseline(os.path.join(REPO, DEFAULT_BASELINE))
        assert entries, "the shipped baseline must exist"
        assert all(str(e["reason"]).strip() for e in entries)


class TestRegistry:
    """The AST-extracted registries must equal the imported package's — the
    single-sourcing contract behind the linter, check_docs and fault_sweep."""

    def test_fault_sites_match_package(self):
        from metrics_tpu.ops import faults

        assert registry.fault_sites() == faults.FAULT_SITES

    def test_span_sites_match_package(self):
        from metrics_tpu.ops import telemetry

        assert registry.span_sites() == tuple(telemetry.SPAN_SITES)

    def test_counter_typing_matches_package(self):
        from metrics_tpu.ops import telemetry

        keys = [
            "sync_payload_collectives", "fault_sync", "journal_saves", "fleet_gathers",
            "sync_coalesce_ratio", "sync_health_epoch", "sync_phase_stats_sync_gather_count",
            "monotonic_step", "spans_retained", "world_size", "builds", "hits",
            "latency_stats_suite-sync_count", "latency_stats_suite-sync_p99_s",
            "slo_violations_total",
            # ISSUE-12 carve-outs: probe / analysis / report counters
            "device_probes", "program_analyses", "perf_reports",
            "programs_count",  # the ledger summary block stays a gauge
        ]
        for key in keys:
            assert registry.is_counter_key(key) == telemetry.is_counter_key(key), key

    def test_histogram_layout_matches_package(self):
        from metrics_tpu.ops import telemetry

        bounds, family, snapshot_key = registry.histogram_layout()
        assert bounds == telemetry._HIST_BOUNDS_S
        assert family == telemetry._HIST_FAMILY
        assert snapshot_key == telemetry._HIST_SNAPSHOT_KEY
        keys = [
            "latency_stats_suite-sync_buckets_1e-06",
            "latency_stats_suite-sync_count",
            "latency_stats_suite-sync_sum_s",
            "latency_stats_suite-sync_p95_s",  # percentile: NOT a sample key
            "sync_payload_collectives",
        ]
        for key in keys:
            assert registry.is_histogram_sample_key(key) == telemetry.is_histogram_sample_key(
                key
            ), key
        # every histogram SAMPLE must also be a counter — the fleet-merge
        # exactness contract INV303 pins statically
        assert telemetry.is_counter_key("latency_stats_suite-sync_buckets_+Inf")

    def test_device_dispatch_site_matches_package(self):
        from metrics_tpu.ops import telemetry

        assert registry.device_dispatch_site() == telemetry._DEVICE_HIST_SITE
        # the per-PROGRAM family keys are histogram samples (and counters)
        # just like the aggregate-site keys — the fleet merge sums them
        key = f"latency_stats_{telemetry._DEVICE_HIST_SITE}:metric-update:1a2b3c4d_count"
        assert registry.is_histogram_sample_key(key) and telemetry.is_counter_key(key)


class TestSeededViolation:
    """The acceptance criterion: deleting one ``note_collective`` epoch audit
    from the REAL per-state sync protocol must make the linter fire INV002
    with the correct rule id on the transport lines."""

    def test_stripped_epoch_audit_fires_inv002(self, tmp_path):
        src_path = os.path.join(REPO, "metrics_tpu", "parallel", "sync.py")
        with open(src_path, encoding="utf-8") as fh:
            source = fh.read()
        assert "note_collective(\"shape\", epoch=epoch)" in source
        seeded = source.replace(", epoch=epoch)", ")")
        target = tmp_path / "sync_seeded.py"
        target.write_text(seeded)
        findings = _findings(str(target))["findings"]
        rules = {f.rule for f in findings}
        assert rules == {"INV002"}
        # both multi-process transport slots (shape + payload exchange)
        # plus the single-process accounting slots lose their audit
        assert len(findings) >= 2

    def test_unfenced_retry_fires_inv101(self, tmp_path):
        target = tmp_path / "unfenced.py"
        target.write_text(
            "def proto(retry_with_backoff, run_with_deadline, gather):\n"
            "    def _attempt():\n"
            "        return run_with_deadline(lambda: gather())\n"
            "    return retry_with_backoff(_attempt, attempts=1, base_delay_s=0.0)\n"
        )
        findings = _findings(str(target))["findings"]
        assert [(f.line, f.rule) for f in findings] == [(2, "INV101")]


class TestRealTree:
    def test_default_paths_clean_with_shipped_baseline(self):
        """What ``make lint`` gates CI with: zero non-baselined findings."""
        baseline = load_baseline(os.path.join(REPO, DEFAULT_BASELINE))
        report = run_paths(list(DEFAULT_PATHS), baseline=baseline)
        assert report["errors"] == []
        assert report["findings"] == [], [f.render() for f in report["findings"]]
        assert report["stale_baseline"] == [], report["stale_baseline"]
        assert report["files"] > 100  # the whole package really was scanned

    def test_cli_exit_codes(self):
        env = dict(os.environ, PYTHONPATH=REPO)
        clean = subprocess.run(
            [sys.executable, "-m", "tools.invlint",
             os.path.join(FIXTURES, "collective_good.py"), "--no-baseline"],
            cwd=REPO, env=env, capture_output=True, text=True,
        )
        assert clean.returncode == 0, clean.stdout + clean.stderr
        dirty = subprocess.run(
            [sys.executable, "-m", "tools.invlint",
             os.path.join(FIXTURES, "collective_bad.py"), "--no-baseline"],
            cwd=REPO, env=env, capture_output=True, text=True,
        )
        assert dirty.returncode == 1
        assert "INV001" in dirty.stdout and "INV003" in dirty.stdout

    def test_rule_catalogue_documented(self):
        """Every rule id is documented in docs/robustness.md (the 'Enforced
        invariants' section) — a new rule without docs is a lint-the-linter
        failure."""
        with open(os.path.join(REPO, "docs", "robustness.md"), encoding="utf-8") as fh:
            text = fh.read()
        for rule in RULES:
            assert rule in text, f"{rule} missing from docs/robustness.md"
