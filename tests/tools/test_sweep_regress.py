"""Sweep regression gate: the ``--explain`` attribution contract (ISSUE 12).

A synthetically perturbed artifact — one row whose p50 gate fails because
the archived ``compile`` phase column exploded — must be attributed to that
phase by name; rows without phase columns must say so instead of guessing.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.sweep_regress import compare, explain  # noqa: E402


def _row(name, p50, phases=None, ratio=10.0):
    row = {
        "metric": name,
        "mode": "deferred",
        "updates_per_s": 100.0,
        "vs_baseline": ratio,
        "latency_ms": {"p50": p50, "p95": p50 * 1.5, "p99": p50 * 2.0},
    }
    if phases is not None:
        row["phases_ms"] = phases
    return row


OLD = {
    "rows": [
        _row("Accuracy", 1.0, {"enqueue": 2.0, "flush": 10.0, "compile": 0.0, "wire": 5.0}),
        _row("MeanMetric", 1.0),  # no phase columns archived
        _row("F1Score", 1.0, {"flush": 8.0, "dispatch": 1.0}),
    ]
}
NEW = {
    "rows": [
        # p50 blew past the 3x gate; the compile phase is what moved
        _row("Accuracy", 9.0, {"enqueue": 2.1, "flush": 11.0, "compile": 812.0, "wire": 5.2}),
        _row("MeanMetric", 9.0),
        _row("F1Score", 1.1, {"flush": 8.2, "dispatch": 1.0}),  # healthy
    ]
}


def test_explain_names_the_regressed_phase():
    problems = compare(OLD, NEW)
    assert any(p.startswith("Accuracy:") for p in problems)
    lines = explain(OLD, NEW, problems)
    acc = [ln for ln in lines if ln.startswith("Accuracy:")]
    assert len(acc) == 1
    assert "regressed phase: compile" in acc[0]
    assert "0.000->812.000" in acc[0]
    # the healthy row is not attributed at all
    assert not any(ln.startswith("F1Score:") for ln in lines)


def test_explain_reports_missing_phase_columns_explicitly():
    problems = compare(OLD, NEW)
    lines = explain(OLD, NEW, problems)
    mean = [ln for ln in lines if ln.startswith("MeanMetric:")]
    assert len(mean) == 1 and "no archived phase columns" in mean[0]


def test_cli_explain_prints_attribution_and_exits_one(tmp_path):
    a, b = tmp_path / "old.json", tmp_path / "new.json"
    a.write_text(json.dumps(OLD))
    b.write_text(json.dumps(NEW))
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "sweep_regress.py"),
         "--explain", str(a), str(b)],
        capture_output=True, text=True,
    )
    assert r.returncode == 1
    assert "attribution (--explain)" in r.stdout
    assert "regressed phase: compile" in r.stdout
    # without the flag the attribution section stays out
    r2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "sweep_regress.py"),
         str(a), str(b)],
        capture_output=True, text=True,
    )
    assert r2.returncode == 1
    assert "attribution" not in r2.stdout


def test_sync_rows_use_coalesced_phase_spelling():
    old = {"rows": [dict(_row("suite_sync(coalesced)", 1.0),
                         coalesced_phases_ms={"wire": 30.0, "pack": 2.0})]}
    new = {"rows": [dict(_row("suite_sync(coalesced)", 9.0),
                         coalesced_phases_ms={"wire": 300.0, "pack": 2.1})]}
    problems = compare(old, new)
    lines = explain(old, new, problems)
    assert lines and "regressed phase: wire" in lines[0]


def _ingest_row(shed, exact=True):
    return dict(
        _row("ingest_gateway(ingest)", 1.0),
        ingest_shed_fraction_2x=shed,
        accounting_exact=exact,
    )


def test_ingest_shed_ceiling_gate():
    old = {"rows": [_ingest_row(0.5)]}
    # shedding 80% at 2x overload: admissible load is being thrown away
    new = {"rows": [_ingest_row(0.8)]}
    problems = compare(old, new)
    assert any("ingest_shed_fraction_2x" in p for p in problems)
    # the excess fraction itself (0.5) passes the default 0.6 ceiling
    assert not compare(old, {"rows": [_ingest_row(0.5)]})
    # a raised ceiling admits the same row
    assert not compare(old, new, ingest_shed_ceiling=0.9)
    # an old artifact without the column still gates the new one
    bare_old = {"rows": [_row("ingest_gateway(ingest)", 1.0)]}
    problems = compare(bare_old, new)
    assert any("(unrecorded)" in p and "ingest_shed_fraction_2x" in p for p in problems)


def test_ingest_accounting_exact_is_a_hard_failure():
    old = {"rows": [_ingest_row(0.5)]}
    new = {"rows": [_ingest_row(0.5, exact=False)]}
    problems = compare(old, new)
    assert any("accounting_exact false" in p for p in problems)


def test_cli_accepts_ingest_shed_ceiling_flag(tmp_path):
    a, b = tmp_path / "old.json", tmp_path / "new.json"
    a.write_text(json.dumps({"rows": [_ingest_row(0.5)]}))
    b.write_text(json.dumps({"rows": [_ingest_row(0.8)]}))
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "sweep_regress.py"),
         "--ingest-shed-ceiling", "0.9", str(a), str(b)],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    r2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "sweep_regress.py"),
         str(a), str(b)],
        capture_output=True, text=True,
    )
    assert r2.returncode == 1
    assert "ingest_shed_fraction_2x" in r2.stdout
