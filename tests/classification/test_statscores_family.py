"""Stat-scores family vs sklearn oracles (Accuracy/Precision/Recall/F1/FBeta/Specificity/StatScores)."""
from functools import partial

import jax.numpy as jnp
import numpy as np
import pytest
import sklearn.metrics as skm

from metrics_tpu import Accuracy, F1Score, FBetaScore, Precision, Recall, Specificity, StatScores
from metrics_tpu.functional import (
    accuracy,
    f1_score,
    fbeta_score,
    precision,
    recall,
    specificity,
    stat_scores,
)
from tests.classification.inputs import (
    _binary,
    _binary_prob,
    _multiclass,
    _multiclass_prob,
    _multilabel_prob,
)
from tests.helpers.testers import NUM_CLASSES, THRESHOLD, MetricTester


def _sk_accuracy_binary_prob(preds, target):
    return skm.accuracy_score(target, (preds >= THRESHOLD).astype(int))


def _sk_accuracy_mc(preds, target):
    if preds.ndim > target.ndim:
        preds = preds.argmax(-1)
    return skm.accuracy_score(target, preds)


class TestAccuracy(MetricTester):
    @pytest.mark.parametrize(
        "preds, target, sk_fn",
        [
            (_binary_prob.preds, _binary_prob.target, _sk_accuracy_binary_prob),
            (_binary.preds, _binary.target, _sk_accuracy_mc),
            (_multiclass.preds, _multiclass.target, _sk_accuracy_mc),
            (_multiclass_prob.preds, _multiclass_prob.target, _sk_accuracy_mc),
        ],
    )
    @pytest.mark.parametrize("ddp", [False, True])
    def test_accuracy_class(self, preds, target, sk_fn, ddp):
        self.run_class_metric_test(preds, target, Accuracy, sk_fn, ddp=ddp, check_batch=not ddp)

    def test_accuracy_functional(self):
        self.run_functional_metric_test(
            _multiclass.preds, _multiclass.target, accuracy, _sk_accuracy_mc
        )

    def test_accuracy_jit(self):
        self.run_jit_test(_multiclass.preds, _multiclass.target, accuracy, metric_args={"num_classes": NUM_CLASSES})

    def test_accuracy_spmd(self):
        # num_classes must be static under shard_map tracing (one-hot width)
        self.run_spmd_test(
            _multiclass.preds,
            _multiclass.target,
            Accuracy,
            _sk_accuracy_mc,
            metric_args={"num_classes": NUM_CLASSES},
        )

    def test_accuracy_top_k(self):
        p, t = _multiclass_prob.preds[0], _multiclass_prob.target[0]
        res = accuracy(p, t, top_k=2)
        ref = skm.top_k_accuracy_score(np.asarray(t), np.asarray(p), k=2, labels=range(NUM_CLASSES))
        np.testing.assert_allclose(np.asarray(res), ref, atol=1e-6)

    def test_subset_accuracy_multilabel(self):
        p, t = _multilabel_prob.preds[0], _multilabel_prob.target[0]
        res = accuracy(p, t, subset_accuracy=True)
        ref = skm.accuracy_score(np.asarray(t), (np.asarray(p) >= THRESHOLD).astype(int))
        np.testing.assert_allclose(np.asarray(res), ref, atol=1e-6)


@pytest.mark.parametrize(
    "metric_class, metric_fn, sk_fn",
    [
        (Precision, precision, skm.precision_score),
        (Recall, recall, skm.recall_score),
        (F1Score, f1_score, skm.f1_score),
        (partial(FBetaScore, beta=2.0), partial(fbeta_score, beta=2.0), partial(skm.fbeta_score, beta=2.0)),
    ],
)
@pytest.mark.parametrize("average", ["micro", "macro", "weighted", "none"])
class TestPrecisionRecallF(MetricTester):
    def test_multiclass_class(self, metric_class, metric_fn, sk_fn, average):
        sk_average = None if average == "none" else average
        self.run_class_metric_test(
            _multiclass.preds,
            _multiclass.target,
            metric_class,
            lambda p, t: sk_fn(t, p, average=sk_average, labels=range(NUM_CLASSES), zero_division=0),
            metric_args={"average": average, "num_classes": NUM_CLASSES},
            check_batch=False,
        )

    def test_multiclass_functional(self, metric_class, metric_fn, sk_fn, average):
        sk_average = None if average == "none" else average
        self.run_functional_metric_test(
            _multiclass.preds,
            _multiclass.target,
            metric_fn,
            lambda p, t: sk_fn(t, p, average=sk_average, labels=range(NUM_CLASSES), zero_division=0),
            metric_args={"average": average, "num_classes": NUM_CLASSES},
        )

    def test_multiclass_prob_ddp(self, metric_class, metric_fn, sk_fn, average):
        sk_average = None if average == "none" else average
        self.run_class_metric_test(
            _multiclass_prob.preds,
            _multiclass_prob.target,
            metric_class,
            lambda p, t: sk_fn(t, p.argmax(-1), average=sk_average, labels=range(NUM_CLASSES), zero_division=0),
            metric_args={"average": average, "num_classes": NUM_CLASSES},
            ddp=True,
        )


class TestSpecificity(MetricTester):
    @pytest.mark.parametrize("ddp", [False, True])
    def test_specificity_binary(self, ddp):
        def sk_specificity(preds, target):
            tn, fp, fn, tp = skm.confusion_matrix(target, (preds >= THRESHOLD).astype(int), labels=[0, 1]).ravel()
            return tn / (tn + fp)

        self.run_class_metric_test(
            _binary_prob.preds, _binary_prob.target, Specificity, sk_specificity, ddp=ddp, check_batch=False
        )

    def test_specificity_functional_macro(self):
        def sk_specificity_macro(preds, target):
            cm = skm.confusion_matrix(target, preds, labels=range(NUM_CLASSES))
            res = []
            for c in range(NUM_CLASSES):
                tp = cm[c, c]
                fp = cm[:, c].sum() - tp
                fn = cm[c, :].sum() - tp
                tn = cm.sum() - tp - fp - fn
                res.append(tn / (tn + fp))
            return np.mean(res)

        self.run_functional_metric_test(
            _multiclass.preds,
            _multiclass.target,
            specificity,
            sk_specificity_macro,
            metric_args={"average": "macro", "num_classes": NUM_CLASSES},
        )


class TestStatScores(MetricTester):
    def test_stat_scores_micro(self):
        def sk_stats(preds, target):
            cm = skm.confusion_matrix(target, preds, labels=range(NUM_CLASSES))
            tp = np.diag(cm).sum()
            fp = cm.sum(0).sum() - np.diag(cm).sum()
            fn = cm.sum(1).sum() - np.diag(cm).sum()
            tn = NUM_CLASSES * cm.sum() - (cm.sum() * 2 - tp) - cm.sum() + tp
            # elementwise over one-hot: tn = N*C - tp - fp - fn
            n = target.shape[0]
            tn = n * NUM_CLASSES - tp - fp - fn
            return np.array([tp, fp, tn, fn, tp + fn])

        self.run_functional_metric_test(_multiclass.preds, _multiclass.target, stat_scores, sk_stats)

    @pytest.mark.parametrize("ddp", [False, True])
    def test_stat_scores_class_macro(self, ddp):
        def sk_stats_macro(preds, target):
            cm = skm.confusion_matrix(target, preds, labels=range(NUM_CLASSES))
            out = []
            n = target.shape[0]
            for c in range(NUM_CLASSES):
                tp = cm[c, c]
                fp = cm[:, c].sum() - tp
                fn = cm[c, :].sum() - tp
                tn = n - tp - fp - fn
                out.append([tp, fp, tn, fn, tp + fn])
            return np.array(out)

        self.run_class_metric_test(
            _multiclass.preds,
            _multiclass.target,
            StatScores,
            sk_stats_macro,
            metric_args={"reduce": "macro", "num_classes": NUM_CLASSES},
            ddp=ddp,
            check_batch=False,
        )

    def test_stat_scores_jit(self):
        self.run_jit_test(
            _multiclass.preds,
            _multiclass.target,
            stat_scores,
            metric_args={"reduce": "macro", "num_classes": NUM_CLASSES},
        )

    def test_ignore_index(self):
        """ignore_index masks the class column exactly like reference deletion."""
        preds = jnp.asarray([1, 0, 2, 1])
        target = jnp.asarray([1, 1, 2, 0])
        res = stat_scores(preds, target, reduce="micro", num_classes=3, ignore_index=0)
        np.testing.assert_array_equal(np.asarray(res), [2, 1, 4, 1, 3])
        res_macro = stat_scores(preds, target, reduce="macro", num_classes=3, ignore_index=0)
        assert (np.asarray(res_macro)[0] == -1).all()


def test_differentiability_of_probs_path():
    """Stat-scores are not differentiable (thresholding), but must not crash under grad of inputs."""
    t = MetricTester()
    # hinge is differentiable; quick check via accuracy of probabilities is skipped
    from metrics_tpu.functional import hinge_loss

    t.run_differentiability_test(
        jnp.asarray(np.random.RandomState(0).randn(2, 8).astype(np.float32)),
        jnp.asarray(np.random.RandomState(1).randint(0, 2, (2, 8))),
        hinge_loss,
    )
