"""Exhaustive differential tests vs the mounted reference implementation.

The analogue of the reference's per-metric parametrized matrices
(`tests/unittests/classification/test_{accuracy,precision_recall,...}.py`):
every (metric x input-type x average x mdmc x ignore_index x top_k) cell is
checked against the reference running the identical inputs on torch/CPU.
"""
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tests.classification.inputs import (
    _binary,
    _binary_prob,
    _multiclass,
    _multiclass_prob,
    _multidim_multiclass,
    _multilabel,
    _multilabel_prob,
)
from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
pytestmark = pytest.mark.skipif(_ref is None, reason="reference mount unavailable")

import metrics_tpu as mt  # noqa: E402

_INPUT_CASES = {
    "binary_prob": _binary_prob,
    "binary": _binary,
    "multiclass_prob": _multiclass_prob,
    "multiclass": _multiclass,
    "multilabel_prob": _multilabel_prob,
    "multilabel": _multilabel,
    "mdmc": _multidim_multiclass,
}

_STAT_METRICS = ["Accuracy", "Precision", "Recall", "F1Score", "Specificity"]


def _to_torch(x):
    return torch.tensor(np.asarray(x))


def _run_pair(name_ours, name_ref, inputs, our_kwargs, ref_kwargs=None, atol=1e-6):
    """Stream all batches through both implementations; compare every compute."""
    ref_kwargs = ref_kwargs if ref_kwargs is not None else our_kwargs
    ours = getattr(mt, name_ours)(**our_kwargs)
    ref = getattr(_ref, name_ref)(**ref_kwargs)
    for i in range(inputs.preds.shape[0]):
        ours.update(inputs.preds[i], inputs.target[i])
        ref.update(_to_torch(inputs.preds[i]), _to_torch(inputs.target[i]))
    ours_val = np.asarray(ours.compute())
    ref_val = ref.compute()
    if isinstance(ref_val, (list, tuple)):
        ref_val = torch.stack([torch.as_tensor(v) for v in ref_val])
    np.testing.assert_allclose(ours_val, ref_val.numpy(), atol=atol, rtol=1e-5)


@pytest.mark.parametrize("metric", _STAT_METRICS)
@pytest.mark.parametrize("case", ["binary_prob", "binary", "multiclass_prob", "multiclass", "multilabel_prob"])
@pytest.mark.parametrize("average", ["micro", "macro", "weighted"])
def test_stat_scores_family_matrix(metric, case, average):
    # int-valued multilabel inputs are mdmc to the reference (see
    # test_multilabel_int_is_mdmc); macro/weighted require num_classes
    # (1 for binary) — invalid cells raise identically in both implementations
    inputs = _INPUT_CASES[case]
    kwargs = {"average": average}
    if case.startswith("binary"):
        if average != "micro":
            kwargs["num_classes"] = 1
            if case == "binary":
                # int-valued 0/1 preds classify as 2-class multiclass; both
                # implementations require the multiclass=False hint here
                kwargs["multiclass"] = False
    else:
        kwargs["num_classes"] = 5
    _run_pair(metric, metric, inputs, kwargs)


@pytest.mark.parametrize("metric", ["Accuracy", "Precision"])
def test_multilabel_int_is_mdmc(metric):
    kwargs = {"average": "macro", "num_classes": 5, "mdmc_average": "global"}
    _run_pair(metric, metric, _multilabel, kwargs)


@pytest.mark.parametrize("metric", ["Precision", "Recall"])
def test_invalid_macro_without_num_classes_raises_like_reference(metric):
    with pytest.raises(ValueError, match="you have to provide the number of classes"):
        getattr(mt, metric)(average="macro")
    with pytest.raises(ValueError, match="you have to provide the number of classes"):
        getattr(_ref, metric)(average="macro")


@pytest.mark.parametrize("metric", ["Accuracy", "Precision", "Recall"])
@pytest.mark.parametrize("mdmc", ["global", "samplewise"])
@pytest.mark.parametrize("average", ["micro", "macro"])
def test_multidim_multiclass(metric, mdmc, average):
    kwargs = {"average": average, "mdmc_average": mdmc, "num_classes": 5}
    _run_pair(metric, metric, _multidim_multiclass, kwargs)


@pytest.mark.parametrize("metric", ["Accuracy", "Precision", "Recall", "F1Score"])
@pytest.mark.parametrize("ignore_index", [0, 2])
def test_ignore_index(metric, ignore_index):
    kwargs = {"num_classes": 5, "average": "macro", "ignore_index": ignore_index}
    _run_pair(metric, metric, _multiclass_prob, kwargs)


@pytest.mark.parametrize("top_k", [1, 2, 3])
def test_top_k_accuracy(top_k):
    kwargs = {"num_classes": 5, "top_k": top_k}
    _run_pair("Accuracy", "Accuracy", _multiclass_prob, kwargs)


@pytest.mark.parametrize("beta", [0.5, 2.0])
@pytest.mark.parametrize("average", ["micro", "macro"])
def test_fbeta(beta, average):
    kwargs = {"num_classes": 5, "beta": beta, "average": average}
    _run_pair("FBetaScore", "FBetaScore", _multiclass_prob, kwargs)


@pytest.mark.parametrize("case", ["binary_prob", "multiclass_prob", "multilabel_prob"])
def test_average_none_returns_per_class(case):
    inputs = _INPUT_CASES[case]
    kwargs = {"average": "none", "num_classes": 1 if case.startswith("binary") else 5}
    _run_pair("Precision", "Precision", inputs, kwargs)


@pytest.mark.parametrize("threshold", [0.3, 0.5, 0.7])
def test_threshold_sweep(threshold):
    _run_pair("Accuracy", "Accuracy", _binary_prob, {"threshold": threshold})


@pytest.mark.parametrize("case", ["binary", "multiclass", "multilabel"])
def test_confusion_matrix_parity(case):
    inputs = _INPUT_CASES[case]
    kwargs = {"num_classes": 2 if case == "binary" else 5}
    if case == "multilabel":
        kwargs["multilabel"] = True
    _run_pair("ConfusionMatrix", "ConfusionMatrix", inputs, kwargs)


@pytest.mark.parametrize("normalize", ["true", "pred", "all"])
def test_confusion_matrix_normalized(normalize):
    _run_pair(
        "ConfusionMatrix", "ConfusionMatrix", _multiclass, {"num_classes": 5, "normalize": normalize}
    )


@pytest.mark.parametrize("metric,kwargs", [
    ("CohenKappa", {"num_classes": 5}),
    ("CohenKappa", {"num_classes": 5, "weights": "linear"}),
    ("CohenKappa", {"num_classes": 5, "weights": "quadratic"}),
    ("MatthewsCorrCoef", {"num_classes": 5}),
    ("JaccardIndex", {"num_classes": 5}),
    ("JaccardIndex", {"num_classes": 5, "average": "none"}),
])
def test_confmat_family(metric, kwargs):
    _run_pair(metric, metric, _multiclass_prob, kwargs)


@pytest.mark.parametrize("metric,kwargs,atol", [
    ("AUROC", {}, 1e-5),
    ("AveragePrecision", {}, 1e-5),
    ("CalibrationError", {"norm": "l1"}, 1e-5),
    ("CalibrationError", {"norm": "max"}, 1e-5),
    ("HingeLoss", {}, 1e-4),
])
def test_binary_prob_metrics(metric, kwargs, atol):
    _run_pair(metric, metric, _binary_prob, kwargs, atol=atol)


@pytest.mark.parametrize("average", ["macro", "weighted"])
def test_auroc_multiclass(average):
    _run_pair("AUROC", "AUROC", _multiclass_prob, {"num_classes": 5, "average": average}, atol=1e-5)


def test_kl_divergence():
    rng = np.random.RandomState(0)
    p = rng.rand(4, 32, 5) + 1e-3
    q = rng.rand(4, 32, 5) + 1e-3
    p /= p.sum(-1, keepdims=True)
    q /= q.sum(-1, keepdims=True)
    ours = mt.KLDivergence()
    ref = _ref.KLDivergence()
    for i in range(4):
        ours.update(jnp.asarray(p[i]), jnp.asarray(q[i]))
        ref.update(torch.tensor(p[i]), torch.tensor(q[i]))
    np.testing.assert_allclose(np.asarray(ours.compute()), ref.compute().numpy(), atol=1e-5)


@pytest.mark.parametrize("metric", ["CoverageError", "LabelRankingAveragePrecision", "LabelRankingLoss"])
def test_ranking_metrics(metric):
    _run_pair(metric, metric, _multilabel_prob, {}, atol=1e-5)


def test_dice():
    _run_pair("Dice", "Dice", _multiclass_prob, {"num_classes": 5, "average": "micro"})


def test_stat_scores_raw():
    _run_pair("StatScores", "StatScores", _multiclass_prob, {"num_classes": 5, "reduce": "macro"})
