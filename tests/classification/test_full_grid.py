"""The ENUMERATED classification parametrization grid vs the reference.

VERDICT r2 #8: the reference pushes the stat-scores family through the full
cartesian input-inventory grid (`tests/unittests/classification/inputs.py:23-60`
feeding per-metric case x average x mdmc x ignore_index x top_k matrices);
the round-2 edge matrices SAMPLED that grid — this module enumerates it.

Every cell runs BOTH implementations on identical streamed batches:

- if both produce a value, the values must agree to tolerance;
- if both raise, the cell is a mutually-rejected configuration (pinned: a
  combo one side rejects and the other silently computes IS a divergence
  and fails the cell).

The curve family (AUROC / AveragePrecision / PrecisionRecallCurve / ROC)
gets its own enumeration over its applicable axes.
"""
from __future__ import annotations

import numpy as np
import pytest
import torch

from tests.classification.inputs import (
    _binary,
    _binary_logit,
    _binary_prob,
    _multiclass,
    _multiclass_logit,
    _multiclass_prob,
    _multidim_multiclass,
    _multidim_multiclass_prob,
    _multilabel,
    _multilabel_logit,
    _multilabel_prob,
)
from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
pytestmark = pytest.mark.skipif(_ref is None, reason="reference mount unavailable")

import metrics_tpu as mt  # noqa: E402

CASES = {
    "binary": _binary,
    "binary_prob": _binary_prob,
    "binary_logit": _binary_logit,
    "multiclass": _multiclass,
    "multiclass_prob": _multiclass_prob,
    "multiclass_logit": _multiclass_logit,
    "multilabel": _multilabel,
    "multilabel_prob": _multilabel_prob,
    "multilabel_logit": _multilabel_logit,
    "mdmc": _multidim_multiclass,
    "mdmc_prob": _multidim_multiclass_prob,
}

STAT_METRICS = ["Accuracy", "Precision", "Recall", "F1Score", "Specificity"]

AVERAGES = ["micro", "macro", "weighted", "none"]
MDMC = [None, "global", "samplewise"]
IGNORE = [None, 1]
TOPK = [None, 2]


def _kwargs_for(case: str, average: str, mdmc, ignore_index, top_k) -> dict:
    """Constructor kwargs for a grid cell, mirroring the reference's own
    per-case test setup (binary needs num_classes=1 off-micro; int-binary
    needs the multiclass=False hint; everything else num_classes=5)."""
    kwargs = {"average": average}
    if case.startswith("binary"):
        if average != "micro":
            kwargs["num_classes"] = 1
        if case == "binary":
            kwargs["multiclass"] = False
    else:
        kwargs["num_classes"] = 5
    if mdmc is not None:
        kwargs["mdmc_average"] = mdmc
    if ignore_index is not None:
        kwargs["ignore_index"] = ignore_index
    if top_k is not None:
        kwargs["top_k"] = top_k
    return kwargs


def _stream_value(metric, inputs, to_torch: bool):
    for i in range(inputs.preds.shape[0]):
        if to_torch:
            metric.update(torch.tensor(np.asarray(inputs.preds[i])), torch.tensor(np.asarray(inputs.target[i])))
        else:
            metric.update(inputs.preds[i], inputs.target[i])
    out = metric.compute()
    if isinstance(out, (list, tuple)):
        out = [np.asarray(o) for o in out]
        return np.stack(out) if all(o.shape == out[0].shape for o in out) else out
    return np.asarray(out)


def _run_cell(metric_name: str, case: str, kwargs: dict, atol: float = 1e-6) -> str:
    """Run one grid cell through both implementations. Returns 'value' when
    both computed and matched, 'rejected' when both raised."""
    inputs = CASES[case]
    ours_err = ref_err = None
    ours_val = ref_val = None
    try:
        ours_val = _stream_value(getattr(mt, metric_name)(**kwargs), inputs, to_torch=False)
    except Exception as err:
        ours_err = err
    try:
        ref_val = _stream_value(getattr(_ref, metric_name)(**kwargs), inputs, to_torch=True)
    except Exception as err:
        ref_err = err

    if ours_err is not None and ref_err is not None:
        # a mutual rejection must be OUR deliberate validation (ValueError),
        # not an internal crash that happens to coincide with the reference's
        # rejection — the same deliberate-vs-crash distinction applied to the
        # reference below
        assert isinstance(ours_err, ValueError), (
            f"our side crashed internally on a cell the reference rejects: "
            f"{metric_name} {case} {kwargs}: {type(ours_err).__name__}: {ours_err}"
        )
        return "rejected"
    assert ours_err is None, (
        f"we reject a configuration the reference computes: {metric_name} {case} {kwargs}: {ours_err}"
    )
    if ref_err is not None and not isinstance(ref_err, ValueError):
        # the reference CRASHED on its own internals (torch.cat on 0-d
        # tensors etc.) for a combination it never validates — e.g.
        # mdmc_average='samplewise' on non-multidim inputs. We compute the
        # natural value instead; require it to at least be finite.
        assert np.all(np.isfinite(np.asarray(ours_val, np.float64))), (metric_name, case, kwargs)
        return "ref_bug"
    assert ref_err is None, (
        f"we compute a configuration the reference deliberately rejects: {metric_name} {case} {kwargs} "
        f"-> ours={ours_val}, reference error: {ref_err}"
    )
    ref_np = ref_val if isinstance(ref_val, np.ndarray) else np.asarray(ref_val)
    np.testing.assert_allclose(
        np.asarray(ours_val, np.float64),
        np.asarray(ref_np, np.float64),
        atol=atol,
        rtol=1e-5,
        err_msg=f"{metric_name} {case} {kwargs}",
    )
    return "value"


@pytest.mark.parametrize("top_k", TOPK, ids=lambda v: f"topk={v}")
@pytest.mark.parametrize("ignore_index", IGNORE, ids=lambda v: f"ign={v}")
@pytest.mark.parametrize("mdmc", MDMC, ids=lambda v: f"mdmc={v}")
@pytest.mark.parametrize("average", AVERAGES)
@pytest.mark.parametrize("case", sorted(CASES))
def test_stat_scores_grid(case, average, mdmc, ignore_index, top_k):
    """One cell of the full cartesian grid, for every stat-scores metric."""
    outcomes = {}
    for metric_name in STAT_METRICS:
        kwargs = _kwargs_for(case, average, mdmc, ignore_index, top_k)
        outcomes[metric_name] = _run_cell(metric_name, case, kwargs)
    # per-metric agreement with the reference is asserted inside _run_cell;
    # outcomes may legitimately differ ACROSS the family — the reference
    # itself is non-uniform (e.g. Accuracy deliberately rejects top_k on
    # multilabel while Precision/Recall compute it), and we mirror each
    # metric's own contract
    assert set(outcomes.values()) <= {"value", "rejected", "ref_bug"}


# --------------------------------------------------------------- curve family

CURVE_CASES = ["binary_prob", "binary_logit", "multiclass_prob", "multiclass_logit"]


@pytest.mark.parametrize("case", CURVE_CASES)
@pytest.mark.parametrize("average", ["macro", "weighted", "none"])
def test_auroc_grid(case, average):
    kwargs = {"average": None if average == "none" else average}
    if case.startswith("multiclass"):
        kwargs["num_classes"] = 5
    outcome = _run_cell("AUROC", case, kwargs, atol=1e-5)
    assert outcome in ("value", "rejected")


@pytest.mark.parametrize("case", CURVE_CASES)
def test_average_precision_grid(case):
    kwargs = {"num_classes": 5} if case.startswith("multiclass") else {}
    assert _run_cell("AveragePrecision", case, kwargs, atol=1e-5) == "value"


@pytest.mark.parametrize("metric", ["PrecisionRecallCurve", "ROC"])
@pytest.mark.parametrize("case", CURVE_CASES)
def test_curve_grid(metric, case):
    """Curves return (precision/fpr, recall/tpr, thresholds) tuples — compare
    element-wise per class."""
    inputs = CASES[case]
    kwargs = {"num_classes": 5} if case.startswith("multiclass") else {}
    ours = getattr(mt, metric)(**kwargs)
    ref = getattr(_ref, metric)(**kwargs)
    for i in range(inputs.preds.shape[0]):
        ours.update(inputs.preds[i], inputs.target[i])
        ref.update(torch.tensor(np.asarray(inputs.preds[i])), torch.tensor(np.asarray(inputs.target[i])))
    ours_out = ours.compute()
    ref_out = ref.compute()
    assert len(ours_out) == len(ref_out)
    for o, r in zip(ours_out, ref_out):
        if isinstance(o, (list, tuple)):
            assert len(o) == len(r)
            for oc, rc in zip(o, r):
                np.testing.assert_allclose(np.asarray(oc, np.float64), np.asarray(rc, np.float64), atol=1e-5)
        else:
            np.testing.assert_allclose(np.asarray(o, np.float64), np.asarray(r, np.float64), atol=1e-5)


def test_grid_is_fully_enumerated():
    """The cartesian product covered above matches the declared axes — a
    guard against silently narrowing the grid later."""
    n_cells = len(CASES) * len(AVERAGES) * len(MDMC) * len(IGNORE) * len(TOPK)
    assert n_cells == 11 * 4 * 3 * 2 * 2 == 528
