"""Curve metrics vs sklearn + reference (PRCurve/ROC/AUROC/AP/AUC/binned family)."""
import jax.numpy as jnp
import numpy as np
import pytest
import sklearn.metrics as skm

from metrics_tpu import (
    AUC,
    AUROC,
    AveragePrecision,
    BinnedAveragePrecision,
    BinnedPrecisionRecallCurve,
    BinnedRecallAtFixedPrecision,
    PrecisionRecallCurve,
    ROC,
)
from metrics_tpu.functional import auc, auroc, average_precision, precision_recall_curve, roc
from tests.classification.inputs import _binary_prob, _multiclass_prob
from tests.helpers.reference_oracle import get_reference
from tests.helpers.testers import NUM_CLASSES, MetricTester


class TestAUROC(MetricTester):
    @pytest.mark.parametrize("ddp", [False, True])
    def test_auroc_binary_class(self, ddp):
        self.run_class_metric_test(
            _binary_prob.preds,
            _binary_prob.target,
            AUROC,
            lambda p, t: skm.roc_auc_score(t, p),
            metric_args={"pos_label": 1},
            ddp=ddp,
            check_batch=False,
        )

    def test_auroc_multiclass(self):
        self.run_functional_metric_test(
            _multiclass_prob.preds,
            _multiclass_prob.target,
            auroc,
            lambda p, t: skm.roc_auc_score(t, p, multi_class="ovr", average="macro", labels=range(NUM_CLASSES)),
            metric_args={"num_classes": NUM_CLASSES},
        )

    def test_auroc_max_fpr(self):
        p, t = _binary_prob.preds[0], _binary_prob.target[0]
        res = auroc(p, t, max_fpr=0.5)
        ref = skm.roc_auc_score(np.asarray(t), np.asarray(p), max_fpr=0.5)
        np.testing.assert_allclose(np.asarray(res), ref, atol=1e-5)


class TestAveragePrecision(MetricTester):
    @pytest.mark.parametrize("ddp", [False, True])
    def test_ap_binary_class(self, ddp):
        self.run_class_metric_test(
            _binary_prob.preds,
            _binary_prob.target,
            AveragePrecision,
            lambda p, t: skm.average_precision_score(t, p),
            metric_args={"pos_label": 1},
            ddp=ddp,
            check_batch=False,
        )

    def test_ap_multiclass_macro(self):
        def sk_ap_macro(p, t):
            onehot = np.eye(NUM_CLASSES)[t]
            scores = [skm.average_precision_score(onehot[:, c], p[:, c]) for c in range(NUM_CLASSES)]
            return np.nanmean(scores)

        self.run_functional_metric_test(
            _multiclass_prob.preds,
            _multiclass_prob.target,
            average_precision,
            sk_ap_macro,
            metric_args={"num_classes": NUM_CLASSES, "average": "macro"},
        )


class TestCurves(MetricTester):
    def test_pr_curve_matches_reference(self):
        ref = get_reference()
        if ref is None:
            pytest.skip("reference implementation not available")
        import torch

        p, t = _binary_prob.preds[0], _binary_prob.target[0]
        mp, mr, mt = precision_recall_curve(p, t, pos_label=1)
        rp, rr, rt = ref.functional.precision_recall_curve(
            torch.tensor(np.asarray(p)), torch.tensor(np.asarray(t)), pos_label=1
        )
        np.testing.assert_allclose(np.asarray(mp), rp.numpy(), atol=1e-6)
        np.testing.assert_allclose(np.asarray(mr), rr.numpy(), atol=1e-6)
        np.testing.assert_allclose(np.asarray(mt), rt.numpy(), atol=1e-6)

    def test_roc_matches_sklearn(self):
        p, t = _binary_prob.preds[0], _binary_prob.target[0]
        fpr, tpr, _ = roc(p, t, pos_label=1)
        sfpr, stpr, _ = skm.roc_curve(np.asarray(t), np.asarray(p), drop_intermediate=False)
        np.testing.assert_allclose(np.asarray(fpr), sfpr, atol=1e-6)
        np.testing.assert_allclose(np.asarray(tpr), stpr, atol=1e-6)

    def test_pr_curve_class_accumulates(self):
        m = PrecisionRecallCurve(pos_label=1)
        for i in range(2):
            m.update(_binary_prob.preds[i], _binary_prob.target[i])
        p, r, t = m.compute()
        all_p = jnp.concatenate([_binary_prob.preds[0], _binary_prob.preds[1]])
        all_t = jnp.concatenate([_binary_prob.target[0], _binary_prob.target[1]])
        fp, fr, ft = precision_recall_curve(all_p, all_t, pos_label=1)
        np.testing.assert_allclose(np.asarray(p), np.asarray(fp), atol=1e-6)

    def test_roc_class(self):
        m = ROC(pos_label=1)
        m.update(_binary_prob.preds[0], _binary_prob.target[0])
        fpr, tpr, th = m.compute()
        assert fpr.shape == tpr.shape == th.shape

    def test_auc(self):
        x = jnp.asarray([0.0, 1.0, 2.0, 3.0])
        y = jnp.asarray([0.0, 1.0, 2.0, 2.0])
        np.testing.assert_allclose(np.asarray(auc(x, y)), 4.0)
        m = AUC()
        m.update(x, y)
        np.testing.assert_allclose(np.asarray(m.compute()), 4.0)
        with pytest.raises(ValueError, match="neither increasing or decreasing"):
            auc(jnp.asarray([1.0, 0.0, 2.0]), jnp.asarray([1.0, 1.0, 1.0]))
        np.testing.assert_allclose(np.asarray(auc(jnp.asarray([1.0, 0.0, 2.0]), jnp.asarray([1.0, 1.0, 1.0]), reorder=True)), 2.0)


class TestBinnedFamily(MetricTester):
    def test_binned_ap_close_to_exact(self):
        m = BinnedAveragePrecision(num_classes=1, thresholds=500)
        for i in range(4):
            m.update(_binary_prob.preds[i], _binary_prob.target[i])
        binned = float(m.compute())
        all_p = jnp.concatenate([_binary_prob.preds[i] for i in range(4)])
        all_t = jnp.concatenate([_binary_prob.target[i] for i in range(4)])
        exact = float(skm.average_precision_score(np.asarray(all_t), np.asarray(all_p)))
        assert abs(binned - exact) < 0.05

    def test_binned_curve_is_jittable(self):
        """The binned curve update must run fully under jit (the TPU-native path)."""
        import jax

        m = BinnedPrecisionRecallCurve(num_classes=1, thresholds=10)
        init, upd, cmp = m.as_functions()
        state = init()
        jupd = jax.jit(upd)
        for i in range(2):
            state = jupd(state, _binary_prob.preds[i], _binary_prob.target[i])
        assert state["TPs"].shape == (1, 10)

    def test_binned_curve_reference_example(self):
        pred = jnp.asarray([0.0, 1.0, 2.0, 3.0]) / 3.0
        target = jnp.asarray([0, 1, 1, 1])
        m = BinnedAveragePrecision(num_classes=1, thresholds=10)
        res = m(pred, target)
        np.testing.assert_allclose(np.asarray(res), 1.0, atol=1e-4)

    def test_binned_recall_at_precision(self):
        pred = jnp.asarray([0.0, 0.2, 0.5, 0.8])
        target = jnp.asarray([0, 1, 1, 0])
        m = BinnedRecallAtFixedPrecision(num_classes=1, thresholds=10, min_precision=0.5)
        recall, thr = m(pred, target)
        np.testing.assert_allclose(np.asarray(recall), 1.0, atol=1e-5)
        np.testing.assert_allclose(np.asarray(thr), 0.1111, atol=1e-3)

    def test_binned_spmd(self):
        """Binned curve state syncs with one psum under shard_map; the SPMD
        result must equal single-device accumulation over all data."""
        m = BinnedAveragePrecision(num_classes=1, thresholds=100)
        for i in range(4):
            m.update(_binary_prob.preds[i], _binary_prob.target[i])
        single = float(m.compute())

        import jax
        from jax.sharding import Mesh, PartitionSpec as P

        m2 = BinnedAveragePrecision(num_classes=1, thresholds=100)
        init, upd, cmp = m2.as_functions()
        mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))

        def f(p, t):
            st = init()
            for i in range(2):
                st = upd(st, p[i], t[i])
            return cmp(st, axis_name="dp")

        out = jax.jit(
            jax.shard_map(f, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P(), check_vma=False)
        )(jnp.stack([_binary_prob.preds[i] for i in range(4)]), jnp.stack([_binary_prob.target[i] for i in range(4)]))
        np.testing.assert_allclose(float(out), single, atol=1e-5)
