"""Hamming/Hinge/KLDiv/Calibration/Ranking/Dice tests vs sklearn + reference conventions."""
import jax.numpy as jnp
import numpy as np
import pytest
import sklearn.metrics as skm

from metrics_tpu import (
    CalibrationError,
    CoverageError,
    Dice,
    HammingDistance,
    HingeLoss,
    KLDivergence,
    LabelRankingAveragePrecision,
    LabelRankingLoss,
)
from metrics_tpu.functional import (
    calibration_error,
    coverage_error,
    dice,
    hamming_distance,
    hinge_loss,
    kl_divergence,
    label_ranking_average_precision,
    label_ranking_loss,
)
from tests.classification.inputs import _multilabel, _multilabel_prob
from tests.helpers.testers import MetricTester

_rng = np.random.RandomState(7)


class TestHamming(MetricTester):
    @pytest.mark.parametrize("ddp", [False, True])
    def test_hamming_multilabel(self, ddp):
        def sk_hamming(preds, target):
            n = target.shape[0]
            return skm.hamming_loss(target.reshape(n, -1), preds.reshape(n, -1))

        self.run_class_metric_test(
            _multilabel.preds, _multilabel.target, HammingDistance, sk_hamming, ddp=ddp
        )

    def test_hamming_functional(self):
        self.run_functional_metric_test(
            _multilabel.preds,
            _multilabel.target,
            hamming_distance,
            lambda p, t: skm.hamming_loss(t.reshape(t.shape[0], -1), p.reshape(p.shape[0], -1)),
        )


class TestHinge(MetricTester):
    def test_hinge_binary(self):
        preds = jnp.asarray(_rng.randn(4, 32).astype(np.float32))
        target = jnp.asarray(_rng.randint(0, 2, (4, 32)))

        def sk_hinge(p, t):
            return skm.hinge_loss(t * 2 - 1, p)

        self.run_functional_metric_test(preds, target, hinge_loss, sk_hinge)

    def test_hinge_multiclass_crammer_singer(self):
        preds = jnp.asarray(_rng.randn(4, 32, 3).astype(np.float32))
        target = jnp.asarray(_rng.randint(0, 3, (4, 32)))

        def sk_hinge_mc(p, t):
            return skm.hinge_loss(t, p, labels=[0, 1, 2])

        self.run_functional_metric_test(preds, target, hinge_loss, sk_hinge_mc)

    @pytest.mark.parametrize("ddp", [False, True])
    def test_hinge_class(self, ddp):
        preds = jnp.asarray(_rng.randn(4, 32).astype(np.float32))
        target = jnp.asarray(_rng.randint(0, 2, (4, 32)))
        self.run_class_metric_test(
            preds, target, HingeLoss, lambda p, t: skm.hinge_loss(t * 2 - 1, p), ddp=ddp, check_batch=False
        )

    def test_hinge_grad(self):
        preds = jnp.asarray(_rng.randn(2, 16).astype(np.float32))
        target = jnp.asarray(_rng.randint(0, 2, (2, 16)))
        self.run_differentiability_test(preds, target, hinge_loss)


class TestKLDivergence(MetricTester):
    def test_kl_functional(self):
        p = _rng.rand(4, 32, 6).astype(np.float32)
        q = _rng.rand(4, 32, 6).astype(np.float32)

        def ref_kl(pp, qq):
            pn = pp / pp.sum(-1, keepdims=True)
            qn = qq / qq.sum(-1, keepdims=True)
            return np.mean(np.sum(pn * np.log(pn / qn), axis=-1))

        self.run_functional_metric_test(jnp.asarray(p), jnp.asarray(q), kl_divergence, ref_kl, atol=1e-5)

    @pytest.mark.parametrize("ddp", [False, True])
    def test_kl_class(self, ddp):
        p = _rng.rand(4, 32, 6).astype(np.float32)
        q = _rng.rand(4, 32, 6).astype(np.float32)

        def ref_kl(pp, qq):
            pn = pp / pp.sum(-1, keepdims=True)
            qn = qq / qq.sum(-1, keepdims=True)
            return np.mean(np.sum(pn * np.log(pn / qn), axis=-1))

        self.run_class_metric_test(jnp.asarray(p), jnp.asarray(q), KLDivergence, ref_kl, ddp=ddp, atol=1e-5)

    def test_kl_jit(self):
        p = jnp.asarray(_rng.rand(4, 8, 3).astype(np.float32))
        q = jnp.asarray(_rng.rand(4, 8, 3).astype(np.float32))
        self.run_jit_test(p, q, kl_divergence)


class TestCalibration(MetricTester):
    def test_ece_vs_manual(self):
        """Binary ECE against a hand-rolled numpy implementation."""
        preds = _rng.rand(200).astype(np.float32)
        target = _rng.randint(0, 2, 200)

        def ref_ece(p, t, n_bins=15):
            bins = np.linspace(0, 1, n_bins + 1)
            idx = np.clip(np.searchsorted(bins, p, side="left") - 1, 0, n_bins - 1)
            ce = 0.0
            for b in range(n_bins):
                m = idx == b
                if m.sum() == 0:
                    continue
                ce += abs(t[m].mean() - p[m].mean()) * m.mean()
            return ce

        res = calibration_error(jnp.asarray(preds), jnp.asarray(target), n_bins=15, norm="l1")
        np.testing.assert_allclose(np.asarray(res), ref_ece(preds, target), atol=1e-5)

    @pytest.mark.parametrize("norm", ["l1", "l2", "max"])
    def test_ce_class_accumulates(self, norm):
        preds = jnp.asarray(_rng.rand(4, 50).astype(np.float32))
        target = jnp.asarray(_rng.randint(0, 2, (4, 50)))
        m = CalibrationError(norm=norm)
        for i in range(4):
            m.update(preds[i], target[i])
        batch_all = calibration_error(preds.reshape(-1), target.reshape(-1), norm=norm)
        np.testing.assert_allclose(np.asarray(m.compute()), np.asarray(batch_all), atol=1e-6)

    def test_invalid_norm(self):
        with pytest.raises(ValueError, match="Norm"):
            CalibrationError(norm="l3")


class TestRanking(MetricTester):
    @pytest.mark.parametrize(
        "functional, module, sk_fn",
        [
            (coverage_error, CoverageError, skm.coverage_error),
            (label_ranking_average_precision, LabelRankingAveragePrecision, skm.label_ranking_average_precision_score),
            (label_ranking_loss, LabelRankingLoss, skm.label_ranking_loss),
        ],
    )
    def test_ranking_functional(self, functional, module, sk_fn):
        self.run_functional_metric_test(
            _multilabel_prob.preds,
            _multilabel_prob.target,
            functional,
            lambda p, t: sk_fn(t, p),
            atol=1e-5,
        )

    @pytest.mark.parametrize(
        "module, sk_fn",
        [
            (CoverageError, skm.coverage_error),
            (LabelRankingAveragePrecision, skm.label_ranking_average_precision_score),
            (LabelRankingLoss, skm.label_ranking_loss),
        ],
    )
    @pytest.mark.parametrize("ddp", [False, True])
    def test_ranking_class(self, module, sk_fn, ddp):
        self.run_class_metric_test(
            _multilabel_prob.preds,
            _multilabel_prob.target,
            module,
            lambda p, t: sk_fn(t, p),
            ddp=ddp,
            check_batch=False,
            atol=1e-5,
        )

    def test_ranking_jit(self):
        self.run_jit_test(_multilabel_prob.preds, _multilabel_prob.target, label_ranking_loss)


class TestDice(MetricTester):
    def test_dice_micro_equals_f1_micro(self):
        preds = jnp.asarray(_rng.randint(0, 3, (4, 32)))
        target = jnp.asarray(_rng.randint(0, 3, (4, 32)))
        self.run_functional_metric_test(
            preds,
            target,
            dice,
            lambda p, t: skm.f1_score(t, p, average="micro"),
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_dice_class(self, ddp):
        preds = jnp.asarray(_rng.randint(0, 3, (4, 32)))
        target = jnp.asarray(_rng.randint(0, 3, (4, 32)))
        self.run_class_metric_test(
            preds, target, Dice, lambda p, t: skm.f1_score(t, p, average="micro"), ddp=ddp
        )
