"""Streaming (per-bin sum state) CalibrationError vs the one-shot functional.

The module redesign replaced cat states with `(n_bins,)` sufficient
statistics; these tests pin batch-invariance, the empty-compute error, and
int32 count exactness.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt


def test_multibatch_equals_oneshot_all_norms():
    rng = np.random.RandomState(0)
    chunks = [(rng.rand(257).astype(np.float32), rng.randint(0, 2, 257)) for _ in range(4)]
    p = np.concatenate([c[0] for c in chunks])
    t = np.concatenate([c[1] for c in chunks])
    for norm in ("l1", "l2", "max"):
        m = mt.CalibrationError(norm=norm)
        for cp, ct in chunks:
            m.update(jnp.asarray(cp), jnp.asarray(ct))
        want = float(mt.functional.calibration_error(jnp.asarray(p), jnp.asarray(t), norm=norm))
        assert float(m.compute()) == pytest.approx(want, abs=1e-6)


def test_empty_compute_raises():
    with pytest.warns(UserWarning, match="was called before the ``update``"):
        with pytest.raises(ValueError, match="No samples"):
            mt.CalibrationError().compute()


def test_count_state_is_int32():
    m = mt.CalibrationError()
    m.update(jnp.asarray([0.2, 0.8]), jnp.asarray([0, 1]))
    assert m.count_bin.dtype == jnp.int32
    assert int(m.count_bin.sum()) == 2
