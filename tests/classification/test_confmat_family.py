"""Confusion-matrix family vs sklearn (ConfusionMatrix/CohenKappa/Matthews/Jaccard)."""
import jax.numpy as jnp
import numpy as np
import pytest
import sklearn.metrics as skm

from metrics_tpu import CohenKappa, ConfusionMatrix, JaccardIndex, MatthewsCorrCoef
from metrics_tpu.functional import cohen_kappa, confusion_matrix, jaccard_index, matthews_corrcoef
from tests.classification.inputs import _multiclass, _multiclass_prob
from tests.helpers.testers import NUM_CLASSES, MetricTester


def _sk_cm(preds, target, normalize=None):
    if preds.ndim > target.ndim:
        preds = preds.argmax(-1)
    return skm.confusion_matrix(target, preds, labels=range(NUM_CLASSES), normalize=normalize)


class TestConfusionMatrix(MetricTester):
    @pytest.mark.parametrize("ddp", [False, True])
    def test_confmat_class(self, ddp):
        self.run_class_metric_test(
            _multiclass.preds,
            _multiclass.target,
            ConfusionMatrix,
            _sk_cm,
            metric_args={"num_classes": NUM_CLASSES},
            ddp=ddp,
        )

    @pytest.mark.parametrize("normalize", ["true", "pred", "all", None])
    def test_confmat_normalize(self, normalize):
        self.run_functional_metric_test(
            _multiclass.preds,
            _multiclass.target,
            confusion_matrix,
            lambda p, t: np.nan_to_num(_sk_cm(p, t, normalize=normalize)),
            metric_args={"num_classes": NUM_CLASSES, "normalize": normalize},
        )

    def test_confmat_probs(self):
        self.run_functional_metric_test(
            _multiclass_prob.preds,
            _multiclass_prob.target,
            confusion_matrix,
            _sk_cm,
            metric_args={"num_classes": NUM_CLASSES},
        )

    def test_confmat_jit(self):
        self.run_jit_test(
            _multiclass.preds, _multiclass.target, confusion_matrix, metric_args={"num_classes": NUM_CLASSES}
        )

    def test_confmat_spmd(self):
        self.run_spmd_test(
            _multiclass.preds,
            _multiclass.target,
            lambda **kw: ConfusionMatrix(num_classes=NUM_CLASSES, **kw),
            _sk_cm,
        )

    def test_confmat_multilabel(self):
        rng = np.random.RandomState(3)
        p = rng.randint(0, 2, (4, 20, NUM_CLASSES))
        t = rng.randint(0, 2, (4, 20, NUM_CLASSES))

        def sk_ml_cm(preds, target):
            return skm.multilabel_confusion_matrix(target, preds)

        self.run_functional_metric_test(
            jnp.asarray(p),
            jnp.asarray(t),
            confusion_matrix,
            sk_ml_cm,
            metric_args={"num_classes": NUM_CLASSES, "multilabel": True},
        )


class TestCohenKappa(MetricTester):
    @pytest.mark.parametrize("weights", [None, "linear", "quadratic"])
    def test_kappa_functional(self, weights):
        self.run_functional_metric_test(
            _multiclass.preds,
            _multiclass.target,
            cohen_kappa,
            lambda p, t: skm.cohen_kappa_score(t, p, weights=weights),
            metric_args={"num_classes": NUM_CLASSES, "weights": weights},
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_kappa_class(self, ddp):
        self.run_class_metric_test(
            _multiclass.preds,
            _multiclass.target,
            CohenKappa,
            lambda p, t: skm.cohen_kappa_score(t, p),
            metric_args={"num_classes": NUM_CLASSES},
            ddp=ddp,
            check_batch=False,
        )


class TestMatthews(MetricTester):
    @pytest.mark.parametrize("ddp", [False, True])
    def test_mcc_class(self, ddp):
        self.run_class_metric_test(
            _multiclass.preds,
            _multiclass.target,
            MatthewsCorrCoef,
            lambda p, t: skm.matthews_corrcoef(t, p),
            metric_args={"num_classes": NUM_CLASSES},
            ddp=ddp,
            check_batch=False,
        )

    def test_mcc_jit(self):
        self.run_jit_test(
            _multiclass.preds, _multiclass.target, matthews_corrcoef, metric_args={"num_classes": NUM_CLASSES}
        )


class TestJaccard(MetricTester):
    @pytest.mark.parametrize("average", ["micro", "macro", "weighted", "none"])
    def test_jaccard_functional(self, average):
        sk_average = None if average == "none" else average
        self.run_functional_metric_test(
            _multiclass.preds,
            _multiclass.target,
            jaccard_index,
            lambda p, t: skm.jaccard_score(t, p, average=sk_average, labels=range(NUM_CLASSES), zero_division=0),
            metric_args={"num_classes": NUM_CLASSES, "average": average},
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_jaccard_class(self, ddp):
        self.run_class_metric_test(
            _multiclass.preds,
            _multiclass.target,
            JaccardIndex,
            lambda p, t: skm.jaccard_score(t, p, average="macro", zero_division=0),
            metric_args={"num_classes": NUM_CLASSES},
            ddp=ddp,
            check_batch=False,
        )
