"""Option grids for classification metrics OUTSIDE the stat-scores engine.

The 528-cell full grid (test_full_grid.py) enumerates the stat-scores family
and the curve family; these cells cover the remaining per-metric option
spaces — Hinge squared x multiclass_mode, KLDivergence log_prob x reduction,
Jaccard average x absent_score x ignore_index, AUROC max_fpr,
AveragePrecision average modes, CalibrationError norm x n_bins — each vs the
mounted reference on identical streamed batches. (AUROC multiclass averages
and CohenKappa weights are already enumerated in test_reference_parity.py.)
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tests.helpers import assert_tree_close, cell_seed
from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
pytestmark = [pytest.mark.skipif(_ref is None, reason="reference mount unavailable"),
              pytest.mark.slow]  # deep-coverage tier (see docs/testing.md)

import metrics_tpu as mt  # noqa: E402

N_CLASSES = 5
N_BATCHES, BATCH = 3, 32


def _run_cell(name, kwargs, batches, atol=1e-5):
    ours = getattr(mt, name)(**kwargs)
    ref = getattr(_ref, name)(**kwargs)
    for preds, target in batches:
        ours.update(jnp.asarray(preds), jnp.asarray(target))
        ref.update(torch.tensor(preds), torch.tensor(target))
    assert_tree_close(ours.compute(), ref.compute(), atol=atol)


def _logit_batches(seed, binary=False):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(N_BATCHES):
        if binary:
            out.append((rng.randn(BATCH).astype(np.float32), rng.randint(0, 2, BATCH)))
        else:
            out.append((rng.randn(BATCH, N_CLASSES).astype(np.float32), rng.randint(0, N_CLASSES, BATCH)))
    return out


def _prob_batches(seed, binary=False):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(N_BATCHES):
        if binary:
            out.append((rng.rand(BATCH).astype(np.float32), rng.randint(0, 2, BATCH)))
        else:
            p = rng.rand(BATCH, N_CLASSES).astype(np.float32)
            out.append((p / p.sum(axis=1, keepdims=True), rng.randint(0, N_CLASSES, BATCH)))
    return out


def _label_batches(seed):
    rng = np.random.RandomState(seed)
    return [
        (rng.randint(0, N_CLASSES, BATCH), rng.randint(0, N_CLASSES, BATCH)) for _ in range(N_BATCHES)
    ]


class TestHingeGrid:
    @pytest.mark.parametrize("squared", (False, True))
    def test_binary(self, squared):
        _run_cell("HingeLoss", {"squared": squared}, _logit_batches(cell_seed("hinge-b", squared), binary=True))

    @pytest.mark.parametrize("squared", (False, True))
    @pytest.mark.parametrize("multiclass_mode", ("crammer-singer", "one-vs-all"))
    def test_multiclass(self, squared, multiclass_mode):
        _run_cell(
            "HingeLoss",
            {"squared": squared, "multiclass_mode": multiclass_mode},
            _logit_batches(cell_seed("hinge-m", squared, multiclass_mode)),
        )


class TestKLDivergenceGrid:
    @pytest.mark.parametrize("log_prob", (False, True))
    @pytest.mark.parametrize("reduction", ("mean", "sum"))
    def test_cell(self, log_prob, reduction):
        rng = np.random.RandomState(cell_seed("kld", log_prob, reduction))
        batches = []
        for _ in range(N_BATCHES):
            p = rng.rand(BATCH, N_CLASSES).astype(np.float32) + 1e-3
            q = rng.rand(BATCH, N_CLASSES).astype(np.float32) + 1e-3
            p /= p.sum(axis=1, keepdims=True)
            q /= q.sum(axis=1, keepdims=True)
            if log_prob:
                p, q = np.log(p), np.log(q)
            batches.append((p, q))
        _run_cell("KLDivergence", {"log_prob": log_prob, "reduction": reduction}, batches, atol=1e-4)


class TestJaccardGrid:
    @pytest.mark.parametrize("average", ("macro", "micro", "weighted", "none"))
    @pytest.mark.parametrize("absent_score", (0.0, 1.0))
    @pytest.mark.parametrize("ignore_index", (None, 0))
    def test_cell(self, average, absent_score, ignore_index):
        kwargs = {
            "num_classes": N_CLASSES,
            "average": average,
            "absent_score": absent_score,
            "ignore_index": ignore_index,
        }
        batches = _label_batches(cell_seed("jaccard", average, absent_score, ignore_index))
        if average == "weighted" and ignore_index is not None:
            # reference-internal crash (`functional/classification/jaccard.py:91`):
            # with ignore_index its `weights` stays length C while `scores`
            # shrinks to C-1, so torch broadcasts and raises. Our side must
            # compute a finite value (full-grid ref_bug convention).
            ours = mt.JaccardIndex(**kwargs)
            ref = getattr(_ref, "JaccardIndex")(**kwargs)
            for p, t in batches:
                ours.update(jnp.asarray(p), jnp.asarray(t))
                ref.update(torch.tensor(p), torch.tensor(t))
            with pytest.raises(RuntimeError):
                ref.compute()
            assert np.all(np.isfinite(np.asarray(ours.compute())))
            return
        _run_cell("JaccardIndex", kwargs, batches)


class TestAurocApGrid:
    @pytest.mark.parametrize("max_fpr", (None, 0.5, 0.9))
    def test_auroc_binary_max_fpr(self, max_fpr):
        _run_cell(
            "AUROC", {"max_fpr": max_fpr}, _prob_batches(cell_seed("auroc-fpr", max_fpr), binary=True)
        )

    @pytest.mark.parametrize("average", ("macro", "weighted"))
    def test_average_precision_multiclass(self, average):
        _run_cell(
            "AveragePrecision",
            {"num_classes": N_CLASSES, "average": average},
            _prob_batches(cell_seed("ap", average)),
        )


class TestCalibrationGrid:
    @pytest.mark.parametrize("norm", ("l1", "l2", "max"))
    @pytest.mark.parametrize("n_bins", (5, 15, 30))
    def test_cell(self, norm, n_bins):
        _run_cell(
            "CalibrationError",
            {"norm": norm, "n_bins": n_bins},
            _prob_batches(cell_seed("cal", norm, n_bins), binary=True),
            atol=1e-4,
        )
