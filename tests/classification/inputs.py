"""Seeded classification input fixtures (analogue of reference tests/unittests/classification/inputs.py)."""
from collections import namedtuple

import jax.numpy as jnp
import numpy as np

from tests.helpers.testers import BATCH_SIZE, EXTRA_DIM, NUM_BATCHES, NUM_CLASSES

Input = namedtuple("Input", ["preds", "target"])

_rng = np.random.RandomState(1)

_binary_prob = Input(
    preds=jnp.asarray(_rng.rand(NUM_BATCHES, BATCH_SIZE).astype(np.float32)),
    target=jnp.asarray(_rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE))),
)
_binary = Input(
    preds=jnp.asarray(_rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE))),
    target=jnp.asarray(_rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE))),
)
_multiclass_prob = Input(
    preds=jnp.asarray(
        (lambda p: p / p.sum(-1, keepdims=True))(_rng.rand(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)).astype(np.float32)
    ),
    target=jnp.asarray(_rng.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE))),
)
_multiclass = Input(
    preds=jnp.asarray(_rng.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE))),
    target=jnp.asarray(_rng.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE))),
)
_multilabel_prob = Input(
    preds=jnp.asarray(_rng.rand(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES).astype(np.float32)),
    target=jnp.asarray(_rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES))),
)
_multilabel = Input(
    preds=jnp.asarray(_rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES))),
    target=jnp.asarray(_rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES))),
)
_multidim_multiclass = Input(
    preds=jnp.asarray(_rng.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE, EXTRA_DIM))),
    target=jnp.asarray(_rng.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE, EXTRA_DIM))),
)


# --------------------------------------------------------------------------
# Reference inventory completion (`tests/unittests/classification/inputs.py`):
# logit-valued scores (outside [0,1] -> sigmoid/softmax autodetection), the
# (N, C, X) multidim probability case, and DELIBERATE degenerate inputs —
# the corner cases fuzz banks don't construct on purpose.

_binary_logit = Input(
    preds=jnp.asarray((_rng.randn(NUM_BATCHES, BATCH_SIZE) * 3).astype(np.float32)),
    target=jnp.asarray(_rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE))),
)
_multilabel_logit = Input(
    preds=jnp.asarray((_rng.randn(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES) * 3).astype(np.float32)),
    target=jnp.asarray(_rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES))),
)
_multiclass_logit = Input(
    preds=jnp.asarray((_rng.randn(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES) * 3).astype(np.float32)),
    target=jnp.asarray(_rng.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE))),
)
_multidim_multiclass_prob = Input(  # (N, C, X) class-dim probabilities
    preds=jnp.asarray(
        # axis 2 is the class dim of each (batch, sample, C, X) entry
        (lambda p: p / p.sum(2, keepdims=True))(_rng.rand(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM)).astype(
            np.float32
        )
    ),
    target=jnp.asarray(_rng.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE, EXTRA_DIM))),
)
_multilabel_multidim_prob = Input(  # (N, C, X) independent labels
    preds=jnp.asarray(_rng.rand(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM).astype(np.float32)),
    target=jnp.asarray(_rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM))),
)

# single-class targets: every sample is class 2 (zero support elsewhere)
_single_class_target = Input(
    preds=jnp.asarray(
        (lambda p: p / p.sum(-1, keepdims=True))(_rng.rand(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)).astype(np.float32)
    ),
    target=jnp.asarray(np.full((NUM_BATCHES, BATCH_SIZE), 2)),
)
# perfectly correct / perfectly wrong label predictions
_perfect_target = jnp.asarray(_rng.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE)))
_perfect = Input(preds=_perfect_target, target=_perfect_target)
_all_wrong = Input(
    preds=jnp.asarray((np.asarray(_perfect_target) + 1) % NUM_CLASSES), target=_perfect_target
)
# multilabel with NO positive targets anywhere
_multilabel_no_positives = Input(
    preds=jnp.asarray(_rng.rand(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES).astype(np.float32)),
    target=jnp.asarray(np.zeros((NUM_BATCHES, BATCH_SIZE, NUM_CLASSES), dtype=np.int64)),
)
