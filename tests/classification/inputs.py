"""Seeded classification input fixtures (analogue of reference tests/unittests/classification/inputs.py)."""
from collections import namedtuple

import jax.numpy as jnp
import numpy as np

from tests.helpers.testers import BATCH_SIZE, EXTRA_DIM, NUM_BATCHES, NUM_CLASSES

Input = namedtuple("Input", ["preds", "target"])

_rng = np.random.RandomState(1)

_binary_prob = Input(
    preds=jnp.asarray(_rng.rand(NUM_BATCHES, BATCH_SIZE).astype(np.float32)),
    target=jnp.asarray(_rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE))),
)
_binary = Input(
    preds=jnp.asarray(_rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE))),
    target=jnp.asarray(_rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE))),
)
_multiclass_prob = Input(
    preds=jnp.asarray(
        (lambda p: p / p.sum(-1, keepdims=True))(_rng.rand(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)).astype(np.float32)
    ),
    target=jnp.asarray(_rng.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE))),
)
_multiclass = Input(
    preds=jnp.asarray(_rng.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE))),
    target=jnp.asarray(_rng.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE))),
)
_multilabel_prob = Input(
    preds=jnp.asarray(_rng.rand(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES).astype(np.float32)),
    target=jnp.asarray(_rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES))),
)
_multilabel = Input(
    preds=jnp.asarray(_rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES))),
    target=jnp.asarray(_rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES))),
)
_multidim_multiclass = Input(
    preds=jnp.asarray(_rng.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE, EXTRA_DIM))),
    target=jnp.asarray(_rng.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE, EXTRA_DIM))),
)
