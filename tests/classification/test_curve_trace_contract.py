"""The dynamic-shape seam, pinned per curve metric (VERDICT #10).

The framework's key curve-family design decision: exact curves have
data-dependent output shapes (one point per distinct score — reference
`functional/classification/precision_recall_curve.py:49-51`), so under jit
tracing they REFUSE with a pointer to the fixed-shape alternative; the
scalar areas (AUROC / AveragePrecision) instead dispatch to static-shape
sorted kernels (`ops/sorted_curves.py`) and must agree with their own eager
path; the binned family is the blessed jit path and must trace end to end.
Every curve metric's contract is asserted here explicitly (functional AND
module), so a regression in any one dispatch seam fails by name.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu.functional import auroc, average_precision, precision_recall_curve, roc

_REFUSE = "cannot run under jit tracing"

rng = np.random.RandomState(7)
BIN_PREDS = jnp.asarray(rng.rand(32).astype(np.float32))
BIN_TARGET = jnp.asarray(rng.randint(0, 2, 32).astype(np.int32))
MC_PREDS_RAW = rng.rand(32, 4).astype(np.float32)
MC_PREDS = jnp.asarray(MC_PREDS_RAW / MC_PREDS_RAW.sum(1, keepdims=True))
MC_TARGET = jnp.asarray(rng.randint(0, 4, 32).astype(np.int32))


class TestExactCurvesRefuseTrace:
    """Exact curves: eager-only, with the documented refusal under jit."""

    def test_precision_recall_curve_binary(self):
        precision_recall_curve(BIN_PREDS, BIN_TARGET)  # eager path fine
        with pytest.raises(ValueError, match=_REFUSE):
            jax.jit(precision_recall_curve)(BIN_PREDS, BIN_TARGET)

    def test_precision_recall_curve_multiclass(self):
        fn = lambda p, t: precision_recall_curve(p, t, num_classes=4)
        fn(MC_PREDS, MC_TARGET)
        with pytest.raises(ValueError, match=_REFUSE):
            jax.jit(fn)(MC_PREDS, MC_TARGET)

    def test_roc_binary(self):
        roc(BIN_PREDS, BIN_TARGET)
        with pytest.raises(ValueError, match=_REFUSE):
            jax.jit(roc)(BIN_PREDS, BIN_TARGET)

    def test_roc_multiclass(self):
        fn = lambda p, t: roc(p, t, num_classes=4)
        fn(MC_PREDS, MC_TARGET)
        with pytest.raises(ValueError, match=_REFUSE):
            jax.jit(fn)(MC_PREDS, MC_TARGET)

    @pytest.mark.parametrize(
        "metric_cls, kwargs",
        [(mt.PrecisionRecallCurve, {}), (mt.ROC, {})],
        ids=["PrecisionRecallCurve", "ROC"],
    )
    def test_module_compute_is_host_only(self, metric_cls, kwargs):
        """Module form: eager update+compute works; the functional seam it
        rides refuses a traced compute."""
        metric = metric_cls(**kwargs)
        metric.update(BIN_PREDS, BIN_TARGET)
        out = metric.compute()
        assert len(out) == 3
        init, upd, cmp = metric_cls(**kwargs).as_functions()
        state = upd(init(), BIN_PREDS, BIN_TARGET)
        with pytest.raises(ValueError, match=_REFUSE):
            jax.jit(cmp)(state)


class TestScalarAreasTraceExactly:
    """AUROC / AveragePrecision: jit dispatches to the sorted static-shape
    kernels and must equal the eager (host curve) value."""

    def test_auroc_binary(self):
        got = float(jax.jit(auroc)(BIN_PREDS, BIN_TARGET))
        assert got == pytest.approx(float(auroc(BIN_PREDS, BIN_TARGET)), abs=1e-5)

    @pytest.mark.parametrize("average", ["macro", "weighted"])
    def test_auroc_multiclass(self, average):
        fn = lambda p, t: auroc(p, t, num_classes=4, average=average)
        assert float(jax.jit(fn)(MC_PREDS, MC_TARGET)) == pytest.approx(float(fn(MC_PREDS, MC_TARGET)), abs=1e-5)

    def test_average_precision_binary(self):
        got = float(jax.jit(average_precision)(BIN_PREDS, BIN_TARGET))
        assert got == pytest.approx(float(average_precision(BIN_PREDS, BIN_TARGET)), abs=1e-5)

    @pytest.mark.parametrize("average", ["macro", "weighted"])
    def test_average_precision_multiclass(self, average):
        fn = lambda p, t: average_precision(p, t, num_classes=4, average=average)
        assert float(jax.jit(fn)(MC_PREDS, MC_TARGET)) == pytest.approx(float(fn(MC_PREDS, MC_TARGET)), abs=1e-5)

    @pytest.mark.parametrize("metric_cls", [mt.AUROC, mt.AveragePrecision], ids=["AUROC", "AveragePrecision"])
    def test_module_compute_traces(self, metric_cls):
        """The module export's compute can run under jit (the sorted-kernel
        dispatch), and matches the eager module value."""
        metric = metric_cls()
        metric.update(BIN_PREDS, BIN_TARGET)
        want = float(metric.compute())
        init, upd, cmp = metric_cls().as_functions()
        state = upd(init(), BIN_PREDS, BIN_TARGET)
        assert float(jax.jit(cmp)(state)) == pytest.approx(want, abs=1e-5)


class TestBinnedFamilyIsTheJitPath:
    """Binned curves: fixed thresholds grid — update AND compute jit end to end."""

    @pytest.mark.parametrize(
        "metric_cls, kwargs, n_outputs",
        [
            (mt.BinnedPrecisionRecallCurve, dict(num_classes=1, thresholds=11), 3),
            (mt.BinnedAveragePrecision, dict(num_classes=1, thresholds=11), 1),
            (mt.BinnedRecallAtFixedPrecision, dict(num_classes=1, min_precision=0.5, thresholds=11), 2),
        ],
        ids=["BinnedPrecisionRecallCurve", "BinnedAveragePrecision", "BinnedRecallAtFixedPrecision"],
    )
    def test_full_lifecycle_under_jit(self, metric_cls, kwargs, n_outputs):
        eager = metric_cls(**kwargs)
        eager.update(BIN_PREDS, BIN_TARGET)
        want = eager.compute()
        want = want if isinstance(want, (tuple, list)) else (want,)

        init, upd, cmp = metric_cls(**kwargs).as_functions()
        state = jax.jit(upd)(init(), BIN_PREDS, BIN_TARGET)
        got = jax.jit(cmp)(state)
        got = got if isinstance(got, (tuple, list)) else (got,)
        assert len(got) == n_outputs == len(want)
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=1e-6)


class TestRetrievalCurvesAreHostSide:
    """Retrieval curve metrics group by query id on the host; eager lifecycle
    works and their compute documents host-only execution."""

    @pytest.mark.parametrize(
        "metric_cls, kwargs",
        [
            (mt.RetrievalPrecisionRecallCurve, dict(max_k=4)),
            (mt.RetrievalRecallAtFixedPrecision, dict(min_precision=0.3, max_k=4)),
        ],
        ids=["RetrievalPrecisionRecallCurve", "RetrievalRecallAtFixedPrecision"],
    )
    def test_eager_lifecycle(self, metric_cls, kwargs):
        metric = metric_cls(**kwargs)
        indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])  # int32: x64 is off
        preds = jnp.asarray([0.9, 0.3, 0.5, 0.8, 0.2, 0.7, 0.4], jnp.float32)
        target = jnp.asarray([1, 0, 1, 0, 1, 1, 0], jnp.int32)
        metric.update(preds, target, indexes=indexes)
        out = metric.compute()
        assert all(np.asarray(o).size > 0 for o in (out if isinstance(out, (tuple, list)) else (out,)))
