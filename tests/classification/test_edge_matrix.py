"""Constructed corner-case matrices vs the mounted reference (VERDICT #4).

The fuzz banks randomize over a domain; these cases are built ON PURPOSE —
the reference's deliberate input inventory
(`/root/reference/tests/unittests/classification/inputs.py:23-60`) plus the
degenerate shapes that actually bite: logit autodetection, (N, C, X)
probability tensors, all-ignored batches, single-class targets, zero-support
classes, top_k == num_classes, no-positive multilabel targets, perfect and
perfectly-wrong predictions. Every cell runs the identical data through our
implementation and the reference on torch/CPU and requires agreement
(NaN-for-NaN where the reference produces NaN).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tests.classification.inputs import (
    _all_wrong,
    _binary_logit,
    _multiclass_logit,
    _multidim_multiclass,
    _multidim_multiclass_prob,
    _multilabel_logit,
    _multilabel_multidim_prob,
    _multilabel_no_positives,
    _perfect,
    _single_class_target,
)
from tests.helpers.reference_oracle import get_reference
from tests.helpers.testers import NUM_CLASSES

_ref = get_reference()
pytestmark = pytest.mark.skipif(_ref is None, reason="reference mount unavailable")

import metrics_tpu as mt  # noqa: E402

_STAT_METRICS = ["Accuracy", "Precision", "Recall", "F1Score", "Specificity"]


def _to_torch(x):
    return torch.tensor(np.asarray(x))


def _run_pair(name, inputs, kwargs, atol=1e-6, ref_kwargs=None):
    ours = getattr(mt, name)(**kwargs)
    ref = getattr(_ref, name)(**(ref_kwargs if ref_kwargs is not None else kwargs))
    for i in range(inputs.preds.shape[0]):
        ours.update(inputs.preds[i], inputs.target[i])
        ref.update(_to_torch(inputs.preds[i]), _to_torch(inputs.target[i]))
    ours_val = np.asarray(ours.compute())
    ref_val = ref.compute()
    if isinstance(ref_val, (list, tuple)):
        ref_val = torch.stack([torch.as_tensor(v) for v in ref_val])
    np.testing.assert_allclose(ours_val, ref_val.numpy(), atol=atol, rtol=1e-5)


# ---------------------------------------------------------------- logits

class TestLogitInputs:
    """Scores outside [0,1] must route through the same sigmoid/softmax
    autodetection as the reference."""

    @pytest.mark.parametrize("metric", _STAT_METRICS)
    @pytest.mark.parametrize("average", ["micro", "macro", "weighted"])
    def test_binary_logits(self, metric, average):
        kwargs = {"average": average}
        if average != "micro":
            kwargs["num_classes"] = 1
        _run_pair(metric, _binary_logit, kwargs)

    @pytest.mark.parametrize("metric", _STAT_METRICS)
    @pytest.mark.parametrize("average", ["micro", "macro", "weighted"])
    def test_multiclass_logits(self, metric, average):
        _run_pair(metric, _multiclass_logit, {"average": average, "num_classes": NUM_CLASSES})

    @pytest.mark.parametrize("metric", ["Accuracy", "Precision", "Recall"])
    @pytest.mark.parametrize("threshold", [0.35, 0.5, 0.65])
    def test_multilabel_logits_threshold(self, metric, threshold):
        """Threshold applies to the POST-sigmoid probabilities."""
        _run_pair(
            metric,
            _multilabel_logit,
            {"average": "micro", "threshold": threshold, "num_classes": NUM_CLASSES},
        )

    def test_confusion_matrix_logits(self):
        _run_pair("ConfusionMatrix", _binary_logit, {"num_classes": 2})


# ------------------------------------------------------- multidim (N, C, X)

class TestMultidimProb:
    """(N, C, X) probability tensors — class dim second, extra dims after."""

    @pytest.mark.parametrize("metric", _STAT_METRICS)
    @pytest.mark.parametrize("mdmc", ["global", "samplewise"])
    def test_stat_family(self, metric, mdmc):
        _run_pair(
            metric,
            _multidim_multiclass_prob,
            {"average": "macro", "mdmc_average": mdmc, "num_classes": NUM_CLASSES},
        )

    @pytest.mark.parametrize("mdmc", ["global", "samplewise"])
    @pytest.mark.parametrize("top_k", [1, 2])
    def test_top_k_multidim(self, mdmc, top_k):
        _run_pair(
            "Accuracy",
            _multidim_multiclass_prob,
            {"mdmc_average": mdmc, "num_classes": NUM_CLASSES, "top_k": top_k},
        )

    @pytest.mark.parametrize("metric", ["Accuracy", "Precision"])
    def test_multilabel_multidim(self, metric):
        # (N, C, X) float + int pair classifies as multilabel with C*X implied
        # labels; num_classes must be omitted (both stacks reject a mismatch)
        _run_pair(metric, _multilabel_multidim_prob, {"average": "micro"})

    def test_multilabel_multidim_num_classes_mismatch_rejected(self):
        ours = mt.Accuracy(average="micro", num_classes=NUM_CLASSES)
        ref = _ref.Accuracy(average="micro", num_classes=NUM_CLASSES)
        with pytest.raises(ValueError, match="does not match num_classes"):
            ours.update(_multilabel_multidim_prob.preds[0], _multilabel_multidim_prob.target[0])
        with pytest.raises(ValueError, match="does not match num_classes"):
            ref.update(
                _to_torch(_multilabel_multidim_prob.preds[0]), _to_torch(_multilabel_multidim_prob.target[0])
            )


# ----------------------------------------------------------- degenerate data

class TestDegenerateTargets:
    @pytest.mark.parametrize("metric", _STAT_METRICS)
    @pytest.mark.parametrize("average", ["micro", "macro", "weighted", "none"])
    def test_single_class_targets(self, metric, average):
        """Zero support for 4 of 5 classes: macro means over 0/0 classes and
        'none' rows for unobserved classes must match the reference exactly."""
        _run_pair(metric, _single_class_target, {"average": average, "num_classes": NUM_CLASSES}, atol=1e-6)

    @pytest.mark.parametrize("metric", _STAT_METRICS)
    def test_perfect_predictions(self, metric):
        _run_pair(metric, _perfect, {"average": "macro", "num_classes": NUM_CLASSES})

    @pytest.mark.parametrize("metric", _STAT_METRICS)
    def test_all_wrong_predictions(self, metric):
        _run_pair(metric, _all_wrong, {"average": "macro", "num_classes": NUM_CLASSES})

    @pytest.mark.parametrize("average", ["micro", "macro"])
    def test_multilabel_without_positives(self, average):
        """Recall denominator is 0 everywhere."""
        for metric in ("Precision", "Recall", "F1Score"):
            _run_pair(metric, _multilabel_no_positives, {"average": average, "num_classes": NUM_CLASSES})

    def test_statscores_raw_single_class(self):
        _run_pair("StatScores", _single_class_target, {"num_classes": NUM_CLASSES, "reduce": "macro"})


class TestIgnoreIndex:
    @pytest.mark.parametrize("metric", ["Accuracy", "Precision", "Recall", "F1Score"])
    def test_all_samples_ignored(self, metric):
        """ignore_index covers EVERY sample (single-class target == ignored
        class): the reference's 0/0 outcome must be reproduced bit-for-bit
        (NaN compares equal to NaN)."""
        _run_pair(metric, _single_class_target, {"average": "micro", "num_classes": NUM_CLASSES, "ignore_index": 2})

    @pytest.mark.parametrize("mdmc", ["global", "samplewise"])
    def test_ignore_index_multidim(self, mdmc):
        _run_pair(
            "Accuracy",
            _multidim_multiclass,
            {"average": "macro", "mdmc_average": mdmc, "num_classes": NUM_CLASSES, "ignore_index": 1},
        )

    @pytest.mark.parametrize("metric", ["Accuracy", "Precision"])
    @pytest.mark.parametrize("ignore_index", [0, 4])
    def test_ignore_index_with_none_average(self, metric, ignore_index):
        _run_pair(
            metric,
            _single_class_target,
            {"average": "none", "num_classes": NUM_CLASSES, "ignore_index": ignore_index},
        )

    def test_ignore_index_above_num_classes_rejected(self):
        """Both stacks reject an ignore_index outside [0, C) at construction."""
        with pytest.raises(ValueError, match="not valid"):
            mt.Accuracy(num_classes=NUM_CLASSES, ignore_index=17)
        with pytest.raises(ValueError, match="not valid"):
            _ref.Accuracy(num_classes=NUM_CLASSES, ignore_index=17)

    def test_negative_ignore_index(self):
        """Negative ignore_index drops those target rows before scoring."""
        rng = np.random.RandomState(8)
        preds = jnp.asarray(rng.rand(1, 64, NUM_CLASSES).astype(np.float32))
        target_np = rng.randint(0, NUM_CLASSES, (1, 64))
        target_np[0, :10] = -1
        from collections import namedtuple

        case = namedtuple("Input", ["preds", "target"])(preds, jnp.asarray(target_np))
        _run_pair("Accuracy", case, {"num_classes": NUM_CLASSES, "ignore_index": -1})


class TestTopK:
    def test_top_k_num_classes_minus_one(self):
        """The largest admissible k (k = C - 1): only the argmin can miss."""
        from tests.classification.inputs import _multiclass_prob

        _run_pair("Accuracy", _multiclass_prob, {"num_classes": NUM_CLASSES, "top_k": NUM_CLASSES - 1})

    @pytest.mark.parametrize("top_k", [NUM_CLASSES, NUM_CLASSES + 2])
    def test_top_k_at_or_above_num_classes_raises(self, top_k):
        """Both stacks require k strictly smaller than C (reference
        `utilities/checks.py:202-203`)."""
        from tests.classification.inputs import _multiclass_prob

        ours = mt.Accuracy(num_classes=NUM_CLASSES, top_k=top_k)
        ref = _ref.Accuracy(num_classes=NUM_CLASSES, top_k=top_k)
        with pytest.raises(ValueError, match="strictly smaller"):
            ours.update(_multiclass_prob.preds[0], _multiclass_prob.target[0])
        with pytest.raises(ValueError, match="strictly smaller"):
            ref.update(_to_torch(_multiclass_prob.preds[0]), _to_torch(_multiclass_prob.target[0]))

    def test_top_k_on_label_preds_raises(self):
        """top_k needs probability inputs; both stacks reject label preds."""
        from tests.classification.inputs import _multiclass

        ours = mt.Accuracy(num_classes=NUM_CLASSES, top_k=2)
        ref = _ref.Accuracy(num_classes=NUM_CLASSES, top_k=2)
        with pytest.raises(ValueError):
            ours.update(_multiclass.preds[0], _multiclass.target[0])
        with pytest.raises((ValueError, RuntimeError)):
            ref.update(_to_torch(_multiclass.preds[0]), _to_torch(_multiclass.target[0]))

    @pytest.mark.parametrize("top_k", [1, 2, 4])
    def test_top_k_precision_recall(self, top_k):
        from tests.classification.inputs import _multiclass_prob

        for metric in ("Precision", "Recall"):
            _run_pair(metric, _multiclass_prob, {"num_classes": NUM_CLASSES, "top_k": top_k, "average": "macro"})


class TestSubsetAccuracy:
    @pytest.mark.parametrize("case_name", ["multilabel_prob", "multilabel_logit", "mdmc"])
    def test_subset_accuracy(self, case_name):
        from tests.classification.inputs import _multilabel_prob

        cases = {
            "multilabel_prob": _multilabel_prob,
            "multilabel_logit": _multilabel_logit,
            "mdmc": _multidim_multiclass,
        }
        _run_pair("Accuracy", cases[case_name], {"subset_accuracy": True})


class TestErrorParity:
    """Invalid configurations must fail in BOTH stacks (same error class)."""

    def test_float_target_rejected(self):
        with pytest.raises(ValueError):
            mt.Accuracy().update(jnp.asarray([0.1, 0.9]), jnp.asarray([0.0, 1.0]))
        with pytest.raises(ValueError):
            _ref.Accuracy().update(torch.tensor([0.1, 0.9]), torch.tensor([0.0, 1.0]))

    def test_negative_target_rejected(self):
        with pytest.raises(ValueError):
            mt.Accuracy().update(jnp.asarray([0.1, 0.9]), jnp.asarray([-1, 1]))
        with pytest.raises(ValueError):
            _ref.Accuracy().update(torch.tensor([0.1, 0.9]), torch.tensor([-1, 1]))

    def test_mismatched_batch_dim_rejected(self):
        with pytest.raises(ValueError):
            mt.Accuracy().update(jnp.zeros((3,)), jnp.zeros((4,), jnp.int32))
        with pytest.raises(ValueError):
            _ref.Accuracy().update(torch.zeros(3), torch.zeros(4, dtype=torch.long))

    def test_multiclass_false_with_large_target_rejected(self):
        preds = jnp.asarray([0.2, 0.7, 0.4])
        target = jnp.asarray([0, 2, 1])
        with pytest.raises(ValueError):
            mt.Accuracy(multiclass=False).update(preds, target)
        with pytest.raises(ValueError):
            _ref.Accuracy(multiclass=False).update(_to_torch(preds), _to_torch(target))

    def test_probabilities_above_one_treated_as_logits_consistently(self):
        """A pred tensor mixing values in and out of [0,1] is logits in both."""
        preds = jnp.asarray([[0.3, 1.7, -0.2], [2.0, 0.1, 0.4]])
        target = jnp.asarray([1, 0])
        ours = mt.Accuracy(num_classes=3)
        ref = _ref.Accuracy(num_classes=3)
        ours.update(preds, target)
        ref.update(_to_torch(preds), _to_torch(target))
        assert float(ours.compute()) == pytest.approx(float(ref.compute()))

    @pytest.mark.parametrize("mdmc", [None, "bogus"])
    def test_bad_mdmc_average_rejected(self, mdmc):
        from tests.classification.inputs import _multidim_multiclass

        with pytest.raises(ValueError):
            m = mt.Precision(num_classes=NUM_CLASSES, average="macro", mdmc_average=mdmc)
            m.update(_multidim_multiclass.preds[0], _multidim_multiclass.target[0])
            m.compute()
        with pytest.raises(ValueError):
            r = _ref.Precision(num_classes=NUM_CLASSES, average="macro", mdmc_average=mdmc)
            r.update(_to_torch(_multidim_multiclass.preds[0]), _to_torch(_multidim_multiclass.target[0]))
            r.compute()
