"""Native C++ text-kernel tests: build, correctness vs pure-python DPs, batching."""
import numpy as np
import pytest

from metrics_tpu import native


def _py_levenshtein(a, b):
    m, n = len(a), len(b)
    d = np.zeros((m + 1, n + 1), dtype=np.int64)
    d[:, 0] = np.arange(m + 1)
    d[0, :] = np.arange(n + 1)
    for i in range(1, m + 1):
        for j in range(1, n + 1):
            d[i, j] = min(d[i - 1, j] + 1, d[i, j - 1] + 1, d[i - 1, j - 1] + (a[i - 1] != b[j - 1]))
    return d


def _py_lcs(a, b):
    m, n = len(a), len(b)
    d = np.zeros((m + 1, n + 1), dtype=np.int64)
    for i in range(1, m + 1):
        for j in range(1, n + 1):
            d[i, j] = d[i - 1, j - 1] + 1 if a[i - 1] == b[j - 1] else max(d[i - 1, j], d[i, j - 1])
    return int(d[m, n])


needs_native = pytest.mark.skipif(not native.available(), reason="no C++ toolchain on host")


@needs_native
class TestNativeKernels:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_levenshtein_random(self, seed):
        rng = np.random.RandomState(seed)
        a = rng.randint(0, 5, size=rng.randint(0, 40)).astype(np.int32)
        b = rng.randint(0, 5, size=rng.randint(0, 40)).astype(np.int32)
        assert native.levenshtein(a, b) == int(_py_levenshtein(a, b)[len(a), len(b)])

    def test_levenshtein_known(self):
        a, b = native.intern_ids(list("kitten"), list("sitting"))
        assert native.levenshtein(a, b) == 3

    def test_matrix_matches_python(self):
        rng = np.random.RandomState(3)
        a = rng.randint(0, 4, size=12).astype(np.int32)
        b = rng.randint(0, 4, size=9).astype(np.int32)
        np.testing.assert_array_equal(native.levenshtein_matrix(a, b), _py_levenshtein(a, b))

    @pytest.mark.parametrize("seed", [0, 4])
    def test_lcs_random(self, seed):
        rng = np.random.RandomState(seed)
        a = rng.randint(0, 4, size=rng.randint(1, 30)).astype(np.int32)
        b = rng.randint(0, 4, size=rng.randint(1, 30)).astype(np.int32)
        assert native.lcs_length(a, b) == _py_lcs(a, b)

    def test_batch_apis(self):
        rng = np.random.RandomState(5)
        a_seqs = [rng.randint(0, 5, size=rng.randint(0, 25)).astype(np.int32) for _ in range(17)]
        b_seqs = [rng.randint(0, 5, size=rng.randint(0, 25)).astype(np.int32) for _ in range(17)]
        lev = native.levenshtein_batch(a_seqs, b_seqs)
        lcs = native.lcs_batch(a_seqs, b_seqs)
        for i, (a, b) in enumerate(zip(a_seqs, b_seqs)):
            assert lev[i] == int(_py_levenshtein(a, b)[len(a), len(b)])
            assert lcs[i] == _py_lcs(a, b)

    def test_empty_batch(self):
        assert native.levenshtein_batch([], []).shape == (0,)


class TestLoaderRobustness:
    def test_unwritable_cache_falls_back(self):
        # a fresh subprocess with an uncreatable cache dir must fall back to
        # python, never crash a metric call
        import subprocess
        import sys

        code = (
            "import metrics_tpu.functional.text.helper as h;"
            "print(h._edit_distance(list('ab'), list('ac')))"
        )
        env = dict(__import__("os").environ, XDG_CACHE_HOME="/dev/null/nope")
        out = subprocess.run([sys.executable, "-c", code], capture_output=True, text=True, env=env)
        assert out.returncode == 0, out.stderr
        assert out.stdout.strip() == "1"


class TestInternIds:
    def test_consistent_across_sequences(self):
        a, b = native.intern_ids(["x", "y", "x"], ["y", "z"])
        assert a.tolist() == [0, 1, 0]
        assert b.tolist() == [1, 2]


class TestMetricsUseNative:
    """The text metrics must produce identical values with and without native."""

    def test_wer_matches_fallback(self, monkeypatch):
        import metrics_tpu.functional.text.helper as helper

        preds = ["the quick brown fox jumps over the lazy dog today"] * 3
        refs = ["the quick brown cat leaps over a lazy dog"] * 3
        fast = [helper._edit_distance(p.split(), r.split()) for p, r in zip(preds, refs)]
        monkeypatch.setattr(native, "levenshtein", lambda *a: None)
        slow = [helper._edit_distance(p.split(), r.split()) for p, r in zip(preds, refs)]
        assert fast == slow

    def test_rouge_l_matches_fallback(self, monkeypatch):
        import metrics_tpu.functional.text.rouge as rouge

        pred = "the cat sat on the mat near the door".split()
        tgt = "a cat was sitting on the mat by the door".split()
        fast = rouge._lcs_length(pred, tgt)
        monkeypatch.setattr(native, "lcs_length", lambda *a: None)
        slow = rouge._lcs_length(pred, tgt)
        assert fast == slow


class TestEEDKernel:
    """Native EED CDER grid must match the python DP bit for bit (double
    precision both sides, first-min tie-break included)."""

    @pytest.mark.parametrize("seed", range(4))
    def test_random_sentences_match_python(self, seed):
        if not native.available():
            pytest.skip("no native toolchain")
        from metrics_tpu.functional.text.eed import _eed_function

        rng = np.random.RandomState(seed)
        vocab = "the cat sat on a mat dog ran fast tall grass bird hills".split()
        for _ in range(6):
            h = " " + " ".join(vocab[i] for i in rng.randint(0, len(vocab), rng.randint(3, 12))) + " "
            r = " " + " ".join(vocab[i] for i in rng.randint(0, len(vocab), rng.randint(3, 12))) + " "
            py = _eed_function(h, r)
            nat = float(
                native.eed_batch(
                    [native.codepoints(h)], [native.codepoints(r)], 2.0, 0.3, 0.2, 1.0
                )[0]
            )
            assert py == nat, (h, r, py, nat)

    def test_update_matches_fallback(self, monkeypatch):
        """The metric value must be identical with the native path disabled
        (and the batched path must actually engage when available)."""
        from metrics_tpu.functional.text import eed as eed_mod

        preds = ["this is the prediction", "here is an other sample"]
        target = [["this is the reference", "an other reference too"], ["here is another one"]]
        fast = eed_mod._eed_update(preds, target)
        monkeypatch.setattr(eed_mod.native, "eed_batch", lambda *a, **k: None)
        slow = eed_mod._eed_update(preds, target)
        np.testing.assert_allclose(
            [float(v) for v in fast], [float(v) for v in slow], rtol=1e-6
        )

    def test_edge_shapes(self):
        if not native.available():
            pytest.skip("no native toolchain")
        # empty hypothesis, single-char pairs, all-space reference
        out = native.eed_batch(
            [native.codepoints(""), native.codepoints("a"), native.codepoints("ab")],
            [native.codepoints("abc"), native.codepoints("a"), native.codepoints("   ")],
            2.0, 0.3, 0.2, 1.0,
        )
        from metrics_tpu.functional.text.eed import _eed_function

        want = [_eed_function("", "abc"), _eed_function("a", "a"), _eed_function("ab", "   ")]
        np.testing.assert_allclose(np.asarray(out), want, rtol=0, atol=0)
