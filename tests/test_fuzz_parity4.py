"""Fuzz-parity wave 4: the raw-row deferral paths under hostile streams.

Round 4 moved cat-state canonicalization out of ``update`` (raw-row
buffering — `docs/performance.md`). This wave fuzzes exactly the edges that
rework touched, always against the mounted reference: random batch ranks
and dtypes, heterogeneous extra dims across batches, ``ignore_index``
filtering, and OBSERVATIONS INTERLEAVED MID-STREAM (canonicalization hook,
pickle round-trip, state_dict) — the result must match the reference no
matter when the rows were canonicalized.
"""
from __future__ import annotations

import pickle

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
pytestmark = [
    pytest.mark.skipif(_ref is None, reason="reference mount unavailable"),
    pytest.mark.slow,  # deep-coverage tier (see docs/testing.md)
]

import metrics_tpu as mt  # noqa: E402

N_VARIATIONS = 4


def _observe(m, rng):
    """Randomly observe the metric mid-stream; must not perturb the result."""
    k = rng.randint(0, 3)
    if k == 0:
        m._canonicalize_list_states()
        return m
    if k == 1:
        return pickle.loads(pickle.dumps(m))
    m.persistent(True)
    m.state_dict()
    return m


@pytest.mark.parametrize("seed", range(N_VARIATIONS))
@pytest.mark.parametrize(
    "name,kwargs",
    [
        ("RetrievalMRR", {}),
        ("RetrievalMAP", {"ignore_index": -1}),
        ("RetrievalNormalizedDCG", {}),
        ("RetrievalFallOut", {"ignore_index": -1}),
        ("RetrievalPrecision", {"k": 3}),
    ],
)
def test_retrieval_raw_rows_fuzz(name, kwargs, seed):
    rng = np.random.RandomState(100 + seed)
    ours = getattr(mt, name)(**kwargs)
    ref = getattr(_ref, name)(**kwargs)
    for _ in range(rng.randint(2, 5)):
        # random rank: flat rows or (queries, docs) matrices
        if rng.rand() < 0.5:
            q, d = rng.randint(2, 5), rng.randint(4, 9)
            shape = (q, d)
            idx = np.repeat(np.arange(q), d).reshape(q, d)
        else:
            n = rng.randint(8, 33)
            shape = (n,)
            idx = rng.randint(0, 4, n)
        preds = rng.rand(*shape).astype(np.float32)
        target = rng.randint(0, 2, shape)
        if kwargs.get("ignore_index") == -1:
            mask = rng.rand(*shape) < 0.2
            target = np.where(mask & (target.sum() > 1), -1, target)
        ours.update(jnp.asarray(preds), jnp.asarray(target), jnp.asarray(idx))
        ref.update(torch.tensor(preds), torch.tensor(target), indexes=torch.tensor(idx))
        ours = _observe(ours, rng)
    np.testing.assert_allclose(
        np.asarray(ours.compute()), ref.compute().numpy(), atol=1e-5, rtol=1e-4
    )


@pytest.mark.parametrize("seed", range(N_VARIATIONS))
@pytest.mark.parametrize("case", ["binary", "multiclass", "multidim_varying", "multilabel"])
def test_exact_curves_raw_rows_fuzz(case, seed):
    rng = np.random.RandomState(200 + seed)
    C = 4
    if case == "binary":
        ours, ref = mt.PrecisionRecallCurve(pos_label=1), _ref.PrecisionRecallCurve(pos_label=1)
        make = lambda: (rng.rand(rng.randint(8, 33)).astype(np.float32),)
        batches = [(p, rng.randint(0, 2, p.shape[0])) for (p,) in (make() for _ in range(3))]
    elif case == "multiclass":
        ours, ref = mt.PrecisionRecallCurve(num_classes=C), _ref.PrecisionRecallCurve(num_classes=C)
        batches = []
        for _ in range(3):
            n = rng.randint(8, 33)
            p = rng.rand(n, C).astype(np.float32)
            batches.append((p / p.sum(1, keepdims=True), rng.randint(0, C, n)))
    elif case == "multidim_varying":
        # extra dim varies per batch: hits the heterogeneous-shape fallback
        ours, ref = mt.PrecisionRecallCurve(num_classes=C), _ref.PrecisionRecallCurve(num_classes=C)
        batches = []
        for x in rng.randint(2, 7, size=3):
            n = rng.randint(4, 9)
            p = rng.rand(n, C, x).astype(np.float32)
            batches.append((p / p.sum(1, keepdims=True), rng.randint(0, C, (n, x))))
    else:  # multilabel
        ours, ref = mt.PrecisionRecallCurve(num_classes=C), _ref.PrecisionRecallCurve(num_classes=C)
        batches = []
        for _ in range(3):
            n = rng.randint(8, 33)
            batches.append((rng.rand(n, C).astype(np.float32), rng.randint(0, 2, (n, C))))
    for p, t in batches:
        ours.update(jnp.asarray(p), jnp.asarray(t))
        ref.update(torch.tensor(p), torch.tensor(t))
        ours = _observe(ours, rng)
    a, b = ours.compute(), ref.compute()
    for xs, ys in zip(a, b):
        xs = xs if isinstance(xs, list) else [xs]
        ys = ys if isinstance(ys, list) else [ys]
        for x, y in zip(xs, ys):
            np.testing.assert_allclose(np.asarray(x), y.numpy(), atol=1e-5, rtol=1e-4)


@pytest.mark.parametrize("seed", range(N_VARIATIONS))
@pytest.mark.parametrize("mode", ["binary", "multiclass", "multilabel"])
def test_auroc_raw_rows_fuzz(mode, seed):
    rng = np.random.RandomState(300 + seed)
    C = 4
    if mode == "binary":
        ours, ref = mt.AUROC(pos_label=1), _ref.AUROC(pos_label=1)
        batches = [
            (rng.rand(n).astype(np.float32), rng.randint(0, 2, n))
            for n in rng.randint(16, 49, size=3)
        ]
    elif mode == "multiclass":
        ours, ref = mt.AUROC(num_classes=C), _ref.AUROC(num_classes=C)
        batches = []
        for n in rng.randint(16, 49, size=3):
            p = rng.rand(n, C).astype(np.float32)
            t = rng.randint(0, C, n)
            t[:C] = np.arange(C)  # every class present
            batches.append((p / p.sum(1, keepdims=True), t))
    else:
        ours, ref = mt.AUROC(num_classes=C, average="macro"), _ref.AUROC(num_classes=C, average="macro")
        batches = []
        for n in rng.randint(16, 49, size=3):
            t = rng.randint(0, 2, (n, C))
            t[0], t[1] = 0, 1  # no degenerate single-class columns
            batches.append((rng.rand(n, C).astype(np.float32), t))
    for p, t in batches:
        ours.update(jnp.asarray(p), jnp.asarray(t))
        ref.update(torch.tensor(p), torch.tensor(t))
        ours = _observe(ours, rng)
    np.testing.assert_allclose(
        np.asarray(ours.compute()), ref.compute().numpy(), atol=1e-5, rtol=1e-4
    )


@pytest.mark.parametrize("seed", range(N_VARIATIONS))
def test_regression_and_cat_raw_rows_fuzz(seed):
    rng = np.random.RandomState(400 + seed)
    pairs = [
        (mt.SpearmanCorrCoef(), _ref.SpearmanCorrCoef(), True),
        (mt.CosineSimilarity(reduction="mean"), _ref.CosineSimilarity(reduction="mean"), False),
        (mt.CatMetric(), _ref.CatMetric(), None),
    ]
    for ours, ref, flat in pairs:
        for _ in range(3):
            n = rng.randint(8, 33)
            if flat is None:  # CatMetric: any shape
                v = rng.randn(n).astype(np.float32)
                ours.update(jnp.asarray(v))
                ref.update(torch.tensor(v))
            elif flat:
                p, t = rng.randn(n).astype(np.float32), rng.randn(n).astype(np.float32)
                ours.update(jnp.asarray(p), jnp.asarray(t))
                ref.update(torch.tensor(p), torch.tensor(t))
            else:
                p = rng.randn(n, 6).astype(np.float32)
                t = (p + 0.3 * rng.randn(n, 6)).astype(np.float32)
                ours.update(jnp.asarray(p), jnp.asarray(t))
                ref.update(torch.tensor(p), torch.tensor(t))
            ours = _observe(ours, rng)
        np.testing.assert_allclose(
            np.asarray(ours.compute()).ravel(), ref.compute().numpy().ravel(), atol=1e-5, rtol=1e-4
        )


@pytest.mark.parametrize("seed", range(N_VARIATIONS))
def test_multioutput_fused_fanout_vs_reference(seed):
    """The one-program column fan-out (remove_nans=False, deterministic) must
    match the reference's per-column eager wrapper exactly."""
    from metrics_tpu.utils import checks

    rng = np.random.RandomState(600 + seed)
    n_out = int(rng.choice([3, 8]))
    prev_mode = checks._get_validation_mode()
    try:
        checks.set_validation_mode("first")
        ours = mt.MultioutputWrapper(mt.MeanSquaredError(), num_outputs=n_out, remove_nans=False)
        ref = _ref.MultioutputWrapper(_ref.MeanSquaredError(), num_outputs=n_out, remove_nans=False)
        n = rng.randint(8, 33)  # fixed per stream: fusion engages on the repeat
        for _ in range(3):
            p = rng.randn(n, n_out).astype(np.float32)
            t = (p + 0.3 * rng.randn(n, n_out)).astype(np.float32)
            ours.update(jnp.asarray(p), jnp.asarray(t))
            ref.update(torch.tensor(p), torch.tensor(t))
        assert ours._mo_program is not None  # fused path actually exercised
        np.testing.assert_allclose(
            [float(v) for v in ours.compute()],
            [float(v) for v in ref.compute()],
            rtol=1e-5,
        )
    finally:
        checks.set_validation_mode(prev_mode)


@pytest.mark.parametrize("seed", range(N_VARIATIONS))
@pytest.mark.parametrize("name", ["UniversalImageQualityIndex", "SpectralAngleMapper"])
def test_image_raw_rows_fuzz(name, seed):
    rng = np.random.RandomState(500 + seed)
    ours, ref = getattr(mt, name)(), getattr(_ref, name)()
    for _ in range(2):
        b = rng.randint(1, 4)
        t = rng.rand(b, 3, 16, 16).astype(np.float32)
        p = np.clip(t + 0.05 * rng.randn(*t.shape), 0, 1).astype(np.float32)
        ours.update(jnp.asarray(p), jnp.asarray(t))
        ref.update(torch.tensor(p), torch.tensor(t))
        ours = _observe(ours, rng)
    np.testing.assert_allclose(
        np.asarray(ours.compute()), ref.compute().numpy(), atol=1e-4, rtol=1e-4
    )
