"""Run every docstring example as a test (reference `Makefile:22-25` parity).

Doctests execute on the pinned 8-device CPU backend (tests/conftest.py), which
is what the expected strings were generated on; float formatting is platform-
deterministic there. Running doctests directly on a TPU backend can print
last-ulp-different values for a handful of reduction-heavy examples (different
fma/reduction order) — that is expected; the CPU run is the contract, same as
the reference generating its tensor reprs on its CPU CI.
"""
import contextlib
import doctest
import importlib
import io
import pkgutil

import pytest

import metrics_tpu

_SKIP_SUBSTRINGS = (
    ".models",  # flax model defs: no examples, heavy imports
    "native",  # ctypes loader: no examples
)


def _module_names():
    names = ["metrics_tpu"]
    for m in pkgutil.walk_packages(metrics_tpu.__path__, prefix="metrics_tpu."):
        if any(s in m.name for s in _SKIP_SUBSTRINGS):
            continue
        names.append(m.name)
    return names


def test_every_export_has_an_example():
    """Every exported metric symbol carries an executable docstring example.

    Reference parity: its docs build fails on example-less metrics and every
    example runs in CI (reference `Makefile:22-25`). Model-backed symbols keep
    ``# doctest: +SKIP`` examples (weights unfetchable here) — presence is
    still enforced.
    """
    import inspect

    import metrics_tpu.functional as functional

    missing = []
    for name in metrics_tpu.__all__:
        obj = getattr(metrics_tpu, name)
        if not (inspect.isclass(obj) or inspect.isfunction(obj)):
            continue  # __version__, the functional subpackage handle, ...
        if ">>>" not in (obj.__doc__ or ""):
            missing.append(f"metrics_tpu.{name}")
    for name in functional.__all__:
        if ">>>" not in (getattr(functional, name).__doc__ or ""):
            missing.append(f"functional.{name}")
    assert not missing, f"exports without a docstring example: {missing}"


@pytest.mark.parametrize("module_name", _module_names())
def test_module_doctests(module_name):
    try:
        mod = importlib.import_module(module_name)
    except ModuleNotFoundError as err:
        pytest.skip(f"optional dependency missing: {err}")
    finder = doctest.DocTestFinder(exclude_empty=True)
    runner = doctest.DocTestRunner(optionflags=doctest.NORMALIZE_WHITESPACE)
    failures = []
    for test in finder.find(mod, module_name):
        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            result = runner.run(test, out=out.write)
        if result.failed:
            failures.append(out.getvalue())
    assert not failures, "\n".join(failures)
