"""Run every docstring example as a test (reference `Makefile:22-25` parity).

Doctests execute on the pinned 8-device CPU backend (tests/conftest.py), which
is what the expected strings were generated on; float formatting is platform-
deterministic there. Running doctests directly on a TPU backend can print
last-ulp-different values for a handful of reduction-heavy examples (different
fma/reduction order) — that is expected; the CPU run is the contract, same as
the reference generating its tensor reprs on its CPU CI.
"""
import contextlib
import doctest
import importlib
import io
import pkgutil

import pytest

import metrics_tpu

_SKIP_SUBSTRINGS = (
    ".models",  # flax model defs: no examples, heavy imports
    "native",  # ctypes loader: no examples
)


def _module_names():
    names = ["metrics_tpu"]
    for m in pkgutil.walk_packages(metrics_tpu.__path__, prefix="metrics_tpu."):
        if any(s in m.name for s in _SKIP_SUBSTRINGS):
            continue
        names.append(m.name)
    return names


@pytest.mark.parametrize("module_name", _module_names())
def test_module_doctests(module_name):
    try:
        mod = importlib.import_module(module_name)
    except ModuleNotFoundError as err:
        pytest.skip(f"optional dependency missing: {err}")
    finder = doctest.DocTestFinder(exclude_empty=True)
    runner = doctest.DocTestRunner(optionflags=doctest.NORMALIZE_WHITESPACE)
    failures = []
    for test in finder.find(mod, module_name):
        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            result = runner.run(test, out=out.write)
        if result.failed:
            failures.append(out.getvalue())
    assert not failures, "\n".join(failures)
