"""Long-context metric evaluation: sequence-parallel state accumulation.

The framework's "long-sequence" axis (SURVEY §5): metric state is O(1) per
device, so a sequence too long for one chip's HBM is evaluated by sharding the
*sequence* dimension over a mesh axis — each device folds its sequence shard
into sum-states, one ``psum`` combines them. Token-level metrics (Perplexity,
Accuracy over next-token predictions) never materialize the full sequence
anywhere. The same program scales batch over ``dp`` and sequence over ``sp``
simultaneously, the way a context-parallel training loop shards activations.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import metrics_tpu as mt

VOCAB = 32
PAD = 0


def shard_map(f, **kw):
    kw.setdefault("check_vma", False)
    return jax.shard_map(f, **kw)


def _sequence(seed: int, batch: int, seq: int):
    rng = np.random.RandomState(seed)
    logits = rng.randn(batch, seq, VOCAB).astype(np.float32)
    target = rng.randint(1, VOCAB, size=(batch, seq))
    # pad tail of each row — exercises masked counting across shard boundaries
    pad_len = rng.randint(0, seq // 4, size=batch)
    for i, n in enumerate(pad_len):
        if n:
            target[i, -n:] = PAD
    return logits, target


def test_sequence_parallel_perplexity():
    """Perplexity over a sequence sharded 8-way equals the unsharded value;
    only O(1) state crosses devices (one psum for two scalars)."""
    logits, target = _sequence(0, batch=2, seq=1024)
    mesh = Mesh(np.array(jax.devices()[:8]), ("sp",))
    init, upd, cmp = mt.Perplexity(ignore_index=PAD).as_functions()

    def f(lg, tg):
        return cmp(upd(init(), lg, tg), axis_name="sp")

    sharded = jax.jit(
        shard_map(f, mesh=mesh, in_specs=(P(None, "sp", None), P(None, "sp")), out_specs=P())
    )(jnp.asarray(logits), jnp.asarray(target))

    oracle = mt.Perplexity(ignore_index=PAD)
    oracle.update(logits, target)
    np.testing.assert_allclose(float(sharded), float(oracle.compute()), rtol=1e-6)


def test_dp_sp_2d_mesh_perplexity():
    """Batch over dp AND sequence over sp in one program: state syncs over
    both axes with a single fused collective."""
    logits, target = _sequence(1, batch=4, seq=512)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dp", "sp"))
    init, upd, cmp = mt.Perplexity(ignore_index=PAD).as_functions()

    def f(lg, tg):
        return cmp(upd(init(), lg, tg), axis_name=("dp", "sp"))

    sharded = jax.jit(
        shard_map(f, mesh=mesh, in_specs=(P("dp", "sp", None), P("dp", "sp")), out_specs=P())
    )(jnp.asarray(logits), jnp.asarray(target))

    oracle = mt.Perplexity(ignore_index=PAD)
    oracle.update(logits, target)
    np.testing.assert_allclose(float(sharded), float(oracle.compute()), rtol=1e-6)


def test_sequence_parallel_token_accuracy():
    """Next-token accuracy with the sequence axis sharded — the multidim
    input-format engine runs identically inside each shard."""
    rng = np.random.RandomState(2)
    seq = 2048
    logits = rng.randn(1, VOCAB, seq).astype(np.float32)  # (N, C, d) multidim layout
    target = rng.randint(0, VOCAB, size=(1, seq))
    mesh = Mesh(np.array(jax.devices()[:8]), ("sp",))
    init, upd, cmp = mt.Accuracy(num_classes=VOCAB, mdmc_average="global").as_functions()

    def f(lg, tg):
        return cmp(upd(init(), lg, tg), axis_name="sp")

    sharded = jax.jit(
        shard_map(f, mesh=mesh, in_specs=(P(None, None, "sp"), P(None, "sp")), out_specs=P())
    )(jnp.asarray(logits), jnp.asarray(target))

    oracle = mt.Accuracy(num_classes=VOCAB, mdmc_average="global")
    oracle.update(logits, target)
    np.testing.assert_allclose(float(sharded), float(oracle.compute()), rtol=1e-6)


def test_scan_over_context_chunks():
    """A sequence processed as lax.scan over chunks — streaming evaluation of
    arbitrarily long contexts in bounded memory, state threaded functionally."""
    logits, target = _sequence(3, batch=1, seq=4096)
    chunks = 16
    lg = jnp.asarray(logits).reshape(chunks, 1, -1, VOCAB)
    tg = jnp.asarray(target).reshape(chunks, 1, -1)
    init, upd, cmp = mt.Perplexity(ignore_index=PAD).as_functions()

    @jax.jit
    def streamed(lg, tg):
        def body(state, xt):
            return upd(state, xt[0], xt[1]), 0.0

        state, _ = jax.lax.scan(body, init(), (lg, tg))
        return cmp(state)

    oracle = mt.Perplexity(ignore_index=PAD)
    oracle.update(logits, target)
    np.testing.assert_allclose(float(streamed(lg, tg)), float(oracle.compute()), rtol=1e-6)
