"""Training-loop integration tests — the Lightning-integration analogue.

Parity target: reference `tests/integrations/test_lightning.py` (metrics
logged from inside a training module via ``forward``/``compute``, reset
between stages, state moving with checkpoints) re-expressed for a Flax/optax
loop: the "trainer" is a plain python loop (eager module API) or a jitted
SPMD step (pure-function API), and "self.log" is reading ``forward``'s
return value every step.
"""
from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax import linen as nn
from jax.sharding import Mesh, PartitionSpec as P

import metrics_tpu as mt

DIN, HIDDEN, NUM_CLASSES = 8, 16, 4
BATCH, STEPS = 32, 6


class _MLP(nn.Module):
    """The BoringModel analogue (reference tests/integrations/lightning/boring_model.py)."""

    @nn.compact
    def __call__(self, x):
        return nn.Dense(NUM_CLASSES)(nn.relu(nn.Dense(HIDDEN)(x)))


def _data(seed: int, steps: int = STEPS):
    rng = np.random.RandomState(seed)
    xs = rng.randn(steps, BATCH, DIN).astype(np.float32)
    ys = rng.randint(0, NUM_CLASSES, size=(steps, BATCH))
    return xs, ys


def _train_setup():
    model = _MLP()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, DIN)))
    opt = optax.sgd(1e-2)
    return model, params, opt, opt.init(params)


class TestTrainLoopModuleAPI:
    """Eager loop + stateful metrics: the `self.log(metric)` pattern."""

    def test_forward_logging_matches_epoch_compute(self):
        model, params, opt, opt_state = _train_setup()
        xs, ys = _data(0)
        metric = mt.Accuracy(num_classes=NUM_CLASSES)
        step_vals, all_logits = [], []

        @jax.jit
        def train_step(params, opt_state, xb, yb):
            def loss_fn(p):
                logits = model.apply(p, xb)
                return optax.softmax_cross_entropy_with_integer_labels(logits, yb).mean(), logits

            (_, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, logits

        for xb, yb in zip(xs, ys):
            params, opt_state, logits = train_step(params, opt_state, xb, yb)
            step_vals.append(float(metric(jax.nn.softmax(logits), yb)))  # "self.log" value
            all_logits.append(np.asarray(logits))

        # per-step forward value is the batch-local metric
        for logits, yb, v in zip(all_logits, ys, step_vals):
            assert v == pytest.approx(float(np.mean(logits.argmax(-1) == yb)))
        # epoch-end compute is the metric over ALL logged batches
        expected = np.mean(np.concatenate([l.argmax(-1) for l in all_logits]) == ys.reshape(-1))
        assert float(metric.compute()) == pytest.approx(float(expected))

    def test_reset_between_stages(self):
        """Train-stage state must not leak into the val stage (reference
        test_lightning.py reset-between-stages contract)."""
        metric = mt.Accuracy(num_classes=NUM_CLASSES)
        xs, ys = _data(1)
        preds = jax.nn.one_hot(jnp.asarray(ys[0]), NUM_CLASSES)
        metric.update(preds, ys[0])  # "train": all correct
        assert float(metric.compute()) == 1.0
        metric.reset()
        assert not metric.update_called
        wrong = jnp.roll(preds, 1, axis=-1)
        metric.update(wrong, ys[0])  # "val": all wrong
        assert float(metric.compute()) == 0.0

    def test_collection_log_dict(self):
        """MetricCollection.forward == the `self.log_dict(collection)` pattern."""
        suite = mt.MetricCollection(
            {
                "acc": mt.Accuracy(num_classes=NUM_CLASSES),
                "f1": mt.F1Score(num_classes=NUM_CLASSES, average="macro"),
            },
            prefix="train_",
        )
        xs, ys = _data(2)
        rng = np.random.RandomState(3)
        for yb in ys:
            pb = rng.rand(BATCH, NUM_CLASSES).astype(np.float32)
            logged = suite(pb / pb.sum(-1, keepdims=True), yb)
            assert set(logged) == {"train_acc", "train_f1"}
        final = suite.compute()
        assert set(final) == {"train_acc", "train_f1"}
        suite.reset()
        assert all(not m.update_called for m in suite.values(copy_state=False))

    def test_checkpoint_mid_epoch_resume(self):
        """state_dict → new instance → resume must equal the uninterrupted run
        (reference persistence contract, metric.py:662-700)."""
        xs, ys = _data(4)
        rng = np.random.RandomState(5)
        probs = rng.rand(STEPS, BATCH, NUM_CLASSES).astype(np.float32)

        uninterrupted = mt.Accuracy(num_classes=NUM_CLASSES)
        for pb, yb in zip(probs, ys):
            uninterrupted.update(pb, yb)

        first = mt.Accuracy(num_classes=NUM_CLASSES)
        first.persistent(True)
        for pb, yb in zip(probs[: STEPS // 2], ys[: STEPS // 2]):
            first.update(pb, yb)
        ckpt = first.state_dict()

        resumed = mt.Accuracy(num_classes=NUM_CLASSES)
        resumed.persistent(True)
        resumed.load_state_dict(ckpt)
        for pb, yb in zip(probs[STEPS // 2 :], ys[STEPS // 2 :]):
            resumed.update(pb, yb)
        assert float(resumed.compute()) == pytest.approx(float(uninterrupted.compute()))


class TestTrainLoopSPMD:
    """Jitted sharded train step with device-resident metric state."""

    def test_dp_train_step_metric_sync(self):
        """Metric accumulated inside a shard_map dp-train step, synced by
        fused collectives at compute, must equal the single-device value."""
        model, params, opt, opt_state = _train_setup()
        xs, ys = _data(6)
        devices = np.array(jax.devices()[:4])
        mesh = Mesh(devices, ("dp",))
        init, upd, cmp = mt.Accuracy(num_classes=NUM_CLASSES).as_functions()

        def step(params, opt_state, mstate, xb, yb):
            def loss_fn(p):
                logits = model.apply(p, xb)
                return optax.softmax_cross_entropy_with_integer_labels(logits, yb).mean(), logits

            (_, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            grads = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), grads)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            mstate = upd(mstate, jax.nn.softmax(logits), yb)
            return params, opt_state, mstate, logits

        sharded = jax.jit(
            jax.shard_map(
                step,
                mesh=mesh,
                in_specs=(P(), P(), P(), P("dp"), P("dp")),
                out_specs=(P(), P(), P(), P("dp")),
                check_vma=False,
            )
        )
        compute_synced = jax.jit(
            jax.shard_map(
                partial(cmp, axis_name="dp"),
                mesh=mesh,
                in_specs=(P(),),
                out_specs=P(),
                check_vma=False,
            )
        )

        # single-device oracle running the same math
        oracle = mt.Accuracy(num_classes=NUM_CLASSES)
        o_params, o_opt_state = params, opt_state
        mstate = init()
        for xb, yb in zip(xs, ys):
            params, opt_state, mstate, logits = sharded(params, opt_state, mstate, xb, yb)

            def loss_fn(p):
                lg = model.apply(p, xb)
                return optax.softmax_cross_entropy_with_integer_labels(lg, yb).mean(), lg

            (_, o_logits), o_grads = jax.value_and_grad(loss_fn, has_aux=True)(o_params)
            o_updates, o_opt_state = opt.update(o_grads, o_opt_state, o_params)
            o_params = optax.apply_updates(o_params, o_updates)
            oracle.update(jax.nn.softmax(o_logits), yb)
            np.testing.assert_allclose(np.asarray(logits), np.asarray(o_logits), atol=1e-5)

        np.testing.assert_allclose(
            float(compute_synced(mstate)), float(oracle.compute()), atol=1e-6
        )

    def test_scan_over_epoch(self):
        """An entire epoch as ONE program: metric state threaded through
        lax.scan — no host dispatch between steps."""
        xs, ys = _data(7)
        init, upd, cmp = mt.MeanMetric().as_functions()
        losses = jnp.abs(jnp.asarray(xs)).mean(axis=(1, 2))  # stand-in per-step losses

        @jax.jit
        def epoch(state, losses):
            def body(st, loss):
                return upd(st, loss), loss

            st, _ = jax.lax.scan(body, state, losses)
            return cmp(st)

        assert float(epoch(init(), losses)) == pytest.approx(float(losses.mean()), rel=1e-6)


@pytest.mark.slow
def test_batched_eval_example_runs():
    """examples/batched_eval.py end to end: the fully-seeded run must print
    the exact epoch totals (pinned below) and the analytically-known MSE."""
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, "examples/batched_eval.py"],
        capture_output=True,
        text=True,
        timeout=560,
        cwd=os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    )
    assert out.returncode == 0, out.stderr[-800:]
    assert "epoch: acc=0.1272 f1=0.1272 confmat.sum=65536" in out.stdout
    assert "MSE over 2 chunks: 0.010000" in out.stdout  # (0.1)^2 exactly
