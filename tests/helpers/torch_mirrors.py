"""Torch-side architecture mirrors used as numerical oracles for model parity.

This environment has no network egress, so the pretrained checkpoints the
reference consumes (torch-fidelity's InceptionV3 for FID/KID/IS —
`/root/reference/src/torchmetrics/image/fid.py:27-45` — and the ``lpips``
package nets — `image/lpip.py:24-40`) cannot be downloaded. What CAN be
proven here is the part that actually goes wrong in practice: that the Flax
models in ``metrics_tpu/models/`` implement the same architecture, tap the
same activations in the same order, and that the weight converters map every
torch parameter to the right Flax leaf with the right layout.

These mirrors are written directly against torch.nn from the published
architecture descriptions (Szegedy et al. 2015 TF-Slim InceptionV3 with
1008-way logits; Zhang et al. 2018 LPIPS over torchvision AlexNet). Their
``state_dict()`` uses the same key naming as the real checkpoints, so the
production converters (`tools/convert_inception_weights.py`,
`tools/convert_lpips_weights.py`) run unmodified on them. A golden test that
passes torch-mirror weights through the converter into the Flax model and
matches taps/end-to-end numbers therefore fails on any tap-ordering,
pooling-mode, padding, or converter-layout drift — exactly the bugs that
would silently corrupt FID/KID/IS/LPIPS once real weights are loaded.
"""
from __future__ import annotations

from typing import Dict

import torch
import torch.nn.functional as F
from torch import nn


class _ConvBN(nn.Module):
    """Bias-free conv + inference BatchNorm(eps=1e-3) + ReLU (tf-compat block)."""

    def __init__(self, cin: int, cout: int, kernel, stride=1, padding=0) -> None:
        super().__init__()
        self.conv = nn.Conv2d(cin, cout, kernel, stride, padding, bias=False)
        self.bn = nn.BatchNorm2d(cout, eps=1e-3)

    def forward(self, x: torch.Tensor) -> torch.Tensor:
        return F.relu(self.bn(self.conv(x)))


def _avg3(x: torch.Tensor) -> torch.Tensor:
    return F.avg_pool2d(x, 3, stride=1, padding=1, count_include_pad=False)


class _MirrorA(nn.Module):
    def __init__(self, cin: int, pool_features: int) -> None:
        super().__init__()
        self.branch1x1 = _ConvBN(cin, 64, 1)
        self.branch5x5_1 = _ConvBN(cin, 48, 1)
        self.branch5x5_2 = _ConvBN(48, 64, 5, padding=2)
        self.branch3x3dbl_1 = _ConvBN(cin, 64, 1)
        self.branch3x3dbl_2 = _ConvBN(64, 96, 3, padding=1)
        self.branch3x3dbl_3 = _ConvBN(96, 96, 3, padding=1)
        self.branch_pool = _ConvBN(cin, pool_features, 1)

    def forward(self, x: torch.Tensor) -> torch.Tensor:
        return torch.cat(
            [
                self.branch1x1(x),
                self.branch5x5_2(self.branch5x5_1(x)),
                self.branch3x3dbl_3(self.branch3x3dbl_2(self.branch3x3dbl_1(x))),
                self.branch_pool(_avg3(x)),
            ],
            dim=1,
        )


class _MirrorB(nn.Module):
    def __init__(self, cin: int) -> None:
        super().__init__()
        self.branch3x3 = _ConvBN(cin, 384, 3, stride=2)
        self.branch3x3dbl_1 = _ConvBN(cin, 64, 1)
        self.branch3x3dbl_2 = _ConvBN(64, 96, 3, padding=1)
        self.branch3x3dbl_3 = _ConvBN(96, 96, 3, stride=2)

    def forward(self, x: torch.Tensor) -> torch.Tensor:
        return torch.cat(
            [
                self.branch3x3(x),
                self.branch3x3dbl_3(self.branch3x3dbl_2(self.branch3x3dbl_1(x))),
                F.max_pool2d(x, 3, stride=2),
            ],
            dim=1,
        )


class _MirrorC(nn.Module):
    def __init__(self, cin: int, c7: int) -> None:
        super().__init__()
        self.branch1x1 = _ConvBN(cin, 192, 1)
        self.branch7x7_1 = _ConvBN(cin, c7, 1)
        self.branch7x7_2 = _ConvBN(c7, c7, (1, 7), padding=(0, 3))
        self.branch7x7_3 = _ConvBN(c7, 192, (7, 1), padding=(3, 0))
        self.branch7x7dbl_1 = _ConvBN(cin, c7, 1)
        self.branch7x7dbl_2 = _ConvBN(c7, c7, (7, 1), padding=(3, 0))
        self.branch7x7dbl_3 = _ConvBN(c7, c7, (1, 7), padding=(0, 3))
        self.branch7x7dbl_4 = _ConvBN(c7, c7, (7, 1), padding=(3, 0))
        self.branch7x7dbl_5 = _ConvBN(c7, 192, (1, 7), padding=(0, 3))
        self.branch_pool = _ConvBN(cin, 192, 1)

    def forward(self, x: torch.Tensor) -> torch.Tensor:
        b7 = self.branch7x7_3(self.branch7x7_2(self.branch7x7_1(x)))
        bd = self.branch7x7dbl_1(x)
        for mod in (self.branch7x7dbl_2, self.branch7x7dbl_3, self.branch7x7dbl_4, self.branch7x7dbl_5):
            bd = mod(bd)
        return torch.cat([self.branch1x1(x), b7, bd, self.branch_pool(_avg3(x))], dim=1)


class _MirrorD(nn.Module):
    def __init__(self, cin: int) -> None:
        super().__init__()
        self.branch3x3_1 = _ConvBN(cin, 192, 1)
        self.branch3x3_2 = _ConvBN(192, 320, 3, stride=2)
        self.branch7x7x3_1 = _ConvBN(cin, 192, 1)
        self.branch7x7x3_2 = _ConvBN(192, 192, (1, 7), padding=(0, 3))
        self.branch7x7x3_3 = _ConvBN(192, 192, (7, 1), padding=(3, 0))
        self.branch7x7x3_4 = _ConvBN(192, 192, 3, stride=2)

    def forward(self, x: torch.Tensor) -> torch.Tensor:
        b7 = self.branch7x7x3_1(x)
        for mod in (self.branch7x7x3_2, self.branch7x7x3_3, self.branch7x7x3_4):
            b7 = mod(b7)
        return torch.cat(
            [self.branch3x3_2(self.branch3x3_1(x)), b7, F.max_pool2d(x, 3, stride=2)], dim=1
        )


class _MirrorE(nn.Module):
    def __init__(self, cin: int, pool_type: str) -> None:
        super().__init__()
        self.pool_type = pool_type
        self.branch1x1 = _ConvBN(cin, 320, 1)
        self.branch3x3_1 = _ConvBN(cin, 384, 1)
        self.branch3x3_2a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.branch3x3_2b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.branch3x3dbl_1 = _ConvBN(cin, 448, 1)
        self.branch3x3dbl_2 = _ConvBN(448, 384, 3, padding=1)
        self.branch3x3dbl_3a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.branch3x3dbl_3b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.branch_pool = _ConvBN(cin, 192, 1)

    def forward(self, x: torch.Tensor) -> torch.Tensor:
        b3 = self.branch3x3_1(x)
        b3 = torch.cat([self.branch3x3_2a(b3), self.branch3x3_2b(b3)], dim=1)
        bd = self.branch3x3dbl_2(self.branch3x3dbl_1(x))
        bd = torch.cat([self.branch3x3dbl_3a(bd), self.branch3x3dbl_3b(bd)], dim=1)
        if self.pool_type == "avg":
            bp = _avg3(x)
        else:
            bp = F.max_pool2d(x, 3, stride=1, padding=1)
        return torch.cat([self.branch1x1(x), b3, bd, self.branch_pool(bp)], dim=1)


class TorchInceptionMirror(nn.Module):
    """TF-compat InceptionV3 trunk returning the same tap dict as the Flax model.

    State-dict keys follow the torch-fidelity checkpoint naming
    (``Conv2d_1a_3x3.conv.weight``, ``Mixed_5b.branch1x1.bn.running_mean``,
    ``fc.weight``…) so ``convert_state_dict`` applies directly.
    """

    def __init__(self, num_classes: int = 1008) -> None:
        super().__init__()
        self.Conv2d_1a_3x3 = _ConvBN(3, 32, 3, stride=2)
        self.Conv2d_2a_3x3 = _ConvBN(32, 32, 3)
        self.Conv2d_2b_3x3 = _ConvBN(32, 64, 3, padding=1)
        self.Conv2d_3b_1x1 = _ConvBN(64, 80, 1)
        self.Conv2d_4a_3x3 = _ConvBN(80, 192, 3)
        self.Mixed_5b = _MirrorA(192, 32)
        self.Mixed_5c = _MirrorA(256, 64)
        self.Mixed_5d = _MirrorA(288, 64)
        self.Mixed_6a = _MirrorB(288)
        self.Mixed_6b = _MirrorC(768, 128)
        self.Mixed_6c = _MirrorC(768, 160)
        self.Mixed_6d = _MirrorC(768, 160)
        self.Mixed_6e = _MirrorC(768, 192)
        self.Mixed_7a = _MirrorD(768)
        self.Mixed_7b = _MirrorE(1280, "avg")
        self.Mixed_7c = _MirrorE(2048, "max")
        self.fc = nn.Linear(2048, num_classes)

    def forward(self, x: torch.Tensor) -> Dict[str, torch.Tensor]:
        out: Dict[str, torch.Tensor] = {}
        x = self.Conv2d_2b_3x3(self.Conv2d_2a_3x3(self.Conv2d_1a_3x3(x)))
        x = F.max_pool2d(x, 3, stride=2)
        out["64"] = x.mean(dim=(2, 3))
        x = self.Conv2d_4a_3x3(self.Conv2d_3b_1x1(x))
        x = F.max_pool2d(x, 3, stride=2)
        out["192"] = x.mean(dim=(2, 3))
        for name in ("Mixed_5b", "Mixed_5c", "Mixed_5d", "Mixed_6a", "Mixed_6b", "Mixed_6c", "Mixed_6d", "Mixed_6e"):
            x = getattr(self, name)(x)
        out["768"] = x.mean(dim=(2, 3))
        for name in ("Mixed_7a", "Mixed_7b", "Mixed_7c"):
            x = getattr(self, name)(x)
        pooled = x.mean(dim=(2, 3))
        out["2048"] = pooled
        out["logits_unbiased"] = pooled @ self.fc.weight.t()
        out["logits"] = out["logits_unbiased"] + self.fc.bias
        return out


def randomize_inception_(model: TorchInceptionMirror, seed: int = 0) -> None:
    """Well-conditioned random weights: BN stats near identity so activations
    stay bounded through the 94-conv trunk (default kaiming init + unit-ish
    running stats keep fp32 tap comparison meaningful)."""
    gen = torch.Generator().manual_seed(seed)
    with torch.no_grad():
        for mod in model.modules():
            if isinstance(mod, nn.BatchNorm2d):
                mod.running_mean.normal_(0.0, 0.05, generator=gen)
                mod.running_var.uniform_(0.8, 1.2, generator=gen)
                mod.weight.uniform_(0.8, 1.2, generator=gen)
                mod.bias.normal_(0.0, 0.05, generator=gen)
            elif isinstance(mod, (nn.Conv2d, nn.Linear)):
                fan_in = mod.weight[0].numel()
                mod.weight.normal_(0.0, (2.0 / fan_in) ** 0.5, generator=gen)
                if getattr(mod, "bias", None) is not None:
                    mod.bias.normal_(0.0, 0.05, generator=gen)
    model.eval()


# ---------------------------------------------------------------------------
# LPIPS (AlexNet backbone) mirror
# ---------------------------------------------------------------------------

# published LPIPS scaling-layer constants (match metrics_tpu.models.lpips)
_LPIPS_SHIFT = torch.tensor([-0.030, -0.088, -0.188]).view(1, 3, 1, 1)
_LPIPS_SCALE = torch.tensor([0.458, 0.448, 0.450]).view(1, 3, 1, 1)

# torchvision AlexNet `features` indices of the five tapped convs
ALEX_FEATURE_INDICES = (0, 3, 6, 8, 10)


class TorchAlexLPIPSMirror(nn.Module):
    """LPIPS-over-AlexNet oracle; state dict keys follow the ``lpips`` package
    layout (``net.slice{k}.{idx}.weight`` for the backbone, ``lin{k}.model.1.weight``
    for the heads) so ``tools/convert_lpips_weights.py`` applies directly."""

    def __init__(self) -> None:
        super().__init__()
        specs = [  # (cin, cout, kernel, stride, padding) per tapped conv
            (3, 64, 11, 4, 2),
            (64, 192, 5, 1, 2),
            (192, 384, 3, 1, 1),
            (384, 256, 3, 1, 1),
            (256, 256, 3, 1, 1),
        ]
        self.net = nn.Module()
        for k, (idx, (cin, cout, ksz, st, pad)) in enumerate(zip(ALEX_FEATURE_INDICES, specs), start=1):
            slice_mod = nn.Module()
            slice_mod.add_module(str(idx), nn.Conv2d(cin, cout, ksz, st, pad))
            self.net.add_module(f"slice{k}", slice_mod)
        for k, (_, cout, *_rest) in enumerate(specs):
            lin = nn.Module()
            lin.model = nn.Module()
            lin.model.add_module("1", nn.Conv2d(cout, 1, 1, bias=False))
            self.add_module(f"lin{k}", lin)

    def _taps(self, x: torch.Tensor):
        taps = []
        convs = [getattr(getattr(self.net, f"slice{k}"), str(i)) for k, i in enumerate(ALEX_FEATURE_INDICES, start=1)]
        x = F.relu(convs[0](x))
        taps.append(x)
        x = F.relu(convs[1](F.max_pool2d(x, 3, 2)))
        taps.append(x)
        x = F.relu(convs[2](F.max_pool2d(x, 3, 2)))
        taps.append(x)
        x = F.relu(convs[3](x))
        taps.append(x)
        taps.append(F.relu(convs[4](x)))
        return taps

    def forward(self, img1: torch.Tensor, img2: torch.Tensor) -> torch.Tensor:
        x1 = (img1 - _LPIPS_SHIFT) / _LPIPS_SCALE
        x2 = (img2 - _LPIPS_SHIFT) / _LPIPS_SCALE
        total = torch.zeros(img1.shape[0])
        for k, (f1, f2) in enumerate(zip(self._taps(x1), self._taps(x2))):
            f1 = f1 / (f1.pow(2).sum(dim=1, keepdim=True).sqrt() + 1e-10)
            f2 = f2 / (f2.pow(2).sum(dim=1, keepdim=True).sqrt() + 1e-10)
            head = getattr(getattr(self, f"lin{k}").model, "1")
            total = total + head((f1 - f2).pow(2)).abs().mean(dim=(2, 3))[:, 0]
        return total


def randomize_lpips_(model: TorchAlexLPIPSMirror, seed: int = 0) -> None:
    """Random backbone + non-negative head weights (published heads are trained
    non-negative; keeping the fixture non-negative makes the |·| a no-op on
    both sides of the comparison)."""
    gen = torch.Generator().manual_seed(seed)
    with torch.no_grad():
        for name, mod in model.named_modules():
            if isinstance(mod, nn.Conv2d):
                fan_in = mod.weight[0].numel()
                mod.weight.normal_(0.0, (2.0 / fan_in) ** 0.5, generator=gen)
                if name.startswith("lin"):
                    mod.weight.abs_()
                if mod.bias is not None:
                    mod.bias.normal_(0.0, 0.05, generator=gen)
    model.eval()
