"""Differential-testing harness — the analogue of the reference MetricTester.

Parity: reference `tests/unittests/helpers/testers.py:111-570`. Checks per metric:

- functional vs oracle per batch (`_functional_test`);
- class lifecycle vs oracle: ``forward`` batch values, final ``compute`` over all
  data, hashability, pickle round-trip, empty ``state_dict`` (`_class_test`);
- emulated multi-rank sync ("ddp"): batches striped across N virtual ranks,
  states combined through the real host sync path (``Metric.sync`` with an
  injected gather), result must equal the oracle on ALL data;
- SPMD sync: the same metric exported via ``as_functions`` and run under
  ``shard_map`` on a 2-device mesh with fused collectives (TPU-native path —
  replaces the reference's gloo process pool, SURVEY §4);
- jit-traceability of the functional (the analogue of TorchScript checks);
- differentiability via ``jax.grad``;
- bf16/fp16 input support.
"""
from __future__ import annotations

import pickle
from functools import partial
from typing import Any, Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

def shard_map(f, **kw):
    kw.setdefault('check_vma', False)
    return jax.shard_map(f, **kw)

from metrics_tpu.metric import Metric

NUM_RANKS = 2
NUM_BATCHES = 4  # must be divisible by NUM_RANKS
BATCH_SIZE = 32
NUM_CLASSES = 5
EXTRA_DIM = 3
THRESHOLD = 0.5


def _assert_allclose(tm_result: Any, ref_result: Any, atol: float = 1e-6, key: Optional[str] = None) -> None:
    if isinstance(tm_result, dict):
        assert key is not None and key in tm_result, f"key {key} missing from {tm_result}"
        tm_result = tm_result[key]
    np.testing.assert_allclose(np.asarray(tm_result), np.asarray(ref_result), atol=atol, rtol=1e-5)


def _select_rank_batches(n_batches: int, rank: int, world: int) -> range:
    return range(rank, n_batches, world)


class _FakeGather:
    """Injectable ``dist_sync_fn`` emulating an N-rank all-gather on one host.

    ``Metric._sync_dist`` builds an input dict (cat-lists pre-concatenated to
    one array) and ``apply_to_collection`` calls the gather once per array
    leaf, walking states in insertion order and list states element by
    element. This object replays exactly that walk over every rank's metric
    instance and hands back the matching leaves — which is also why, like the
    real collective, it requires ``None``-spec list states to hold the same
    number of elements on every rank (same number of gather calls).
    """

    def __init__(self, rank_metrics: Sequence[Metric]) -> None:
        self.rank_metrics = rank_metrics
        # real sync is symmetric: every rank's sync() canonicalizes its lazily
        # buffered list states before gathering. Only the syncing rank's
        # sync() runs in this emulation, so canonicalize the others here.
        for rm in rank_metrics:
            self._canon_recursive(rm)
        # built eagerly so the cross-rank agreement diagnostics fire even when
        # the syncing rank itself would make zero gather calls
        self._schedule = self._build_schedule(rank_metrics[0])
        self._call_idx = 0

    @classmethod
    def _canon_recursive(cls, m: Metric) -> None:
        m._canonicalize_list_states()
        for child in m._sync_children():
            cls._canon_recursive(child)

    @staticmethod
    def _resolve(m: Metric, path: tuple) -> Metric:
        for child_idx in path:
            m = m._sync_children()[child_idx]
        return m

    def _build_schedule(self, m: Metric, path: tuple = ()):
        """Schedule entries are ``(path, name, elem)`` — ``path`` drills into
        ``_sync_children()`` (wrappers/compositions recurse their children
        through the same gather, in sync's child order)."""
        schedule = []
        rank_subs = [self._resolve(rm, path) for rm in self.rank_metrics]
        for name, spec in m._reduction_specs.items():
            value = getattr(m, name)
            if isinstance(value, list):
                if spec == "cat":
                    empties = {len(getattr(rm, name)) == 0 for rm in rank_subs}
                    assert len(empties) == 1, (
                        f"cat state {name!r} is empty on some ranks but not others; the"
                        " schedule is built once from rank 0, so emptiness must agree"
                        " across ranks for the replayed walk to line up"
                    )
                    if len(value) > 0:
                        schedule.append((path, name, None))  # pre-concatenated → 1 call
                else:
                    lengths = {len(getattr(rm, name)) for rm in rank_subs}
                    assert len(lengths) == 1, (
                        f"list state {name!r} has different lengths across ranks {lengths};"
                        " the per-element gather protocol (ours and the reference's) needs"
                        " equal update counts per rank"
                    )
                    schedule.extend((path, name, j) for j in range(len(value)))
            else:
                schedule.append((path, name, None))
        for i, child in enumerate(m._sync_children()):
            schedule.extend(self._build_schedule(child, path + (i,)))
        return schedule

    def __call__(self, tensor: jax.Array, group: Any = None):
        from metrics_tpu.utils.data import dim_zero_cat

        path, name, elem = self._schedule[self._call_idx]
        self._call_idx += 1
        out = []
        for m in self.rank_metrics:
            value = getattr(self._resolve(m, path), name)
            if elem is not None:
                out.append(jnp.asarray(value[elem]))
            elif isinstance(value, list):
                out.append(jnp.asarray(dim_zero_cat(value)))
            else:
                out.append(jnp.asarray(value))
        return out


class MetricTester:
    """Subclass per metric; provide inputs + a numpy/sklearn oracle."""

    atol: float = 1e-6

    # ------------------------------------------------------------ functional
    def run_functional_metric_test(
        self,
        preds: jax.Array,
        target: jax.Array,
        metric_functional: Callable,
        reference_metric: Callable,
        metric_args: Optional[dict] = None,
        atol: Optional[float] = None,
        **kwargs_update: Any,
    ) -> None:
        metric_args = metric_args or {}
        atol = atol if atol is not None else self.atol
        fn = partial(metric_functional, **metric_args)
        for i in range(NUM_BATCHES):
            extra = {k: v[i] if isinstance(v, (jnp.ndarray, jax.Array)) and v.ndim > 0 else v for k, v in kwargs_update.items()}
            tm_result = fn(preds[i], target[i], **extra)
            ref_result = reference_metric(np.asarray(preds[i]), np.asarray(target[i]), **{k: np.asarray(v) for k, v in extra.items()})
            _assert_allclose(tm_result, ref_result, atol=atol)

    # ------------------------------------------------------------------ class
    def run_class_metric_test(
        self,
        preds: jax.Array,
        target: jax.Array,
        metric_class: type,
        reference_metric: Callable,
        metric_args: Optional[dict] = None,
        ddp: bool = False,
        check_batch: bool = True,
        atol: Optional[float] = None,
        **kwargs_update: Any,
    ) -> None:
        metric_args = metric_args or {}
        atol = atol if atol is not None else self.atol
        if ddp:
            self._class_test_ddp(preds, target, metric_class, reference_metric, metric_args, atol, **kwargs_update)
        else:
            self._class_test_single(
                preds, target, metric_class, reference_metric, metric_args, atol, check_batch, **kwargs_update
            )

    def _class_test_single(
        self,
        preds,
        target,
        metric_class,
        reference_metric,
        metric_args,
        atol,
        check_batch,
        **kwargs_update,
    ) -> None:
        metric = metric_class(**metric_args)

        # class constants must be frozen (reference testers.py:158-161)
        with pytest.raises(RuntimeError):
            metric.is_differentiable = not metric.is_differentiable
        with pytest.raises(RuntimeError):
            metric.higher_is_better = not metric.higher_is_better

        # pickle round-trip (reference testers.py:174-176)
        pickled = pickle.dumps(metric)
        metric = pickle.loads(pickled)

        assert metric.state_dict() == {} or all(
            isinstance(v, (np.ndarray, list)) for v in metric.state_dict().values()
        )

        for i in range(NUM_BATCHES):
            extra = {k: v[i] if isinstance(v, (jnp.ndarray, jax.Array)) and v.ndim > 0 else v for k, v in kwargs_update.items()}
            batch_result = metric(preds[i], target[i], **extra)
            if check_batch:
                ref_batch = reference_metric(
                    np.asarray(preds[i]), np.asarray(target[i]), **{k: np.asarray(v) for k, v in extra.items()}
                )
                _assert_allclose(batch_result, ref_batch, atol=atol)

        assert isinstance(hash(metric), int)

        total_pred = np.concatenate([np.asarray(preds[i]) for i in range(NUM_BATCHES)])
        total_target = np.concatenate([np.asarray(target[i]) for i in range(NUM_BATCHES)])
        total_extra = {
            k: np.concatenate([np.asarray(v[i]) for i in range(NUM_BATCHES)])
            if isinstance(v, (jnp.ndarray, jax.Array)) and v.ndim > 0
            else np.asarray(v)
            for k, v in kwargs_update.items()
        }
        ref_total = reference_metric(total_pred, total_target, **total_extra)
        _assert_allclose(metric.compute(), ref_total, atol=atol)

    def _class_test_ddp(
        self,
        preds,
        target,
        metric_class,
        reference_metric,
        metric_args,
        atol,
        **kwargs_update,
    ) -> None:
        """Emulated N-rank run through the real host sync path."""
        rank_metrics = [metric_class(**metric_args) for _ in range(NUM_RANKS)]
        for rank, metric in enumerate(rank_metrics):
            for i in _select_rank_batches(NUM_BATCHES, rank, NUM_RANKS):
                extra = {
                    k: v[i] if isinstance(v, (jnp.ndarray, jax.Array)) and v.ndim > 0 else v
                    for k, v in kwargs_update.items()
                }
                metric.update(preds[i], target[i], **extra)

        total_pred = np.concatenate([np.asarray(preds[i]) for i in range(NUM_BATCHES)])
        total_target = np.concatenate([np.asarray(target[i]) for i in range(NUM_BATCHES)])
        total_extra = {
            k: np.concatenate([np.asarray(v[i]) for i in range(NUM_BATCHES)])
            if isinstance(v, (jnp.ndarray, jax.Array)) and v.ndim > 0
            else np.asarray(v)
            for k, v in kwargs_update.items()
        }
        ref_total = reference_metric(total_pred, total_target, **total_extra)

        for rank, metric in enumerate(rank_metrics):
            gather = _FakeGather(rank_metrics)
            with metric.sync_context(dist_sync_fn=gather, distributed_available=lambda: True):
                synced_value = metric._inner_compute()
            _assert_allclose(synced_value, ref_total, atol=atol)
            # after unsync local state must be restored: rank-local compute differs
            assert metric._is_synced is False

    # ------------------------------------------------------------------- spmd
    def run_spmd_test(
        self,
        preds,
        target,
        metric_class,
        reference_metric,
        metric_args: Optional[dict] = None,
        atol: Optional[float] = None,
        n_devices: int = 2,
    ) -> None:
        """Fused-collective sync under shard_map — the TPU-native DDP analogue."""
        metric_args = metric_args or {}
        atol = atol if atol is not None else self.atol
        metric = metric_class(**metric_args)
        init, update_fn, compute_fn = metric.as_functions()

        devices = jax.devices()[:n_devices]
        mesh = Mesh(np.array(devices), ("dp",))
        nb = NUM_BATCHES

        # stripe batches: device d sees batches [d*nb/n : (d+1)*nb/n]
        preds_arr = jnp.stack([preds[i] for i in range(nb)])
        target_arr = jnp.stack([target[i] for i in range(nb)])

        def shard_fn(p, t):
            state = init()
            for i in range(nb // n_devices):
                state = update_fn(state, p[i], t[i])
            return compute_fn(state, axis_name="dp")

        result = jax.jit(
            shard_map(shard_fn, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P())
        )(preds_arr, target_arr)

        total_pred = np.concatenate([np.asarray(preds[i]) for i in range(nb)])
        total_target = np.concatenate([np.asarray(target[i]) for i in range(nb)])
        ref_total = reference_metric(total_pred, total_target)
        _assert_allclose(result, ref_total, atol=atol)

    # -------------------------------------------------------------------- jit
    def run_jit_test(
        self,
        preds,
        target,
        metric_functional: Callable,
        metric_args: Optional[dict] = None,
        atol: Optional[float] = None,
    ) -> None:
        """The functional must trace under jit with static shapes (scriptability analogue)."""
        metric_args = metric_args or {}
        atol = atol if atol is not None else self.atol
        fn = partial(metric_functional, **metric_args)
        eager = fn(preds[0], target[0])
        jitted = jax.jit(fn)(preds[0], target[0])
        _assert_allclose(jitted, eager, atol=atol)

    # ------------------------------------------------------------------- grad
    def run_differentiability_test(
        self,
        preds,
        target,
        metric_functional: Callable,
        metric_args: Optional[dict] = None,
    ) -> None:
        metric_args = metric_args or {}

        def scalar_fn(p):
            out = metric_functional(p, target[0], **metric_args)
            if isinstance(out, dict):
                out = next(iter(out.values()))
            return jnp.sum(jnp.asarray(out))

        grad = jax.grad(scalar_fn)(preds[0].astype(jnp.float32))
        assert bool(jnp.all(jnp.isfinite(grad))), "gradient contains NaN/inf"

    # -------------------------------------------------------------- precision
    def run_precision_test(
        self,
        preds,
        target,
        metric_functional: Callable,
        metric_args: Optional[dict] = None,
        dtype=jnp.bfloat16,
        atol: float = 1e-2,
    ) -> None:
        metric_args = metric_args or {}
        fn = partial(metric_functional, **metric_args)
        full = fn(preds[0], target[0])
        low = fn(preds[0].astype(dtype), target[0])
        _assert_allclose(jnp.asarray(low, dtype=jnp.float32), np.asarray(full), atol=atol)


class DummyMetric(Metric):
    """Scalar sum metric for base-class tests (reference testers.py:573-590)."""

    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("x", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, x) -> None:
        self.x = self.x + jnp.asarray(x, dtype=jnp.float32)

    def compute(self):
        return self.x


class DummyListMetric(Metric):
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("x", [], dist_reduce_fx="cat")

    def update(self, x) -> None:
        self.x.append(jnp.atleast_1d(jnp.asarray(x, dtype=jnp.float32)))

    def compute(self):
        from metrics_tpu.utils.data import dim_zero_cat

        return dim_zero_cat(self.x) if self.x else jnp.zeros((0,))


__all__ = [
    "MetricTester",
    "DummyMetric",
    "DummyListMetric",
    "NUM_RANKS",
    "NUM_BATCHES",
    "BATCH_SIZE",
    "NUM_CLASSES",
    "EXTRA_DIM",
    "THRESHOLD",
    "assert_dict_outputs_equal",
]


def assert_dict_outputs_equal(ours: dict, theirs: dict, atol: float = 1e-6) -> None:
    """Shared oracle for dict-valued metric outputs: key sets must match and
    every value must agree within tolerance."""
    assert set(ours) == set(theirs), set(ours) ^ set(theirs)
    for key in theirs:
        np.testing.assert_allclose(
            np.asarray(ours[key], np.float64), np.asarray(theirs[key], np.float64), atol=atol, err_msg=str(key)
        )
