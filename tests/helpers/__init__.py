import random

import numpy as np


def seed_all(seed: int = 42) -> None:
    random.seed(seed)
    np.random.seed(seed)


__all__ = ["seed_all"]


def cell_seed(*parts) -> int:
    """Deterministic per-cell RNG seed from grid coordinates.

    Shared by the full-grid suites so every cell sees distinct data without a
    dataset multiplier, and so the seeding convention can't drift per domain.
    """
    import zlib

    return zlib.crc32("|".join(str(p) for p in parts).encode()) & 0x7FFFFFFF


def assert_tree_close(a, b, atol=1e-5, rtol=1e-4):
    """Recursive allclose over dict/list/tuple trees of array-likes.

    The one shared tree comparator for the parity/grid suites (keys must
    match exactly for dicts, lengths for sequences).
    """
    import numpy as np

    if isinstance(a, dict):
        assert set(a) == set(b)
        for k in a:
            assert_tree_close(a[k], b[k], atol, rtol)
        return
    if isinstance(a, (list, tuple)):
        assert len(a) == len(b)
        for x, y in zip(a, b):
            assert_tree_close(x, y, atol, rtol)
        return
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=atol, rtol=rtol)
