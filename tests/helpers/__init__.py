import random

import numpy as np


def seed_all(seed: int = 42) -> None:
    random.seed(seed)
    np.random.seed(seed)


__all__ = ["seed_all"]


def cell_seed(*parts) -> int:
    """Deterministic per-cell RNG seed from grid coordinates.

    Shared by the full-grid suites so every cell sees distinct data without a
    dataset multiplier, and so the seeding convention can't drift per domain.
    """
    import zlib

    return zlib.crc32("|".join(str(p) for p in parts).encode()) & 0x7FFFFFFF
