"""Import the mounted reference implementation as a differential-test oracle.

The reference (`/root/reference/src`, pure torch, runs on CPU) is the behavioral
contract for cases sklearn handles differently (e.g. curve endpoint conventions,
hamming over one-hot). Requires a tiny ``pkg_resources`` shim on python >= 3.12.
Tests using it must skip when the mount is absent.
"""
from __future__ import annotations

import sys
import types

_REF_PATH = "/root/reference/src"


def _install_pkg_resources_shim() -> None:
    if "pkg_resources" in sys.modules:
        return
    pr = types.ModuleType("pkg_resources")

    class DistributionNotFound(Exception):
        pass

    def get_distribution(name):
        import importlib.metadata as im

        class _Dist:
            def __init__(self, version):
                self.version = version

        try:
            return _Dist(im.version(name))
        except Exception as err:
            raise DistributionNotFound(name) from err

    pr.DistributionNotFound = DistributionNotFound
    pr.get_distribution = get_distribution
    sys.modules["pkg_resources"] = pr


def reference_available() -> bool:
    import os

    return os.path.isdir(_REF_PATH)


_cache = {}


def get_reference():
    """Returns the reference `torchmetrics` module, or None if unavailable."""
    if "mod" in _cache:
        return _cache["mod"]
    if not reference_available():
        _cache["mod"] = None
        return None
    _install_pkg_resources_shim()
    if _REF_PATH not in sys.path:
        sys.path.insert(0, _REF_PATH)
    try:
        import torchmetrics  # noqa: F401

        _cache["mod"] = torchmetrics
    except Exception:
        _cache["mod"] = None
    return _cache["mod"]


__all__ = ["get_reference", "reference_available"]
