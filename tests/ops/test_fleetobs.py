"""Fleet observability plane: the ISSUE-9 contracts.

Contracts (`metrics_tpu/ops/fleetobs.py`):

- **Single-process is free** — with a world size of 1, ``fleet_snapshot()``
  serves the local plane directly: ZERO collectives issued, schema identical
  to the gathered case.
- **Exact aggregation** — in a (fake) multi-rank world the aggregate plane's
  counters equal the EXACT per-key sum of the per-rank planes, gauges reduce
  to min/median/max, and the merge rides the real epoch-fenced
  ``_host_allgather`` blob protocol.
- **Dead ranks** — declared-dead ranks appear as placeholder planes sourced
  from the membership registry and are excluded from every aggregate.
- **Straggler attribution** — per-rank ``sync_phase_stats`` reduce into a
  report naming the slowest ranks per phase with deviation scores; the fleet
  Prometheus exposition carries ``rank``/``phase`` labels and is well-formed.
- **Merged trace** — ``export_fleet_trace`` emits one process per rank,
  clock-aligned on paired payload-gather anchors, and the output passes
  ``tools/trace_report.py --check``; ``--diff`` reports counter deltas
  between two snapshots.
"""
from __future__ import annotations

import json
import os
import re
import sys

import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu.ops import engine, fleetobs, telemetry
from metrics_tpu.parallel import bucketing
from metrics_tpu.parallel import sync as psync
from metrics_tpu.utils.exceptions import EpochFault

_REPO_DIR = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if _REPO_DIR not in sys.path:
    sys.path.insert(0, _REPO_DIR)

from tools.trace_report import check_trace, diff_report  # noqa: E402

RNG = np.random.RandomState(9)
DIST_ON = lambda: True  # noqa: E731


def _suite():
    s = mt.MetricCollection({"mean": mt.MeanMetric(), "acc": mt.Accuracy()})
    s.update(
        jnp.asarray(RNG.rand(32).astype(np.float32)),
        jnp.asarray(RNG.randint(0, 2, 32)),
    )
    return s


@pytest.fixture(autouse=True)
def _armed_clean_world():
    """Armed recorder, empty ring, pristine membership registry per test."""
    was = telemetry.armed
    telemetry.set_telemetry(True)
    telemetry.clear_spans()
    psync.reset_membership()
    yield
    psync.reset_membership()
    telemetry.set_telemetry(was)
    telemetry.clear_spans()


def _sync_cycle(suite):
    suite.sync(distributed_available=DIST_ON)
    suite.unsync()


class _FakeWorld:
    """A 3-rank world at the ``_host_allgather`` transport seam: the real
    blob protocol (length exchange + padded payload) runs; rank 1/2 rows are
    produced by ``make_blobs()`` at length-exchange time."""

    def __init__(self, monkeypatch, make_blobs):
        self.make_blobs = make_blobs
        self.blobs = []
        psync.set_expected_world(3)
        monkeypatch.setattr(bucketing, "_host_allgather", self._host)

    def _host(self, vec):
        vec = np.asarray(vec)
        if vec.dtype != np.uint8:  # the length exchange
            self.blobs = self.make_blobs()
            return np.stack([vec] + [np.asarray([len(b)], np.int64) for b in self.blobs])
        rows = [vec]
        for b in self.blobs:
            row = np.zeros(vec.size, np.uint8)
            row[: len(b)] = np.frombuffer(b, np.uint8)
            rows.append(row)
        return np.stack(rows)


def _plane_blobs(tweak=None):
    def make():
        out = []
        for r in (1, 2):
            plane = fleetobs._local_plane()
            if tweak is not None:
                tweak(r, plane)
            out.append(json.dumps(plane, separators=(",", ":")).encode())
        return out

    return make


# ------------------------------------------------------------- single process
def test_single_process_local_plane_zero_collectives():
    suite = _suite()
    _sync_cycle(suite)
    s0 = engine.engine_stats()["sync_collectives_issued"]
    snap = fleetobs.fleet_snapshot()
    assert engine.engine_stats()["sync_collectives_issued"] == s0, (
        "a world-size-1 fleet_snapshot issued collectives"
    )
    assert snap["world_size"] == 1 and snap["gathered"] is False
    assert sorted(snap["ranks"]) == [snap["rank"]] == [0]
    local = snap["ranks"][0]
    assert "failure_log" not in local
    assert local["snapshot_schema"] == 1
    # the lone plane aggregates as itself
    assert snap["aggregate"]["ranks_merged"] == [0]
    assert snap["aggregate"]["counters"]["sync_payload_collectives"] == local[
        "sync_payload_collectives"
    ]


def test_fleet_schema_stable_and_keys():
    snap = fleetobs.fleet_snapshot()
    assert snap["fleet_schema"] == fleetobs.FLEET_SCHEMA == 1
    expected = {
        "fleet_schema", "world_size", "rank", "epoch", "gathered", "dead_ranks",
        "ranks", "aggregate", "stragglers", "streaming", "world_health",
        "fleet_stats",
    }
    assert set(snap) == expected
    assert set(snap) == set(fleetobs.fleet_snapshot()), "fleet keys drift call-over-call"
    assert set(snap["aggregate"]) == {"counters", "gauges", "latency_stats", "ranks_merged"}
    assert set(snap["stragglers"]) == {"phases", "ranked", "threshold", "stragglers"}


# ------------------------------------------------------------- fake multi-rank
def test_fleet_merge_sums_counters_exactly(monkeypatch):
    suite = _suite()
    _sync_cycle(suite)

    def tweak(r, plane):
        plane["deferred_steps"] = int(plane.get("deferred_steps", 0)) + 100 * r
        plane["sync_bytes_gathered"] = int(plane.get("sync_bytes_gathered", 0)) + r

    _FakeWorld(monkeypatch, _plane_blobs(tweak))
    snap = fleetobs.fleet_snapshot()
    assert snap["world_size"] == 3 and snap["gathered"] is True
    assert sorted(snap["ranks"]) == [0, 1, 2]
    # independent exact-sum oracle over the per-rank planes (the latency
    # plane merges structurally, not through the flat counter/gauge walk)
    expected = {}
    gauge_vals = {}
    for plane in snap["ranks"].values():
        numeric = {k: v for k, v in plane.items() if k != "latency_stats"}
        for key, val in telemetry._flat_numeric("", numeric):
            if fleetobs._fleet_is_counter(key):
                expected[key] = expected.get(key, 0) + val
            else:
                gauge_vals.setdefault(key, []).append(val)
    got = snap["aggregate"]["counters"]
    assert set(got) == set(expected)
    for key, val in expected.items():
        assert float(got[key]) == float(val), f"aggregate[{key!r}] != exact sum"
    # the deliberately-offset counters prove three distinct planes summed
    base = snap["ranks"][0]["deferred_steps"]
    assert got["deferred_steps"] == 3 * base + 300
    # gauges reduce to min/median/max over the live planes
    gauges = snap["aggregate"]["gauges"]
    for key, vals in gauge_vals.items():
        assert gauges[key]["min"] == min(vals)
        assert gauges[key]["max"] == max(vals)
        assert gauges[key]["median"] == sorted(vals)[1]  # 3 planes
    # the shared monotonic event axis reduces as a gauge (cross-rank step
    # skew), never as a 3x sum
    assert "monotonic_step" not in got
    assert "monotonic_step" in gauges


def test_fleet_merge_dead_rank_placeholder_excluded(monkeypatch):
    suite = _suite()
    _sync_cycle(suite)
    # declare rank 2 dead: the gather's rows are the survivors {0, 1}
    psync.set_expected_world(3)
    psync.mark_peer_dead(2, "test-dead")

    def make():
        plane = fleetobs._local_plane()
        plane["deferred_steps"] = int(plane.get("deferred_steps", 0)) + 7
        return [json.dumps(plane, separators=(",", ":")).encode()]

    world = _FakeWorld(monkeypatch, make)
    assert world  # registry already declared the world via mark_peer_dead
    snap = fleetobs.fleet_snapshot()
    assert snap["world_size"] == 3
    assert snap["dead_ranks"] == [2]
    assert sorted(snap["ranks"]) == [0, 1, 2]
    dead_plane = snap["ranks"][2]
    assert dead_plane["dead"] is True and dead_plane["rank"] == 2
    assert dead_plane["declared_dead_epoch"] is not None
    # aggregates exclude the placeholder: only the two live planes merged
    assert snap["aggregate"]["ranks_merged"] == [0, 1]
    live_sum = (
        snap["ranks"][0]["deferred_steps"] + snap["ranks"][1]["deferred_steps"]
    )
    assert snap["aggregate"]["counters"]["deferred_steps"] == live_sum
    # the straggler report also ignores the dead plane
    for entry in snap["stragglers"]["phases"].values():
        assert 2 not in entry["per_rank_mean_s"]


def test_fleet_gather_rides_the_epoch_fence(monkeypatch):
    """A membership change racing the fleet gather fences the retry with the
    classified EpochFault instead of re-issuing into the wrong cohort."""
    suite = _suite()
    _sync_cycle(suite)
    monkeypatch.setenv("METRICS_TPU_SYNC_RETRIES", "1")
    monkeypatch.setenv("METRICS_TPU_SYNC_BACKOFF_MS", "0")
    psync.set_expected_world(3)
    calls = {"n": 0}

    def racing_host(vec):
        calls["n"] += 1
        if calls["n"] == 1:
            psync.bump_epoch("test-race")
            raise RuntimeError("transport reset by membership change")
        raise AssertionError("a stale-epoch retry reached the transport")

    monkeypatch.setattr(bucketing, "_host_allgather", racing_host)
    with pytest.raises(EpochFault):
        fleetobs.fleet_snapshot()
    assert calls["n"] == 1


# ---------------------------------------------------------------- stragglers
def test_straggler_report_names_delayed_rank(monkeypatch):
    suite = _suite()
    _sync_cycle(suite)

    def tweak(r, plane):
        if r == 2:
            for block in (plane.get("sync_phase_stats") or {}).values():
                for key in ("total_s", "mean_s", "max_s"):
                    block[key] = float(block.get(key, 0.0)) * 10.0

    _FakeWorld(monkeypatch, _plane_blobs(tweak))
    report = fleetobs.fleet_snapshot()["stragglers"]
    phase = report["phases"]["sync-payload-gather"]
    assert phase["slowest_rank"] == 2
    assert phase["deviation"] == pytest.approx(9.0)  # (10x - 1x) / 1x
    assert set(phase["per_rank_mean_s"]) == {0, 1, 2}
    assert 2 in report["stragglers"]
    assert report["ranked"][0]["rank"] == 2
    # the healthy ranks sit at the median: not flagged
    assert 0 not in report["stragglers"] and 1 not in report["stragglers"]


def test_straggler_threshold_env(monkeypatch):
    monkeypatch.setenv("METRICS_TPU_STRAGGLER_THRESHOLD", "2.5")
    assert fleetobs.straggler_threshold() == 2.5
    monkeypatch.setenv("METRICS_TPU_STRAGGLER_THRESHOLD", "garbage")
    with pytest.warns(UserWarning, match="METRICS_TPU_STRAGGLER_THRESHOLD"):
        engine.reset_stats(reset_warnings=True)
        assert fleetobs.straggler_threshold() == 0.5


def test_fleet_prometheus_well_formed(monkeypatch):
    suite = _suite()
    _sync_cycle(suite)

    def tweak(r, plane):
        if r == 2:
            for block in (plane.get("sync_phase_stats") or {}).values():
                for key in ("total_s", "mean_s", "max_s"):
                    block[key] = float(block.get(key, 0.0)) * 10.0

    _FakeWorld(monkeypatch, _plane_blobs(tweak))
    text = fleetobs.fleet_prometheus_text()
    lines = [ln for ln in text.strip().splitlines() if ln]
    sample_re = re.compile(
        r"^(metrics_tpu_fleet_[a-zA-Z0-9_]+)(\{[a-z]+=\"[^\"]+\"(,[a-z]+=\"[^\"]+\")*\})? (-?[0-9.e+-]+|\+?[0-9.e+-]*inf)$",
        re.IGNORECASE,
    )
    current_family, current_kind = None, None
    seen_families = set()
    for ln in lines:
        if ln.startswith("# TYPE "):
            _, _, name, kind = ln.split(" ")
            assert kind in ("counter", "gauge", "histogram")
            assert name not in seen_families, f"family {name} split across TYPE lines"
            seen_families.add(name)
            current_family, current_kind = name, kind
            continue
        m = sample_re.match(ln)
        assert m, f"malformed sample line: {ln!r}"
        base = m.group(1)
        # a histogram family carries _bucket/_sum/_count suffixed samples
        assert base == current_family or (
            current_kind == "histogram"
            and base in (
                f"{current_family}_bucket", f"{current_family}_sum", f"{current_family}_count"
            )
        ), f"{ln!r} outside its TYPE block"
        float(m.group(4))
    # the headline fleet families, with rank/phase labels where promised
    assert "# TYPE metrics_tpu_fleet_world_size gauge" in text
    assert "# TYPE metrics_tpu_fleet_sync_collectives_issued counter" in text
    assert 'metrics_tpu_fleet_rank_live{rank="2"} 1' in text
    assert re.search(
        r'metrics_tpu_fleet_sync_phase_mean_seconds\{rank="2",phase="sync-payload-gather"\}', text
    )
    assert re.search(
        r'metrics_tpu_fleet_straggler_deviation\{rank="2",phase="[a-z-]+"\}', text
    )
    assert 'metrics_tpu_fleet_straggler_flagged{rank="2"} 1' in text
    # the histogram planes: fleet-merged (site label) and per-rank (rank +
    # site labels), both passing the shared --check exposition validator
    from tools.trace_report import check_histogram_exposition

    assert "# TYPE metrics_tpu_fleet_latency_seconds histogram" in text
    assert re.search(
        r'metrics_tpu_fleet_rank_latency_seconds_bucket\{rank="2",site="[a-z-]+",le="\+Inf"\}',
        text,
    )
    assert check_histogram_exposition(text) == []
    # histogram SAMPLE keys never render as flat aggregate counter scalars
    flat_counter_lines = [
        ln for ln in lines
        if ln.startswith("metrics_tpu_fleet_latency_stats_") and "_buckets_" in ln
    ]
    assert not flat_counter_lines, flat_counter_lines[:3]


def test_fleet_latency_bucket_sums_exact_vs_oracle(monkeypatch):
    """The fleet histogram merge is EXACT: every site's merged bucket/count/
    sum equals an independent per-rank sum (the planes are deliberately
    asymmetric so symmetry cannot fake it), max maxes, and the fleet
    percentiles re-interpolate from the MERGED buckets."""
    suite = _suite()
    _sync_cycle(suite)

    def tweak(r, plane):
        lat = plane.get("latency_stats") or {}
        block = lat.get("suite-sync")
        if block:
            block["buckets"]["0.002048"] = int(block["buckets"].get("0.002048", 0)) + 10 * r
            block["count"] = int(block["count"]) + 10 * r
            block["sum_s"] = float(block["sum_s"]) + 0.002 * 10 * r
            block["max_s"] = max(float(block["max_s"]), 0.002)

    _FakeWorld(monkeypatch, _plane_blobs(tweak))
    snap = fleetobs.fleet_snapshot()
    merged = snap["aggregate"]["latency_stats"]
    assert merged, "no latency histograms travelled in the fleet gather"
    live = [p for p in snap["ranks"].values() if fleetobs._is_live_plane(p)]
    assert len(live) == 3
    for site, block in merged.items():
        per_rank = [b for b in ((p.get("latency_stats") or {}).get(site) for p in live) if b]
        assert block["count"] == sum(int(b["count"]) for b in per_rank), site
        assert block["sum_s"] == pytest.approx(sum(float(b["sum_s"]) for b in per_rank)), site
        assert block["max_s"] == max(float(b["max_s"]) for b in per_rank), site
        for label, n in block["buckets"].items():
            oracle = sum(int((b.get("buckets") or {}).get(label, 0)) for b in per_rank)
            assert n == oracle, (site, label)
        if block["count"]:
            assert 0 < block["p50_s"] <= block["p95_s"] <= block["p99_s"] <= block["max_s"] * (
                1 + 1e-9
            )
    # the deliberate asymmetry really merged three distinct planes
    base = snap["ranks"][0]["latency_stats"]["suite-sync"]["count"]
    assert merged["suite-sync"]["count"] == 3 * base + 30


def test_straggler_report_tail_aware_deviation(monkeypatch):
    """A rank whose MEAN looks healthy but whose full-lifetime p95 is 10x
    the fleet's is flagged by the tail measure — exactly the straggler the
    windowed mean hides."""
    suite = _suite()
    _sync_cycle(suite)

    def tweak(r, plane):
        if r == 2:
            # leave sync_phase_stats (the mean plane) untouched; inflate
            # only the full-lifetime tail
            for block in (plane.get("latency_stats") or {}).values():
                for key in ("p50_s", "p95_s", "p99_s", "max_s", "sum_s"):
                    block[key] = float(block.get(key, 0.0)) * 10.0

    _FakeWorld(monkeypatch, _plane_blobs(tweak))
    report = fleetobs.fleet_snapshot()["stragglers"]
    phase = report["phases"]["sync-payload-gather"]
    # the mean-based scoring sees three identical planes...
    assert phase["slowest_rank"] in (0, 1, 2) and phase["deviation"] == pytest.approx(0.0)
    # ...the tail-aware scoring names the slow rank
    assert phase["tail_slowest_rank"] == 2
    assert phase["tail_deviation"] == pytest.approx(9.0)
    assert set(phase["per_rank_p95_s"]) == {0, 1, 2}
    assert 2 in report["stragglers"]
    top = report["ranked"][0]
    assert top["rank"] == 2 and top["measure"] == "p95_s"


# -------------------------------------------------------------- merged trace
def test_export_fleet_trace_merged_and_aligned(monkeypatch, tmp_path):
    suite = _suite()
    _sync_cycle(suite)
    skew = {1: 0.004, 2: -0.006}

    def make():
        out = []
        for r in (1, 2):
            doc = {
                "rank": r,
                "spans": [dict(s) for s in telemetry.spans()],
                "snapshot": {
                    k: v
                    for k, v in telemetry._json_safe(telemetry.snapshot()).items()
                    if k != "failure_log"
                },
            }
            for s in doc["spans"]:
                s["t_start"] = float(s["t_start"]) + skew[r]
            out.append(json.dumps(telemetry._json_safe(doc), separators=(",", ":")).encode())
        return out

    _FakeWorld(monkeypatch, make)
    path = str(tmp_path / "fleet-trace.json")
    n = fleetobs.export_fleet_trace(path)
    assert n > 0
    with open(path) as fh:
        doc = json.load(fh)
    assert not check_trace(doc), check_trace(doc)
    # one process per rank, named
    procs = {
        e["pid"]: e["args"]["name"]
        for e in doc["traceEvents"]
        if e["ph"] == "M" and e["name"] == "process_name"
    }
    assert procs == {0: "rank 0", 1: "rank 1", 2: "rank 2"}
    # every non-meta event belongs to a rank process and carries its rank
    for ev in doc["traceEvents"]:
        if ev["ph"] == "M":
            continue
        assert ev["pid"] in (0, 1, 2)
        assert ev["args"]["rank"] == ev["pid"]
    # the recovered clock offsets invert the injected skew
    offsets = doc["otherData"]["clock_offsets_s"]
    for r in (1, 2):
        assert abs(float(offsets[str(r)]) + skew[r]) < 1e-6
    # timestamps globally monotonic (what --check pins) and non-negative
    ts = [e["ts"] for e in doc["traceEvents"] if e["ph"] != "M"]
    assert ts == sorted(ts) and all(t >= 0 for t in ts)
    # the embedded fleet snapshot is the summed counter plane
    assert doc["snapshot"]["spans_recorded"] > 0


def test_export_fleet_trace_single_process(tmp_path):
    suite = _suite()
    _sync_cycle(suite)
    s0 = engine.engine_stats()["sync_collectives_issued"]
    path = str(tmp_path / "local-fleet.json")
    n = fleetobs.export_fleet_trace(path)
    assert engine.engine_stats()["sync_collectives_issued"] == s0
    assert n > 0
    with open(path) as fh:
        doc = json.load(fh)
    assert not check_trace(doc)
    procs = [e for e in doc["traceEvents"] if e["ph"] == "M" and e["name"] == "process_name"]
    assert len(procs) == 1 and procs[0]["args"]["name"] == "rank 0"


# ------------------------------------------------------------------ the tools
def test_trace_report_diff(tmp_path):
    a = {"sync_collectives_issued": 3, "gone_key": 1.5, "same": 7}
    b = {"sync_collectives_issued": 9, "fresh_key": 2, "same": 7}
    pa, pb = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    with open(pa, "w") as fh:
        json.dump(a, fh)
    with open(pb, "w") as fh:
        json.dump(b, fh)
    text = diff_report(pa, pb)
    assert "+ fresh_key = 2" in text
    assert "- gone_key (was 1.5)" in text
    assert "sync_collectives_issued" in text and "(+6)" in text
    assert "same" not in [ln.split()[0] for ln in text.splitlines() if ln.strip()]


def test_trace_report_diff_reads_embedded_trace_snapshots(tmp_path):
    suite = _suite()
    _sync_cycle(suite)
    pa = str(tmp_path / "a.json")
    pb = str(tmp_path / "b.json")
    fleetobs.export_fleet_trace(pa)
    _sync_cycle(suite)
    fleetobs.export_fleet_trace(pb)
    text = diff_report(pa, pb)
    assert "top movers" in text
    assert "sync_payload_collectives" in text or "spans_recorded" in text


# -------------------------------------------------------------- suite + reset
def test_collection_fleet_health():
    suite = _suite()
    out = suite.fleet_health()
    assert out["fleet_schema"] == 1
    assert "suite" in out and "degraded" in out["suite"]
    assert out["suite"]["epoch"] == psync.world_epoch()


def test_fleet_counters_reset_with_the_registry():
    fleetobs.fleet_snapshot()
    assert fleetobs.fleet_stats()["fleet_snapshots"] >= 1
    engine.reset_stats()
    assert fleetobs.fleet_stats() == {
        "fleet_snapshots": 0,
        "fleet_trace_exports": 0,
        "fleet_gathers": 0,
        "fleet_gather_bytes": 0,
    }


def test_fleet_sites_documented():
    suite = _suite()
    _sync_cycle(suite)
    fleetobs.fleet_snapshot()
    emitted = {s["site"] for s in telemetry.spans()}
    assert "fleet-snapshot" in emitted
    undocumented = emitted - set(telemetry.SPAN_SITES)
    assert not undocumented, undocumented


def test_merge_snapshots_unit():
    planes = {
        0: {"sync_payload_collectives": 2, "cached": 5, "sync_coalesce_ratio": 2.0},
        1: {"sync_payload_collectives": 3, "cached": 7, "sync_coalesce_ratio": 4.0},
        2: {"dead": True},
    }
    out = fleetobs.merge_snapshots(planes)
    assert out["ranks_merged"] == [0, 1]
    assert out["counters"] == {"sync_payload_collectives": 5}
    assert out["gauges"]["cached"] == {"min": 5.0, "median": 6.0, "max": 7.0}
    assert out["gauges"]["sync_coalesce_ratio"] == {"min": 2.0, "median": 3.0, "max": 4.0}
