"""Crash-consistent state journal: round trips, corruption, generations.

Contracts (`metrics_tpu/ops/journal.py`):

- **Round trip is bit-exact by construction** across representative metric
  families — classification count states, cat/list states, BootStrapper
  clone trees, compute-group collections: save → fresh instance → load →
  ``compute()`` identical to the live oracle, and save → crash → load →
  replay-the-tail identical to the uninterrupted oracle.
- **Corruption demotes, never corrupts**: a truncated or flipped-byte newest
  generation records a classified ``journal`` fault and restores the
  previous good generation; when every generation is bad the classified
  ``JournalFault`` raises with live state untouched.
- **The ring is bounded and writes are atomic** (temp + rename; an injected
  ``journal-write`` fault leaves the ring byte-identical).
"""
from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu.metric import Metric
from metrics_tpu.ops import engine, faults
from metrics_tpu.ops import journal as journal_mod
from metrics_tpu.utils.exceptions import JournalFault

RNG = np.random.RandomState(7)


def _equal_values(got, want) -> None:
    if isinstance(want, dict):
        assert got.keys() == want.keys()
        for k in want:
            _equal_values(got[k], want[k])
    else:
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def _batch(n=16):
    return (
        jnp.asarray(RNG.rand(n).astype(np.float32)),
        jnp.asarray(RNG.randint(0, 2, n)),
    )


FAMILIES = {
    # classification count states (tensor-kind sum accumulators)
    "accuracy": (lambda: mt.Accuracy(), _batch),
    # multi-state mean accumulators
    "mean": (lambda: mt.MeanMetric(), lambda: (_batch()[0],)),
    # cat/list states with uneven row counts
    "auroc": (lambda: mt.AUROC(pos_label=1), _batch),
    "cat": (lambda: mt.CatMetric(), lambda: (_batch()[0],)),
    # wrapper clone tree: every bootstrap clone's states ride the record
    "bootstrap": (
        lambda: mt.BootStrapper(mt.MeanSquaredError(), num_bootstraps=3, sampling_strategy="multinomial"),
        lambda: (_batch()[0], _batch()[0]),
    ),
}


class TestRoundTrip:
    @pytest.mark.parametrize("family", sorted(FAMILIES))
    def test_save_load_bit_exact(self, family, tmp_path):
        make, data = FAMILIES[family]
        path = str(tmp_path / f"{family}.journal")
        live = make()
        for _ in range(3):
            live.update(*data())
        nbytes = live.save_state(path)
        assert nbytes > 0 and os.path.getsize(path) == nbytes
        fresh = make()
        assert fresh.load_state(path) == 0
        _equal_values(fresh.compute(), live.compute())
        assert fresh.update_count == live.update_count

    def test_unknown_extra_manifest_keys_round_trip(self, tmp_path):
        """Forward compatibility: a NEWER writer may stamp manifest keys this
        reader does not know (the world-membership epoch stamps are the
        first); decode must tolerate them — while still rejecting magic /
        version / CRC corruption and a manifest missing its entries table."""
        import json
        import struct
        import zlib

        m = mt.MeanMetric()
        m.update(jnp.asarray([2.0, 4.0]))
        nodes = [m]
        extra = {"epoch": 7, "barrier_step": 41, "from_the_future": {"list": [1, 2]}}
        record = journal_mod.pack_record(nodes, manifest_extra=extra)
        manifest, payload = journal_mod.decode_record(record)
        for key, value in extra.items():
            assert manifest[key] == value
        # reserved structural keys cannot be shadowed by extras
        shadowing = journal_mod.pack_record(nodes, manifest_extra={"entries": [], "epoch": 1})
        manifest2, _ = journal_mod.decode_record(shadowing)
        assert manifest2["entries"], "manifest_extra must not override the entries table"
        # the extra-stamped record restores bit-exactly
        fresh = mt.MeanMetric()
        journal_mod.restore_nodes([fresh], manifest, payload)
        np.testing.assert_array_equal(np.asarray(fresh.compute()), np.asarray(m.compute()))
        # ...and corruption is still rejected: flip one manifest byte
        torn = bytearray(record)
        torn[journal_mod._HEADER.size + 2] ^= 0xFF
        with pytest.raises(JournalFault, match="checksum"):
            journal_mod.decode_record(bytes(torn))
        # a CRC-valid record whose manifest lacks the entries table is corrupt
        mbytes = json.dumps({"only": "stamps"}).encode()
        header = journal_mod._HEADER.pack(
            journal_mod._MAGIC, journal_mod._VERSION, len(mbytes), 0, zlib.crc32(mbytes), zlib.crc32(b"")
        )
        with pytest.raises(JournalFault, match="entries"):
            journal_mod.decode_record(header + mbytes)
        # version skew still rejects (forward-compat is manifest-level only)
        skewed = struct.pack("<I", 99)
        with pytest.raises(JournalFault, match="version"):
            journal_mod.decode_record(record[:4] + skewed + record[8:])

    def test_save_state_stamps_world_meta(self, tmp_path):
        """Every save stamps the membership meta (epoch, last-good sync step,
        monotonic step) — what rejoin compares against a survivor handoff."""
        from metrics_tpu.parallel import sync as psync

        path = str(tmp_path / "meta.journal")
        m = mt.MeanMetric()
        m.update(jnp.asarray([1.0]))
        m.save_state(path)
        manifest, _ = journal_mod.read_record(path)
        assert manifest["epoch"] == psync.world_epoch()
        assert manifest["monotonic_step"] == faults.current_step()
        fresh = mt.MeanMetric()
        fresh.load_state(path)
        meta = journal_mod.restored_meta(fresh)
        assert meta["epoch"] == manifest["epoch"]
        assert meta["monotonic_step"] == manifest["monotonic_step"]

    @pytest.mark.parametrize("family", sorted(FAMILIES))
    def test_save_crash_load_replay_equals_uninterrupted_oracle(self, family, tmp_path):
        """The acceptance walk: save mid-stream, 'crash' (fresh instance),
        load, replay the tail — compute() bit-exact vs never crashing.

        Validation mode is pinned to "full" here (every call eager, fusion
        and deferral off): the LIVE instance carries 3 calls of fusion/
        certification history into the tail while the restored instance
        replays it fresh, so their tier schedules can differ — and a fused
        step's float rounding is only ulp-close to the eager path's, not
        bit-identical. Bit-exact replay is a statement about the journaled
        STATE (covered for the fast paths by the save/load and deferred-queue
        tests above); identical tier decisions make it testable exactly."""
        from metrics_tpu.utils import checks

        mode = checks._get_validation_mode()
        checks.set_validation_mode("full")
        try:
            make, data = FAMILIES[family]
            path = str(tmp_path / f"{family}.journal")
            batches = [data() for _ in range(5)]
            live = make()
            for b in batches[:3]:
                live.update(*b)
            live.save_state(path)
            for b in batches[3:]:
                live.update(*b)
            oracle = live.compute()

            restored = make()
            restored.load_state(path)
            for b in batches[3:]:
                restored.update(*b)
            _equal_values(restored.compute(), oracle)
        finally:
            checks.set_validation_mode(mode)

    def test_compute_group_collection_round_trip(self, tmp_path):
        path = str(tmp_path / "suite.journal")

        def make():
            return mt.MetricCollection(
                {
                    "prec": mt.Precision(num_classes=3, average="macro"),
                    "rec": mt.Recall(num_classes=3, average="macro"),
                    "acc": mt.Accuracy(num_classes=3),
                    "mean": mt.MeanMetric(),
                }
            )

        probs = jnp.asarray(RNG.randint(0, 3, 32))
        labels = jnp.asarray(RNG.randint(0, 3, 32))
        live = make()
        live.update(probs, labels)
        assert len(live.compute_groups) < 4, "compute groups must have merged"
        live.save_state(path)
        fresh = make()
        assert fresh.load_state(path) == 0
        _equal_values(fresh.compute(), live.compute())
        # the restored suite keeps working: group sharing re-established
        more_p = jnp.asarray(RNG.randint(0, 3, 16))
        more_l = jnp.asarray(RNG.randint(0, 3, 16))
        live.update(more_p, more_l)
        fresh.update(more_p, more_l)
        _equal_values(fresh.compute(), live.compute())

    def test_deferred_queue_flushes_into_the_record(self, tmp_path):
        """save_state is an observation point: pending deferred micro-batches
        land in the record."""
        path = str(tmp_path / "m.journal")
        engine.set_deferred_dispatch(True)
        x = jnp.asarray(RNG.rand(8).astype(np.float32))
        m = mt.MeanMetric()
        for _ in range(5):
            m.update(x)
        m.save_state(path)
        fresh = mt.MeanMetric()
        fresh.load_state(path)
        engine.set_deferred_dispatch(False)
        try:
            oracle = mt.MeanMetric()
            for _ in range(5):
                oracle.update(x)
            _equal_values(fresh.compute(), oracle.compute())
        finally:
            engine.set_deferred_dispatch(True)

    def test_non_cat_list_state_declines_classified(self, tmp_path):
        class _SpecNoneList(Metric):
            full_state_update = True

            def __init__(self):
                super().__init__()
                self.add_state("rows", [], dist_reduce_fx=None)

            def update(self, x):
                self.rows.append(jnp.asarray(x))

            def compute(self):
                return self.rows[0]

        m = _SpecNoneList()
        m.update(jnp.asarray([1.0]))
        with pytest.raises(JournalFault, match="non-'cat' list state"):
            m.save_state(str(tmp_path / "x.journal"))


class TestCorruption:
    def _save_two_generations(self, tmp_path):
        path = str(tmp_path / "m.journal")
        x1, x2 = jnp.asarray([1.0, 3.0]), jnp.asarray([100.0])
        m = mt.MeanMetric()
        m.update(x1)
        m.save_state(path)  # generation 1 after the next save
        m.update(x2)
        m.save_state(path)  # generation 0 (newest)
        oracle_gen1 = mt.MeanMetric()
        oracle_gen1.update(x1)
        return path, m, oracle_gen1

    def test_flipped_byte_demotes_to_previous_generation(self, tmp_path):
        path, live, oracle_gen1 = self._save_two_generations(tmp_path)
        data = bytearray(open(path, "rb").read())
        data[len(data) // 2] ^= 0xFF
        open(path, "wb").write(bytes(data))
        j0 = engine.engine_stats()["fault_journal"]
        fresh = mt.MeanMetric()
        with pytest.warns(UserWarning, match="demoting to the previous good generation"):
            assert fresh.load_state(path) == 1
        assert engine.engine_stats()["fault_journal"] > j0
        _equal_values(fresh.compute(), oracle_gen1.compute())

    def test_truncated_file_demotes_to_previous_generation(self, tmp_path):
        path, live, oracle_gen1 = self._save_two_generations(tmp_path)
        data = open(path, "rb").read()
        open(path, "wb").write(data[: len(data) // 2])  # torn write
        fresh = mt.MeanMetric()
        with pytest.warns(UserWarning, match="demoting"):
            assert fresh.load_state(path) == 1
        _equal_values(fresh.compute(), oracle_gen1.compute())

    def test_every_generation_corrupt_raises_classified_state_untouched(self, tmp_path):
        path, live, _ = self._save_two_generations(tmp_path)
        for p in (path, path + ".g1"):
            open(p, "wb").write(b"garbage")
        fresh = mt.MeanMetric()
        fresh.update(jnp.asarray([7.0]))
        before = {k: np.asarray(v) for k, v in fresh.metric_state.items()}
        with pytest.warns(UserWarning, match="demoting"):
            with pytest.raises(JournalFault):
                fresh.load_state(path)
        after = {k: np.asarray(v) for k, v in fresh.metric_state.items()}
        for k in before:  # all-or-nothing: live state untouched
            np.testing.assert_array_equal(after[k], before[k])

    def test_missing_path_raises_classified(self, tmp_path):
        m = mt.MeanMetric()
        with pytest.raises(JournalFault, match="no journal record"):
            m.load_state(str(tmp_path / "never-written.journal"))

    def test_record_from_smaller_suite_never_partially_restores(self, tmp_path):
        """A record whose node tree doesn't match the live one must raise
        classified — restoring only the overlapping nodes would be a silent
        partial restore (corruption, not durability)."""
        path = str(tmp_path / "small.journal")
        small = mt.MetricCollection({"mean": mt.MeanMetric()})
        small.update(jnp.asarray([2.0]))
        small.save_state(path)
        big = mt.MetricCollection({"mean": mt.MeanMetric(), "sum": mt.SumMetric()})
        big.update(jnp.asarray([7.0]))
        before = {
            k: {s: np.asarray(v) for s, v in m.metric_state.items()}
            for k, m in big.items(keep_base=True, copy_state=False)
        }
        with pytest.warns(UserWarning, match="demoting"):
            with pytest.raises(JournalFault):
                big.load_state(path)
        for k, m in big.items(keep_base=True, copy_state=False):
            for s, v in m.metric_state.items():
                np.testing.assert_array_equal(np.asarray(v), before[k][s])

    def test_layout_mismatch_raises_and_leaves_state(self, tmp_path):
        path = str(tmp_path / "mean.journal")
        m = mt.MeanMetric()
        m.update(jnp.asarray([2.0]))
        m.save_state(path)
        other = mt.Accuracy()
        other.update(*_batch())
        before = {k: np.asarray(v) for k, v in other.metric_state.items()}
        with pytest.warns(UserWarning, match="demoting"):
            with pytest.raises(JournalFault):
                other.load_state(path)
        after = {k: np.asarray(v) for k, v in other.metric_state.items()}
        for k in before:
            np.testing.assert_array_equal(after[k], before[k])


class TestRingAndAtomicity:
    def test_generation_ring_is_bounded(self, tmp_path, monkeypatch):
        monkeypatch.setenv("METRICS_TPU_JOURNAL_GENERATIONS", "3")
        path = str(tmp_path / "m.journal")
        m = mt.MeanMetric()
        for i in range(6):
            m.update(jnp.asarray([float(i)]))
            m.save_state(path)
        files = sorted(p.name for p in tmp_path.iterdir())
        assert files == ["m.journal", "m.journal.g1", "m.journal.g2"]
        # newest first: gen0 has 6 updates, gen1 five, gen2 four
        for gen, n_updates in ((0, 6), (1, 5), (2, 4)):
            fresh = mt.MeanMetric()
            monkeypatch.setattr(journal_mod, "journal_generations", lambda: 1)
            manifest, payload = journal_mod.read_record(journal_mod._gen_path(path, gen))
            journal_mod.restore_nodes([fresh], manifest, payload)
            assert fresh.update_count == n_updates

    def test_injected_write_fault_leaves_ring_byte_identical(self, tmp_path):
        path = str(tmp_path / "m.journal")
        m = mt.MeanMetric()
        m.update(jnp.asarray([1.0]))
        m.save_state(path)
        ring_before = open(path, "rb").read()
        m.update(jnp.asarray([2.0]))
        with faults.inject_faults("journal-write") as plan:
            with pytest.raises(JournalFault):
                m.save_state(path)
        assert plan.fired == 1
        assert open(path, "rb").read() == ring_before
        assert not os.path.exists(path + ".g1")

    def test_collection_journal_hook_every_n(self, tmp_path):
        path = str(tmp_path / "suite.journal")
        coll = mt.MetricCollection({"mean": mt.MeanMetric(), "sum": mt.SumMetric()})
        coll.journal(path, every_n=3)
        x = jnp.asarray([1.0, 2.0])
        for _ in range(2):
            coll.update(x)
        assert not os.path.exists(path)  # not yet at the cadence
        coll.update(x)
        assert os.path.exists(path)
        oracle3 = {k: np.asarray(v) for k, v in coll.compute().items()}
        for _ in range(3):
            coll.update(x)
        fresh = mt.MetricCollection({"mean": mt.MeanMetric(), "sum": mt.SumMetric()})
        fresh.load_state(path)
        got = {k: np.asarray(v) for k, v in fresh.compute().items()}
        # the newest record covers 6 updates (second cadence hit)
        want = {k: np.asarray(v) for k, v in coll.compute().items()}
        for k in want:
            np.testing.assert_array_equal(got[k], want[k])
        # and the previous generation is the 3-update snapshot
        fresh_prev = mt.MetricCollection({"mean": mt.MeanMetric(), "sum": mt.SumMetric()})
        manifest, payload = journal_mod.read_record(path + ".g1")
        journal_mod.restore_nodes(fresh_prev._journal_nodes(), manifest, payload)
        got_prev = {k: np.asarray(v) for k, v in fresh_prev.compute().items()}
        for k in oracle3:
            np.testing.assert_array_equal(got_prev[k], oracle3[k])
        coll.journal(None)  # disarm
        coll.update(x)
        assert not os.path.exists(path + ".g2")  # no further saves

    def test_forward_driven_loop_journals_too(self, tmp_path):
        """The standard coll(p, t) step API must tick the journal cadence —
        a forward-driven training loop is exactly where a crash loses the
        most accumulated state."""
        path = str(tmp_path / "fwd.journal")
        coll = mt.MetricCollection({"mean": mt.MeanMetric(), "sum": mt.SumMetric()})
        coll.journal(path, every_n=2)
        x = jnp.asarray([3.0, 5.0])
        coll(x)
        coll(x)  # cadence hit via forward/__call__
        assert os.path.exists(path)
        fresh = mt.MetricCollection({"mean": mt.MeanMetric(), "sum": mt.SumMetric()})
        fresh.load_state(path)
        _equal_values(fresh.compute(), coll.compute())

    def test_degrade_incident_not_double_counted(self, tmp_path, monkeypatch):
        """One degradable sync failure: the demotion into the degraded tier
        must not re-count the already-recorded fault (no 'sync-degrade' ring
        entries; fault_demotions still moves)."""
        import metrics_tpu.metric as metric_mod
        from metrics_tpu.parallel import bucketing

        monkeypatch.setenv("METRICS_TPU_SYNC_BACKOFF_MS", "0")
        monkeypatch.setenv("METRICS_TPU_SYNC_RETRIES", "0")
        monkeypatch.setenv("METRICS_TPU_SYNC_DEADLINE_MS", "100")
        monkeypatch.setenv("METRICS_TPU_SYNC_DEGRADED", "local")
        monkeypatch.setattr(metric_mod, "_dist_available", lambda: True)

        def hung(xx):
            import time

            time.sleep(0.5)
            raise RuntimeError("abandoned")

        monkeypatch.setattr(bucketing, "_payload_allgather", hung)
        engine.reset_stats()
        m = mt.MeanMetric()
        m.update(jnp.asarray([2.0, 4.0]))
        with pytest.warns(UserWarning, match="LOCAL-ONLY"):
            m.compute()
        log = engine.engine_stats()["failure_log"]
        assert not [e for e in log if e["site"] == "sync-degrade"]
        assert engine.engine_stats()["fault_demotions"] >= 1
        # exactly one raise-site incident chain: the watchdog timeout noted
        # by the retry wrapper + the one sync-site note on re-raise
        assert engine.engine_stats()["fault_sync"] == 2

    def test_journal_hook_write_fault_degrades_without_breaking_updates(self, tmp_path):
        faults.set_recovery_policy(steps=2)
        try:
            path = str(tmp_path / "suite.journal")
            coll = mt.MetricCollection({"mean": mt.MeanMetric()})
            coll.journal(path, every_n=1)
            x = jnp.asarray([4.0])
            coll.update(x)
            with faults.inject_faults("journal-write", count=1) as plan:
                with pytest.warns(UserWarning, match="journaling failed"):
                    coll.update(x)  # must NOT raise
            assert plan.fired == 1
            lad = coll.__dict__["_fault_ladders"]["journal"]
            assert lad.demoted
            # updates keep working and clean observed steps re-arm the lane
            for _ in range(2):
                coll.update(x)
                coll.compute()
            assert not lad.demoted
            coll.update(x)  # journaling resumed
            fresh = mt.MetricCollection({"mean": mt.MeanMetric()})
            fresh.load_state(path)
            _equal_values(fresh.compute(), coll.compute())
        finally:
            faults.set_recovery_policy(steps=8)
