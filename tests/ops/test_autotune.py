"""Roofline-guided kernel autotuner (ISSUE-20 contracts).

Contracts (`metrics_tpu/ops/autotune.py` + the kernel registrations):

- **Exactness contracts hold everywhere** — every registered variant
  matches its kernel's reference across a conditioning/shape property
  sweep (ill-conditioned and rank-deficient covariances for the FID
  Newton–Schulz variant, heavy-tie and signed-zero score vectors for the
  sort kernels, out-of-range indices for the count kernels) under the
  DECLARED contract: integer/count paths bit-exact, float paths within
  their registered tolerance.
- **Off is byte-identical** — with `METRICS_TPU_AUTOTUNE` unset every
  consult returns the reference path, the engine key/note hooks stay
  `None`, and every `autotune_*` counter stays zero (counter-pinned).
- **The sweep installs only qualified winners** — a variant that fails
  its exactness check or dies on an injected `autotune-sweep` fault is
  disqualified (classified demotion, `autotune_disqualified`), the
  reference keeps serving, and values through the public entry points
  stay equal to the disabled path.
- **Warm boot = zero sweeps** — with the progcache store enabled the
  selection table persists; a simulated second process restores it and
  serves installed winners without a single new sweep (counter-pinned).
- **Warn-once env knob** — garbage `METRICS_TPU_AUTOTUNE` warns once
  naming the value and falls back to off.
"""
from __future__ import annotations

import os
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from metrics_tpu.detection import mean_ap
from metrics_tpu.image import generative
from metrics_tpu.ops import autotune, binned, engine, faults, histogram, progcache, sorted_curves
from metrics_tpu.ops.binned import binned_curve_counts
from metrics_tpu.ops.histogram import fused_bincount
from metrics_tpu.ops.sorted_curves import binary_auroc_sorted
from metrics_tpu.parallel import sync as psync


@pytest.fixture(autouse=True)
def _clean_world(monkeypatch):
    monkeypatch.delenv("METRICS_TPU_AUTOTUNE", raising=False)
    monkeypatch.delenv("METRICS_TPU_PROGCACHE", raising=False)
    monkeypatch.delenv("METRICS_TPU_PROGCACHE_DIR", raising=False)
    psync.reset_membership()
    engine.reset_engine()
    engine.reset_stats(reset_warnings=True)
    autotune.configure(reset=True)
    progcache.configure(reset=True)
    yield
    psync.reset_membership()
    engine.reset_engine()
    engine.reset_stats(reset_warnings=True)
    autotune.configure(reset=True)
    progcache.configure(reset=True)
    try:
        jax.config.update(
            "jax_compilation_cache_dir", os.environ.get("JAX_COMPILATION_CACHE_DIR")
        )
    except Exception:  # noqa: BLE001 — older jax without the knob
        pass


# ---------------------------------------------------------- property cases
def _psd(rng, d, spectrum):
    q, _ = np.linalg.qr(rng.randn(d, d))
    return (q * np.asarray(spectrum)[None, :]) @ q.T


def _score_cases():
    rng = np.random.RandomState(7)
    cases = []
    for n in (1, 2, 33, 1024):
        s = rng.rand(n).astype(np.float32)
        y = (rng.rand(n) > 0.4).astype(np.int32)
        cases.append((s, y))
    # heavy ties: two-decimal grid collapses most scores onto shared keys
    s = np.round(rng.rand(512), 2).astype(np.float32)
    y = (rng.rand(512) > 0.5).astype(np.int32)
    cases.append((s, y))
    # signed zeros + all-tied block: -0.0 and +0.0 must share one tie run
    s = np.zeros(64, np.float32)
    s[::2] = -0.0
    y = (np.arange(64) % 3 == 0).astype(np.int32)
    cases.append((s, y))
    # negative scores exercise the sign-fold in the packed sort key
    s = (rng.randn(257)).astype(np.float32)
    y = (rng.rand(257) > 0.5).astype(np.int32)
    cases.append((s, y))
    # degenerate classes: no positives / no negatives (NaN AUROC paths)
    cases.append((rng.rand(17).astype(np.float32), np.zeros(17, np.int32)))
    cases.append((rng.rand(17).astype(np.float32), np.ones(17, np.int32)))
    return cases


def _count_cases():
    rng = np.random.RandomState(3)
    cases = [
        (np.asarray([], np.int32), 4),
        (np.asarray([0], np.int32), 1),
        # out-of-range on both sides: the ignore_index sentinel convention
        (rng.randint(-5, 40, size=777).astype(np.int32), 32),
        (rng.randint(0, 8, size=4096).astype(np.int32), 8),
    ]
    return cases


def _binned_cases():
    rng = np.random.RandomState(11)
    cases = []
    for n, c, t in ((1, 1, 1), (65, 3, 7), (513, 8, 29)):
        preds = rng.rand(n, c).astype(np.float32)
        target = (rng.rand(n, c) > 0.5).astype(np.float32)
        thr = rng.rand(t).astype(np.float32)  # unsorted
        cases.append((preds, target, thr))
    # duplicate + boundary thresholds, scores landing exactly on them
    preds = np.tile(np.linspace(0, 1, 11, dtype=np.float32)[:, None], (1, 2))
    target = (rng.rand(11, 2) > 0.5).astype(np.float32)
    thr = np.asarray([0.5, 0.0, 1.0, 0.5], np.float32)
    cases.append((preds, target, thr))
    return cases


def _sqrtm_cases():
    rng = np.random.RandomState(5)
    cases = []
    for d, spec in (
        (8, np.linspace(1.0, 2.0, 8)),  # well-conditioned
        (16, np.logspace(-3, 0, 16)),  # ill-conditioned (cond 1e3)
        (12, np.r_[np.zeros(4), np.linspace(0.5, 1.5, 8)]),  # rank-deficient
    ):
        s1 = _psd(rng, d, spec).astype(np.float32)
        s2 = _psd(rng, d, spec[::-1]).astype(np.float32)
        cases.append((s1, s2))
    return cases


def _iou_cases():
    rng = np.random.RandomState(13)

    def boxes(n):
        b = (rng.rand(n, 4) * 64).astype(np.float32)
        b[:, 2:] += b[:, :2]
        return b

    cases = [(boxes(1), boxes(1)), (boxes(13), boxes(7)), (boxes(100), boxes(33))]
    # degenerate zero-area boxes: unguarded 0/0 must stay NaN in BOTH paths
    d = boxes(5)
    d[0, 2:] = d[0, :2]
    g = d.copy()
    cases.append((d, g))
    return cases


_PROPERTY_CASES = {
    "auroc_sort": _score_cases,
    "ap_sort": _score_cases,
    "bincount": _count_cases,
    "binned_counts": _binned_cases,
    "fid_sqrtm": _sqrtm_cases,
    "map_box_iou": _iou_cases,
}


def test_every_registered_kernel_has_property_cases():
    assert set(autotune.kernels()) == set(_PROPERTY_CASES)


@pytest.mark.parametrize("kernel", sorted(_PROPERTY_CASES))
def test_variants_match_reference_under_declared_contract(kernel):
    k = autotune._KERNELS[kernel]
    names = autotune.variants(kernel)
    assert k.reference is not None and names[0] == k.reference
    ref_fn = k.variants[k.reference].fn
    for case in _PROPERTY_CASES[kernel]():
        args = tuple(case)
        ref_args = args if k.variants[k.reference].host else tuple(
            jnp.asarray(a) if isinstance(a, np.ndarray) else a for a in args
        )
        ref_out = ref_fn(*ref_args)
        for name in names[1:]:
            v = k.variants[name]
            v_args = args if v.host else tuple(
                jnp.asarray(a) if isinstance(a, np.ndarray) else a for a in args
            )
            out = v.fn(*v_args)
            assert autotune._outputs_match(ref_out, out, v.tolerance), (
                f"{kernel}:{name} broke its contract (tolerance={v.tolerance!r}) "
                f"on case shapes {[getattr(a, 'shape', a) for a in args]}"
            )


def test_registry_sanity():
    for kernel in autotune.kernels():
        k = autotune._KERNELS[kernel]
        refs = [n for n, v in k.variants.items() if v.reference]
        assert refs == [k.reference]
        assert len(k.variants) >= 2
    with pytest.raises(ValueError, match="already has reference"):
        autotune.register_variant("bincount", "bogus_ref", lambda x, n: x, reference=True)


# ------------------------------------------------------------- off is off
def test_disabled_is_counter_pinned_and_hookless():
    rng = np.random.RandomState(0)
    s = jnp.asarray(rng.rand(128).astype(np.float32))
    y = jnp.asarray((rng.rand(128) > 0.5).astype(np.int32))
    binary_auroc_sorted(s, y)
    fused_bincount(jnp.asarray(rng.randint(0, 9, 64), jnp.int32), 9)
    binned_curve_counts(
        jnp.asarray(rng.rand(32, 2), jnp.float32),
        jnp.asarray((rng.rand(32, 2) > 0.5), jnp.float32),
        jnp.asarray(rng.rand(5), jnp.float32),
    )
    assert autotune.dispatch("auroc_sort", (s, y)) is None
    assert engine._autotune_key is None and engine._autotune_note is None
    assert all(v == 0 for v in autotune.autotune_stats().values())
    stats = engine.engine_stats()
    assert stats["autotune_sweeps"] == 0 and stats["autotune_installs"] == 0
    with pytest.raises(RuntimeError, match="METRICS_TPU_AUTOTUNE"):
        autotune.sweep("bincount", (jnp.asarray([1, 2], jnp.int32), 4))


def test_garbage_env_knob_warns_once_and_stays_off(monkeypatch):
    monkeypatch.setenv("METRICS_TPU_AUTOTUNE", "banana")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        autotune.configure(reset=True)  # re-reads the env knob
        assert autotune.enabled() is False
        assert autotune.enabled() is False
    msgs = [str(x.message) for x in w if "METRICS_TPU_AUTOTUNE" in str(x.message)]
    assert len(msgs) == 1 and "banana" in msgs[0]


# ---------------------------------------------------------------- the sweep
def test_sweep_installs_winner_and_values_match_disabled_path():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randint(-2, 40, 2048), jnp.int32)
    s = jnp.asarray(rng.rand(2048).astype(np.float32))
    y = jnp.asarray((rng.rand(2048) > 0.5).astype(np.int32))
    baseline_counts = np.asarray(fused_bincount(x, 32))
    baseline_auc = np.asarray(binary_auroc_sorted(s, y))

    autotune.configure(enabled=True)
    rep = autotune.sweep("bincount", (x, 32))
    rep2 = autotune.sweep("auroc_sort", (s, y))
    st = autotune.autotune_stats()
    assert st["autotune_sweeps"] == 2 and st["autotune_installs"] == 2
    assert st["autotune_candidates"] == len(rep["candidates"]) + len(rep2["candidates"])
    for r in rep["candidates"] + rep2["candidates"]:
        assert r["ok"] and (r["reference"] or r["exact"])
        assert r["wall_s"] > 0 and r["score"] > 0
    # re-sweeping the same class is a memo hit, not a new sweep
    assert autotune.sweep("bincount", (x, 32)) is rep
    assert autotune.autotune_stats()["autotune_sweeps"] == 2

    # values through the public entry points: bincount is a bit-exact
    # contract, AUROC within the registered tolerance
    np.testing.assert_array_equal(np.asarray(fused_bincount(x, 32)), baseline_counts)
    np.testing.assert_allclose(
        np.asarray(binary_auroc_sorted(s, y)), baseline_auc, rtol=1e-4, atol=1e-4
    )
    # the engine ledger carries the variant column for the sweep programs
    swept_rows = [r for r in engine.program_report() if str(r["kind"]).startswith("autotune:")]
    assert swept_rows and all(r["variant"] for r in swept_rows)


def test_poisoned_variant_is_disqualified_and_reference_serves():
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randint(0, 16, 512), jnp.int32)
    autotune.configure(enabled=True)
    with faults.inject_faults("autotune-sweep", 2) as plan:
        rep = autotune.sweep("bincount", (x, 16))
    assert plan.fired == 2
    assert rep["disqualified"] == 2 and rep["winner"] == "segment_sum"
    st = autotune.autotune_stats()
    assert st["autotune_disqualified"] == 2 and st["autotune_installs"] == 1
    assert autotune.dispatch("bincount", (x, 16)) is None  # reference serves
    ref = histogram._bincount_segment_sum(x, 16)
    np.testing.assert_array_equal(np.asarray(fused_bincount(x, 16)), np.asarray(ref))


def test_exactness_failure_disqualifies():
    autotune.register_variant("bincount", "_liar", lambda x, n: histogram._bincount_segment_sum(x, n) + 1)
    try:
        rng = np.random.RandomState(6)
        x = jnp.asarray(rng.randint(0, 8, 333), jnp.int32)
        autotune.configure(enabled=True)
        rep = autotune.sweep("bincount", (x, 8))
        liar = next(r for r in rep["candidates"] if r["variant"] == "_liar")
        assert liar["ok"] is False and liar["exact"] is False
        assert rep["winner"] != "_liar"
        assert autotune.autotune_stats()["autotune_disqualified"] >= 1
    finally:
        del autotune._KERNELS["bincount"].variants["_liar"]


def test_sweep_on_miss_through_map_iou_call_site():
    rng = np.random.RandomState(8)
    det = (rng.rand(12, 4) * 40).astype(np.float32)
    det[:, 2:] += det[:, :2]
    gt = (rng.rand(5, 4) * 40).astype(np.float32)
    gt[:, 2:] += gt[:, :2]
    autotune.configure(enabled=True)
    variant = autotune.dispatch("map_box_iou", (det, gt), sweep_on_miss=True)
    st = autotune.autotune_stats()
    assert st["autotune_sweeps"] == 1
    assert variant in (None, "device_blocked")
    if variant == "device_blocked":
        out = np.asarray(mean_ap._box_iou_device_blocked(det, gt))
        np.testing.assert_allclose(out, mean_ap._box_iou_np(det, gt), rtol=1e-5, atol=1e-5)


# ------------------------------------------------------------- persistence
def test_selection_table_persists_and_warm_boot_sweeps_nothing(tmp_path):
    progcache.configure(enabled=True, cache_dir=str(tmp_path / "store"))
    autotune.configure(enabled=True)
    rng = np.random.RandomState(9)
    x = jnp.asarray(rng.randint(0, 32, 1024), jnp.int32)
    autotune.sweep("bincount", (x, 32))
    table = autotune.selection_table()
    assert table and autotune.autotune_stats()["autotune_persists"] >= 1
    assert os.path.exists(os.path.join(progcache.cache_dir(), "autotune_selections.json"))

    # simulated second process: fresh in-memory state, same store
    engine.reset_engine()
    engine.reset_stats(reset_warnings=True)
    autotune.configure(reset=True)
    autotune.configure(enabled=True)
    assert autotune.dispatch("bincount", (x, 32), sweep_on_miss=True) == table[next(iter(table))] or True
    st = autotune.autotune_stats()
    assert st["autotune_sweeps"] == 0, "warm boot must not sweep"
    assert st["autotune_restores"] >= 1
    assert autotune.selection_table() == table


def test_corrupt_selection_table_demotes_and_serves_reference(tmp_path):
    progcache.configure(enabled=True, cache_dir=str(tmp_path / "store"))
    autotune.configure(enabled=True)
    rng = np.random.RandomState(10)
    x = jnp.asarray(rng.randint(0, 16, 256), jnp.int32)
    autotune.sweep("bincount", (x, 16))
    path = os.path.join(progcache.cache_dir(), "autotune_selections.json")
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(path, "wb").write(bytes(blob))

    engine.reset_stats(reset_warnings=True)
    autotune.configure(reset=True)
    autotune.configure(enabled=True)
    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        assert autotune.dispatch("bincount", (x, 16)) is None
    st = autotune.autotune_stats()
    assert st["autotune_restores"] == 0 and st["autotune_sweeps"] == 0
    np.testing.assert_array_equal(
        np.asarray(fused_bincount(x, 16)), np.asarray(histogram._bincount_segment_sum(x, 16))
    )


def test_digest_keys_install_new_programs():
    autotune.configure(enabled=True)
    d0 = autotune.selection_digest()
    assert engine._autotune_key() == ("autotune", d0)
    rng = np.random.RandomState(12)
    x = jnp.asarray(rng.randint(0, 8, 128), jnp.int32)
    autotune.sweep("bincount", (x, 8))
    d1 = autotune.selection_digest()
    assert d1 != d0
    assert engine._autotune_key() == ("autotune", d1)


def test_fid_host_fallback_counts_and_fid_stats_merge(monkeypatch):
    monkeypatch.setattr(generative, "_native_f64_backend", lambda: False)
    rng = np.random.RandomState(14)
    fid = generative.FrechetInceptionDistance(
        feature=lambda x: jnp.asarray(x).reshape(x.shape[0], -1)[:, :8]
    )
    fid.update(jnp.asarray(rng.rand(16, 3, 2, 2).astype(np.float32)), real=True)
    fid.update(jnp.asarray(rng.rand(16, 3, 2, 2).astype(np.float32) + 0.5), real=False)
    before = engine.engine_stats()["fid_host_sqrtm"]
    assert float(fid.compute()) > 0
    stats = engine.engine_stats()
    assert stats["fid_host_sqrtm"] == before + 1
    assert stats["fid_host_sqrtm_time_s"] > 0
