"""Overload-safe ingestion gateway (ISSUE-19 contracts).

Contracts (`metrics_tpu/ingest.py`):

- **Routed parity** — payloads offered through the gateway land bit-exactly
  on direct `update()` oracles: suite targets (replayed through the deferral
  queue), arena targets (pow2-bucketed keyed routing), ragged/skewed and
  duplicate-id tenant batches (occurrence-split into duplicate-free
  dispatches).
- **Exact accounting** — `admitted + coalesced + shed + quarantined +
  staged == offered` rows at every instant, including under forced shed and
  priority eviction; after a drain the pure counter identity is exact.
- **Poison quarantine** — schema-mismatched and NaN/Inf-storm payloads
  settle into the bounded quarantine ring (classified `ingest` fault,
  warn-once), never raise, and leave target state bit-intact.
- **SLO-driven tiers** — synthetic `slo_violations_*` increments demote the
  gateway's ladder lane (shrunk watermarks, coalesce-before-shed); the
  standard recovery edge (clean flushes) re-promotes.
- **Disarmed overhead** — with telemetry/faults disarmed, offers after the
  schema pin record zero spans and pay one schema validation total
  (counter-pinned).
- **Warn-once env knobs** — `METRICS_TPU_INGEST_*` garbage values warn once
  naming the value and fall back to the default.
"""
from __future__ import annotations

import warnings

import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu import ingest as ingest_mod
from metrics_tpu.ingest import IngestGateway
from metrics_tpu.ops import engine, faults, telemetry
from metrics_tpu.parallel import sync as psync


@pytest.fixture(autouse=True)
def _clean_world():
    # retire gateways a failed test kept alive (pytest pins traceback locals)
    # so their staged rows can't skew this test's accounting identity
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for gw in list(ingest_mod._GATEWAYS):
            gw.close()
    psync.reset_membership()
    engine.reset_stats()
    yield
    psync.reset_membership()
    engine.reset_stats()


def _identity_holds() -> bool:
    s = engine.engine_stats()
    staged = ingest_mod.ingest_state()["staging_rows"]
    return s["ingest_offered_rows"] == (
        s["ingest_admitted_rows"] + s["ingest_coalesced_rows"]
        + s["ingest_shed_rows"] + s["ingest_quarantined_rows"] + staged
    )


def _mean_arena(name, capacity=8):
    arena = mt.MetricArena(mt.MeanMetric(), capacity=capacity, slab=4, name=name)
    return arena, arena.add(capacity)


# ------------------------------------------------------------------- parity
def test_suite_parity_vs_direct_update():
    rng = np.random.RandomState(0)
    m = mt.MeanMetric()
    oracle = mt.MeanMetric()
    gw = IngestGateway(m, name="sp")
    for _ in range(6):
        x = rng.rand(8).astype(np.float32)
        out = gw.offer(x)
        assert out["outcome"] == "staged"
        oracle.update(x)
    gw.flush()
    np.testing.assert_array_equal(np.asarray(m.compute()), np.asarray(oracle.compute()))
    assert _identity_holds()


def test_collection_parity_vs_direct_update():
    rng = np.random.RandomState(1)
    def make():
        return mt.MetricCollection({"mean": mt.MeanMetric(), "mse": mt.MeanSquaredError()})
    coll, oracle = make(), make()
    gw = IngestGateway(coll, name="cp")
    for _ in range(4):
        a = rng.rand(8).astype(np.float32)
        b = rng.rand(8).astype(np.float32)
        gw.offer(a, b)
        oracle.update(a, b)
    gw.flush()
    got = {k: np.asarray(v) for k, v in coll.compute().items()}
    want = {k: np.asarray(v) for k, v in oracle.compute().items()}
    for k in want:
        np.testing.assert_array_equal(got[k], want[k], err_msg=k)


def test_arena_parity_ragged_skewed_batches():
    rng = np.random.RandomState(2)
    arena, ids = _mean_arena("ing-par")
    direct, ids2 = _mean_arena("ing-ora")
    gw = IngestGateway(arena, name="ap", auto_flush=False)
    # skewed ragged batches: 1, 3, 7, 5 tenants per payload
    for size in (1, 3, 7, 5):
        tids = rng.choice(ids, size=size, replace=False).astype(np.int64)
        x = rng.rand(size, 2).astype(np.float32)
        assert gw.offer(x, tenant_ids=tids)["outcome"] == "staged"
        direct.update(tids, x)
    gw.flush()
    np.testing.assert_array_equal(
        np.asarray(arena.compute(ids)), np.asarray(direct.compute(ids2))
    )
    assert _identity_holds()


def test_arena_duplicate_ids_split_into_dup_free_dispatches():
    rng = np.random.RandomState(3)
    arena, ids = _mean_arena("ing-dup")
    direct, ids2 = _mean_arena("ing-dup-ora")
    gw = IngestGateway(arena, name="dp", auto_flush=False)
    tids = np.array([1, 4, 1, 1, 4], dtype=np.int64)  # tenant 1 x3, tenant 4 x2
    x = rng.rand(5, 2).astype(np.float32)
    gw.offer(x, tenant_ids=tids)
    out = gw.flush()
    assert out["dispatches"] == 3  # three occurrence levels
    # oracle: per-tenant rows applied in offer order, duplicate-free calls
    direct.update(np.array([1, 4]), x[[0, 1]])
    direct.update(np.array([1, 4]), x[[2, 4]])
    direct.update(np.array([1]), x[[3]])
    np.testing.assert_array_equal(
        np.asarray(arena.compute(ids)), np.asarray(direct.compute(ids2))
    )


def test_mapping_target_keyed_routing():
    rng = np.random.RandomState(4)
    suites = {"a": mt.MeanMetric(), "b": mt.MeanMetric()}
    oracles = {"a": mt.MeanMetric(), "b": mt.MeanMetric()}
    gw = IngestGateway(suites, name="rt")
    for route in ("a", "b", "a"):
        x = rng.rand(4).astype(np.float32)
        assert gw.offer(x, route=route)["outcome"] == "staged"
        oracles[route].update(x)
    gw.flush()
    for k in suites:
        np.testing.assert_array_equal(
            np.asarray(suites[k].compute()), np.asarray(oracles[k].compute()), err_msg=k
        )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        assert gw.offer(rng.rand(4).astype(np.float32), route="nope")["outcome"] == "quarantined"


# -------------------------------------------------------------- accounting
def test_exact_accounting_under_forced_shed():
    rng = np.random.RandomState(5)
    arena, ids = _mean_arena("ing-shed")
    gw = IngestGateway(arena, name="fs", auto_flush=False, max_rows=8)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        outcomes = [
            gw.offer(rng.rand(4, 2).astype(np.float32), tenant_ids=np.arange(4))["outcome"]
            for _ in range(5)
        ]
    assert outcomes.count("staged") == 2 and outcomes.count("shed") == 3
    assert _identity_holds()
    gw.flush()
    s = engine.engine_stats()
    assert ingest_mod.ingest_state()["staging_rows"] == 0
    assert s["ingest_offered_rows"] == 20
    assert s["ingest_admitted_rows"] == 8 and s["ingest_shed_rows"] == 12
    assert s["ingest_offered_rows"] == (
        s["ingest_admitted_rows"] + s["ingest_coalesced_rows"]
        + s["ingest_shed_rows"] + s["ingest_quarantined_rows"]
    )
    # sheds were classified into the ingest fault domain
    assert s["fault_ingest"] >= 1


def test_priority_evicts_lower_priority_staged_load():
    rng = np.random.RandomState(6)
    arena, ids = _mean_arena("ing-prio")
    gw = IngestGateway(arena, name="pr", auto_flush=False, max_rows=8)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        assert gw.offer(rng.rand(8, 2).astype(np.float32),
                        tenant_ids=np.arange(8), priority=0)["outcome"] == "staged"
        # higher-priority arrival displaces the staged low-priority payload
        assert gw.offer(rng.rand(8, 2).astype(np.float32),
                        tenant_ids=np.arange(8), priority=5)["outcome"] == "staged"
        # lower-priority arrival is the one shed when nothing outranked exists
        assert gw.offer(rng.rand(4, 2).astype(np.float32),
                        tenant_ids=np.arange(4), priority=1)["outcome"] == "shed"
    s = engine.engine_stats()
    assert s["ingest_shed_rows"] == 12 and s["ingest_shed_payloads"] == 2
    assert _identity_holds()
    gw.flush()
    assert _identity_holds()


def test_close_settles_staged_rows_as_shed():
    arena, ids = _mean_arena("ing-close")
    gw = IngestGateway(arena, name="cl", auto_flush=False)
    gw.offer(np.ones((4, 2), np.float32), tenant_ids=np.arange(4))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        gw.close()
    assert ingest_mod.ingest_state()["staging_rows"] == 0
    assert engine.engine_stats()["ingest_shed_rows"] == 4
    assert _identity_holds()


# ---------------------------------------------------------------- quarantine
def test_poison_quarantine_leaves_target_bit_intact():
    rng = np.random.RandomState(7)
    arena, ids = _mean_arena("ing-poison")
    gw = IngestGateway(arena, name="pq", auto_flush=False)
    good = rng.rand(8, 2).astype(np.float32)
    gw.offer(good, tenant_ids=np.asarray(ids))
    gw.flush()
    before = np.asarray(arena.compute(ids))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        nan_storm = np.full((8, 2), np.inf, dtype=np.float32)
        assert gw.offer(nan_storm, tenant_ids=np.asarray(ids))["outcome"] == "quarantined"
        wrong_shape = rng.rand(8, 3).astype(np.float32)
        assert gw.offer(wrong_shape, tenant_ids=np.asarray(ids))["outcome"] == "quarantined"
        wrong_dtype = rng.rand(8, 2).astype(np.float64)
        assert gw.offer(wrong_dtype, tenant_ids=np.asarray(ids))["outcome"] == "quarantined"
        ragged_ids = np.arange(3)
        assert gw.offer(rng.rand(8, 2).astype(np.float32),
                        tenant_ids=ragged_ids)["outcome"] == "quarantined"
    gw.flush()
    np.testing.assert_array_equal(np.asarray(arena.compute(ids)), before)
    ring = gw.quarantined()
    assert len(ring) == 4
    assert any("NaN/Inf" in e["reason"] for e in ring)
    assert any("schema mismatch" in e["reason"] for e in ring)
    s = engine.engine_stats()
    assert s["ingest_quarantined_payloads"] == 4
    assert s["fault_ingest"] >= 4
    assert _identity_holds()
    # warn-once: quarantines dedupe per gateway+domain
    ingest_warnings = [w for w in caught if "quarantined" in str(w.message)]
    assert len(ingest_warnings) == 1


def test_quarantine_ring_is_bounded():
    arena, ids = _mean_arena("ing-ring")
    gw = IngestGateway(arena, name="qr", auto_flush=False, quarantine_cap=2)
    gw.offer(np.ones((2, 2), np.float32), tenant_ids=np.arange(2))  # pins schema
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for _ in range(5):
            gw.offer(np.ones((2, 3), np.float32), tenant_ids=np.arange(2))
    assert len(gw.quarantined()) == 2
    assert engine.engine_stats()["ingest_quarantine_evictions"] == 3
    assert _identity_holds()


def test_injected_admission_fault_settles_as_quarantine():
    arena, ids = _mean_arena("ing-inj")
    gw = IngestGateway(arena, name="ij", auto_flush=False)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with faults.inject_faults("ingest-admit") as plan:
            out = gw.offer(np.ones((2, 2), np.float32), tenant_ids=np.arange(2))
    assert plan.fired == 1 and out["outcome"] == "quarantined"
    assert _identity_holds()


def test_injected_flush_fault_never_raises():
    arena, ids = _mean_arena("ing-flt")
    gw = IngestGateway(arena, name="fl", auto_flush=False)
    gw.offer(np.ones((2, 2), np.float32), tenant_ids=np.arange(2))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with faults.inject_faults("ingest-shed") as plan:
            out = gw.flush()
    assert plan.fired == 1 and out["rows"] == 0
    s = engine.engine_stats()
    assert s["ingest_apply_faults"] == 1 and s["ingest_quarantined_rows"] == 2
    assert _identity_holds()


# ------------------------------------------------------------ degraded tier
def test_slo_violation_demotes_and_recovery_edge_promotes():
    rng = np.random.RandomState(8)
    arena, ids = _mean_arena("ing-slo")
    gw = IngestGateway(arena, name="sl", auto_flush=False, max_rows=64)
    tids = np.asarray(ids)
    x = lambda: rng.rand(8, 2).astype(np.float32)  # noqa: E731
    assert gw.offer(x(), tenant_ids=tids)["outcome"] == "staged"
    assert not gw.degraded
    faults.set_recovery_policy(steps=2)
    try:
        # synthetic SLO violation: the budget plane reports a new firing
        telemetry._slo_violations["engine-flush"] = (
            telemetry._slo_violations.get("engine-flush", 0) + 1
        )
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            # the demote fires inside this very offer, and coalesce-first
            # applies immediately: it merges into offer 1's staged payload
            assert gw.offer(x(), tenant_ids=tids)["outcome"] == "coalesced"
        assert gw.degraded
        # degraded: same-schema arena payloads coalesce before anything sheds
        assert gw.offer(x(), tenant_ids=tids)["outcome"] == "coalesced"
        # clean flushes with no new violations walk the standard recovery edge
        gw.flush()
        assert gw.degraded  # 1 clean flush < steps=2
        gw.offer(x(), tenant_ids=tids)
        gw.flush()
        assert not gw.degraded
        assert engine.engine_stats()["ingest_degraded_offers"] >= 2
        assert _identity_holds()
    finally:
        faults.set_recovery_policy(steps=8)


def test_degraded_tier_shrinks_watermarks():
    arena, ids = _mean_arena("ing-shrink")
    gw = IngestGateway(arena, name="sh", auto_flush=False, max_rows=16,
                       degraded_factor=0.5)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        telemetry._slo_violations["engine-flush"] = (
            telemetry._slo_violations.get("engine-flush", 0) + 1
        )
        # degraded effective watermark = 8 rows: a 12-row payload sheds
        out = gw.offer(np.ones((12, 2), np.float32),
                       tenant_ids=np.arange(12) % 8)
    assert gw.degraded and out["outcome"] == "shed"
    assert _identity_holds()


# --------------------------------------------------------- disarmed overhead
def test_disarmed_gateway_counter_pinned_overhead():
    rng = np.random.RandomState(9)
    arena, ids = _mean_arena("ing-cheap")
    gw = IngestGateway(arena, name="ch", auto_flush=False, max_rows=10_000)
    tids = np.asarray(ids)
    gw.offer(rng.rand(8, 2).astype(np.float32), tenant_ids=tids)  # pins schema
    prev_armed = telemetry.armed
    telemetry.set_telemetry(False)
    try:
        assert not telemetry.armed and not faults.armed
        spans0 = telemetry.telemetry_stats()["spans_recorded"]
        val0 = engine.engine_stats()["ingest_schema_validations"]
        for _ in range(50):
            gw.offer(rng.rand(8, 2).astype(np.float32), tenant_ids=tids)
        # disarmed: zero spans recorded, zero further schema validations — the
        # per-offer cost is the fingerprint lookup plus the list append
        assert telemetry.telemetry_stats()["spans_recorded"] == spans0
        assert engine.engine_stats()["ingest_schema_validations"] == val0 == 1
        gw.flush()
        assert _identity_holds()
    finally:
        telemetry.set_telemetry(prev_armed)


def test_reset_stats_zeroes_ingest_without_resurrecting_warn_once():
    arena, ids = _mean_arena("ing-reset")
    gw = IngestGateway(arena, name="rs", auto_flush=False, max_rows=4)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        gw.offer(np.ones((8, 2), np.float32), tenant_ids=np.arange(8) % 4)  # shed
        assert engine.engine_stats()["ingest_shed_rows"] == 8
        engine.reset_stats()
        assert engine.engine_stats()["ingest_shed_rows"] == 0
        # the warn-once marker survived the counter reset: a second shed
        # does not warn again
        gw.offer(np.ones((8, 2), np.float32), tenant_ids=np.arange(8) % 4)
    shed_warnings = [w for w in caught if "shedding load" in str(w.message)]
    assert len(shed_warnings) == 1
    # the explicit opt-in clears the marker
    engine.reset_stats(reset_warnings=True)
    with warnings.catch_warnings(record=True) as caught2:
        warnings.simplefilter("always")
        gw.offer(np.ones((8, 2), np.float32), tenant_ids=np.arange(8) % 4)
    assert any("shedding load" in str(w.message) for w in caught2)


# ----------------------------------------------------------------- env knobs
def test_env_knobs_warn_once_naming_value(monkeypatch):
    monkeypatch.setenv("METRICS_TPU_INGEST_MAX_ROWS", "lots")
    monkeypatch.setattr(ingest_mod, "_MAX_ROWS_OWNER", ingest_mod._IngestWarnOwner())
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        assert ingest_mod._knob_max_rows() == 4096
        assert ingest_mod._knob_max_rows() == 4096
    messages = [str(w.message) for w in caught]
    assert len(messages) == 1 and "lots" in messages[0]
    monkeypatch.setenv("METRICS_TPU_INGEST_DEGRADED_FACTOR", "9.0")
    assert ingest_mod._knob_degraded_factor() == 1.0  # clamped, no warning


def test_env_knobs_configure_gateway(monkeypatch):
    monkeypatch.setenv("METRICS_TPU_INGEST_MAX_ROWS", "32")
    monkeypatch.setenv("METRICS_TPU_INGEST_QUARANTINE_CAP", "3")
    m = mt.MeanMetric()
    gw = IngestGateway(m, name="ek")
    assert gw.max_rows == 32 and gw._quarantine.maxlen == 3


# ----------------------------------------------------------------- telemetry
def test_span_sites_and_snapshot_plane():
    arena, ids = _mean_arena("ing-tel")
    gw = IngestGateway(arena, name="tl", auto_flush=False)
    prev_armed = telemetry.armed
    telemetry.set_telemetry(True)
    try:
        gw.offer(np.ones((4, 2), np.float32), tenant_ids=np.arange(4))
        gw.flush()
        sites = {s[3] for s in telemetry._ring}
        assert "ingest-offer" in sites and "ingest-flush" in sites
        snap = telemetry.snapshot()
        assert snap["ingest_state"]["gateway_count"] >= 1
        assert "tl" in snap["ingest_state"]["gateways"]
        assert not telemetry.is_counter_key("ingest_state_staging_rows")
        assert telemetry.is_counter_key("ingest_offered_rows")
    finally:
        telemetry.set_telemetry(prev_armed)
