"""Multi-tenant metric arenas (ISSUE-17 contracts).

Contracts (`metrics_tpu/arena.py`):

- **Vmapped parity** — `update(tenant_ids, *batch)` / `compute()` /
  `reset(mask)` over the stacked `FuncState` trees are bit-exact vs a
  per-instance module loop, across the fused lane (Accuracy, Mean, a
  compute-group collection) and the row lane (AUROC's cat states).
- **Slab-bucketed shapes** — capacity only takes `slab * 2**k` values, so
  add/remove across a slab boundary retraces exactly once per NEW bucket
  (pinned by the engine's `builds` counter) and zero times inside one.
- **Reset-mask isolation** — resetting tenant A never perturbs tenant B's
  state, bit-exactly; removed ids recycle through the free list.
- **Slab-granular durability** — one CRC-framed journal record per slab,
  each with its own generation ring; a torn slab record demotes to ITS
  previous good generation while every other slab restores untouched.
- **Warn-once env knobs** — `METRICS_TPU_ARENA_*` garbage values warn once
  naming the value and fall back to the default.
- **Arena-native streaming** — per-cohort merge/close/drift run as fused
  programs and render in `fleet_prometheus_text` with `tenant_cohort`
  labels.
"""
from __future__ import annotations

import os
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import metrics_tpu as mt
from metrics_tpu import arena as arena_mod
from metrics_tpu.arena import MetricArena, stack_states, unstack_states
from metrics_tpu.ops import engine, fleetobs, journal as journal_mod, telemetry
from metrics_tpu.parallel import sync as psync


@pytest.fixture(autouse=True)
def _clean_world():
    psync.reset_membership()
    engine.reset_stats()
    yield
    psync.reset_membership()
    engine.reset_stats()


def _binary_batch(rng, n, b=8):
    preds = jnp.asarray(rng.randint(0, 2, (n, b)).astype(np.int32))
    target = jnp.asarray(rng.randint(0, 2, (n, b)).astype(np.int32))
    return preds, target


# ------------------------------------------------------------------- parity
def test_parity_accuracy_vs_oracle():
    rng = np.random.RandomState(0)
    n = 6
    arena = MetricArena(mt.Accuracy(num_classes=2), capacity=n, slab=8, name="par-acc")
    ids = arena.add(n)
    oracles = [mt.Accuracy(num_classes=2) for _ in range(n)]
    for _ in range(3):
        preds, target = _binary_batch(rng, n)
        arena.update(ids, preds, target)
        for i, m in enumerate(oracles):
            m.update(preds[i], target[i])
    got = np.asarray(arena.compute(ids))
    want = np.stack([np.asarray(m.compute()) for m in oracles])
    np.testing.assert_array_equal(got, want)


def test_parity_mean_ragged_rounds():
    rng = np.random.RandomState(1)
    n = 5
    arena = MetricArena(mt.MeanMetric(), capacity=n, slab=4, name="par-mean")
    ids = arena.add(n)
    oracles = [mt.MeanMetric() for _ in range(n)]
    for r in range(4):
        sub = list(range(n - r))  # ragged: shrinking tenant subset
        vals = jnp.asarray(rng.randn(len(sub), 3).astype(np.float32))
        arena.update(sub, vals)
        for pos, tid in enumerate(sub):
            oracles[tid].update(vals[pos])
    got = np.asarray(arena.compute(ids))
    want = np.stack([np.asarray(m.compute()) for m in oracles])
    np.testing.assert_array_equal(got, want)


def test_parity_auroc_row_lane():
    rng = np.random.RandomState(2)
    n = 4
    arena = MetricArena(mt.AUROC(pos_label=1), capacity=n, slab=4, name="par-roc")
    ids = arena.add(n)
    assert not arena.fused  # cat-state suites ride the row lane
    oracles = [mt.AUROC(pos_label=1) for _ in range(n)]
    for _ in range(2):
        scores = jnp.asarray(rng.rand(n, 16).astype(np.float32))
        hits = jnp.asarray(rng.randint(0, 2, (n, 16)))
        arena.update(ids, scores, hits)
        for i, m in enumerate(oracles):
            m.update(scores[i], hits[i])
    got = np.asarray(arena.compute(ids))
    want = np.stack([np.asarray(m.compute()) for m in oracles])
    # the batched compute vmaps the trapezoid fold, which may reassociate
    # the float32 sum by one ulp vs the scalar oracle
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_parity_compute_group_collection():
    rng = np.random.RandomState(3)
    n = 4

    def make():
        return mt.MetricCollection(
            {"acc": mt.Accuracy(num_classes=2), "mean": mt.MeanMetric()}
        )

    arena = MetricArena(make(), capacity=n, slab=4, name="par-col")
    ids = arena.add(n)
    oracles = [make() for _ in range(n)]
    for _ in range(2):
        preds, target = _binary_batch(rng, n)
        arena.update(ids, preds, target)
        for i, m in enumerate(oracles):
            m.update(preds[i], target[i])
    got = arena.compute(ids)
    for key in got:
        want = np.stack([np.asarray(m.compute()[key]) for m in oracles])
        np.testing.assert_array_equal(np.asarray(got[key]), want)


# --------------------------------------------------- slab buckets / retraces
def test_slab_boundary_retraces_exactly_once_per_bucket():
    engine.reset_engine()  # drop cached programs: pin builds from a cold cache
    rng = np.random.RandomState(4)
    arena = MetricArena(mt.MeanMetric(), capacity=8, slab=8, name="slabs")
    one = jnp.asarray(rng.randn(1, 2).astype(np.float32))
    builds0 = engine.engine_stats()["builds"]
    for _ in range(32):  # capacity walks 8 -> 16 -> 32: three buckets
        (tid,) = arena.add(1)
        arena.update([tid], one)
    built = engine.engine_stats()["builds"] - builds0
    assert built == 3, f"expected one chunk-1 program per bucket, built {built}"
    assert arena.capacity == 32
    # inside the bucket: more adds + updates retrace nothing
    builds1 = engine.engine_stats()["builds"]
    arena.update([0], one)
    arena.update([5], one)
    assert engine.engine_stats()["builds"] == builds1


def test_remove_recycles_ids_and_shrinks_trailing_slabs():
    arena = MetricArena(mt.MeanMetric(), capacity=8, slab=8, name="recycle")
    ids = arena.add(20)  # grows to 32
    assert arena.capacity == 32
    arena.remove([2, 5])  # mid-stack holes go on the free list, no shrink
    assert arena.capacity == 32
    new_ids = arena.add(2)
    assert set(new_ids) == {2, 5}  # lowest freed ids recycle first
    assert arena_mod.arena_stats()["arena_ids_recycled"] == 2
    arena.remove(ids[8:])  # trailing tenants gone -> trailing slabs release
    assert arena.capacity == 8
    assert arena_mod.arena_stats()["arena_shrinks"] >= 1
    assert arena.tenants == 8


def test_duplicate_and_dead_tenant_ids_rejected():
    arena = MetricArena(mt.MeanMetric(), capacity=4, slab=4, name="ids")
    ids = arena.add(2)
    one = jnp.ones((2, 1))
    with pytest.raises(ValueError, match="duplicate"):
        arena.update([ids[0], ids[0]], one)
    with pytest.raises(ValueError, match="not live"):
        arena.update([3], jnp.ones((1, 1)))


# ---------------------------------------------------------- reset isolation
def test_reset_mask_isolation_bit_exact():
    rng = np.random.RandomState(5)
    n = 8
    arena = MetricArena(mt.MeanMetric(), capacity=n, slab=8, name="isolate")
    ids = arena.add(n)
    arena.update(ids, jnp.asarray(rng.randn(n, 4).astype(np.float32)))
    before = np.asarray(arena.compute(ids))
    reset_ids = [2, 6]
    arena.reset(tenant_ids=reset_ids)
    after = np.asarray(arena.compute(ids))
    survivors = [i for i in range(n) if i not in reset_ids]
    np.testing.assert_array_equal(after[survivors], before[survivors])
    # the reset tenants restart from init: their next update is their whole state
    vals = jnp.asarray([[3.0], [7.0]])
    arena.update(reset_ids, vals)
    np.testing.assert_array_equal(
        np.asarray(arena.compute(reset_ids)), np.asarray([3.0, 7.0])
    )


def test_reset_full_mask_matches_capacity():
    arena = MetricArena(mt.MeanMetric(), capacity=4, slab=4, name="mask")
    ids = arena.add(2)
    arena.update(ids, jnp.ones((2, 1)))
    mask = np.zeros(arena.capacity, dtype=bool)
    mask[ids[0]] = True
    arena.reset(mask)
    with pytest.raises(ValueError, match="capacity"):
        arena.reset(np.zeros(3, dtype=bool))


# ------------------------------------------------------------- durability
def test_slab_journal_roundtrip(tmp_path):
    rng = np.random.RandomState(6)
    path = str(tmp_path / "arena.j")
    arena = MetricArena(mt.MeanMetric(), capacity=8, slab=4, name="dur", journal_path=path)
    ids = arena.add(8, cohort="blue")
    vals = jnp.asarray(rng.randn(8, 2).astype(np.float32))
    arena.update(ids, vals)
    total = arena.save()
    assert total > 0 and os.path.exists(path + ".slab0") and os.path.exists(path + ".slab1")
    twin = MetricArena(mt.MeanMetric(), capacity=8, slab=4, name="dur2", journal_path=path)
    info = twin.restore()
    assert info == {"slabs": 2, "demotions": 0, "tenants": 8}
    np.testing.assert_array_equal(
        np.asarray(twin.compute()), np.asarray(arena.compute())
    )
    assert twin.cohort_of(0) == "blue"


def test_torn_slab_record_demotes_without_touching_neighbours(tmp_path):
    path = str(tmp_path / "arena.j")
    arena = MetricArena(mt.MeanMetric(), capacity=8, slab=4, name="torn", journal_path=path)
    ids = arena.add(8)
    arena.update(ids, jnp.arange(8.0).reshape(8, 1) + 1)
    arena.save()  # generation 1 (rotated to .g1 by the next save)
    gen1 = np.asarray(arena.compute())
    arena.update(ids, jnp.arange(8.0).reshape(8, 1) + 100)
    arena.save()  # generation 0 (newest)
    gen0 = np.asarray(arena.compute())
    # tear slab 1's NEWEST generation mid-record
    with open(path + ".slab1", "r+b") as fh:
        fh.seek(24)
        fh.write(b"\xff\xff\xff\xff")
    twin = MetricArena(mt.MeanMetric(), capacity=8, slab=4, name="torn2", journal_path=path)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        info = twin.restore()
    assert info["demotions"] == 1
    restored = np.asarray(twin.compute())
    # slab 0 (tenants 0-3) restored from the newest generation, untouched
    np.testing.assert_array_equal(restored[:4], gen0[:4])
    # slab 1 (tenants 4-7) demoted to ITS previous good generation
    np.testing.assert_array_equal(restored[4:], gen1[4:])
    assert arena_mod.arena_stats()["arena_slab_demotions"] == 1


def test_all_generations_torn_slab_resets_to_init(tmp_path):
    path = str(tmp_path / "arena.j")
    arena = MetricArena(mt.MeanMetric(), capacity=4, slab=4, name="dead", journal_path=path)
    ids = arena.add(4)
    arena.update(ids, jnp.ones((4, 1)))
    arena.save()
    with open(path + ".slab0", "r+b") as fh:
        fh.seek(0)
        fh.write(b"XXXX")  # foreign magic: the only generation is bad
    twin = MetricArena(mt.MeanMetric(), capacity=4, slab=4, name="dead2", journal_path=path)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        info = twin.restore()
    assert info["demotions"] == 1 and info["tenants"] == 0  # slab reset to init, dead


def test_shrink_save_restore_never_resurrects_removed_tenants(tmp_path):
    path = str(tmp_path / "arena.j")
    arena = MetricArena(mt.MeanMetric(), capacity=8, slab=4, name="shrink", journal_path=path)
    ids = arena.add(8)
    arena.update(ids, jnp.ones((8, 1)))
    arena.save()
    assert os.path.exists(path + ".slab1")
    with open(path + ".slab1", "rb") as fh:
        stale = fh.read()
    arena.remove(ids[4:])  # trailing slab empties -> shrink to 1 slab
    assert arena.slabs == 1
    arena.save()
    # save() pruned the retired slab's files...
    assert not os.path.exists(path + ".slab1")
    assert arena_mod.arena_stats()["arena_slab_prunes"] >= 1
    # ...and even if a stale record survives (crash between the shrink's save
    # and its prune, or an older writer), the newest slab-0 record's capacity
    # is authoritative: the stale slab must not resurrect removed tenants
    with open(path + ".slab1", "wb") as fh:
        fh.write(stale)
    twin = MetricArena(mt.MeanMetric(), capacity=8, slab=4, name="shrink2", journal_path=path)
    info = twin.restore()
    assert info == {"slabs": 1, "demotions": 0, "tenants": 4}
    assert twin.capacity == 4
    np.testing.assert_array_equal(np.asarray(twin.compute()), np.asarray(arena.compute()))


def test_template_layout_mismatch_demotes_not_silent_init(tmp_path):
    path = str(tmp_path / "arena.j")
    arena = MetricArena(mt.MeanMetric(), capacity=4, slab=4, name="layout", journal_path=path)
    ids = arena.add(4)
    arena.update(ids, jnp.ones((4, 1)))
    arena.save()
    # a different template config (different state names) must demote the
    # record like any other corruption — never come back live at init values
    twin = MetricArena(
        mt.Accuracy(num_classes=2), capacity=4, slab=4, name="layout2", journal_path=path
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        info = twin.restore()
    assert info["demotions"] == 1 and info["tenants"] == 0


def test_row_lane_refuses_slab_journal(tmp_path):
    arena = MetricArena(mt.AUROC(pos_label=1), capacity=2, slab=2, name="rowj")
    with pytest.raises(ValueError, match="cat/list"):
        arena.save(str(tmp_path / "x.j"))
    with pytest.raises(ValueError, match="cat/list"):
        arena.restore(str(tmp_path / "x.j"))


# --------------------------------------------------------------- env knobs
def test_env_knobs_warn_once_naming_value(monkeypatch):
    monkeypatch.setenv("METRICS_TPU_ARENA_SLAB", "not-a-number")
    monkeypatch.setattr(arena_mod, "_SLAB_WARN_OWNER", arena_mod._ArenaWarnOwner())
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        assert arena_mod.arena_default_slab() == 256
        assert arena_mod.arena_default_slab() == 256
    messages = [str(w.message) for w in caught]
    assert len(messages) == 1 and "not-a-number" in messages[0]
    monkeypatch.setenv("METRICS_TPU_ARENA_JOURNAL_EVERY", "-3")
    assert arena_mod.arena_journal_every() == 0  # floored, no warning (parseable)


def test_journal_every_autosaves(tmp_path, monkeypatch):
    path = str(tmp_path / "auto.j")
    arena = MetricArena(
        mt.MeanMetric(), capacity=4, slab=4, name="auto",
        journal_path=path, journal_every=2,
    )
    ids = arena.add(2)
    arena.update(ids, jnp.ones((2, 1)))
    assert not os.path.exists(path + ".slab0")
    arena.update(ids, jnp.ones((2, 1)))
    assert os.path.exists(path + ".slab0")  # every-2 cadence fired


# ------------------------------------------------- streaming / exposition
def test_cohort_values_match_merged_oracle():
    arena = MetricArena(mt.MeanMetric(), capacity=8, slab=8, name="cohorts")
    eu = arena.add(2, cohort="eu")
    us = arena.add(2, cohort="us")
    arena.update(eu + us, jnp.asarray([[1.0], [3.0], [10.0], [30.0]]))
    vals = arena.cohort_values()
    assert float(np.asarray(vals["eu"])) == 2.0
    assert float(np.asarray(vals["us"])) == 20.0
    # count-weighted: one more update for eu tenant 0 only
    arena.update([eu[0]], jnp.asarray([[5.0]]))
    oracle = mt.MeanMetric()
    oracle.update(jnp.asarray([1.0, 5.0]))
    oracle.update(jnp.asarray([3.0]))
    np.testing.assert_allclose(
        float(np.asarray(arena.cohort_values()["eu"])), float(oracle.compute()), atol=1e-6
    )


def test_close_window_resets_and_window_values_fold():
    arena = MetricArena(mt.SumMetric(), capacity=4, slab=4, name="win", window_slots=2)
    ids = arena.add(2, cohort="c")
    arena.update(ids, jnp.asarray([[1.0], [2.0]]))
    out = arena.close_window()
    assert out["window"] == 1
    assert float(np.asarray(out["cohorts"]["c"])) == 3.0
    # close resets the live tenants: next stride starts clean
    arena.update(ids, jnp.asarray([[10.0], [20.0]]))
    arena.close_window()
    folded = arena.window_values()
    assert float(np.asarray(folded["c"])) == 33.0  # both retained slots fold


def test_decay_tick_scales_and_validates():
    arena = MetricArena(mt.SumMetric(), capacity=2, slab=2, name="decay")
    ids = arena.add(2)
    arena.update(ids, jnp.asarray([[8.0], [16.0]]))
    arena.decay_tick(1.0)  # halflife of one tick: exactly halve
    np.testing.assert_array_equal(np.asarray(arena.compute(ids)), [4.0, 8.0])
    acc = MetricArena(mt.Accuracy(num_classes=2), capacity=2, slab=2, name="decay-int")
    with pytest.raises(ValueError, match="decay_tick"):
        acc.decay_tick(4.0)


def test_cohort_drift_and_fleet_exposition():
    arena = MetricArena(mt.MeanMetric(), capacity=8, slab=8, name="expo")
    a = arena.add(3, cohort="ref")
    b = arena.add(3, cohort="cur")
    arena.update(a + b, jnp.concatenate([jnp.ones((3, 2)), 5 * jnp.ones((3, 2))]))
    report = arena.cohort_drift("cur", "ref")
    assert report["psi"] > 0
    arena.cohort_values()  # publish the cohort block
    from metrics_tpu import streaming

    assert "expo" in streaming.streaming_snapshot()["arenas"]
    text = fleetobs.fleet_prometheus_text()
    assert 'tenant_cohort="ref"' in text and 'tenant_cohort="cur"' in text
    assert 'metrics_tpu_fleet_arena_tenants{name="expo"} 6' in text
    assert 'metrics_tpu_drift_score{name="expo/cur",kind="psi"}' in text


def test_arena_counters_fold_into_engine_stats():
    arena = MetricArena(mt.MeanMetric(), capacity=2, slab=2, name="stats")
    ids = arena.add(2)
    arena.update(ids, jnp.ones((2, 1)))
    stats = engine.engine_stats()
    assert stats["arena_updates"] >= 1 and stats["arena_tenants_added"] >= 2
    assert telemetry.is_counter_key("arena_updates")
    engine.reset_stats()
    assert engine.engine_stats()["arena_updates"] == 0


# ------------------------------------------------------- stacking helpers
def test_stack_unstack_roundtrip():
    trees = [
        {"a": jnp.asarray([float(i)]), "b": jnp.asarray(i)} for i in range(3)
    ]
    stacked = stack_states(trees)
    assert stacked["a"].shape == (3, 1)
    back = unstack_states(stacked, 3)
    for i in range(3):
        np.testing.assert_array_equal(np.asarray(back[i]["a"]), np.asarray(trees[i]["a"]))


def test_bootstrapper_uses_arena_stacking(monkeypatch):
    # the fused clone fan-out must flow through the arena's stacking helper
    engine.reset_engine()  # drop cached fan-out programs so build() reruns
    calls = {"n": 0}
    real = arena_mod.stack_states

    def spy(states):
        calls["n"] += 1
        return real(states)

    monkeypatch.setattr(arena_mod, "stack_states", spy)
    import metrics_tpu.wrappers.bootstrapping as boot

    rng = np.random.RandomState(7)
    wrapper = boot.BootStrapper(mt.MeanMetric(), num_bootstraps=4)
    x = jnp.asarray(rng.randn(32).astype(np.float32))
    for _ in range(4):  # build() reruns on the cold cache -> spy traces
        wrapper.update(x)
    assert calls["n"] >= 1, "fused fan-out no longer stacks through the arena helper"
