"""Device-time probes: the ISSUE-12 sampled ``block_until_ready`` path.

Contracts (`metrics_tpu/ops/engine.py` + `ops/telemetry.py`):

- **Bit-exact** — probing only *observes* (a forced wait on the output);
  a probed loop's results equal an unprobed loop's exactly, including
  through the deferral queue.
- **Disarmed allocates nothing** — ``METRICS_TPU_DEVICE_PROBE_EVERY``
  unset/0 (the default) leaves the counter at zero and creates no
  per-program histogram families; a garbage value warns once NAMING the
  offending value and stays disarmed.
- **Sampling** — ``EVERY=N`` probes every Nth non-compile dispatch
  globally; compile events are never probed (their wall is trace+XLA, not
  device execution).
- **Composes with deferral** — a probed flush forces the WHOLE stacked
  chunk and counts as ONE probe per chunk program dispatched, never one
  per enqueued step.
- **The plane lands where the roofline reads it** — probes fill the
  aggregate ``device-dispatch`` site histogram, the per-program
  ``device-dispatch:<program>`` families (``device_dispatch_stats``), and
  ``program_report`` rows join them under ``device`` / ``roofline``.
"""
from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu.ops import engine, telemetry

RNG = np.random.RandomState(11)


def _batch(n=32):
    return (
        jnp.asarray(RNG.rand(n).astype(np.float32)),
        jnp.asarray(RNG.randint(0, 2, n)),
    )


@pytest.fixture(autouse=True)
def _probe_isolation():
    """Probes off on entry and exit (re-armed per test), recorder armed,
    latency plane isolated."""
    was = telemetry.armed
    telemetry.set_telemetry(True)
    telemetry.clear_spans()
    telemetry.reset_latency()
    engine.set_device_probe(0)
    yield
    engine.set_device_probe(None)
    telemetry.set_telemetry(was)
    telemetry.clear_spans()
    telemetry.reset_latency()


def _drive(metric, batches, probe_every):
    engine.set_device_probe(probe_every)
    for b in batches:
        metric.update(*b)
    value = metric.compute()
    engine.set_device_probe(0)
    return value


def test_probed_dispatch_is_bit_exact_vs_unprobed():
    batches = [_batch() for _ in range(9)]
    engine.set_deferred_dispatch(True)
    unprobed = _drive(mt.Accuracy(), batches, 0)
    probed = _drive(mt.Accuracy(), batches, 1)
    np.testing.assert_array_equal(np.asarray(unprobed), np.asarray(probed))
    assert engine.engine_stats()["device_probes"] > 0


def test_unset_allocates_nothing_and_counts_nothing():
    probes_before = engine.engine_stats()["device_probes"]
    metric = mt.Accuracy()
    for _ in range(5):
        metric.update(*_batch())
    metric.compute()
    assert engine.engine_stats()["device_probes"] == probes_before
    assert telemetry.device_dispatch_stats() == {}
    assert not any(
        site.startswith(telemetry._DEVICE_HIST_SITE)
        for site in telemetry.latency_stats()
    )


def test_garbage_env_warns_once_naming_value(monkeypatch):
    monkeypatch.setenv("METRICS_TPU_DEVICE_PROBE_EVERY", "banana")
    engine.set_device_probe(None)  # drop the cache so the env is re-read
    engine.reset_stats(reset_warnings=True)
    with pytest.warns(UserWarning, match="banana"):
        assert engine.device_probe_every() == 0
    # warn-once: the cached parse re-serves without a second warning
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert engine.device_probe_every() == 0


def test_probe_sampling_period_counts_every_nth_dispatch():
    exe = engine.acquire_keyed(
        ("probe-period-test",), lambda: (lambda s: s + 1, None, {}), donate=False
    )
    x = jnp.zeros((), jnp.float32)
    exe.run(x, donate=False)  # compile event: never probed
    engine.set_device_probe(3)
    before = engine.engine_stats()["device_probes"]
    for _ in range(9):
        exe.run(x, donate=False)
    assert engine.engine_stats()["device_probes"] - before == 3
    block = telemetry.device_dispatch_stats()[exe.probe_key]
    assert block["count"] == 3 and block["sum_s"] > 0


def test_compile_events_are_never_probed():
    engine.set_device_probe(1)
    exe = engine.acquire_keyed(
        ("probe-compile-test",), lambda: (lambda s: s * 2, None, {}), donate=False
    )
    exe.run(jnp.zeros((4,), jnp.float32), donate=False)  # compile
    exe.run(jnp.zeros((8,), jnp.float32), donate=False)  # new aval: compile
    assert exe.compiles == 2
    assert exe.probe_key not in telemetry.device_dispatch_stats()
    exe.run(jnp.zeros((8,), jnp.float32), donate=False)  # cached: probed
    assert telemetry.device_dispatch_stats()[exe.probe_key]["count"] == 1


def test_probed_flush_forces_whole_chunk_counted_once():
    """8 enqueued steps flush as ONE stacked chunk program: with EVERY=1 the
    probe blocks the whole chunk and counts once per chunk DISPATCH, never
    per step — and the flushed value is bit-exact vs the unprobed queue."""
    engine.set_deferred_dispatch(True)
    batches = [_batch() for _ in range(8)]

    def run(probe_every):
        metric = mt.Accuracy()
        metric.update(*batches[0])  # eager first sight (validated)
        # warm the chunk program (the queue below re-hits this exact shape)
        for b in batches[1:]:
            metric.update(*b)
        jax.block_until_ready(metric.metric_state)
        metric.reset()
        metric.update(*batches[0])
        jax.block_until_ready(metric.metric_state)
        engine.set_device_probe(probe_every)
        before = engine.engine_stats()["device_probes"]
        dispatch_spans_before = sum(
            1 for s in telemetry.spans() if s["site"] == "engine-dispatch"
        )
        for b in batches[1:]:
            metric.update(*b)  # 7 enqueues, zero dispatches
        assert engine.engine_stats()["device_probes"] == before, (
            "enqueues must not probe — nothing dispatched yet"
        )
        value = metric.compute()  # observation: the flush dispatches chunks
        engine.set_device_probe(0)
        probes = engine.engine_stats()["device_probes"] - before
        dispatches = (
            sum(1 for s in telemetry.spans() if s["site"] == "engine-dispatch")
            - dispatch_spans_before
        )
        return value, probes, dispatches

    unprobed_value, zero_probes, _ = run(0)
    assert zero_probes == 0
    probed_value, probes, dispatches = run(1)
    np.testing.assert_array_equal(np.asarray(unprobed_value), np.asarray(probed_value))
    assert probes >= 1, "a probed flush must land at least one device sample"
    # one probe per PROGRAM DISPatch in the flush (EVERY=1 probes each
    # non-compile dispatch; compile dispatches carry no probe), never one
    # per enqueued step
    assert probes <= dispatches + 1 < len(batches), (probes, dispatches)


def test_program_report_joins_probes_into_roofline():
    engine.set_deferred_dispatch(True)
    batches = [_batch() for _ in range(6)]
    _drive(mt.MeanMetric(), batches, 0)  # warmup: compiles
    _drive(mt.MeanMetric(), batches, 1)  # probed pass over cached programs
    rows = engine.program_report(analyze=True)
    probed = [r for r in rows if (r.get("device") or {}).get("count")]
    assert probed, "no ledger row carries a probed device block"
    for row in probed:
        rl = row["roofline"]
        assert rl["probes"] == row["device"]["count"]
        assert rl["bound"] in (
            "compute-bound", "memory-bound", "dispatch-bound", "host-bound"
        )
        assert rl["device_p50_s"] > 0
    # achieved FLOP/s nonzero wherever the cost analysis reports arithmetic
    for row in probed:
        flops = float((row.get("analysis") or {}).get("flops", 0.0) or 0.0)
        if flops > 0:
            assert row["roofline"]["achieved_flops_per_s"] > 0


def test_analysis_memoized_per_signature():
    """program_report(analyze=True) twice must lower each program at most
    once (the program_analyses counter counts actual lowers) — the roofline
    join stays cheap enough for perf_report() to call per invocation."""
    exe = engine.acquire_keyed(
        ("probe-memo-test",), lambda: (lambda s: s + 1, None, {}), donate=False
    )
    exe.run(jnp.zeros((), jnp.float32), donate=False)
    engine.program_report(analyze=True)
    analyses_after_first = engine.engine_stats()["program_analyses"]
    engine.program_report(analyze=True)
    engine.program_report(analyze=True)
    assert engine.engine_stats()["program_analyses"] == analyses_after_first
    # a NEW compiled signature invalidates the memo: exactly one more lower
    exe.run(jnp.zeros((2,), jnp.float32), donate=False)
    engine.program_report(analyze=True)
    assert engine.engine_stats()["program_analyses"] > analyses_after_first
