"""Performance attribution plane: the ISSUE-12 perf_report contracts.

Contracts (`metrics_tpu/ops/perf.py` + `ops/fleetobs.fleet_perf_report`):

- **Exclusive decomposition** — the interval nesting scan attributes every
  timed span exactly once: phase totals sum to the top-level span wall, a
  dispatch nested in a flush nested in a suite-step counts only under
  ``dispatch``, and a probed device span's excess over its host sibling is
  the ``device`` phase.
- **Reconciliation** — against an externally measured wall over a driven
  suite loop, coverage sits within the stated tolerance.
- **Sync decomposition** — pack/serialize/wire/unpack itemize the
  suite-sync span, with the wire block carrying gathered bytes and the
  effective bandwidth.
- **Opportunities** — ranked worst-first with per-phase evidence.
- **Fleet merge** — ``fleet_perf_report()`` at world size 1 serves the
  local report with ZERO collectives; the aggregate sums phase seconds
  exactly across hand-fed rank reports.
- **suite-step span** — every MetricCollection update/forward emits one.
"""
from __future__ import annotations

import time

import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu.ops import engine, fleetobs, perf, telemetry

RNG = np.random.RandomState(23)
DIST_ON = lambda: True  # noqa: E731


def _batch(n=32):
    return (
        jnp.asarray(RNG.rand(n).astype(np.float32)),
        jnp.asarray(RNG.randint(0, 2, n)),
    )


@pytest.fixture(autouse=True)
def _armed_and_clean():
    was = telemetry.armed
    telemetry.set_telemetry(True)
    telemetry.clear_spans()
    telemetry.reset_latency()
    yield
    engine.set_device_probe(None)
    telemetry.set_telemetry(was)
    telemetry.clear_spans()
    telemetry.reset_latency()


# ------------------------------------------------- the exclusive interval scan
def test_exclusive_spans_subtract_nested_children():
    rows = [
        {"site": "suite-step", "t_start": 0.0, "dur": 1.0, "attrs": None},
        {"site": "engine-flush", "t_start": 0.1, "dur": 0.6, "attrs": None},
        {"site": "engine-dispatch", "t_start": 0.2, "dur": 0.2, "attrs": None},
        {"site": "journal-save", "t_start": 2.0, "dur": 0.5, "attrs": None},
    ]
    recs = {r["site"]: r for r in perf._exclusive_spans(rows)}
    assert recs["suite-step"]["top"] and recs["suite-step"]["exclusive_s"] == pytest.approx(0.4)
    assert recs["engine-flush"]["parent"] == "suite-step"
    assert recs["engine-flush"]["exclusive_s"] == pytest.approx(0.4)
    assert recs["engine-dispatch"]["parent"] == "engine-flush"
    assert recs["engine-dispatch"]["exclusive_s"] == pytest.approx(0.2)
    assert recs["journal-save"]["top"] and recs["journal-save"]["exclusive_s"] == pytest.approx(0.5)
    # phase totals == top-level wall: nothing double-counted, nothing lost
    total = sum(r["exclusive_s"] for r in recs.values())
    assert total == pytest.approx(1.0 + 0.5)


def test_device_span_excess_over_host_sibling_is_device_phase():
    # a probed dispatch emits BOTH spans from the same t_start: the host
    # async wall (shorter) and the device-inclusive wall (longer); the
    # exclusive scan must make the host span the child of the device span
    rows = [
        {"site": "device-dispatch", "t_start": 0.0, "dur": 0.010, "attrs": None},
        {"site": "engine-dispatch", "t_start": 0.0, "dur": 0.002, "attrs": None},
    ]
    recs = {r["site"]: r for r in perf._exclusive_spans(rows)}
    assert recs["engine-dispatch"]["parent"] == "device-dispatch"
    assert recs["device-dispatch"]["exclusive_s"] == pytest.approx(0.008)
    assert recs["engine-dispatch"]["exclusive_s"] == pytest.approx(0.002)


# --------------------------------------------------------- the live report
def _drive_suite(steps=10):
    engine.set_deferred_dispatch(True)
    suite = mt.MetricCollection({"mean": mt.MeanMetric(), "acc": mt.Accuracy()})
    b = _batch()
    # warmup: two full cycles so the measured window is steady state
    for _ in range(2):
        for _ in range(steps):
            suite.update(*b)
        suite.sync(distributed_available=DIST_ON)
        suite.unsync()
    telemetry.clear_spans()
    t0 = time.perf_counter()
    for _ in range(steps):
        suite.update(*b)
    suite.sync(distributed_available=DIST_ON)
    suite.unsync()
    return suite, time.perf_counter() - t0


def test_perf_report_reconciles_against_measured_wall():
    engine.set_device_probe(1)
    _, wall = _drive_suite()
    report = mt.perf_report(measured_wall_s=wall)
    recon = report["reconciliation"]
    assert recon["within_tolerance"], recon
    assert recon["attributed_s"] <= recon["measured_wall_s"] * (1 + 1e-6)
    assert sorted(report["phases"]) == sorted(perf.PHASES)
    assert report["step"]["steps"] == 10
    assert report["device_probe"]["every"] == 1
    assert report["device_probe"]["probes"] > 0


def test_sync_decomposition_itemizes_the_suite_sync_span():
    _, _ = _drive_suite()
    report = mt.perf_report()
    sync = report["sync"]
    assert sync["syncs"] == 1
    assert sync["reconciliation"]["within_tolerance"], sync["reconciliation"]
    assert sync["phases"]["wire"] > 0 and sync["phases"]["pack"] > 0
    wire = sync["wire"]
    assert wire["bytes_gathered"] > 0
    assert wire["effective_bytes_per_s"] > 0
    assert 0.0 < wire["wire_share_of_sync"] <= 1.0


def test_opportunities_ranked_worst_first_with_evidence():
    _, _ = _drive_suite()
    report = mt.perf_report(top=4)
    opps = report["opportunities"]
    assert 1 <= len(opps) <= 4
    totals = [o["total_s"] for o in opps]
    assert totals == sorted(totals, reverse=True)
    for o in opps:
        assert o["phase"] in perf.PHASES
        assert o["evidence"] and isinstance(o["evidence"], str)
        assert 0.0 < o["share"] <= 1.0


def test_suite_step_span_emitted_per_update_and_forward():
    suite = mt.MetricCollection({"mean": mt.MeanMetric()})
    b = _batch()
    telemetry.clear_spans()
    suite.update(*b)
    suite(*b)
    apis = [
        (s["attrs"] or {}).get("api")
        for s in telemetry.spans()
        if s["site"] == "suite-step"
    ]
    assert apis.count("update") == 1 and apis.count("forward") == 1


def test_phase_columns_between_latency_snapshots():
    before = telemetry.latency_stats()
    _, _ = _drive_suite(steps=6)
    cols = perf.phase_columns(before, telemetry.latency_stats())
    assert cols.get("wire", 0) > 0 and cols.get("enqueue", 0) > 0
    # per-program device families are excluded (the aggregate site carries
    # them); every column is a known phase
    assert set(cols) <= set(perf.PHASES)


def test_perf_reports_counter_on_reset_registry():
    before = perf.perf_stats()["perf_reports"]
    mt.perf_report()
    assert perf.perf_stats()["perf_reports"] == before + 1
    engine.reset_stats()
    assert perf.perf_stats()["perf_reports"] == 0


# ------------------------------------------------------------- fleet merge
def test_fleet_perf_report_world_one_zero_collectives():
    from metrics_tpu.parallel import sync as psync

    _drive_suite(steps=4)
    gathers_before = fleetobs.fleet_stats()["fleet_gathers"]
    collectives_before = psync.collective_stats()["sync_collectives_issued"]
    report = mt.fleet_perf_report()
    assert report["gathered"] is False
    assert report["rank"] in report["reports"]
    assert fleetobs.fleet_stats()["fleet_gathers"] == gathers_before
    assert psync.collective_stats()["sync_collectives_issued"] == collectives_before
    # the local report travels whole: aggregate == the one rank's phases
    local = report["reports"][report["rank"]]
    for p, total in report["aggregate_phases"].items():
        assert total == pytest.approx(local["phases"][p]["total_s"], abs=1e-9)


def test_fleet_perf_report_merge_sums_phases_exactly(monkeypatch):
    import json as _json

    from metrics_tpu.parallel import sync as psync

    _drive_suite(steps=4)

    def fake_gather(blob, *, owner=None, site="fleet-gather"):
        doc = _json.loads(blob.decode("utf-8"))
        rows = [blob]
        for scale in (2.0, 3.0):
            d = _json.loads(blob.decode("utf-8"))
            for p in d["phases"]:
                d["phases"][p]["total_s"] = doc["phases"][p]["total_s"] * scale
            rows.append(_json.dumps(d).encode("utf-8"))
        rows.append(b"not json")  # a corrupt row must placeholder, not crash
        return rows

    monkeypatch.setattr(fleetobs, "_gather_blobs", fake_gather)
    psync.set_expected_world(4)
    try:
        report = mt.fleet_perf_report()
    finally:
        psync.reset_membership()
    assert report["gathered"] and report["world_size"] == 4
    assert report["reports"][3].get("corrupt") is True
    local = report["reports"][0]
    for p, total in report["aggregate_phases"].items():
        oracle = local["phases"][p]["total_s"] * (1.0 + 2.0 + 3.0)
        assert total == pytest.approx(oracle, rel=1e-6, abs=1e-9), p
    # the slowest rank per phase is the 3x clone wherever there is any time
    for p, row in report["slowest_rank_per_phase"].items():
        assert row["rank"] == 2, (p, row)
