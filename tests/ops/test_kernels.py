"""Device-kernel unit tests: fused histogram, binned-curve counts, segment ops.

The Pallas kernel itself is exercised in interpreter mode (runs on the CPU test
mesh, same lowering semantics); the XLA fallbacks are checked against numpy.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.ops import (
    binned_curve_counts,
    fused_bincount,
    segment_count,
    segment_cumsum,
    segment_max,
    segment_ranks,
    segment_starts,
    segment_sum,
)


def _pallas_interpret_bincount(x, weights, length):
    """Run the REAL production wrapper in interpreter mode on CPU."""
    from metrics_tpu.ops.histogram import _pallas_weighted_bincount

    return _pallas_weighted_bincount(
        jnp.asarray(x, jnp.int32), jnp.asarray(weights, jnp.float32), length, interpret=True
    )


class TestFusedBincount:
    @pytest.mark.parametrize("length", [7, 128, 1000])
    def test_matches_numpy(self, length):
        rng = np.random.RandomState(0)
        x = rng.randint(0, length, size=(4096,))
        expected = np.bincount(x, minlength=length)
        got = fused_bincount(jnp.asarray(x), length)
        np.testing.assert_array_equal(np.asarray(got), expected)

    def test_weighted(self):
        rng = np.random.RandomState(1)
        x = rng.randint(0, 50, size=(2000,))
        w = rng.rand(2000).astype(np.float32)
        expected = np.bincount(x, weights=w, minlength=50)
        got = fused_bincount(jnp.asarray(x), 50, weights=jnp.asarray(w))
        np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5, atol=1e-4)

    def test_out_of_range_ignored(self):
        x = jnp.asarray([-1, 0, 1, 5, 99])
        got = fused_bincount(x, 3)
        np.testing.assert_array_equal(np.asarray(got), [1, 1, 0])

    def test_jittable(self):
        x = jnp.asarray(np.random.RandomState(2).randint(0, 10, size=(512,)))
        got = jax.jit(lambda a: fused_bincount(a, 10))(x)
        np.testing.assert_array_equal(np.asarray(got), np.bincount(np.asarray(x), minlength=10))

    @pytest.mark.parametrize("n,length", [(600, 300), (2048, 1024), (513, 129)])
    def test_pallas_kernel_interpret(self, n, length):
        rng = np.random.RandomState(3)
        x = rng.randint(0, length, size=(n,))
        w = rng.rand(n).astype(np.float32)
        got = _pallas_interpret_bincount(jnp.asarray(x), jnp.asarray(w), length)
        expected = np.bincount(x, weights=w, minlength=length)
        np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5, atol=1e-4)

    def test_pallas_kernel_interpret_padding_sentinel(self):
        # padded tail (-1 ids, 0 weights) must not contribute to bin 0
        x = jnp.zeros((10,), jnp.int32)
        w = jnp.ones((10,), jnp.float32)
        got = _pallas_interpret_bincount(x, w, 256)
        assert float(got[0]) == 10.0
        assert float(got.sum()) == 10.0


class TestBinnedCurveCounts:
    @pytest.mark.parametrize("t", [5, 100])
    @pytest.mark.parametrize("c", [1, 4])
    def test_matches_broadcast(self, c, t):
        rng = np.random.RandomState(0)
        preds = rng.rand(256, c).astype(np.float32)
        target = (rng.rand(256, c) > 0.5).astype(np.float32)
        thr = np.linspace(0, 1, t).astype(np.float32)

        ge = (preds[:, :, None] >= thr[None, None, :]).astype(np.float32)
        tps_e = np.einsum("nc,nct->ct", target, ge)
        fps_e = np.einsum("nc,nct->ct", 1 - target, ge)
        fns_e = np.einsum("nc,nct->ct", target, 1 - ge)

        tps, fps, fns = binned_curve_counts(jnp.asarray(preds), jnp.asarray(target), jnp.asarray(thr))
        np.testing.assert_allclose(np.asarray(tps), tps_e, atol=1e-4)
        np.testing.assert_allclose(np.asarray(fps), fps_e, atol=1e-4)
        np.testing.assert_allclose(np.asarray(fns), fns_e, atol=1e-4)

    def test_unsorted_thresholds(self):
        rng = np.random.RandomState(1)
        preds = rng.rand(64, 2).astype(np.float32)
        target = (rng.rand(64, 2) > 0.3).astype(np.float32)
        thr = np.asarray([0.9, 0.1, 0.5], dtype=np.float32)
        ge = (preds[:, :, None] >= thr[None, None, :]).astype(np.float32)
        tps_e = np.einsum("nc,nct->ct", target, ge)
        tps, _, _ = binned_curve_counts(jnp.asarray(preds), jnp.asarray(target), jnp.asarray(thr))
        np.testing.assert_allclose(np.asarray(tps), tps_e, atol=1e-4)

    def test_exact_threshold_ties(self):
        # preds exactly equal to a threshold must count as >= (side="right")
        preds = jnp.asarray([[0.5], [0.5], [0.2]])
        target = jnp.asarray([[1.0], [0.0], [1.0]])
        thr = jnp.asarray([0.2, 0.5, 0.8])
        tps, fps, fns = binned_curve_counts(preds, target, thr)
        np.testing.assert_allclose(np.asarray(tps[0]), [2.0, 1.0, 0.0])
        np.testing.assert_allclose(np.asarray(fps[0]), [1.0, 1.0, 0.0])
        np.testing.assert_allclose(np.asarray(fns[0]), [0.0, 1.0, 2.0])


class TestSegmentOps:
    def _ids(self):
        return jnp.asarray([0, 0, 0, 1, 1, 3, 3, 3, 3], dtype=jnp.int32), 4

    def test_count_starts_ranks(self):
        ids, n = self._ids()
        np.testing.assert_array_equal(np.asarray(segment_count(ids, n)), [3, 2, 0, 4])
        np.testing.assert_array_equal(np.asarray(segment_starts(ids, n)), [0, 3, 5, 5])
        np.testing.assert_array_equal(np.asarray(segment_ranks(ids, n)), [1, 2, 3, 1, 2, 1, 2, 3, 4])

    def test_cumsum(self):
        ids, n = self._ids()
        data = jnp.asarray([1.0, 2, 3, 4, 5, 6, 7, 8, 9])
        got = segment_cumsum(data, ids, n)
        np.testing.assert_allclose(np.asarray(got), [1, 3, 6, 4, 9, 6, 13, 21, 30])

    def test_sum_max(self):
        ids, n = self._ids()
        data = jnp.asarray([1.0, 2, 3, 4, 5, 6, 7, 8, 9])
        np.testing.assert_allclose(np.asarray(segment_sum(data, ids, n)), [6, 9, 0, 30])
        got_max = np.asarray(segment_max(data, ids, n))
        np.testing.assert_allclose(got_max[[0, 1, 3]], [3, 5, 9])

    def test_cumsum_empty(self):
        got = segment_cumsum(jnp.zeros((0,)), jnp.zeros((0,), jnp.int32), 0)
        assert got.shape == (0,)

    @pytest.mark.slow
    def test_cumsum_no_cancellation_after_huge_group(self):
        # a tiny group following a 2M-row group must not inherit float32
        # rounding from the global prefix (segmented scan, not cumsum-minus-offset)
        rng = np.random.RandomState(0)
        big = rng.rand(2_000_000).astype(np.float32)
        small = rng.rand(10).astype(np.float32)
        data = jnp.asarray(np.concatenate([big, small]))
        ids = jnp.asarray(np.concatenate([np.zeros(big.size), np.ones(small.size)]).astype(np.int32))
        got = np.asarray(segment_cumsum(data, ids, 2))[-small.size:]
        np.testing.assert_allclose(got, np.cumsum(small), rtol=1e-6)
