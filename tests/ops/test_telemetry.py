"""Flight recorder + program ledger: the ISSUE-7 observability contracts.

Contracts (`metrics_tpu/ops/telemetry.py`, `engine.program_report`):

- **Span emission at every instrumented boundary** — the deferred engine
  path (enqueue/flush/build/compile), the coalesced sync faces
  (pack/payload-gather/unpack under the suite-sync parent), the fault lane
  (an injected demotion at ``sync-pack`` must produce a matching
  ``ladder-demote`` span), and the journal (save/load/demote) — every site
  drawn from the documented :data:`telemetry.SPAN_SITES` table, every span
  stamped with the same monotonic step index as the ``failure_log``.
- **Export round-trip** — ``engine.export_trace`` writes valid Chrome-trace
  JSON (monotonic timestamps, well-formed events, per-owner tracks, the
  program ledger joined) that passes ``tools/trace_report.py``'s validator.
- **Snapshot schema stability** — ``telemetry_snapshot()`` is a strict key
  superset of ``engine_stats()``, key-stable call-over-call, and its
  Prometheus rendering is well-formed.
- **Disarmed is free** — with the recorder off the ring records nothing and
  allocates nothing.
- **One reset registry** — ``engine.reset_stats()`` zeroes engine, sync,
  fault, journal AND span counters in one walk (monotonic step preserved);
  ``reset_stats(reset_warnings=True)`` is the explicit opt-in that lets
  ``faults.warn_fault`` warn again.
"""
from __future__ import annotations

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu.ops import engine, faults, telemetry

_REPO_DIR = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if _REPO_DIR not in sys.path:
    sys.path.insert(0, _REPO_DIR)

from tools.trace_report import check_trace  # noqa: E402

RNG = np.random.RandomState(3)
DIST_ON = lambda: True  # noqa: E731


def _batch(n=32):
    return (
        jnp.asarray(RNG.rand(n).astype(np.float32)),
        jnp.asarray(RNG.randint(0, 2, n)),
    )


def _suite():
    s = mt.MetricCollection(
        {
            "mean": mt.MeanMetric(),
            "mse": mt.MeanSquaredError(),
            "mae": mt.MeanAbsoluteError(),
            "acc": mt.Accuracy(),
        }
    )
    s.update(*_batch())
    return s


@pytest.fixture(autouse=True)
def _armed_and_clean():
    """Every test starts armed with an empty ring, a zeroed latency plane
    (full-lifetime in production, isolated per test here) and leaves the
    recorder in its default state."""
    was = telemetry.armed
    telemetry.set_telemetry(True)
    telemetry.clear_spans()
    telemetry.reset_latency()
    yield
    telemetry.set_telemetry(was)
    telemetry.clear_spans()
    telemetry.reset_latency()


def _sites():
    return [s["site"] for s in telemetry.spans()]


# ------------------------------------------------------------- span emission
def test_every_emitted_site_is_documented():
    suite = _suite()
    for _ in range(4):
        suite.update(*_batch())
    suite.sync(distributed_available=DIST_ON)
    suite.unsync()
    suite.compute()
    emitted = set(_sites())
    assert emitted, "an armed recorder saw no spans from a full suite cycle"
    undocumented = emitted - set(telemetry.SPAN_SITES)
    assert not undocumented, f"sites missing from the SPAN_SITES table: {undocumented}"


def test_deferred_engine_spans():
    m = mt.Accuracy()
    p, t = _batch()
    m(p, t)  # eager validation call
    telemetry.clear_spans()
    for _ in range(6):
        m(p, t)  # enqueue
    jax.block_until_ready(m.correct)  # observation: flush
    sites = _sites()
    assert sites.count("engine-enqueue") == 6
    flushes = [s for s in telemetry.spans() if s["site"] == "engine-flush"]
    assert len(flushes) == 1 and flushes[0]["attrs"]["entries"] == 6
    assert flushes[0]["dur"] > 0
    # the flush either compiled (first bucket) or dispatched cached programs
    assert any(s in ("engine-compile", "engine-dispatch") for s in sites)


def test_host_fast_lane_span():
    m = mt.CatMetric()
    x = jnp.asarray(RNG.rand(8).astype(np.float32))
    m.update(x)  # first call installs the lane
    telemetry.clear_spans()
    m.update(x)
    assert "host-lane" in _sites()


def test_sync_spans_nest_and_agree_with_counters():
    suite = _suite()
    telemetry.clear_spans()
    s0 = engine.engine_stats()
    suite.sync(distributed_available=DIST_ON)
    suite.unsync()
    s1 = engine.engine_stats()
    spans = telemetry.spans()
    by_site = {s["site"]: s for s in spans}
    for site in ("suite-sync", "sync-pack", "sync-payload-gather", "sync-unpack"):
        assert site in by_site, f"coalesced suite sync emitted no {site} span"
        assert by_site[site]["dur"] > 0
    # the payload span's bytes must agree exactly with the gathered-bytes
    # counter for the same window (the certification pins the full equality)
    payload = [s for s in spans if s["site"] == "sync-payload-gather"]
    assert sum(s["attrs"]["bytes"] for s in payload) == (
        s1["sync_bytes_gathered"] - s0["sync_bytes_gathered"]
    )
    assert len(payload) == s1["sync_payload_collectives"] - s0["sync_payload_collectives"]
    # child spans nest inside the suite-sync parent slice on the timeline
    parent = by_site["suite-sync"]
    child = by_site["sync-payload-gather"]
    assert parent["t_start"] <= child["t_start"]
    assert child["t_start"] + child["dur"] <= parent["t_start"] + parent["dur"] + 1e-6


def test_injected_demotion_produces_matching_span():
    suite = _suite()
    telemetry.clear_spans()
    with pytest.warns(UserWarning, match="Coalesced suite sync failed"):
        with faults.inject_faults("sync-pack") as plan:
            suite.sync(distributed_available=DIST_ON)
            suite.unsync()
    assert plan.fired == 1
    demotes = [s for s in telemetry.spans() if s["site"] == "ladder-demote"]
    assert [d["lane"] for d in demotes] == ["sync-pack"], demotes
    assert demotes[0]["attrs"]["domain"] == "runtime"
    # the classified fault itself is marked too, with the same step index
    # stamped on the failure_log entry it mirrors
    fault_spans = [s for s in telemetry.spans() if s["site"] == "fault"]
    assert fault_spans and fault_spans[0]["lane"] == "runtime"
    log_steps = {e["step"] for e in engine.engine_stats()["failure_log"]}
    assert fault_spans[-1]["step"] in log_steps


def test_journal_spans_and_counters(tmp_path):
    path = str(tmp_path / "suite.journal")
    suite = _suite()
    s0 = engine.engine_stats()
    telemetry.clear_spans()
    nbytes = suite.save_state(path)
    restored = _suite()
    restored.load_state(path)
    spans = {s["site"]: s for s in telemetry.spans()}
    assert spans["journal-save"]["attrs"]["bytes"] == nbytes
    assert spans["journal-load"]["attrs"]["generation"] == 0
    s1 = engine.engine_stats()
    assert s1["journal_saves"] - s0["journal_saves"] == 1
    assert s1["journal_loads"] - s0["journal_loads"] == 1
    assert s1["journal_bytes_written"] - s0["journal_bytes_written"] == nbytes
    # corrupt the newest generation: the load demotes with an instant mark
    suite.save_state(path)  # gen1 = the good record
    with open(path, "r+b") as fh:
        fh.seek(30)
        byte = fh.read(1)
        fh.seek(30)
        fh.write(bytes([byte[0] ^ 0xFF]))
    telemetry.clear_spans()
    fresh = _suite()
    with pytest.warns(UserWarning, match="failed verification"):
        assert fresh.load_state(path) == 1
    sites = _sites()
    assert "journal-demote" in sites and "journal-load" in sites
    assert engine.engine_stats()["journal_load_demotions"] >= 1


# ------------------------------------------------------------ export + faces
def test_export_trace_round_trip(tmp_path):
    suite = _suite()
    for _ in range(3):
        suite.update(*_batch())
    suite.sync(distributed_available=DIST_ON)
    suite.unsync()
    suite.compute()
    path = str(tmp_path / "trace.json")
    n = engine.export_trace(path)
    assert n > 0
    with open(path) as fh:
        doc = json.load(fh)
    problems = check_trace(doc)
    assert not problems, problems
    events = [e for e in doc["traceEvents"] if e["ph"] != "M"]
    # monotonic timestamps (Perfetto renders any order; we pin sorted output)
    ts = [e["ts"] for e in events]
    assert ts == sorted(ts)
    assert all(e.get("dur", 0) >= 0 for e in events)
    # per-owner tracks carry thread_name metadata
    names = {
        e["args"]["name"] for e in doc["traceEvents"] if e["ph"] == "M" and e["name"] == "thread_name"
    }
    assert "MetricCollection" in names
    # both event flavors present: slices and instant marks
    assert {e["ph"] for e in events} >= {"X", "i"}
    # the program ledger rides along
    assert isinstance(doc["programLedger"], list) and doc["programLedger"]
    assert all("kind" in row for row in doc["programLedger"])
    assert isinstance(doc["snapshot"], dict)


def test_snapshot_schema_superset_and_stable():
    suite = _suite()
    suite.compute()
    es = engine.engine_stats()
    snap = mt.telemetry_snapshot()
    missing = set(es) - set(snap)
    assert not missing, f"snapshot dropped engine_stats keys: {missing}"
    for key in (
        "snapshot_schema",
        "telemetry_armed",
        "spans_recorded",
        "spans_dropped",
        "span_ring_cap",
        "monotonic_step",
        "programs",
        "sync_health",
        "sync_phase_stats",
        "latency_stats",
        "slo_violations",
    ):
        assert key in snap, f"snapshot is missing its own {key!r}"
    assert snap["snapshot_schema"] == 1
    assert set(snap) == set(mt.telemetry_snapshot()), "snapshot keys drift call-over-call"
    progs = snap["programs"]
    assert set(progs) == {
        "count",
        "compiles",
        "compile_time_s",
        "cache_load_time_s",
        "hits",
        "donated_runs",
        "plain_runs",
    }
    health = snap["sync_health"]
    assert set(health) == {
        "monotonic_step",
        "degraded",
        "epoch",
        "dead_ranks",
        "consecutive_timeouts",
        "last_good_sync_step",
        "sync_degraded_serves",
        "sync_quorum_serves",
        "sync_deadline_timeouts",
        "slo_violations",
        "fault_domain_counts",
        # the in-flight async-sync block (ISSUE 13): count + oldest future
        # age/dispatch-epoch gauges from the SyncFuture registry
        "inflight",
        "transitions",
    }
    assert set(health["inflight"]) == {"count", "oldest_age_steps", "oldest_dispatch_epoch"}
    # the per-phase sync span statistics (the fleet straggler input) cover
    # every documented phase, schema-stable
    stats = snap["sync_phase_stats"]
    assert set(stats) == set(telemetry.SYNC_PHASE_SITES)
    for block in stats.values():
        assert set(block) == {"count", "total_s", "mean_s", "max_s"}


def test_prometheus_text_well_formed():
    _suite().compute()
    text = mt.prometheus_text()
    lines = [ln for ln in text.strip().splitlines() if ln]
    assert lines and lines[0].startswith("# TYPE metrics_tpu_")
    family_name, family_kind, family_samples = None, None, 0
    for line in lines:
        if line.startswith("# TYPE "):
            if family_name is not None:
                assert family_samples >= 1, f"family {family_name} has no samples"
            _, _, family_name, family_kind = line.split(" ")
            assert family_kind in ("counter", "gauge", "histogram")
            family_samples = 0
        else:
            name, value = line.rsplit(" ", 1)
            base = name.split("{", 1)[0]
            # histogram families carry _bucket/_sum/_count suffixed samples
            assert base == family_name or (
                family_kind == "histogram"
                and base in (f"{family_name}_bucket", f"{family_name}_sum", f"{family_name}_count")
            ), f"sample {name} outside its family {family_name}"
            # scalar counter/gauge families carry exactly one unlabelled
            # sample; labelled families (histogram + site-labelled gauges)
            # may carry many
            if "{" not in name:
                assert family_samples == 0, f"unlabelled family {family_name} has >1 sample"
            float(value)  # parses
            family_samples += 1
    assert family_samples >= 1
    # the headline counters are scrapeable
    assert "metrics_tpu_sync_payload_collectives" in text
    assert "metrics_tpu_programs_count" in text
    # a recomputed ratio must scrape as a gauge, never a counter
    assert "# TYPE metrics_tpu_sync_coalesce_ratio gauge" in text
    # integers render exactly — '%g'-style 6-sig-digit rounding would scrape
    # a multi-MiB byte counter off by thousands
    big = mt.prometheus_text({"sync_bytes_gathered": 16777217})
    assert "metrics_tpu_sync_bytes_gathered 16777217" in big.splitlines()[-1]


def test_prometheus_exports_sync_health_as_typed_gauges():
    """The one monitoring surface must actually export HEALTH, not just raw
    event counters: the flattened sync_health block (degraded flag, epoch,
    last-good sync step, per-domain fault counts) scrapes as typed GAUGES —
    state that can fall must never carry counter semantics."""
    from metrics_tpu.ops import faults
    from metrics_tpu.parallel import sync as psync

    faults.note_fault("sync", site="sync-gather")
    text = mt.prometheus_text()
    for gauge in (
        "metrics_tpu_sync_health_degraded",
        "metrics_tpu_sync_health_epoch",
        "metrics_tpu_sync_health_dead_ranks",
        "metrics_tpu_sync_health_consecutive_timeouts",
        "metrics_tpu_sync_health_last_good_sync_step",
        "metrics_tpu_sync_health_fault_domain_counts_sync",
    ):
        assert f"# TYPE {gauge} gauge" in text, f"{gauge} missing or mistyped"
    # the epoch gauge tracks the live registry
    line = next(ln for ln in text.splitlines() if ln.startswith("metrics_tpu_sync_health_epoch "))
    assert int(float(line.split()[1])) == psync.world_epoch()
    # never-synced renders the -1 sentinel rather than dropping the sample
    snap = mt.telemetry_snapshot()
    assert isinstance(snap["sync_health"]["last_good_sync_step"], int)
    # membership event counters (outside the health block) stay counters
    assert "# TYPE metrics_tpu_sync_epoch_bumps counter" in text
    assert "# TYPE metrics_tpu_sync_quorum_serves counter" in text


def test_program_report_ledger():
    engine.reset_stats()
    m1 = mt.Accuracy()
    p, t = _batch()
    for _ in range(4):
        m1(p, t)
    jax.block_until_ready(m1.correct)
    m2 = mt.Accuracy()  # same config: cache hit, zero new compiles
    m2(p, t)
    report = engine.program_report()
    assert report
    # the deferred forward flush runs the SAME "many" scan programs
    # forward_many compiles (shared engine keys — the PR-2 contract)
    many = [r for r in report if r["kind"] == "many" and r["compiles"] >= 1]
    assert many, f"ledger missing the many/flush program: {[r['kind'] for r in report]}"
    row = many[0]
    assert row["compiles"] >= 1 and row["compile_time_s"] > 0
    assert row["donated_runs"] + row["plain_runs"] >= 1
    a = row["analysis"]
    assert a is not None and a["bytes_accessed"] > 0 and a["peak_bytes"] > 0
    # counters-only report skips the AOT analysis entirely
    assert all(r["analysis"] is None for r in engine.program_report(analyze=False))
    summary = engine.program_summary()
    assert summary["count"] == len(report) == engine.engine_stats()["cached"]
    assert summary["compiles"] == sum(r["compiles"] for r in report)


# ------------------------------------------------------------- disarmed path
def test_disarmed_emits_nothing_and_allocates_nothing(tmp_path):
    suite = _suite()  # constructed armed: its first update emits (suite-step)
    telemetry.clear_spans()
    telemetry.set_telemetry(False)
    telemetry.reset_latency()
    before = telemetry.telemetry_stats()
    probes_before = engine.engine_stats()["device_probes"]
    ring_id = id(telemetry._ring)
    # the histogram plane too: same preallocated dict object, same site
    # count, same (all-zero) per-site counts lists after the loop
    hists_id = id(telemetry._site_hists)
    n_sites = len(telemetry._site_hists)
    # device probes ride the ARMED dispatch branch: with the recorder off,
    # even an aggressive EVERY=1 must neither block nor count nor allocate
    engine.set_device_probe(1)
    try:
        for _ in range(4):
            suite.update(*_batch())
        suite.sync(distributed_available=DIST_ON)
        suite.unsync()
        suite.compute()
        suite.save_state(str(tmp_path / "j"))
    finally:
        engine.set_device_probe(None)
    after = telemetry.telemetry_stats()
    assert after["spans_recorded"] == before["spans_recorded"]
    assert after["spans_retained"] == before["spans_retained"] == 0
    assert id(telemetry._ring) == ring_id  # no reallocation either
    assert after["telemetry_armed"] is False
    assert telemetry.latency_stats() == {}, "a disarmed recorder fed the histograms"
    assert id(telemetry._site_hists) == hists_id and len(telemetry._site_hists) == n_sites
    assert engine.engine_stats()["device_probes"] == probes_before
    assert telemetry.device_dispatch_stats() == {}


def test_span_ring_bounded():
    telemetry.set_telemetry(True, span_cap=32)
    try:
        for i in range(100):
            telemetry.emit("engine-enqueue", None, "defer")
        stats = telemetry.telemetry_stats()
        assert stats["spans_retained"] == 32
        assert stats["spans_recorded"] == 100
        assert stats["spans_dropped"] == 68
    finally:
        telemetry.set_telemetry(True, span_cap=4096)


def test_span_ring_overflow_warns_exactly_once():
    """No-silent-caps: the first dropped span warns (via faults.warn_fault),
    later drops stay silent, a plain counter reset does NOT resurrect the
    warning, and reset_stats(reset_warnings=True) is the explicit opt-in
    that lets the next overflow warn again."""
    import warnings as _warnings

    telemetry.set_telemetry(True, span_cap=32)
    engine.reset_stats(reset_warnings=True)  # an earlier test may have overflowed
    try:
        with pytest.warns(UserWarning, match="span ring overflowed"):
            for _ in range(40):
                telemetry.emit("engine-enqueue", None, "defer")
        # exactly once: further drops are silent
        with _warnings.catch_warnings():
            _warnings.simplefilter("error")
            for _ in range(10):
                telemetry.emit("engine-enqueue", None, "defer")
        # a plain counter reset clears the ring but must NOT re-warn
        engine.reset_stats()
        with _warnings.catch_warnings():
            _warnings.simplefilter("error")
            for _ in range(40):
                telemetry.emit("engine-enqueue", None, "defer")
        # the explicit opt-in re-arms the warning
        engine.reset_stats(reset_warnings=True)
        with pytest.warns(UserWarning, match="span ring overflowed"):
            for _ in range(40):
                telemetry.emit("engine-enqueue", None, "defer")
    finally:
        telemetry.set_telemetry(True, span_cap=4096)


def test_snapshot_sync_phase_stats_reduce_the_ring():
    suite = _suite()
    telemetry.clear_spans()
    suite.sync(distributed_available=DIST_ON)
    suite.unsync()
    stats = mt.telemetry_snapshot()["sync_phase_stats"]
    for site in ("sync-pack", "sync-payload-gather", "sync-unpack", "suite-sync"):
        block = stats[site]
        assert block["count"] >= 1, f"{site} saw no spans"
        assert block["mean_s"] > 0 and block["max_s"] >= block["mean_s"]
        assert block["total_s"] >= block["max_s"]
    # phases with no retained spans report zeros, not missing keys (a
    # static-fast-lane single-process sync exchanges no metadata)
    assert stats["sync-metadata"] == {"count": 0, "total_s": 0.0, "mean_s": 0.0, "max_s": 0.0}


def test_sync_health_carries_bounded_transition_log():
    from metrics_tpu.parallel import sync as psync

    before = len(mt.telemetry_snapshot()["sync_health"]["transitions"])
    epoch = psync.bump_epoch("test-transition")
    trans = mt.telemetry_snapshot()["sync_health"]["transitions"]
    assert len(trans) <= 32
    assert len(trans) >= min(32, before + 1)
    last = trans[-1]
    assert last["epoch"] == epoch == psync.world_epoch()
    assert last["reason"] == "test-transition"
    # ordered on the shared monotonic step axis, so membership events sort
    # against spans and failure_log entries without a second clock
    assert last["step"] <= faults.current_step()


# ------------------------------------------------------------- reset registry
def test_reset_stats_unifies_every_counter_plane(tmp_path):
    suite = _suite()
    for _ in range(3):
        suite.update(*_batch())
    suite.sync(distributed_available=DIST_ON)
    suite.unsync()
    suite.save_state(str(tmp_path / "j"))
    with pytest.warns(UserWarning):
        with faults.inject_faults("sync-pack"):
            suite.sync(distributed_available=DIST_ON)
            suite.unsync()
    stats = engine.engine_stats()
    assert stats["deferred_steps"] > 0
    assert stats["sync_payload_collectives"] > 0
    assert stats["fault_runtime"] > 0 and stats["failure_log"]
    assert stats["journal_saves"] > 0
    assert telemetry.telemetry_stats()["spans_recorded"] > 0
    step_before = faults.current_step()
    cached_before = stats["cached"]
    ladders_before = dict(suite.__dict__["_fault_ladders"])

    engine.reset_stats()

    after = engine.engine_stats()
    for key, value in after.items():
        if key == "failure_log":
            assert value == []
        elif key == "cached":
            assert value == cached_before  # programs survive
        elif isinstance(value, bool):
            continue
        elif isinstance(value, (int, float)):
            assert value == 0, f"{key} survived reset_stats: {value}"
    assert telemetry.telemetry_stats()["spans_recorded"] == 0
    assert telemetry.telemetry_stats()["spans_retained"] == 0
    # the never-resetting monotonic step and per-owner ladder state persist
    assert faults.current_step() == step_before
    assert suite.__dict__["_fault_ladders"] == ladders_before


# ------------------------------------------------- latency histogram plane
def test_latency_plane_is_full_lifetime_not_ring_windowed():
    """The ring drops old spans; the histogram plane NEVER does — 100 timed
    spans through a 32-slot ring keep exact count/sum/buckets."""
    import warnings as _warnings

    telemetry.set_telemetry(True, span_cap=32)
    try:
        with _warnings.catch_warnings():
            _warnings.simplefilter("ignore")  # the ring-overflow warn-once
            for _ in range(100):
                telemetry.emit("suite-sync", None, "sync", telemetry.now(), 0.002)
        assert telemetry.telemetry_stats()["spans_retained"] == 32
        block = telemetry.latency_stats()["suite-sync"]
        assert block["count"] == 100
        assert block["buckets"]["0.002048"] == 100
        assert sum(block["buckets"].values()) == block["count"]
        assert block["sum_s"] == pytest.approx(0.2)
        assert block["max_s"] == pytest.approx(0.002)
        assert 0 < block["p50_s"] <= block["p95_s"] <= block["p99_s"] <= block["max_s"]
        # the windowed view decayed; the full-lifetime one did not
        assert mt.telemetry_snapshot()["sync_phase_stats"]["suite-sync"]["count"] == 32
    finally:
        telemetry.set_telemetry(True, span_cap=4096)


def test_latency_percentiles_interpolate_within_their_bucket():
    """A bimodal 90/10 distribution: p50 must land in the 1 ms bucket, p95/
    p99 in the 100 ms bucket — each within its log2 bucket's bounds (the
    documented <=2x resolution), clamped to the exact observed max."""
    for _ in range(90):
        telemetry.emit("suite-sync", None, "sync", telemetry.now(), 0.001)
    for _ in range(10):
        telemetry.emit("suite-sync", None, "sync", telemetry.now(), 0.1)
    block = telemetry.latency_stats()["suite-sync"]
    assert block["count"] == 100
    assert block["buckets"]["0.001024"] == 90 and block["buckets"]["0.131072"] == 10
    assert 0.000512 < block["p50_s"] <= 0.001024
    assert 0.065536 < block["p95_s"] <= 0.1  # clamped to the observed max
    assert 0.065536 < block["p99_s"] <= 0.1
    assert block["max_s"] == pytest.approx(0.1)


def test_histogram_exposition_conformance():
    """The le-labelled histogram families pass the shared --check validator:
    cumulative buckets non-decreasing, ending at +Inf == _count, _sum
    consistent — and the flattened histogram SAMPLE keys never leak into the
    scalar exposition beside them."""
    from tools.trace_report import check_histogram_exposition

    suite = _suite()
    suite.sync(distributed_available=DIST_ON)
    suite.unsync()
    suite.compute()
    text = mt.prometheus_text()
    assert check_histogram_exposition(text) == []
    assert "# TYPE metrics_tpu_latency_seconds histogram" in text
    # manual spot check on one site: cumulative ordering and the +Inf==count
    site_lines = [
        ln for ln in text.splitlines()
        if ln.startswith('metrics_tpu_latency_seconds_bucket{site="suite-sync"')
    ]
    cums = [int(ln.rsplit(" ", 1)[1]) for ln in site_lines]
    assert cums and all(b >= a for a, b in zip(cums, cums[1:]))
    assert 'le="+Inf"' in site_lines[-1]
    count_line = next(
        ln for ln in text.splitlines()
        if ln.startswith('metrics_tpu_latency_seconds_count{site="suite-sync"}')
    )
    assert int(count_line.rsplit(" ", 1)[1]) == cums[-1]
    # percentile gauges render per site; the flat scalar plane must NOT
    # carry the histogram samples a second time
    assert 'metrics_tpu_latency_seconds_p99{site="suite-sync"}' in text
    assert "metrics_tpu_latency_stats_" not in text
    # every flattened histogram sample classifies as BOTH a counter (the
    # fleet merge sums it) and a histogram sample (the exposition hides it)
    key = "latency_stats_suite-sync_buckets_+Inf"
    assert telemetry.is_counter_key(key) and telemetry.is_histogram_sample_key(key)
    assert not telemetry.is_histogram_sample_key("latency_stats_suite-sync_p99_s")


def test_snapshot_latency_stats_round_trip_check(tmp_path):
    """The exported trace's embedded latency plane passes check_trace's
    histogram well-formedness validation, and a corrupted plane fails it."""
    suite = _suite()
    suite.sync(distributed_available=DIST_ON)
    suite.unsync()
    path = str(tmp_path / "trace.json")
    engine.export_trace(path)
    with open(path) as fh:
        doc = json.load(fh)
    assert check_trace(doc) == []
    # corrupt one bucket: count no longer equals the bucket total
    site = next(iter(doc["snapshot"]["latency_stats"]))
    doc["snapshot"]["latency_stats"][site]["count"] += 1
    assert any("bucket total" in p for p in check_trace(doc))


# ----------------------------------------------------------------- SLO budgets
def test_slo_budget_counts_violations_and_warns_once(monkeypatch):
    import warnings as _warnings

    monkeypatch.setenv("METRICS_TPU_SLO_SUITE_SYNC_MS", "1")
    telemetry.reset_latency()  # drop cached budgets: re-read the env
    engine.reset_stats(reset_warnings=True)
    assert telemetry.slo_limit_s("suite-sync") == pytest.approx(0.001)
    with pytest.warns(UserWarning, match="suite-sync span ran"):
        telemetry.emit("suite-sync", None, "sync", telemetry.now(), 0.05)
    # warn-once per owner+phase: the second violation counts silently
    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        telemetry.emit("suite-sync", None, "sync", telemetry.now(), 0.05)
    v = telemetry.slo_violations()
    assert v["suite-sync"] == 2 and v["total"] == 2
    # a within-budget span does not count
    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        telemetry.emit("suite-sync", None, "sync", telemetry.now(), 0.0005)
    assert telemetry.slo_violations()["suite-sync"] == 2
    # surfaced in the snapshot (health state + counter family) and scrape
    snap = mt.telemetry_snapshot()
    assert snap["sync_health"]["slo_violations"] == 2
    assert snap["slo_violations"]["suite-sync"] == 2
    text = mt.prometheus_text()
    assert "# TYPE metrics_tpu_slo_violations_total counter" in text
    assert "# TYPE metrics_tpu_sync_health_slo_violations gauge" in text


def test_slo_reset_rereads_environment_and_rearms_warning(monkeypatch):
    monkeypatch.setenv("METRICS_TPU_SLO_SUITE_SYNC_MS", "1")
    telemetry.reset_latency()
    engine.reset_stats(reset_warnings=True)
    with pytest.warns(UserWarning, match="budget"):
        telemetry.emit("suite-sync", None, "sync", telemetry.now(), 0.05)
    assert telemetry.slo_violations()["total"] == 1
    # a plain counter reset zeroes the counts AND drops the cached budget,
    # so a redeploy's new environment is honored...
    monkeypatch.delenv("METRICS_TPU_SLO_SUITE_SYNC_MS")
    engine.reset_stats()
    assert telemetry.slo_violations() == {"total": 0}
    telemetry.emit("suite-sync", None, "sync", telemetry.now(), 0.05)
    assert telemetry.slo_violations() == {"total": 0}  # budget now OFF
    # ...but does NOT resurrect the warning; reset_warnings=True does
    monkeypatch.setenv("METRICS_TPU_SLO_SUITE_SYNC_MS", "1")
    engine.reset_stats()
    import warnings as _warnings

    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        telemetry.emit("suite-sync", None, "sync", telemetry.now(), 0.05)
    assert telemetry.slo_violations()["total"] == 1
    engine.reset_stats(reset_warnings=True)
    with pytest.warns(UserWarning, match="budget"):
        telemetry.emit("suite-sync", None, "sync", telemetry.now(), 0.05)


def test_slo_unparseable_env_warns_once_naming_value(monkeypatch):
    monkeypatch.setenv("METRICS_TPU_SLO_SUITE_SYNC_MS", "not-a-number")
    telemetry.reset_latency()
    engine.reset_stats(reset_warnings=True)
    with pytest.warns(UserWarning, match="not-a-number"):
        assert telemetry.slo_limit_s("suite-sync") is None
    # the budget stays OFF: violations never count
    telemetry.emit("suite-sync", None, "sync", telemetry.now(), 10.0)
    assert telemetry.slo_violations() == {"total": 0}


# ------------------------------------------------------------ env-knob parses
def test_span_cap_garbage_env_warns_once_naming_value(monkeypatch):
    """The satellite contract: a garbage METRICS_TPU_TELEMETRY_SPANS no
    longer falls back SILENTLY — the queued import-time warning drains at
    the first cold surface, naming the offending value, once."""
    import warnings as _warnings

    monkeypatch.setenv("METRICS_TPU_TELEMETRY_SPANS", "a-lot")
    engine.reset_stats(reset_warnings=True)
    assert telemetry._env_cap() == telemetry._DEFAULT_CAP
    with pytest.warns(UserWarning, match="a-lot"):
        mt.telemetry_snapshot()
    # drained: the next snapshot is silent
    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        mt.telemetry_snapshot()
    # unset/blank stays the silent default
    monkeypatch.setenv("METRICS_TPU_TELEMETRY_SPANS", "")
    assert telemetry._env_cap() == telemetry._DEFAULT_CAP
    monkeypatch.delenv("METRICS_TPU_TELEMETRY_SPANS")
    assert telemetry._env_cap() == telemetry._DEFAULT_CAP


def test_reset_warnings_is_an_explicit_optin():
    class Owner:
        pass

    owner = Owner()
    with pytest.warns(UserWarning, match="boom"):
        assert faults.warn_fault(owner, "runtime", "boom")
    # deduped, and a plain counter reset must NOT resurrect the warning
    assert not faults.warn_fault(owner, "runtime", "boom")
    engine.reset_stats()
    assert not faults.warn_fault(owner, "runtime", "boom")
    # the opt-in clears the dedupe markers so sweeps re-observe warnings
    engine.reset_stats(reset_warnings=True)
    with pytest.warns(UserWarning, match="boom"):
        assert faults.warn_fault(owner, "runtime", "boom")
