"""Differential tests for the static-shape exact AUROC/AP kernels.

The kernels (ops/sorted_curves.py) must match sklearn exactly — including on
heavily tied scores, where the midrank / tie-group collapse math is the whole
point — and must produce identical values traced vs eager, single-device vs
SPMD-sharded.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from sklearn.metrics import average_precision_score, roc_auc_score

import metrics_tpu as mt
from metrics_tpu.functional import auroc, average_precision
from metrics_tpu.ops.sorted_curves import (
    binary_auroc_sorted,
    binary_average_precision_sorted,
    midranks,
    multiclass_auroc_sorted,
    multiclass_average_precision_sorted,
)

NUM_CLASSES = 5


def _binary_case(seed: int, n: int = 257, tie_decimals: int = 2):
    rng = np.random.RandomState(seed)
    preds = np.round(rng.rand(n), tie_decimals).astype(np.float32)
    target = (rng.rand(n) > 0.45).astype(np.int32)
    return preds, target


def _multiclass_case(seed: int, n: int = 300):
    rng = np.random.RandomState(seed)
    p = rng.rand(n, NUM_CLASSES).astype(np.float32)
    preds = p / p.sum(1, keepdims=True)
    target = rng.randint(0, NUM_CLASSES, n).astype(np.int32)
    return preds, target


def test_midranks_ties():
    x = jnp.asarray([3.0, 1.0, 3.0, 2.0, 3.0])
    # ascending ranks: 1 -> 1, 2 -> 2, the three 3s share (3+4+5)/3 = 4
    np.testing.assert_allclose(np.asarray(midranks(x)), [4.0, 1.0, 4.0, 2.0, 4.0])


@pytest.mark.parametrize("seed", range(4))
@pytest.mark.parametrize("tie_decimals", [1, 2, 6])
def test_binary_auroc_vs_sklearn(seed, tie_decimals):
    preds, target = _binary_case(seed, tie_decimals=tie_decimals)
    got = float(jax.jit(binary_auroc_sorted)(preds, target))
    assert got == pytest.approx(roc_auc_score(target, preds), abs=1e-5)


@pytest.mark.parametrize("seed", range(4))
@pytest.mark.parametrize("tie_decimals", [1, 2, 6])
def test_binary_ap_vs_sklearn(seed, tie_decimals):
    preds, target = _binary_case(seed, tie_decimals=tie_decimals)
    got = float(jax.jit(binary_average_precision_sorted)(preds, target))
    assert got == pytest.approx(average_precision_score(target, preds), abs=1e-5)


def test_degenerate_classes_nan():
    preds, _ = _binary_case(0)
    assert np.isnan(float(binary_auroc_sorted(preds, np.zeros_like(preds, np.int32))))
    assert np.isnan(float(binary_auroc_sorted(preds, np.ones_like(preds, np.int32))))
    assert np.isnan(float(binary_average_precision_sorted(preds, np.zeros_like(preds, np.int32))))


def test_empty_input_nan():
    empty = jnp.zeros((0,))
    assert np.isnan(float(binary_auroc_sorted(empty, empty)))
    assert np.isnan(float(binary_average_precision_sorted(empty, empty)))


@pytest.mark.parametrize("average", ["macro", "none"])
def test_multiclass_auroc_vs_sklearn(average):
    preds, target = _multiclass_case(1)
    onehot = np.eye(NUM_CLASSES)[target]
    got = jax.jit(lambda p, t: multiclass_auroc_sorted(p, t, NUM_CLASSES, average))(preds, target)
    per_class = [roc_auc_score(onehot[:, c], preds[:, c]) for c in range(NUM_CLASSES)]
    if average == "none":
        np.testing.assert_allclose(np.asarray(got), per_class, atol=1e-5)
    else:
        assert float(got) == pytest.approx(np.mean(per_class), abs=1e-5)


@pytest.mark.parametrize("average", ["macro", "micro", "weighted"])
def test_multiclass_ap_vs_sklearn(average):
    preds, target = _multiclass_case(2)
    onehot = np.eye(NUM_CLASSES)[target]
    got = float(
        jax.jit(lambda p, t: multiclass_average_precision_sorted(p, t, NUM_CLASSES, average))(
            preds, target
        )
    )
    assert got == pytest.approx(average_precision_score(onehot, preds, average=average), abs=1e-5)


class TestTracedDispatch:
    """The functional auroc/average_precision route to the static kernels
    under trace and must agree with their own eager (host curve) path."""

    def test_binary_traced_eq_eager(self):
        preds, target = _binary_case(3)
        np.testing.assert_allclose(
            float(jax.jit(auroc)(preds, target)), float(auroc(preds, target)), atol=1e-6
        )
        np.testing.assert_allclose(
            float(jax.jit(average_precision)(preds, target)),
            float(average_precision(preds, target)),
            atol=1e-6,
        )

    @pytest.mark.parametrize("average", ["macro", "weighted", "none"])
    def test_multiclass_traced_eq_eager(self, average):
        preds, target = _multiclass_case(4)
        f = lambda p, t: auroc(p, t, num_classes=NUM_CLASSES, average=average)
        got, want = jax.jit(f)(preds, target), f(preds, target)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)

    @pytest.mark.parametrize("average", ["macro", "none", "weighted", None])
    def test_unobserved_class_traced_eq_eager(self, average):
        """Degenerate (unobserved) classes must give the SAME value traced and
        eager: score 0.0 in 'none', counted as 0 in the macro mean, dropped by
        support weighting (review regression)."""
        rng = np.random.RandomState(0)
        p = rng.rand(50, 4).astype(np.float32)
        preds = p / p.sum(1, keepdims=True)
        target = rng.randint(0, 3, 50).astype(np.int32)  # class 3 unobserved
        f = lambda p, t: auroc(p, t, num_classes=4, average=average)
        with pytest.warns(UserWarning):
            eager = np.asarray(f(preds, target))
        traced = np.asarray(jax.jit(f)(preds, target))
        np.testing.assert_allclose(eager, traced, atol=1e-5)

    def test_traced_unsupported_options_raise(self):
        preds, target = _binary_case(5)
        with pytest.raises(ValueError, match="max_fpr"):
            jax.jit(lambda p, t: auroc(p, t, max_fpr=0.5))(preds, target)


class TestSPMD:
    """Exact AUROC/AP inside a shard_map program with fused sync — the
    capability the reference cannot express (its exact curves must gather all
    scores to the host)."""

    @pytest.mark.parametrize("metric_cls", [mt.AUROC, mt.AveragePrecision])
    def test_spmd_exact_equals_sklearn(self, metric_cls):
        preds, target = _binary_case(6, n=256)
        mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
        init, upd, cmp = metric_cls().as_functions()

        def f(p, t):
            return cmp(upd(init(), p, t), axis_name="dp")

        out = jax.jit(
            jax.shard_map(
                f, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P(), check_vma=False
            )
        )(jnp.asarray(preds), jnp.asarray(target))
        oracle = (
            roc_auc_score(target, preds)
            if metric_cls is mt.AUROC
            else average_precision_score(target, preds)
        )
        assert float(out) == pytest.approx(oracle, abs=1e-5)


def test_single_class_binary_traced_eq_eager():
    """Single-class binary targets: eager warns and returns 0.0; the traced
    path can't warn but must agree on the value (advisor regression)."""
    preds = jnp.asarray(np.random.RandomState(0).rand(20).astype(np.float32))
    for fill in (0, 1):
        target = jnp.full((20,), fill, jnp.int32)
        with pytest.warns(UserWarning):
            eager = float(auroc(preds, target))
        traced = float(jax.jit(auroc)(preds, target))
        assert eager == traced == 0.0
