"""Streaming monitoring plane: windows, decay, drift (ISSUE-15 contracts).

Contracts (`metrics_tpu/streaming.py`):

- **Window arithmetic is re-accumulation over packed ring slots** — a
  sliding window's value is bit-exact vs a fresh metric fed only the
  retained raw updates, across multiple closes and for sum/mean/cat/max
  reduction families.
- **A fleet close is ONE payload collective** — in a fake 3-rank world the
  close id rides the ``agree_step`` exchange and the stride state merges
  through exactly one coalesced payload gather (zero collectives at world
  size 1, counter-asserted); a membership change mid-close classifies as
  ``EpochFault`` with the ring AND the live accumulator intact, and
  survivors re-close at the new epoch.
- **Crash consistency through the journal** — ring slots persist as
  generation-ringed journal records; a torn newest generation demotes to
  the previous good one (classified, counted) instead of restoring corrupt
  bytes.
- **Decay is the closed form** — ``Decayed`` matches the host EMA oracle
  within float32 tolerance and rejects non-``sum``/integer state trees at
  construction.
- **Drift scores flow to the scrape** — PSI/KS are zero for identical
  samples, positive for shifted ones, and render through
  ``fleet_prometheus_text`` as ``metrics_tpu_drift_score{name,kind}``.
"""
from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

import metrics_tpu as mt
from metrics_tpu import streaming
from metrics_tpu.ops import engine, fleetobs, journal as journal_mod, telemetry
from metrics_tpu.parallel import bucketing
from metrics_tpu.parallel import sync as psync
from metrics_tpu.utils.exceptions import EpochFault

DIST_ON = lambda: True  # noqa: E731


@pytest.fixture(autouse=True)
def _clean_world():
    psync.reset_membership()
    engine.reset_stats()
    yield
    psync.reset_membership()
    engine.reset_stats()
    # simulated fleet closes complete real coalesced syncs, which memoize
    # their layout in the fast-lane manifest cache — a later test in the
    # same process must see the first-sync cross-check path again
    bucketing._MANIFEST_CACHE.clear()


# ----------------------------------------------------------- window arithmetic
def test_sliding_window_bit_exact_vs_oracle():
    win = streaming.Windowed(mt.SumMetric(), window=6, stride=2, name="s")
    fed = []
    closes = 0
    for step in range(12):
        x = jnp.asarray([float(step), float(step) * 0.5])
        fed.append(np.asarray(x))
        out = win.update(x)
        if out is not None:
            closes += 1
            # oracle: a fresh metric fed ONLY the updates inside the window
            oracle = mt.SumMetric()
            for row in fed[-win._window:]:
                oracle.update(jnp.asarray(row))
            assert np.array_equal(np.asarray(out["value"]), np.asarray(oracle.compute())), (
                f"window {out['window']} value diverged from the re-accumulation oracle"
            )
    assert closes >= 3 and win.window_id == closes


def test_window_families_mean_cat_max():
    data = [np.asarray([float(i), float(i) + 0.25], np.float32) for i in range(8)]
    for base, oracle_base in ((mt.MeanMetric, mt.MeanMetric), (mt.CatMetric, mt.CatMetric), (mt.MaxMetric, mt.MaxMetric)):
        win = streaming.Windowed(base(), window=4, stride=2)
        for x in data:
            win.update(jnp.asarray(x))
        oracle = oracle_base()
        for x in data[-4:]:
            oracle.update(jnp.asarray(x))
        assert np.array_equal(np.asarray(win.value()), np.asarray(oracle.compute())), base.__name__


def test_tumbling_default_and_validation():
    win = streaming.Windowed(mt.SumMetric(), window=3)
    assert win._stride == 3 and win._slots_cap == 1
    assert win.value() is None and win.slots == 0
    with pytest.raises(ValueError, match="divisor"):
        streaming.Windowed(mt.SumMetric(), window=4, stride=3)
    with pytest.raises(ValueError, match="positive"):
        streaming.Windowed(mt.SumMetric(), window=0)
    with pytest.raises(TypeError):
        streaming.Windowed(object(), window=2)


def test_window_collection_and_reset():
    suite = mt.MetricCollection({"mean": mt.MeanMetric(), "total": mt.SumMetric()})
    win = streaming.Windowed(suite, window=2, stride=2, name="suite")
    for i in range(4):
        win.update(jnp.asarray([float(i)]))
    value = win.value()
    assert set(value) == {"mean", "total"}
    assert float(value["mean"]) == 2.5 and float(value["total"]) == 5.0
    before = win.window_id
    win.reset()
    assert win.slots == 0 and win.value() is None
    assert win.window_id == before, "close ids must stay monotonic across reset"


# ------------------------------------------------------------------ fleet close
class _FakeFleet:
    """3 identical ranks at both transport seams (shape + payload)."""

    def __init__(self, monkeypatch):
        psync.set_expected_world(3)
        monkeypatch.setattr(
            bucketing, "_host_allgather", lambda vec: np.stack([np.asarray(vec)] * 3)
        )
        monkeypatch.setattr(
            bucketing, "_payload_allgather", lambda packed: jnp.stack([packed] * 3)
        )


def test_fleet_close_is_one_payload_collective(monkeypatch):
    _FakeFleet(monkeypatch)
    win = streaming.Windowed(mt.SumMetric(), window=4, stride=2, name="fleet")
    win.base.update(jnp.asarray([1.0, 2.0]))
    win.base.update(jnp.asarray([3.0, 4.0]))
    p0 = psync.collective_stats()["sync_payload_collectives"]
    out = win.close_window(distributed_available=DIST_ON)
    p1 = psync.collective_stats()["sync_payload_collectives"]
    assert p1 - p0 == 1, "a fleet window close must issue exactly ONE payload collective"
    assert out["world"] == 3
    # the fake world stacks 3 identical rows: fleet sum = 3x local
    assert float(out["value"]) == 3.0 * 10.0
    assert streaming.streaming_stats()["window_close_payload_collectives"] >= 1


def test_world1_close_is_zero_collectives():
    win = streaming.Windowed(mt.SumMetric(), window=2, stride=2)
    before = psync.collective_stats()["sync_collectives_issued"]
    win.update(jnp.asarray([1.0]))
    win.update(jnp.asarray([2.0]))
    after = psync.collective_stats()["sync_collectives_issued"]
    assert win.window_id == 1
    assert after == before, "a world-size-1 close must issue zero collectives"


def test_membership_change_mid_close_is_epoch_fault(monkeypatch):
    _FakeFleet(monkeypatch)
    win = streaming.Windowed(mt.SumMetric(), window=2, stride=2, name="fence")
    win.base.update(jnp.asarray([5.0]))
    state_before = np.asarray(win.base.compute())

    def racing(vec):
        psync.bump_epoch("test-membership-race")
        raise RuntimeError("transport interrupted by membership change")

    monkeypatch.setattr(bucketing, "_host_allgather", racing)
    trips0 = streaming.streaming_stats()["window_epoch_trips"]
    with pytest.raises(EpochFault):
        win.close_window(distributed_available=DIST_ON)
    assert streaming.streaming_stats()["window_epoch_trips"] == trips0 + 1
    # never a torn window: ring empty, live accumulator intact
    assert win.slots == 0 and win.window_id == 0
    assert np.array_equal(np.asarray(win.base.compute()), state_before)
    # survivors re-close at the new epoch once the transport heals
    monkeypatch.setattr(
        bucketing, "_host_allgather", lambda vec: np.stack([np.asarray(vec)] * 3)
    )
    out = win.close_window(distributed_available=DIST_ON)
    assert out["window"] == 1 and out["epoch"] == psync.world_epoch()
    assert float(out["value"]) == 15.0  # 3 ranks x 5.0


# ------------------------------------------------------------ crash consistency
def test_ring_persistence_and_torn_slot_demotes(tmp_path):
    path = str(tmp_path / "win.journal")
    win = streaming.Windowed(mt.SumMetric(), window=4, stride=2, name="disk", journal_path=path)
    for i in range(8):
        win.update(jnp.asarray([float(i)]))
    live_value = float(win.value())
    assert streaming.streaming_stats()["window_slot_writes"] >= 4

    fresh = streaming.Windowed(mt.SumMetric(), window=4, stride=2, name="disk", journal_path=path)
    report = fresh.restore()
    assert report["slots"] == 2 and report["window"] == win.window_id
    assert float(report["value"]) == live_value

    # tear the NEWEST generation of one slot: restore must demote to the
    # previous good generation, not restore corrupt bytes
    victim = win._slot_path(win.window_id % win._slots_cap)
    raw = bytearray(open(victim, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(victim, "wb").write(bytes(raw))
    torn = streaming.Windowed(mt.SumMetric(), window=4, stride=2, name="disk", journal_path=path)
    demotions0 = streaming.streaming_stats()["window_ring_demotions"]
    report = torn.restore()
    assert streaming.streaming_stats()["window_ring_demotions"] > demotions0
    # the generation ring held an older good copy of the slot, so the window
    # still re-accumulates from verified records only
    assert report["slots"] >= 1
    for _, record in torn._ring:
        journal_mod.decode_record(record)  # every retained slot verifies


# ------------------------------------------------------------------------ decay
def test_decayed_matches_closed_form():
    halflife = 3.0
    ema = streaming.Decayed(mt.SumMetric(), halflife=halflife)
    xs = [1.0, 2.0, 4.0, 8.0, 16.0]
    for x in xs:
        ema.update(jnp.asarray([x]))
    d = 0.5 ** (1.0 / halflife)
    oracle = sum(x * d ** (len(xs) - 1 - i) for i, x in enumerate(xs))
    assert float(ema.compute()) == pytest.approx(oracle, rel=1e-6)
    assert streaming.streaming_stats()["window_decay_ticks"] == len(xs)
    ema.reset()
    assert float(ema.compute()) == 0.0


def test_decayed_mean_is_weighted_ema():
    halflife = 2.0
    ema = streaming.Decayed(mt.MeanMetric(), halflife=halflife, name="ema-mean")
    xs = [0.0, 0.0, 8.0]
    for x in xs:
        ema.update(jnp.asarray([x]))
    d = 0.5 ** (1.0 / halflife)
    num = sum(x * d ** (len(xs) - 1 - i) for i, x in enumerate(xs))
    den = sum(d ** (len(xs) - 1 - i) for i in range(len(xs)))
    assert float(ema.compute()) == pytest.approx(num / den, rel=1e-6)


def test_decayed_rejects_nonlinear_states():
    with pytest.raises(ValueError, match="sum-reduction"):
        streaming.Decayed(mt.MaxMetric(), halflife=2.0)
    with pytest.raises(ValueError, match="positive"):
        streaming.Decayed(mt.SumMetric(), halflife=0.0)


# ------------------------------------------------------------------------ drift
def test_drift_report_scores():
    rng = np.random.RandomState(3)
    base = rng.normal(0.0, 1.0, 2000)
    same = streaming.drift_report(base, base)
    assert same["psi"] == pytest.approx(0.0, abs=1e-9)
    assert same["ks"] == pytest.approx(0.0, abs=1e-9)
    shifted = streaming.drift_report(base + 3.0, base, name="shifted")
    assert shifted["psi"] > 0.2 and 0.0 < shifted["ks"] <= 1.0
    # degenerate constant samples score zero drift, not NaN/inf
    flat = streaming.drift_report(np.ones(10), np.ones(10))
    assert np.isfinite(flat["psi"]) and flat["psi"] == pytest.approx(0.0, abs=1e-9)
    with pytest.raises(ValueError, match="non-empty"):
        streaming.drift_report(np.asarray([np.nan]), base)


def test_windowed_drift_detects_shift():
    win = streaming.Windowed(mt.CatMetric(), window=4, stride=2, name="dist")
    rng = np.random.RandomState(7)
    for i in range(4):
        loc = 0.0 if i < 2 else 5.0  # distribution shifts mid-stream
        win.update(jnp.asarray(rng.normal(loc, 1.0, 64).astype(np.float32)))
    report = win.drift_report()
    assert report["psi"] > 0.2
    assert streaming.streaming_snapshot()["drift"]["dist"]["psi"] == report["psi"]


# -------------------------------------------------------------- observability
def test_streaming_block_and_counter_typing():
    win = streaming.Windowed(mt.MeanMetric(), window=2, stride=2, name="obs")
    win.update(jnp.asarray([1.0]))
    win.update(jnp.asarray([3.0]))
    snap = telemetry.telemetry_snapshot()
    block = snap["streaming"]["windows"]["obs"]
    assert block["window"] == 1 and block["values"]["1"]["value"] == 2.0
    assert snap["window_closes"] >= 1  # event counters ride engine_stats
    # typing discipline: events are counters, window STATE/VALUES are gauges
    assert telemetry.is_counter_key("window_closes")
    assert telemetry.is_counter_key("drift_reports")
    assert not telemetry.is_counter_key("streaming_windows_obs_window")
    assert not telemetry.is_counter_key("streaming_windows_obs_values_1_value")


def test_drift_renders_in_fleet_prometheus_text():
    win = streaming.Windowed(mt.MeanMetric(), window=2, stride=2, name="served")
    win.update(jnp.asarray([1.0]))
    win.update(jnp.asarray([2.0]))
    streaming.drift_report(np.arange(50.0) + 40.0, np.arange(50.0), name="served")
    text = fleetobs.fleet_prometheus_text()
    assert 'metrics_tpu_metric_value{name="served",window="1"} 1.5' in text
    assert 'metrics_tpu_drift_score{name="served",kind="psi"}' in text
    psi_line = next(
        line for line in text.splitlines()
        if line.startswith('metrics_tpu_drift_score{name="served",kind="psi"}')
    )
    assert float(psi_line.rsplit(" ", 1)[1]) > 0.0
    assert 'metrics_tpu_fleet_window_id{name="served"} 1' in text
    assert 'metrics_tpu_fleet_window_skew{rank="0",name="served"} 0' in text


def test_window_skew_attribution(monkeypatch):
    # two live planes whose "served" windows reached different close ids
    planes = {
        0: {"snapshot_schema": 1, "streaming": {"windows": {"w": {"window": 5}}, "drift": {}}},
        1: {"snapshot_schema": 1, "streaming": {"windows": {"w": {"window": 3}}, "drift": {}}},
        2: {"dead": True, "rank": 2},
    }
    merged = fleetobs.merge_streaming(planes)
    skew = merged["window_skew"]["w"]
    assert skew["agreed"] == 5 and skew["max_skew"] == 2
    assert skew["per_rank_lag"] == {0: 0, 1: 2}


# -------------------------------------------------------------------- env knobs
def test_env_knobs_parse_and_fall_back(monkeypatch):
    monkeypatch.setenv("METRICS_TPU_DRIFT_BINS", "32")
    assert streaming.drift_bins() == 32
    monkeypatch.setenv("METRICS_TPU_DRIFT_BINS", "banana")
    assert streaming.drift_bins() == 16  # warn-once fallback, never a crash
    monkeypatch.setenv("METRICS_TPU_DRIFT_EPS", "-3")
    assert streaming.drift_eps() == 1e-6
    monkeypatch.setenv("METRICS_TPU_WINDOW_DEFAULT_STRIDE", "2")
    win = streaming.Windowed(mt.SumMetric(), window=4)
    assert win._stride == 2
    monkeypatch.setenv("METRICS_TPU_WINDOW_VALUES_KEPT", "1")
    w2 = streaming.Windowed(mt.SumMetric(), window=2, stride=2, name="kept")
    for i in range(6):
        w2.update(jnp.asarray([float(i)]))
    values = streaming.streaming_snapshot()["windows"]["kept"]["values"]
    assert list(values) == [str(w2.window_id)], "only the newest value is retained"
