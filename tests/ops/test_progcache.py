"""Persistent cross-process program cache (ISSUE-18 contracts).

Contracts (`metrics_tpu/ops/progcache.py` + engine wiring):

- **Cross-process round trip** — a second process (simulated by
  `engine.reset_engine()` + fresh module instances) replaying the same
  traffic over Accuracy / Mean / AUROC / compute-group suites and an
  arena slab program serves every stored program from disk: zero fresh
  compiles where the store covered the cold boot, bit-exact values
  always.
- **Fault ladder, never a wrong program** — truncated, bit-flipped,
  wrong-jax-version and wrong-backend entries each demote through the
  `progcache` lane with ONE classified warning (warn-once per
  owner+domain), count in `progcache_demotions`, and traffic falls back
  to fresh compiles with bit-identical results.
- **AOT precompile** — `MetricCollection.precompile()` then live ragged
  traffic compiles nothing new (counter-pinned on
  `program_summary()["compiles"]`).
- **Disabled by default** — with the knob unset the store allocates no
  directory and probes no disk: every `progcache_*` counter stays zero.
- **Warn-once env knobs** — garbage `METRICS_TPU_PROGCACHE` warns once
  naming the value and falls back to off.
"""
from __future__ import annotations

import os
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import metrics_tpu as mt
from metrics_tpu.ops import engine, progcache
from metrics_tpu.parallel import sync as psync


@pytest.fixture(autouse=True)
def _clean_world(monkeypatch):
    monkeypatch.delenv("METRICS_TPU_PROGCACHE", raising=False)
    monkeypatch.delenv("METRICS_TPU_PROGCACHE_DIR", raising=False)
    monkeypatch.delenv("METRICS_TPU_PROGCACHE_MAX_MB", raising=False)
    psync.reset_membership()
    engine.reset_engine()
    engine.reset_stats(reset_warnings=True)
    progcache.configure(reset=True)
    yield
    psync.reset_membership()
    engine.reset_engine()
    engine.reset_stats(reset_warnings=True)
    progcache.configure(reset=True)
    try:
        # an enabled store points JAX's own compilation cache under it;
        # point it back off the (about-to-be-deleted) tmp dir
        jax.config.update(
            "jax_compilation_cache_dir", os.environ.get("JAX_COMPILATION_CACHE_DIR")
        )
    except Exception:  # noqa: BLE001 — older jax without the knob
        pass


def _enable(tmp_path, **kw):
    progcache.configure(enabled=True, cache_dir=str(tmp_path / "store"), **kw)


def _new_process():
    """Simulate a replacement process sharing only the on-disk store."""
    engine.reset_engine()
    engine.reset_stats(reset_warnings=True)


def _assert_bitexact(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        xa, ya = np.asarray(x), np.asarray(y)
        assert xa.dtype == ya.dtype and xa.shape == ya.shape
        assert xa.tobytes() == ya.tobytes()


# ------------------------------------------------------------- suite zoo
def _acc_suite():
    return mt.MetricCollection({"acc": mt.Accuracy(num_classes=2)})


def _acc_batch(rng):
    return (
        jnp.asarray(rng.randint(0, 2, (16,)).astype(np.int32)),
        jnp.asarray(rng.randint(0, 2, (16,)).astype(np.int32)),
    )


def _mean_suite():
    return mt.MetricCollection({"mean": mt.MeanMetric()})


def _mean_batch(rng):
    return (jnp.asarray(rng.randn(16).astype(np.float32)),)


def _auroc_suite():
    return mt.MetricCollection({"auroc": mt.AUROC()})


def _auroc_batch(rng):
    return (
        jnp.asarray(rng.rand(16).astype(np.float32)),
        jnp.asarray(rng.randint(0, 2, (16,)).astype(np.int32)),
    )


def _group_suite():
    # Precision + Recall share StatScores state: a real compute group.
    return mt.MetricCollection(
        {
            "precision": mt.Precision(num_classes=2),
            "recall": mt.Recall(num_classes=2),
        }
    )


SUITES = {
    "accuracy": (_acc_suite, _acc_batch),
    "mean": (_mean_suite, _mean_batch),
    "auroc": (_auroc_suite, _auroc_batch),
    "compute-group": (_group_suite, _acc_batch),
}


def _run_traffic(factory, batch_fn, rounds=(3, 2), seed=7):
    suite = factory()
    rng = np.random.RandomState(seed)
    vals = []
    for n in rounds:
        for _ in range(n):
            suite.update(*batch_fn(rng))
        vals.append(suite.compute())
    return vals


# ------------------------------------------------------------ round trips
@pytest.mark.parametrize("name", sorted(SUITES))
def test_roundtrip_bitexact(tmp_path, name):
    factory, batch_fn = SUITES[name]
    _enable(tmp_path)

    cold_vals = _run_traffic(factory, batch_fn)
    cold = engine.program_summary()
    stats = progcache.progcache_stats()
    cold_compiles, cold_stores = cold["compiles"], stats["progcache_stores"]

    _new_process()
    warm_vals = _run_traffic(factory, batch_fn)
    warm = engine.program_summary()

    _assert_bitexact(cold_vals, warm_vals)
    if cold_stores == cold_compiles:
        # every cold program was exportable: warm boot is compile-free
        assert warm["compiles"] == 0
    else:
        assert warm["compiles"] < cold_compiles or cold_compiles == 0
    if cold_stores:
        assert progcache.progcache_stats()["progcache_hits"] > 0


def test_arena_slab_roundtrip(tmp_path):
    _enable(tmp_path)

    def drive():
        arena = mt.MetricArena(mt.MeanMetric(), capacity=4, slab=4, name="pc")
        ids = arena.add(4)
        rng = np.random.RandomState(11)
        for _ in range(3):
            arena.update(ids, jnp.asarray(rng.randn(4).astype(np.float32)))
        return np.asarray(arena.compute(ids))

    cold_vals = drive()
    cold_compiles = engine.program_summary()["compiles"]
    assert cold_compiles > 0
    assert progcache.progcache_stats()["progcache_stores"] > 0

    _new_process()
    warm_vals = drive()
    assert engine.program_summary()["compiles"] == 0
    assert progcache.progcache_stats()["progcache_hits"] > 0
    assert cold_vals.tobytes() == warm_vals.tobytes()


# --------------------------------------------------------------- corruption
def _tamper(root, how):
    """Corrupt every stored entry the given way; return how many."""
    names = [n for n in os.listdir(root) if n.endswith(".mpc")]
    assert names, "cold boot stored nothing to corrupt"
    for name in names:
        path = os.path.join(root, name)
        blob = bytearray(open(path, "rb").read())
        if how == "truncate":
            blob = blob[: len(blob) // 2]
        elif how == "bitflip":
            blob[-1] ^= 0xFF
        else:  # rewrite the manifest with a mismatched field
            manifest, payload = progcache.decode_entry(bytes(blob), origin=name)
            if how == "jax-version":
                manifest["jax_version"] = "0.0.0-elsewhere"
            elif how == "backend":
                manifest["backend"] = "not-a-backend"
            blob = progcache._frame_entry(manifest, payload)
        with open(path, "wb") as fh:
            fh.write(bytes(blob))
    return len(names)


@pytest.mark.parametrize("how", ["truncate", "bitflip", "jax-version", "backend"])
def test_corrupt_entries_demote_classified_warn_once(tmp_path, how):
    factory, batch_fn = SUITES["accuracy"]
    _enable(tmp_path)
    cold_vals = _run_traffic(factory, batch_fn)
    assert progcache.progcache_stats()["progcache_stores"] > 0

    _tamper(str(tmp_path / "store"), how)
    _new_process()
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        warm_vals = _run_traffic(factory, batch_fn)

    # never a wrong program: the demoted entry is replaced by a fresh
    # compile with bit-identical results
    _assert_bitexact(cold_vals, warm_vals)
    assert progcache.progcache_stats()["progcache_demotions"] >= 1
    assert engine.program_summary()["compiles"] > 0
    matching = [w for w in caught if "progcache" in str(w.message)]
    assert len(matching) == 1, [str(w.message) for w in caught]


# ------------------------------------------------------------- precompile
def test_precompile_then_live_traffic_zero_compiles(tmp_path):
    _enable(tmp_path)
    suite = mt.MetricCollection(
        {"acc": mt.Accuracy(num_classes=2), "mean": mt.MeanMetric()}
    )
    sds = jax.ShapeDtypeStruct((16,), jnp.int32)
    report = suite.precompile(sds, sds, defer_chunks=8, forward=False)
    assert report["programs"] > 0

    before = engine.program_summary()["compiles"]
    rng = np.random.RandomState(3)
    for n in (4, 3, 7, 1, 6):  # ragged: exercises every pow2 flush chunk
        for _ in range(n):
            suite.update(*_acc_batch(rng))
        suite.compute()
    assert engine.program_summary()["compiles"] == before


def test_precompile_restores_state(tmp_path):
    _enable(tmp_path)
    suite = mt.MetricCollection({"mean": mt.MeanMetric()})
    suite.update(jnp.ones((16,)))
    want = np.asarray(suite.compute()["mean"])
    suite.precompile(jax.ShapeDtypeStruct((16,), jnp.float32), defer_chunks=2)
    got = np.asarray(suite.compute()["mean"])
    assert want.tobytes() == got.tobytes()


# ------------------------------------------------------ disabled by default
def test_disabled_by_default_probes_nothing(tmp_path, monkeypatch):
    store = tmp_path / "never"
    monkeypatch.setenv("METRICS_TPU_PROGCACHE_DIR", str(store))
    assert not progcache.enabled()

    _run_traffic(*SUITES["accuracy"])
    assert not store.exists()
    assert all(v == 0 for v in progcache.progcache_stats().values())
    assert progcache.stored_sigs("collection-deferred-update", "x") == frozenset()


def test_garbage_enable_knob_warns_once_and_stays_off(monkeypatch):
    monkeypatch.setenv("METRICS_TPU_PROGCACHE", "banana")
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        assert not progcache.enabled()
        assert not progcache.enabled()
    matching = [w for w in caught if "METRICS_TPU_PROGCACHE" in str(w.message)]
    assert len(matching) == 1
