"""Known-good fixture for the warn-once-discipline pass: the sanctioned
spellings, plus the pragma escape for a deliberate direct warning."""
import warnings


def informational(rank_zero_warn):
    rank_zero_warn("the span ring shrank; oldest spans dropped")


def fault_driven(warn_fault, owner):
    warn_fault(owner, "sync", "deadline exceeded; serving the degraded value")


def deliberate_direct(message):
    warnings.warn(message)  # invlint: allow(INV401) — fixture: demonstrates the sanctioned pragma escape
