"""Known-bad fixture for the fault-taxonomy pass (INV201/INV202)."""


def swallow(fn):
    """A broad handler that swallows silently: the failure never reaches
    the taxonomy, the failure_log, or the operator."""
    try:
        return fn()
    except Exception:  # expect: INV201
        return None


def swallow_bare(fn):
    try:
        return fn()
    except:  # noqa: E722 # expect: INV201
        return None


def unknown_injection_site(inject_faults):
    with inject_faults("sync-gatherx"):  # expect: INV202
        pass


def unknown_span_site(_telemetry):
    _telemetry.emit("sync-payload-gatherx", None, "sync")  # expect: INV202
