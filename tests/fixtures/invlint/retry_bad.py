"""Known-bad fixture for the retry-purity pass (INV101/INV102)."""


def protocol_no_fence(retry_with_backoff, run_with_deadline, gather, vec):
    """The retried closure issues a collective but never re-checks the
    epoch fence: a membership change between attempts re-issues into the
    wrong cohort (pairs with the new cohort's next collective, or hangs)."""

    def _attempt():  # expect: INV101
        return run_with_deadline(lambda: gather(vec))

    return retry_with_backoff(_attempt, attempts=2, base_delay_s=0.0)


def protocol_mutating(retry_with_backoff, check_epoch, gather, node, fence):
    """The retried closure mutates object state with no snapshot/restore in
    scope: a half-applied failed attempt leaks into the retry."""

    def _attempt():
        check_epoch(fence)
        node.value = gather()  # expect: INV102
        return node.value

    return retry_with_backoff(_attempt, attempts=1, base_delay_s=0.0)


def protocol_setattr(retry_with_backoff, check_epoch, gather, node, fence):
    def _attempt():
        check_epoch(fence)
        setattr(node, "value", gather())  # expect: INV102

    return retry_with_backoff(_attempt, attempts=1, base_delay_s=0.0)
