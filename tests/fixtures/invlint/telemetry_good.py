"""Known-good fixture for the telemetry-typing pass: counter-prefixed keys
plus a deliberate gauge carve-out (ratio suffix). Zero findings."""

_counters = {
    "sync_custom_exchanges": 0,
    "journal_rewrites": 0,
    "sync_window_ratio": 0,  # gauge carve-out: ratios recompute per scrape
}


def _bump(name, n=1):
    _counters[name] += n  # dynamic key: typed at its literal call sites


def bump_typed():
    _counters["sync_custom_exchanges"] += 1
    _bump("journal_rewrites")
