"""Known-good fixture for the collective-discipline pass: the sanctioned
patterns — primitive seam, delegated wrapper, guarded+audited protocol,
and a rank-symmetric early-out. Zero findings expected."""
import numpy as np  # noqa: F401 — fixture, never imported
from jax.experimental import multihost_utils  # noqa: F401


def _host_allgather(vec):
    """The primitive seam itself is exempt: its CALLERS carry the guard."""
    return multihost_utils.process_allgather(vec)


def _exchange_once(vec, note_collective, fence):
    """Delegated body: runs under the caller's run_with_deadline lambda and
    audits its own collective slots against the fence (the _gather_once
    pattern in parallel/sync.py)."""
    rows = multihost_utils.process_allgather(vec)
    note_collective("shape", epoch=fence)
    return rows


def protocol(vec, run_with_deadline, note_collective, world_epoch):
    """Inline-guarded protocol: fence at entry, audit on every slot."""
    fence = world_epoch()
    rows = run_with_deadline(lambda: multihost_utils.process_allgather(vec))
    note_collective("payload", nbytes=int(rows.size), epoch=fence)
    return rows


def delegating_protocol(vec, run_with_deadline, note_collective, world_epoch):
    fence = world_epoch()
    return run_with_deadline(lambda: _exchange_once(vec, note_collective, fence))


def early_out(vec, distributed_available, run_with_deadline, note_collective, fence):
    """Branching on distributed_available() is rank-symmetric (the process
    count is uniform across the world) — allowed."""
    if not distributed_available():
        note_collective("shape", epoch=fence)
        return vec[None]
    rows = run_with_deadline(lambda: multihost_utils.process_allgather(vec))
    note_collective("shape", epoch=fence)
    return rows
