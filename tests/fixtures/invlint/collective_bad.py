"""Known-bad fixture for the collective-discipline pass (INV001/002/003).

Never imported — parsed by ``tools/invlint`` in ``tests/tools/test_invlint.py``.
``# expect: RULE`` markers pin the exact line each rule must fire on.
"""
from jax.experimental import multihost_utils  # noqa: F401 — fixture, never imported

_LAYOUT_CACHE = {}


def unguarded_unaudited(vec):
    """A raw transport call: no watchdog, no audit — both rules fire."""
    return multihost_utils.process_allgather(vec)  # expect: INV001, INV002


def guarded_but_unaudited(vec, run_with_deadline):
    """Deadline-guarded, but no note_collective(epoch=...) in the protocol."""
    return run_with_deadline(lambda: multihost_utils.process_allgather(vec))  # expect: INV002


def rank_keyed(vec, run_with_deadline, note_collective, fence):
    """Only rank 0 issues the collective: the cohort deadlocks."""
    import jax

    rows = None
    if jax.process_index() == 0:
        rows = run_with_deadline(lambda: multihost_utils.process_allgather(vec))  # expect: INV003
    note_collective("shape", epoch=fence)
    return rows


def cache_keyed(vec, key, run_with_deadline, note_collective, fence):
    """Branching a collective on a process-local cache: first-touch skew
    between ranks issues it on some ranks and not others."""
    rows = None
    if key not in _LAYOUT_CACHE:
        rows = run_with_deadline(lambda: multihost_utils.process_allgather(vec))  # expect: INV003
    note_collective("payload", epoch=fence)
    return rows
