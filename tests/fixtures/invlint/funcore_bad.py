"""Known-bad fixture for the in-graph collective discipline (INV003).

Never imported — parsed by ``tools/invlint`` in ``tests/tools/test_invlint.py``.
In-graph ``lax`` collectives are exempt from the host-transport rules
(INV001/INV002: no host wall, no protocol audit), but rank-divergent control
flow around one desyncs the compiled mesh program exactly like a host
collective — INV003 must still fire.
"""
from jax import lax  # noqa: F401 — fixture, never imported

_SPEC_CACHE = {}


def rank_keyed_compute(state, axis_name):
    """Only rank 0 merges: every other device's trace skips the psum."""
    import jax

    merged = state
    if jax.process_index() == 0:
        merged = lax.psum(state, axis_name)  # expect: INV003
    return merged


def rank_name_keyed(state, axis_name, rank):
    """Branching the gather on a rank-local name."""
    if rank == 0:
        return lax.all_gather(state, axis_name, axis=0, tiled=True)  # expect: INV003
    return state


def cache_keyed_merge(state, key, axis_name):
    """First-touch skew on a process-local cache: some ranks trace the
    pmean, others serve the memo and skip it."""
    if key not in _SPEC_CACHE:
        _SPEC_CACHE[key] = lax.pmean(state, axis_name)  # expect: INV003
    return _SPEC_CACHE[key]
