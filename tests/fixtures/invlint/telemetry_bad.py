"""Known-bad fixture for the telemetry-typing pass (INV301/INV302)."""

_counters = {
    "orphan_total": 0,  # expect: INV301
    "bad-name": 0,  # expect: INV302
}


def bump_untyped():
    _counters["orphan_total"] += 1  # expect: INV301


def bump_invalid(_bump):
    _bump("sync.dotted.name")  # expect: INV302
