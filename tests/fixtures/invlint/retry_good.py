"""Known-good fixture for the retry-purity pass: fence re-checked inside the
closure; mutation covered by a snapshot/restore in scope. Zero findings."""


def protocol(retry_with_backoff, run_with_deadline, check_epoch, note_collective, world_epoch, gather, vec):
    fence = world_epoch()

    def _attempt():
        check_epoch(fence)
        rows = run_with_deadline(lambda: gather(vec))
        note_collective("payload", epoch=fence)
        return rows

    return retry_with_backoff(_attempt, attempts=2, base_delay_s=0.0)


def protocol_with_snapshot(retry_with_backoff, check_epoch, gather, node, fence):
    snapshot = {"value": node.value}

    def _attempt():
        check_epoch(fence)
        node.value = gather()
        return node.value

    try:
        return retry_with_backoff(_attempt, attempts=1, base_delay_s=0.0)
    except Exception:
        node.value = snapshot["value"]  # restore the entry state, then surface
        raise
