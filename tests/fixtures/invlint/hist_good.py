"""Known-good fixture for the histogram-typing pass: positive strictly
increasing bounds, a valid family stem, a snapshot key whose flattened
bucket/count/sum samples classify as counters (and whose percentile
samples stay gauge carve-outs). Zero findings."""

_HIST_BOUNDS_S = (0.001, 0.002, 0.004, 0.008)
_HIST_FAMILY = "latency_seconds"
_HIST_SNAPSHOT_KEY = "latency_stats"
