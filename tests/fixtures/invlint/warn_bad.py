"""Known-bad fixture for the warn-once-discipline pass (INV401)."""
import warnings
import warnings as _w
from warnings import warn as _direct_warn


def hot_path_warning(value):
    warnings.warn(f"value {value} fell back to the eager path")  # expect: INV401


def aliased_module_warning(value):
    _w.warn(f"value {value} fell back")  # expect: INV401


def bare_imported_warning(value):
    _direct_warn(f"value {value} fell back")  # expect: INV401
