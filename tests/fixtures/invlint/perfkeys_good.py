"""Known-good fixture for the ISSUE-12 performance-attribution carve-outs:
the probe / analysis / report counters all classify as counters under the
``device_`` / ``program_`` / ``perf_`` prefixes, and the device-histogram
site prefix is label-safe. Zero findings."""

_stats = {"device_probes": 0, "program_analyses": 0}

_counters = {"perf_reports": 0}

_DEVICE_HIST_SITE = "device-dispatch"
