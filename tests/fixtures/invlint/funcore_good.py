"""Known-good fixture for the in-graph collective discipline: the sanctioned
functional-core pattern — pure ``apply_*`` kernels whose cross-device merge
is an in-graph ``lax`` collective keyed on a mesh axis name. No watchdog, no
``note_collective`` audit: there is no host transport to guard (INV001/INV002
are host-transport discipline), and the epoch fence rides the state treedef.
Spec-keyed and world-size branches are rank-SYMMETRIC (every device traces
the same program), so INV003 stays quiet. Zero findings expected."""
from jax import lax  # noqa: F401 — fixture, never imported


def apply_update(state, batch):
    """Pure per-device accumulation: no collective at all."""
    return {k: v + batch[k] for k, v in state.items()}


def sync_array(x, spec, axis_name):
    """The spec -> collective lowering (parallel/collectives.py): the branch
    is keyed on the reduction SPEC, identical on every device."""
    if spec == "sum":
        return lax.psum(x, axis_name)
    if spec == "mean":
        return lax.pmean(x, axis_name)
    if spec == "max":
        return lax.pmax(x, axis_name)
    if spec == "min":
        return lax.pmin(x, axis_name)
    return lax.all_gather(x, axis_name, axis=0, tiled=True)


def apply_compute(state, specs, axis_name=None):
    """The in-graph merge: one collective per state, inside the jitted step,
    gated only on the (trace-time, rank-symmetric) axis name."""
    if axis_name is not None:
        state = {k: sync_array(v, specs[k], axis_name) for k, v in state.items()}
    return sum(state.values())


def world_size_early_out(x, axis_name, world_size):
    """Branching on the world size is rank-symmetric (uniform across the
    mesh) — allowed, mirroring the host path's distributed_available gate."""
    if world_size == 1:
        return x
    return lax.psum(x, axis_name)
