"""Known-bad fixture for the ISSUE-12 performance-attribution carve-outs:
probe/analysis counters must carry a counter prefix (INV301), and the
per-program device-histogram site prefix must stay label-safe (INV303)."""

# untyped: neither a counter prefix nor a declared gauge carve-out — the
# probe counter would scrape as a gauge and the fleet merge would
# min/median/max it instead of summing
_stats = {"probe_block_walls": 0}  # expect: INV301

# a quote inside the site prefix would corrupt every le-labelled exposition
# line the per-program families render into
_DEVICE_HIST_SITE = 'device "dispatch"'  # expect: INV303
