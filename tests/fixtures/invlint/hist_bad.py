"""Known-bad fixture for the histogram-typing pass (INV303)."""

# not strictly increasing: the cumulative le exposition would decrease
_HIST_BOUNDS_S = (0.001, 0.0005, 0.002)  # expect: INV303

# '-' is not in the Prometheus name alphabet
_HIST_FAMILY = "latency-seconds"  # expect: INV303

# flattened bucket/count/sum samples would NOT classify as counters
_HIST_SNAPSHOT_KEY = "orphan_hist"  # expect: INV303
