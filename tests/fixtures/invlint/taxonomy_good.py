"""Known-good fixture for the fault-taxonomy pass: routed, re-raised,
pragma'd and noqa'd handlers; registry-valid site strings. Zero findings."""


def routed(fn, classify, note_fault):
    try:
        return fn()
    except Exception as exc:
        note_fault(classify(exc, "runtime"), error=exc)
        return None


def warned(fn, warn_fault, owner):
    try:
        return fn()
    except Exception:
        warn_fault(owner, "runtime", "probe failed; serving the fallback")
        return None


def reraised(fn, rollback):
    try:
        return fn()
    except Exception:
        rollback()
        raise


def pragma_escape(fn):
    try:
        return fn()
    except Exception:  # invlint: allow(INV201) — intentional probe: the failure IS the signal under test
        return None


def noqa_escape(fn):
    try:
        return fn()
    except Exception:  # noqa: BLE001 — best-effort cleanup, outcome already recorded
        return None


def registry_valid_sites(inject_faults, maybe_fail, _telemetry):
    with inject_faults("flush-chunk-3"):
        maybe_fail("sync-gather")
    _telemetry.emit("sync-payload-gather", None, "sync")
