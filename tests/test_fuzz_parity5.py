"""Fuzz-parity wave 5: input-container tolerance across the functional surface.

Round 4's only crash was `pairwise_euclidean_distance(numpy_array)` — the
parity suite fed jax arrays everywhere, so a numpy-only code path
(`.at[]` on an ndarray) shipped broken. This wave closes that matrix hole
mechanically: every exported functional symbol's doctest is executed twice,
once with the real ``jnp`` and once with a shim whose array *constructors*
return numpy arrays (everything else delegates), and the results must match.
Any symbol whose implementation assumes jax-array-only input crashes here.

A second targeted wave feeds plain nested python lists to the callable
surface that the reference accepts tensor-likes for
(reference `functional/pairwise/helpers.py:20-45` via ``torch.as_tensor``).
"""
from __future__ import annotations

import doctest
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu.functional as functional

_CTOR_NAMES = frozenset(
    {"asarray", "array", "arange", "zeros", "ones", "full", "linspace", "eye"}
)


class _NumpyCtorShim:
    """Looks like ``jax.numpy`` but its array constructors return numpy arrays.

    Everything else (dtypes, ufuncs the doctest may apply to already-built
    arrays) delegates to the real ``jnp``, so only the *inputs handed to the
    metric* change container type.
    """

    def __getattr__(self, name):
        if name in _CTOR_NAMES:
            return getattr(np, name)
        return getattr(jnp, name)


_IMPORT_JNP = re.compile(r"^\s*(import\s+jax\.numpy\s+as\s+jnp|from\s+jax\s+import\s+numpy\s+as\s+jnp)\s*$")


def _examples_for(name):
    fn = getattr(functional, name)
    finder = doctest.DocTestFinder(exclude_empty=True)
    examples = []
    for test in finder.find(fn, name):
        examples.extend(test.examples)
    return examples


def _run_examples(examples, jnp_like):
    """Execute doctest examples with ``jnp`` bound to *jnp_like*; collect the
    value of every output-producing expression."""
    ns = {"jnp": jnp_like, "np": np, "jax": jax}
    values = []
    for ex in examples:
        src = ex.source
        if _IMPORT_JNP.match(src.strip()):
            continue  # jnp is pre-seeded (shimmed in the numpy run)
        if ex.want:
            try:
                code = compile(src, "<fuzz5>", "eval")
            except SyntaxError:
                exec(compile(src, "<fuzz5>", "exec"), ns)
                ns["jnp"] = jnp_like  # combined imports must not unbind the shim
                continue
            values.append(eval(code, ns))
        else:
            exec(compile(src, "<fuzz5>", "exec"), ns)
            ns["jnp"] = jnp_like  # e.g. `import jax, jax.numpy as jnp`
    return values


def _assert_trees_match(a, b, name):
    la, sa = jax.tree_util.tree_flatten(a)
    lb, sb = jax.tree_util.tree_flatten(b)
    assert sa == sb, f"{name}: result tree structure differs between jax and numpy inputs"
    for x, y in zip(la, lb):
        if isinstance(x, str):
            assert x == y, f"{name}: {x!r} != {y!r}"
        else:
            np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), atol=1e-5, rtol=1e-4,
                err_msg=f"{name}: jax-input vs numpy-input result mismatch",
            )


def _runnable_symbols():
    out = []
    for name in sorted(functional.__all__):
        examples = _examples_for(name)
        if not examples:
            continue
        if any(ex.options.get(doctest.SKIP) for ex in examples):
            continue  # model-backed examples (weights unfetchable here)
        out.append(name)
    return out


@pytest.mark.parametrize("name", _runnable_symbols())
def test_functional_accepts_numpy_inputs(name):
    examples = _examples_for(name)
    try:
        with_jax = _run_examples(examples, jnp)
    except ModuleNotFoundError as err:  # optional dependency gate
        pytest.skip(f"optional dependency missing: {err}")
    with_numpy = _run_examples(examples, _NumpyCtorShim())
    _assert_trees_match(with_jax, with_numpy, name)


@pytest.mark.parametrize(
    "name,args",
    [
        ("pairwise_cosine_similarity", ([[2.0, 3.0], [3.0, 5.0], [5.0, 8.0]],)),
        ("pairwise_euclidean_distance", ([[2.0, 3.0], [3.0, 5.0], [5.0, 8.0]],)),
        ("pairwise_linear_similarity", ([[2.0, 3.0], [3.0, 5.0], [5.0, 8.0]],)),
        ("pairwise_manhattan_distance", ([[2.0, 3.0], [3.0, 5.0], [5.0, 8.0]],)),
        ("accuracy", ([0, 1, 1, 0], [0, 1, 0, 0])),
    ],
)
def test_functional_accepts_python_lists(name, args):
    """Where an input-conversion layer exists (pairwise ``_check_pairwise_input``,
    the classification input-format engine), nested python lists must convert
    rather than crash. Regression metrics mirror the reference in requiring
    array inputs (reference `_check_same_shape` would raise on lists too)."""
    fn = getattr(functional, name)
    got = fn(*args)
    want = fn(*(jnp.asarray(a) for a in args))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6, rtol=1e-5)


def test_pairwise_numpy_zero_diagonal_regression():
    """The round-4 crash: one-argument numpy input hits the zero-diagonal
    ``.at[]`` path. Must produce the same matrix as the jax-input call."""
    rng = np.random.RandomState(0)
    x = rng.rand(6, 4).astype(np.float32)
    for fname in (
        "pairwise_cosine_similarity",
        "pairwise_euclidean_distance",
        "pairwise_linear_similarity",
        "pairwise_manhattan_distance",
    ):
        fn = getattr(functional, fname)
        got = fn(x)  # zero_diagonal defaults to True in the one-argument form
        want = fn(jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)
        assert float(np.asarray(got)[np.arange(6), np.arange(6)].max()) == 0.0
        got2 = fn(x, x.copy(), zero_diagonal=True)
        np.testing.assert_allclose(
            np.asarray(got2)[np.arange(6), np.arange(6)], np.zeros(6), atol=1e-6
        )
