"""Pairwise distance functions vs sklearn."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics.pairwise import (
    cosine_similarity as sk_cosine,
    euclidean_distances as sk_euclidean,
    linear_kernel as sk_linear,
    manhattan_distances as sk_manhattan,
)

from metrics_tpu.functional import (
    pairwise_cosine_similarity,
    pairwise_euclidean_distance,
    pairwise_linear_similarity,
    pairwise_manhattan_distance,
)

_rng = np.random.RandomState(5)
_x = jnp.asarray(_rng.rand(12, 6).astype(np.float32))
_y = jnp.asarray(_rng.rand(8, 6).astype(np.float32))

_CASES = [
    (pairwise_cosine_similarity, sk_cosine),
    (pairwise_euclidean_distance, sk_euclidean),
    (pairwise_linear_similarity, sk_linear),
    (pairwise_manhattan_distance, sk_manhattan),
]


@pytest.mark.parametrize("fn, sk_fn", _CASES)
def test_pairwise_two_inputs(fn, sk_fn):
    np.testing.assert_allclose(np.asarray(fn(_x, _y)), sk_fn(np.asarray(_x), np.asarray(_y)), atol=1e-5)


@pytest.mark.parametrize("fn, sk_fn", _CASES)
def test_pairwise_single_input_zero_diagonal(fn, sk_fn):
    res = np.asarray(fn(_x))
    ref = sk_fn(np.asarray(_x))
    np.fill_diagonal(ref, 0)
    np.testing.assert_allclose(res, ref, atol=1e-5)


@pytest.mark.parametrize("fn, sk_fn", _CASES)
@pytest.mark.parametrize("reduction", ["mean", "sum"])
def test_pairwise_reductions(fn, sk_fn, reduction):
    ref = sk_fn(np.asarray(_x), np.asarray(_y))
    ref = ref.mean(-1) if reduction == "mean" else ref.sum(-1)
    np.testing.assert_allclose(np.asarray(fn(_x, _y, reduction=reduction)), ref, atol=1e-4)


@pytest.mark.parametrize("fn, sk_fn", _CASES)
def test_pairwise_jit(fn, sk_fn):
    jitted = jax.jit(fn)
    np.testing.assert_allclose(np.asarray(jitted(_x, _y)), np.asarray(fn(_x, _y)), atol=1e-6)


def test_pairwise_invalid_inputs():
    with pytest.raises(ValueError, match="Expected argument `x`"):
        pairwise_cosine_similarity(jnp.zeros(3))
    with pytest.raises(ValueError, match="Expected argument `y`"):
        pairwise_cosine_similarity(_x, jnp.zeros((3, 2)))
    with pytest.raises(ValueError, match="Expected reduction"):
        pairwise_cosine_similarity(_x, _y, reduction="bogus")
