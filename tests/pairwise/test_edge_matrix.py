"""Pairwise distance corner cases vs the mounted reference.

Zero vectors, duplicate rows (zero-diagonal semantics), single-row inputs,
and the reduction surface — identical matrices through both stacks.
"""
from __future__ import annotations

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
pytestmark = pytest.mark.skipif(_ref is None, reason="reference mount unavailable")

import metrics_tpu.functional as F  # noqa: E402

RNG = np.random.RandomState(47)
X = RNG.randn(6, 5).astype(np.float32)
Y = RNG.randn(4, 5).astype(np.float32)

_FNS = [
    "pairwise_cosine_similarity",
    "pairwise_euclidean_distance",
    "pairwise_manhattan_distance",
    "pairwise_linear_similarity",
]


def _close(ours, theirs, atol=1e-4):
    np.testing.assert_allclose(
        np.asarray(ours, np.float64), theirs.numpy().astype(np.float64), atol=atol, rtol=1e-4, equal_nan=True
    )


@pytest.mark.parametrize("fn", _FNS)
def test_two_input_parity(fn):
    _close(getattr(F, fn)(jnp.asarray(X), jnp.asarray(Y)), getattr(_ref.functional, fn)(torch.tensor(X), torch.tensor(Y)))


@pytest.mark.parametrize("fn", _FNS)
def test_single_input_zero_diagonal(fn):
    _close(getattr(F, fn)(jnp.asarray(X)), getattr(_ref.functional, fn)(torch.tensor(X)))


@pytest.mark.parametrize("fn", _FNS)
@pytest.mark.parametrize("reduction", ["mean", "sum", "none"])
def test_reductions(fn, reduction):
    _close(
        getattr(F, fn)(jnp.asarray(X), jnp.asarray(Y), reduction=reduction),
        getattr(_ref.functional, fn)(torch.tensor(X), torch.tensor(Y), reduction=reduction),
    )


@pytest.mark.parametrize("fn", _FNS)
def test_zero_vector_rows(fn):
    """A zero row makes cosine 0/0 — both stacks must agree cell-for-cell."""
    x = X.copy()
    x[0] = 0.0
    _close(getattr(F, fn)(jnp.asarray(x), jnp.asarray(Y)), getattr(_ref.functional, fn)(torch.tensor(x), torch.tensor(Y)))


@pytest.mark.parametrize("fn", _FNS)
def test_duplicate_rows(fn):
    """Identical rows across the two inputs: exact zeros / perfect similarity."""
    y = np.concatenate([X[:2], Y[:2]], axis=0)
    _close(getattr(F, fn)(jnp.asarray(X), jnp.asarray(y)), getattr(_ref.functional, fn)(torch.tensor(X), torch.tensor(y)))


@pytest.mark.parametrize("fn", _FNS)
def test_single_row_each(fn):
    _close(
        getattr(F, fn)(jnp.asarray(X[:1]), jnp.asarray(Y[:1])),
        getattr(_ref.functional, fn)(torch.tensor(X[:1]), torch.tensor(Y[:1])),
    )


def test_invalid_ndim_rejected_in_both():
    with pytest.raises(ValueError):
        F.pairwise_cosine_similarity(jnp.zeros((2, 3, 4)))
    with pytest.raises(ValueError):
        _ref.functional.pairwise_cosine_similarity(torch.zeros(2, 3, 4))


def test_bad_reduction_rejected_in_both():
    with pytest.raises(ValueError):
        F.pairwise_euclidean_distance(jnp.asarray(X), reduction="bogus")
    with pytest.raises(ValueError):
        _ref.functional.pairwise_euclidean_distance(torch.tensor(X), reduction="bogus")
