"""Fuzz-parity wave 2: wrappers, composition, curves, aggregation, pairwise.

Same contract as `tests/test_fuzz_parity.py` — seeded random variations
streamed batch-identically through ours and the mounted reference — covering
the families the first wave skipped: L4 wrappers, CompositionalMetric
arithmetic, exact curve outputs, nan-strategy aggregation, and the pairwise
functionals.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
pytestmark = [pytest.mark.skipif(_ref is None, reason="reference mount unavailable"),
              pytest.mark.slow]  # deep-coverage tier (see docs/testing.md)

import metrics_tpu as mt  # noqa: E402
import metrics_tpu.functional as F  # noqa: E402

N_VARIATIONS = 3


from tests.helpers import assert_tree_close as _assert_tree_close  # noqa: E402


@pytest.mark.parametrize("seed", range(N_VARIATIONS))
def test_minmax_wrapper_fuzz(seed):
    rng = np.random.RandomState(seed)
    ours = mt.MinMaxMetric(mt.Accuracy(num_classes=4))
    ref = _ref.MinMaxMetric(_ref.Accuracy(num_classes=4))
    for _ in range(4):
        p = rng.rand(32, 4).astype(np.float32)
        p /= p.sum(1, keepdims=True)
        t = rng.randint(0, 4, 32)
        ours.update(jnp.asarray(p), jnp.asarray(t))
        ref.update(torch.tensor(p), torch.tensor(t))
        # forward-style interleaved compute exercises min/max tracking
        _assert_tree_close(ours.compute(), {k: v for k, v in ref.compute().items()})


@pytest.mark.parametrize("seed", range(N_VARIATIONS))
def test_multioutput_wrapper_fuzz(seed):
    rng = np.random.RandomState(10 + seed)
    ours = mt.MultioutputWrapper(mt.MeanSquaredError(), num_outputs=3)
    ref = _ref.MultioutputWrapper(_ref.MeanSquaredError(), num_outputs=3)
    for _ in range(3):
        p = rng.randn(16, 3).astype(np.float32)
        t = rng.randn(16, 3).astype(np.float32)
        ours.update(jnp.asarray(p), jnp.asarray(t))
        ref.update(torch.tensor(p), torch.tensor(t))
    _assert_tree_close(list(ours.compute()), list(ref.compute()))


@pytest.mark.parametrize("seed", range(N_VARIATIONS))
def test_classwise_wrapper_fuzz(seed):
    rng = np.random.RandomState(20 + seed)
    ours = mt.ClasswiseWrapper(mt.Precision(num_classes=4, average="none"))
    ref = _ref.ClasswiseWrapper(_ref.Precision(num_classes=4, average="none"))
    for _ in range(3):
        p = rng.rand(32, 4).astype(np.float32)
        p /= p.sum(1, keepdims=True)
        t = rng.randint(0, 4, 32)
        ours.update(jnp.asarray(p), jnp.asarray(t))
        ref.update(torch.tensor(p), torch.tensor(t))
    _assert_tree_close(ours.compute(), ref.compute())


@pytest.mark.parametrize("seed", range(N_VARIATIONS))
def test_tracker_fuzz(seed):
    rng = np.random.RandomState(30 + seed)
    ours = mt.MetricTracker(mt.Accuracy(num_classes=3), maximize=True)
    ref = _ref.MetricTracker(_ref.Accuracy(num_classes=3), maximize=True)
    for _step in range(3):
        ours.increment()
        ref.increment()
        for _ in range(2):
            p = rng.rand(16, 3).astype(np.float32)
            p /= p.sum(1, keepdims=True)
            t = rng.randint(0, 3, 16)
            ours.update(jnp.asarray(p), jnp.asarray(t))
            ref.update(torch.tensor(p), torch.tensor(t))
    _assert_tree_close(ours.compute_all(), ref.compute_all())
    # best_metric: the reference unpacks torch.max(t, 0) as (idx, best), so its
    # "best" is actually the argmax INDEX (upstream bug, fixed in later
    # torchmetrics). Assert our documented contract — the actual best value —
    # against the history the reference agrees on.
    history = np.asarray(ref.compute_all().numpy())
    np.testing.assert_allclose(float(ours.best_metric()), history.max(), atol=1e-6)
    best_val, best_step = ours.best_metric(return_step=True)
    assert history.argmax() == best_step and float(best_val) == pytest.approx(history.max())


@pytest.mark.parametrize("seed", range(N_VARIATIONS))
@pytest.mark.parametrize("expr", ["add", "mul", "div", "abs_diff"])
def test_composition_fuzz(expr, seed):
    rng = np.random.RandomState(40 + seed)

    def build(mod):
        a = mod.Precision(num_classes=3)
        b = mod.Recall(num_classes=3)
        if expr == "add":
            return a + b
        if expr == "mul":
            return a * b
        if expr == "div":
            return a / (b + 1.0)
        return abs(a - b)

    ours, ref = build(mt), build(_ref)
    for _ in range(3):
        p = rng.rand(24, 3).astype(np.float32)
        p /= p.sum(1, keepdims=True)
        t = rng.randint(0, 3, 24)
        ours.update(jnp.asarray(p), jnp.asarray(t))
        ref.update(torch.tensor(p), torch.tensor(t))
    np.testing.assert_allclose(float(ours.compute()), float(ref.compute()), atol=1e-5)


@pytest.mark.parametrize("seed", range(N_VARIATIONS))
@pytest.mark.parametrize("metric", ["PrecisionRecallCurve", "ROC"])
def test_exact_curve_outputs_fuzz(metric, seed):
    """Full curve arrays (not just areas) match the reference point-for-point."""
    rng = np.random.RandomState(50 + seed)
    preds = np.round(rng.rand(80), 2).astype(np.float32)  # ties on purpose
    target = (rng.rand(80) > 0.5).astype(np.int64)
    ours = getattr(mt, metric)()
    ref = getattr(_ref, metric)()
    ours.update(jnp.asarray(preds), jnp.asarray(target))
    ref.update(torch.tensor(preds), torch.tensor(target))
    for x, y in zip(ours.compute(), ref.compute()):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y.numpy()), atol=1e-6)


@pytest.mark.parametrize("seed", range(N_VARIATIONS))
@pytest.mark.parametrize("agg,kwargs", [
    ("MeanMetric", {"nan_strategy": "ignore"}),
    ("SumMetric", {"nan_strategy": "ignore"}),
    ("MaxMetric", {"nan_strategy": "ignore"}),
    ("MinMetric", {"nan_strategy": "ignore"}),
    ("CatMetric", {"nan_strategy": "ignore"}),
    ("MeanMetric", {"nan_strategy": 0.0}),
])
def test_aggregation_nan_fuzz(agg, kwargs, seed):
    rng = np.random.RandomState(60 + seed)
    ours = getattr(mt, agg)(**kwargs)
    ref = getattr(_ref, agg)(**kwargs)
    for _ in range(3):
        v = rng.randn(16).astype(np.float32)
        v[rng.rand(16) < 0.2] = np.nan
        ours.update(jnp.asarray(v))
        ref.update(torch.tensor(v))
    _assert_tree_close(ours.compute(), ref.compute())


@pytest.mark.parametrize("seed", range(N_VARIATIONS))
@pytest.mark.parametrize("fn,reduction", [
    ("pairwise_cosine_similarity", None),
    ("pairwise_euclidean_distance", "mean"),
    ("pairwise_manhattan_distance", "sum"),
    ("pairwise_linear_similarity", None),
])
def test_pairwise_fuzz(fn, reduction, seed):
    import torchmetrics.functional as RF

    rng = np.random.RandomState(70 + seed)
    x = rng.randn(int(rng.randint(3, 9)), 6).astype(np.float32)
    y = rng.randn(int(rng.randint(3, 9)), 6).astype(np.float32)
    ours = getattr(F, fn)(jnp.asarray(x), jnp.asarray(y), reduction=reduction)
    ref = getattr(RF, fn)(torch.tensor(x), torch.tensor(y), reduction=reduction)
    np.testing.assert_allclose(np.asarray(ours), ref.numpy(), atol=1e-5, rtol=1e-4)


@pytest.mark.parametrize("seed", range(N_VARIATIONS))
@pytest.mark.parametrize("metric,kwargs", [
    ("HammingDistance", {}),
    ("StatScores", {"num_classes": 4, "reduce": "macro", "mdmc_reduce": "global"}),
    ("HingeLoss", {}),
    ("AUC", {"reorder": True}),
])
def test_classification_extras_fuzz(metric, kwargs, seed):
    rng = np.random.RandomState(80 + seed)
    ours = getattr(mt, metric)(**kwargs)
    ref = getattr(_ref, metric)(**kwargs)
    for _ in range(3):
        if metric == "AUC":
            x = np.sort(rng.rand(16)).astype(np.float32)
            y = rng.rand(16).astype(np.float32)
            ours.update(jnp.asarray(x), jnp.asarray(y))
            ref.update(torch.tensor(x), torch.tensor(y))
        elif metric == "HingeLoss":
            p = rng.rand(24).astype(np.float32)
            t = rng.randint(0, 2, 24)
            ours.update(jnp.asarray(p), jnp.asarray(t))
            ref.update(torch.tensor(p), torch.tensor(t))
        elif metric == "StatScores":
            p = rng.rand(24, 4).astype(np.float32)
            p /= p.sum(1, keepdims=True)
            t = rng.randint(0, 4, 24)
            ours.update(jnp.asarray(p), jnp.asarray(t))
            ref.update(torch.tensor(p), torch.tensor(t))
        else:
            p = (rng.rand(24, 4) > 0.5).astype(np.int64)
            t = (rng.rand(24, 4) > 0.5).astype(np.int64)
            ours.update(jnp.asarray(p), jnp.asarray(t))
            ref.update(torch.tensor(p), torch.tensor(t))
    _assert_tree_close(ours.compute(), ref.compute())
