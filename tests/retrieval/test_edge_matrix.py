"""Constructed retrieval corner cases vs the mounted reference.

The grouping engine's deliberate degenerate inputs: queries with no positive
documents crossed with every `empty_target_action`, all-positive queries,
single-document queries, heavily tied scores, and `ignore_index` row
filtering — each cell runs identical data through both stacks.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
pytestmark = pytest.mark.skipif(_ref is None, reason="reference mount unavailable")

import metrics_tpu as mt  # noqa: E402

_METRICS = ["RetrievalMAP", "RetrievalMRR", "RetrievalNormalizedDCG", "RetrievalHitRate", "RetrievalRPrecision"]


def _run_pair(name, idx, preds, target, our_kwargs=None, ref_kwargs=None):
    our_kwargs = our_kwargs or {}
    ours = getattr(mt, name)(**our_kwargs)
    ref = getattr(_ref, name)(**(ref_kwargs if ref_kwargs is not None else our_kwargs))
    ours.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(idx))
    ref.update(torch.tensor(preds), torch.tensor(target), indexes=torch.tensor(idx))
    ours_val, ref_val = ours.compute(), ref.compute()
    np.testing.assert_allclose(np.asarray(ours_val), np.asarray(ref_val), atol=1e-6)


RNG = np.random.RandomState(3)
# query 0: no positives; query 1: mixed; query 2: all positive; query 3: single doc
IDX = np.asarray([0, 0, 0, 1, 1, 1, 1, 2, 2, 3], dtype=np.int64)
PREDS = RNG.rand(10).astype(np.float32)
TARGET = np.asarray([0, 0, 0, 1, 0, 1, 0, 1, 1, 1], dtype=np.int64)


class TestEmptyTargetAction:
    @pytest.mark.parametrize("metric", _METRICS)
    @pytest.mark.parametrize("action", ["skip", "neg", "pos"])
    def test_matches_reference(self, metric, action):
        _run_pair(metric, IDX, PREDS, TARGET, {"empty_target_action": action})

    @pytest.mark.parametrize("metric", _METRICS)
    def test_error_action_raises_in_both(self, metric):
        ours = getattr(mt, metric)(empty_target_action="error")
        ref = getattr(_ref, metric)(empty_target_action="error")
        ours.update(jnp.asarray(PREDS), jnp.asarray(TARGET), indexes=jnp.asarray(IDX))
        ref.update(torch.tensor(PREDS), torch.tensor(TARGET), indexes=torch.tensor(IDX))
        with pytest.raises(ValueError):
            ours.compute()
        with pytest.raises(ValueError):
            ref.compute()

    def test_all_queries_empty_skip(self):
        """Every query empty + skip: the reference returns 0.0."""
        idx = np.asarray([0, 0, 1, 1], dtype=np.int64)
        preds = RNG.rand(4).astype(np.float32)
        target = np.zeros(4, dtype=np.int64)
        _run_pair("RetrievalMAP", idx, preds, target, {"empty_target_action": "skip"})


class TestDegenerateGroups:
    @pytest.mark.parametrize("metric", _METRICS)
    def test_single_document_queries(self, metric):
        idx = np.arange(6, dtype=np.int64)  # six queries of one doc each
        preds = RNG.rand(6).astype(np.float32)
        target = np.asarray([1, 0, 1, 1, 0, 1], dtype=np.int64)
        _run_pair(metric, idx, preds, target, {"empty_target_action": "skip"})

    @pytest.mark.parametrize("metric", _METRICS)
    def test_fully_tied_scores(self, metric):
        """All scores identical: ranking is order-of-appearance in both stacks."""
        idx = np.asarray([0] * 6 + [1] * 6, dtype=np.int64)
        preds = np.full(12, 0.5, dtype=np.float32)
        target = np.asarray([1, 0, 0, 1, 0, 1] * 2, dtype=np.int64)
        _run_pair(metric, idx, preds, target)

    @pytest.mark.parametrize("metric", _METRICS)
    def test_interleaved_query_ids(self, metric):
        """Group ids arrive interleaved, unsorted, and non-contiguous."""
        idx = np.asarray([7, 2, 7, 2, 7, 9, 2, 9], dtype=np.int64)
        preds = RNG.rand(8).astype(np.float32)
        target = np.asarray([1, 0, 0, 1, 1, 1, 0, 0], dtype=np.int64)
        _run_pair(metric, idx, preds, target)


class TestIgnoreIndex:
    @pytest.mark.parametrize("metric", _METRICS)
    def test_rows_filtered(self, metric):
        """Rows whose target equals ignore_index drop before grouping."""
        idx = np.asarray([0, 0, 0, 1, 1, 1], dtype=np.int64)
        preds = RNG.rand(6).astype(np.float32)
        target = np.asarray([1, -1, 0, -1, 1, 0], dtype=np.int64)
        _run_pair(metric, idx, preds, target, {"ignore_index": -1, "empty_target_action": "skip"})

    def test_ignoring_everything_raises_in_both(self):
        """ignore_index filtering happens before the non-empty check: removing
        every row raises at update in both stacks."""
        idx = np.asarray([0, 0], dtype=np.int64)
        preds = RNG.rand(2).astype(np.float32)
        target = np.asarray([-1, -1], dtype=np.int64)
        with pytest.raises(ValueError, match="non-empty"):
            mt.RetrievalMAP(ignore_index=-1).update(
                jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(idx)
            )
        with pytest.raises(ValueError, match="non-empty"):
            _ref.RetrievalMAP(ignore_index=-1).update(
                torch.tensor(preds), torch.tensor(target), indexes=torch.tensor(idx)
            )


class TestValidationParity:
    @pytest.mark.parametrize("metric", ["RetrievalMAP", "RetrievalMRR", "RetrievalPrecision", "RetrievalRecall", "RetrievalHitRate"])
    def test_float_relevance_in_unit_interval_accepted(self, metric):
        """The reference allows FLOAT relevance targets whose values lie in
        [0, 1] (its binary check constrains values, not dtype); AP/MRR
        binarize via > 0, precision/recall sum raw values. Same data, same
        numbers, both stacks."""
        idx = np.asarray([0, 0, 0, 1, 1, 1], dtype=np.int64)
        preds = RNG.rand(6).astype(np.float32)
        target = np.asarray([0.3, 0.0, 0.7, 1.0, 0.0, 0.5], dtype=np.float32)
        _run_pair(metric, idx, preds, target)

    def test_float_target_above_one_rejected_in_both(self):
        preds = jnp.asarray([0.5, 0.2])
        bad = jnp.asarray([1.5, 0.7])
        with pytest.raises(ValueError, match="binary"):
            mt.RetrievalMAP().update(preds, bad, indexes=jnp.asarray([0, 0]))
        with pytest.raises(ValueError, match="binary"):
            _ref.RetrievalMAP().update(
                torch.tensor([0.5, 0.2]), torch.tensor([1.5, 0.7]), indexes=torch.tensor([0, 0])
            )

    def test_missing_indexes_rejected_in_both(self):
        with pytest.raises(ValueError):
            mt.RetrievalMAP().update(jnp.asarray([0.5]), jnp.asarray([1]), indexes=None)
        with pytest.raises(ValueError):
            _ref.RetrievalMAP().update(torch.tensor([0.5]), torch.tensor([1]), indexes=None)


def test_fall_out_float_relevance_raw_semantics():
    """FallOut with graded float targets uses RAW 1 - relevance (reference
    `fall_out.py:56`): partial relevance contributes partial non-relevance —
    module, functional, and reference must all agree (review regression)."""
    idx = np.asarray([0, 0], dtype=np.int64)
    preds = np.asarray([0.9, 0.1], dtype=np.float32)
    target = np.asarray([0.5, 0.0], dtype=np.float32)
    _run_pair("RetrievalFallOut", idx, preds, target, {"k": 1})
    from metrics_tpu.functional import retrieval_fall_out

    ours_fn = float(retrieval_fall_out(jnp.asarray(preds), jnp.asarray(target), k=1))
    module = mt.RetrievalFallOut(k=1)
    module.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(idx))
    assert ours_fn == pytest.approx(float(module.compute()), abs=1e-6)


def test_r_precision_float_relevance_binarizes():
    """RPrecision defines graded float relevance by binarizing hits via > 0
    (documented divergence: the reference raises a TypeError indexing with a
    float R on the same input). Module and functional must agree with the
    integer-binarized ground truth."""
    idx = np.asarray([0, 0, 0, 1, 1, 1], dtype=np.int64)
    preds = RNG.rand(6).astype(np.float32)
    graded = np.asarray([0.3, 0.0, 0.7, 1.0, 0.0, 0.5], dtype=np.float32)
    binary = (graded > 0).astype(np.int64)

    want = mt.RetrievalRPrecision()
    want.update(jnp.asarray(preds), jnp.asarray(binary), indexes=jnp.asarray(idx))

    got = mt.RetrievalRPrecision()
    got.update(jnp.asarray(preds), jnp.asarray(graded), indexes=jnp.asarray(idx))
    np.testing.assert_allclose(float(got.compute()), float(want.compute()), atol=1e-6)

    from metrics_tpu.functional import retrieval_r_precision

    fn_graded = float(retrieval_r_precision(jnp.asarray(preds[:3]), jnp.asarray(graded[:3])))
    fn_binary = float(retrieval_r_precision(jnp.asarray(preds[:3]), jnp.asarray(binary[:3])))
    assert fn_graded == pytest.approx(fn_binary, abs=1e-6)
