"""Differential retrieval tests vs the mounted reference, focused on the
k-vs-document-count edge cases (precision divides by k itself unless
adaptive_k; curves keep max_k entries with decaying precision)."""
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
pytestmark = pytest.mark.skipif(_ref is None, reason="reference mount unavailable")

import metrics_tpu as mt  # noqa: E402
import metrics_tpu.functional as mf  # noqa: E402

_rng = np.random.RandomState(11)
# 6 queries with group sizes 3..8 — smaller than some k values below
_SIZES = [3, 4, 5, 6, 7, 8]
_IDX = np.concatenate([np.full(s, i) for i, s in enumerate(_SIZES)])
_PREDS = _rng.rand(_IDX.size).astype(np.float32)
_TARGET = (_rng.rand(_IDX.size) > 0.4).astype(np.int64)


def _run_module(ours_cls, ref_cls, **kwargs):
    ours = ours_cls(**kwargs)
    ref = ref_cls(**kwargs)
    ours.update(jnp.asarray(_PREDS), jnp.asarray(_TARGET), indexes=jnp.asarray(_IDX))
    ref.update(torch.tensor(_PREDS), torch.tensor(_TARGET), indexes=torch.tensor(_IDX))
    return ours.compute(), ref.compute()


@pytest.mark.parametrize("k", [1, 3, 5, 10])
@pytest.mark.parametrize("adaptive_k", [False, True])
def test_precision_k_semantics(k, adaptive_k):
    ov, rv = _run_module(mt.RetrievalPrecision, _ref.RetrievalPrecision, k=k, adaptive_k=adaptive_k)
    np.testing.assert_allclose(float(ov), float(rv), atol=1e-6)


@pytest.mark.parametrize("k", [1, 3, 5, 10])
@pytest.mark.parametrize(
    "name", ["RetrievalRecall", "RetrievalFallOut", "RetrievalHitRate", "RetrievalNormalizedDCG"]
)
def test_k_metrics(name, k):
    ov, rv = _run_module(getattr(mt, name), getattr(_ref, name), k=k)
    np.testing.assert_allclose(float(ov), float(rv), atol=1e-6)


@pytest.mark.parametrize("name", ["RetrievalMAP", "RetrievalMRR", "RetrievalRPrecision"])
def test_rankless_metrics(name):
    ov, rv = _run_module(getattr(mt, name), getattr(_ref, name))
    np.testing.assert_allclose(float(ov), float(rv), atol=1e-6)


@pytest.mark.parametrize("max_k", [2, 5, 12])
@pytest.mark.parametrize("adaptive_k", [False, True])
def test_curve_parity(max_k, adaptive_k):
    ov, rv = _run_module(
        mt.RetrievalPrecisionRecallCurve, _ref.RetrievalPrecisionRecallCurve, max_k=max_k, adaptive_k=adaptive_k
    )
    for o, r in zip(ov[:2], rv[:2]):
        np.testing.assert_allclose(np.asarray(o), r.numpy(), atol=1e-6)


@pytest.mark.parametrize("min_precision", [0.2, 0.5, 0.8])
def test_recall_at_fixed_precision(min_precision):
    ov, rv = _run_module(
        mt.RetrievalRecallAtFixedPrecision,
        _ref.RetrievalRecallAtFixedPrecision,
        min_precision=min_precision,
        max_k=10,
    )
    np.testing.assert_allclose(float(ov[0]), float(rv[0]), atol=1e-6)
    assert int(ov[1]) == int(rv[1])


@pytest.mark.parametrize("k", [2, 9])
@pytest.mark.parametrize("adaptive_k", [False, True])
def test_functional_precision_parity(k, adaptive_k):
    p, t = _PREDS[:5], _TARGET[:5]
    ov = mf.retrieval_precision(jnp.asarray(p), jnp.asarray(t), k=k, adaptive_k=adaptive_k)
    rv = _ref.functional.retrieval_precision(torch.tensor(p), torch.tensor(t), k=k, adaptive_k=adaptive_k)
    np.testing.assert_allclose(float(ov), float(rv), atol=1e-6)


@pytest.mark.parametrize("max_k", [3, 9])
@pytest.mark.parametrize("adaptive_k", [False, True])
def test_functional_curve_parity(max_k, adaptive_k):
    p, t = _PREDS[:5], _TARGET[:5]
    op, orc, ok = mf.retrieval_precision_recall_curve(
        jnp.asarray(p), jnp.asarray(t), max_k=max_k, adaptive_k=adaptive_k
    )
    rp, rr, rk = _ref.functional.retrieval_precision_recall_curve(
        torch.tensor(p), torch.tensor(t), max_k=max_k, adaptive_k=adaptive_k
    )
    np.testing.assert_allclose(np.asarray(op), rp.numpy(), atol=1e-6)
    np.testing.assert_allclose(np.asarray(orc), rr.numpy(), atol=1e-6)
    np.testing.assert_array_equal(np.asarray(ok), rk.numpy())
