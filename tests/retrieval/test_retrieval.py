"""Retrieval metrics vs sklearn/hand-numpy per-group oracles."""
import jax.numpy as jnp
import numpy as np
import pytest
import sklearn.metrics as skm

from metrics_tpu import (
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalPrecisionRecallCurve,
    RetrievalRecall,
    RetrievalRecallAtFixedPrecision,
    RetrievalRPrecision,
)
from metrics_tpu.functional import (
    retrieval_average_precision,
    retrieval_fall_out,
    retrieval_hit_rate,
    retrieval_normalized_dcg,
    retrieval_precision,
    retrieval_r_precision,
    retrieval_recall,
    retrieval_reciprocal_rank,
)

_rng = np.random.RandomState(21)
N = 200
_indexes = np.sort(_rng.randint(0, 10, N))
_preds = _rng.rand(N).astype(np.float32)
_target = _rng.randint(0, 2, N)


def _grouped_mean(fn, empty="skip"):
    res = []
    for g in np.unique(_indexes):
        mask = _indexes == g
        t, p = _target[mask], _preds[mask]
        if t.sum() == 0:
            if empty == "neg":
                res.append(0.0)
            elif empty == "pos":
                res.append(1.0)
            continue
        res.append(fn(p, t))
    return np.mean(res)


def _np_mrr(p, t):
    order = np.argsort(-p, kind="stable")
    rel = t[order]
    pos = np.nonzero(rel)[0]
    return 1.0 / (pos[0] + 1) if len(pos) else 0.0


def _np_precision_at(p, t, k=None):
    # reference semantics: examine min(k, n) docs but divide by k itself
    k = k or len(p)
    order = np.argsort(-p, kind="stable")
    return t[order][: min(k, len(p))].sum() / k


def _np_recall_at(p, t, k=None):
    k = k or len(p)
    k = min(k, len(p))
    order = np.argsort(-p, kind="stable")
    return t[order][:k].sum() / t.sum()


def _np_fallout_at(p, t, k=None):
    k = k or len(p)
    k = min(k, len(p))
    order = np.argsort(-p, kind="stable")
    nr = 1 - t[order]
    return nr[:k].sum() / max(nr.sum(), 1)


def _np_hit_at(p, t, k=None):
    k = k or len(p)
    k = min(k, len(p))
    order = np.argsort(-p, kind="stable")
    return float(t[order][:k].sum() > 0)


def _np_rprec(p, t):
    r = int(t.sum())
    order = np.argsort(-p, kind="stable")
    return t[order][:r].sum() / r if r else 0.0


class TestFunctionalKernels:
    @pytest.mark.slow
    def test_ap(self):
        for g in np.unique(_indexes):
            m = _indexes == g
            if _target[m].sum() == 0:
                continue
            ref = skm.average_precision_score(_target[m], _preds[m])
            res = retrieval_average_precision(jnp.asarray(_preds[m]), jnp.asarray(_target[m]))
            np.testing.assert_allclose(np.asarray(res), ref, atol=1e-5)

    def test_mrr(self):
        m = _indexes == 0
        np.testing.assert_allclose(
            np.asarray(retrieval_reciprocal_rank(jnp.asarray(_preds[m]), jnp.asarray(_target[m]))),
            _np_mrr(_preds[m], _target[m]),
            atol=1e-6,
        )

    @pytest.mark.parametrize("k", [None, 1, 3, 100])
    def test_precision_recall_fallout_hit(self, k):
        m = _indexes == 1
        p, t = _preds[m], _target[m]
        np.testing.assert_allclose(np.asarray(retrieval_precision(jnp.asarray(p), jnp.asarray(t), k=k)), _np_precision_at(p, t, k), atol=1e-6)
        np.testing.assert_allclose(np.asarray(retrieval_recall(jnp.asarray(p), jnp.asarray(t), k=k)), _np_recall_at(p, t, k), atol=1e-6)
        np.testing.assert_allclose(np.asarray(retrieval_fall_out(jnp.asarray(p), jnp.asarray(t), k=k)), _np_fallout_at(p, t, k), atol=1e-6)
        np.testing.assert_allclose(np.asarray(retrieval_hit_rate(jnp.asarray(p), jnp.asarray(t), k=k)), _np_hit_at(p, t, k), atol=1e-6)

    def test_ndcg_vs_sklearn(self):
        m = _indexes == 2
        p, t = _preds[m], _target[m]
        ref = skm.ndcg_score(t[None, :], p[None, :])
        np.testing.assert_allclose(
            np.asarray(retrieval_normalized_dcg(jnp.asarray(p), jnp.asarray(t))), ref, atol=1e-5
        )

    def test_ndcg_graded(self):
        p = jnp.asarray([0.1, 0.2, 0.3, 4.0, 70.0])
        t_graded = np.array([10, 0, 0, 1, 5])
        ref = skm.ndcg_score(t_graded[None, :], np.asarray(p)[None, :])
        np.testing.assert_allclose(
            np.asarray(retrieval_normalized_dcg(p, jnp.asarray(t_graded))), ref, atol=1e-5
        )

    def test_rprecision(self):
        m = _indexes == 3
        p, t = _preds[m], _target[m]
        np.testing.assert_allclose(
            np.asarray(retrieval_r_precision(jnp.asarray(p), jnp.asarray(t))), _np_rprec(p, t), atol=1e-6
        )

    def test_invalid_inputs(self):
        with pytest.raises(ValueError, match="same shape"):
            retrieval_precision(jnp.zeros(3), jnp.zeros(4, dtype=jnp.int32))
        with pytest.raises(ValueError, match="floats"):
            retrieval_precision(jnp.zeros(3, dtype=jnp.int32), jnp.zeros(3, dtype=jnp.int32))
        with pytest.raises(ValueError, match="positive integer"):
            retrieval_precision(jnp.zeros(3), jnp.zeros(3, dtype=jnp.int32), k=-1)


@pytest.mark.parametrize(
    "module_cls, np_fn",
    [
        (RetrievalMAP, lambda p, t: skm.average_precision_score(t, p)),
        (RetrievalMRR, _np_mrr),
        (RetrievalPrecision, _np_precision_at),
        (RetrievalRecall, _np_recall_at),
        (RetrievalHitRate, _np_hit_at),
        (RetrievalRPrecision, _np_rprec),
    ],
)
class TestRetrievalModules:
    @pytest.mark.slow
    def test_module_vs_grouped_oracle(self, module_cls, np_fn):
        m = module_cls(empty_target_action="skip")
        half = N // 2
        m.update(jnp.asarray(_preds[:half]), jnp.asarray(_target[:half]), indexes=jnp.asarray(_indexes[:half]))
        m.update(jnp.asarray(_preds[half:]), jnp.asarray(_target[half:]), indexes=jnp.asarray(_indexes[half:]))
        ref = _grouped_mean(np_fn, empty="skip")
        np.testing.assert_allclose(np.asarray(m.compute()), ref, atol=1e-5)

    def test_module_emulated_ddp(self, module_cls, np_fn):
        from tests.helpers.testers import _FakeGather

        ranks = [module_cls(empty_target_action="skip") for _ in range(2)]
        half = N // 2
        ranks[0].update(jnp.asarray(_preds[:half]), jnp.asarray(_target[:half]), indexes=jnp.asarray(_indexes[:half]))
        ranks[1].update(jnp.asarray(_preds[half:]), jnp.asarray(_target[half:]), indexes=jnp.asarray(_indexes[half:]))
        gather = _FakeGather(ranks)
        with ranks[0].sync_context(dist_sync_fn=gather, distributed_available=lambda: True):
            value = ranks[0]._inner_compute()
        ref = _grouped_mean(np_fn, empty="skip")
        np.testing.assert_allclose(np.asarray(value), ref, atol=1e-5)


def test_fallout_module():
    m = RetrievalFallOut(empty_target_action="skip")
    m.update(jnp.asarray(_preds), jnp.asarray(_target), indexes=jnp.asarray(_indexes))
    res = []
    for g in np.unique(_indexes):
        mask = _indexes == g
        t, p = _target[mask], _preds[mask]
        if (1 - t).sum() == 0:
            continue
        res.append(_np_fallout_at(p, t))
    np.testing.assert_allclose(np.asarray(m.compute()), np.mean(res), atol=1e-5)


def test_empty_target_actions():
    idx = jnp.asarray([0, 0, 1, 1])
    p = jnp.asarray([0.5, 0.3, 0.2, 0.8])
    t = jnp.asarray([0, 0, 1, 0])  # group 0 has no positives

    m = RetrievalMAP(empty_target_action="error")
    m.update(p, t, indexes=idx)
    with pytest.raises(ValueError, match="no positive"):
        m.compute()

    for action, expected_g0 in [("neg", 0.0), ("pos", 1.0)]:
        m = RetrievalMAP(empty_target_action=action)
        m.update(p, t, indexes=idx)
        g1 = skm.average_precision_score([1, 0], [0.2, 0.8])
        np.testing.assert_allclose(np.asarray(m.compute()), np.mean([expected_g0, g1]), atol=1e-6)

    with pytest.raises(ValueError, match="wrong value"):
        RetrievalMAP(empty_target_action="bogus")


def test_ignore_index_filters_rows():
    idx = jnp.asarray([0, 0, 0, 0])
    p = jnp.asarray([0.9, 0.7, 0.5, 0.3])
    t = jnp.asarray([1, -1, 0, 1])
    m = RetrievalMAP(ignore_index=-1)
    m.update(p, t, indexes=idx)
    ref = skm.average_precision_score([1, 0, 1], [0.9, 0.5, 0.3])
    np.testing.assert_allclose(np.asarray(m.compute()), ref, atol=1e-6)


def test_retrieval_pr_curve_and_recall_at_precision():
    idx = jnp.asarray([0] * 6 + [1] * 6)
    p = jnp.asarray(_rng.rand(12).astype(np.float32))
    t = jnp.asarray([1, 0, 1, 0, 1, 0, 0, 1, 0, 1, 0, 1])
    m = RetrievalPrecisionRecallCurve(max_k=4)
    m.update(p, t, indexes=idx)
    prec, rec, top_k = m.compute()
    assert prec.shape == rec.shape == (4,)
    ref_p = np.mean(
        [[_np_precision_at(np.asarray(p[s]), np.asarray(t[s]), k) for k in range(1, 5)] for s in (slice(0, 6), slice(6, 12))],
        axis=0,
    )
    np.testing.assert_allclose(np.asarray(prec), ref_p, atol=1e-5)

    m2 = RetrievalRecallAtFixedPrecision(min_precision=0.3, max_k=4)
    m2.update(p, t, indexes=idx)
    best_r, best_k = m2.compute()
    assert 0.0 <= float(best_r) <= 1.0
    assert 1 <= int(best_k) <= 4


def test_indexes_required():
    m = RetrievalMAP()
    with pytest.raises(ValueError, match="cannot be None"):
        m.update(jnp.asarray([0.1]), jnp.asarray([1]), indexes=None)
