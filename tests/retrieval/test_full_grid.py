"""The full retrieval parametrization grid vs the mounted reference.

The reference enumerates every retrieval metric over its whole constructor
space (`tests/unittests/retrieval/helpers.py` feeding per-metric test files,
~2.2k LoC); the edge matrix here samples corners. This file closes the gap by
enumerating metric x k x adaptive_k x empty_target_action x ignore_index on
seeded streamed batches, every cell differentially checked against the
reference on identical data. Cell seeds derive from the cell coordinates so
each cell sees distinct data without a dataset multiplier.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tests.helpers import cell_seed as _cell_seed
from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
pytestmark = [pytest.mark.skipif(_ref is None, reason="reference mount unavailable"),
              pytest.mark.slow]  # deep-coverage tier (see docs/testing.md)

import metrics_tpu as mt  # noqa: E402

ACTIONS = ("skip", "neg", "pos")
IGNORE = (None, -100)
KS = (None, 1, 2, 4, 10)
N_BATCHES, BATCH = 3, 10
N_QUERIES = 6


def _make_batches(seed: int, ignore_index):
    """Streamed (indexes, preds, target) batches; plants ignored rows when asked."""
    rng = np.random.RandomState(seed)
    batches = []
    for _ in range(N_BATCHES):
        idx = rng.randint(0, N_QUERIES, size=BATCH).astype(np.int64)
        preds = rng.rand(BATCH).astype(np.float32)
        target = rng.randint(0, 2, size=BATCH).astype(np.int64)
        if ignore_index is not None:
            target[rng.rand(BATCH) < 0.25] = ignore_index
        batches.append((idx, preds, target))
    return batches


def _run_cell(name, kwargs, seed, ignore_index):
    kwargs = dict(kwargs, ignore_index=ignore_index)
    ours = getattr(mt, name)(**kwargs)
    ref = getattr(_ref, name)(**kwargs)
    for idx, preds, target in _make_batches(seed, ignore_index):
        ours.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(idx))
        ref.update(torch.tensor(preds), torch.tensor(target), indexes=torch.tensor(idx))
    ours_val, ref_val = ours.compute(), ref.compute()
    if isinstance(ours_val, tuple):
        assert len(ours_val) == len(ref_val)
        for o, r in zip(ours_val, ref_val):
            np.testing.assert_allclose(np.asarray(o), np.asarray(r), atol=1e-5)
    else:
        np.testing.assert_allclose(np.asarray(ours_val), np.asarray(ref_val), atol=1e-5)


class TestPlainMetricsGrid:
    """MAP / MRR / RPrecision: action x ignore_index."""

    @pytest.mark.parametrize("name", ["RetrievalMAP", "RetrievalMRR", "RetrievalRPrecision"])
    @pytest.mark.parametrize("action", ACTIONS)
    @pytest.mark.parametrize("ignore_index", IGNORE)
    def test_cell(self, name, action, ignore_index):
        _run_cell(name, {"empty_target_action": action}, _cell_seed(name, action, ignore_index), ignore_index)


class TestKMetricsGrid:
    """Top-k family: k x action x ignore_index for every k-accepting metric."""

    @pytest.mark.parametrize(
        "name", ["RetrievalRecall", "RetrievalFallOut", "RetrievalHitRate", "RetrievalNormalizedDCG"]
    )
    @pytest.mark.parametrize("k", KS)
    @pytest.mark.parametrize("action", ACTIONS)
    @pytest.mark.parametrize("ignore_index", IGNORE)
    def test_cell(self, name, k, action, ignore_index):
        _run_cell(
            name, {"empty_target_action": action, "k": k}, _cell_seed(name, k, action, ignore_index), ignore_index
        )


class TestPrecisionGrid:
    """RetrievalPrecision additionally crosses adaptive_k."""

    @pytest.mark.parametrize("k", KS)
    @pytest.mark.parametrize("adaptive_k", (False, True))
    @pytest.mark.parametrize("action", ACTIONS)
    @pytest.mark.parametrize("ignore_index", IGNORE)
    def test_cell(self, k, adaptive_k, action, ignore_index):
        _run_cell(
            "RetrievalPrecision",
            {"empty_target_action": action, "k": k, "adaptive_k": adaptive_k},
            _cell_seed("P", k, adaptive_k, action, ignore_index),
            ignore_index,
        )


class TestCurveGrid:
    """PrecisionRecallCurve / RecallAtFixedPrecision over max_k x adaptive_k."""

    @pytest.mark.parametrize("max_k", (None, 2, 5))
    @pytest.mark.parametrize("adaptive_k", (False, True))
    @pytest.mark.parametrize("action", ACTIONS)
    @pytest.mark.parametrize("ignore_index", IGNORE)
    def test_curve_cell(self, max_k, adaptive_k, action, ignore_index):
        _run_cell(
            "RetrievalPrecisionRecallCurve",
            {"empty_target_action": action, "max_k": max_k, "adaptive_k": adaptive_k},
            _cell_seed("PRC", max_k, adaptive_k, action, ignore_index),
            ignore_index,
        )

    @pytest.mark.parametrize("min_precision", (0.2, 0.5, 0.8))
    @pytest.mark.parametrize("max_k", (None, 5))
    @pytest.mark.parametrize("action", ACTIONS)
    @pytest.mark.parametrize("ignore_index", IGNORE)
    def test_rafp_cell(self, min_precision, max_k, action, ignore_index):
        _run_cell(
            "RetrievalRecallAtFixedPrecision",
            {"empty_target_action": action, "min_precision": min_precision, "max_k": max_k},
            _cell_seed("RAFP", min_precision, max_k, action, ignore_index),
            ignore_index,
        )
