"""Randomized cross-domain parity fuzz vs the mounted reference.

Each case draws several random (shape, config, seed) variations and streams
identical batches through our metric and the reference TorchMetrics
implementation, asserting the final computes agree. This is breadth insurance
on top of the per-domain differential banks: a config combination nobody
hand-picked still gets exercised every run.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
pytestmark = [pytest.mark.skipif(_ref is None, reason="reference mount unavailable"),
              pytest.mark.slow]  # deep-coverage tier (see docs/testing.md)

import metrics_tpu as mt  # noqa: E402

N_VARIATIONS = 3


def _agree(ours, ref, batches, atol=1e-5, rtol=1e-4):
    for ours_args, ref_args in batches:
        ours.update(*ours_args)
        ref.update(*ref_args)
    a, b = ours.compute(), ref.compute()
    flat_a = a if isinstance(a, (list, tuple)) else [a]
    flat_b = b if isinstance(b, (list, tuple)) else [b]
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=atol, rtol=rtol)


def _cls_batches(rng, n_batches, batch, num_classes, kind):
    out = []
    for _ in range(n_batches):
        if kind == "probs":
            p = rng.rand(batch, num_classes).astype(np.float32)
            p /= p.sum(1, keepdims=True)
        elif kind == "logits":
            p = rng.randn(batch, num_classes).astype(np.float32)
        else:
            p = rng.randint(0, num_classes, batch)
        t = rng.randint(0, num_classes, batch)
        out.append(((jnp.asarray(p), jnp.asarray(t)), (torch.tensor(p), torch.tensor(t))))
    return out


@pytest.mark.parametrize("seed", range(N_VARIATIONS))
@pytest.mark.parametrize(
    "name,kwargs_fn",
    [
        ("Accuracy", lambda rng, c: {"num_classes": c, "average": rng.choice(["micro", "macro", "weighted"])}),
        ("Precision", lambda rng, c: {"num_classes": c, "average": rng.choice(["micro", "macro"])}),
        ("Recall", lambda rng, c: {"num_classes": c, "average": rng.choice(["macro", "weighted"])}),
        ("F1Score", lambda rng, c: {"num_classes": c, "average": rng.choice(["micro", "macro"])}),
        ("FBetaScore", lambda rng, c: {"num_classes": c, "beta": float(rng.choice([0.5, 2.0])), "average": "macro"}),
        ("Specificity", lambda rng, c: {"num_classes": c, "average": rng.choice(["micro", "macro"])}),
        ("ConfusionMatrix", lambda rng, c: {"num_classes": c}),
        ("CohenKappa", lambda rng, c: {"num_classes": c}),
        ("MatthewsCorrCoef", lambda rng, c: {"num_classes": c}),
        ("JaccardIndex", lambda rng, c: {"num_classes": c}),
        ("CalibrationError", lambda rng, c: {"n_bins": int(rng.choice([10, 15])), "norm": rng.choice(["l1", "max"])}),
    ],
)
def test_classification_fuzz(name, kwargs_fn, seed):
    rng = np.random.RandomState(seed)
    num_classes = int(rng.randint(3, 8))
    batch = int(rng.choice([16, 33, 64]))
    n_batches = int(rng.randint(2, 5))
    kwargs = kwargs_fn(rng, num_classes)
    kind = "probs" if name == "CalibrationError" else str(rng.choice(["probs", "labels", "logits"]))
    if name == "CalibrationError":
        kwargs.pop("num_classes", None)
    ours = getattr(mt, name)(**kwargs)
    ref = getattr(_ref, name)(**kwargs)
    _agree(ours, ref, _cls_batches(rng, n_batches, batch, num_classes, kind), atol=1e-4)


@pytest.mark.parametrize("seed", range(N_VARIATIONS))
@pytest.mark.parametrize(
    "name,kwargs_fn,positive",
    [
        ("MeanSquaredError", lambda rng: {"squared": bool(rng.rand() > 0.5)}, False),
        ("MeanAbsoluteError", lambda rng: {}, False),
        ("MeanAbsolutePercentageError", lambda rng: {}, True),
        ("SymmetricMeanAbsolutePercentageError", lambda rng: {}, True),
        ("WeightedMeanAbsolutePercentageError", lambda rng: {}, True),
        ("MeanSquaredLogError", lambda rng: {}, True),
        ("ExplainedVariance", lambda rng: {"multioutput": rng.choice(["uniform_average", "variance_weighted"])}, False),
        ("R2Score", lambda rng: {"adjusted": int(rng.choice([0, 2]))}, False),
        ("PearsonCorrCoef", lambda rng: {}, False),
        ("SpearmanCorrCoef", lambda rng: {}, False),
        ("CosineSimilarity", lambda rng: {"reduction": rng.choice(["mean", "sum"])}, False),
        ("TweedieDevianceScore", lambda rng: {"power": float(rng.choice([0.0, 1.0, 1.5, 2.0]))}, True),
        ("KLDivergence", lambda rng: {}, True),
    ],
)
def test_regression_fuzz(name, kwargs_fn, positive, seed):
    rng = np.random.RandomState(100 + seed)
    kwargs = kwargs_fn(rng)
    batch = int(rng.choice([16, 33, 64]))
    n_batches = int(rng.randint(2, 5))
    two_d = name in ("CosineSimilarity", "KLDivergence")
    batches = []
    for _ in range(n_batches):
        shape = (batch, 5) if two_d else (batch,)
        p = rng.randn(*shape).astype(np.float32)
        t = (p + 0.5 * rng.randn(*shape)).astype(np.float32)
        if positive or name == "KLDivergence":
            p, t = np.abs(p) + 0.1, np.abs(t) + 0.1
        if name == "KLDivergence":
            p, t = p / p.sum(1, keepdims=True), t / t.sum(1, keepdims=True)
        batches.append(((jnp.asarray(p), jnp.asarray(t)), (torch.tensor(p), torch.tensor(t))))
    _agree(getattr(mt, name)(**kwargs), getattr(_ref, name)(**kwargs), batches, atol=1e-4)


_CORPUS = [
    "the cat sat on the mat",
    "a quick brown fox jumps over the lazy dog",
    "hello world this is a test sentence with several words",
    "jax compiles to xla which runs on tensor processing units",
    "the rain in spain stays mainly in the plain",
    "never gonna give you up never gonna let you down",
]


@pytest.mark.parametrize("seed", range(N_VARIATIONS))
@pytest.mark.parametrize(
    "name,kwargs_fn",
    [
        ("WordErrorRate", lambda rng: {}),
        ("CharErrorRate", lambda rng: {}),
        ("MatchErrorRate", lambda rng: {}),
        ("WordInfoLost", lambda rng: {}),
        ("WordInfoPreserved", lambda rng: {}),
        ("BLEUScore", lambda rng: {"n_gram": int(rng.choice([2, 3, 4]))}),
        ("CHRFScore", lambda rng: {"n_char_order": int(rng.choice([4, 6])), "n_word_order": int(rng.choice([0, 2]))}),
        ("TranslationEditRate", lambda rng: {"lowercase": bool(rng.rand() > 0.5)}),
        ("ExtendedEditDistance", lambda rng: {}),
    ],
)
def test_text_fuzz(name, kwargs_fn, seed):
    rng = np.random.RandomState(200 + seed)
    kwargs = kwargs_fn(rng)
    n = int(rng.randint(2, 5))
    idx = rng.randint(0, len(_CORPUS), size=n)
    preds = [_CORPUS[i] for i in idx]
    # targets: corrupt predictions by swapping/duplicating words
    targets = []
    for s in preds:
        words = s.split()
        if rng.rand() > 0.5 and len(words) > 2:
            j = rng.randint(0, len(words) - 1)
            words[j], words[j + 1] = words[j + 1], words[j]
        targets.append([" ".join(words), _CORPUS[rng.randint(0, len(_CORPUS))]])
    ours, ref = getattr(mt, name)(**kwargs), getattr(_ref, name)(**kwargs)
    if name in ("BLEUScore", "CHRFScore", "TranslationEditRate", "ExtendedEditDistance"):
        _agree(ours, ref, [((preds, targets), (preds, targets))], atol=1e-4)
    else:
        flat_t = [t[0] for t in targets]
        _agree(ours, ref, [((preds, flat_t), (preds, flat_t))], atol=1e-4)


@pytest.mark.parametrize("seed", range(N_VARIATIONS))
@pytest.mark.parametrize(
    "name,kwargs_fn",
    [
        ("SignalNoiseRatio", lambda rng: {"zero_mean": bool(rng.rand() > 0.5)}),
        ("ScaleInvariantSignalNoiseRatio", lambda rng: {}),
        ("ScaleInvariantSignalDistortionRatio", lambda rng: {"zero_mean": bool(rng.rand() > 0.5)}),
        ("SignalDistortionRatio", lambda rng: {}),
    ],
)
def test_audio_fuzz(name, kwargs_fn, seed):
    rng = np.random.RandomState(300 + seed)
    kwargs = kwargs_fn(rng)
    batch, length = int(rng.choice([2, 4])), int(rng.choice([256, 1000]))
    batches = []
    for _ in range(2):
        t = rng.randn(batch, length).astype(np.float32)
        p = (t + 0.3 * rng.randn(batch, length)).astype(np.float32)
        batches.append(((jnp.asarray(p), jnp.asarray(t)), (torch.tensor(p), torch.tensor(t))))
    atol = 1e-3 if name == "SignalDistortionRatio" else 1e-4
    _agree(getattr(mt, name)(**kwargs), getattr(_ref, name)(**kwargs), batches, atol=atol, rtol=1e-3)


@pytest.mark.parametrize("seed", range(N_VARIATIONS))
@pytest.mark.parametrize(
    "name,kwargs_fn",
    [
        ("PeakSignalNoiseRatio", lambda rng: {"data_range": float(rng.choice([1.0, 255.0]))}),
        ("StructuralSimilarityIndexMeasure", lambda rng: {"kernel_size": int(rng.choice([7, 11]))}),
        ("UniversalImageQualityIndex", lambda rng: {}),
        ("ErrorRelativeGlobalDimensionlessSynthesis", lambda rng: {}),
        ("SpectralAngleMapper", lambda rng: {}),
    ],
)
def test_image_fuzz(name, kwargs_fn, seed):
    rng = np.random.RandomState(400 + seed)
    kwargs = kwargs_fn(rng)
    b, c, h, w = 2, 3, int(rng.choice([24, 32])), int(rng.choice([24, 32]))
    batches = []
    for _ in range(2):
        t = rng.rand(b, c, h, w).astype(np.float32)
        p = np.clip(t + 0.1 * rng.randn(b, c, h, w), 0, 1).astype(np.float32)
        batches.append(((jnp.asarray(p), jnp.asarray(t)), (torch.tensor(p), torch.tensor(t))))
    _agree(getattr(mt, name)(**kwargs), getattr(_ref, name)(**kwargs), batches, atol=1e-4, rtol=1e-3)


@pytest.mark.parametrize("seed", range(N_VARIATIONS))
@pytest.mark.parametrize(
    "name,kwargs_fn",
    [
        ("RetrievalMAP", lambda rng: {}),
        ("RetrievalMRR", lambda rng: {}),
        ("RetrievalPrecision", lambda rng: {"k": int(rng.choice([2, 5]))}),
        ("RetrievalRecall", lambda rng: {"k": int(rng.choice([2, 5]))}),
        ("RetrievalNormalizedDCG", lambda rng: {"k": int(rng.choice([3, 5]))}),
        ("RetrievalHitRate", lambda rng: {"k": int(rng.choice([2, 4]))}),
        ("RetrievalFallOut", lambda rng: {"k": int(rng.choice([2, 4]))}),
        ("RetrievalRPrecision", lambda rng: {}),
    ],
)
def test_retrieval_fuzz(name, kwargs_fn, seed):
    rng = np.random.RandomState(500 + seed)
    kwargs = kwargs_fn(rng)
    n_queries, per_q = int(rng.randint(3, 7)), int(rng.randint(5, 12))
    n = n_queries * per_q
    indexes = np.repeat(np.arange(n_queries), per_q)
    preds = rng.rand(n).astype(np.float32)
    target = (rng.rand(n) > 0.6).astype(np.int64)
    target[::per_q] = 1  # every query has at least one positive
    ours, ref = getattr(mt, name)(**kwargs), getattr(_ref, name)(**kwargs)
    _agree(
        ours,
        ref,
        [
            (
                (jnp.asarray(preds), jnp.asarray(target), jnp.asarray(indexes)),
                (torch.tensor(preds), torch.tensor(target), torch.tensor(indexes)),
            )
        ],
        atol=1e-5,
    )
