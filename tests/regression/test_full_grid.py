"""The full regression option grid vs the mounted reference.

Enumerates every regression metric's constructor space (reference
`tests/unittests/regression/`, ~930 LoC: MSE squared, R2 num_outputs x
adjusted x multioutput, ExplainedVariance multioutput, CosineSimilarity
reductions, Tweedie powers) on seeded streamed batches, every cell
differentially checked against the reference on identical data.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tests.helpers import cell_seed as _cell_seed
from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
pytestmark = pytest.mark.skipif(_ref is None, reason="reference mount unavailable")

import metrics_tpu as mt  # noqa: E402

N_BATCHES, BATCH = 3, 16


def _make_batches(seed: int, n_outputs: int = 0, positive: bool = False):
    rng = np.random.RandomState(seed)
    shape = (BATCH, n_outputs) if n_outputs else (BATCH,)
    out = []
    for _ in range(N_BATCHES):
        preds = rng.randn(*shape).astype(np.float32)
        target = (preds + 0.5 * rng.randn(*shape)).astype(np.float32)
        if positive:
            preds, target = np.abs(preds) + 0.1, np.abs(target) + 0.1
        out.append((preds, target))
    return out


def _run_cell(name, kwargs, seed, n_outputs=0, positive=False, atol=1e-5):
    ours = getattr(mt, name)(**kwargs)
    ref = getattr(_ref, name)(**kwargs)
    for preds, target in _make_batches(seed, n_outputs, positive):
        ours.update(jnp.asarray(preds), jnp.asarray(target))
        ref.update(torch.tensor(preds), torch.tensor(target))
    np.testing.assert_allclose(np.asarray(ours.compute()), np.asarray(ref.compute()), atol=atol)


class TestOptionGrids:
    @pytest.mark.parametrize("squared", (True, False))
    def test_mse(self, squared):
        _run_cell("MeanSquaredError", {"squared": squared}, _cell_seed("mse", squared))

    @pytest.mark.parametrize("num_outputs", (1, 3))
    @pytest.mark.parametrize("adjusted", (0, 2, 5))
    @pytest.mark.parametrize("multioutput", ("raw_values", "uniform_average", "variance_weighted"))
    def test_r2(self, num_outputs, adjusted, multioutput):
        _run_cell(
            "R2Score",
            {"num_outputs": num_outputs, "adjusted": adjusted, "multioutput": multioutput},
            _cell_seed("r2", num_outputs, adjusted, multioutput),
            n_outputs=num_outputs if num_outputs > 1 else 0,
        )

    @pytest.mark.parametrize("multioutput", ("raw_values", "uniform_average", "variance_weighted"))
    @pytest.mark.parametrize("n_outputs", (0, 3))
    def test_explained_variance(self, multioutput, n_outputs):
        _run_cell(
            "ExplainedVariance",
            {"multioutput": multioutput},
            _cell_seed("ev", multioutput, n_outputs),
            n_outputs=n_outputs,
        )

    @pytest.mark.parametrize("reduction", ("mean", "sum", "none"))
    def test_cosine_similarity(self, reduction):
        _run_cell("CosineSimilarity", {"reduction": reduction}, _cell_seed("cos", reduction), n_outputs=4)

    @pytest.mark.parametrize("power", (0.0, 1.0, 1.5, 2.0, 3.0))
    def test_tweedie(self, power):
        _run_cell(
            "TweedieDevianceScore",
            {"power": power},
            _cell_seed("tweedie", power),
            positive=power > 0,
            atol=1e-4,
        )

    @pytest.mark.parametrize(
        "name",
        [
            "MeanAbsoluteError",
            "MeanAbsolutePercentageError",
            "SymmetricMeanAbsolutePercentageError",
            "WeightedMeanAbsolutePercentageError",
            "MeanSquaredLogError",
            "PearsonCorrCoef",
            "SpearmanCorrCoef",
        ],
    )
    @pytest.mark.parametrize("seed_tag", ("a", "b"))
    def test_plain(self, name, seed_tag):
        _run_cell(name, {}, _cell_seed(name, seed_tag), positive=name == "MeanSquaredLogError")


class TestStreamedEqualsOneShot:
    """Streaming accumulation equals the one-shot functional on all data.

    The reference pins this via its class-vs-functional testers; here every
    regression metric crosses it in one place.
    """

    CASES = [
        ("MeanSquaredError", "mean_squared_error", {}),
        ("MeanAbsoluteError", "mean_absolute_error", {}),
        ("MeanAbsolutePercentageError", "mean_absolute_percentage_error", {}),
        ("SymmetricMeanAbsolutePercentageError", "symmetric_mean_absolute_percentage_error", {}),
        ("WeightedMeanAbsolutePercentageError", "weighted_mean_absolute_percentage_error", {}),
        ("ExplainedVariance", "explained_variance", {}),
        ("R2Score", "r2_score", {}),
        ("PearsonCorrCoef", "pearson_corrcoef", {}),
        ("SpearmanCorrCoef", "spearman_corrcoef", {}),
        ("TweedieDevianceScore", "tweedie_deviance_score", {"power": 1.5}),
    ]

    @pytest.mark.parametrize("cls_name,fn_name,kwargs", CASES, ids=[c[0] for c in CASES])
    def test_streamed(self, cls_name, fn_name, kwargs):
        import metrics_tpu.functional as F

        positive = cls_name == "TweedieDevianceScore"
        batches = _make_batches(_cell_seed("stream", cls_name), positive=positive)
        metric = getattr(mt, cls_name)(**kwargs)
        for preds, target in batches:
            metric.update(jnp.asarray(preds), jnp.asarray(target))
        all_p = jnp.asarray(np.concatenate([p for p, _ in batches]))
        all_t = jnp.asarray(np.concatenate([t for _, t in batches]))
        one_shot = getattr(F, fn_name)(all_p, all_t, **kwargs)
        np.testing.assert_allclose(np.asarray(metric.compute()), np.asarray(one_shot), atol=1e-5)
