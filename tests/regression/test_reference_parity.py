"""Differential tests for the regression domain vs the mounted reference.

Mirrors the reference's per-metric test coverage
(`tests/unittests/regression/test_{mean_error,pearson,spearman,r2,...}.py`)
by streaming identical batches through both implementations.
"""
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
pytestmark = pytest.mark.skipif(_ref is None, reason="reference mount unavailable")

import metrics_tpu as mt  # noqa: E402

NUM_BATCHES, BATCH = 4, 32
_rng = np.random.RandomState(7)
_PREDS_1D = _rng.randn(NUM_BATCHES, BATCH).astype(np.float32)
_TARGET_1D = (_PREDS_1D + 0.5 * _rng.randn(NUM_BATCHES, BATCH)).astype(np.float32)
_PREDS_2D = _rng.randn(NUM_BATCHES, BATCH, 3).astype(np.float32)
_TARGET_2D = (_PREDS_2D + 0.5 * _rng.randn(NUM_BATCHES, BATCH, 3)).astype(np.float32)
_PREDS_POS = np.abs(_PREDS_1D) + 0.1
_TARGET_POS = np.abs(_TARGET_1D) + 0.1


def _stream(ours, ref, preds, target, atol=1e-5):
    for i in range(preds.shape[0]):
        ours.update(jnp.asarray(preds[i]), jnp.asarray(target[i]))
        ref.update(torch.tensor(preds[i]), torch.tensor(target[i]))
    np.testing.assert_allclose(
        np.asarray(ours.compute()), np.asarray(ref.compute()), atol=atol, rtol=1e-4
    )


@pytest.mark.parametrize("name,kwargs,atol", [
    ("MeanSquaredError", {}, 1e-5),
    ("MeanSquaredError", {"squared": False}, 1e-5),
    ("MeanAbsoluteError", {}, 1e-5),
    ("MeanAbsolutePercentageError", {}, 1e-4),
    ("SymmetricMeanAbsolutePercentageError", {}, 1e-4),
    ("WeightedMeanAbsolutePercentageError", {}, 1e-4),
    ("ExplainedVariance", {}, 1e-4),
    ("R2Score", {}, 1e-4),
    ("PearsonCorrCoef", {}, 1e-4),
    ("SpearmanCorrCoef", {}, 1e-4),
    ("CosineSimilarity", {}, 1e-4),
])
def test_regression_parity_1d(name, kwargs, atol):
    if name == "CosineSimilarity":
        _stream(getattr(mt, name)(**kwargs), getattr(_ref, name)(**kwargs), _PREDS_2D[:, :, :2], _TARGET_2D[:, :, :2], atol)
    else:
        _stream(getattr(mt, name)(**kwargs), getattr(_ref, name)(**kwargs), _PREDS_1D, _TARGET_1D, atol)


def test_msle_parity():
    _stream(mt.MeanSquaredLogError(), _ref.MeanSquaredLogError(), _PREDS_POS, _TARGET_POS)


@pytest.mark.parametrize("power", [0.0, 1.0, 1.5, 2.0, 3.0])
def test_tweedie_parity(power):
    _stream(
        mt.TweedieDevianceScore(power=power),
        _ref.TweedieDevianceScore(power=power),
        _PREDS_POS,
        _TARGET_POS,
        atol=1e-4,
    )


@pytest.mark.parametrize("multioutput", ["raw_values", "uniform_average", "variance_weighted"])
def test_explained_variance_multioutput_parity(multioutput):
    _stream(
        mt.ExplainedVariance(multioutput=multioutput),
        _ref.ExplainedVariance(multioutput=multioutput),
        _PREDS_2D,
        _TARGET_2D,
        atol=1e-4,
    )


@pytest.mark.parametrize("adjusted", [0, 5])
@pytest.mark.parametrize("multioutput", ["raw_values", "uniform_average", "variance_weighted"])
def test_r2_parity(adjusted, multioutput):
    _stream(
        mt.R2Score(num_outputs=3, adjusted=adjusted, multioutput=multioutput),
        _ref.R2Score(num_outputs=3, adjusted=adjusted, multioutput=multioutput),
        _PREDS_2D,
        _TARGET_2D,
        atol=1e-4,
    )


def test_pearson_intermediate_compute_does_not_corrupt_state():
    """compute() between updates must leave the streaming state untouched.

    The reference FAILS this (its `_pearson_corrcoef_compute` divides the
    variance states in-place, so an epoch-mid compute corrupts later results);
    we pin the correct behavior against numpy on all data seen so far.
    """
    ours = mt.PearsonCorrCoef()
    for i in range(NUM_BATCHES):
        ours.update(jnp.asarray(_PREDS_1D[i]), jnp.asarray(_TARGET_1D[i]))
        expected = np.corrcoef(_PREDS_1D[: i + 1].ravel(), _TARGET_1D[: i + 1].ravel())[0, 1]
        np.testing.assert_allclose(np.asarray(ours.compute()), expected, atol=1e-4)
        ours._computed = None  # drop cache so later updates recompute


def test_cosine_similarity_reduction_parity():
    for reduction in ["mean", "sum", "none"]:
        _stream(
            mt.CosineSimilarity(reduction=reduction),
            _ref.CosineSimilarity(reduction=reduction),
            _PREDS_2D[:, :8, :],
            _TARGET_2D[:, :8, :],
            atol=1e-4,
        )
