"""Constructed regression corner cases vs the mounted reference.

Degenerate numerics built on purpose: zero-variance inputs for the
correlation family, heavy rank ties, sub-minimal sample counts, zero
targets for percentage errors, zero vectors for cosine similarity, and
negative-R2 regimes — identical data through both stacks.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from tests.helpers.reference_oracle import get_reference

_ref = get_reference()
pytestmark = pytest.mark.skipif(_ref is None, reason="reference mount unavailable")

import metrics_tpu as mt  # noqa: E402

RNG = np.random.RandomState(17)


def _run_pair(name, preds, target, our_kwargs=None, atol=1e-5, equal_nan=True):
    our_kwargs = our_kwargs or {}
    ours = getattr(mt, name)(**our_kwargs)
    ref = getattr(_ref, name)(**our_kwargs)
    ours.update(jnp.asarray(preds), jnp.asarray(target))
    ref.update(torch.tensor(preds), torch.tensor(target))
    np.testing.assert_allclose(
        np.asarray(ours.compute(), np.float64),
        np.asarray(ref.compute().numpy(), np.float64),
        atol=atol,
        rtol=1e-4,
        equal_nan=equal_nan,
    )


class TestCorrelationDegenerates:
    def test_pearson_constant_preds(self):
        """Zero prediction variance: 0/0 correlation must agree (NaN-for-NaN)."""
        preds = np.full(32, 2.5, dtype=np.float32)
        target = RNG.randn(32).astype(np.float32)
        _run_pair("PearsonCorrCoef", preds, target)

    def test_pearson_constant_both(self):
        preds = np.full(16, 1.0, dtype=np.float32)
        target = np.full(16, 3.0, dtype=np.float32)
        _run_pair("PearsonCorrCoef", preds, target)

    def test_pearson_perfect_anticorrelation(self):
        x = RNG.randn(64).astype(np.float32)
        _run_pair("PearsonCorrCoef", x, (-x).astype(np.float32))

    def test_pearson_two_samples(self):
        _run_pair("PearsonCorrCoef", np.asarray([1.0, 2.0], np.float32), np.asarray([3.0, 1.0], np.float32))

    def test_spearman_heavy_ties(self):
        preds = np.asarray([1, 1, 1, 2, 2, 3, 3, 3, 3, 4] * 3, dtype=np.float32)
        target = np.asarray([2, 1, 2, 2, 3, 1, 3, 2, 3, 4] * 3, dtype=np.float32)
        _run_pair("SpearmanCorrCoef", preds, target)

    def test_spearman_constant_target(self):
        preds = RNG.randn(20).astype(np.float32)
        target = np.zeros(20, dtype=np.float32)
        _run_pair("SpearmanCorrCoef", preds, target)


class TestR2Degenerates:
    def test_r2_fewer_than_two_samples_raises_in_both(self):
        ours = mt.R2Score()
        ref = _ref.R2Score()
        ours.update(jnp.asarray([1.0]), jnp.asarray([2.0]))
        ref.update(torch.tensor([1.0]), torch.tensor([2.0]))
        with pytest.raises(ValueError, match="Needs at least two samples"):
            ours.compute()
        with pytest.raises(ValueError, match="Needs at least two samples"):
            ref.compute()

    def test_r2_worse_than_mean_is_negative(self):
        target = RNG.randn(64).astype(np.float32)
        preds = (-3 * target + 5).astype(np.float32)
        _run_pair("R2Score", preds, target)

    def test_r2_constant_target(self):
        """Zero target variance: both stacks divide by a zero total sum of
        squares and must agree on the (infinite) result."""
        preds = RNG.randn(32).astype(np.float32)
        target = np.full(32, 4.0, dtype=np.float32)
        _run_pair("R2Score", preds, target)

    @pytest.mark.parametrize("multioutput", ["raw_values", "uniform_average", "variance_weighted"])
    def test_r2_multioutput_with_one_degenerate_column(self, multioutput):
        preds = RNG.randn(32, 3).astype(np.float32)
        target = RNG.randn(32, 3).astype(np.float32)
        target[:, 1] = 7.0  # constant column
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            _run_pair("R2Score", preds, target, {"multioutput": multioutput, "num_outputs": 3})

    def test_adjusted_r2(self):
        preds = RNG.randn(64).astype(np.float32)
        target = (preds + 0.5 * RNG.randn(64)).astype(np.float32)
        _run_pair("R2Score", preds, target, {"adjusted": 5})


class TestPercentageErrors:
    def test_mape_with_zero_targets(self):
        """Zero targets exercise the epsilon-clamped denominator identically."""
        preds = RNG.rand(16).astype(np.float32)
        target = np.concatenate([np.zeros(4), RNG.rand(12)]).astype(np.float32)
        _run_pair("MeanAbsolutePercentageError", preds, target, atol=1e-4)

    def test_smape_with_opposite_signs(self):
        preds = RNG.randn(32).astype(np.float32)
        target = (-preds + 0.1 * RNG.randn(32)).astype(np.float32)
        _run_pair("SymmetricMeanAbsolutePercentageError", preds, target, atol=1e-4)

    def test_wmape_zero_target_sum(self):
        preds = RNG.rand(8).astype(np.float32)
        target = np.zeros(8, dtype=np.float32)
        _run_pair("WeightedMeanAbsolutePercentageError", preds, target, atol=1e-4)


class TestCosineDegenerates:
    def test_zero_vector(self):
        preds = np.zeros((4, 8), dtype=np.float32)
        preds[1:] = RNG.randn(3, 8)
        target = RNG.randn(4, 8).astype(np.float32)
        _run_pair("CosineSimilarity", preds, target)

    def test_antiparallel(self):
        x = RNG.randn(4, 8).astype(np.float32)
        _run_pair("CosineSimilarity", x, (-x).astype(np.float32))


class TestStreamingConsistency:
    """Many tiny batches must equal one big batch — the moment-accumulator
    merge identities under extreme batch fragmentation."""

    @pytest.mark.parametrize(
        "name,kwargs",
        [
            ("PearsonCorrCoef", {}),
            ("ExplainedVariance", {}),
            ("R2Score", {}),
            ("MeanSquaredError", {}),
        ],
    )
    def test_one_sample_batches(self, name, kwargs):
        preds = RNG.randn(32).astype(np.float32)
        target = (preds + 0.3 * RNG.randn(32)).astype(np.float32)
        big = getattr(mt, name)(**kwargs)
        big.update(jnp.asarray(preds), jnp.asarray(target))
        tiny = getattr(mt, name)(**kwargs)
        for i in range(32):
            tiny.update(jnp.asarray(preds[i : i + 1]), jnp.asarray(target[i : i + 1]))
        np.testing.assert_allclose(float(big.compute()), float(tiny.compute()), atol=1e-4)
