"""Regression metrics vs sklearn/scipy oracles."""
from functools import partial

import jax.numpy as jnp
import numpy as np
import pytest
import scipy.stats
import sklearn.metrics as skm

from metrics_tpu import (
    CosineSimilarity,
    ExplainedVariance,
    MeanAbsoluteError,
    MeanAbsolutePercentageError,
    MeanSquaredError,
    MeanSquaredLogError,
    PearsonCorrCoef,
    R2Score,
    SpearmanCorrCoef,
    SymmetricMeanAbsolutePercentageError,
    TweedieDevianceScore,
    WeightedMeanAbsolutePercentageError,
)
from metrics_tpu.functional import (
    cosine_similarity,
    explained_variance,
    mean_absolute_error,
    mean_absolute_percentage_error,
    mean_squared_error,
    mean_squared_log_error,
    pearson_corrcoef,
    r2_score,
    spearman_corrcoef,
    symmetric_mean_absolute_percentage_error,
    tweedie_deviance_score,
    weighted_mean_absolute_percentage_error,
)
from tests.helpers.testers import MetricTester

_rng = np.random.RandomState(11)
_preds = jnp.asarray(_rng.rand(4, 32).astype(np.float32))
_target = jnp.asarray(_rng.rand(4, 32).astype(np.float32))


def _sk_smape(preds, target):
    return np.mean(2 * np.abs(preds - target) / (np.abs(preds) + np.abs(target)))


def _sk_wmape(preds, target):
    return np.sum(np.abs(preds - target)) / np.sum(np.abs(target))


@pytest.mark.parametrize(
    "metric_class, metric_fn, sk_fn, atol",
    [
        (MeanSquaredError, mean_squared_error, lambda p, t: skm.mean_squared_error(t, p), 1e-6),
        (MeanAbsoluteError, mean_absolute_error, lambda p, t: skm.mean_absolute_error(t, p), 1e-6),
        (MeanSquaredLogError, mean_squared_log_error, lambda p, t: skm.mean_squared_log_error(t, p), 1e-6),
        (
            MeanAbsolutePercentageError,
            mean_absolute_percentage_error,
            lambda p, t: skm.mean_absolute_percentage_error(t, p),
            1e-4,
        ),
        (SymmetricMeanAbsolutePercentageError, symmetric_mean_absolute_percentage_error, _sk_smape, 1e-4),
        (WeightedMeanAbsolutePercentageError, weighted_mean_absolute_percentage_error, _sk_wmape, 1e-5),
        (ExplainedVariance, explained_variance, lambda p, t: skm.explained_variance_score(t, p), 1e-5),
        (R2Score, r2_score, lambda p, t: skm.r2_score(t, p), 1e-4),
        (PearsonCorrCoef, pearson_corrcoef, lambda p, t: scipy.stats.pearsonr(t.ravel(), p.ravel())[0], 1e-4),
        (SpearmanCorrCoef, spearman_corrcoef, lambda p, t: scipy.stats.spearmanr(t.ravel(), p.ravel())[0], 1e-4),
    ],
)
class TestRegressionSuite(MetricTester):
    def test_functional(self, metric_class, metric_fn, sk_fn, atol):
        self.run_functional_metric_test(_preds, _target, metric_fn, sk_fn, atol=atol)

    def test_class_single(self, metric_class, metric_fn, sk_fn, atol):
        self.run_class_metric_test(_preds, _target, metric_class, sk_fn, atol=atol, check_batch=False)

    def test_class_ddp(self, metric_class, metric_fn, sk_fn, atol):
        self.run_class_metric_test(_preds, _target, metric_class, sk_fn, ddp=True, atol=atol)

    def test_jit(self, metric_class, metric_fn, sk_fn, atol):
        self.run_jit_test(_preds, _target, metric_fn, atol=atol)

    def test_grad(self, metric_class, metric_fn, sk_fn, atol):
        if metric_fn is spearman_corrcoef:
            pytest.skip("rank transform is not differentiable")
        self.run_differentiability_test(_preds, _target, metric_fn)


def test_rmse():
    t = MetricTester()
    t.run_functional_metric_test(
        _preds,
        _target,
        partial(mean_squared_error, squared=False),
        lambda p, tt: np.sqrt(skm.mean_squared_error(tt, p)),
    )


def test_pearson_spmd_parallel_merge():
    """Pearson's per-device moment stats merge exactly (Chan parallel formula)."""
    t = MetricTester()
    t.run_spmd_test(
        _preds,
        _target,
        PearsonCorrCoef,
        lambda p, tt: scipy.stats.pearsonr(tt.ravel(), p.ravel())[0],
        atol=1e-4,
    )


def test_spearman_ties():
    p = jnp.asarray([1.0, 1.0, 2.0, 3.0, 3.0, 3.0])
    t = jnp.asarray([1.0, 2.0, 2.0, 2.0, 3.0, 4.0])
    ref = scipy.stats.spearmanr(np.asarray(t), np.asarray(p))[0]
    np.testing.assert_allclose(np.asarray(spearman_corrcoef(p, t)), ref, atol=1e-5)


def test_cosine_similarity_reductions():
    p = jnp.asarray(_rng.rand(10, 5).astype(np.float32))
    t = jnp.asarray(_rng.rand(10, 5).astype(np.float32))
    sims = np.array(
        [np.dot(p[i], t[i]) / (np.linalg.norm(p[i]) * np.linalg.norm(t[i])) for i in range(10)]
    )
    np.testing.assert_allclose(np.asarray(cosine_similarity(p, t, "mean")), sims.mean(), atol=1e-5)
    np.testing.assert_allclose(np.asarray(cosine_similarity(p, t, "sum")), sims.sum(), atol=1e-4)
    np.testing.assert_allclose(np.asarray(cosine_similarity(p, t, None)), sims, atol=1e-5)
    m = CosineSimilarity(reduction="mean")
    m.update(p[:5], t[:5])
    m.update(p[5:], t[5:])
    np.testing.assert_allclose(np.asarray(m.compute()), sims.mean(), atol=1e-5)


@pytest.mark.parametrize("power", [0, 1, 2, 3, -1, 1.5])
def test_tweedie(power):
    p = jnp.asarray(_rng.rand(64).astype(np.float32) + 0.1)
    t = jnp.asarray(_rng.rand(64).astype(np.float32) + 0.1)
    ref = skm.mean_tweedie_deviance(np.asarray(t), np.asarray(p), power=power)
    np.testing.assert_allclose(np.asarray(tweedie_deviance_score(p, t, power=power)), ref, atol=1e-4)
    m = TweedieDevianceScore(power=power)
    m.update(p[:32], t[:32])
    m.update(p[32:], t[32:])
    np.testing.assert_allclose(np.asarray(m.compute()), ref, atol=1e-4)


def test_tweedie_invalid_power():
    with pytest.raises(ValueError, match="not defined"):
        TweedieDevianceScore(power=0.5)


@pytest.mark.parametrize("multioutput", ["raw_values", "uniform_average", "variance_weighted"])
def test_explained_variance_multioutput(multioutput):
    p = jnp.asarray(_rng.rand(32, 3).astype(np.float32))
    t = jnp.asarray(_rng.rand(32, 3).astype(np.float32))
    ref = skm.explained_variance_score(np.asarray(t), np.asarray(p), multioutput=multioutput)
    np.testing.assert_allclose(np.asarray(explained_variance(p, t, multioutput)), ref, atol=1e-5)


def test_r2_adjusted_and_multioutput():
    p = jnp.asarray(_rng.rand(32, 2).astype(np.float32))
    t = jnp.asarray(_rng.rand(32, 2).astype(np.float32))
    ref = skm.r2_score(np.asarray(t), np.asarray(p), multioutput="raw_values")
    np.testing.assert_allclose(np.asarray(r2_score(p, t, multioutput="raw_values")), ref, atol=1e-4)
    # adjusted
    n, k = 32, 1
    raw = skm.r2_score(np.asarray(t[:, 0]), np.asarray(p[:, 0]))
    adj = 1 - (1 - raw) * (n - 1) / (n - k - 1)
    np.testing.assert_allclose(np.asarray(r2_score(p[:, 0], t[:, 0], adjusted=1)), adj, atol=1e-4)
    m = R2Score(num_outputs=2, multioutput="raw_values")
    m.update(p, t)
    np.testing.assert_allclose(np.asarray(m.compute()), ref, atol=1e-4)
