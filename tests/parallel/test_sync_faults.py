"""Sync failure-domain contract under the SPMD dryrun environment.

Runs on the suite's 8-virtual-device CPU mesh (tests/conftest.py — the same
environment `make dryrun` validates). Pins, under ``inject_faults`` at the
``sync-gather`` site:

- a failed distributed gather leaves LOCAL state intact and the metric
  retryable (``Metric.sync`` snapshots before gathering and restores on
  failure);
- the retry-with-backoff wrapper absorbs transient failures within its
  budget (``METRICS_TPU_SYNC_RETRIES``) and surfaces a classified
  ``SyncFault`` when the budget is exhausted;
- ``compute()`` after a failed sync raises the classified error instead of
  returning a half-synced value;
- the ``process_ids`` range check documented at ``metric.py`` construction
  runs against the LIVE world size at sync time (classified
  ``SyncConfigFault``, which is also a ``ValueError`` — no retry);
- the in-program SPMD sync path (``sync_pytree`` under ``shard_map``) is a
  different lane entirely and is untouched by armed host-gather plans.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import metrics_tpu as mt
from metrics_tpu.ops import engine, faults
from metrics_tpu.parallel.sync import gather_all_tensors, sync_backoff_s, sync_retries, validate_group_live
from metrics_tpu.parallel.collectives import sync_pytree
from metrics_tpu.utils.exceptions import SyncConfigFault, SyncFault


@pytest.fixture(autouse=True)
def _fast_backoff(monkeypatch):
    monkeypatch.setenv("METRICS_TPU_SYNC_BACKOFF_MS", "0")
    yield


def _force_distributed(monkeypatch):
    """Route compute()'s auto-sync through the host gather on one process:
    `jit_distributed_available` reads `metrics_tpu.metric._dist_available`."""
    import metrics_tpu.metric as metric_mod

    monkeypatch.setattr(metric_mod, "_dist_available", lambda: True)


class TestRetryWithBackoff:
    def test_transient_failure_retries_and_succeeds(self):
        x = jnp.arange(4.0)
        with faults.inject_faults("sync-gather", count=1) as plan:
            out = gather_all_tensors(x)
        assert plan.fired == 1  # first attempt failed, retry succeeded
        assert len(out) == 1
        np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(x))

    def test_budget_exhaustion_raises_classified_sync_fault(self):
        n_attempts = sync_retries() + 1
        with faults.inject_faults("sync-gather", count=n_attempts + 5) as plan:
            with pytest.raises(SyncFault):
                gather_all_tensors(jnp.arange(3.0))
        assert plan.fired == n_attempts  # one failure per attempt, then raise
        assert engine.engine_stats()["fault_sync"] >= n_attempts

    def test_retry_knobs_read_from_env(self, monkeypatch):
        monkeypatch.setenv("METRICS_TPU_SYNC_RETRIES", "0")
        monkeypatch.setenv("METRICS_TPU_SYNC_BACKOFF_MS", "125")
        assert sync_retries() == 0
        assert sync_backoff_s() == 0.125
        with faults.inject_faults("sync-gather", count=1) as plan:
            with pytest.raises(SyncFault):
                gather_all_tensors(jnp.arange(2.0))
        assert plan.fired == 1  # zero retries: first failure is final


class TestSyncLeavesStateIntact:
    def test_failed_sync_is_retryable(self):
        m = mt.MeanMetric()
        m.update(jnp.asarray([2.0, 4.0]))
        with faults.inject_faults("sync-gather", count=100):
            with pytest.raises(SyncFault):
                m.sync(distributed_available=lambda: True)
        # local state intact, flags consistent, metric retryable
        assert m._is_synced is False
        assert m._cache is None
        np.testing.assert_array_equal(np.asarray(m.value), np.asarray(6.0))
        m.sync(distributed_available=lambda: True)  # retry succeeds
        assert m._is_synced is True
        m.unsync()
        assert float(m.compute()) == 3.0

    def test_failed_sync_mid_state_restores_every_state(self):
        """MeanMetric gathers two states; a failure on the SECOND gather must
        restore the first (no half-synced value survives)."""
        m = mt.MeanMetric()
        m.update(jnp.asarray([1.0, 3.0]))
        before = {k: np.asarray(v) for k, v in m.metric_state.items()}

        calls = {"n": 0}

        def flaky_gather(x, group=None):
            calls["n"] += 1
            if calls["n"] == 2:
                raise SyncFault("second state gather died", site="sync-gather")
            return [jnp.asarray(x) * 2]  # visibly-wrong merged value

        with pytest.raises(SyncFault):
            m.sync(dist_sync_fn=flaky_gather, distributed_available=lambda: True)
        after = {k: np.asarray(v) for k, v in m.metric_state.items()}
        for k in before:
            np.testing.assert_array_equal(after[k], before[k])
        assert m._is_synced is False

    def test_compute_after_failed_sync_raises_classified(self, monkeypatch):
        _force_distributed(monkeypatch)
        m = mt.MeanMetric()
        m.update(jnp.asarray([2.0, 4.0]))
        with faults.inject_faults("sync-gather", count=100):
            with pytest.raises(SyncFault):
                m.compute()  # auto-sync inside compute: classified, not half-synced
        assert m._computed is None  # no poisoned compute cache
        assert m._is_synced is False
        # with the fault gone, the same compute succeeds on intact local state
        assert float(m.compute()) == 3.0


class TestLiveWorldSizeCheck:
    def test_deferred_range_check_enforced_at_sync(self):
        """Construction defers the process-index range check (metrics may be
        built before jax.distributed initializes); sync() must enforce it
        against the live world size with the classified error."""
        m = mt.SumMetric(process_group=[3])  # accepted at construction
        m.update(jnp.asarray([1.0]))
        with pytest.raises(SyncConfigFault, match="out of range"):
            m.sync(distributed_available=lambda: True)
        # classified AND backward compatible
        assert issubclass(SyncConfigFault, ValueError)
        # state untouched, flags consistent
        assert m._is_synced is False
        assert float(m.compute()) == 1.0

    def test_validate_group_live_passthrough_and_classify(self):
        assert validate_group_live(None) is None
        assert validate_group_live([0]) == [0]
        with pytest.raises(SyncConfigFault):
            validate_group_live([0, 1])  # world size 1 in this suite
        with pytest.raises(SyncConfigFault, match="iterable of process indices"):
            validate_group_live(123)

    def test_config_faults_are_not_retried(self):
        s0 = engine.engine_stats()["fault_sync"]
        with pytest.raises(SyncConfigFault):
            gather_all_tensors(jnp.zeros(2), group=[5])
        # exactly one classified config fault — no retry loop ran
        assert engine.engine_stats()["fault_sync"] == s0 + 1


class TestSpmdPathUnaffected:
    def test_inprogram_sync_ignores_host_gather_plans(self):
        """The SPMD dryrun lane (shard_map + sync_pytree over the 8-device
        mesh) performs no host gather — armed sync-gather plans must neither
        fire nor perturb its collectives."""
        mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))

        def f(x):
            state = {"s": x, "mx": x}
            return sync_pytree(state, {"s": "sum", "mx": "max"}, "dp")

        x = jnp.asarray([1.0, 2.0, 3.0, 4.0])
        with faults.inject_faults("sync-gather", count=100) as plan:
            out = jax.jit(
                jax.shard_map(f, mesh=mesh, in_specs=P("dp"), out_specs=P(), check_vma=False)
            )(x)
        assert plan.fired == 0
        assert float(out["s"][0]) == 10.0
        assert float(out["mx"][0]) == 4.0
