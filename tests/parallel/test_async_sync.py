"""Async pipelined sync (dispatch/force split) vs the blocking oracle.

The async lane (``Metric.sync_async`` / ``MetricCollection.sync_async`` →
``SyncFuture``) must be observationally identical to the blocking protocol:
the forced value BIT-EXACT against the ``_FakeGather`` per-state rank-walk
oracle, compute() auto-forcing a pending future, double-force idempotent,
local state intact and retryable across every failure path (force deadline,
fence trip at force), and the quantized payload lane
(``METRICS_TPU_SYNC_QUANT``) exact for integer count states, within
tolerance for float states, warning once on a garbage value. The
multi-process world is simulated at the transport hooks exactly like
``test_coalesced_sync.py``.
"""
from __future__ import annotations

import copy
import time

import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu.ops import engine, faults
from metrics_tpu.parallel import bucketing
from metrics_tpu.parallel import sync as psync
from metrics_tpu.utils.exceptions import EpochFault, MetricsUserError, SyncTimeoutFault
from tests.helpers.testers import _FakeGather


@pytest.fixture(autouse=True)
def _fast_backoff(monkeypatch):
    monkeypatch.setenv("METRICS_TPU_SYNC_BACKOFF_MS", "0")
    yield
    psync.reset_membership()


DIST_ON = lambda: True  # noqa: E731


def _install_world(monkeypatch, rank_node_lists):
    """Simulate an N-process world at the transport hooks: rank 0 is the live
    syncing instance; the other ranks' trees pack lazily through the SAME
    layout/pack/quantize code at collective time."""
    cache = {}

    def _rank_packs():
        if "packs" not in cache:
            packs, vecs = [], []
            for nodes in rank_node_lists[1:]:
                for n in nodes:
                    n._canonicalize_list_states()
                entries, values = bucketing._collect(nodes)
                tier = psync.sync_quant_tier()
                if tier is not None:
                    bucketing._quant_encode(entries, values, tier, nodes[0])
                p, v = bucketing._pack(entries, values)
                packs.append(p)
                vecs.append(v)
            cache["packs"], cache["vecs"] = packs, vecs
        return cache["packs"], cache["vecs"]

    def host(vec):
        _, vecs = _rank_packs()
        return np.stack([np.asarray(vec)] + [np.asarray(v) for v in vecs])

    def payload(x):
        packs, _ = _rank_packs()
        pad_to = int(x.shape[0])
        return jnp.stack([x] + [jnp.pad(p, (0, pad_to - int(p.shape[0]))) for p in packs])

    monkeypatch.setattr(bucketing, "_host_allgather", host)
    monkeypatch.setattr(bucketing, "_payload_allgather", payload)


def _oracle_sync(rank_metrics):
    """The blocking per-state protocol on deep copies: the reference walk."""
    copies = [copy.deepcopy(m) for m in rank_metrics]
    copies[0].sync(dist_sync_fn=_FakeGather(copies), distributed_available=DIST_ON)
    return copies[0]


def _mean_ranks(n=3):
    ranks = []
    for r in range(n):
        m = mt.MeanMetric()
        m.update(jnp.asarray([1.0 + r, 4.0 * (r + 1)]))
        ranks.append(m)
    return ranks


class TestAsyncBitExact:
    def test_overlapped_sync_bitexact_vs_blocking_oracle(self, monkeypatch):
        ranks = _mean_ranks()
        oracle = _oracle_sync(ranks)
        _install_world(monkeypatch, [bucketing.tree_nodes(m) for m in ranks])
        s0 = engine.engine_stats()
        fut = ranks[0].sync_async(distributed_available=DIST_ON)
        assert fut is not None and not fut._forced
        fut.wait()
        s1 = engine.engine_stats()
        assert s1["sync_async_dispatches"] - s0["sync_async_dispatches"] == 1
        assert s1["sync_async_forces"] - s0["sync_async_forces"] == 1
        assert s1["sync_payload_collectives"] - s0["sync_payload_collectives"] == 1
        assert ranks[0]._is_synced
        for name in ranks[0].metric_state:
            np.testing.assert_array_equal(
                np.asarray(getattr(ranks[0], name)), np.asarray(getattr(oracle, name))
            )
        np.testing.assert_array_equal(
            np.asarray(ranks[0].compute()), np.asarray(oracle.compute())
        )
        ranks[0].unsync()
        # zero stale collectives across the whole cycle: the fence held
        assert engine.engine_stats()["sync_stale_collectives"] == s0["sync_stale_collectives"]

    def test_compute_before_force_auto_waits(self, monkeypatch):
        ranks = _mean_ranks()
        oracle_val = float(_oracle_sync(ranks).compute())
        _install_world(monkeypatch, [bucketing.tree_nodes(m) for m in ranks])
        s0 = engine.engine_stats()["sync_async_auto_forces"]
        ranks[0].sync_async(distributed_available=DIST_ON)
        # no explicit wait(): compute() is the force point
        assert float(ranks[0].compute()) == oracle_val
        assert engine.engine_stats()["sync_async_auto_forces"] == s0 + 1
        # the auto-forced cycle mirrored the blocking auto-sync: local state
        # restored after the value was computed and cached
        assert not ranks[0]._is_synced
        assert ranks[0].__dict__.get("_pending_sync") is None

    def test_double_force_idempotent(self, monkeypatch):
        ranks = _mean_ranks()
        _install_world(monkeypatch, [bucketing.tree_nodes(m) for m in ranks])
        fut = ranks[0].sync_async(distributed_available=DIST_ON)
        fut.wait()
        state = {k: np.asarray(v) for k, v in ranks[0].metric_state.items()}
        forces = engine.engine_stats()["sync_async_forces"]
        fut.wait()  # idempotent: no second apply, no error, no counter
        fut.wait()
        assert engine.engine_stats()["sync_async_forces"] == forces
        for k, v in ranks[0].metric_state.items():
            np.testing.assert_array_equal(np.asarray(v), state[k])
        ranks[0].unsync()

    def test_inflight_tail_updates_restore_through_unsync(self, monkeypatch):
        ranks = _mean_ranks()
        oracle = _oracle_sync(ranks)
        _install_world(monkeypatch, [bucketing.tree_nodes(m) for m in ranks])
        m = ranks[0]
        fut = m.sync_async(distributed_available=DIST_ON)
        # overlap window: a tail update lands locally while the wire flies
        m.update(jnp.asarray([100.0]))
        tail_state = {k: np.asarray(v) for k, v in m.metric_state.items()}
        fut.wait()
        # the forced (merged) value reflects the DISPATCH point
        np.testing.assert_array_equal(np.asarray(m.compute()), np.asarray(oracle.compute()))
        m.unsync()
        # ...and the tail restores through unsync
        for k, v in m.metric_state.items():
            np.testing.assert_array_equal(np.asarray(v), tail_state[k])

    def test_dispatch_while_pending_raises(self, monkeypatch):
        ranks = _mean_ranks()
        _install_world(monkeypatch, [bucketing.tree_nodes(m) for m in ranks])
        fut = ranks[0].sync_async(distributed_available=DIST_ON)
        with pytest.raises(MetricsUserError, match="in flight"):
            ranks[0].sync_async(distributed_available=DIST_ON)
        with pytest.raises(MetricsUserError, match="in flight"):
            ranks[0].sync(distributed_available=DIST_ON)
        fut.wait()
        ranks[0].unsync()

    def test_suite_async_bitexact_and_auto_force(self, monkeypatch):
        rng = np.random.RandomState(3)
        p = rng.rand(48).astype(np.float32)
        t = rng.randint(0, 2, 48)

        def make():
            c = mt.MetricCollection({"mean": mt.MeanMetric(), "acc": mt.Accuracy()})
            c.update(jnp.asarray(p), jnp.asarray(t))
            return c

        suites = [make() for _ in range(3)]
        # blocking oracle: the identical fake world, blocking suite sync
        oracles = [make() for _ in range(3)]

        def trees(suite_list):
            return [
                [
                    n
                    for _, m in s.items(keep_base=True, copy_state=False)
                    for n in bucketing.tree_nodes(m)
                ]
                for s in suite_list
            ]

        _install_world(monkeypatch, trees(oracles))
        oracles[0].sync(distributed_available=DIST_ON)
        oracle_vals = {k: np.asarray(v) for k, v in oracles[0].compute().items()}
        oracles[0].unsync()

        _install_world(monkeypatch, trees(suites))
        fut = suites[0].sync_async(distributed_available=DIST_ON)
        assert fut is not None
        got = {k: np.asarray(v) for k, v in suites[0].compute().items()}
        for k, v in oracle_vals.items():
            np.testing.assert_array_equal(got[k], v)
        # compute auto-forced and unsynced the suite
        assert suites[0].__dict__.get("_pending_sync") is None
        for _, m in suites[0].items(keep_base=True, copy_state=False):
            assert not m._is_synced

    def test_blocking_sync_drains_inflight_first(self, monkeypatch):
        # collectives pair by issue order: a blocking protocol entered while
        # another owner's async sync is in flight must drain (force) it
        # first, or the two could pair with different partners across ranks
        ranks = _mean_ranks()
        _install_world(monkeypatch, [bucketing.tree_nodes(m) for m in ranks])
        m1 = ranks[0]
        fut = m1.sync_async(distributed_available=DIST_ON)
        assert psync.inflight_stats()["count"] == 1
        other = mt.MeanMetric()
        other.update(jnp.asarray([5.0, 7.0]))
        other.sync(distributed_available=DIST_ON)  # blocking: drains m1 first
        assert psync.inflight_stats()["count"] == 0
        assert m1._is_synced and fut._forced
        other.unsync()
        m1.unsync()

    def test_member_compute_during_suite_flight_no_double_merge(self, monkeypatch):
        # a member computing while its COLLECTION's future is in flight: the
        # drain at the sync-context entry forces the suite rows first and
        # the member computes presynced — it must NOT re-sync its already-
        # merged state (which would double the merged counts)
        rng = np.random.RandomState(5)
        p = rng.rand(48).astype(np.float32)
        t = rng.randint(0, 2, 48)

        def make():
            c = mt.MetricCollection({"mean": mt.MeanMetric(), "acc": mt.Accuracy()})
            c.update(jnp.asarray(p), jnp.asarray(t))
            return c

        suites = [make() for _ in range(2)]
        oracles = [make() for _ in range(2)]

        def trees(ss):
            return [
                [
                    n
                    for _, m in s.items(keep_base=True, copy_state=False)
                    for n in bucketing.tree_nodes(m)
                ]
                for s in ss
            ]

        _install_world(monkeypatch, trees(oracles))
        oracles[0].sync(distributed_available=DIST_ON)
        oracle_mean = float(oracles[0]["mean"].compute())
        oracles[0].unsync()

        _install_world(monkeypatch, trees(suites))
        import metrics_tpu.metric as metric_mod

        monkeypatch.setattr(metric_mod, "_dist_available", lambda: True)
        fut = suites[0].sync_async(distributed_available=DIST_ON)
        assert fut is not None
        # the member's own compute while the suite future is in flight
        got = float(suites[0]["mean"].compute())
        assert got == oracle_mean, f"member compute double-merged: {got} != {oracle_mean}"
        assert fut._forced  # the drain at sync-context entry forced it

    def test_cancel_still_blocks_next_collective_until_wire_idle(self, monkeypatch):
        # a CANCELLED future's collective may still be on the wire — the
        # next blocking sync must wait the dispatcher out, not race it
        ranks = _mean_ranks(2)
        _install_world(monkeypatch, [bucketing.tree_nodes(m) for m in ranks])
        real_payload = bucketing._payload_allgather
        calls = []

        def slow_payload(x):
            calls.append(("start", time.perf_counter()))
            time.sleep(0.15)
            calls.append(("end", time.perf_counter()))
            return real_payload(x)

        monkeypatch.setattr(bucketing, "_payload_allgather", slow_payload)
        m = ranks[0]
        m.sync_async(distributed_available=DIST_ON)
        m.reset()  # cancels the future; the slow gather is still flying
        assert psync.inflight_stats()["count"] == 0
        other = mt.MeanMetric()
        other.update(jnp.asarray([5.0, 7.0]))
        other.sync(distributed_available=DIST_ON)  # must wait out the wire first
        other.unsync()
        # two gathers ran, STRICTLY serialized: the blocking one started
        # only after the cancelled in-flight one ended
        assert len(calls) == 4, calls
        (k0, _), (k1, t_end_cancelled), (k2, t_start_blocking), _ = calls
        assert (k0, k1, k2) == ("start", "end", "start")
        assert t_start_blocking >= t_end_cancelled, "blocking sync raced the cancelled wire"

    def test_fallback_future_auto_unsyncs_at_compute(self, monkeypatch):
        # the blocking-fallback future is registered like a live one: the
        # compute() auto-force path must unsync after serving, leaving the
        # metric in the same state as the truly-async lane
        monkeypatch.setenv("METRICS_TPU_SYNC_COALESCE", "0")
        ranks = _mean_ranks(2)
        oracle_val = float(_oracle_sync(ranks).compute())
        m = ranks[0]
        fut = m.sync_async(dist_sync_fn=_FakeGather(ranks), distributed_available=DIST_ON)
        assert fut.done() and m.__dict__.get("_pending_sync") is fut
        assert float(m.compute()) == oracle_val
        assert not m._is_synced, "fallback lane left the metric synced after compute"
        assert m.__dict__.get("_pending_sync") is None
        # the cycle closed: a fresh dispatch must not raise "in flight"
        m._computed = None
        fut2 = m.sync_async(dist_sync_fn=_FakeGather(ranks), distributed_available=DIST_ON)
        fut2.wait()
        m.unsync()

    def test_dispatch_pack_fault_demotes_and_replays_blocking(self, monkeypatch):
        # a pack failure at DISPATCH must demote the sync-pack lane and
        # replay the blocking protocol, exactly like the blocking paths —
        # never leak the internal CoalesceError to the caller. The oracle is
        # the BLOCKING twin under the identical injected fault (in this fake
        # world the per-state replay is the single-process identity — the
        # hooks only simulate the coalesced transports — so twin-vs-twin is
        # the apples-to-apples comparison).
        ranks = _mean_ranks(2)
        twin = copy.deepcopy(ranks[0])
        with faults.inject_faults("sync-pack", count=1):
            with pytest.warns(UserWarning, match="Coalesced sync failed"):
                twin.sync(distributed_available=DIST_ON)
        twin_val = np.asarray(twin.compute())
        twin.unsync()

        _install_world(monkeypatch, [bucketing.tree_nodes(m) for m in ranks])
        m = ranks[0]
        with faults.inject_faults("sync-pack", count=1) as plan:
            with pytest.warns(UserWarning, match="dispatch"):
                fut = m.sync_async(distributed_available=DIST_ON)
        assert plan.fired >= 1
        assert fut is not None and fut.done()
        assert m._is_synced  # the blocking replay completed the sync
        np.testing.assert_array_equal(np.asarray(m.compute()), twin_val)
        # the registered fallback future made compute() auto-unsync —
        # the same end state as the truly-async lane
        assert not m._is_synced
        lad = m.__dict__.get("_fault_ladders", {}).get("sync-pack")
        assert lad is not None and lad.demoted

    def test_fallback_to_blocking_when_not_coalescible(self, monkeypatch):
        # METRICS_TPU_SYNC_COALESCE=0: the async lane cannot pack — the
        # blocking protocol runs at dispatch and a completed future returns
        monkeypatch.setenv("METRICS_TPU_SYNC_COALESCE", "0")
        ranks = _mean_ranks(2)
        oracle = _oracle_sync(ranks)
        fb0 = engine.engine_stats()["sync_async_fallbacks"]
        fut = ranks[0].sync_async(
            dist_sync_fn=_FakeGather(ranks), distributed_available=DIST_ON
        )
        assert fut is not None and fut.done()
        fut.wait()  # no-op on a completed future
        assert engine.engine_stats()["sync_async_fallbacks"] == fb0 + 1
        assert ranks[0]._is_synced
        np.testing.assert_array_equal(
            np.asarray(ranks[0].compute()), np.asarray(oracle.compute())
        )
        ranks[0].unsync()


class TestForceFaults:
    def test_fence_trip_at_force_classified_state_intact(self, monkeypatch):
        ranks = _mean_ranks()
        _install_world(monkeypatch, [bucketing.tree_nodes(m) for m in ranks])
        m = ranks[0]
        before = {k: np.asarray(v) for k, v in m.metric_state.items()}
        s0 = engine.engine_stats()
        fut = m.sync_async(distributed_available=DIST_ON)
        # membership changes between dispatch and force: the in-flight
        # future is from a dead world — the force must classify, not pair
        psync.bump_epoch("test-membership-race")
        with pytest.raises(EpochFault):
            fut.wait()
        s1 = engine.engine_stats()
        assert s1["sync_epoch_fence_trips"] > s0["sync_epoch_fence_trips"]
        assert s1["sync_stale_collectives"] == s0["sync_stale_collectives"]
        assert not m._is_synced
        for k, v in m.metric_state.items():
            np.testing.assert_array_equal(np.asarray(v), before[k])
        # spent future: the second wait is a no-op, and a fresh sync at the
        # current epoch succeeds
        fut.wait()
        m.sync(distributed_available=DIST_ON)
        m.unsync()

    def test_force_deadline_timeout_classified_state_intact(self, monkeypatch):
        monkeypatch.setenv("METRICS_TPU_SYNC_DEADLINE_MS", "80")
        ranks = _mean_ranks(2)
        _install_world(monkeypatch, [bucketing.tree_nodes(m) for m in ranks])

        def hung(x):
            time.sleep(0.5)
            raise RuntimeError("abandoned hung collective (force deadline fired long ago)")

        monkeypatch.setattr(bucketing, "_payload_allgather", hung)
        m = ranks[0]
        before = {k: np.asarray(v) for k, v in m.metric_state.items()}
        t0 = engine.engine_stats()["sync_deadline_timeouts"]
        fut = m.sync_async(distributed_available=DIST_ON)
        with pytest.raises(SyncTimeoutFault):
            fut.wait()
        assert engine.engine_stats()["sync_deadline_timeouts"] > t0
        assert not m._is_synced
        for k, v in m.metric_state.items():
            np.testing.assert_array_equal(np.asarray(v), before[k])

    def test_force_timeout_degrades_through_local_tier(self, monkeypatch):
        monkeypatch.setenv("METRICS_TPU_SYNC_DEADLINE_MS", "80")
        monkeypatch.setenv("METRICS_TPU_SYNC_DEGRADED", "local")
        ranks = _mean_ranks(2)
        local_val = float(copy.deepcopy(ranks[0]).compute())
        _install_world(monkeypatch, [bucketing.tree_nodes(m) for m in ranks])

        def hung(x):
            time.sleep(0.5)
            raise RuntimeError("abandoned hung collective")

        monkeypatch.setattr(bucketing, "_payload_allgather", hung)
        m = ranks[0]
        fut = m.sync_async(distributed_available=DIST_ON)
        assert fut is not None
        with pytest.warns(UserWarning, match="LOCAL-ONLY"):
            served = float(m.compute())
        assert served == local_val
        health = m.sync_health()
        assert health["degraded"] and health["degraded_serves"] >= 1

    def test_reset_cancels_inflight_future(self, monkeypatch):
        ranks = _mean_ranks()
        _install_world(monkeypatch, [bucketing.tree_nodes(m) for m in ranks])
        m = ranks[0]
        fut = m.sync_async(distributed_available=DIST_ON)
        m.reset()
        assert m.__dict__.get("_pending_sync") is None
        fut.wait()  # cancelled: a no-op, nothing applied on the reset state
        assert not m._is_synced
        assert float(np.asarray(m.weight)) == 0.0


class TestSyncHealthInflight:
    def test_inflight_block_surfaces(self, monkeypatch):
        ranks = _mean_ranks()
        _install_world(monkeypatch, [bucketing.tree_nodes(m) for m in ranks])
        m = ranks[0]
        assert m.sync_health()["inflight"] is None
        fut = m.sync_async(distributed_available=DIST_ON)
        block = m.sync_health()["inflight"]
        assert block is not None
        assert block["dispatch_epoch"] == fut.dispatch_epoch
        assert block["age_steps"] >= 0 and block["quant_tier"] is None
        # the global plane carries the registry view
        from metrics_tpu.ops import telemetry

        snap_inflight = telemetry.snapshot()["sync_health"]["inflight"]
        assert snap_inflight["count"] >= 1
        fut.wait()
        assert m.sync_health()["inflight"] is None
        assert telemetry.snapshot()["sync_health"]["inflight"]["count"] == 0
        m.unsync()


class TestQuantLane:
    def test_integer_states_exact_under_any_tier(self, monkeypatch):
        rng = np.random.RandomState(0)
        for tier in ("bf16", "int8"):
            ranks = []
            for r in range(3):
                m = mt.ConfusionMatrix(num_classes=4)
                m.update(jnp.asarray(rng.randint(0, 4, 32)), jnp.asarray(rng.randint(0, 4, 32)))
                ranks.append(m)
            oracle = _oracle_sync(ranks)  # quant off: the bit-exact protocol
            monkeypatch.setenv("METRICS_TPU_SYNC_QUANT", tier)
            _install_world(monkeypatch, [bucketing.tree_nodes(m) for m in ranks])
            s0 = engine.engine_stats()
            ranks[0].sync(distributed_available=DIST_ON)
            s1 = engine.engine_stats()
            # every state routed the exact carve-out: integer counts
            assert s1["sync_quant_exact_states"] > s0["sync_quant_exact_states"]
            assert s1["sync_quant_lossy_states"] == s0["sync_quant_lossy_states"]
            np.testing.assert_array_equal(
                np.asarray(ranks[0].compute()), np.asarray(oracle.compute())
            )
            ranks[0].unsync()
            monkeypatch.delenv("METRICS_TPU_SYNC_QUANT")

    def test_float_states_within_tolerance_and_fewer_bytes(self, monkeypatch):
        rng = np.random.RandomState(7)

        def make_ranks():
            ranks = []
            for r in range(3):
                m = mt.BinnedPrecisionRecallCurve(num_classes=2, thresholds=11)
                probs = rng.rand(32, 2).astype(np.float32)
                probs /= probs.sum(axis=1, keepdims=True)
                m.update(jnp.asarray(probs), jnp.asarray(rng.randint(0, 2, 32)))
                # BinnedPrecisionRecallCurve state dtypes are float vectors —
                # the lossy lane's target shape
                return_ranks = m
                ranks.append(m)
            return ranks

        exact_ranks = make_ranks()
        rng = np.random.RandomState(7)
        quant_ranks = make_ranks()
        # exact baseline
        _install_world(monkeypatch, [bucketing.tree_nodes(m) for m in exact_ranks])
        b0 = engine.engine_stats()["sync_bytes_gathered"]
        exact_ranks[0].sync(distributed_available=DIST_ON)
        exact_bytes = engine.engine_stats()["sync_bytes_gathered"] - b0
        exact_vals = [np.asarray(v) for v in exact_ranks[0].compute()[0]]
        exact_ranks[0].unsync()
        # bf16 lane
        monkeypatch.setenv("METRICS_TPU_SYNC_QUANT", "bf16")
        _install_world(monkeypatch, [bucketing.tree_nodes(m) for m in quant_ranks])
        s0 = engine.engine_stats()
        quant_ranks[0].sync(distributed_available=DIST_ON)
        s1 = engine.engine_stats()
        quant_bytes = s1["sync_bytes_gathered"] - s0["sync_bytes_gathered"]
        assert s1["sync_quant_lossy_states"] > s0["sync_quant_lossy_states"]
        assert s1["sync_quant_bytes_saved"] > s0["sync_quant_bytes_saved"]
        assert quant_bytes < exact_bytes
        quant_vals = [np.asarray(v) for v in quant_ranks[0].compute()[0]]
        quant_ranks[0].unsync()
        for e, q in zip(exact_vals, quant_vals):
            np.testing.assert_allclose(q, e, atol=2e-2)

    def test_async_quant_tier_rides_the_future(self, monkeypatch):
        monkeypatch.setenv("METRICS_TPU_SYNC_QUANT", "bf16")
        ranks = _mean_ranks()
        _install_world(monkeypatch, [bucketing.tree_nodes(m) for m in ranks])
        fut = ranks[0].sync_async(distributed_available=DIST_ON)
        assert fut.quant_tier == "bf16"
        assert ranks[0].sync_health()["inflight"]["quant_tier"] == "bf16"
        fut.wait()
        ranks[0].unsync()

    def test_env_garbage_warns_once_naming_value(self, monkeypatch):
        monkeypatch.setenv("METRICS_TPU_SYNC_QUANT", "fp4")
        monkeypatch.setattr(psync, "_QUANT_WARN_OWNER", psync._EnvWarnOwner())
        with pytest.warns(UserWarning, match="fp4"):
            assert psync.sync_quant_tier() is None
        import warnings as _warnings

        with _warnings.catch_warnings():
            _warnings.simplefilter("error")
            assert psync.sync_quant_tier() is None


class TestHierarchicalLane:
    def test_two_node_psum_lane_bitexact(self, monkeypatch):
        rng = np.random.RandomState(1)
        ranks = []
        for r in range(4):
            m = mt.ConfusionMatrix(num_classes=3)
            m.update(jnp.asarray(rng.randint(0, 3, 16)), jnp.asarray(rng.randint(0, 3, 16)))
            ranks.append(m)
        flat_oracle = sum(np.asarray(m.confmat) for m in ranks)
        trees = [bucketing.tree_nodes(m) for m in ranks]

        def pack_tree(nodes):
            for n in nodes:
                n._canonicalize_list_states()
            e, v = bucketing._collect(nodes)
            return bucketing._pack(e, v)[0]

        ctx_box = {}
        orig_pack_phase = bucketing._pack_phase

        def spy_pack_phase(*a, **k):
            ctx = orig_pack_phase(*a, **k)
            ctx_box["ctx"] = ctx
            return ctx

        monkeypatch.setattr(bucketing, "_pack_phase", spy_pack_phase)

        def intranode(x):  # node 0 = ranks {0, 1}
            return jnp.stack([x, pack_tree(trees[1])])

        def internode(block):  # node 1's leader reduced ranks {2, 3}
            intra2 = jnp.stack([pack_tree(trees[2]), pack_tree(trees[3])])
            other = bucketing._node_reduce(ctx_box["ctx"], intra2)
            return jnp.stack([block, other])

        monkeypatch.setattr(bucketing, "_intranode_allgather", intranode)
        monkeypatch.setattr(bucketing, "_internode_allgather", internode)
        monkeypatch.setenv("METRICS_TPU_SYNC_HIER", "2")
        s0 = engine.engine_stats()
        ranks[0].sync(distributed_available=DIST_ON)
        s1 = engine.engine_stats()
        assert s1["sync_hier_intranode_collectives"] - s0["sync_hier_intranode_collectives"] == 1
        assert s1["sync_hier_internode_collectives"] - s0["sync_hier_internode_collectives"] == 1
        assert s1["sync_hier_node_reduces"] - s0["sync_hier_node_reduces"] == 1
        assert s1["sync_stale_collectives"] == s0["sync_stale_collectives"]
        np.testing.assert_array_equal(np.asarray(ranks[0].compute()), flat_oracle)
        ranks[0].unsync()

    def test_two_stage_gather_bitexact_for_float_layouts(self, monkeypatch):
        # float sum states decline the psum reduce (reassociation) but still
        # ride the bit-exact two-stage block gather
        ranks = _mean_ranks(4)
        oracle = _oracle_sync(ranks)
        trees = [bucketing.tree_nodes(m) for m in ranks]

        def pack_tree(nodes):
            for n in nodes:
                n._canonicalize_list_states()
            e, v = bucketing._collect(nodes)
            return bucketing._pack(e, v)[0]

        def intranode(x):
            return jnp.stack([x, pack_tree(trees[1])])

        def internode(block):
            other = jnp.concatenate([pack_tree(trees[2]), pack_tree(trees[3])])
            return jnp.stack([block, other])

        monkeypatch.setattr(bucketing, "_intranode_allgather", intranode)
        monkeypatch.setattr(bucketing, "_internode_allgather", internode)
        monkeypatch.setenv("METRICS_TPU_SYNC_HIER", "2")
        s0 = engine.engine_stats()["sync_hier_node_reduces"]
        ranks[0].sync(distributed_available=DIST_ON)
        assert engine.engine_stats()["sync_hier_node_reduces"] == s0  # no reduce: floats
        np.testing.assert_array_equal(
            np.asarray(ranks[0].compute()), np.asarray(oracle.compute())
        )
        ranks[0].unsync()


class TestPerfAttribution:
    def test_wire_hidden_fraction_on_slow_transport(self, monkeypatch):
        from metrics_tpu import perf_report
        from metrics_tpu.ops import telemetry

        ranks = _mean_ranks(2)
        _install_world(monkeypatch, [bucketing.tree_nodes(m) for m in ranks])
        real_payload = bucketing._payload_allgather

        def slow_payload(x):  # the simulated tunnel round trip
            time.sleep(0.05)
            return real_payload(x)

        monkeypatch.setattr(bucketing, "_payload_allgather", slow_payload)
        was_armed = telemetry.armed
        telemetry.set_telemetry(True)
        try:
            telemetry.clear_spans()
            fut = ranks[0].sync_async(distributed_available=DIST_ON)
            # the overlap window: host compute longer than the wire
            other = mt.MeanMetric()
            deadline = time.perf_counter() + 0.1
            while time.perf_counter() < deadline:
                other.update(jnp.asarray([1.0]))
            fut.wait()
            report = perf_report()
            wire = report["sync"]["wire"]
            assert wire["overlapped_wire_s"] > 0
            assert wire["wire_hidden_fraction"] >= 0.5, wire
        finally:
            telemetry.set_telemetry(was_armed)
        ranks[0].unsync()
