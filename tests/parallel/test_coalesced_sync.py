"""Coalesced bucketed sync vs the per-state oracle.

The coalesced engine (``metrics_tpu/parallel/bucketing.py``) must be
observationally invisible: every value BIT-EXACT against the per-state
gather protocol (the ``_FakeGather`` rank-walk oracle — no tolerance
widening), with the collective count collapsing from 2-per-state-per-metric
to one payload (plus at most one metadata exchange for uneven ``cat``
states). The multi-process world is simulated by monkeypatching the two
transport hooks (``_host_allgather`` / ``_payload_allgather``) with a fake
that packs every other rank's metric tree through the same layout/pack code
the syncing rank uses.
"""
from __future__ import annotations

import copy

import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu.metric import Metric
from metrics_tpu.ops import engine, faults
from metrics_tpu.parallel import bucketing
from metrics_tpu.parallel import sync as psync
from metrics_tpu.utils.exceptions import SyncFault
from tests.helpers.testers import _FakeGather


@pytest.fixture(autouse=True)
def _fast_backoff(monkeypatch):
    monkeypatch.setenv("METRICS_TPU_SYNC_BACKOFF_MS", "0")
    yield


DIST_ON = lambda: True  # noqa: E731


def _install_world(monkeypatch, rank_node_lists):
    """Simulate an N-process world: rank 0 is the live syncing instance; the
    other ranks' trees are packed lazily through the SAME layout/pack code at
    collective time (after rank 0's own canonicalization, mirroring the
    symmetric protocol)."""
    cache = {}

    def _rank_packs():
        if "packs" not in cache:
            packs, vecs = [], []
            for nodes in rank_node_lists[1:]:
                for n in nodes:
                    n._canonicalize_list_states()
                entries, values = bucketing._collect(nodes)
                p, v = bucketing._pack(entries, values)
                packs.append(p)
                vecs.append(v)
            cache["packs"], cache["vecs"] = packs, vecs
        return cache["packs"], cache["vecs"]

    def host(vec):
        _, vecs = _rank_packs()
        return np.stack([np.asarray(vec)] + [np.asarray(v) for v in vecs])

    def payload(x):
        packs, _ = _rank_packs()
        pad_to = int(x.shape[0])
        return jnp.stack([x] + [jnp.pad(p, (0, pad_to - int(p.shape[0]))) for p in packs])

    monkeypatch.setattr(bucketing, "_host_allgather", host)
    monkeypatch.setattr(bucketing, "_payload_allgather", payload)


def _states_equal(a, b) -> None:
    assert a.keys() == b.keys()
    for k in a:
        va, vb = a[k], b[k]
        if isinstance(va, list) or isinstance(vb, list):
            assert isinstance(va, list) and isinstance(vb, list) and len(va) == len(vb)
            for ra, rb in zip(va, vb):
                np.testing.assert_array_equal(np.asarray(ra), np.asarray(rb))
        else:
            np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))


def _collect_tree_state(m: Metric) -> dict:
    out = {}
    for i, node in enumerate(bucketing.tree_nodes(m)):
        for name in node._defaults:
            out[(i, name)] = getattr(node, name)
    return out


def _oracle_sync(rank_metrics):
    """The per-state protocol on deep copies: the reference rank-walk."""
    copies = [copy.deepcopy(m) for m in rank_metrics]
    copies[0].sync(dist_sync_fn=_FakeGather(copies), distributed_available=DIST_ON)
    return copies[0]


class TestBitExactVsPerStateOracle:
    def test_multi_state_metric(self, monkeypatch):
        ranks = []
        for r in range(3):
            m = mt.MeanMetric()
            m.update(jnp.asarray([1.0 + r, 4.0 * (r + 1)]))
            ranks.append(m)
        oracle = _oracle_sync(ranks)
        _install_world(monkeypatch, [bucketing.tree_nodes(m) for m in ranks])
        s0 = engine.engine_stats()
        ranks[0].sync(distributed_available=DIST_ON)
        s1 = engine.engine_stats()
        assert s1["sync_payload_collectives"] - s0["sync_payload_collectives"] == 1
        assert s1["sync_shape_collectives"] - s0["sync_shape_collectives"] == 0  # static lane
        _states_equal(
            {k: v for k, v in ranks[0].metric_state.items()},
            {k: v for k, v in oracle.metric_state.items()},
        )
        np.testing.assert_array_equal(
            np.asarray(ranks[0].compute()), np.asarray(oracle.compute())
        )
        ranks[0].unsync()

    def test_uneven_cat_states(self, monkeypatch):
        rng = np.random.RandomState(3)
        ranks = []
        for r in range(3):
            a = mt.AUROC(pos_label=1)
            n = 12 - 3 * r  # UNEVEN per-rank row counts
            a.update(jnp.asarray(rng.rand(n).astype(np.float32)), jnp.asarray(rng.randint(0, 2, n)))
            ranks.append(a)
        oracle = _oracle_sync(ranks)
        _install_world(monkeypatch, [bucketing.tree_nodes(m) for m in ranks])
        s0 = engine.engine_stats()
        ranks[0].sync(distributed_available=DIST_ON)
        s1 = engine.engine_stats()
        # uneven-shape lane: ONE metadata exchange + ONE payload, not 2/state
        assert s1["sync_payload_collectives"] - s0["sync_payload_collectives"] == 1
        assert s1["sync_shape_collectives"] - s0["sync_shape_collectives"] == 1
        _states_equal(dict(ranks[0].metric_state), dict(oracle.metric_state))
        np.testing.assert_array_equal(
            np.asarray(ranks[0].compute()), np.asarray(oracle.compute())
        )

    def test_never_updated_list_state(self, monkeypatch):
        class _Mixed(Metric):
            full_state_update = True

            def __init__(self):
                super().__init__()
                self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")
                self.add_state("rows", [], dist_reduce_fx="cat")

            def update(self, x):
                self.total = self.total + jnp.sum(x)

            def compute(self):
                return self.total

        ranks = []
        for r in range(2):
            m = _Mixed()
            m.update(jnp.asarray([1.0 + r]))
            ranks.append(m)
        oracle = _oracle_sync(ranks)
        _install_world(monkeypatch, [bucketing.tree_nodes(m) for m in ranks])
        ranks[0].sync(distributed_available=DIST_ON)
        assert ranks[0].rows == [] and oracle.rows == []
        np.testing.assert_array_equal(np.asarray(ranks[0].total), np.asarray(oracle.total))
        ranks[0].unsync()
        assert ranks[0].rows == []

    def test_wrapper_child_recursion(self, monkeypatch):
        rng = np.random.RandomState(11)
        ranks = []
        for r in range(2):
            b = mt.BootStrapper(mt.MeanSquaredError(), num_bootstraps=3, sampling_strategy="multinomial")
            b._rng = np.random.RandomState(50 + r)
            b.update(
                jnp.asarray(rng.rand(8).astype(np.float32)),
                jnp.asarray(rng.rand(8).astype(np.float32)),
            )
            ranks.append(b)
        oracle = _oracle_sync(ranks)
        _install_world(monkeypatch, [bucketing.tree_nodes(m) for m in ranks])
        s0 = engine.engine_stats()
        ranks[0].sync(distributed_available=DIST_ON)
        s1 = engine.engine_stats()
        # the whole clone fleet rides ONE payload collective
        assert s1["sync_payload_collectives"] - s0["sync_payload_collectives"] == 1
        _states_equal(_collect_tree_state(ranks[0]), _collect_tree_state(oracle))
        got = {k: np.asarray(v) for k, v in ranks[0].compute().items()}
        want = {k: np.asarray(v) for k, v in oracle.compute().items()}
        for k in want:
            np.testing.assert_array_equal(got[k], want[k])
        # children were marked synced; unsync restores the whole tree
        assert all(c._is_synced for c in ranks[0]._sync_children())
        pre = _collect_tree_state(oracle)  # oracle still synced; compare post-restore below
        ranks[0].unsync()
        assert not any(c._is_synced for c in ranks[0]._sync_children())
        oracle.unsync()
        _states_equal(_collect_tree_state(ranks[0]), _collect_tree_state(oracle))
        assert pre  # silence unused warning


class TestProtocolGates:
    def test_custom_dist_sync_fn_bypasses_coalescing(self):
        ranks = [mt.MeanMetric() for _ in range(2)]
        for r, m in enumerate(ranks):
            m.update(jnp.asarray([float(r + 1)]))
        s0 = engine.engine_stats()
        ranks[0].sync(dist_sync_fn=_FakeGather(ranks), distributed_available=DIST_ON)
        s1 = engine.engine_stats()
        # the injected protocol owns the walk: nothing was coalesced
        assert s1["sync_coalesced_payloads"] == s0["sync_coalesced_payloads"]
        np.testing.assert_allclose(float(ranks[0].compute()), 1.5)

    def test_coalesce_env_off_restores_per_state_protocol(self, monkeypatch):
        monkeypatch.setenv("METRICS_TPU_SYNC_COALESCE", "0")
        m = mt.MeanMetric()
        m.update(jnp.asarray([2.0, 4.0]))
        s0 = engine.engine_stats()
        m.sync(distributed_available=DIST_ON)
        s1 = engine.engine_stats()
        # 2 states -> one shape + one payload slot EACH, zero coalesced
        assert s1["sync_payload_collectives"] - s0["sync_payload_collectives"] == 2
        assert s1["sync_shape_collectives"] - s0["sync_shape_collectives"] == 2
        assert s1["sync_coalesced_payloads"] == s0["sync_coalesced_payloads"]
        m.unsync()
        np.testing.assert_allclose(float(m.compute()), 3.0)

    def test_sync_retries_env_garbage_uses_distributed_aware_default(self, monkeypatch):
        monkeypatch.setenv("METRICS_TPU_SYNC_RETRIES", "not-a-number")
        monkeypatch.setattr(psync, "_RETRIES_WARN_OWNER", psync._EnvWarnOwner())
        with pytest.warns(UserWarning, match="METRICS_TPU_SYNC_RETRIES"):
            assert psync.sync_retries() == 2  # single-process default
        # warned ONCE per owner+domain
        import warnings as _warnings

        with _warnings.catch_warnings():
            _warnings.simplefilter("error")
            assert psync.sync_retries() == 2
        # a live multi-process world must NOT inherit the unilateral-retry 2
        monkeypatch.setattr(psync, "_RETRIES_WARN_OWNER", psync._EnvWarnOwner())
        monkeypatch.setattr(psync, "distributed_available", lambda: True)
        with pytest.warns(UserWarning, match="distributed-aware default"):
            assert psync.sync_retries() == 0


class TestFaultIntegration:
    def test_sync_pack_demote_fallback_and_repromote(self):
        # threshold 2: the in-call fallback sync counts clean step 1, so the
        # demotion is still observable after the failing sync returns
        faults.set_recovery_policy(steps=2)
        try:
            m = mt.MeanMetric()
            m.update(jnp.asarray([2.0, 4.0]))
            before = {k: np.asarray(v) for k, v in m.metric_state.items()}
            s0 = engine.engine_stats()
            with faults.inject_faults("sync-pack") as plan:
                with pytest.warns(UserWarning, match="Coalesced sync failed"):
                    m.sync(distributed_available=DIST_ON)
            assert plan.fired == 1
            # the fallback ran the per-state protocol, bit-exact (1-process
            # sync is the identity) and the ladder recorded the demotion
            after = {k: np.asarray(v) for k, v in m.metric_state.items()}
            for k in before:
                np.testing.assert_array_equal(after[k], before[k])
            lad = m.__dict__["_fault_ladders"]["sync-pack"]
            assert lad.demoted
            s1 = engine.engine_stats()
            assert s1["sync_pack_fallbacks"] - s0["sync_pack_fallbacks"] == 1
            assert s1["sync_coalesced_payloads"] == s0["sync_coalesced_payloads"]
            m.unsync()
            # demoted: the next sync stays per-state AND counts clean step 2
            m.sync(distributed_available=DIST_ON)
            m.unsync()
            assert not lad.demoted  # recovery edge fired (threshold 2)
            # re-promoted: the next sync coalesces again
            s2 = engine.engine_stats()
            m.sync(distributed_available=DIST_ON)
            s3 = engine.engine_stats()
            assert s3["sync_coalesced_payloads"] - s2["sync_coalesced_payloads"] == 1
            m.unsync()
            np.testing.assert_allclose(float(m.compute()), 3.0)
        finally:
            faults.set_recovery_policy(steps=8)

    def test_rank_local_pack_failure_in_live_world_raises_classified(self, monkeypatch):
        """Sync is a collective protocol: in a LIVE multi-process world a
        rank-local pack failure must surface classified (state intact,
        retryable) instead of unilaterally switching to per-state collectives
        the other ranks cannot pair with. Only rank-symmetric failures (the
        layout cross-check mismatch) may demote-and-fall-back there."""
        from metrics_tpu.utils.exceptions import RuntimeFault

        m = mt.SumMetric()
        m.update(jnp.asarray([5.0]))
        monkeypatch.setattr(psync, "distributed_available", lambda: True)
        monkeypatch.setattr(
            psync, "_gather_once", lambda result, members, epoch=None: [jnp.asarray(result)]
        )
        with faults.inject_faults("sync-pack") as plan:
            with pytest.raises(RuntimeFault):
                m.sync(distributed_available=DIST_ON)
        assert plan.fired == 1
        lad = m.__dict__.get("_fault_ladders", {}).get("sync-pack")
        assert lad is None or not lad.demoted  # no unilateral protocol switch
        assert not m._is_synced
        np.testing.assert_array_equal(np.asarray(m.value), np.asarray(5.0))
        # the symmetric layout mismatch DOES fall back, on every rank alike
        monkeypatch.setattr(
            bucketing, "_host_allgather", lambda v: np.stack([np.asarray(v), np.asarray(v) + 4])
        )
        with pytest.warns(UserWarning, match="Coalesced sync failed"):
            m.sync(distributed_available=DIST_ON)
        assert m.__dict__["_fault_ladders"]["sync-pack"].demoted
        m.unsync()
        np.testing.assert_array_equal(np.asarray(m.compute()), np.asarray(5.0))

    def test_sync_gather_fault_mid_suite_restores_every_member(self):
        coll = mt.MetricCollection(
            {"mean": mt.MeanMetric(), "mse": mt.MeanSquaredError(), "mae": mt.MeanAbsoluteError()}
        )
        p = jnp.asarray([0.2, 0.8])
        t = jnp.asarray([0.0, 1.0])
        coll.update(p, t)
        before = {
            k: {s: np.asarray(v) for s, v in m.metric_state.items()}
            for k, m in coll.items(keep_base=True, copy_state=False)
        }
        with faults.inject_faults("sync-gather", count=100) as plan:
            with pytest.raises(SyncFault):
                coll.sync(distributed_available=DIST_ON)
        assert plan.fired >= 1
        # EVERY member's local state intact and retryable
        for k, m in coll.items(keep_base=True, copy_state=False):
            assert not m._is_synced
            for s, v in m.metric_state.items():
                np.testing.assert_array_equal(np.asarray(v), before[k][s])
        coll.sync(distributed_available=DIST_ON)  # retry succeeds
        coll.unsync()

    def test_suite_pack_fault_falls_back_member_wise_bit_exact(self):
        faults.set_recovery_policy(steps=1)
        try:
            coll = mt.MetricCollection({"mean": mt.MeanMetric(), "mse": mt.MeanSquaredError()})
            coll.update(jnp.asarray([0.4]), jnp.asarray([0.5]))
            oracle = copy.deepcopy(coll)
            with faults.inject_faults("sync-pack") as plan:
                with pytest.warns(UserWarning, match="Coalesced suite sync failed"):
                    coll.sync(distributed_available=DIST_ON)
            assert plan.fired == 1
            coll.unsync()
            got = {k: np.asarray(v) for k, v in coll.compute().items()}
            want = {k: np.asarray(v) for k, v in oracle.compute().items()}
            for k in want:
                np.testing.assert_array_equal(got[k], want[k])
            lad = coll.__dict__["_fault_ladders"]["sync-pack"]
            assert lad.demoted
            # member-wise suite syncs count clean steps; the edge re-arms
            coll.sync(distributed_available=DIST_ON)
            coll.unsync()
            assert not lad.demoted
            s0 = engine.engine_stats()
            coll.sync(distributed_available=DIST_ON)
            s1 = engine.engine_stats()
            assert s1["sync_coalesced_payloads"] - s0["sync_coalesced_payloads"] == 1
            coll.unsync()
        finally:
            faults.set_recovery_policy(steps=8)


class TestSuiteCoalescing:
    def _make(self):
        return mt.MetricCollection(
            {
                "mean": mt.MeanMetric(),
                "mse": mt.MeanSquaredError(),
                "mae": mt.MeanAbsoluteError(),
                "acc": mt.Accuracy(),
            }
        )

    def test_one_payload_collective_per_suite_sync(self, monkeypatch):
        rng = np.random.RandomState(0)
        rank_colls = []
        for r in range(2):
            c = self._make()
            c.update(
                jnp.asarray(rng.rand(16).astype(np.float32)), jnp.asarray(rng.randint(0, 2, 16))
            )
            rank_colls.append(c)

        # per-member per-state oracle on deep copies
        oracle = copy.deepcopy(rank_colls)
        for name, m0 in oracle[0].items(keep_base=True, copy_state=False):
            gather = _FakeGather([oc[name] for oc in oracle])
            m0.sync(dist_sync_fn=gather, distributed_available=DIST_ON)
        oracle_vals = {k: np.asarray(v) for k, v in oracle[0].compute().items()}

        def suite_nodes(coll):
            return [
                n
                for _, m in coll.items(keep_base=True, copy_state=False)
                for n in bucketing.tree_nodes(m)
            ]

        _install_world(monkeypatch, [suite_nodes(c) for c in rank_colls])
        s0 = engine.engine_stats()
        rank_colls[0].sync(distributed_available=DIST_ON)
        s1 = engine.engine_stats()
        # >=4 multi-state metrics, ONE payload collective, zero shape exchanges
        assert s1["sync_payload_collectives"] - s0["sync_payload_collectives"] == 1
        assert s1["sync_shape_collectives"] - s0["sync_shape_collectives"] == 0
        assert s1["sync_states_coalesced"] - s0["sync_states_coalesced"] >= 8
        got = {k: np.asarray(v) for k, v in rank_colls[0].compute().items()}
        for k in oracle_vals:
            np.testing.assert_array_equal(got[k], oracle_vals[k])
        rank_colls[0].unsync()
        for _, m in rank_colls[0].items(keep_base=True, copy_state=False):
            assert not m._is_synced
        # steady state: the cached manifest keeps repeat syncs at 1 collective
        s2 = engine.engine_stats()
        rank_colls[0].sync(distributed_available=DIST_ON)
        s3 = engine.engine_stats()
        assert s3["sync_payload_collectives"] - s2["sync_payload_collectives"] == 1
        assert s3["sync_shape_collectives"] - s2["sync_shape_collectives"] == 0
        assert s3["sync_fastlane_hits"] == s2["sync_fastlane_hits"] + 1
        rank_colls[0].unsync()

    def test_compute_auto_suite_sync_in_distributed_world(self, monkeypatch):
        """In a live distributed world collection.compute() pre-syncs the
        whole suite as ONE packed collective; every member computes presynced
        and unsyncs on exit — values identical to the per-member protocol."""
        import metrics_tpu.metric as metric_mod

        p = jnp.asarray([0.2, 0.7, 0.9])
        t = jnp.asarray([0, 1, 1])

        monkeypatch.setenv("METRICS_TPU_SYNC_COALESCE", "0")
        oracle = self._make()
        oracle.update(p, t)
        monkeypatch.setattr(metric_mod, "_dist_available", lambda: True)
        oracle_vals = {k: np.asarray(v) for k, v in oracle.compute().items()}
        monkeypatch.delenv("METRICS_TPU_SYNC_COALESCE")

        coll = self._make()
        monkeypatch.setattr(metric_mod, "_dist_available", lambda: False)
        coll.update(p, t)
        monkeypatch.setattr(metric_mod, "_dist_available", lambda: True)
        s0 = engine.engine_stats()
        got = {k: np.asarray(v) for k, v in coll.compute().items()}
        s1 = engine.engine_stats()
        assert s1["sync_payload_collectives"] - s0["sync_payload_collectives"] == 1
        for k in oracle_vals:
            np.testing.assert_array_equal(got[k], oracle_vals[k])
        # the context unsynced on exit: local state back, metrics retryable
        for _, m in coll.items(keep_base=True, copy_state=False):
            assert not m._is_synced

    def test_member_with_custom_gather_syncs_individually(self):
        coll = mt.MetricCollection({"mean": mt.MeanMetric(), "sum": mt.SumMetric()})
        coll.update(jnp.asarray([2.0]))
        calls = {"n": 0}

        def custom(x, group=None):
            calls["n"] += 1
            return [jnp.asarray(x)]

        coll["sum"].dist_sync_fn = custom
        s0 = engine.engine_stats()
        coll.sync(distributed_available=DIST_ON)
        s1 = engine.engine_stats()
        assert calls["n"] >= 1  # the custom protocol ran for its member
        # the other member still coalesced
        assert s1["sync_coalesced_payloads"] - s0["sync_coalesced_payloads"] == 1
        coll.unsync()

    def test_second_suite_sync_reuses_programs(self, monkeypatch):
        c1 = self._make()
        c1.update(jnp.asarray([0.3, 0.9]), jnp.asarray([0, 1]))
        c1.sync(distributed_available=DIST_ON)
        c1.unsync()
        # an identically-configured suite adds ZERO new program builds
        c2 = self._make()
        c2.update(jnp.asarray([0.6, 0.1]), jnp.asarray([1, 0]))
        builds0 = engine.engine_stats()["builds"]
        c2.sync(distributed_available=DIST_ON)
        assert engine.engine_stats()["builds"] == builds0
        c2.unsync()
