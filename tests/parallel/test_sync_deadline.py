"""Sync deadlines and the quorum-degraded compute tier.

The watchdog (``METRICS_TPU_SYNC_DEADLINE_MS``) must convert a hung
collective into a classified ``SyncTimeoutFault`` with local state bit-exact
and retryable — and, with ``METRICS_TPU_SYNC_DEGRADED=local``, ``compute()``
must serve the local-only value tagged via ``sync_health()`` and promote back
to the full coalesced sync after the ``sync-degrade`` recovery edge. The
multi-process world is the same transport-hook fake world the coalesced-sync
suite certifies against (``_install_world``), so degraded (local) and healed
(merged) values are distinguishable.
"""
from __future__ import annotations

import time

import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
import metrics_tpu.metric as metric_mod
from metrics_tpu.ops import engine, faults
from metrics_tpu.parallel import bucketing
from metrics_tpu.parallel import sync as psync
from metrics_tpu.utils.exceptions import SyncTimeoutFault
from tests.parallel.test_coalesced_sync import DIST_ON, _install_world

DEADLINE_MS = "150"


@pytest.fixture(autouse=True)
def _fast_sync(monkeypatch):
    monkeypatch.setenv("METRICS_TPU_SYNC_BACKOFF_MS", "0")
    monkeypatch.setenv("METRICS_TPU_SYNC_RETRIES", "0")
    yield


def _hang_payload(monkeypatch, seconds: float = 1.0):
    # after the sleep the abandoned call raises pure-python instead of
    # re-entering XLA: its result is discarded anyway, and a daemon thread
    # inside a jax dispatch at interpreter exit can abort process teardown
    def hung(x):
        time.sleep(seconds)
        raise RuntimeError("abandoned hung collective (watchdog timed out long ago)")

    monkeypatch.setattr(bucketing, "_payload_allgather", hung)


class TestDeadline:
    def test_default_off_is_direct_call(self, monkeypatch):
        monkeypatch.delenv("METRICS_TPU_SYNC_DEADLINE_MS", raising=False)
        assert psync.sync_deadline_s() is None
        # direct call: the caller's exception propagates untouched and the
        # timeout counter never moves
        s0 = engine.engine_stats()["sync_deadline_timeouts"]
        with pytest.raises(KeyError):
            psync.run_with_deadline(lambda: {}["missing"])
        assert psync.run_with_deadline(lambda: 41 + 1) == 42
        assert engine.engine_stats()["sync_deadline_timeouts"] == s0

    def test_env_garbage_warns_once_and_stays_off(self, monkeypatch):
        monkeypatch.setenv("METRICS_TPU_SYNC_DEADLINE_MS", "soon")
        monkeypatch.setattr(psync, "_DEADLINE_WARN_OWNER", psync._EnvWarnOwner())
        with pytest.warns(UserWarning, match="METRICS_TPU_SYNC_DEADLINE_MS"):
            assert psync.sync_deadline_s() is None
        import warnings as _warnings

        with _warnings.catch_warnings():
            _warnings.simplefilter("error")
            assert psync.sync_deadline_s() is None

    def test_timeout_raises_classified_state_intact_retryable(self, monkeypatch):
        monkeypatch.setenv("METRICS_TPU_SYNC_DEADLINE_MS", DEADLINE_MS)
        ranks = []
        for r in range(2):
            m = mt.MeanMetric()
            m.update(jnp.asarray([1.0 + r, 3.0 + r]))
            ranks.append(m)
        _install_world(monkeypatch, [bucketing.tree_nodes(m) for m in ranks])
        _hang_payload(monkeypatch)
        before = {k: np.asarray(v) for k, v in ranks[0].metric_state.items()}
        s0 = engine.engine_stats()["sync_deadline_timeouts"]
        with pytest.raises(SyncTimeoutFault):
            ranks[0].sync(distributed_available=DIST_ON)
        assert engine.engine_stats()["sync_deadline_timeouts"] == s0 + 1
        # local state bit-exact and retryable
        after = {k: np.asarray(v) for k, v in ranks[0].metric_state.items()}
        for k in before:
            np.testing.assert_array_equal(after[k], before[k])
        assert not ranks[0]._is_synced
        # transport heals: the SAME metric syncs (still coalesced — a
        # transport fault never demotes the sync-pack lane) and lands on the
        # fake-world merged value
        monkeypatch.undo()  # drop the hang; reinstall the healthy world
        monkeypatch.setenv("METRICS_TPU_SYNC_BACKOFF_MS", "0")
        _install_world(monkeypatch, [bucketing.tree_nodes(m) for m in ranks])
        s1 = engine.engine_stats()
        ranks[0].sync(distributed_available=DIST_ON)
        s2 = engine.engine_stats()
        assert s2["sync_coalesced_payloads"] - s1["sync_coalesced_payloads"] == 1
        np.testing.assert_allclose(float(ranks[0].compute()), 2.5)  # mean of 1,3,2,4
        ranks[0].unsync()

    def test_timeout_on_per_state_gather_path(self, monkeypatch):
        monkeypatch.setenv("METRICS_TPU_SYNC_DEADLINE_MS", DEADLINE_MS)
        monkeypatch.setenv("METRICS_TPU_SYNC_COALESCE", "0")

        def hung_gather(result, members, epoch=None):
            time.sleep(1.0)
            raise RuntimeError("abandoned hung gather (watchdog timed out long ago)")

        monkeypatch.setattr(psync, "_gather_once", hung_gather)
        m = mt.SumMetric()
        m.update(jnp.asarray([5.0]))
        with pytest.raises(SyncTimeoutFault):
            m.sync(distributed_available=DIST_ON)
        assert not m._is_synced
        np.testing.assert_array_equal(np.asarray(m.value), np.asarray(5.0))

    def test_healthy_path_identical_armed_vs_disarmed(self, monkeypatch):
        """Armed deadline on a healthy transport: same values, same
        collective counts, zero timeouts — the acceptance 'armed≈disarmed'
        contract, behavior side."""
        vals = {}
        for armed in (False, True):
            if armed:
                monkeypatch.setenv("METRICS_TPU_SYNC_DEADLINE_MS", "60000")
            else:
                monkeypatch.delenv("METRICS_TPU_SYNC_DEADLINE_MS", raising=False)
            m = mt.MeanMetric()
            m.update(jnp.asarray([2.0, 4.0]))
            s0 = engine.engine_stats()
            m.sync(distributed_available=DIST_ON)
            s1 = engine.engine_stats()
            assert s1["sync_payload_collectives"] - s0["sync_payload_collectives"] == 1
            assert s1["sync_deadline_timeouts"] == s0["sync_deadline_timeouts"]
            m.unsync()
            vals[armed] = float(m.compute())
        assert vals[True] == vals[False]


class TestDegradedCompute:
    def _two_rank_world(self, monkeypatch):
        ranks = []
        for r in range(2):
            m = mt.MeanMetric()
            m.update(jnp.asarray([1.0 + 2 * r, 3.0 + 2 * r]))  # rank0: 1,3  rank1: 3,5
            ranks.append(m)
        _install_world(monkeypatch, [bucketing.tree_nodes(m) for m in ranks])
        return ranks

    def test_metric_serves_local_then_promotes_to_full_sync(self, monkeypatch):
        monkeypatch.setenv("METRICS_TPU_SYNC_DEADLINE_MS", DEADLINE_MS)
        monkeypatch.setenv("METRICS_TPU_SYNC_DEGRADED", "local")
        monkeypatch.setattr(metric_mod, "_dist_available", lambda: True)
        faults.set_recovery_policy(steps=2)
        try:
            ranks = self._two_rank_world(monkeypatch)
            m = ranks[0]
            _hang_payload(monkeypatch)
            s0 = engine.engine_stats()["sync_degraded_serves"]
            with pytest.warns(UserWarning, match="LOCAL-ONLY"):
                v = m.compute()
            # local-only value (rank0's own mean), explicitly tagged
            np.testing.assert_allclose(float(v), 2.0)
            health = m.sync_health()
            assert health["degraded"] and health["degraded_tier"] == "local"
            assert health["degraded_serves"] == 1
            assert health["degraded_since_step"] is not None
            assert health["last_good_sync_step"] is None
            assert engine.engine_stats()["sync_degraded_serves"] == s0 + 1
            # state stays retryable: the local accumulators are untouched
            np.testing.assert_allclose(float(np.asarray(m.value)), 4.0)
            np.testing.assert_allclose(float(np.asarray(m.weight)), 2.0)

            # transport heals; clean serves advance the sync-degrade edge
            monkeypatch.undo()
            monkeypatch.setenv("METRICS_TPU_SYNC_BACKOFF_MS", "0")
            monkeypatch.setenv("METRICS_TPU_SYNC_DEGRADED", "local")
            monkeypatch.setattr(metric_mod, "_dist_available", lambda: True)
            ranks = self._two_rank_world(monkeypatch)  # fresh healthy world
            m._computed = None
            v = m.compute()  # clean step 1: still local
            np.testing.assert_allclose(float(v), 2.0)
            assert m.sync_health()["degraded"]
            m._computed = None
            v = m.compute()  # edge fires -> promote -> full sync re-probe
            np.testing.assert_allclose(float(v), 3.0)  # mean of 1,3,3,5
            health = m.sync_health()
            assert not health["degraded"]
            assert health["last_good_sync_step"] is not None
            assert health["degraded_since_step"] is None
        finally:
            faults.set_recovery_policy(steps=8)

    def test_degraded_off_by_default_failure_raises(self, monkeypatch):
        monkeypatch.setenv("METRICS_TPU_SYNC_DEADLINE_MS", DEADLINE_MS)
        monkeypatch.delenv("METRICS_TPU_SYNC_DEGRADED", raising=False)
        monkeypatch.setattr(metric_mod, "_dist_available", lambda: True)
        m = mt.MeanMetric()
        m.update(jnp.asarray([2.0, 4.0]))
        _hang_payload(monkeypatch)
        with pytest.raises(SyncTimeoutFault):
            m.compute()
        lad = m.__dict__.get("_fault_ladders", {}).get("sync-degrade")
        assert lad is None or not lad.demoted

    def test_config_fault_never_degrades(self, monkeypatch):
        from metrics_tpu.utils.exceptions import SyncConfigFault

        monkeypatch.setenv("METRICS_TPU_SYNC_DEGRADED", "local")
        monkeypatch.setattr(metric_mod, "_dist_available", lambda: True)
        m = mt.MeanMetric(process_group=[0, 99])  # range-checked at sync time
        m.update(jnp.asarray([2.0]))
        with pytest.raises(SyncConfigFault):
            m.compute()
        assert not m.sync_health()["degraded"]

    def test_collection_degrades_and_promotes_suite_wide(self, monkeypatch):
        monkeypatch.setenv("METRICS_TPU_SYNC_DEADLINE_MS", DEADLINE_MS)
        monkeypatch.setenv("METRICS_TPU_SYNC_DEGRADED", "local")
        monkeypatch.setattr(metric_mod, "_dist_available", lambda: True)
        faults.set_recovery_policy(steps=1)
        try:
            coll = mt.MetricCollection({"mean": mt.MeanMetric(), "sum": mt.SumMetric()})
            coll.update(jnp.asarray([2.0, 4.0]))
            local_vals = {"mean": 3.0, "sum": 6.0}
            _hang_payload(monkeypatch)
            with pytest.warns(UserWarning, match="LOCAL-ONLY"):
                got = {k: float(v) for k, v in coll.compute().items()}
            assert got == local_vals
            health = coll.sync_health()
            assert health["degraded"] and health["degraded_serves"] == 1
            # heal: edge (steps=1) fires on the next compute -> full sync
            monkeypatch.undo()
            monkeypatch.setenv("METRICS_TPU_SYNC_BACKOFF_MS", "0")
            monkeypatch.setenv("METRICS_TPU_SYNC_DEGRADED", "local")
            monkeypatch.setattr(metric_mod, "_dist_available", lambda: True)
            for _, m in coll.items(keep_base=True, copy_state=False):
                m._computed = None
            s0 = engine.engine_stats()
            got = {k: float(v) for k, v in coll.compute().items()}
            s1 = engine.engine_stats()
            # the re-probe ran the real coalesced suite sync (1-process
            # gather = identity, so values match; the collective proves it)
            assert s1["sync_payload_collectives"] - s0["sync_payload_collectives"] == 1
            assert got == local_vals
            assert not coll.sync_health()["degraded"]
            assert coll.sync_health()["last_good_sync_step"] is not None
        finally:
            faults.set_recovery_policy(steps=8)


class TestTaxonomySatellites:
    def test_classify_maps_stdlib_timeout_and_oserror(self):
        assert faults.classify(TimeoutError("peer hung")) == "sync"
        assert faults.classify(OSError(28, "No space left on device")) == "journal"
        assert faults.classify(IOError("disk detached")) == "journal"
        # the catching site's default wins for I/O-ish domains
        assert faults.classify(OSError("host path"), default="host") == "host"
        assert faults.classify(SyncTimeoutFault("deadline", site="sync-gather")) == "sync"
        # journal domain is recoverable (ladder re-probes)
        assert faults.domain_recoverable("journal")

    def test_failure_log_entries_carry_monotonic_step(self):
        faults.note_fault("sync", site="sync-gather")
        faults.note_fault("journal", site="journal-load")
        log = engine.engine_stats()["failure_log"]
        steps = [e["step"] for e in log[-2:]]
        assert steps[1] > steps[0] > 0
        assert faults.current_step() == steps[1]

    def test_reset_stats_zeroes_counters_keeps_programs(self):
        m = mt.MeanMetric()
        m.update(jnp.asarray([1.0, 2.0]))
        m.sync(distributed_available=DIST_ON)
        m.unsync()
        faults.note_fault("runtime", site="probe")
        stats = engine.engine_stats()
        assert stats["cached"] > 0 and stats["sync_payload_collectives"] >= 1
        assert stats["fault_runtime"] >= 1 and stats["failure_log"]
        step_before = faults.current_step()
        engine.reset_stats()
        stats = engine.engine_stats()
        # counters + log zeroed...
        assert stats["builds"] == 0 and stats["hits"] == 0
        assert stats["sync_payload_collectives"] == 0
        assert stats["fault_runtime"] == 0 and stats["failure_log"] == []
        # ...but programs survive (zero new builds on the next same-config
        # sync) and the monotonic step index keeps counting
        assert stats["cached"] > 0
        assert faults.current_step() >= step_before
        m2 = mt.MeanMetric()
        m2.update(jnp.asarray([3.0, 4.0]))
        m2.sync(distributed_available=DIST_ON)
        m2.unsync()
        assert engine.engine_stats()["builds"] == 0  # cache hits only
