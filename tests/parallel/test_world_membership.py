"""Elastic world membership: epoch fencing, surviving-quorum compute, rejoin.

The membership layer must make every world transition safe for the sync
protocol:

- **Epoch fence** — a membership change between a protocol's entry and any
  (re)issued collective raises the classified ``EpochFault`` with local
  state intact, instead of pairing a collective with the wrong cohort (the
  ``sync_stale_collectives`` audit counter stays 0 — the certified
  invariant).
- **Surviving quorum** — with ``METRICS_TPU_SYNC_DEGRADED=quorum`` and a
  declared-dead peer, ``compute()`` aggregates over the surviving subgroup
  BIT-EXACTLY vs the ``_FakeGather`` rank-walk oracle over the survivors,
  then promotes back to the full world once the dead rank rejoins.
- **Rejoin + barrier** — a restarted rank restores its journal (or a
  survivor's handoff record), enters the next epoch, and the post-rejoin
  full-world sync is bit-exact vs an uninterrupted run;
  ``checkpoint_barrier`` stamps one agreed step + the epoch into every
  manifest.

The multi-process world is the same transport-hook fake world the
coalesced-sync suite certifies against, extended with a "kill switch": the
full-world transport hangs while the dead rank is undeclared and the
re-formed survivor transport works.
"""
from __future__ import annotations

import copy
import time

import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu as mt
import metrics_tpu.metric as metric_mod
from metrics_tpu.ops import engine, faults
from metrics_tpu.parallel import bucketing
from metrics_tpu.parallel import sync as psync
from metrics_tpu.utils.exceptions import EpochFault, SyncFault, SyncTimeoutFault
from tests.helpers.testers import _FakeGather
from tests.parallel.test_coalesced_sync import DIST_ON, _install_world

DEADLINE_MS = "150"


@pytest.fixture(autouse=True)
def _fresh_membership(monkeypatch):
    monkeypatch.setenv("METRICS_TPU_SYNC_BACKOFF_MS", "0")
    psync.reset_membership()
    yield
    psync.reset_membership()
    faults.set_recovery_policy(steps=8)


class _ElasticWorld:
    """Kill-switch controller for the fake transport: ``revive()`` models the
    dead rank's process restarting (its transport becomes reachable again)."""

    def __init__(self):
        self.killed = True

    def revive(self):
        self.killed = False


def _install_elastic_world(monkeypatch, rank_node_lists, dead_rank):
    """A 3-rank fake world with a killed rank: while the killed peer is
    UNDECLARED the full-world collective hangs (the dead peer never shows
    up); once the membership registry declares it dead, the transport models
    the re-formed surviving world — rows for the survivors only, in
    ascending rank order; after ``revive()`` (the restarted process is
    back), the full world answers again."""
    world = _ElasticWorld()

    def _pack(nodes):
        for n in nodes:
            n._canonicalize_list_states()
        entries, values = bucketing._collect(nodes)
        packed, vec = bucketing._pack(entries, values)
        return packed, vec

    def _rows():
        if not world.killed:
            return [nodes for r, nodes in enumerate(rank_node_lists) if r != 0]
        alive = psync.surviving_members()
        if alive is None:
            return None  # full world requested, dead peer undeclared: hang
        return [rank_node_lists[r] for r in alive if r != 0]

    def host(vec):
        rows = _rows()
        if rows is None:
            time.sleep(1.0)
            raise RuntimeError("abandoned hung metadata exchange")
        return np.stack([np.asarray(vec)] + [np.asarray(_pack(nodes)[1]) for nodes in rows])

    def payload(x):
        rows = _rows()
        if rows is None:
            time.sleep(1.0)
            raise RuntimeError("abandoned hung collective (dead peer)")
        pad_to = int(x.shape[0])
        packs = [_pack(nodes)[0] for nodes in rows]
        return jnp.stack([x] + [jnp.pad(p, (0, pad_to - int(p.shape[0]))) for p in packs])

    monkeypatch.setattr(bucketing, "_host_allgather", host)
    monkeypatch.setattr(bucketing, "_payload_allgather", payload)
    return world


class TestEpochRegistry:
    def test_bump_is_monotonic_and_counted(self):
        s0 = engine.engine_stats()["sync_epoch_bumps"]
        e0 = psync.world_epoch()
        e1 = psync.bump_epoch("test-transition")
        assert e1 == e0 + 1 == psync.world_epoch()
        assert engine.engine_stats()["sync_epoch_bumps"] == s0 + 1
        assert psync.world_health()["transitions"][-1]["reason"] == "test-transition"

    def test_stale_fence_raises_classified_epoch_fault(self):
        fence = psync.world_epoch()
        psync.check_epoch(fence)  # current epoch passes silently
        psync.bump_epoch("membership-change")
        t0 = engine.engine_stats()["sync_epoch_fence_trips"]
        with pytest.raises(EpochFault) as err:
            psync.check_epoch(fence, site="sync-gather")
        assert err.value.site == "epoch-fence"
        assert isinstance(err.value, SyncFault)  # degradable, sync domain
        assert faults.classify(err.value) == "sync"
        stats = engine.engine_stats()
        assert stats["sync_epoch_fence_trips"] == t0 + 1
        assert stats["failure_log"][-1]["site"] == "epoch-fence"

    def test_epoch_fault_is_never_retried(self):
        """retry_with_backoff must re-raise an EpochFault immediately — a
        re-issued collective at a stale epoch can never pair."""
        calls = {"n": 0}

        def fenced():
            calls["n"] += 1
            raise EpochFault("stale", site="epoch-fence")

        with pytest.raises(EpochFault):
            faults.retry_with_backoff(fenced, attempts=5, base_delay_s=0.0)
        assert calls["n"] == 1

    def test_injection_site_fires_classified(self):
        with faults.inject_faults("epoch-fence", count=1) as plan:
            with pytest.raises(EpochFault):
                psync.check_epoch(psync.world_epoch())
        assert plan.fired == 1

    def test_mid_sync_membership_change_fences_the_retry(self, monkeypatch):
        """The chaos shape: the first transport attempt fails transiently AND
        the membership epoch bumps (a peer died mid-protocol); the retry must
        trip the fence — classified EpochFault, local state intact and
        retryable at the new epoch, zero stale collectives issued."""
        monkeypatch.setenv("METRICS_TPU_SYNC_RETRIES", "1")
        m = mt.MeanMetric()
        m.update(jnp.asarray([2.0, 4.0]))
        before = {k: np.asarray(v) for k, v in m.metric_state.items()}
        calls = {"n": 0}

        def flaky(x):
            calls["n"] += 1
            if calls["n"] == 1:
                psync.bump_epoch("peer-died-mid-sync")
                raise RuntimeError("transport reset by membership change")
            return x[None]

        monkeypatch.setattr(bucketing, "_payload_allgather", flaky)
        s0 = engine.engine_stats()
        with pytest.raises(EpochFault):
            m.sync(distributed_available=DIST_ON)
        s1 = engine.engine_stats()
        assert s1["sync_epoch_fence_trips"] - s0["sync_epoch_fence_trips"] == 1
        assert s1["sync_stale_collectives"] == s0["sync_stale_collectives"] == 0
        assert calls["n"] == 1  # the stale retry never reached the transport
        assert not m._is_synced
        after = {k: np.asarray(v) for k, v in m.metric_state.items()}
        for k in before:
            np.testing.assert_array_equal(after[k], before[k])
        # re-entering at the current epoch succeeds
        m.sync(distributed_available=DIST_ON)
        m.unsync()
        np.testing.assert_allclose(float(m.compute()), 3.0)


class TestPeerHealth:
    def test_timeouts_fold_into_suspicion_and_success_clears(self):
        psync.set_expected_world(3)
        psync.note_sync_timeout("sync-gather")
        psync.note_sync_timeout("sync-gather")
        h = psync.world_health()
        assert h["consecutive_timeouts"] == 2
        assert h["peers"][1]["timeouts"] == 2  # anonymous: cohort-wide
        psync.note_sync_success(world=3)
        h = psync.world_health()
        assert h["consecutive_timeouts"] == 0
        assert h["peers"][1]["timeouts"] == 0
        assert h["last_good_sync_step"] is not None
        assert h["observed_world"] == 3

    def test_kth_timeout_consults_prober_and_declares_dead(self, monkeypatch):
        monkeypatch.setenv("METRICS_TPU_SYNC_DEAD_AFTER", "2")
        psync.set_expected_world(3)
        psync.set_peer_prober(lambda: [2])
        s0 = engine.engine_stats()
        e0 = psync.world_epoch()
        psync.note_sync_timeout("sync-gather")
        assert psync.world_health()["dead_ranks"] == []  # below the threshold
        psync.note_sync_timeout("sync-gather")
        h = psync.world_health()
        assert h["dead_ranks"] == [2]
        assert h["surviving_ranks"] == [0, 1]
        assert h["degraded"]
        assert psync.world_epoch() == e0 + 1
        s1 = engine.engine_stats()
        assert s1["sync_peers_declared_dead"] - s0["sync_peers_declared_dead"] == 1
        assert h["peers"][2]["state"] == "dead"

    def test_no_prober_means_no_membership_change(self, monkeypatch):
        monkeypatch.setenv("METRICS_TPU_SYNC_DEAD_AFTER", "1")
        psync.set_expected_world(2)
        e0 = psync.world_epoch()
        psync.note_sync_timeout("sync-gather")
        psync.note_sync_timeout("sync-gather")
        assert psync.world_health()["dead_ranks"] == []
        assert psync.world_epoch() == e0

    def test_rejoin_clears_dead_mark_and_bumps_epoch(self):
        psync.set_expected_world(2)
        psync.mark_peer_dead(1, reason="operator")
        e_dead = psync.world_epoch()
        s0 = engine.engine_stats()["sync_rank_rejoins"]
        e_new = psync.rejoin_rank(1)
        assert e_new == e_dead + 1
        h = psync.world_health()
        assert h["dead_ranks"] == [] and h["surviving_ranks"] is None
        assert h["peers"][1]["state"] == "live"
        assert engine.engine_stats()["sync_rank_rejoins"] == s0 + 1


class TestQuorumCompute:
    def _three_rank_metrics(self):
        ranks = []
        for r in range(3):
            m = mt.MeanMetric()
            m.update(jnp.asarray([1.0 + 2 * r, 3.0 + 2 * r]))  # distinguishable per rank
            ranks.append(m)
        return ranks

    def test_quorum_merge_bit_exact_vs_survivor_oracle(self, monkeypatch):
        """A dead rank 2: K timeouts auto-declare it, the epoch bumps, and
        METRICS_TPU_SYNC_DEGRADED=quorum computes the merge over ranks {0,1}
        bit-exactly vs the _FakeGather rank-walk oracle over the survivors —
        then the rejoin promotes back to the bit-exact full-world sync."""
        monkeypatch.setenv("METRICS_TPU_SYNC_DEADLINE_MS", DEADLINE_MS)
        monkeypatch.setenv("METRICS_TPU_SYNC_DEGRADED", "quorum")
        monkeypatch.setenv("METRICS_TPU_SYNC_RETRIES", "1")
        monkeypatch.setenv("METRICS_TPU_SYNC_DEAD_AFTER", "2")
        monkeypatch.setattr(metric_mod, "_dist_available", lambda: True)
        faults.set_recovery_policy(steps=1)
        ranks = self._three_rank_metrics()
        trees = [bucketing.tree_nodes(m) for m in ranks]

        # oracles: the per-state rank walk over the survivors and full world
        surv_copies = [copy.deepcopy(ranks[r]) for r in (0, 1)]
        surv_copies[0].sync(dist_sync_fn=_FakeGather(surv_copies), distributed_available=DIST_ON)
        quorum_oracle = np.asarray(surv_copies[0].compute())
        full_copies = [copy.deepcopy(m) for m in ranks]
        full_copies[0].sync(dist_sync_fn=_FakeGather(full_copies), distributed_available=DIST_ON)
        full_oracle = np.asarray(full_copies[0].compute())

        psync.set_expected_world(3)
        psync.set_peer_prober(lambda: [2])
        world = _install_elastic_world(monkeypatch, trees, dead_rank=2)
        m = ranks[0]
        s0 = engine.engine_stats()
        with pytest.warns(UserWarning, match="QUORUM"):
            got = np.asarray(m.compute())
        # retries=1 and DEAD_AFTER=2: the 2nd timeout declared rank 2 dead,
        # the epoch bumped, and the degraded handler aggregated over {0, 1}
        np.testing.assert_array_equal(got, quorum_oracle)
        assert not np.array_equal(got, full_oracle)  # genuinely a subgroup merge
        s1 = engine.engine_stats()
        assert s1["sync_quorum_serves"] - s0["sync_quorum_serves"] == 1
        assert s1["sync_stale_collectives"] == 0
        health = m.sync_health()
        assert health["degraded"] and health["degraded_tier"] == "quorum"
        assert health["quorum_serves"] == 1
        # the subgroup merge must NOT report fresh full-world health: no
        # last-good stamp, the degradation onset stays visible
        assert health["last_good_sync_step"] is None
        assert health["degraded_since_step"] is not None
        assert psync.world_health()["dead_ranks"] == [2]
        # local accumulators stay intact and retryable under the hood
        np.testing.assert_allclose(float(np.asarray(m.value)), 4.0)

        # rank 2 rejoins (its restarted process is reachable again); the
        # recovery edge (steps=1) re-probes the FULL world on the next
        # compute — bit-exact vs the uninterrupted oracle
        world.revive()
        psync.rejoin_rank(2)
        m._computed = None
        got2 = np.asarray(m.compute())
        np.testing.assert_array_equal(got2, full_oracle)
        health = m.sync_health()
        assert not health["degraded"]
        # the full-world re-probe IS the last-good marker and clears the onset
        assert health["last_good_sync_step"] is not None
        assert health["degraded_since_step"] is None
        assert engine.engine_stats()["sync_stale_collectives"] == 0

    def test_quorum_without_known_survivors_serves_local(self, monkeypatch):
        """quorum tier with no declared-dead peers behaves exactly like the
        local tier: no subgroup is known, so the degraded serve is local."""
        monkeypatch.setenv("METRICS_TPU_SYNC_DEADLINE_MS", DEADLINE_MS)
        monkeypatch.setenv("METRICS_TPU_SYNC_DEGRADED", "quorum")
        monkeypatch.setenv("METRICS_TPU_SYNC_RETRIES", "0")
        monkeypatch.setattr(metric_mod, "_dist_available", lambda: True)
        m = mt.MeanMetric()
        m.update(jnp.asarray([2.0, 4.0]))

        def hung(x):
            time.sleep(1.0)
            raise RuntimeError("abandoned hung collective")

        monkeypatch.setattr(bucketing, "_payload_allgather", hung)
        s0 = engine.engine_stats()
        with pytest.warns(UserWarning, match="QUORUM"):
            v = m.compute()
        np.testing.assert_allclose(float(v), 3.0)  # the local value
        s1 = engine.engine_stats()
        assert s1["sync_degraded_serves"] - s0["sync_degraded_serves"] == 1
        assert s1["sync_quorum_serves"] == s0["sync_quorum_serves"]

    def test_suite_quorum_serve_and_sync_health(self, monkeypatch):
        """Suite-level: the whole collection aggregates over the surviving
        subgroup as one coalesced group-scoped sync."""
        monkeypatch.setenv("METRICS_TPU_SYNC_DEADLINE_MS", DEADLINE_MS)
        monkeypatch.setenv("METRICS_TPU_SYNC_DEGRADED", "quorum")
        monkeypatch.setenv("METRICS_TPU_SYNC_RETRIES", "1")
        monkeypatch.setenv("METRICS_TPU_SYNC_DEAD_AFTER", "2")
        monkeypatch.setattr(metric_mod, "_dist_available", lambda: True)
        faults.set_recovery_policy(steps=1)

        def make(r):
            c = mt.MetricCollection({"mean": mt.MeanMetric(), "sum": mt.SumMetric()})
            c.update(jnp.asarray([1.0 + 2 * r, 3.0 + 2 * r]))
            return c

        rank_colls = [make(r) for r in range(3)]
        trees = [
            [
                n
                for _, mm in c.items(keep_base=True, copy_state=False)
                for n in bucketing.tree_nodes(mm)
            ]
            for c in rank_colls
        ]
        # survivor oracle, member-wise per-state rank walk over ranks {0, 1}
        oracle = [copy.deepcopy(rank_colls[r]) for r in (0, 1)]
        for name, m0 in oracle[0].items(keep_base=True, copy_state=False):
            m0.sync(dist_sync_fn=_FakeGather([oc[name] for oc in oracle]), distributed_available=DIST_ON)
        oracle_vals = {k: np.asarray(v) for k, v in oracle[0].compute().items()}

        psync.set_expected_world(3)
        psync.set_peer_prober(lambda: [2])
        _install_elastic_world(monkeypatch, trees, dead_rank=2)
        suite = rank_colls[0]
        with pytest.warns(UserWarning, match="QUORUM"):
            got = {k: np.asarray(v) for k, v in suite.compute().items()}
        for k in oracle_vals:
            np.testing.assert_array_equal(got[k], oracle_vals[k])
        health = suite.sync_health()
        assert health["degraded"] and health["quorum_serves"] == 1
        assert health["world"]["dead_ranks"] == [2]
        assert health["world"]["surviving_ranks"] == [0, 1]
        # every member is unsynced after the serve: retryable
        for _, mm in suite.items(keep_base=True, copy_state=False):
            assert not mm._is_synced


class TestMixedHealthSuite:
    def test_subset_degraded_members_aggregate_and_order_vs_failure_log(self, monkeypatch):
        """sync_health() when a STRICT SUBSET of members is degraded: the
        suite flag folds member-wise, the healthy member stays clean, and
        the degradation onset orders against the failure_log's monotonic
        steps."""
        monkeypatch.setenv("METRICS_TPU_SYNC_DEGRADED", "local")
        coll = mt.MetricCollection({"mean": mt.MeanMetric(), "sum": mt.SumMetric()})
        coll.update(jnp.asarray([2.0, 4.0]))
        # demote ONLY the "mean" member's sync-degrade lane, via the real
        # entry path (fault counted at a raise site first, like sync does)
        exc = SyncTimeoutFault("peer hung", site="sync-gather")
        faults.note_fault("sync", site="sync-gather", owner=coll["mean"], error=exc)
        fault_step = engine.engine_stats()["failure_log"][-1]["step"]
        with pytest.warns(UserWarning, match="LOCAL-ONLY"):
            metric_mod._enter_degraded(coll["mean"], exc, "local")

        health = coll.sync_health()
        assert health["degraded"] is True  # folded from the member
        members = health["members"]
        assert members["mean"]["degraded"] is True
        assert members["sum"]["degraded"] is False
        assert members["sum"]["degraded_since_step"] is None
        # ordering: the onset stamp is at-or-after the classified fault that
        # caused it, on the SAME monotonic axis as the failure_log ring
        onset = members["mean"]["degraded_since_step"]
        assert onset is not None and onset >= fault_step
        assert faults.current_step() >= onset
        # a completed suite sync stamps last_good AFTER the onset and clears it
        coll.sync(distributed_available=DIST_ON)
        coll.unsync()
        health = coll.sync_health()
        assert health["last_good_sync_step"] > onset
        assert members["mean"]["degraded_since_step"] is not None  # old dict
        assert coll.sync_health()["members"]["mean"]["degraded_since_step"] is None

    def test_member_counts_fold_from_failure_log_domains(self):
        coll = mt.MetricCollection({"mean": mt.MeanMetric()})
        coll.update(jnp.asarray([1.0]))
        faults.note_fault("sync", site="sync-gather")
        faults.note_fault("journal", site="journal-load")
        counts = coll.sync_health()["members"]["mean"]["fault_domain_counts"]
        assert counts.get("sync", 0) >= 1 and counts.get("journal", 0) >= 1


class TestBarrierAndRejoin:
    def test_checkpoint_barrier_stamps_epoch_and_agreed_step(self, tmp_path):
        from metrics_tpu.ops import journal

        path = str(tmp_path / "suite.journal")
        coll = mt.MetricCollection({"mean": mt.MeanMetric()})
        coll.update(jnp.asarray([1.0, 3.0]))
        info = coll.checkpoint_barrier(path)
        assert info["epoch"] == psync.world_epoch()
        assert info["world_size"] == 1 and info["bytes"] > 0
        manifest, _ = journal.read_record(path)
        assert manifest["epoch"] == info["epoch"]
        assert manifest["barrier_step"] == info["barrier_step"]
        assert manifest["barrier"] is True
        # a second barrier agrees a strictly newer step (monotonic axis)
        coll.update(jnp.asarray([5.0]))
        info2 = coll.checkpoint_barrier(path)
        assert info2["barrier_step"] >= info["barrier_step"]

    def test_barrier_fences_on_mid_exchange_epoch_bump(self, tmp_path, monkeypatch):
        coll = mt.MetricCollection({"mean": mt.MeanMetric()})
        coll.update(jnp.asarray([1.0]))

        def bumping_exchange(vec):
            psync.bump_epoch("peer-died-mid-barrier")
            return np.asarray(vec)[None]

        monkeypatch.setattr(bucketing, "_host_allgather", bumping_exchange)
        with pytest.raises(EpochFault):
            coll.checkpoint_barrier(str(tmp_path / "j"))

    def test_rejoin_restores_journal_and_enters_next_epoch(self, tmp_path):
        path = str(tmp_path / "rank2.journal")
        live = mt.MetricCollection({"mean": mt.MeanMetric()})
        live.update(jnp.asarray([2.0, 4.0]))
        live.save_state(path)
        oracle = {k: np.asarray(v) for k, v in live.compute().items()}

        psync.set_expected_world(3)
        psync.mark_peer_dead(2, reason="crash")
        e_dead = psync.world_epoch()
        restored = mt.MetricCollection({"mean": mt.MeanMetric()})
        out = restored.rejoin(path, rank=2)
        assert out["generation"] == 0 and out["handoff"] is False
        assert out["epoch"] == e_dead + 1 == psync.world_epoch()
        assert psync.world_health()["dead_ranks"] == []
        got = {k: np.asarray(v) for k, v in restored.compute().items()}
        for k in oracle:
            np.testing.assert_array_equal(got[k], oracle[k])

    def test_rejoin_handoff_fast_forwards_to_newer_record(self, tmp_path):
        """A survivor hands the rejoiner a NEWER record (by barrier_step):
        one bucketed state handoff wins over the stale local generation."""
        from metrics_tpu.ops import journal

        path = str(tmp_path / "rank1.journal")
        live = mt.MetricCollection({"mean": mt.MeanMetric()})
        live.update(jnp.asarray([2.0, 4.0]))
        live.checkpoint_barrier(path)  # the stale local generation
        live.update(jnp.asarray([9.0]))
        # the survivor's copy of the NEWER barrier record (shared storage)
        newer = journal.pack_record(
            live._journal_nodes(),
            manifest_extra={
                "epoch": psync.world_epoch(),
                "barrier_step": faults.tick(),
                "nodes": None,  # reserved keys cannot be overridden
            },
        )
        oracle = {k: np.asarray(v) for k, v in live.compute().items()}

        handoffs = []

        def handoff(meta):
            handoffs.append(meta)
            return newer

        restored = mt.MetricCollection({"mean": mt.MeanMetric()})
        out = restored.rejoin(path, handoff=handoff, rank=1)
        assert out["handoff"] is True
        assert handoffs and "barrier_step" in handoffs[0]
        got = {k: np.asarray(v) for k, v in restored.compute().items()}
        for k in oracle:
            np.testing.assert_array_equal(got[k], oracle[k])

    def test_rejoin_handoff_corrupt_record_demotes_to_local_restore(self, tmp_path):
        """A broken survivor handoff must never abort the rejoin: the local
        generation already restored all-or-nothing, so a corrupt record
        classifies a journal fault (warn once) and the rank still enters
        the next epoch on its local state."""
        path = str(tmp_path / "rank1.journal")
        live = mt.MetricCollection({"mean": mt.MeanMetric()})
        live.update(jnp.asarray([2.0, 4.0]))
        live.checkpoint_barrier(path)
        oracle = {k: np.asarray(v) for k, v in live.compute().items()}
        j0 = engine.engine_stats()["fault_journal"]
        e0 = psync.world_epoch()
        restored = mt.MetricCollection({"mean": mt.MeanMetric()})
        with pytest.warns(UserWarning, match="handoff record failed verification"):
            out = restored.rejoin(path, handoff=lambda meta: b"garbage-not-a-record", rank=1)
        assert out["handoff"] is False
        assert out["epoch"] == e0 + 1  # the rejoin still completed
        assert engine.engine_stats()["fault_journal"] > j0  # classified
        got = {k: np.asarray(v) for k, v in restored.compute().items()}
        for k in oracle:
            np.testing.assert_array_equal(got[k], oracle[k])

    def test_rejoin_handoff_older_record_is_ignored(self, tmp_path):
        from metrics_tpu.ops import journal

        path = str(tmp_path / "rank1.journal")
        live = mt.MetricCollection({"mean": mt.MeanMetric()})
        live.update(jnp.asarray([2.0]))
        older = journal.pack_record(live._journal_nodes(), manifest_extra={"barrier_step": 0})
        live.update(jnp.asarray([4.0]))
        live.checkpoint_barrier(path)
        oracle = {k: np.asarray(v) for k, v in live.compute().items()}
        restored = mt.MetricCollection({"mean": mt.MeanMetric()})
        out = restored.rejoin(path, handoff=lambda meta: older, rank=1)
        assert out["handoff"] is False
        got = {k: np.asarray(v) for k, v in restored.compute().items()}
        for k in oracle:
            np.testing.assert_array_equal(got[k], oracle[k])


class TestEnvParserSatellites:
    def test_backoff_garbage_warns_once_naming_the_value(self, monkeypatch):
        monkeypatch.setenv("METRICS_TPU_SYNC_BACKOFF_MS", "soonish")
        monkeypatch.setattr(psync, "_BACKOFF_WARN_OWNER", psync._EnvWarnOwner())
        with pytest.warns(UserWarning, match=r"METRICS_TPU_SYNC_BACKOFF_MS='soonish'"):
            assert psync.sync_backoff_s() == 0.05
        import warnings as _warnings

        with _warnings.catch_warnings():
            _warnings.simplefilter("error")
            assert psync.sync_backoff_s() == 0.05  # warned ONCE

    def test_retries_warning_names_the_value(self, monkeypatch):
        monkeypatch.setenv("METRICS_TPU_SYNC_RETRIES", "many")
        monkeypatch.setattr(psync, "_RETRIES_WARN_OWNER", psync._EnvWarnOwner())
        with pytest.warns(UserWarning, match=r"METRICS_TPU_SYNC_RETRIES='many'"):
            assert psync.sync_retries() == 2

    def test_dead_after_garbage_falls_back_to_default(self, monkeypatch):
        monkeypatch.setenv("METRICS_TPU_SYNC_DEAD_AFTER", "never")
        monkeypatch.setattr(psync, "_MEMBERSHIP_WARN_OWNER", psync._EnvWarnOwner())
        with pytest.warns(UserWarning, match=r"METRICS_TPU_SYNC_DEAD_AFTER='never'"):
            assert psync.sync_dead_after() == 3

    def test_degraded_tier_accepts_quorum_and_rejects_garbage(self, monkeypatch):
        monkeypatch.setenv("METRICS_TPU_SYNC_DEGRADED", "quorum")
        assert psync.sync_degraded_tier() == "quorum"
        monkeypatch.setenv("METRICS_TPU_SYNC_DEGRADED", "mostly")
        monkeypatch.setattr(psync, "_DEADLINE_WARN_OWNER", psync._EnvWarnOwner())
        with pytest.warns(UserWarning, match="quorum"):
            assert psync.sync_degraded_tier() is None
